"""Digits (USPS↔MNIST) entrypoint — reference ``usps_mnist.py:329-404``."""

from __future__ import annotations

import argparse

from dwt_tpu.config import DigitsConfig
from dwt_tpu.utils import MetricLogger


def build_parser() -> argparse.ArgumentParser:
    d = DigitsConfig()
    p = argparse.ArgumentParser(description="dwt_tpu digits (DIAL/DWT) trainer")
    p.add_argument("--num_workers", type=int, default=d.num_workers,
                   help="item-loading worker threads (decode+augment)")
    p.add_argument("--data_stall_timeout", type=float,
                   default=d.data_stall_timeout,
                   help="data-pipeline head-of-window stall budget "
                        "(seconds): a worker silent past this is logged, "
                        "counted (dwt_data_stalls_total), and its item "
                        "speculatively re-submitted to a fresh worker — "
                        "dead/slow-worker recovery instead of a silent "
                        "stall.  0 disables detection")
    p.add_argument("--source_batch_size", type=int, default=d.source_batch_size)
    p.add_argument("--target_batch_size", type=int, default=d.target_batch_size)
    p.add_argument("--test_batch_size", type=int, default=d.test_batch_size)
    p.add_argument("--source", type=str, default=d.source)
    p.add_argument("--target", type=str, default=d.target)
    p.add_argument("--epochs", type=int, default=d.epochs)
    p.add_argument("--lr", type=float, default=d.lr)
    p.add_argument("--sgd_momentum", type=float, default=d.sgd_momentum,
                   help="accepted for parity; unused (Adam), as in reference")
    p.add_argument("--running_momentum", type=float, default=d.running_momentum)
    p.add_argument("--lambda_entropy_loss", type=float,
                   default=d.lambda_entropy_loss)
    p.add_argument("--log_interval", type=int, default=d.log_interval)
    p.add_argument("--seed", type=int, default=d.seed)
    p.add_argument("--group_size", type=int, default=d.group_size)
    p.add_argument("--data_root", type=str, default=d.data_root)
    # dwt_tpu extensions
    p.add_argument("--synthetic", action="store_true")
    p.add_argument("--synthetic_size", type=int, default=d.synthetic_size)
    p.add_argument("--data_parallel", action="store_true")
    p.add_argument("--distributed", action="store_true",
                   help="multi-host bring-up: call jax.distributed.initialize(); "
                        "launch the same command on every host")
    p.add_argument("--pallas_whiten", action="store_true",
                   help="route whitening through the Pallas two-pass "
                        "kernels (single-chip; incompatible with "
                        "--data_parallel)")
    p.add_argument("--whitener",
                   choices=["cholesky", "newton_schulz", "swbn"],
                   default=d.whitener,
                   help="whitening numerics backend: cholesky (reference "
                        "unrolled factorization, default), newton_schulz "
                        "(fixed-K iteration of pure batched matmuls — "
                        "MXU-native, batches across sites), swbn (online "
                        "whitening-matrix tracking, no factorization; "
                        "checkpoints carry the extra per-site matrix)")
    p.add_argument("--apply_lowering",
                   choices=["auto", "grouped", "blockdiag"],
                   default=d.apply_lowering,
                   help="force the whitening-apply matmul lowering; auto "
                        "keeps the backend heuristic (CPU: blockdiag; "
                        "TPU: blockdiag up to the DWT_APPLY_CROSSOVER_C "
                        "channel crossover, default 128, then grouped)")
    p.add_argument("--dcn_slices", type=int, default=d.dcn_slices,
                   help=">1: 2-D (dcn, data) mesh — pod-level DP across "
                        "slices, per-slice reductions on ICI")
    p.add_argument("--mesh_shape", type=str, default=d.mesh_shape,
                   help="sharding-rules engine mesh as 'dcn,data,model' "
                        "sizes (e.g. 1,2,2); '4' and '2,4' shorthands "
                        "pad the missing axes to 1.  Unset keeps the "
                        "legacy single/--data_parallel decision")
    p.add_argument("--sharding_rules", type=str, default=d.sharding_rules,
                   help="rules table driving per-leaf placement: preset "
                        "'dp' (replicate all state — bitwise the legacy "
                        "paths), preset 'model' (out-channel model "
                        "sharding, whitening/BN stats pinned replicated), "
                        "or a path to a JSON [[regex, spec], ...] file")
    p.add_argument("--steps_per_dispatch", type=int,
                   default=d.steps_per_dispatch,
                   help=">1: run k train steps per dispatch (lax.scan "
                        "over k stacked batches) — amortizes host "
                        "dispatch latency; same numerics")
    p.add_argument("--eval_steps_per_dispatch", type=int,
                   default=d.eval_steps_per_dispatch,
                   help="k eval batches per scanned dispatch; counters "
                        "stay device-resident across the whole eval "
                        "pass (O(1) host fetches), ragged tails are "
                        "pad-and-masked so counts stay exact")
    p.add_argument("--harvest_depth", type=int, default=d.harvest_depth,
                   help="async metric harvesting: depth of the bounded "
                        "ring deferring the train-record host fetch "
                        "(non-blocking device→host copies, drained once "
                        "full — amortized 1/depth syncs per step — or "
                        "fully at eval/ckpt/preempt/rollback "
                        "boundaries); records keep their original step "
                        "stamps byte-identically, and the divergence "
                        "guard reads the step's harvested finite flag "
                        "with staleness <= depth.  0 = legacy "
                        "synchronous fetch")
    p.add_argument("--ckpt_dir", type=str, default=None)
    p.add_argument("--ckpt_every_epochs", type=int, default=d.ckpt_every_epochs)
    p.add_argument("--async_ckpt", action=argparse.BooleanOptionalAction,
                   default=d.async_ckpt,
                   help="background checkpoint pipeline: the loop only "
                        "snapshots + enqueues; digest/Orbax write/rename "
                        "run on a writer thread (--no-async_ckpt: every "
                        "save blocks the loop)")
    p.add_argument("--ckpt_format", choices=["full", "delta"],
                   default=d.ckpt_format,
                   help="checkpoint on-disk format: 'full' writes the "
                        "whole tree every save (existing Orbax/host-shard "
                        "artifacts, byte-compatible default); 'delta' is "
                        "the content-addressed incremental store — leaf "
                        "blobs keyed by digest under <ckpt_dir>/blobs, "
                        "manifests chaining to a parent full save, only "
                        "moved leaves written per save, refcounted blob "
                        "GC, topology-elastic streaming restore")
    p.add_argument("--delta_max_chain", type=int, default=d.delta_max_chain,
                   help="delta-format chain cap: after this many chained "
                        "delta saves the next save is forced full, "
                        "bounding the manifests a restore must read and "
                        "the blast radius of a torn chain")
    p.add_argument("--blob_store", type=str, default=d.blob_store,
                   help="delta-format blob store override: a SHARED "
                        "store path multiple runs save into, deduping "
                        "identical leaves across runs; sharing disables "
                        "this run's local blob GC (cross-run refcounted "
                        "GC is the sweep supervisor's).  Default: "
                        "<ckpt_dir>/blobs (private, locally GC'd)")
    p.add_argument("--anchor_every", type=int, default=d.anchor_every,
                   help=">0: every N epochs also save an anchor checkpoint "
                        "under ckpt_dir/anchors, exempt from any pruning — "
                        "bounds rollback distance under repeated divergence")
    p.add_argument("--guard_policy",
                   choices=["none", "halt", "skip_step", "rollback"],
                   default=d.guard_policy,
                   help="divergence guard: on a non-finite loss/grad-norm, "
                        "halt, skip back to the last good in-memory state, "
                        "or roll back to the newest valid checkpoint with a "
                        "re-seeded data order")
    p.add_argument("--guard_interval", type=int, default=d.guard_interval,
                   help="steps between guard finite-checks (each check is "
                        "one host sync; NaN is absorbing, so detection is "
                        "at most interval-1 steps late)")
    p.add_argument("--guard_max_rollbacks", type=int,
                   default=d.guard_max_rollbacks,
                   help="rollback attempts before the guard halts the run")
    p.add_argument("--guard_lr_backoff", type=float, default=d.guard_lr_backoff,
                   help="in (0,1): first guard rung — revert to the last "
                        "good in-memory state and scale optimizer updates "
                        "by this factor (e.g. 0.5); recovers to 1.0 after "
                        "--guard_backoff_recovery clean checks, escalates "
                        "to --guard_policy if it strikes again while "
                        "backed off.  0 disables the rung")
    p.add_argument("--guard_backoff_recovery", type=int,
                   default=d.guard_backoff_recovery,
                   help="clean guard checks before a backed-off lr "
                        "recovers to 1.0 (re-arming the backoff rung)")
    p.add_argument("--watchdog_timeout", type=float, default=d.watchdog_timeout,
                   help=">0: hang watchdog — if no step boundary completes "
                        "for this many seconds, dump all-thread stacks "
                        "under ckpt_dir/watchdog/ and exit 113 so the "
                        "scheduler relaunches into resume; budget for the "
                        "first step's compile and boundary evals.  0 = off")
    p.add_argument("--watchdog_keep", type=int, default=d.watchdog_keep,
                   help="cap on retained watchdog stack dumps under "
                        "ckpt_dir/watchdog/ (oldest pruned first); a "
                        "relaunch loop must not fill the disk")
    p.add_argument("--preempt_notice_file", type=str,
                   default=d.preempt_notice_file,
                   help="preemption notice file: when this path comes "
                        "into existence (scheduler prolog/preStop hook), "
                        "every host takes a proactive save at the next "
                        "step boundary while training continues — the "
                        "later SIGTERM exits fast")
    p.add_argument("--preempt_notice_metadata",
                   action=argparse.BooleanOptionalAction,
                   default=d.preempt_notice_metadata,
                   help="poll the GCE instance/preempted metadata key "
                        "(~30 s advance warning on spot/preemptible VMs) "
                        "as a preemption notice source; URL overridable "
                        "via DWT_PREEMPT_METADATA_URL for tests")
    p.add_argument("--keep_ckpts", type=int, default=d.keep_ckpts,
                   help=">0: prune the main --ckpt_dir to the newest N "
                        "steps after each periodic/final save; anchors "
                        "(--anchor_every) and best_* artifacts live in "
                        "separate directories and are never pruned")
    p.add_argument("--obs_trace", type=str, default=d.obs_trace,
                   help="span tracing: write a Chrome trace-event JSON of "
                        "the run's per-phase spans (batch wait / step "
                        "dispatch / host fetch / consensus / checkpoint) "
                        "to this path — open in Perfetto or feed "
                        "tools/obs_report.py; DWT_OBS_TRACE env is the "
                        "flagless form.  Off by default; disabled spans "
                        "cost ~one global read")
    p.add_argument("--heartbeat_every", type=int, default=d.heartbeat_every,
                   help=">0: emit a heartbeat record (steps/s EWMA, host "
                        "RSS MB, device memory, async-ckpt in-flight "
                        "depth) every N steps — the cheap always-on "
                        "liveness signal when full tracing is off.  "
                        "0 disables")
    p.add_argument("--metrics_port", type=int, default=d.metrics_port,
                   help="live metrics plane: serve Prometheus text "
                        "exposition at /metrics on this port (daemon "
                        "thread; 0 = ephemeral, port logged as a "
                        "metrics_exporter record).  Scrape steps/s, "
                        "loss, guard events, checkpoint stalls mid-run")
    p.add_argument("--alert_rules", type=str, default=d.alert_rules,
                   help="SLO alert rules JSON (list of {name, metric, "
                        "op, threshold, for_s, severity, labels}): "
                        "evaluated each step boundary against the live "
                        "registry; fire/clear transitions emit 'alert' "
                        "JSONL records and the dwt_alerts_firing gauge")
    p.add_argument("--bf16", action="store_true",
                   help="legacy alias for --compute_dtype bf16")
    p.add_argument("--compute_dtype", type=str, default=d.compute_dtype,
                   choices=("f32", "bf16"),
                   help="training compute dtype: params/optimizer state "
                        "stay f32; bf16 runs activations, backprop "
                        "traffic, and the whitening apply in bf16 (each "
                        "whitener backend's precision_policy decides "
                        "whether its factorization promotes or runs "
                        "natively — ops/whitening.py).  f32 (default) "
                        "is bitwise the legacy path")
    p.add_argument("--metrics_jsonl", type=str, default=None)
    p.add_argument("--expect_accuracy", type=float, default=None,
                   help="repro assertion: exit nonzero unless final target "
                        "accuracy is within --tolerance of this (paper "
                        "digits-table value, see baselines/)")
    p.add_argument("--tolerance", type=float, default=0.3,
                   help="±%% band for --expect_accuracy (BASELINE "
                        "north-star: 0.3)")
    p.add_argument("--debug_nans", action="store_true",
                   help="jax_debug_nans: fail fast at the op that produced a NaN "
                        "(the whitening Cholesky guard, SURVEY \u00a75)")
    return p


def config_from_args(args: argparse.Namespace) -> DigitsConfig:
    fields = {f.name for f in DigitsConfig.__dataclass_fields__.values()}
    return DigitsConfig(
        **{k: v for k, v in vars(args).items() if k in fields}
    )


def main(argv=None) -> float:
    args = build_parser().parse_args(argv)
    if args.debug_nans:
        import jax

        jax.config.update("jax_debug_nans", True)
    from dwt_tpu.train.loop import run_digits
    from dwt_tpu.utils import check_cli_accuracy

    logger = MetricLogger(jsonl_path=args.metrics_jsonl)
    try:
        acc = run_digits(config_from_args(args), logger)
        if not check_cli_accuracy(
            acc, args.expect_accuracy, args.tolerance, logger
        ):
            raise SystemExit(1)
        return acc
    finally:
        logger.close()


if __name__ == "__main__":
    main()
