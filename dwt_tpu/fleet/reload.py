"""Hot reload: watch → restore → canary → atomic swap → monitor → rollback.

Two producers feed one deploy pipeline:

* the :class:`HotReloader` — new CHECKPOINTS from the watched directory
  (restore → structural graft → build → submit);
* the serve-side :class:`~dwt_tpu.serve.adapt.DomainAdapter` — ADAPTED
  generations folded from live-traffic whitening stats (same params,
  mutated ``batch_stats`` + refreshed cache → submit).

Both go through the shared :class:`DeployController`, which owns the
gate → swap → monitor → rollback sequence for ONE serving process:
every candidate — wherever it came from — passes the same
:class:`~dwt_tpu.fleet.canary.CanaryGate` fixture eval, swaps in as the
same atomic pointer flip, and is watched by the same
:class:`~dwt_tpu.fleet.canary.PostSwapMonitor` against the same
access-log windows.  The controller serializes submissions (one deploy
in flight at a time) and routes the rollback CONSEQUENCE by origin:
a regressed checkpoint is blacklisted by the reloader, a regressed
adapted generation freezes the adapter (verdict listeners).

Everything expensive — the loose checkpoint read, the structural graft
onto the model template, the whiten-cache factorization, the device
placement through the sharding plan — runs on the producer's own thread
while the dispatcher keeps serving the live generation (the double
buffer); only the final pointer flip (``ServeEngine.swap``) touches the
serving path, and that flip is a single reference assignment between
dispatches.

Failure containment mirrors the training guard ladder:

* a candidate that fails to RESTORE (torn bytes, digest mismatch —
  ``restore_tree`` re-verifies the manifest digest) or to BUILD
  (structure/shape mismatch at ``adapt_tree``) is refused and
  remembered, so the watcher re-seeing the same artifact does not retry
  it forever;
* a candidate the :class:`~dwt_tpu.fleet.canary.CanaryGate` refuses
  (non-finite / regressed fixture eval) likewise never goes live;
* a candidate that goes live but regresses the post-swap access-log
  windows (:class:`~dwt_tpu.fleet.canary.PostSwapMonitor`) is rolled
  back to the last-good state — kept device-resident since the swap —
  and blacklisted (checkpoints) or frozen out (adapted generations).

Every transition writes a JSONL event through the access log, version-
labelled, so one file tells the deployment story next to the requests
it affected: ``reload``/``canary``/``swap``/``rollback`` for the
checkpoint path, ``adapt_canary``/``adapt_swap``/``adapt_rollback`` for
adapted generations (plus the adapter's own ``adapt_build``).
"""

from __future__ import annotations

import logging
import threading
from typing import Callable, List, Optional, Tuple

from dwt_tpu import obs
from dwt_tpu.fleet.canary import CanaryGate, PostSwapMonitor
from dwt_tpu.fleet.watcher import Candidate, CheckpointWatcher, newest_candidate
from dwt_tpu.serve.engine import EngineState, ServeEngine, Version
from dwt_tpu.utils.checkpoint import restore_tree

log = logging.getLogger(__name__)


class DeployController:
    """The shared gate → swap → monitor → rollback pipeline.

    Origin-agnostic: ``submit(state, origin=...)`` runs the canary on
    any built :class:`EngineState` and flips it live on a pass; ``poll``
    acts on the post-swap monitor's verdict (every producer loop calls
    it — whichever thread polls first performs the rollback, under one
    lock).  ``origin`` selects the JSONL event kinds (``canary``/
    ``swap``/``rollback`` vs ``adapt_canary``/…) and is handed to
    verdict listeners so each producer applies its own consequence
    (checkpoint blacklist vs adaptation freeze).
    """

    def __init__(
        self,
        engine: ServeEngine,
        *,
        access_log=None,
        canary: Optional[CanaryGate] = None,
        monitor: Optional[PostSwapMonitor] = None,
    ):
        self.engine = engine
        self.access_log = access_log
        self.canary = canary
        self.monitor = monitor
        self.last_good: Optional[EngineState] = None
        self._last_good_label: Optional[str] = None
        self.swap_count = 0
        self.rollback_count = 0
        # One deploy in flight at a time: a reloader deploy and an
        # adapter fold racing each other would interleave their canary
        # baselines and fight over last_good.  RLock — rollback() runs
        # inside poll()'s critical section.
        self._lock = threading.RLock()
        # fn(origin, version: Version, verdict: str) — called on the
        # post-swap "ok" (the generation survived its watch window) and
        # on every rollback ("rollback: …"), AFTER the swap-back.
        self._verdict_listeners: List[
            Callable[[str, Version, str], None]
        ] = []

    # ------------------------------------------------------------- events

    def add_verdict_listener(
        self, fn: Callable[[str, Version, str], None]
    ) -> None:
        self._verdict_listeners.append(fn)

    def _notify(self, origin: str, version: Version, verdict: str) -> None:
        for fn in self._verdict_listeners:
            try:
                fn(origin, version, verdict)
            except Exception:
                log.exception("fleet: verdict listener failed")

    def _event(self, kind: str, origin: str = "reload", **fields) -> None:
        if self.access_log is not None:
            # The checkpoint path keeps its historical bare kinds; other
            # origins prefix theirs (adapt_canary/adapt_swap/…), so one
            # JSONL stream tells both deployment stories apart.
            name = kind if origin == "reload" else f"{origin}_{kind}"
            self.access_log.event(name, **fields)

    # ------------------------------------------------------------- deploy

    def submit(
        self,
        state: EngineState,
        *,
        origin: str = "reload",
        skip_canary: bool = False,
    ) -> Tuple[bool, str]:
        """Gate one built candidate and flip it live on a pass.  Returns
        ``(went_live, reason)``; never raises on a refusal — the caller
        applies its origin-specific consequence."""
        with self._lock:
            label = state.version.label
            if self.canary is not None and not skip_canary:
                # Measure the live baseline BEFORE the swap moves it.
                verdict = self.canary.check(state)
                self._event("canary", origin, version=label, ok=verdict.ok,
                            reason=verdict.reason, **verdict.metrics)
                if not verdict.ok:
                    return False, verdict.reason
            old_label = self.engine.version.label
            baseline_p99 = None
            if self.access_log is not None:
                baseline_p99 = self.access_log.version_stats(
                    old_label
                ).get("e2e_ms_p99")
            with obs.span("swap", "fleet", version=label):
                prev = self.engine.swap(state)
            self.swap_count += 1
            self.last_good = prev
            self._last_good_label = old_label
            self._event("swap", origin, version=label,
                        from_version=old_label, step=state.version.step)
            if self.monitor is not None:
                self.monitor.arm(label, baseline_p99, origin=origin)
            return True, "ok"

    def rollback(self, reason: str, origin: Optional[str] = None) -> bool:
        """Swap the last-good state back in.  Returns False when there
        is nothing to roll back to (first deploy of a fresh server —
        keep serving, keep alarming).  ``origin`` defaults to whatever
        the monitor was armed with."""
        with self._lock:
            if origin is None:
                origin = (
                    self.monitor.armed_origin
                    if self.monitor is not None and self.monitor.armed
                    else "reload"
                )
            bad = self.engine.version
            if self.last_good is None:
                log.error(
                    "fleet: %s but no last-good state to roll back to "
                    "(version %s stays live)", reason, bad.label,
                )
                self._event("rollback", origin, version=bad.label,
                            ok=False, reason=reason)
                return False
            with obs.span("swap", "fleet",
                          version=self.last_good.version.label, rollback=1):
                self.engine.swap(self.last_good)
            self.rollback_count += 1
            self._event("rollback", origin, version=bad.label,
                        to_version=self.last_good.version.label,
                        reason=reason)
            log.warning(
                "fleet: rolled back %s -> %s (%s)",
                bad.label, self.last_good.version.label, reason,
            )
            # The rolled-back-to state is live again; nothing newer is
            # good.
            self.last_good = None
            if self.monitor is not None:
                self.monitor.disarm()
            self._notify(origin, bad, reason)
            return True

    def poll(self) -> Optional[str]:
        """Act on the monitor's verdict.  Returns ``None`` (not armed),
        ``"hold"`` (undecided — producers must not deploy on top of a
        version under watch), ``"ok"`` (survived; disarmed), or
        ``"rollback"`` (performed).  Safe to call from every producer
        loop; the lock makes whoever gets there first do the work."""
        with self._lock:
            if self.monitor is None or not self.monitor.armed:
                return None
            verdict = self.monitor.verdict()
            if verdict is None:
                return "hold"
            if verdict.startswith("rollback"):
                self.rollback(verdict)
                return "rollback"
            # "ok": the new version held — it is the bar now.
            origin = self.monitor.armed_origin
            version = self.engine.version
            self.monitor.disarm()
            self._notify(origin, version, "ok")
            return "ok"


class HotReloader:
    """One serving process's continuous-deployment loop.

    ``step()`` is the single-iteration core (poll → maybe deploy → maybe
    roll back) — unit-testable with no thread; ``start()``/``stop()``
    wrap it in a daemon.  ``reload_newest(force=True)`` is the bench's
    direct lever: swap the newest checkpoint in NOW (even if it is the
    version already live — a same-checkpoint swap is the numeric no-op
    the parity tests pin).

    The gate/swap/monitor mechanics live in the shared
    :class:`DeployController`; pass ``controller=`` to share one with
    the online adapter (``--watch`` + ``--adapt_every`` on one server),
    so both producers serialize through one pipeline and one last-good
    buffer.
    """

    def __init__(
        self,
        engine: ServeEngine,
        ckpt_dir: str,
        *,
        access_log=None,
        poll_s: float = 2.0,
        canary: Optional[CanaryGate] = None,
        monitor: Optional[PostSwapMonitor] = None,
        controller: Optional[DeployController] = None,
    ):
        self.engine = engine
        self.ckpt_dir = ckpt_dir
        self.access_log = access_log
        if controller is None:
            controller = DeployController(
                engine, access_log=access_log, canary=canary,
                monitor=monitor,
            )
        self.controller = controller
        self.canary = controller.canary
        self.monitor = controller.monitor
        controller.add_verdict_listener(self._on_verdict)
        self.watcher = CheckpointWatcher(ckpt_dir, poll_s)
        # The version the server booted with must not redeploy on the
        # first poll: prime the watcher with it when it IS the newest.
        boot = newest_candidate(ckpt_dir)
        if boot is not None and self._is_live(boot):
            self.watcher.prime(boot)
        self.rejected: dict = {}     # version key -> refusal reason
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # Deploy bookkeeping lives on the (possibly shared) controller; the
    # historical attribute names keep reading through.
    @property
    def last_good(self) -> Optional[EngineState]:
        return self.controller.last_good

    @last_good.setter
    def last_good(self, value: Optional[EngineState]) -> None:
        self.controller.last_good = value

    @property
    def swap_count(self) -> int:
        return self.controller.swap_count

    @property
    def rollback_count(self) -> int:
        return self.controller.rollback_count

    def _is_live(self, cand: Candidate) -> bool:
        """Is this candidate the generation already serving?  Digest
        first — it is the content identity and identical whether it came
        from the manifest or was recomputed over the restored params;
        the step number alone can differ between a checkpoint's
        directory name and the train state it holds (legacy manifests
        without a digest fall back to the step)."""
        live = self.engine.version
        if cand.digest is not None and live.digest is not None:
            return cand.digest == live.digest
        return cand.step == live.step

    # ------------------------------------------------------------- events

    def _event(self, kind: str, **fields) -> None:
        if self.access_log is not None:
            self.access_log.event(kind, **fields)

    def _reject(self, cand_key, label: str, reason: str) -> None:
        self.rejected[cand_key] = reason
        log.warning("fleet: candidate %s refused: %s", label, reason)
        self._event("canary", version=label, ok=False, reason=reason)

    def _on_verdict(self, origin: str, version: Version,
                    verdict: str) -> None:
        # A checkpoint generation the monitor rolled back is blacklisted
        # so the watcher re-seeing the same artifact does not redeploy
        # it.  Adapted generations are NOT checkpoint candidates — their
        # consequence (freeze + re-arm) belongs to the adapter's own
        # listener.
        if origin == "reload" and verdict != "ok":
            self.rejected[(version.step, version.digest)] = verdict

    # ------------------------------------------------------------ deploy

    def _build_candidate(self, cand: Candidate) -> EngineState:
        with obs.span("reload_restore", "fleet", step=cand.step):
            tree = restore_tree(cand.path)  # digest re-verified here
        return self.engine.build_state_from_tree(
            tree,
            version=Version(cand.step, cand.digest),
            what=f"candidate step {cand.step}",
        )

    def deploy(self, cand: Candidate, *, skip_canary: bool = False) -> bool:
        """Restore → build → canary → swap one candidate.  Returns True
        when the candidate went live."""
        label = Version(cand.step, cand.digest).label
        self._event("reload", version=label, step=cand.step,
                    source=cand.source)
        try:
            state = self._build_candidate(cand)
        except Exception as e:
            self._reject(cand.key, label,
                         f"restore/build failed: {type(e).__name__}: {e}")
            return False
        label = state.version.label  # digest may have been computed late
        ok, reason = self.controller.submit(
            state, origin="reload", skip_canary=skip_canary
        )
        if not ok:
            self._reject(cand.key, label, reason)
        return ok

    def rollback(self, reason: str) -> bool:
        """Swap the last-good state back in and blacklist the regressed
        version.  Returns False when there is nothing to roll back to
        (first deploy of a fresh server — keep serving, keep alarming)."""
        return self.controller.rollback(reason)

    def reload_newest(self, *, force: bool = False,
                      skip_canary: bool = False) -> bool:
        """Deploy the newest valid checkpoint directly (bench/ops lever).
        ``force`` redeploys even the live version (a same-checkpoint
        swap: numerically a no-op, operationally the swap-cost probe)."""
        cand = newest_candidate(self.ckpt_dir)
        if cand is None:
            return False
        if not force and self._is_live(cand):
            return False
        return self.deploy(cand, skip_canary=skip_canary)

    # -------------------------------------------------------------- loop

    def step(self) -> None:
        """One reloader iteration: act on a monitor verdict, then on a
        new candidate.  Rollback first — deploying on top of a regressed
        version would destroy the evidence."""
        status = self.controller.poll()
        if status in ("hold", "rollback"):
            return
        cand = self.watcher.poll_once()
        if cand is None:
            return
        if cand.key in self.rejected:
            log.info(
                "fleet: skipping already-refused candidate step %s (%s)",
                cand.step, self.rejected[cand.key],
            )
            return
        self.deploy(cand)

    def start(self) -> None:
        if self._thread is not None:
            raise RuntimeError("reloader already started")

        def _run():
            while not self._stop.wait(self.watcher.poll_s):
                try:
                    self.step()
                except Exception:
                    log.exception("fleet: reloader step failed")

        self._thread = threading.Thread(
            target=_run, name="dwt-fleet-reload", daemon=True
        )
        self._thread.start()

    def stop(self, timeout: float = 30.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        self.watcher.stop()
