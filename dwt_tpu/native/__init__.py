"""Native (C++) host-side kernels for the input pipeline, ctypes-loaded.

The compute path of the framework is JAX/XLA on TPU; the *host* runtime
around it — here, the per-item augmentation tail of the data loader —
is native C++ (``augment.cpp``), mirroring how the reference leans on
torchvision/cv2 native loops (``resnet50_dwt_mec_officehome.py:481-492``)
rather than Python pixel math.

Design:

* **Build on demand, never required.**  ``load()`` compiles
  ``augment.cpp`` with g++ into a cache directory on first use (~1 s),
  memoizes the handle, and returns ``None`` on any failure (no compiler,
  read-only FS, exotic platform) — callers fall back to the numpy/cv2
  path.  Set ``DWT_DISABLE_NATIVE=1`` to force the fallback (used for
  pipeline A/B benchmarks).
* **ctypes, not a CPython extension module** — no Python.h/pybind11
  dependency, no per-interpreter ABI; and ctypes drops the GIL during
  the call, so ``batch_iterator``'s worker threads scale on real
  multi-core hosts.
"""

from __future__ import annotations

import ctypes
import os
import shutil
import subprocess
import sys
import tempfile
import threading

import numpy as np

_SRC = os.path.join(os.path.dirname(os.path.abspath(__file__)), "augment.cpp")
_LIB_NAME = "_dwtnative.so"

_lib = None
_load_attempted = False
_load_error: str | None = None
# Serializes build+load: batch_iterator's worker threads may race into
# load() on a cold cache; without the lock two threads could compile to
# the same path concurrently, and every thread arriving mid-build would
# silently take the numpy fallback — making which items get which
# numerics scheduler-dependent.  With it, first thread builds (~1 s),
# the rest block and then share the handle.
_load_lock = threading.Lock()


def _lib_path() -> str:
    """Where to build/load the .so.

    Package dir when writable (dev checkout), with an atomic
    rename-into-place so concurrent *processes* never load a
    half-written file.  Otherwise a fresh private (0700, random-name)
    per-process directory — deliberately NOT a predictable shared /tmp
    path, which another local user could pre-seed with a hostile .so.
    The per-process rebuild costs ~1 s once.
    """
    pkg = os.path.dirname(os.path.abspath(__file__))
    if os.access(pkg, os.W_OK):
        return os.path.join(pkg, _LIB_NAME)
    return os.path.join(tempfile.mkdtemp(prefix="dwt_native_"), _LIB_NAME)


def _build(out_path: str) -> None:
    gxx = shutil.which("g++") or shutil.which("c++")
    if gxx is None:
        raise RuntimeError("no C++ compiler on PATH")
    tmp = f"{out_path}.{os.getpid()}.tmp"
    cmd = [
        gxx, "-O3", "-shared", "-fPIC", "-std=c++17",
        "-o", tmp, _SRC,
    ]
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=120
        )
        if proc.returncode != 0:
            raise RuntimeError(f"g++ failed: {proc.stderr[-500:]}")
        os.replace(tmp, out_path)  # atomic within the directory
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def load():
    """The ctypes library handle, building it if needed; None on failure."""
    global _lib, _load_attempted
    # Lock-free fast path once the one-time attempt has CONCLUDED (either
    # way): the Fused* transforms call this per item on every loader
    # worker thread, and a mutex here would serialize exactly the
    # fallback side of the DWT_DISABLE_NATIVE A/B.  _load_attempted is
    # only set True after _load_locked finishes (under the lock), so a
    # thread observing it True sees the final _lib value.
    if _load_attempted:
        return _lib
    with _load_lock:
        if _load_attempted:
            return _lib
        try:
            return _load_locked()
        finally:
            _load_attempted = True


def _load_locked():
    global _lib, _load_error
    if os.environ.get("DWT_DISABLE_NATIVE") == "1":
        _load_error = "disabled by DWT_DISABLE_NATIVE=1"
        return None
    try:
        path = _lib_path()
        if (
            not os.path.exists(path)
            or os.path.getmtime(path) < os.path.getmtime(_SRC)
        ):
            _build(path)
        lib = ctypes.CDLL(path)
        f32p = ctypes.POINTER(ctypes.c_float)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.dwt_norm_u8.argtypes = [
            u8p, ctypes.c_longlong, ctypes.c_int, f32p, f32p, f32p
        ]
        lib.dwt_norm_u8.restype = None
        lib.dwt_warp_affine_norm_u8.argtypes = [
            u8p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            f32p, f32p, f32p, f32p,
        ]
        lib.dwt_warp_affine_norm_u8.restype = None
        _lib = lib
    except Exception as e:  # pragma: no cover - environment-dependent
        _load_error = f"{type(e).__name__}: {e}"
        print(
            f"dwt_tpu.native: build/load failed ({_load_error}); "
            "using the numpy/cv2 fallback path",
            file=sys.stderr,
        )
    return _lib


def available() -> bool:
    return load() is not None


def _f32p(a):
    a = np.ascontiguousarray(a, dtype=np.float32)
    # Returning the array too keeps the buffer alive across the call.
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), a


def _per_channel(v, c: int):
    """mean/std as a length-``c`` f32 vector (numpy broadcast semantics —
    a scalar or length-1 input applies to every channel, like
    ``transforms.Normalize``); the C kernel indexes ``[0, c)``, so a
    short buffer would be read past its end."""
    return np.broadcast_to(np.asarray(v, np.float32).reshape(-1), (c,))


def normalize_from_u8(
    a: np.ndarray, mean: np.ndarray, std: np.ndarray
) -> np.ndarray:
    """``(a/255 - mean)/std`` in one native pass; ``a`` uint8 HWC."""
    lib = load()
    assert lib is not None, "call available() first"
    a = np.ascontiguousarray(a, dtype=np.uint8)
    h, w, c = a.shape
    if not 1 <= c <= 16:
        # The C kernels statically bound their per-channel scale/bias
        # arrays at 16 and silently no-op beyond it — never hand back
        # uninitialized output instead of an error.
        raise ValueError(f"native kernels support 1..16 channels, got {c}")
    out = np.empty((h, w, c), np.float32)
    (pm, _m), (ps, _s), (po, _o) = (
        _f32p(_per_channel(mean, c)),
        _f32p(_per_channel(std, c)),
        _f32p(out),
    )
    lib.dwt_norm_u8(
        a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.c_longlong(h * w),
        ctypes.c_int(c),
        pm, ps, po,
    )
    return out


def warp_affine_normalize_from_u8(
    a: np.ndarray, m: np.ndarray, mean: np.ndarray, std: np.ndarray
) -> np.ndarray:
    """cv2.warpAffine(default flags) + /255 + normalize, one native pass.

    ``a`` uint8 HWC; ``m`` the forward 2x3 float32 matrix exactly as
    cv2.warpAffine would receive it.
    """
    lib = load()
    assert lib is not None, "call available() first"
    a = np.ascontiguousarray(a, dtype=np.uint8)
    h, w, c = a.shape
    if not 1 <= c <= 16:
        raise ValueError(f"native kernels support 1..16 channels, got {c}")
    out = np.empty((h, w, c), np.float32)
    (pM, _M), (pm, _m), (ps, _s), (po, _o) = (
        _f32p(m),
        _f32p(_per_channel(mean, c)),
        _f32p(_per_channel(std, c)),
        _f32p(out),
    )
    lib.dwt_warp_affine_norm_u8(
        a.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        ctypes.c_int(h), ctypes.c_int(w), ctypes.c_int(c),
        pM, pm, ps, po,
    )
    return out
