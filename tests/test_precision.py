"""Reduced-precision frontier tests (bf16 end-to-end training).

Tier-1 (fast): the per-backend ``precision_policy`` contract, NS
native-bf16 factorization staying close to its f32 reference, bf16
``group_whiten`` through every backend (f32 EMA stats preserved), the
step-side grad cast, and the ``--compute_dtype`` config resolution
(including the legacy ``--bf16`` alias).

Slow-marked (tools/t1_budget.py discipline): the CLI-level proofs —
``--compute_dtype f32`` is BITWISE the default run (digits + tiny
officehome params digests) and ``--compute_dtype bf16`` lands in the
accuracy band per whitener backend (NS factorizes natively in bf16;
Cholesky/SWBN promote at the site).
"""

from __future__ import annotations

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dwt_tpu.ops.whitening import (
    WHITENER_NAMES,
    _shrink,
    get_whitener,
    group_whiten,
    newton_schulz_inverse_sqrt,
)

# ------------------------------------------------------- precision policy


def test_precision_policy_promotes_by_default():
    """Cholesky and SWBN cannot hold bf16: their policy promotes to f32
    at the site (so a bf16 net's factorization is bitwise the f32
    net's); NS declares the compute dtype itself — it factorizes
    natively in bf16."""
    for name in ("cholesky", "swbn"):
        wh = get_whitener(name)
        assert wh.precision_policy(jnp.bfloat16) == jnp.float32
        assert wh.precision_policy(jnp.float32) == jnp.float32
    ns = get_whitener("newton_schulz")
    assert ns.precision_policy(jnp.bfloat16) == jnp.bfloat16
    assert ns.precision_policy(jnp.float32) == jnp.float32


def test_newton_schulz_bf16_native_close_to_f32():
    """The bf16 NS factorization (bf16 iterate, f32 trace-normalization
    accumulators) stays within bf16 round-off of the f32 reference and
    keeps the compute dtype end to end."""
    rng = np.random.default_rng(0)
    a = rng.normal(size=(16, 4, 4))
    spd = jnp.asarray(
        a @ a.transpose(0, 2, 1) + 4 * np.eye(4), jnp.float32
    )
    spd = _shrink(spd, 1e-3)
    w32 = newton_schulz_inverse_sqrt(spd, 5)
    w16 = newton_schulz_inverse_sqrt(spd.astype(jnp.bfloat16), 5)
    assert w16.dtype == jnp.bfloat16
    assert bool(jnp.isfinite(w16).all())
    # bf16 has ~3 decimal digits; the iterate is contractive so errors
    # do not amplify — a 5% relative band is loose but meaningful.
    ref = np.asarray(w32)
    got = np.asarray(w16, np.float32)
    rel = np.abs(got - ref) / (np.abs(ref) + 1e-2)
    assert float(rel.max()) < 0.05, float(rel.max())


def test_newton_schulz_f32_path_unchanged_by_bf16_support():
    """The f32 path's casts are identities: same-dtype astype is a
    traced no-op, so adding bf16 support must not perturb f32 numerics.
    Pinned against a direct dtype check + determinism (the golden npz
    in test_whitener_backends pins the absolute numbers)."""
    rng = np.random.default_rng(1)
    a = rng.normal(size=(8, 4, 4))
    spd = _shrink(
        jnp.asarray(a @ a.transpose(0, 2, 1) + 4 * np.eye(4), jnp.float32),
        1e-3,
    )
    w1 = newton_schulz_inverse_sqrt(spd, 5)
    w2 = newton_schulz_inverse_sqrt(spd, 5)
    assert w1.dtype == jnp.float32
    np.testing.assert_array_equal(np.asarray(w1), np.asarray(w2))


@pytest.mark.parametrize("name", WHITENER_NAMES)
def test_group_whiten_bf16_every_backend(name):
    """bf16 activations through every backend: finite bf16 output, f32
    running stats (the EMA contract — reduced precision never touches
    the running statistics), and train-matrix numerics that actually
    whiten (output covariance near identity)."""
    wh = get_whitener(name)
    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(512, 8)), jnp.bfloat16)
    stats = wh.init_stats(8, 4)
    y, new_stats = group_whiten(
        x, stats, group_size=4, train=True, whitener=name
    )
    assert y.dtype == jnp.bfloat16
    assert y.shape == x.shape
    assert bool(jnp.isfinite(y).all())
    assert new_stats.mean.dtype == jnp.float32
    assert new_stats.cov.dtype == jnp.float32
    if name != "swbn":  # SWBN converges over steps, not in one batch
        yf = np.asarray(y, np.float32).reshape(512, 2, 4)
        for gi in range(2):
            cov = np.cov(yf[:, gi, :], rowvar=False, bias=True)
            np.testing.assert_allclose(
                cov, np.eye(4), atol=0.1,
                err_msg=f"{name} group {gi} not whitened under bf16",
            )


def test_group_whiten_bf16_cholesky_matches_promoted_f32():
    """The promote policy's guarantee, concretely: a bf16 batch through
    Cholesky produces the SAME factorization as whitening the f32 cast
    of that batch (the only differences are the input rounding and the
    final cast back — the factorization itself runs f32 either way)."""
    rng = np.random.default_rng(3)
    xb = jnp.asarray(rng.normal(size=(256, 8)), jnp.bfloat16)
    wh = get_whitener("cholesky")
    stats = wh.init_stats(8, 4)
    _, st_bf = group_whiten(xb, stats, group_size=4, train=True)
    _, st_f32 = group_whiten(
        xb.astype(jnp.float32), stats, group_size=4, train=True
    )
    np.testing.assert_array_equal(
        np.asarray(st_bf.cov), np.asarray(st_f32.cov)
    )
    np.testing.assert_array_equal(
        np.asarray(st_bf.mean), np.asarray(st_f32.mean)
    )


# ------------------------------------------------------- train-side casts


def test_grads_in_param_dtype_casts_to_param_dtype():
    from dwt_tpu.train.optim import grads_in_param_dtype

    params = {"w": jnp.zeros((3,), jnp.float32),
              "b": jnp.zeros((2,), jnp.float32)}
    grads = {"w": jnp.ones((3,), jnp.bfloat16),
             "b": jnp.ones((2,), jnp.float32)}
    out = grads_in_param_dtype(grads, params)
    assert out["w"].dtype == jnp.float32
    assert out["b"].dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(out["w"]), 1.0)


def test_bf16_train_step_keeps_f32_params_and_opt_state():
    """One digits train step at model dtype bf16: params, grads-applied
    params, and optimizer moments all stay f32 (flax param_dtype + the
    step-side grad cast) — the 'params and optimizer state stay f32'
    half of the --compute_dtype contract."""
    import optax

    from dwt_tpu.nn import LeNetDWT
    from dwt_tpu.train import adam_l2, create_train_state
    from dwt_tpu.train.steps import make_digits_train_step

    model = LeNetDWT(group_size=4, dtype=jnp.bfloat16)
    rng = np.random.default_rng(4)
    batch = {
        "source_x": jnp.asarray(
            rng.normal(size=(8, 28, 28, 1)), jnp.bfloat16
        ),
        "source_y": jnp.asarray(rng.integers(0, 10, size=(8,))),
        "target_x": jnp.asarray(
            rng.normal(size=(8, 28, 28, 1)), jnp.bfloat16
        ),
    }
    tx = adam_l2(1e-3)
    state = create_train_state(
        model, jax.random.key(0),
        jnp.stack([batch["source_x"], batch["target_x"]]), tx,
    )
    step = jax.jit(make_digits_train_step(model, tx))
    new_state, metrics = step(state, batch)
    for leaf in jax.tree.leaves(new_state.params):
        assert leaf.dtype == jnp.float32
    for leaf in jax.tree.leaves(new_state.opt_state):
        if hasattr(leaf, "dtype") and jnp.issubdtype(
            leaf.dtype, jnp.floating
        ):
            assert leaf.dtype == jnp.float32
    assert np.isfinite(float(metrics["loss"]))


# ------------------------------------------------------ config resolution


def test_resolve_compute_dtype_default_and_alias():
    from dwt_tpu.config import DigitsConfig, resolve_compute_dtype

    assert resolve_compute_dtype(DigitsConfig()) == "f32"
    assert resolve_compute_dtype(
        DigitsConfig(compute_dtype="bf16")
    ) == "bf16"
    # Legacy --bf16 alias maps onto the unified knob.
    assert resolve_compute_dtype(DigitsConfig(bf16=True)) == "bf16"
    with pytest.raises(ValueError, match="compute_dtype"):
        resolve_compute_dtype(DigitsConfig(compute_dtype="fp8"))


def test_cli_exposes_compute_dtype_flag():
    """Both CLIs accept --compute_dtype and thread it into the config
    (config_from_args filters by dataclass fields, so presence in both
    proves the wiring end to end without running a training job)."""
    from dwt_tpu.cli import officehome, usps_mnist

    for mod in (usps_mnist, officehome):
        args = mod.build_parser().parse_args(["--compute_dtype", "bf16"])
        cfg = mod.config_from_args(args)
        assert cfg.compute_dtype == "bf16"


# ------------------------------------------------------- CLI-level proofs


def _run_digits(tmp_path, tag, extra):
    from dwt_tpu.cli.usps_mnist import main

    jsonl = tmp_path / f"{tag}.jsonl"
    acc = main([
        "--synthetic", "--synthetic_size", "32",
        "--source_batch_size", "8", "--target_batch_size", "8",
        "--test_batch_size", "16", "--group_size", "4",
        "--epochs", "2", "--log_interval", "100",
        "--metrics_jsonl", str(jsonl),
    ] + extra)
    records = [json.loads(l) for l in jsonl.read_text().splitlines()]
    digest = [
        r for r in records if r["kind"] == "params_digest"
    ][-1]["digest"]
    return acc, digest, records


@pytest.mark.slow
def test_digits_cli_compute_dtype_f32_bitwise_default(tmp_path):
    """--compute_dtype f32 IS the default path: identical final params
    digest — the flag must be a no-op at default precision (acceptance:
    f32 digests bitwise-identical to the pre-flag CLI)."""
    acc0, digest0, _ = _run_digits(tmp_path, "default", [])
    acc1, digest1, _ = _run_digits(
        tmp_path, "f32", ["--compute_dtype", "f32"]
    )
    assert digest0 == digest1
    assert acc0 == acc1


@pytest.mark.slow
@pytest.mark.parametrize("name", WHITENER_NAMES)
def test_digits_cli_bf16_band_per_backend(tmp_path, name):
    """End-of-run accuracy under --compute_dtype bf16 stays within the
    synthetic band of the f32 run, per whitener backend (NS factorizes
    natively in bf16 — the arm that actually exercises reduced-precision
    factorization numerics)."""
    acc_f32, _, _ = _run_digits(
        tmp_path, f"f32_{name}", ["--whitener", name]
    )
    acc_bf16, _, _ = _run_digits(
        tmp_path, f"bf16_{name}",
        ["--whitener", name, "--compute_dtype", "bf16"],
    )
    # 32-sample synthetic test set quantizes accuracy at 3.125 %/item;
    # same convention as the backend-parity bands.
    assert abs(acc_f32 - acc_bf16) <= 12.5, (name, acc_f32, acc_bf16)


def _run_officehome(tmp_path, tag, extra):
    from dwt_tpu.cli.officehome import main

    jsonl = tmp_path / f"{tag}.jsonl"
    acc = main([
        "--synthetic", "--synthetic_size", "24", "--arch", "tiny",
        "--source_batch_size", "4", "--test_batch_size", "8",
        "--num_iters", "4", "--check_acc_step", "4",
        "--group_size", "4", "--log_interval", "100",
        "--metrics_jsonl", str(jsonl),
    ] + extra)
    records = [json.loads(l) for l in jsonl.read_text().splitlines()]
    digests = [r for r in records if r["kind"] == "params_digest"]
    digest = digests[-1]["digest"] if digests else None
    return acc, digest


@pytest.mark.slow
def test_officehome_cli_compute_dtype_f32_bitwise_default(tmp_path):
    acc0, digest0 = _run_officehome(tmp_path, "default", [])
    acc1, digest1 = _run_officehome(
        tmp_path, "f32", ["--compute_dtype", "f32"]
    )
    assert digest0 == digest1
    assert acc0 == acc1


@pytest.mark.slow
def test_officehome_cli_bf16_band(tmp_path):
    acc_f32, _ = _run_officehome(tmp_path, "f32", [])
    acc_bf16, _ = _run_officehome(
        tmp_path, "bf16", ["--compute_dtype", "bf16"]
    )
    # 12-sample synthetic test set quantizes at ~8.3 %/item.
    assert abs(acc_f32 - acc_bf16) <= 25.0, (acc_f32, acc_bf16)
