"""Native (C++) fused augmentation kernels: parity with the Python path.

The native module fuses the per-item pixel tails of the input pipeline
(reference semantics: ``resnet50_dwt_mec_officehome.py:481-492,535-543``):

* ``normalize_from_u8``  == ToArray() -> Normalize(mean, std)
* ``warp_affine_normalize_from_u8`` == ToArray -> cv2.warpAffine(m) ->
  Normalize, with the blur no-op folded away.

Tolerances: the normalize fusion is float32-exact; the warp is compared
both against an exact float64 bilinear golden (tight) and against the
cv2 path (loose — cv2 quantizes sample coordinates to 1/32 px).
"""

import numpy as np
import pytest

from dwt_tpu import native
from dwt_tpu.data.transforms import (
    Compose,
    FusedAffineBlurNormalize,
    FusedToArrayNormalize,
    Normalize,
    ToArray,
    draw_affine_matrix,
    gaussian_blur,
    warp_affine,
)

MEAN = [0.485, 0.456, 0.406]
STD = [0.229, 0.224, 0.225]

needs_native = pytest.mark.skipif(
    not native.available(), reason="native library unavailable (no g++?)"
)

# The native-vs-cv2 comparisons assume cv2.warpAffine behaves like exact
# bilinear up to its documented 1/32-px fixed-point coordinate
# quantization (<~0.02 on the normalized scale).  Some cv2 builds (e.g.
# this container's headless 4.12) deviate from the float64 golden by 5x
# that, which makes "native within 0.05 of cv2" unsatisfiable even
# though the native kernel matches exact math to 1.5e-3 — measured, so
# the skip reason names the number.  The float64-golden tests below keep
# pinning the kernel's correctness either way.
_CV2_GOLDEN_BUDGET = 0.02


def _cv2_golden_deviation():
    """Max |cv2 warp chain − float64 golden| over a few seeded draws, or
    None when cv2 is absent (transforms fall back to scipy)."""
    from dwt_tpu.data import transforms

    if not transforms._HAS_CV2:
        return None
    a = _img(64, 64, seed=17)
    rng = np.random.default_rng(17)
    worst = 0.0
    for _ in range(3):
        m = draw_affine_matrix(rng, 0.1)
        got = (
            warp_affine(a.astype(np.float32) / 255.0, m) - np.float32(MEAN)
        ) / np.float32(STD)
        worst = max(worst, np.abs(got - _golden_warp_norm(a, m, MEAN, STD)).max())
    return float(worst)


def _cv2_comparable():
    dev = _cv2_golden_deviation()
    if dev is None:
        return False, "cv2 unavailable (warp_affine falls back to scipy)"
    if dev > _CV2_GOLDEN_BUDGET:
        return False, (
            f"this cv2 build's warpAffine deviates {dev:.3f} from the "
            f"float64 bilinear golden (> {_CV2_GOLDEN_BUDGET}): the "
            "native-vs-cv2 tolerance assumes 1/32-px fixed-point "
            "behavior; the float64-golden tests pin the native kernel"
        )
    return True, ""


def _img(h=61, w=53, c=3, seed=0):
    return np.random.default_rng(seed).integers(
        0, 256, size=(h, w, c), dtype=np.uint8
    )


def _golden_warp_norm(a_u8, m, mean, std):
    """Exact float64 reference of the fused op: invert m, bilinear with
    zero border, /255, normalize."""
    h, w, c = a_u8.shape
    full = np.eye(3)
    full[:2] = np.asarray(m, np.float64)
    inv = np.linalg.inv(full)
    ys, xs = np.mgrid[0:h, 0:w]
    sx = inv[0, 0] * xs + inv[0, 1] * ys + inv[0, 2]
    sy = inv[1, 0] * xs + inv[1, 1] * ys + inv[1, 2]
    x0 = np.floor(sx).astype(int)
    y0 = np.floor(sy).astype(int)
    fx = sx - x0
    fy = sy - y0
    out = np.zeros((h, w, c))
    src = a_u8.astype(np.float64)
    for dy, dx, wgt in (
        (0, 0, (1 - fx) * (1 - fy)),
        (0, 1, fx * (1 - fy)),
        (1, 0, (1 - fx) * fy),
        (1, 1, fx * fy),
    ):
        yy, xx = y0 + dy, x0 + dx
        inb = (yy >= 0) & (yy < h) & (xx >= 0) & (xx < w)
        vals = np.where(
            inb[..., None],
            src[np.clip(yy, 0, h - 1), np.clip(xx, 0, w - 1)],
            0.0,
        )
        out += wgt[..., None] * vals
    return (out / 255.0 - np.asarray(mean)) / np.asarray(std)


# Evaluated here (not next to its helpers above): the probe needs _img
# and _golden_warp_norm, defined in between.
_CV2_OK, _CV2_SKIP_REASON = _cv2_comparable()

needs_comparable_cv2 = pytest.mark.skipif(not _CV2_OK,
                                          reason=_CV2_SKIP_REASON)


@needs_native
def test_normalize_from_u8_matches_python_chain():
    a = _img()
    got = native.normalize_from_u8(a, np.float32(MEAN), np.float32(STD))
    want = Normalize(MEAN, STD)(ToArray()(a))
    assert got.dtype == np.float32
    np.testing.assert_allclose(got, want, atol=1e-6)


@needs_native
@pytest.mark.parametrize("sigma", [0.1, 0.3])
def test_warp_norm_matches_float64_golden(sigma):
    a = _img(97, 89)
    rng = np.random.default_rng(3)
    for _ in range(5):
        m = draw_affine_matrix(rng, sigma)
        got = native.warp_affine_normalize_from_u8(
            a, m, np.float32(MEAN), np.float32(STD)
        )
        want = _golden_warp_norm(a, m, MEAN, STD)
        # The kernel keeps sample coordinates in float32 (incremental
        # per-row accumulation); a coordinate ulp propagates through
        # 255-ranged pixel gradients and the /std scaling into ~1e-3
        # worst-case on the normalized scale — 40x below cv2's own
        # 1/32-px fixed-point quantization, and invisible to training.
        np.testing.assert_allclose(got, want, atol=1.5e-3)


@needs_native
@needs_comparable_cv2
def test_warp_norm_close_to_cv2_path():
    a = _img(128, 128)
    rng = np.random.default_rng(7)
    for _ in range(5):
        m = draw_affine_matrix(rng)
        got = native.warp_affine_normalize_from_u8(
            a, m, np.float32(MEAN), np.float32(STD)
        )
        want = (
            warp_affine(a.astype(np.float32) / 255.0, m)
            - np.float32(MEAN)
        ) / np.float32(STD)
        d = np.abs(got - want)
        # cv2 uses 1/32-px fixed-point sample coordinates; bounded by the
        # max per-pixel jump (~1/255-ranged gradients / std).
        assert d.max() < 0.05 and d.mean() < 2e-3


@needs_native
def test_warp_zero_border_normalizes_zero():
    # Strong zoom-in: the destination corners sample far outside the
    # source and must be exactly (0 - mean)/std, matching
    # warp(border=0) -> normalize order.
    a = _img(64, 64)
    m = np.float32([[4.0, 0, 0], [0, 4.0, 0]])  # dst covers src/4 region
    got = native.warp_affine_normalize_from_u8(
        a, m, np.float32(MEAN), np.float32(STD)
    )
    # inverse maps dst corner (63, 63) -> (15.75, 15.75): in bounds; use
    # a shifted matrix that pushes samples negative instead.
    m2 = np.float32([[1.0, 0, 80.0], [0, 1.0, 80.0]])  # src shifted off
    got2 = native.warp_affine_normalize_from_u8(
        a, m2, np.float32(MEAN), np.float32(STD)
    )
    border = (0.0 - np.float32(MEAN)) / np.float32(STD)
    np.testing.assert_allclose(got2[0, 0], border, atol=1e-6)
    assert np.isfinite(got).all()


@needs_native
@needs_comparable_cv2
def test_fused_transforms_match_fallback_streams():
    # Same seed: the fused class and the manual unfused chain must draw
    # identical matrices and produce matching outputs (within the cv2
    # fixed-point tolerance when cv2 backs warp_affine).
    a = _img(96, 96, seed=5)

    fused = FusedAffineBlurNormalize(
        MEAN, STD, rng=np.random.default_rng(11)
    )
    got = fused(a)

    rng = np.random.default_rng(11)
    m = draw_affine_matrix(rng, 0.1)
    want = Normalize(MEAN, STD)(
        gaussian_blur(warp_affine(ToArray()(a), m))
    )
    assert np.abs(got - want).max() < 0.05


@needs_native
def test_fused_normalize_matches_fallback_stream_exact():
    # Split from the warp comparison above: the normalize fusion is
    # float32-exact and does not depend on the cv2 build, so it keeps
    # running where the warp comparison must skip.
    a = _img(96, 96, seed=5)
    f2 = FusedToArrayNormalize(MEAN, STD)
    np.testing.assert_allclose(
        f2(a), Normalize(MEAN, STD)(ToArray()(a)), atol=1e-6
    )


def test_fused_transforms_work_without_native(monkeypatch):
    # Force the fallback branch; outputs must be the plain Python chain.
    monkeypatch.setattr(native, "available", lambda: False)
    a = _img(48, 40, seed=9)
    f = FusedToArrayNormalize(MEAN, STD)
    np.testing.assert_allclose(
        f(a), Normalize(MEAN, STD)(ToArray()(a)), atol=0
    )
    fused = FusedAffineBlurNormalize(MEAN, STD, rng=np.random.default_rng(2))
    rng = np.random.default_rng(2)
    m = draw_affine_matrix(rng, 0.1)
    want = Normalize(MEAN, STD)(gaussian_blur(warp_affine(ToArray()(a), m)))
    np.testing.assert_allclose(fused(a), want, atol=0)


def test_fused_grayscale_falls_back():
    # 2-D (PIL 'L'-mode) input isn't uint8 HWC — must route through the
    # fallback and still return HWC float32 with a channel axis.
    a = np.random.default_rng(1).integers(0, 256, (32, 32), dtype=np.uint8)
    out = FusedToArrayNormalize([0.5], [0.5])(a)
    assert out.shape == (32, 32, 1) and out.dtype == np.float32
