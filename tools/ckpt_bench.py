"""Checkpoint-stall microbench: per-save training-loop stall, sync vs async.

A synchronous ``save_state`` blocks the train loop for a device→host
fetch, a SHA-256 over the param tree, and an Orbax serialize + fsync +
atomic rename.  The async pipeline (``dwt_tpu.resilience.async_ckpt``)
charges the loop only a snapshot (``jnp.copy`` per leaf, dispatch-only)
plus a thread handoff; everything else runs on the writer thread and
overlaps the following train steps.

This tool measures exactly that hot-path stall: the wall time of the save
CALL alone.  Between saves it dispatches train-ish steps and then DRAINS
the device queue (untimed), and on the async path it joins the writer
(untimed) before the next timed enqueue — the regime the pipeline is
designed for, where the checkpoint cadence (minutes in production)
comfortably exceeds one save's duration (seconds).  Measuring with a
congested queue would charge the sync path for queue drain and the async
path for backpressure, i.e. measure the cadence configuration, not the
pipeline.  The writer's own wall time is reported separately — the stall
moved off the loop, it did not disappear.

Prints one JSON line:
``{"model": ..., "sync_save_ms": X, "async_enqueue_ms": Y,
   "stall_reduction_x": X/Y, "async_writer_ms": ..., ...}``

Acceptance gate for the ISSUE-2 pipeline: ``stall_reduction_x >= 5`` on
CPU.  Run with ``JAX_PLATFORMS=cpu python tools/ckpt_bench.py``.

``--delta`` (ISSUE-13) benches the content-addressed incremental store
against full whole-tree saves on a **frozen-backbone churn profile**:
between saves only the classifier head (``--churn`` regex, default
``fc5|fc_out``) moves — the flagship fine-tune's save shape, where a
frozen backbone's params AND Adam moments are bitwise-stable (zero
grads keep the moments still).  Reports per-save bytes on disk and the
synchronous save wall for both arms; the first delta save is the chain
base (full) and is reported separately.  Acceptance gate: on
tiny-resnet, a steady-state delta save writes <= 1/5 the bytes of a
full save (measured: ~1/360 — the head is that small a slice).

``--shared_store`` (ISSUE-16) measures the SWEEP's storage claim: N
frozen-backbone runs (``--runs``, same backbone bits, run-distinct
heads — the pair matrix's shape) checkpointing into one shared CAS
store versus N private stores.  Content addressing stores the shared
backbone once regardless of run count; the record's ``sweep_dedup_x``
is the measured private/shared total-byte ratio (→ ~N for backbone-
dominated trees).

``--processes 2`` (ISSUE-5) measures the MULTI-HOST arms on one machine:
the parent respawns itself as N distributed ranks (loopback
coordinator, the test harness's env-var convention) and rank 0 prints
the record.  Sync there is the coordinated Orbax save (collective-
bearing, barrier at the end — the path ``--async_ckpt`` used to
downgrade to); async is the collective-free host-shard pipeline
(``MultiHostAsyncCheckpointer``): the timed enqueue is snapshot +
host-side fetch, the untimed join covers the pure-I/O shard write plus
the consensus-driven promotion rendezvous.
"""

import argparse
import json
import os
import shutil
import statistics
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_state(model_name: str, batch: int):
    import jax
    import jax.numpy as jnp

    from dwt_tpu.nn import LeNetDWT, ResNetDWT
    from dwt_tpu.train import adam_l2, create_train_state

    tx = adam_l2(1e-3)
    if model_name == "lenet":
        model = LeNetDWT(group_size=4)
        sample = jnp.zeros((2, batch, 28, 28, 1), jnp.float32)
    elif model_name == "tiny-resnet":
        model = ResNetDWT(stage_sizes=(1, 1, 1, 1), num_classes=10,
                          group_size=4)
        sample = jnp.zeros((3, batch, 32, 32, 3), jnp.float32)
    else:
        raise SystemExit(f"unknown --model {model_name!r}")
    state = create_train_state(model, jax.random.key(0), sample, tx)
    return state, sample


def make_busywork(state):
    """A stand-in train step: enough dispatched device work between saves
    that the async path is measured against a busy queue, as in training."""
    import jax

    @jax.jit
    def bump(s):
        return s.replace(
            step=s.step + 1,
            params=jax.tree.map(lambda x: x * 0.999, s.params),
        )

    return bump


def _advance(state, bump, steps: int):
    """Dispatch ``steps`` steps, then drain the queue (untimed): both
    modes are measured against a quiet device, so the save-call timing is
    the save's own cost, not queue-drain attribution."""
    import jax

    for _ in range(steps):
        state = bump(state)
    jax.block_until_ready(jax.tree.leaves(state))
    return state


def bench_sync(state, bump, ckpt_dir: str, saves: int, steps_between: int):
    from dwt_tpu.utils.checkpoint import save_state

    stalls = []
    for k in range(saves):
        state = _advance(state, bump, steps_between)
        t0 = time.perf_counter()
        save_state(ckpt_dir, int(k + 1), state)
        stalls.append(time.perf_counter() - t0)
    return stalls, state


def bench_async(state, bump, ckpt_dir: str, saves: int, steps_between: int):
    from dwt_tpu.resilience import AsyncCheckpointer

    acp = AsyncCheckpointer()
    stalls, writer = [], []
    for k in range(saves):
        state = _advance(state, bump, steps_between)
        t0 = time.perf_counter()
        acp.save(ckpt_dir, int(k + 1), state)
        stalls.append(time.perf_counter() - t0)
        # Untimed writer join before the next timed enqueue: production
        # cadence >> save duration, so a real loop's next save never hits
        # backpressure — the join's cost is reported, not hidden.
        t0 = time.perf_counter()
        acp.flush()
        writer.append(time.perf_counter() - t0)
    return stalls, writer, state


def bench_async_multihost(state, bump, ckpt_dir: str, saves: int,
                          steps_between: int):
    """Multi-host async arm: timed snapshot+host-fetch enqueue; untimed
    writer join + finalization rendezvous (gather done-bits → process-0
    promotion → barrier) so every timed enqueue starts quiescent."""
    from dwt_tpu.resilience import Coordinator, MultiHostAsyncCheckpointer

    coord = Coordinator()
    acp = MultiHostAsyncCheckpointer()
    stalls, writer = [], []
    for k in range(saves):
        state = _advance(state, bump, steps_between)
        t0 = time.perf_counter()
        acp.save(ckpt_dir, int(k + 1), state)
        stalls.append(time.perf_counter() - t0)
        t0 = time.perf_counter()
        acp.flush()
        agreed = coord.agree_step(acp.done_seq)
        acp.promote_up_to(agreed)
        coord.agree_step(agreed)
        writer.append(time.perf_counter() - t0)
    return stalls, writer, state


def make_frozen_bump(state, churn_regex: str):
    """The frozen-backbone churn profile: one jitted step that perturbs
    ONLY the leaves whose tree path matches ``churn_regex`` (params and
    their mirrored optimizer moments both match — opt-state paths embed
    the param names) plus the step counter.  Everything else stays
    bitwise-stable, exactly like a frozen backbone under zero grads."""
    import re

    import jax
    import jax.numpy as jnp

    flat, treedef = jax.tree_util.tree_flatten_with_path(state)
    churn = [
        bool(re.search(churn_regex, jax.tree_util.keystr(p)))
        and hasattr(leaf, "dtype")
        and jnp.issubdtype(leaf.dtype, jnp.floating)
        for p, leaf in flat
    ]

    @jax.jit
    def bump(s):
        leaves = jax.tree_util.tree_leaves(s)
        out = [x * 0.999 if c else x for x, c in zip(leaves, churn)]
        s = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(s), out
        )
        return s.replace(step=s.step + 1)

    return bump, sum(churn)


def bench_delta(state, bump, scratch: str, saves: int):
    """Delta-vs-full byte/stall comparison under the frozen profile.

    Both arms save the SAME state sequence synchronously; the full arm
    is the existing whole-tree ``save_state`` (per-save bytes = the
    finalized step dir's size), the delta arm is the cas store (per-save
    bytes = the manifest's own accounting: blobs written + manifest).
    """
    import json as _json
    import os as _os

    import jax

    from dwt_tpu.ckpt import save_delta, tree_bytes
    from dwt_tpu.utils.checkpoint import host_fetch, save_state

    full_dir = _os.path.join(scratch, "full")
    delta_dir = _os.path.join(scratch, "delta")
    full_ms, full_bytes, delta_ms, delta_bytes = [], [], [], []
    for k in range(saves):
        state = _advance(state, bump, 1)
        t0 = time.perf_counter()
        path = save_state(full_dir, int(k + 1), state)
        full_ms.append((time.perf_counter() - t0) * 1e3)
        full_bytes.append(tree_bytes(path))
        t0 = time.perf_counter()
        path = save_delta(delta_dir, int(k + 1), host_fetch(state))
        delta_ms.append((time.perf_counter() - t0) * 1e3)
        # Symmetric accounting: blobs written + the manifest file itself
        # (the full arm's tree_bytes includes ITS manifest too).
        mpath = _os.path.join(path, "manifest.json")
        manifest = _json.load(open(mpath))
        delta_bytes.append(
            int(manifest["bytes_written"]) + _os.path.getsize(mpath)
        )
    return full_ms, full_bytes, delta_ms, delta_bytes


def run_delta_bench(args) -> dict:
    state, _ = build_state(args.model, args.batch)
    bump, churned = make_frozen_bump(state, args.churn)
    state = bump(state)  # compile outside the timed region
    scratch = args.ckpt_dir or tempfile.mkdtemp(prefix="dwt_ckpt_delta_")
    try:
        from dwt_tpu.utils.checkpoint import save_state

        save_state(os.path.join(scratch, "warmup"), 0, state)  # untimed
        full_ms, full_bytes, delta_ms, delta_bytes = bench_delta(
            state, bump, scratch, args.saves
        )
        # The first delta save is the chain base (a full save) — report
        # it separately; steady state is everything after it.
        steady_bytes = delta_bytes[1:] or delta_bytes
        steady_ms = delta_ms[1:] or delta_ms
        fb = statistics.median(full_bytes)
        db = statistics.median(steady_bytes)
        record = {
            "model": args.model,
            "mode": "delta_vs_full",
            "churn": args.churn,
            "churned_leaves": int(churned),
            "saves": args.saves,
            "full_save_ms": round(statistics.median(full_ms), 3),
            "full_bytes": int(fb),
            "delta_save_ms": round(statistics.median(steady_ms), 3),
            "delta_bytes": int(db),
            "delta_first_bytes": int(delta_bytes[0]),
            "bytes_reduction_x": round(fb / max(db, 1), 1),
        }
        print(json.dumps(record))
        return record
    finally:
        if args.ckpt_dir is None:
            shutil.rmtree(scratch, ignore_errors=True)


def _dir_bytes(root: str) -> int:
    total = 0
    for dirpath, _dirnames, filenames in os.walk(root):
        for name in filenames:
            try:
                total += os.path.getsize(os.path.join(dirpath, name))
            except OSError:
                continue
    return total


def run_shared_store_bench(args) -> dict:
    """The sweep's storage claim, measured: N frozen-backbone runs
    (same backbone bits, run-distinct heads — the OfficeHome pair
    matrix's shape, where every pair fine-tunes one pretrained
    backbone) checkpointing into ONE shared CAS store versus N private
    stores.  The backbone's blobs are content-addressed, so the shared
    store holds them once no matter how many runs reference them;
    ``sweep_dedup_x`` is the measured private/shared byte ratio."""
    import jax

    from dwt_tpu.ckpt import save_delta
    from dwt_tpu.utils.checkpoint import host_fetch

    state, _ = build_state(args.model, args.batch)
    bump, churned = make_frozen_bump(state, args.churn)
    state = bump(state)  # compile outside the timed region
    scratch = args.ckpt_dir or tempfile.mkdtemp(prefix="dwt_ckpt_sweep_")
    shared_store = os.path.join(scratch, "shared_blobs")
    try:
        # Run-distinct initial states: run i's head has advanced i extra
        # steps, the backbone is bitwise-identical across all of them —
        # distinct fine-tunes of one pretrained trunk.
        starts = []
        s = state
        for _ in range(args.runs):
            starts.append(s)
            s = bump(s)
        jax.block_until_ready(jax.tree.leaves(s))

        def _save_run(s0, ckpt_dir, store_root):
            s = s0
            for k in range(args.saves):
                s = bump(s)
                save_delta(
                    ckpt_dir, int(k + 1), host_fetch(s),
                    store_root=store_root,
                    # Shared store: local GC off — one run's view cannot
                    # see sibling references (the sweep supervisor's
                    # cross-run GC owns reclamation there).
                    gc=store_root is None,
                )

        for i, s0 in enumerate(starts):
            _save_run(s0, os.path.join(scratch, "shared", f"run{i}"),
                      shared_store)
        for i, s0 in enumerate(starts):
            _save_run(s0, os.path.join(scratch, "private", f"run{i}"),
                      None)  # default: a blobs/ store per run dir

        shared_bytes = _dir_bytes(shared_store)
        private_bytes = sum(
            _dir_bytes(os.path.join(scratch, "private", f"run{i}"))
            for i in range(args.runs)
        )
        # Manifests live in the run dirs either way; add the shared
        # arm's run dirs so both arms count manifest overhead alike.
        shared_bytes += sum(
            _dir_bytes(os.path.join(scratch, "shared", f"run{i}"))
            for i in range(args.runs)
        )
        record = {
            "model": args.model,
            "mode": "shared_store",
            "churn": args.churn,
            "churned_leaves": int(churned),
            "runs": args.runs,
            "saves": args.saves,
            "shared_store_bytes": int(shared_bytes),
            "private_store_bytes": int(private_bytes),
            "sweep_dedup_x": round(
                private_bytes / max(shared_bytes, 1), 2
            ),
        }
        print(json.dumps(record))
        return record
    finally:
        if args.ckpt_dir is None:
            shutil.rmtree(scratch, ignore_errors=True)


def _spawn_ranks(argv, processes: int) -> int:
    """Parent mode: respawn this script as N loopback-distributed ranks;
    forward rank 0's output (the JSON record)."""
    import socket
    import subprocess

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    procs = []
    for rank in range(processes):
        env = {k: v for k, v in os.environ.items()
               if k != "PALLAS_AXON_POOL_IPS"}
        env.update(
            JAX_PLATFORMS="cpu",
            DWT_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            DWT_NUM_PROCESSES=str(processes),
            DWT_PROCESS_ID=str(rank),
            PYTHONPATH=repo + os.pathsep + env.get("PYTHONPATH", ""),
        )
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), *argv],
            env=env,
            stdout=subprocess.PIPE if rank else None,
            text=bool(rank) or None,
        ))
    rc = 0
    for rank, proc in enumerate(procs):
        out, _ = proc.communicate(timeout=1800)
        rc = rc or proc.returncode
    return rc


def main(argv=None):
    p = argparse.ArgumentParser(description="per-save loop stall, sync vs async")
    p.add_argument("--model", choices=["lenet", "tiny-resnet"], default="lenet")
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--processes", type=int, default=1,
                   help=">1: respawn as N loopback-distributed ranks and "
                        "bench the MULTI-HOST arms (coordinated Orbax sync "
                        "save vs collective-free host-shard async)")
    p.add_argument("--saves", type=int, default=6,
                   help="timed saves per mode (one shared untimed warmup "
                        "save runs first: Orbax lazily builds its type-"
                        "handler registry and the finite-check jit "
                        "compiles on the first save)")
    p.add_argument("--steps_between", type=int, default=4,
                   help="dispatched train-ish steps between saves")
    p.add_argument("--ckpt_dir", type=str, default=None,
                   help="scratch directory (default: a fresh temp dir)")
    p.add_argument("--delta", action="store_true",
                   help="bench the content-addressed delta store vs full "
                        "whole-tree saves on the frozen-backbone churn "
                        "profile (bytes written + save stall per arm)")
    p.add_argument("--churn", type=str, default="fc5|fc_out",
                   help="regex over tree paths naming the leaves that "
                        "move between saves in the --delta profile "
                        "(default: the classifier head — params and "
                        "their mirrored optimizer moments)")
    p.add_argument("--shared_store", action="store_true",
                   help="bench N frozen-backbone runs checkpointing "
                        "into ONE shared CAS store vs N private stores "
                        "(the sweep's storage dedup claim)")
    p.add_argument("--runs", type=int, default=4,
                   help="simulated runs in the --shared_store arm")
    args = p.parse_args(argv)

    if args.shared_store:
        if args.processes > 1:
            raise SystemExit("--shared_store benches the single-process "
                             "sync arms; drop --processes")
        return run_shared_store_bench(args)
    if args.delta:
        if args.processes > 1:
            raise SystemExit("--delta benches the single-process sync "
                             "arms; drop --processes")
        return run_delta_bench(args)

    worker_rank = os.environ.get("DWT_PROCESS_ID")
    if args.processes > 1 and worker_rank is None:
        return _spawn_ranks(
            [a for a in (argv if argv is not None else sys.argv[1:])],
            args.processes,
        )
    multihost = args.processes > 1
    if multihost:
        from dwt_tpu.parallel import initialize_distributed

        initialize_distributed(
            coordinator_address=os.environ["DWT_COORDINATOR_ADDRESS"],
            num_processes=args.processes,
            process_id=int(worker_rank),
        )

    state, _ = build_state(args.model, args.batch)
    if multihost:
        # The loops' state lives on the global mesh (replicated): mirror
        # that here, or the coordinated Orbax arm refuses host-local
        # arrays and the shard arm wouldn't exercise the global-array
        # fetch path.
        import numpy as _np

        import jax
        from jax.experimental import multihost_utils
        from jax.sharding import Mesh, PartitionSpec

        mesh = Mesh(_np.array(jax.devices()), ("d",))
        state = multihost_utils.host_local_array_to_global_array(
            state, mesh, PartitionSpec()
        )
    bump = make_busywork(state)
    state = bump(state)  # compile outside the timed region

    # Multi-host ranks must share ONE scratch dir (the shared-ckpt_dir
    # layout the pipeline coordinates over): derive it from the port so
    # every rank of this bench — and only this bench — agrees on it.
    # Only auto-created scratch is cleaned up afterwards; a user-supplied
    # --ckpt_dir is left alone.
    auto_scratch = args.ckpt_dir is None
    if multihost and args.ckpt_dir is None:
        port = os.environ["DWT_COORDINATOR_ADDRESS"].rsplit(":", 1)[-1]
        args.ckpt_dir = os.path.join(
            tempfile.gettempdir(), f"dwt_ckpt_bench_mh_{port}"
        )
    scratch = args.ckpt_dir or tempfile.mkdtemp(prefix="dwt_ckpt_bench_")
    sync_dir = os.path.join(scratch, "sync")
    async_dir = os.path.join(scratch, "async")
    primary = not multihost or int(worker_rank) == 0
    try:
        # One untimed warmup save (Orbax registry + XLA finite-check jit).
        from dwt_tpu.utils.checkpoint import save_state

        save_state(os.path.join(scratch, "warmup"), 0, state)

        sync_stalls, state = bench_sync(
            state, bump, sync_dir, args.saves, args.steps_between
        )
        if multihost:
            async_stalls, writer, state = bench_async_multihost(
                state, bump, async_dir, args.saves, args.steps_between
            )
        else:
            async_stalls, writer, state = bench_async(
                state, bump, async_dir, args.saves, args.steps_between
            )

        sync_ms = statistics.median(sync_stalls) * 1e3
        async_ms = statistics.median(async_stalls) * 1e3
        record = {
            "model": args.model,
            "processes": args.processes,
            "saves": args.saves,
            "steps_between": args.steps_between,
            "sync_save_ms": round(sync_ms, 3),
            "async_enqueue_ms": round(async_ms, 3),
            "stall_reduction_x": round(sync_ms / max(async_ms, 1e-9), 1),
            "async_writer_ms": round(statistics.median(writer) * 1e3, 3),
        }
        if primary:
            print(json.dumps(record))
        return record
    finally:
        if auto_scratch and primary:
            shutil.rmtree(scratch, ignore_errors=True)


if __name__ == "__main__":
    out = main()
    if isinstance(out, int):  # parent mode forwards the ranks' status
        sys.exit(out)
