"""Job-slot supervisor: the fleet's respawn/probe machinery generalized
from HTTP replicas to preemptible training subprocesses.

Mapping from the serving fleet (``dwt_tpu/fleet/balancer.py``):

* replica slot → **job slot** (``--slots`` concurrent training
  subprocesses; the pair matrix queues behind them);
* ``/healthz`` probe → **heartbeat liveness**: a job proves life by
  appending to its metrics JSONL (every ``train``/``heartbeat`` record
  bumps the mtime); a job silent past ``--job_stall_timeout_s`` is
  SIGKILLed and charged a crash;
* :class:`~dwt_tpu.fleet.balancer.Respawner` →
  :class:`~dwt_tpu.fleet.retry.RespawnBudget` per pair: crashes back
  off exponentially and quarantine the pair after
  ``--job_max_respawns`` — the rest of the matrix completes;
* balancer ``/metrics`` → the aggregated scrape surface: the
  supervisor's own registry merged with every running job's exposition
  under a ``pair`` label (``obs.prom.merge_expositions``).

Preemption is FREE: a job that exits 0 with a ``preempt`` record in its
JSONL (the loops' SIGTERM save-and-exit contract) is rescheduled without
touching its crash budget — its resume is exact (the checkpoint's
``data_state``), so the matrix's results are identical to an
undisturbed sweep's.  The supervisor itself is preemptible too: every
scheduling decision is journaled atomically BEFORE the spawn
(:mod:`~dwt_tpu.sweep.journal`), so a SIGKILLed supervisor relaunches,
adopts still-running jobs (pid + cmdline-token check), and reschedules
the rest.

All jobs share one CAS blob store (``--blob_store`` on the training
CLI): per-job local GC is disabled there, and the supervisor — the only
party that knows EVERY run dir — refcounts cross-run GC against the
union of all manifest roots (``gc_blobs(..., manifest_roots=...)``).
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import signal
import subprocess
import sys
import time
from typing import Callable, Dict, List, Optional, Tuple

from dwt_tpu.fleet.retry import RespawnBudget
from dwt_tpu.obs import prom
from dwt_tpu.obs.registry import get_registry
from dwt_tpu.resilience import inject
from dwt_tpu.resilience.notice import post_notice
from dwt_tpu.sweep import journal as jnl
from dwt_tpu.sweep.journal import SweepJournal, decide_adoption

log = logging.getLogger(__name__)


@dataclasses.dataclass
class JobSpec:
    """One pair's filesystem contract with its training subprocess.

    The job is a single-pair ``officehome_sweep`` invocation; the sweep
    wrapper's per-pair naming (ckpt under ``<run>/ckpt/<tag>``, metrics
    at ``<run>/metrics.<tag>.jsonl``) is deterministic, so the
    supervisor computes the same paths for liveness, resume-step, and
    GC accounting without any back-channel from the job."""

    source: str
    target: str
    run_dir: str

    @property
    def tag(self) -> str:
        return f"{self.source}2{self.target}"

    @property
    def pair_key(self) -> str:
        return f"{self.source}->{self.target}"

    @property
    def result_json(self) -> str:
        return os.path.join(self.run_dir, "result.json")

    @property
    def ckpt_base(self) -> str:
        return os.path.join(self.run_dir, "ckpt")

    @property
    def ckpt_tree(self) -> str:
        # officehome_sweep appends the tag to --ckpt_dir.
        return os.path.join(self.ckpt_base, self.tag)

    @property
    def metrics_base(self) -> str:
        return os.path.join(self.run_dir, "metrics.jsonl")

    @property
    def metrics_jsonl(self) -> str:
        # officehome_sweep rewrites --metrics_jsonl to <root>.<tag><ext>.
        return os.path.join(self.run_dir, f"metrics.{self.tag}.jsonl")

    @property
    def notice_file(self) -> str:
        return os.path.join(self.run_dir, "notice")

    @property
    def log_file(self) -> str:
        return os.path.join(self.run_dir, "job.log")


def _count_kinds(jsonl_path: str, kinds: Tuple[str, ...]) -> int:
    """How many records of the given kinds the job has logged — the
    preemption evidence (``preempt`` is fsync'd by the loops before
    exit 0, so a reap after the exit always sees it)."""
    try:
        f = open(jsonl_path, "r")
    except OSError:
        return 0
    n = 0
    with f:
        for line in f:
            try:
                if json.loads(line).get("kind") in kinds:
                    n += 1
            except ValueError:
                continue  # a torn tail line is not evidence
    return n


def _exporter_port(jsonl_path: str) -> Optional[int]:
    """The job's ephemeral ``/metrics`` port, from its
    ``metrics_exporter`` JSONL record (``--metrics_port 0``)."""
    try:
        f = open(jsonl_path, "r")
    except OSError:
        return None
    with f:
        for line in f:
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if rec.get("kind") == "metrics_exporter":
                try:
                    return int(rec["port"])
                except (KeyError, TypeError, ValueError):
                    return None
    return None


def _read_accuracy(spec: JobSpec) -> Optional[float]:
    """The pair's final accuracy from the job's results JSON, or None
    while incomplete.  Presence of the accuracy IS the completion
    signal — it works identically for owned jobs (whose exit code we
    see) and adopted ones (whose exit code died with the previous
    supervisor)."""
    try:
        with open(spec.result_json) as f:
            payload = json.load(f)
        acc = payload["pairs"][spec.pair_key]
    except (OSError, ValueError, KeyError, TypeError):
        return None
    return float(acc) if isinstance(acc, (int, float)) else None


@dataclasses.dataclass
class _RunningJob:
    spec: JobSpec
    proc: Optional[subprocess.Popen]  # None = adopted from a dead parent
    pid: int
    spawned_at: float
    preempts_at_spawn: int
    notice_sent: bool = False
    sigterm_due: bool = False
    port: Optional[int] = None
    log_fh: Optional[object] = None

    @property
    def alive(self) -> bool:
        if self.proc is not None:
            return self.proc.poll() is None
        return jnl.job_process_alive(self.pid, self.spec.run_dir)

    @property
    def returncode(self) -> Optional[int]:
        return self.proc.returncode if self.proc is not None else None


class SweepSupervisor:
    """Schedule ``pairs`` over bounded job slots until every pair is
    done or quarantined (class/module doc).  ``argv_fn(spec)`` builds a
    job's command line (the CLI wires the real training invocation;
    tests substitute cheap scripts); ``clock`` and ``popen`` are
    injectable the same way the fleet's are."""

    def __init__(
        self,
        pairs: List[Tuple[str, str]],
        sweep_root: str,
        argv_fn: Callable[[JobSpec], List[str]],
        *,
        slots: int = 2,
        job_max_respawns: int = 2,
        backoff_s: float = 1.0,
        poll_interval_s: float = 0.5,
        stall_timeout_s: float = 0.0,
        blob_store: Optional[str] = None,
        gc_every_polls: int = 0,
        gc_min_age_s: Optional[float] = None,
        alert_rules: Optional[str] = None,
        metrics_port: Optional[int] = None,
        clock: Callable[[], float] = time.monotonic,
        popen: Callable[..., subprocess.Popen] = subprocess.Popen,
    ):
        self.sweep_root = os.path.abspath(sweep_root)
        self.specs = {
            f"{s}2{t}": JobSpec(s, t, os.path.join(self.sweep_root, f"{s}2{t}"))
            for s, t in pairs
        }
        self.argv_fn = argv_fn
        self.slots = max(1, int(slots))
        self.job_max_respawns = int(job_max_respawns)
        self.poll_interval_s = float(poll_interval_s)
        self.stall_timeout_s = float(stall_timeout_s)
        self.blob_store = (
            os.path.abspath(blob_store) if blob_store else None
        )
        self.gc_every_polls = int(gc_every_polls)
        self.gc_min_age_s = gc_min_age_s
        self._clock = clock
        self._popen = popen
        # Crash budget per pair: `attempts` here counts CRASHES only —
        # preemption resumes are free (count=False), exactly the policy
        # split the fleet cannot express for replicas (an HTTP replica
        # has no orderly save-and-exit-0).
        self.budget = RespawnBudget(
            max_attempts=self.job_max_respawns, backoff_s=float(backoff_s),
            clock=clock,
        )
        os.makedirs(self.sweep_root, exist_ok=True)
        self.journal = SweepJournal.load(
            os.path.join(self.sweep_root, jnl.JOURNAL_NAME)
        )
        self.journal.ensure_pairs(
            pairs, lambda tag: self.specs[tag].run_dir
        )
        self.running: Dict[str, _RunningJob] = {}
        self._drain = False
        self._schedule_events = 0
        self._polls = 0
        self._gc_swept = [0, 0]

        reg = get_registry()
        self._m_state = reg.gauge(
            "dwt_sweep_pairs", "pairs by lifecycle state",
            labelnames=("state",),
        )
        self._m_respawns = reg.counter(
            "dwt_sweep_respawns_total",
            "job respawns after a crash", labelnames=("pair",),
        )
        self._m_preempts = reg.counter(
            "dwt_sweep_preempt_resumes_total",
            "preempted jobs rescheduled (save-and-exit-0 resumes)",
            labelnames=("pair",),
        )
        self._m_adopted = reg.counter(
            "dwt_sweep_adopted_total",
            "running jobs adopted by a relaunched supervisor",
        )
        self._m_gc_swept = reg.gauge(
            "dwt_sweep_gc_swept_bytes_total",
            "bytes swept from the shared store by cross-run GC",
        )
        self._engine = None
        if alert_rules:
            from dwt_tpu.obs.rules import AlertEngine, load_rules

            self._engine = AlertEngine(load_rules(alert_rules), registry=reg)
        self._exporter = None
        if metrics_port is not None:
            self._exporter = prom.start_exporter(
                int(metrics_port), render_fn=self._render_metrics
            )
            log.info(
                "sweep: aggregated /metrics on port %d",
                self._exporter.server_address[1],
            )

    # ------------------------------------------------------------ metrics

    def _render_metrics(self) -> str:
        """The sweep's one scrape surface: supervisor registry first,
        then every running job's exposition under its ``pair`` label —
        the fleet's merge, with pairs in place of replicas.  A job
        mid-compile (no exporter record yet) or mid-death simply
        contributes nothing this scrape."""
        self._refresh_state_gauge()
        parts: List[Tuple[Dict[str, str], str]] = [
            ({}, prom.render(get_registry()))
        ]
        for tag, job in list(self.running.items()):
            if job.port is None:
                job.port = _exporter_port(job.spec.metrics_jsonl)
            if job.port is None:
                continue
            try:
                import urllib.request

                with urllib.request.urlopen(
                    f"http://127.0.0.1:{job.port}/metrics", timeout=1.0
                ) as resp:
                    parts.append(
                        ({"pair": tag}, resp.read().decode("utf-8"))
                    )
            except Exception:  # noqa: BLE001 — scrape must not kill polls
                continue
        return prom.merge_expositions(parts)

    def _refresh_state_gauge(self) -> None:
        counts = {jnl.PENDING: 0, jnl.RUNNING: 0, jnl.DONE: 0,
                  jnl.QUARANTINED: 0}
        for e in self.journal.pairs.values():
            counts[e["status"]] = counts.get(e["status"], 0) + 1
        for state, n in counts.items():
            self._m_state.labels(state=state).set(float(n))

    # ---------------------------------------------------------- lifecycle

    def _install_signals(self) -> None:
        def _flag(signum, frame):
            # Flag only — the poll loop drains; a handler that does I/O
            # could tear a journal write it interrupted.
            self._drain = True

        try:
            signal.signal(signal.SIGTERM, _flag)
            signal.signal(signal.SIGINT, _flag)
        except ValueError:
            pass  # not the main thread (in-process tests)

    def _adopt_phase(self) -> None:
        """Relaunch recovery: walk the journal's ``running`` entries —
        adopt live jobs, harvest results a dead one already finished,
        reschedule the rest.  Crash/attempt history is restored into
        the budget so a relaunch cannot reset a pair's quarantine
        arithmetic."""
        for tag, entry in self.journal.pairs.items():
            self.budget.restore(tag, int(entry.get("crashes", 0)))
            if entry["status"] != jnl.RUNNING:
                continue
            spec = self.specs[tag]
            baseline = int(entry.get("preempt_baseline", 0))
            verdict = decide_adoption(entry)
            if verdict == "adopt":
                self.running[tag] = _RunningJob(
                    spec=spec, proc=None, pid=int(entry["pid"]),
                    spawned_at=self._clock(),
                    # The journaled baseline, NOT a fresh count: the job
                    # may have been preempted while unsupervised, and
                    # that evidence must survive into this reap.
                    preempts_at_spawn=baseline,
                )
                self._m_adopted.inc()
                log.info("sweep: adopted running job %s (pid %d)",
                         tag, entry["pid"])
                continue
            preempts_now = _count_kinds(spec.metrics_jsonl, ("preempt",))
            if preempts_now > baseline:
                # Parked (save-and-exit-0) while unsupervised: its
                # results JSON holds a partial accuracy — reschedule,
                # free, exactly as a supervised reap would have.
                self.journal.update(
                    tag, status=jnl.PENDING, pid=None,
                    preempts=int(entry.get("preempts", 0)) + 1,
                )
                self._m_preempts.labels(pair=tag).inc()
                log.info("sweep: %s was preempted while unsupervised — "
                         "rescheduling free", tag)
                continue
            acc = _read_accuracy(spec)
            if acc is not None:
                self.journal.update(
                    tag, status=jnl.DONE, accuracy=acc, pid=None
                )
                log.info("sweep: %s finished while unsupervised "
                         "(acc=%.2f)", tag, acc)
                continue
            self.journal.update(tag, status=jnl.PENDING, pid=None)
            log.info("sweep: rescheduling %s (journal pid %s not "
                     "adoptable)", tag, entry.get("pid"))

    # --------------------------------------------------------- scheduling

    def _spawn(self, tag: str) -> None:
        spec = self.specs[tag]
        os.makedirs(spec.run_dir, exist_ok=True)
        # Stale notice file from a previous preemption: the job's
        # watcher keys on existence, so an old notice would make the
        # resume save-and-park immediately (the loops keep training on
        # notice, but the follow-up SIGTERM contract reads cleaner with
        # a fresh slate per attempt).
        try:
            os.remove(spec.notice_file)
        except OSError:
            pass
        # A result file present at (re)spawn time is non-final by
        # definition — a preempted attempt's partial accuracy, which a
        # later reap must not mistake for the finish line.
        try:
            os.remove(spec.result_json)
        except OSError:
            pass
        entry = self.journal.pairs[tag]
        baseline = _count_kinds(spec.metrics_jsonl, ("preempt",))
        # Journal BEFORE spawn (module doc): a supervisor killed between
        # these two lines leaves a pid-less running entry any relaunch
        # reschedules.
        self.journal.update(
            tag, status=jnl.RUNNING, pid=None,
            attempts=int(entry["attempts"]) + 1,
            preempt_baseline=baseline,
        )
        self._schedule_events += 1
        inject.maybe_kill_supervisor_at_schedule(self._schedule_events)
        env = {k: v for k, v in os.environ.items() if k != inject.ENV_VAR}
        job_fault = inject.take_sweep_job_fault(tag)
        if job_fault is not None:
            env[inject.ENV_VAR] = json.dumps(job_fault)
            log.warning("sweep: injecting fault plan %s into %s",
                        job_fault, tag)
        log_fh = open(spec.log_file, "ab")
        try:
            proc = self._popen(
                self.argv_fn(spec), env=env, stdout=log_fh,
                stderr=subprocess.STDOUT,
                start_new_session=False,
            )
        except OSError:
            log_fh.close()
            self.journal.update(tag, status=jnl.PENDING, pid=None)
            raise
        self.journal.update(tag, pid=proc.pid)
        self.running[tag] = _RunningJob(
            spec=spec, proc=proc, pid=proc.pid, spawned_at=self._clock(),
            preempts_at_spawn=baseline,
            log_fh=log_fh,
        )
        log.info("sweep: %s scheduled (pid %d, attempt %d)",
                 tag, proc.pid, int(entry["attempts"]) + 1)

    def _schedule_pending(self) -> None:
        for tag, entry in self.journal.pairs.items():
            if len(self.running) >= self.slots or self._drain:
                return
            if entry["status"] != jnl.PENDING or tag in self.running:
                continue
            if not self.budget.ready(tag):
                continue  # backing off after a crash
            self._spawn(tag)

    # -------------------------------------------------------------- reaping

    def _resume_step(self, spec: JobSpec) -> Optional[int]:
        from dwt_tpu.utils.checkpoint import latest_step

        try:
            return latest_step(spec.ckpt_tree)
        except Exception:  # noqa: BLE001 — accounting only
            return None

    def _finish(self, tag: str, job: _RunningJob) -> None:
        if job.log_fh is not None:
            try:
                job.log_fh.close()
            except OSError:
                pass
        self.running.pop(tag, None)

    def _reap(self, tag: str, job: _RunningJob,
              stalled: bool = False) -> None:
        """Classify one finished (or killed-for-stalling) job:
        preempted (free reschedule), done, or crashed (budget-charged,
        quarantine once exhausted).  Preemption evidence is checked
        BEFORE the result file: a parked job returns its best-so-far
        accuracy through the normal exit path (the single-run CLI's
        rerun-to-resume contract), so its results JSON holds a PARTIAL
        number — only the resumed attempt's finish line is final."""
        spec = job.spec
        self._finish(tag, job)
        preempts = _count_kinds(spec.metrics_jsonl, ("preempt",))
        rc = job.returncode
        clean_exit = rc == 0 or (job.proc is None and rc is None)
        if (not stalled and clean_exit
                and preempts > job.preempts_at_spawn):
            # Save-and-exit-0 under SIGTERM: the loops fsync a `preempt`
            # record after their final save, so this is durable evidence
            # the job parked itself in good order.  Resume is free.
            entry = self.journal.update(
                tag, status=jnl.PENDING, pid=None,
                preempts=int(self.journal.pairs[tag]["preempts"]) + 1,
                resume_step=self._resume_step(spec),
            )
            self._m_preempts.labels(pair=tag).inc()
            log.info(
                "sweep: %s preempted (exit 0, resume step %s) — "
                "rescheduling free", tag, entry["resume_step"],
            )
            return
        acc = _read_accuracy(spec)
        if acc is not None and not stalled:
            self.journal.update(
                tag, status=jnl.DONE, accuracy=acc, pid=None,
                resume_step=None,
            )
            log.info("sweep: %s done (acc=%.2f)", tag, acc)
            return
        reason = (
            f"stalled: no metrics activity for {self.stall_timeout_s:g}s"
            if stalled else f"crashed rc={rc}"
        )
        self.budget.begin(tag)  # charge the crash + arm backoff
        crashes = self.budget.attempts(tag)
        if self.budget.exhausted(tag):
            self.journal.update(
                tag, status=jnl.QUARANTINED, pid=None, crashes=crashes,
                reason=f"{reason} ({crashes} crash(es), budget "
                       f"{self.job_max_respawns})",
                resume_step=self._resume_step(spec),
            )
            log.error(
                "sweep: %s QUARANTINED after %d crash(es) (%s); the rest "
                "of the matrix continues", tag, crashes, reason,
            )
            return
        self.journal.update(
            tag, status=jnl.PENDING, pid=None, crashes=crashes,
            reason=reason, resume_step=self._resume_step(spec),
        )
        self._m_respawns.labels(pair=tag).inc()
        log.warning(
            "sweep: %s %s — respawn %d/%d after backoff", tag, reason,
            crashes, self.job_max_respawns,
        )

    def _poll_running(self) -> None:
        now = self._clock()
        for tag, job in list(self.running.items()):
            if not job.alive:
                self._reap(tag, job)
                continue
            # Injected preemption: notice first (the scheduler's advance
            # warning — the job saves proactively and keeps training),
            # SIGTERM one poll later (save-and-exit-0).  Gated on a
            # flushed train/heartbeat record: only once the LOOP is
            # demonstrably running (its SIGTERM handler installed) is a
            # SIGTERM a preemption — during interpreter startup it would
            # be a plain kill, testing nothing about preemption.
            if (not job.notice_sent
                    and _count_kinds(job.spec.metrics_jsonl,
                                     ("train", "heartbeat")) > 0
                    and inject.take_sweep_preempt(tag)):
                post_notice(job.spec.notice_file)
                job.notice_sent = True
                job.sigterm_due = True
                log.warning("sweep: injected preemption notice to %s", tag)
                continue
            if job.sigterm_due:
                job.sigterm_due = False
                try:
                    os.kill(job.pid, signal.SIGTERM)
                except OSError:
                    pass
                continue
            if self.stall_timeout_s > 0:
                try:
                    beat = os.path.getmtime(job.spec.metrics_jsonl)
                except OSError:
                    beat = 0.0
                # monotonic clock vs file mtime: compare ages, anchored
                # at spawn (compile time produces no records and must
                # not read as a stall).
                silent_s = min(
                    now - job.spawned_at,
                    time.time() - beat if beat else float("inf"),
                )
                if silent_s > self.stall_timeout_s:
                    log.error(
                        "sweep: %s silent for %.0fs — SIGKILL (wedged "
                        "job)", tag, silent_s,
                    )
                    try:
                        os.kill(job.pid, signal.SIGKILL)
                    except OSError:
                        pass
                    if job.proc is not None:
                        try:
                            job.proc.wait(timeout=10.0)
                        except Exception:  # noqa: BLE001
                            pass
                    self._reap(tag, job, stalled=True)

    # ------------------------------------------------------------------ GC

    def manifest_roots(self) -> List[str]:
        """Every run's checkpoint tree — the union ``gc_blobs`` must
        refcount against.  ALL pairs count, not just live ones: a
        quarantined run's checkpoints may be wanted for debugging, and
        a done run's for warm starts; reclaiming a finished run is an
        explicit operator action (delete its run dir, then GC)."""
        return [
            spec.ckpt_tree for spec in self.specs.values()
            if os.path.isdir(spec.ckpt_tree)
        ]

    def gc_shared_store(self) -> Tuple[int, int]:
        """One cross-run GC pass over the shared store (no-op without
        ``--blob_store``)."""
        if not self.blob_store or not os.path.isdir(self.blob_store):
            return 0, 0
        from dwt_tpu.ckpt.store import GC_MIN_AGE_S, gc_blobs

        roots = self.manifest_roots()
        if not roots:
            return 0, 0
        swept, swept_bytes = gc_blobs(
            self.blob_store,
            min_age_s=(
                self.gc_min_age_s if self.gc_min_age_s is not None
                else GC_MIN_AGE_S
            ),
            manifest_roots=roots,
        )
        self._gc_swept[0] += swept
        self._gc_swept[1] += swept_bytes
        self._m_gc_swept.set(float(self._gc_swept[1]))
        return swept, swept_bytes

    # ---------------------------------------------------------------- drain

    def _drain_running(self) -> None:
        """Supervisor SIGTERM: warn every job (notice file), SIGTERM
        them, wait for the save-and-exit-0, journal them pending.  The
        relaunch resumes the whole matrix exactly where it parked."""
        log.warning(
            "sweep: draining %d running job(s) before exit",
            len(self.running),
        )
        for job in self.running.values():
            post_notice(job.spec.notice_file)
            try:
                os.kill(job.pid, signal.SIGTERM)
            except OSError:
                pass
        deadline = self._clock() + 120.0
        while self.running and self._clock() < deadline:
            for tag, job in list(self.running.items()):
                if not job.alive:
                    self._reap(tag, job)
            time.sleep(0.2)
        for tag, job in list(self.running.items()):
            # Still alive past the grace window: record it running so a
            # relaunch can adopt it.
            self._finish(tag, job)
            self.journal.update(tag, status=jnl.RUNNING, pid=job.pid)

    # ----------------------------------------------------------------- run

    def run(self) -> dict:
        """Drive the matrix to completion; returns the summary record
        (per-pair accuracies, quarantines, respawn/preempt counts)."""
        self._install_signals()
        self._adopt_phase()
        while not self.journal.all_settled():
            if self._drain:
                self._drain_running()
                break
            self._poll_running()
            self._schedule_pending()
            self._polls += 1
            if (self.gc_every_polls > 0
                    and self._polls % self.gc_every_polls == 0):
                self.gc_shared_store()
            if self._engine is not None:
                self._engine.maybe_evaluate()
            self._refresh_state_gauge()
            if self.journal.all_settled():
                break
            time.sleep(self.poll_interval_s)
        if not self._drain and self.blob_store:
            self.gc_shared_store()
        self._refresh_state_gauge()
        return self.summary()

    def summary(self) -> dict:
        results = {
            e["source"] + "->" + e["target"]: e["accuracy"]
            for e in self.journal.pairs.values()
            if e["status"] == jnl.DONE and e["accuracy"] is not None
        }
        quarantined = {
            tag: e["reason"]
            for tag, e in self.journal.pairs.items()
            if e["status"] == jnl.QUARANTINED
        }
        return {
            "kind": "sweep_summary",
            "pairs": results,
            "mean": sum(results.values()) / max(len(results), 1),
            "completed": len(results),
            "total": len(self.journal.pairs),
            "quarantined": quarantined,
            "drained": self._drain,
            "respawns": {
                tag: e["crashes"] for tag, e in self.journal.pairs.items()
                if e["crashes"]
            },
            "preempt_resumes": {
                tag: e["preempts"] for tag, e in self.journal.pairs.items()
                if e["preempts"]
            },
            "gc_swept_bytes": self._gc_swept[1],
        }
