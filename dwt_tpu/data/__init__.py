"""dwt_tpu.data — host-side input pipelines (L1 of SURVEY §1).

Re-provides the reference's data layer — USPS/MNIST digit datasets
(``usps_mnist.py:26-181``), the ImageFolder walker with the dual-view
``transform_aug`` triple protocol (``utils/folder.py:58-190,138-147``), and
the OfficeHome augmentation stack (``resnet50_dwt_mec_officehome.py:481-492,
527-543``) — as plain numpy/PIL pipelines built for feeding jitted TPU
steps:

* datasets hand out HWC float32 numpy; batching stacks to NHWC — the TPU's
  native layout (no NCHW anywhere);
* no worker processes: decode/augment cost is hidden by a background
  prefetch thread that overlaps host work with device steps
  (``prefetch_to_device``), the JAX equivalent of DataLoader workers;
* per-process sharding for multi-host DP is a ``shard=(index, count)``
  slice at the sampler, mirroring what DistributedSampler would do.
"""

from dwt_tpu.data.datasets import (
    ArrayDataset,
    ImageFolderDataset,
    load_mnist,
    load_usps,
)
from dwt_tpu.data.transforms import (
    Compose,
    FusedAffineBlurNormalize,
    FusedToArrayNormalize,
    Normalize,
    RandomCrop,
    RandomHorizontalFlip,
    Resize,
    ThreadLocalRng,
    ToArray,
    draw_affine_matrix,
    gaussian_blur,
    random_affine,
    warp_affine,
)
from dwt_tpu.data.loader import (
    QuarantineRegistry,
    batch_iterator,
    infinite,
    prefetch_to_device,
)
from dwt_tpu.data.sampler import (
    SeekableSampler,
    epoch_batch_count,
)
from dwt_tpu.data.pipeline import (
    DATA_STATE_VERSION,
    DataPlane,
    OrderedWorkerPool,
    StreamPos,
)

__all__ = [
    "ArrayDataset",
    "ImageFolderDataset",
    "load_mnist",
    "load_usps",
    "Compose",
    "FusedAffineBlurNormalize",
    "FusedToArrayNormalize",
    "Normalize",
    "RandomCrop",
    "RandomHorizontalFlip",
    "Resize",
    "ThreadLocalRng",
    "ToArray",
    "draw_affine_matrix",
    "gaussian_blur",
    "random_affine",
    "warp_affine",
    "QuarantineRegistry",
    "batch_iterator",
    "infinite",
    "prefetch_to_device",
    "SeekableSampler",
    "epoch_batch_count",
    "DATA_STATE_VERSION",
    "DataPlane",
    "OrderedWorkerPool",
    "StreamPos",
]
