"""Backbone registry + ViT-DWT + padded-head contracts (ISSUE-19).

Three contract groups:

* **registry** — one name → one constructor, uniform kwarg surface, the
  train loop's ``build_model`` consumes any entry with no special-casing;
* **ViT-DWT** — train/eval forward shapes and the whitening-site
  placement (DomainWhiten at patch embed + early blocks, DomainBatchNorm
  deeper) on the tiny config;
* **padded head** — ``pad_classes_to`` pads the head's kernel columns
  but slices the logits INSIDE the forward, so logits, eval counters
  (on a ragged masked chunk), and loss sums are BITWISE those of the
  unpadded head with the same weights; a divisible-classes control pads
  to a no-op.

The resnet152 rules-file validation runs over eval_shape (abstract
trace, no replicated materialization) so even the 60M-param tree stays
tier-1; the one >10 s param here (resnet padded-head parity) is
slow-marked (t1 budget).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dwt_tpu.nn import (
    BACKBONES,
    ResNetDWT,
    ViTDWT,
    build_backbone,
    padded_num_classes,
    register_backbone,
)
from dwt_tpu.train import adam_l2, create_train_state
from dwt_tpu.train.steps import eval_counters, make_accum_eval_step


# --------------------------------------------------------------- registry


def test_registry_entries_and_uniform_kwarg_surface():
    assert {"resnet50", "resnet101", "resnet152", "tiny",
            "vit_dwt", "vit_tiny"} <= set(BACKBONES)
    # Every entry takes the common kwarg surface the train loop passes.
    for name in ("tiny", "vit_tiny"):
        m = build_backbone(
            name, num_classes=7, group_size=4, momentum=0.05,
            axis_name=None, use_pallas=False, whitener="cholesky",
            dtype=jnp.float32, remat=False, pad_classes_to=2,
        )
        assert m.num_classes == 7 and m.pad_classes_to == 2


def test_registry_unknown_name_lists_registered():
    with pytest.raises(ValueError, match="resnet152.*vit_dwt"):
        build_backbone("resnet200")


def test_register_backbone_extends_registry():
    register_backbone("_test_stub", lambda **kw: ResNetDWT(
        stage_sizes=(1, 1, 1, 1), **kw))
    try:
        m = build_backbone("_test_stub", num_classes=3)
        assert m.num_classes == 3
    finally:
        del BACKBONES["_test_stub"]


def test_resnet152_stage_sizes():
    assert ResNetDWT.resnet152().stage_sizes == (3, 8, 36, 3)


# ---------------------------------------------------------------- ViT-DWT


def test_vit_tiny_train_eval_forward_and_site_placement():
    m = build_backbone("vit_tiny", num_classes=65)
    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(3, 4, 16, 16, 3)), jnp.float32
    )
    variables = m.init(jax.random.key(0), x, True)
    out, mutated = m.apply(
        x=x, train=True, variables=variables, mutable=["batch_stats"]
    )
    assert out.shape == (3, 4, 65)                   # [D, N, classes]
    xe = x[0]
    oe = m.apply(variables, xe, False)
    assert oe.shape == (4, 65)
    # Site placement: patch embed + first quarter of blocks whiten
    # (depth 2 → blk0), deeper blocks batch-normalize.
    stats = variables["batch_stats"]
    assert "whitening" in stats["dn_patch"]
    assert "whitening" in stats["blk0"]["dn"]
    assert "whitening" not in stats["blk1"]["dn"]
    # The fsdp naming contract: a 4-D conv_patch kernel, 2-D attention/
    # MLP/head kernels (never DenseGeneral's 3-D form).
    params = variables["params"]
    assert params["conv_patch"]["kernel"].ndim == 4
    for layer in ("attn_q", "attn_k", "attn_v", "attn_out",
                  "mlp_fc1", "mlp_fc2"):
        assert params["blk0"][layer]["kernel"].ndim == 2
    assert params["fc_out"]["kernel"].ndim == 2


def test_vit_rejects_bad_shapes():
    m = ViTDWT.vit_tiny(num_classes=5)
    with pytest.raises(ValueError, match="train input"):
        m.init(jax.random.key(0), jnp.zeros((2, 4, 16, 16, 3)), True)
    with pytest.raises(ValueError, match="divisible"):
        m.init(jax.random.key(0), jnp.zeros((3, 4, 15, 15, 3)), True)


# ------------------------------------------------------------ padded head


def _graft_padded_head(variables, padded_variables, num_classes):
    """Copy every leaf from the unpadded init into the padded tree,
    zero-padding fc_out's kernel columns / bias entries — a Dense output
    column depends only on its own kernel column, so the real logit
    columns of the padded head are bitwise the unpadded head's."""
    def graft(dst, src):
        if dst.shape != src.shape:
            pad = [(0, d - s) for d, s in zip(dst.shape, src.shape)]
            return jnp.asarray(np.pad(np.asarray(src), pad))
        return src

    out = jax.tree.map(graft, padded_variables, variables)
    assert out["params"]["fc_out"]["kernel"].shape[-1] > num_classes
    return out


@pytest.mark.parametrize(
    "backbone",
    [
        # The resnet param pays two full tiny-resnet init traces + the
        # accum-eval compile (~12 s); the vit_tiny row keeps the
        # bitwise-parity contract tier-1.  (t1 budget)
        pytest.param("tiny", marks=pytest.mark.slow),
        "vit_tiny",
    ],
)
def test_padded_head_bitwise_logits_and_exact_counters(backbone):
    """pad_classes_to with the same (zero-padded) weights: bitwise
    logits, and EXACT eval counters on a ragged masked chunk — the
    padded columns are sliced off inside the forward, so loss/accuracy/
    serve never see them."""
    size = 16 if backbone == "vit_tiny" else 32
    kw = dict(num_classes=5, group_size=4)
    plain = build_backbone(backbone, **kw)
    padded = build_backbone(backbone, pad_classes_to=3, **kw)  # head: 6

    rng = np.random.default_rng(1)
    xt = jnp.asarray(
        rng.normal(size=(3, 4, size, size, 3)), jnp.float32
    )
    v_plain = plain.init(jax.random.key(7), xt, True)
    v_padded = _graft_padded_head(
        v_plain, padded.init(jax.random.key(7), xt, True), 5
    )

    xe = jnp.asarray(rng.normal(size=(4, size, size, 3)), jnp.float32)
    logits_plain = plain.apply(v_plain, xe, False)
    logits_padded = padded.apply(v_padded, xe, False)
    assert logits_padded.shape == logits_plain.shape == (4, 5)
    np.testing.assert_array_equal(
        np.asarray(logits_plain), np.asarray(logits_padded)
    )

    # Ragged dataset: k=2 chunk, final batch padded + masked out.
    chunk = {
        "x": jnp.stack([xe, xe]),
        "y": jnp.asarray(rng.integers(0, 5, size=(2, 4))),
        "mask": jnp.asarray([[True] * 4, [True, True, False, False]]),
    }
    results = []
    for model, variables in ((plain, v_plain), (padded, v_padded)):
        step = make_accum_eval_step(model)
        results.append(jax.device_get(step(
            eval_counters(), variables["params"],
            variables["batch_stats"], {}, chunk,
        )))
    assert results[0]["count"] == results[1]["count"] == 6
    assert results[0]["correct"] == results[1]["correct"]
    np.testing.assert_array_equal(
        results[0]["loss_sum"], results[1]["loss_sum"]
    )


def test_divisible_classes_pad_is_identity():
    """The divisible-classes control: padding to a divisor of
    num_classes changes NOTHING — same param shapes, same module, so
    counters trivially bitwise-match the unpadded path."""
    assert padded_num_classes(65, 0) == 65
    assert padded_num_classes(65, 1) == 65
    assert padded_num_classes(10, 5) == 10           # divisible: no-op
    assert padded_num_classes(65, 2) == 66
    a = build_backbone("tiny", num_classes=10, pad_classes_to=5)
    b = build_backbone("tiny", num_classes=10)
    x = jnp.zeros((3, 2, 32, 32, 3), jnp.float32)
    va = jax.eval_shape(lambda: a.init(jax.random.key(0), x, True))
    vb = jax.eval_shape(lambda: b.init(jax.random.key(0), x, True))
    assert jax.tree.map(lambda l: l.shape, va) == \
        jax.tree.map(lambda l: l.shape, vb)


# --------------------------------------------- through the subsystems


def test_vit_padded_head_serves_bitwise_through_engine():
    """ViT-DWT + padded head through the UNCHANGED ServeEngine: served
    logits are bitwise the eval-mode forward's (the padded columns are
    sliced inside the forward, so the serve path never sees them), with
    the engine's whiten-cache build driven purely off model attrs."""
    import optax

    from dwt_tpu.serve import ServeEngine
    from dwt_tpu.train.evalpipe import make_whiten_cache_fn
    from dwt_tpu.train.steps import eval_variables

    model = build_backbone(
        "vit_tiny", num_classes=5, group_size=4, pad_classes_to=3
    )
    rng = np.random.default_rng(0)
    sample = jnp.asarray(rng.normal(size=(3, 4, 16, 16, 3)), jnp.float32)
    state = create_train_state(
        model, jax.random.key(0), sample, optax.identity()
    )
    engine = ServeEngine(
        model, state.params, state.batch_stats, (16, 16, 3), buckets=(1, 4)
    )
    cache = make_whiten_cache_fn("cholesky")(state.batch_stats)
    oracle = jax.jit(
        lambda p, s, c, x: model.apply(
            eval_variables(p, s, c), x, train=False
        )
    )
    x = rng.normal(size=(3, 16, 16, 3)).astype(np.float32)
    served = engine.infer(x, bucket=4)
    assert served.shape == (3, 5)                    # num_classes, not 6
    padded = np.concatenate([x, x[-1:]])
    want = np.asarray(
        oracle(state.params, state.batch_stats, cache, padded)
    )[:3]
    np.testing.assert_array_equal(served, want)


@pytest.mark.slow
def test_vit_fsdp_cli_end_to_end_with_resume(tmp_path):
    """The acceptance path in one run: vit_tiny + the fsdp preset at a
    (1, 4, 2) mesh trains, evals, checkpoints, and RESUMES through the
    stock OfficeHome CLI — no special-casing outside registry + rules."""
    from dwt_tpu.cli.officehome import main

    args = [
        "--synthetic",
        "--synthetic_size", "12",
        "--backbone", "vit_tiny",
        "--pad_classes_to", "2",
        "--mesh_shape", "1,4,2",
        "--sharding_rules", "fsdp",
        "--img_resize", "16",
        "--img_crop_size", "16",
        "--num_classes", "5",
        "--source_batch_size", "4",
        "--target_batch_size", "4",
        "--test_batch_size", "4",
        "--check_acc_step", "2",
        "--stat_collection_passes", "1",
        "--log_interval", "1",
        "--group_size", "4",
        "--ckpt_dir", str(tmp_path / "ckpt"),
        "--ckpt_every_iters", "2",
        "--no-async_ckpt",
    ]
    acc = main(args + ["--num_iters", "2"])
    assert 0.0 <= acc <= 100.0
    # Resume from the step-2 checkpoint and run to 4.
    acc = main(args + ["--num_iters", "4"])
    assert 0.0 <= acc <= 100.0


# --------------------------------------------------- worked rules file


def test_resnet152_worked_rules_file_validates_against_real_tree():
    """The README's worked ResNet-152 rules JSON must validate against
    the REAL resnet152 param+opt tree (via eval_shape — materializing
    it replicated is exactly what fsdp exists to avoid; the abstract
    trace keeps this tier-1): every leaf claimed, head + moments on the
    model axis, stats replicated."""
    from dwt_tpu.parallel import MODEL_AXIS, load_rules_file, make_plan_mesh
    from dwt_tpu.parallel.plan import match_partition_rules

    rules = load_rules_file("configs/resnet152_fsdp_rules.json")
    model = build_backbone(
        "resnet152", num_classes=65, group_size=4, pad_classes_to=2
    )
    tx = adam_l2(1e-3)
    sample = jax.ShapeDtypeStruct((3, 2, 64, 64, 3), jnp.float32)
    state = jax.eval_shape(
        lambda s: create_train_state(model, jax.random.key(0), s, tx),
        sample,
    )
    mesh = make_plan_mesh((1, 4, 2))
    specs = match_partition_rules(rules, state, mesh=mesh, what="resnet152")
    from jax.sharding import PartitionSpec as P
    assert specs.params["conv1"]["kernel"] == P(None, None, None, MODEL_AXIS)
    assert specs.params["layer3_35"]["conv3"]["kernel"] == \
        P(None, None, None, MODEL_AXIS)
    assert specs.params["fc_out"]["kernel"] == P(None, MODEL_AXIS)
    assert specs.opt_state[1].mu["fc_out"]["kernel"] == P(None, MODEL_AXIS)
    assert all(
        s == P() for s in jax.tree.leaves(
            match_partition_rules(rules, state.batch_stats)
        )
    )
