"""Async metric harvesting: the last per-step host sync off the hot path.

PR 8's attribution (``tools/obs_report.py`` over a traced digits run)
measured ``metric_host_fetch`` — the ``float()`` materialization in the
train-record path — at 79.6% of per-step loop wall: it is exactly where
async-dispatched step work gets waited on, because a blocking device→host
read of step *s*'s metrics cannot complete before step *s* itself does.
The training algorithm only *consumes* these scalars at logging/guard
cadence, so nothing requires the read to be synchronous.

:class:`AsyncMetricHarvester` is the deferred pipeline: each dispatch
enqueues its (step-stamped) metrics into a bounded ring after starting a
non-blocking device→host copy (``copy_to_host_async``), and entries are
drained — materialized and emitted as byte-identical ``MetricLogger``
records carrying their *original* step stamps — only once the ring fills
(or at eval/checkpoint/preempt/final/rollback boundaries, which drain
fully).  A full ring is drained with ONE blocking rendezvous for all
``depth`` entries, so the amortized per-step host-sync count drops from
1 to 1/depth — and by the time the ring has refilled, the oldest copies
completed long ago, so the rendezvous waits essentially on the newest
entry alone.

Contracts, load-bearing for the loops:

* **exact records, nothing lost or reordered** — the ring is FIFO and
  every boundary drain flushes it completely; the emitted JSONL records
  are byte-identical (modulo wall-clock fields) to the synchronous
  path's, with their original step stamps.
* **depth 0 = legacy synchronous fetch** — ``put`` materializes and
  emits immediately (one sync per record-bearing step), bitwise record
  parity with the async path by construction (same emit closure).
* **bounded guard staleness** — the train step computes a device-side
  ``finite`` flag (one bool scalar; the guard inspects it instead of
  forcing the whole metrics tree), harvested through the same ring: a
  NaN at step *s* reaches :meth:`DivergenceGuard.observe_flags` by the
  drain at *s + depth* entries, so detection lags at most ``depth``
  dispatches on top of the existing ``--guard_interval`` amortization.
* **generation fencing** — after a guard recovery the ring may still
  hold entries from the poisoned trajectory; :meth:`bump_generation`
  makes their flags inert (the records still emit — they narrate steps
  that really ran) so a replayed segment is never re-tripped by stale
  verdicts.

Spans (``dwt_tpu.obs``): ``metric_copy_start`` books the enqueue +
async-copy dispatch, ``harvest_drain`` the drain site, and the nested
``metric_host_fetch`` keeps its name for the one genuinely blocking
materialization — so the attribution table shows the fetch share
collapse rather than hiding it under a new label.
"""

from __future__ import annotations

import collections
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from dwt_tpu import obs


class _Entry:
    """One dispatch's booked metrics: step range, the device scalars the
    record needs, the optional finite flag, and the emit closure."""

    __slots__ = ("lo", "hi", "values", "flag", "emit", "gen")

    def __init__(self, lo: int, hi: int, values: Dict[str, Any],
                 flag: Any, emit: Optional[Callable], gen: int):
        self.lo = lo
        self.hi = hi
        self.values = values
        self.flag = flag
        self.emit = emit
        self.gen = gen

    def arrays(self):
        for v in self.values.values():
            yield v
        if self.flag is not None:
            yield self.flag

    def ready(self) -> bool:
        """All leaves computed (``jax.Array.is_ready`` — a host-side
        queue poll, NOT a sync); host-resident leaves are trivially
        ready."""
        for a in self.arrays():
            probe = getattr(a, "is_ready", None)
            if probe is not None and not probe():
                return False
        return True


def _start_copy(arr: Any) -> None:
    # jax.Array exposes copy_to_host_async; plain numpy (tests, depth-0
    # shortcuts) has nothing to start.
    start = getattr(arr, "copy_to_host_async", None)
    if start is not None:
        start()


class AsyncMetricHarvester:
    """Bounded-ring deferred metric pipeline (see module docstring).

    ``flag_observer(lo, hi, host_flags)`` — typically
    ``DivergenceGuard.observe_flags`` — receives each drained entry's
    materialized finite flag(s) *before* the entry's records emit, and
    only for entries of the current generation.

    Main-thread only, like the loops that drive it: no locking.
    """

    def __init__(self, depth: int,
                 flag_observer: Optional[Callable] = None):
        self.depth = max(0, int(depth))
        self._ring: "collections.deque[_Entry]" = collections.deque()
        self._observer = flag_observer
        self.generation = 0
        self.puts = 0
        self.emitted = 0
        self.lag_steps = 0
        self._last_put_hi: Optional[int] = None
        # Lo-stamps of the last `depth` puts: the ring never holds more
        # than `depth` entries after a put returns (overflow drains), so
        # any still-pending flag covers at earliest _lo_history[0] —
        # a bound derived from put CONTROL FLOW, not local drain timing,
        # hence identical on every host (the guard's lockstep
        # history-prune floor, pending_floor()).
        self._lo_history: "collections.deque[int]" = collections.deque(
            maxlen=max(self.depth, 1)
        )
        # Live metrics plane: both gauges are host-side integers the
        # drain site already holds — zero new device syncs (spans.py /
        # registry.py discipline).  Surfaced in /metrics on both
        # training CLIs and mirrored into heartbeat records.
        from dwt_tpu.obs.registry import get_registry

        reg = get_registry()
        self._g_ring = reg.gauge(
            "dwt_harvest_ring_depth",
            "metric-harvest entries in flight (ring occupancy)",
        )
        self._g_lag = reg.gauge(
            "dwt_harvest_lag_steps",
            "staleness of the oldest harvested metrics at the last "
            "drain, in steps",
        )
        self._g_ring.set(0)
        self._g_lag.set(0)

    # ----------------------------------------------------------- recording

    @property
    def async_mode(self) -> bool:
        return self.depth > 0

    @property
    def pending(self) -> int:
        return len(self._ring)

    def put(self, lo: int, hi: int, values: Optional[Dict[str, Any]] = None,
            flag: Any = None, emit: Optional[Callable] = None) -> None:
        """Book the metrics of steps ``[lo, hi]`` (one step per dispatch
        on the per-step paths; a chunk's range on the scanned path, with
        ``[n]``-stacked leaves).

        ``values`` holds exactly the device scalars ``emit`` will need
        (None when this step logs nothing), ``flag`` the device-side
        finite verdict (None when no guard consumes it) — so a
        non-logging step with an active guard copies ONE bool, not the
        whole metrics tree.  Nothing to book at all → no ring entry.
        """
        if values is None and flag is None:
            return
        self.puts += 1
        self._last_put_hi = int(hi)
        e = _Entry(int(lo), int(hi), values or {}, flag, emit,
                   self.generation)
        if self.depth == 0:
            # Legacy synchronous fetch: materialize + emit in place.
            with obs.span("metric_host_fetch"):
                host = self._wait([e])
            self._emit(e, host[0])
            return
        with obs.span("metric_copy_start"):
            for arr in e.arrays():
                _start_copy(arr)
            self._ring.append(e)
            self._lo_history.append(e.lo)
        # Opportunistic drain: entries whose copies already landed emit
        # now with NO blocking rendezvous (is_ready is a queue poll).
        # FIFO discipline — only the ready PREFIX drains, so records
        # never reorder around a still-in-flight older entry.
        while self._ring and self._ring[0].ready():
            entry = self._ring.popleft()
            with obs.span("harvest_drain", n=1):
                self._emit(entry, self._materialize(entry))
        if len(self._ring) > self.depth:
            # Ring overflow (device more than `depth` record-bearing
            # dispatches behind): force a full drain — ONE blocking
            # rendezvous for every pending entry, so even with nothing
            # ever ready the amortized sync count is 1/depth per entry,
            # not 1.
            self.drain()
        self._note_gauges()

    def drain(self) -> None:
        """Flush the whole ring: ONE blocking rendezvous materializes
        every pending entry (the oldest copies completed long ago — the
        wait is effectively on the newest), then the entries emit in
        FIFO order.  Called by ``put`` on ring overflow and by the loops
        at every eval/checkpoint/preempt/final/rollback boundary, so no
        record is ever lost or reordered."""
        if not self._ring:
            return
        entries = list(self._ring)
        self._ring.clear()
        if self._last_put_hi is not None:
            self.lag_steps = self._last_put_hi - entries[0].lo
            self._g_lag.set(self.lag_steps)
        with obs.span("harvest_drain", n=len(entries)):
            with obs.span("metric_host_fetch"):
                hosts = self._wait(entries)
            for e, host in zip(entries, hosts):
                self._emit(e, host)
        self._note_gauges()

    def _note_gauges(self) -> None:
        self._g_ring.set(len(self._ring))
        if self._ring and self._last_put_hi is not None:
            self.lag_steps = self._last_put_hi - self._ring[0].lo
            self._g_lag.set(self.lag_steps)

    def pending_floor(self) -> Optional[int]:
        """Oldest step any still-pending flag could cover (None until
        `depth` puts happened): the guard prunes snapshots strictly
        below the newest one under this floor.  Deterministic across
        hosts — see _lo_history."""
        if len(self._lo_history) < max(self.depth, 1):
            return None
        return self._lo_history[0]

    def bump_generation(self) -> None:
        """Fence pending entries' flags: after a guard recovery the ring
        still holds pre-recovery verdicts that must not re-trip the
        guard on the replayed segment.  Their records still emit."""
        self.generation += 1

    def reset_stamps(self) -> None:
        """Forget the put-stamp bookkeeping.  The rollback handlers call
        this (right after their full drain) because the restore REWINDS
        step numbering: a floor still derived from pre-rollback stamps
        would make the guard prune the restore-point snapshot the replay
        may yet need, and the lag gauge would report pre-rollback
        deltas.  In-memory recoveries (lr_backoff/skip_step) keep
        monotonic host numbering and must NOT reset."""
        self._lo_history.clear()
        self._last_put_hi = None

    # ----------------------------------------------------------- internals

    def _wait(self, entries: List[_Entry]) -> List[Tuple[dict, Any]]:
        """THE blocking device→host rendezvous — the one countable host
        sync on the record path (tests shim this to prove the 1 →
        amortized <= 1/depth drop; opportunistic ready-drains never come
        through here)."""
        return [self._materialize(e) for e in entries]

    @staticmethod
    def _materialize(e: _Entry) -> Tuple[dict, Any]:
        """``np.asarray`` on each leaf completes the async copy started
        at ``put`` time; values come back as numpy scalars/arrays whose
        ``float()`` is bitwise the device scalar's.  Non-blocking when
        the entry is ready()."""
        host_values = {k: np.asarray(v) for k, v in e.values.items()}
        host_flag = None if e.flag is None else np.asarray(e.flag)
        return host_values, host_flag

    def _emit(self, e: _Entry, host: Tuple[dict, Any]) -> None:
        host_values, host_flag = host
        if (
            host_flag is not None
            and self._observer is not None
            and e.gen == self.generation
        ):
            self._observer(e.lo, e.hi, host_flag)
        if e.emit is not None:
            e.emit(host_values)
        self.emitted += 1


def make_harvester(cfg, guard=None) -> AsyncMetricHarvester:
    """The loops' one constructor: ``--harvest_depth`` (default 2; 0 =
    legacy synchronous fetch) wired to the run's guard.  With an active
    guard and depth > 0 the guard switches to harvested-flag verdicts
    (:meth:`DivergenceGuard.enable_harvest`); at depth 0 the guard keeps
    its PR-1 synchronous metrics check, so depth 0 is bitwise the
    pre-harvest loop."""
    depth = max(0, int(getattr(cfg, "harvest_depth", 2)))
    observer = None
    if guard is not None and depth > 0:
        observer = guard.observe_flags
    return AsyncMetricHarvester(depth, flag_observer=observer)
