"""dwt_tpu.train — jitted train/eval steps, optimizers, schedules.

TPU-first re-design of the reference's L4 training loops
(``usps_mnist.py:281-327``, ``resnet50_dwt_mec_officehome.py:380-464``):
the per-batch body collapses into one jitted, functionally-pure
``train_step(state, batch) -> (state, metrics)`` (SURVEY §3.4), with running
norm statistics carried in the train state rather than mutated module
buffers.  Host code only feeds batches and logs metrics.
"""

from dwt_tpu.train.state import TrainState, create_train_state
from dwt_tpu.train.optim import adam_l2, multistep_schedule, sgd_two_group
from dwt_tpu.train.steps import (
    eval_counters,
    eval_variables,
    make_accum_eval_step,
    make_digits_train_step,
    make_eval_step,
    make_officehome_train_step,
    make_scanned_collect,
    make_scanned_step,
    make_serve_forward,
    make_stat_collection_step,
    stack_batches,
)
from dwt_tpu.train.evalpipe import EvalPipeline

__all__ = [
    "TrainState",
    "create_train_state",
    "adam_l2",
    "multistep_schedule",
    "sgd_two_group",
    "EvalPipeline",
    "eval_counters",
    "eval_variables",
    "make_accum_eval_step",
    "make_digits_train_step",
    "make_eval_step",
    "make_officehome_train_step",
    "make_scanned_collect",
    "make_scanned_step",
    "make_serve_forward",
    "make_stat_collection_step",
    "stack_batches",
]
