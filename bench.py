"""Canonical perf driver: jitted DWT train-step throughput on one chip.

Prints ONE JSON line:
``{"metric": ..., "value": N, "unit": "imgs/sec", "vs_baseline": N}``.

The reference publishes no throughput numbers (BASELINE.md) — the baseline
is established de novo, so ``vs_baseline`` is this run's value normalized by
``BASELINE_IMGS_PER_SEC`` below (the first recorded TPU number; ratio > 1.0
means faster than that round's result).

Flagship benchmark: LeNet-DWT digits train step at the reference's batch
size (32 source + 32 target, ``usps_mnist.py:333-336``), group_size=4.
Selectable with ``--model resnet50`` once the ResNet path lands to measure
the OfficeHome configuration (18/18/18 thirds, ``resnet50…py:500-502``).
"""

import argparse
import json
import time

import jax
import jax.numpy as jnp
import numpy as np

# First real-TPU measurement (round 2, LeNet-DWT bs32, TPU v5e via axon).
# Update only to re-anchor; vs_baseline compares against this.
BASELINE_IMGS_PER_SEC = None  # set after first TPU run; None -> vs_baseline=1.0


def _bench_lenet(steps: int, batch: int):
    from dwt_tpu.nn import LeNetDWT
    from dwt_tpu.train import adam_l2, create_train_state, make_digits_train_step

    rng = np.random.default_rng(0)
    b = {
        "source_x": jnp.asarray(
            rng.normal(size=(batch, 28, 28, 1)), jnp.float32
        ),
        "source_y": jnp.asarray(rng.integers(0, 10, size=(batch,))),
        "target_x": jnp.asarray(
            rng.normal(size=(batch, 28, 28, 1)), jnp.float32
        ),
    }
    model = LeNetDWT(group_size=4)
    tx = adam_l2(1e-3, 5e-4)
    state = create_train_state(
        model, jax.random.key(0), jnp.stack([b["source_x"], b["target_x"]]), tx
    )
    step = jax.jit(make_digits_train_step(model, tx, 0.1), donate_argnums=0)
    return _time_steps(step, state, b, steps, imgs_per_step=2 * batch)


def _bench_resnet50(steps: int, batch: int):
    from dwt_tpu.nn import ResNetDWT
    from dwt_tpu.train import (
        create_train_state,
        make_officehome_train_step,
        sgd_two_group,
    )

    rng = np.random.default_rng(0)
    b = {
        "source_x": jnp.asarray(
            rng.normal(size=(batch, 224, 224, 3)), jnp.bfloat16
        ),
        "source_y": jnp.asarray(rng.integers(0, 65, size=(batch,))),
        "target_x": jnp.asarray(
            rng.normal(size=(batch, 224, 224, 3)), jnp.bfloat16
        ),
        "target_aug_x": jnp.asarray(
            rng.normal(size=(batch, 224, 224, 3)), jnp.bfloat16
        ),
    }
    model = ResNetDWT.resnet50(num_classes=65, dtype=jnp.bfloat16)
    tx = sgd_two_group(1e-2, 1e-3)
    sample = jnp.stack([b["source_x"], b["target_x"], b["target_aug_x"]])
    state = create_train_state(model, jax.random.key(0), sample, tx)
    step = jax.jit(
        make_officehome_train_step(model, tx, 0.1), donate_argnums=0
    )
    return _time_steps(step, state, b, steps, imgs_per_step=3 * batch)


def _time_steps(step, state, batch, steps, imgs_per_step):
    # Warmup: compile + 2 steady-state steps.
    state, m = step(state, batch)
    jax.block_until_ready(m)
    for _ in range(2):
        state, m = step(state, batch)
    jax.block_until_ready(m)
    t0 = time.perf_counter()
    for _ in range(steps):
        state, m = step(state, batch)
    jax.block_until_ready(m)
    dt = time.perf_counter() - t0
    assert np.isfinite(float(m["loss"])), "non-finite loss in bench"
    return imgs_per_step * steps / dt, dt / steps


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", choices=["lenet", "resnet50"], default="lenet")
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--batch", type=int, default=32)
    args = ap.parse_args()

    if args.model == "lenet":
        imgs_per_sec, step_time = _bench_lenet(args.steps, args.batch)
        metric = "lenet_dwt_train_imgs_per_sec"
    else:
        imgs_per_sec, step_time = _bench_resnet50(args.steps, max(args.batch, 18))
        metric = "resnet50_dwt_train_imgs_per_sec"

    vs = 1.0 if BASELINE_IMGS_PER_SEC is None else imgs_per_sec / BASELINE_IMGS_PER_SEC
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(imgs_per_sec, 2),
                "unit": "imgs/sec",
                "vs_baseline": round(vs, 4),
                "step_time_ms": round(step_time * 1e3, 3),
                "backend": jax.default_backend(),
            }
        )
    )


if __name__ == "__main__":
    main()
