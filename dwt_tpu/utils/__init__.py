"""dwt_tpu.utils — metrics logging and checkpoint helpers."""

from dwt_tpu.utils.metrics import MetricLogger
from dwt_tpu.utils.checkpoint import (
    latest_step,
    restore_state,
    save_state,
)

__all__ = ["MetricLogger", "latest_step", "restore_state", "save_state"]
