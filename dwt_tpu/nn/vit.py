"""ViT-DWT — the paper's whitening op at transformer module boundaries.

A genuinely new placement for Domain Whitening (the paper only studies
conv nets): per-domain grouped whitening applied to the token stream at
the **patch-embed boundary** and at **transformer-block boundaries**, in
the spirit of Decorrelated Batch Normalization's whiten-at-module-
boundary design (arXiv:1804.08450).  The whitening op itself is reused
unchanged — ``group_whiten`` reduces moments over ALL leading axes, so
``[B, L, C]`` token batches feed the same ``[.., C]`` sites the conv
nets use, and the triple stat-branch / shared-affine contract (source /
target / augmented-target sharing one ``gamma``/``beta``) carries over
verbatim.

Depth placement mirrors the ResNet recipe (stem + stage 1 whiten, deeper
stages batch-normalize): the patch embed and the first quarter of blocks
carry ``DomainWhiten`` sites, the rest ``DomainBatchNorm`` — whitening
where domain covariance structure is strongest (low-level statistics),
cheap BN where features are already task-aligned.

Sharding-first construction: every weight matrix — attention q/k/v/out,
MLP fc1/fc2, the head — is a plain 2-D ``fnn.Dense`` kernel (never
DenseGeneral's 3-D form), and the patch embed is named ``conv_patch`` so
the fsdp preset's 4-D conv rule claims its kernel.  Under
``--sharding_rules fsdp`` the whole backbone model-shards out of the box
(stats/whiten_cache pinned replicated), and ``pad_classes_to`` makes the
head divisible — see ``parallel/plan.py``.

Train input ``[D, N, H, W, C]`` / eval ``[N, H, W, C]``, the same
contract as :class:`~dwt_tpu.nn.resnet.ResNetDWT`, so the train loop,
EvalPipeline, ServeEngine, and checkpoints flow unchanged.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as fnn

from dwt_tpu.nn.norms import (
    AxisName,
    DomainBatchNorm,
    DomainWhiten,
    apply_domain_norm,
    merge_domains,
    split_domains,
)
from dwt_tpu.nn.resnet import _conv_init, padded_num_classes


class TransformerBlockDWT(fnn.Module):
    """Pre-LN transformer block with a domain-norm site at its boundary.

    LayerNorm inside the residual branches is per-token (domain-blind,
    like the convs); the DWT structure lives in the boundary site, where
    the block's output tokens are whitened/normalized per domain branch.
    """

    width: int
    num_heads: int
    mlp_ratio: int = 4
    use_whitening: bool = False
    group_size: int = 4
    num_domains: int = 3
    eval_domain: int = 1
    momentum: float = 0.1
    axis_name: Optional[AxisName] = None
    dtype: jnp.dtype = jnp.float32
    use_pallas: bool = False
    whitener: str = "cholesky"

    def _make_norm(self, features: int, name: str):
        kw = dict(
            num_domains=self.num_domains,
            eval_domain=self.eval_domain,
            momentum=self.momentum,
            axis_name=self.axis_name,
            name=name,
        )
        if self.use_whitening:
            return DomainWhiten(
                features, self.group_size, use_pallas=self.use_pallas,
                whitener=self.whitener, **kw
            )
        return DomainBatchNorm(features, **kw)

    @fnn.compact
    def __call__(self, x: jax.Array, train: bool) -> jax.Array:
        dense = partial(fnn.Dense, dtype=self.dtype)
        ch = self.width
        head_dim = ch // self.num_heads

        h = fnn.LayerNorm(dtype=self.dtype, name="ln1")(x)
        # Plain 2-D Dense kernels (NOT DenseGeneral's [C, heads, hd]):
        # the fsdp preset's dense rule shards out-features over the model
        # axis, which is only correct on 2-D kernels.
        q = dense(ch, name="attn_q")(h)
        k = dense(ch, name="attn_k")(h)
        v = dense(ch, name="attn_v")(h)

        def heads(t: jax.Array) -> jax.Array:
            t = t.reshape(t.shape[:-1] + (self.num_heads, head_dim))
            return t.transpose(0, 2, 1, 3)  # [B, H, L, hd]

        q, k, v = heads(q), heads(k), heads(v)
        attn = jax.nn.softmax(
            (q @ k.transpose(0, 1, 3, 2)) * (head_dim ** -0.5), axis=-1
        )
        o = (attn @ v).transpose(0, 2, 1, 3).reshape(x.shape[:-1] + (ch,))
        x = x + dense(ch, name="attn_out")(o)

        h = fnn.LayerNorm(dtype=self.dtype, name="ln2")(x)
        h = fnn.gelu(dense(ch * self.mlp_ratio, name="mlp_fc1")(h))
        x = x + dense(ch, name="mlp_fc2")(h)

        # Block-boundary domain site: [D*N, L, C] splits to [D, N, L, C],
        # group_whiten/batch_norm reduce over (N, L) per branch.
        return apply_domain_norm(
            x, self._make_norm(ch, "dn"), train, self.num_domains
        )


class ViTDWT(fnn.Module):
    """ViT backbone with domain whitening at module boundaries.

    Same attribute surface and input contract as ``ResNetDWT`` so every
    subsystem (train loop, EvalPipeline, ServeEngine, checkpoints,
    sharding plans) consumes it with no special-casing.
    """

    patch_size: int = 16
    depth: int = 12
    width: int = 384
    num_heads: int = 6
    mlp_ratio: int = 4
    num_classes: int = 65
    group_size: int = 4
    num_domains: int = 3
    eval_domain: int = 1
    momentum: float = 0.1
    axis_name: Optional[AxisName] = None
    dtype: jnp.dtype = jnp.float32
    whiten: bool = True  # False: every site is DomainBatchNorm (ablation)
    remat: bool = False  # jax.checkpoint per block (HBM for FLOPs)
    use_pallas: bool = False
    whitener: str = "cholesky"
    pad_classes_to: int = 0  # see ResNetDWT.pad_classes_to

    @classmethod
    def vit_dwt(cls, **kw) -> "ViTDWT":
        """ViT-S/16-shaped flagship (384 wide, 12 deep, 6 heads)."""
        return cls(**kw)

    @classmethod
    def vit_tiny(cls, **kw) -> "ViTDWT":
        """Small-config twin for tests/CI dryruns (32 wide, 2 deep)."""
        return cls(patch_size=4, depth=2, width=32, num_heads=4, **kw)

    @fnn.compact
    def __call__(self, x: jax.Array, train: bool) -> jax.Array:
        if train:
            if x.shape[0] != self.num_domains:
                raise ValueError(
                    f"train input must be [domains={self.num_domains}, "
                    f"N, H, W, C]; got {x.shape}"
                )
            x = merge_domains(x)
        if x.shape[-3] % self.patch_size or x.shape[-2] % self.patch_size:
            raise ValueError(
                f"input spatial dims {x.shape[-3:-1]} must be divisible "
                f"by patch_size={self.patch_size}"
            )
        x = x.astype(self.dtype)

        # Patch embed: named conv_patch so the fsdp preset's 4-D conv
        # rule claims its [p, p, 3, width] kernel (out-channel sharding).
        p = self.patch_size
        x = fnn.Conv(
            self.width, (p, p), strides=(p, p), use_bias=False,
            dtype=self.dtype, kernel_init=_conv_init, name="conv_patch",
        )(x)
        x = x.reshape(x.shape[0], -1, self.width)  # [B, L, C]
        pos = self.param(
            "pos_embed", fnn.initializers.normal(0.02),
            (1, x.shape[1], self.width), jnp.float32,
        )
        x = x + pos.astype(x.dtype)

        # Patch-embed boundary whitening site (the "stem" site).
        stem_kw = dict(
            num_domains=self.num_domains,
            eval_domain=self.eval_domain,
            momentum=self.momentum,
            axis_name=self.axis_name,
            name="dn_patch",
        )
        x = apply_domain_norm(
            x,
            DomainWhiten(
                self.width, self.group_size, use_pallas=self.use_pallas,
                whitener=self.whitener, **stem_kw
            )
            if self.whiten
            else DomainBatchNorm(self.width, **stem_kw),
            train,
            self.num_domains,
        )

        block_cls = (
            fnn.remat(TransformerBlockDWT, static_argnums=(2,))
            if self.remat
            else TransformerBlockDWT
        )
        # First quarter of blocks whiten (at least one), the rest BN —
        # the ResNet stem+stage-1 recipe transplanted to depth.
        whiten_depth = max(1, self.depth // 4)
        for i in range(self.depth):
            x = block_cls(
                width=self.width,
                num_heads=self.num_heads,
                mlp_ratio=self.mlp_ratio,
                use_whitening=(i < whiten_depth and self.whiten),
                group_size=self.group_size,
                num_domains=self.num_domains,
                eval_domain=self.eval_domain,
                momentum=self.momentum,
                axis_name=self.axis_name,
                dtype=self.dtype,
                use_pallas=self.use_pallas,
                whitener=self.whitener,
                name=f"blk{i}",
            )(x, train)

        x = fnn.LayerNorm(dtype=self.dtype, name="ln_out")(x)
        x = jnp.mean(x, axis=-2)  # mean pool over tokens → [B, C]
        x = fnn.Dense(
            padded_num_classes(self.num_classes, self.pad_classes_to),
            dtype=self.dtype,
            name="fc_out",
        )(x)
        x = x[..., : self.num_classes]  # no-op unless the head is padded

        if train:
            x = split_domains(x, self.num_domains)
        return x
