"""SURVEY §4.4 distributed tests: sharded-vs-global parity on the fake mesh.

The invariant: a shard_map'd train step over 8 devices, with batch moments
and gradients pmean'd, must reproduce the single-device global-batch step
bit-for-bit (up to summation-order float noise) — exactly the semantics of
the reference's one-GPU global-batch moments (``whitening.py:41,47``).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dwt_tpu.nn import LeNetDWT
from dwt_tpu.parallel import (
    DATA_AXIS,
    make_mesh,
    make_sharded_train_step,
    replicate_state,
    shard_batch,
)
from dwt_tpu.train import adam_l2, create_train_state, make_digits_train_step


def _batch(n=8, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "source_x": jnp.asarray(
            rng.normal(size=(n, 28, 28, 1)), jnp.float32
        ),
        "source_y": jnp.asarray(rng.integers(0, 10, size=(n,))),
        "target_x": jnp.asarray(
            rng.normal(loc=0.5, size=(n, 28, 28, 1)), jnp.float32
        ),
    }


def _run_parity(tx, steps=2, dcn_slices=None):
    """Run the same batch through the global step and the 8-way DP step.

    ``dcn_slices=S`` uses the 2-D ``(dcn, data)`` mesh with two-axis
    collectives instead of the 1-D mesh.  Returns ``(state_g, metrics_g,
    state_s, metrics_s)``.  Init is axis-free (init must not trace
    collectives outside the mesh context); both steps start from identical
    state.
    """
    assert jax.device_count() >= 8, "conftest must force 8 CPU devices"
    mesh = make_mesh(jax.devices()[:8], dcn_slices=dcn_slices)
    axis_name = tuple(mesh.axis_names) if dcn_slices else DATA_AXIS
    batch = _batch(8)

    model_global = LeNetDWT(group_size=4)
    model_dp = LeNetDWT(group_size=4, axis_name=axis_name)
    sample = jnp.stack([batch["source_x"], batch["target_x"]])
    state = create_train_state(model_global, jax.random.key(0), sample, tx)

    global_step = jax.jit(make_digits_train_step(model_global, tx, 0.1))
    dp_step = make_sharded_train_step(
        make_digits_train_step(model_dp, tx, 0.1, axis_name=axis_name), mesh
    )

    state_g, metrics_g = state, None
    state_s, metrics_s = replicate_state(state, mesh), None
    sharded = shard_batch(batch, mesh)
    # Multiple steps so EMA'd stats feed back into the forward.
    for _ in range(steps):
        state_g, metrics_g = global_step(state_g, batch)
        state_s, metrics_s = dp_step(state_s, sharded)
    return state_g, metrics_g, state_s, metrics_s


def _assert_tree_close(a_tree, b_tree, rtol, atol):
    for a, b in zip(jax.tree.leaves(a_tree), jax.tree.leaves(b_tree)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=rtol, atol=atol
        )


@pytest.mark.slow
def test_sharded_train_step_matches_global_batch():
    """SURVEY §4.4 parity, SGD: the per-replica step with pmean'd moments,
    gradients, and metrics reproduces single-device global-batch numerics.

    SGD's update is linear in the gradient, so float summation-order noise
    in a pmean (~1e-7) stays ~lr·1e-7 in the params and tight tolerances
    hold.  (Adam would normalize near-zero gradients to full ±lr, amplifying
    reassociation noise into sign flips — covered by the looser Adam test
    below.)
    """
    state_g, metrics_g, state_s, metrics_s = _run_parity(
        optax.sgd(1e-2, momentum=0.9)
    )
    for k in metrics_g:
        np.testing.assert_allclose(
            float(metrics_s[k]), float(metrics_g[k]), rtol=1e-5, atol=1e-6
        )
    # atol 2e-5 on a handful of elements: sharded pmean and the
    # single-device global reduction sum in different f32 orders; that
    # ~1e-7 moment wobble is amplified through the whitening
    # factorization's sqrt/div chain and its VJP (see whitening_matrix).
    # Observed: <=7e-6 abs on 4 of 38400 params after a step — reduction-
    # order noise, not drift.
    _assert_tree_close(state_s.params, state_g.params, rtol=1e-5, atol=2e-5)
    _assert_tree_close(
        state_s.batch_stats, state_g.batch_stats, rtol=1e-5, atol=2e-5
    )


@pytest.mark.slow
def test_sharded_adam_step_matches_global_batch_semantics():
    """Adam (the digits recipe): metrics and batch stats must match tightly;
    params only loosely — Adam's ``m/(sqrt(v)+eps)`` maps a near-zero
    gradient to a full ±lr step, so float reassociation noise across the 8
    pmean'd replicas can flip a whole update's sign.  The loose bound is
    2·steps·lr.
    """
    lr = 1e-3
    steps = 2
    state_g, metrics_g, state_s, metrics_s = _run_parity(
        adam_l2(lr, 5e-4), steps=steps
    )
    # Step-2 metrics/stats pass through step-1 params, which can carry a few
    # sign-flipped ±lr updates — tolerances are an order looser than SGD's.
    for k in metrics_g:
        np.testing.assert_allclose(
            float(metrics_s[k]), float(metrics_g[k]), rtol=1e-3, atol=1e-5
        )
    # Absolute-only for stats: near-zero covariance entries make relative
    # error meaningless, and step-1 param flips perturb activations at ~lr.
    _assert_tree_close(
        state_s.batch_stats, state_g.batch_stats, rtol=0.0, atol=1e-3
    )
    _assert_tree_close(
        state_s.params, state_g.params, rtol=0.0, atol=2 * steps * lr
    )


@pytest.mark.slow
def test_2d_dcn_mesh_matches_global_batch():
    """Multi-slice DP (BASELINE configs[4]): the 2-D ``(dcn, data)`` mesh
    with two-axis moment/gradient/metric collectives reproduces the
    single-device global-batch numerics, same bars as the 1-D SGD test."""
    state_g, metrics_g, state_s, metrics_s = _run_parity(
        optax.sgd(1e-2, momentum=0.9), dcn_slices=2
    )
    for k in metrics_g:
        np.testing.assert_allclose(
            float(metrics_s[k]), float(metrics_g[k]), rtol=1e-5, atol=1e-6
        )
    # atol 2e-5 on a handful of elements: sharded pmean and the
    # single-device global reduction sum in different f32 orders; that
    # ~1e-7 moment wobble is amplified through the whitening
    # factorization's sqrt/div chain and its VJP (see whitening_matrix).
    # Observed: <=7e-6 abs on 4 of 38400 params after a step — reduction-
    # order noise, not drift.
    _assert_tree_close(state_s.params, state_g.params, rtol=1e-5, atol=2e-5)
    _assert_tree_close(
        state_s.batch_stats, state_g.batch_stats, rtol=1e-5, atol=2e-5
    )


def test_make_mesh_dcn_shapes_and_errors():
    from dwt_tpu.parallel import DCN_AXIS

    mesh = make_mesh(jax.devices()[:8], dcn_slices=2)
    assert mesh.axis_names == (DCN_AXIS, DATA_AXIS)
    assert mesh.devices.shape == (2, 4)
    # 1-D when dcn_slices is absent/1.
    assert make_mesh(jax.devices()[:8]).axis_names == (DATA_AXIS,)
    assert make_mesh(jax.devices()[:8], dcn_slices=1).axis_names == (DATA_AXIS,)
    with pytest.raises(ValueError, match="equal slices"):
        make_mesh(jax.devices()[:8], dcn_slices=3)


def test_shard_batch_places_leading_axis_across_mesh():
    mesh = make_mesh(jax.devices()[:8])
    batch = _batch(8)
    sharded = shard_batch(batch, mesh)
    x = sharded["source_x"]
    assert len(x.sharding.device_set) == 8
    # Each device holds one sample.
    shard = x.addressable_shards[0]
    assert shard.data.shape == (1, 28, 28, 1)

    replicated = replicate_state({"w": jnp.ones((4, 4))}, mesh)
    assert replicated["w"].addressable_shards[0].data.shape == (4, 4)
