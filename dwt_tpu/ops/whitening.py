"""Grouped domain-specific whitening transform (DWT) — the core op.

TPU-first re-design of the reference's ``utils/whitening.py:5-61`` (math spec
only; the implementation here is new):

* channels-LAST layout (``[..., C]``, e.g. NHWC) — the native TPU layout;
* statistics and the Cholesky factorization are carried out in float32 even
  when activations are bf16 (stability of the small ``g``-by-``g`` factors);
* the whitening matrix is obtained with a *triangular solve* against the
  identity instead of a general matrix inverse (same math — ``L^{-1}`` of the
  Cholesky factor, cf. ``whitening.py:53`` — but cheaper and with a stabler
  VJP), and is applied as one batched matmul that XLA tiles onto the MXU
  (equivalent to the reference's grouped 1x1 conv, ``whitening.py:55``);
* running statistics are *functional state* — passed in, new state returned —
  instead of hidden mutable buffers, so the op composes with jit/pjit/scan;
* optional ``axis_name`` performs a cross-replica ``pmean`` of the batch
  moments so per-replica shards reproduce the reference's global-batch
  moments (``whitening.py:41,47``) under data parallelism via shard_map.

Semantics matched to the reference (see tests/test_whitening.py):

* covariance is biased (divide by ``N*H*W``), per group (``whitening.py:47``);
* shrinkage toward identity ``(1-eps)*cov + eps*I`` with eps=1e-3 before
  factorization (``whitening.py:48``);
* eval uses running mean, and applies shrinkage to the *running* covariance
  at use time (``whitening.py:42-43,50-51``) — the EMA itself accumulates the
  UNSHRUNK covariance (``whitening.py:59``);
* EMA convention: ``running <- momentum*new + (1-momentum)*running`` with
  momentum=0.1 weighting the NEW observation (``whitening.py:57-59``); the
  EMA update is detached from the gradient graph;
* gradients flow through the batch moments and the Cholesky factorization in
  training mode (``cholesky``/``solve_triangular`` both have JVP rules).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax
from jax.scipy.linalg import solve_triangular

# A mapped-axis name or a tuple of them (2-D dcn/data mesh).
AxisName = Union[str, Tuple[str, ...]]


class WhiteningStats(NamedTuple):
    """Running statistics for one whitening site (one domain branch).

    mean: ``[C]`` float32 running channel means.
    cov:  ``[G, g, g]`` float32 running *unshrunk* per-group covariance.
    """

    mean: jax.Array
    cov: jax.Array


def _resolve_groups(num_features: int, group_size: int) -> Tuple[int, int]:
    group_size = min(num_features, group_size)
    if num_features % group_size != 0:
        raise ValueError(
            f"num_features={num_features} must be divisible by "
            f"group_size={group_size}"
        )
    return num_features // group_size, group_size


def init_whitening_stats(
    num_features: int, group_size: int, dtype=jnp.float32
) -> WhiteningStats:
    """Fresh stats: zero means; all-ones covariance.

    The all-ones (not identity) covariance init replicates the reference's
    ``torch.ones([G, g, g])`` buffer init (``whitening.py:24``); it is PSD
    (rank-1), and the eval-time shrinkage makes it PD.
    """
    num_groups, group_size = _resolve_groups(num_features, group_size)
    return WhiteningStats(
        mean=jnp.zeros((num_features,), dtype),
        cov=jnp.ones((num_groups, group_size, group_size), dtype),
    )


def _shrink(cov: jax.Array, eps: float) -> jax.Array:
    g = cov.shape[-1]
    return (1.0 - eps) * cov + eps * jnp.eye(g, dtype=cov.dtype)


def group_cov(
    xn: jax.Array,
    num_groups: int,
    group_size: int,
    axis_name: Optional[AxisName] = None,
) -> jax.Array:
    """Biased per-group covariance of centered, channels-last ``xn``.

    Returns ``[G, g, g]`` float32. With ``axis_name``, moments are averaged
    across replicas so sharded batches match global-batch numerics.
    """
    acc_dtype = jnp.promote_types(xn.dtype, jnp.float32)
    t = xn.reshape(-1, num_groups, group_size).astype(acc_dtype)
    m = t.shape[0]
    # HIGHEST precision: on TPU the default lowers f32 matmuls to bf16
    # passes — fine for activations, not for the statistics that feed a
    # Cholesky factorization (the eps shrinkage guards PSD-ness, not
    # accuracy). The [G,g,g] output is tiny; the cost is negligible.
    cov = jnp.einsum(
        "mgc,mgd->gcd",
        t,
        t,
        preferred_element_type=acc_dtype,
        precision=lax.Precision.HIGHEST,
    )
    if axis_name is not None:
        cov = lax.psum(cov, axis_name)
        m = m * lax.psum(1, axis_name)
    return cov / m


# Unroll the factorization below this group size: LAPACK-style
# ``jnp.linalg.cholesky``/``solve_triangular`` lower to sequential
# column loops (While thunks on TPU) whose per-iteration latency dwarfs
# the [G, g, g] arithmetic; a statically-unrolled Cholesky-Banachiewicz
# + forward substitution is ~g^2 fused vector ops with no control flow.
_UNROLL_MAX_G = 8


def _cholesky_unrolled(a: jax.Array) -> jax.Array:
    """Cholesky factor of batched tiny SPD matrices ``[..., g, g]``,
    statically unrolled (g is a compile-time constant <= _UNROLL_MAX_G).

    Same math as ``jnp.linalg.cholesky`` (parity pinned in
    tests/test_whitening.py); every operation is elementwise over the
    batch, so XLA fuses the whole factorization into one kernel.
    """
    g = a.shape[-1]
    # cols[j][i] is scalar-per-batch L[..., i, j]; build column by column.
    cols = [[None] * g for _ in range(g)]
    for j in range(g):
        d = a[..., j, j]
        for k in range(j):
            d = d - cols[k][j] * cols[k][j]
        ljj = jnp.sqrt(d)
        cols[j][j] = ljj
        inv = 1.0 / ljj
        for i in range(j + 1, g):
            s = a[..., i, j]
            for k in range(j):
                s = s - cols[k][i] * cols[k][j]
            cols[j][i] = s * inv
    zero = jnp.zeros_like(a[..., 0, 0])
    rows = [
        jnp.stack(
            [cols[j][i] if j <= i else zero for j in range(g)], axis=-1
        )
        for i in range(g)
    ]
    return jnp.stack(rows, axis=-2)


def _tri_inverse_unrolled(L: jax.Array) -> jax.Array:
    """``L^{-1}`` of batched tiny lower-triangular ``[..., g, g]`` by
    statically-unrolled forward substitution (solve ``L X = I``)."""
    g = L.shape[-1]
    one = jnp.ones_like(L[..., 0, 0])
    zero = jnp.zeros_like(one)
    rows = []  # rows[i][j] = X[..., i, j]
    for i in range(g):
        inv = 1.0 / L[..., i, i]
        row = []
        for j in range(g):
            if j > i:  # strict upper triangle of a lower-tri inverse
                row.append(zero)
                continue
            s = one if i == j else zero
            for k in range(j, i):  # X[k][j] == 0 for k < j (lower tri)
                s = s - L[..., i, k] * rows[k][j]
            row.append(s * inv)
        rows.append(row)
    return jnp.stack(
        [jnp.stack(r, axis=-1) for r in rows], axis=-2
    )


def whitening_matrix(cov_shrunk: jax.Array) -> jax.Array:
    """``L^{-1}`` for ``cov = L L^T`` — the (triangular) whitening matrix.

    Cholesky whitening, not ZCA: applying ``L^{-1}`` to centered data gives
    identity covariance. Triangular solve against I replaces the reference's
    explicit ``inverse`` (``whitening.py:53``) for speed and VJP stability.
    For the typical tiny group sizes (g<=8; the reference uses 4) both the
    factorization and the solve are statically unrolled — no sequential
    While-loop lowering on TPU.
    """
    g = cov_shrunk.shape[-1]
    if g <= _UNROLL_MAX_G:
        return _tri_inverse_unrolled(_cholesky_unrolled(cov_shrunk))
    chol = jnp.linalg.cholesky(cov_shrunk)
    eye = jnp.broadcast_to(jnp.eye(g, dtype=cov_shrunk.dtype), cov_shrunk.shape)
    return solve_triangular(chol, eye, lower=True)


def _block_diag_expand(w: jax.Array) -> jax.Array:
    """``[G, g, g]`` per-group matrices -> one ``[C, C]`` block-diagonal
    matrix (C = G*g) with ``B[(g,c),(h,d)] = w[h,d,c] * (g == h)``, so that
    ``xn.reshape(-1, C) @ B`` equals the grouped apply."""
    G, g = w.shape[0], w.shape[1]
    eye = jnp.eye(G, dtype=w.dtype)
    # rows indexed by (g_in, c), cols by (h_out, d).
    return jnp.einsum("hdc,gh->gchd", w, eye).reshape(G * g, G * g)


def apply_whitening(
    xn: jax.Array, w: jax.Array, compute_dtype=None, lowering: str = "auto"
) -> jax.Array:
    """Apply per-group whitening matrix ``w [G, g, g]`` to centered ``xn``.

    One batched matmul over groups — XLA maps it straight onto the MXU; it is
    mathematically the reference's grouped 1x1 conv (``whitening.py:55``).

    ``compute_dtype`` sets the matmul operand dtype (default: ``w.dtype``,
    i.e. f32).  bf16 nets pass bf16 so the apply rides the full-rate bf16
    MXU path with half the operand traffic; accumulation stays f32 via
    ``preferred_element_type``.
    """
    compute_dtype = compute_dtype or w.dtype
    acc_dtype = jnp.promote_types(compute_dtype, jnp.float32)
    shape = xn.shape
    num_groups, group_size = w.shape[0], w.shape[1]
    C = num_groups * group_size
    if lowering not in ("auto", "grouped", "blockdiag"):
        raise ValueError(f"unknown apply lowering: {lowering!r}")
    if lowering == "auto":
        # The grouped einsum contracts over only g (4) channels — a shape
        # both the MXU (heavy tile padding) and CPU BLAS (strided tiny
        # batched matmuls) handle poorly.  The [C, C] block-diagonal
        # matmul costs C/g more FLOPs but runs dense: measured on CPU it
        # is 7x (C=64) to 17x (C=256) faster than grouped despite the
        # inflation, so CPU always takes it; on TPU it is taken for
        # narrow C where the padding waste dominates, and past C=128 the
        # C/g FLOP inflation plausibly wins — tools/pallas_bench.py's
        # apply_{grouped,blockdiag}_ms A/B is the data to revisit this.
        if jax.default_backend() == "cpu":
            lowering = "blockdiag"
        else:
            lowering = "blockdiag" if C <= 128 else "grouped"
    if lowering == "blockdiag":
        t = xn.reshape(-1, C).astype(compute_dtype)
        B = _block_diag_expand(w).astype(compute_dtype)
        y = jnp.matmul(t, B, preferred_element_type=acc_dtype)
        return y.reshape(shape).astype(xn.dtype)
    t = xn.reshape(-1, num_groups, group_size)
    y = jnp.einsum(
        "mgc,gdc->mgd",
        t.astype(compute_dtype),
        w.astype(compute_dtype),
        preferred_element_type=acc_dtype,
    )
    return y.reshape(shape).astype(xn.dtype)


def group_whiten(
    x: jax.Array,
    stats: WhiteningStats,
    *,
    group_size: int,
    train: bool,
    momentum: float = 0.1,
    eps: float = 1e-3,
    axis_name: Optional[AxisName] = None,
) -> Tuple[jax.Array, WhiteningStats]:
    """Whiten channels-last ``x`` per group of channels.

    Args:
      x: ``[..., C]`` activations (any number of leading axes; NHWC for conv
        features). Moments reduce over ALL leading axes.
      stats: running stats for this (domain) branch.
      group_size: channels per whitening group (clamped to ``C``).
      train: True → batch moments + EMA update; False → running stats, no
        state change (``whitening.py:42-43,50-51``).
      momentum: EMA weight of the NEW observation (``whitening.py:57-59``).
      eps: shrinkage toward identity (``whitening.py:48``).
      axis_name: optional mapped axis for cross-replica moment pmean.

    Returns:
      ``(whitened, new_stats)`` — whitened has the dtype/shape of ``x``.
    """
    num_features = x.shape[-1]
    num_groups, group_size = _resolve_groups(num_features, group_size)

    # f32 statistics under bf16 activations; f64 passes through untruncated.
    xf = x.astype(jnp.promote_types(x.dtype, jnp.float32))
    if train:
        reduce_axes = tuple(range(x.ndim - 1))
        m = jnp.mean(xf, axis=reduce_axes)
        if axis_name is not None:
            m = lax.pmean(m, axis_name)
        xn = xf - m
        cov = group_cov(xn, num_groups, group_size, axis_name)
        w = whitening_matrix(_shrink(cov, eps))
        # Moments/factorization stay f32; the apply matmul runs in the
        # activation dtype (bf16 nets → bf16 MXU path, f32 accumulation) —
        # the standard mixed-precision norm recipe.
        y = apply_whitening(xn, w, compute_dtype=x.dtype).astype(x.dtype)
        new_stats = WhiteningStats(
            mean=(
                momentum * lax.stop_gradient(m)
                + (1.0 - momentum) * stats.mean
            ),
            cov=(
                momentum * lax.stop_gradient(cov)
                + (1.0 - momentum) * stats.cov
            ),
        )
        return y, new_stats
    else:
        xn = xf - stats.mean
        w = whitening_matrix(_shrink(stats.cov.astype(xf.dtype), eps))
        y = apply_whitening(xn, w, compute_dtype=x.dtype).astype(x.dtype)
        return y, stats
