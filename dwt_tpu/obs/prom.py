"""Prometheus text-format v0.0.4 exposition, dependency-free.

Three consumers share this module:

* ``dwt-serve`` and ``dwt-fleet`` add a ``/metrics`` route to their
  existing HTTP front ends (``render`` + :data:`CONTENT_TYPE`);
* the training CLIs — which have no HTTP server — start a
  :func:`start_exporter` stdlib-HTTP daemon thread on ``--metrics_port``
  (the train loop's first live surface: scrape steps/s, loss, guard
  events, checkpoint stalls mid-run instead of tailing JSONL);
* the fleet balancer aggregates its replicas' expositions
  (:func:`parse_exposition` + :func:`merge_expositions`): every replica
  sample re-emitted with a ``replica="N"`` label next to the balancer's
  own series, one scrape for the whole fleet.

``validate_exposition`` is the format gate the tests assert — line
grammar, HELP/TYPE/sample consistency, histogram bucket monotonicity and
the ``+Inf``-equals-``_count`` invariant — so "valid Prometheus text"
is a checked property, not a hope.
"""

from __future__ import annotations

import json
import logging
import math
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, List, Optional, Sequence, Tuple

from dwt_tpu.obs.registry import MetricsRegistry, get_registry

log = logging.getLogger(__name__)

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

__all__ = [
    "CONTENT_TYPE",
    "render",
    "parse_exposition",
    "validate_exposition",
    "merge_expositions",
    "start_exporter",
    "exporter_port",
]


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s: str) -> str:
    return (
        s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _fmt_value(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _fmt_labels(labels: Dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label(str(v))}"' for k, v in labels.items()
    )
    return "{" + inner + "}"


def render(registry: Optional[MetricsRegistry] = None) -> str:
    """The registry as Prometheus text exposition (one scrape body)."""
    registry = registry or get_registry()
    lines: List[str] = []
    for fam in registry.families():
        lines.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
        lines.append(f"# TYPE {fam.name} {fam.kind}")
        for labels, child in fam.samples():
            if fam.kind == "histogram":
                bounds, counts, total, count = child.snapshot()
                cum = 0
                for b, c in zip(bounds, counts):
                    cum += c
                    lab = dict(labels)
                    lab["le"] = _fmt_value(b)
                    lines.append(
                        f"{fam.name}_bucket{_fmt_labels(lab)} {cum}"
                    )
                lab = dict(labels)
                lab["le"] = "+Inf"
                lines.append(
                    f"{fam.name}_bucket{_fmt_labels(lab)} {count}"
                )
                lines.append(
                    f"{fam.name}_sum{_fmt_labels(labels)} "
                    f"{_fmt_value(total)}"
                )
                lines.append(
                    f"{fam.name}_count{_fmt_labels(labels)} {count}"
                )
            else:
                lines.append(
                    f"{fam.name}{_fmt_labels(labels)} "
                    f"{_fmt_value(child.get())}"
                )
    return "\n".join(lines) + ("\n" if lines else "")


# ------------------------------------------------------------- parsing

_NAME_RE = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_SAMPLE_RE = re.compile(
    rf"^({_NAME_RE})(\{{(.*)\}})?\s+(\S+)(\s+-?\d+)?\s*$"
)
_LABEL_RE = re.compile(
    rf'({_NAME_RE})="((?:[^"\\]|\\.)*)"\s*(,|$)'
)
_HELP_RE = re.compile(rf"^# HELP ({_NAME_RE})(?: (.*))?$")
_TYPE_RE = re.compile(rf"^# TYPE ({_NAME_RE}) (\w+)$")

_HIST_SUFFIXES = ("_bucket", "_sum", "_count")


def _unescape_label(s: str) -> str:
    # One left-to-right pass, NOT chained str.replace: sequential
    # replaces mis-decode an escaped backslash followed by 'n'/'"'
    # ('ckpt\\next' escaped is 'ckpt\\\\next'; replace("\\n", ...) would
    # eat the second backslash plus the n).
    out = []
    i = 0
    while i < len(s):
        c = s[i]
        if c == "\\" and i + 1 < len(s):
            nxt = s[i + 1]
            if nxt == "n":
                out.append("\n")
                i += 2
                continue
            if nxt in ('"', "\\"):
                out.append(nxt)
                i += 2
                continue
        out.append(c)
        i += 1
    return "".join(out)


def _parse_labels(body: str) -> Dict[str, str]:
    labels: Dict[str, str] = {}
    pos = 0
    body = body.strip()
    while pos < len(body):
        m = _LABEL_RE.match(body, pos)
        if m is None:
            raise ValueError(f"bad label syntax at {body[pos:]!r}")
        labels[m.group(1)] = _unescape_label(m.group(2))
        pos = m.end()
    return labels


def _parse_value(s: str) -> float:
    if s == "+Inf":
        return math.inf
    if s == "-Inf":
        return -math.inf
    if s == "NaN":
        return math.nan
    return float(s)


class Family:
    """One parsed metric family: declared type/help + raw samples."""

    def __init__(self, name: str, kind: str = "untyped", help: str = ""):
        self.name = name
        self.kind = kind
        self.help = help
        # Raw sample rows: (sample_name, labels dict, value) — histogram
        # samples keep their _bucket/_sum/_count names so a merged
        # re-render is byte-faithful to what each process exported.
        self.samples: List[Tuple[str, Dict[str, str], float]] = []


def _base_name(sample_name: str, families: Dict[str, "Family"]) -> str:
    """The family a sample row belongs to: its own name, or — for
    histogram sub-samples — the declared family it suffixes."""
    if sample_name in families:
        return sample_name
    for suf in _HIST_SUFFIXES:
        if sample_name.endswith(suf):
            base = sample_name[: -len(suf)]
            if base in families and families[base].kind == "histogram":
                return base
    return sample_name


def parse_exposition(text: str) -> Dict[str, Family]:
    """Text exposition -> ordered {family name: :class:`Family`}.
    Raises ``ValueError`` on lines that fit no grammar."""
    families: Dict[str, Family] = {}

    def fam(name: str, kind=None, help=None) -> Family:
        f = families.get(name)
        if f is None:
            f = families[name] = Family(name)
        if kind is not None:
            f.kind = kind
        if help is not None:
            f.help = help
        return f

    for lineno, line in enumerate(text.splitlines(), 1):
        if not line.strip():
            continue
        if line.startswith("#"):
            m = _HELP_RE.match(line)
            if m:
                fam(m.group(1), help=m.group(2) or "")
                continue
            m = _TYPE_RE.match(line)
            if m:
                fam(m.group(1), kind=m.group(2))
                continue
            continue  # other comments are legal and ignored
        m = _SAMPLE_RE.match(line)
        if m is None:
            raise ValueError(f"line {lineno}: unparsable sample {line!r}")
        name, _, label_body, value_s = (
            m.group(1), m.group(2), m.group(3), m.group(4)
        )
        labels = _parse_labels(label_body) if label_body else {}
        value = _parse_value(value_s)
        fam(_base_name(name, families)).samples.append(
            (name, labels, value)
        )
    return families


_KNOWN_TYPES = ("counter", "gauge", "histogram", "summary", "untyped")


def validate_exposition(text: str) -> List[str]:
    """Structural problems with a text exposition ([] = valid).

    Checks: line grammar (via the parser), known TYPE values, counter
    monotonic-from-zero plausibility (non-negative, non-NaN), histogram
    cumulative-bucket monotonicity per series and ``le="+Inf"`` equal to
    the series' ``_count``.
    """
    problems: List[str] = []
    try:
        families = parse_exposition(text)
    except ValueError as e:
        return [str(e)]
    for fam in families.values():
        if fam.kind not in _KNOWN_TYPES:
            problems.append(f"{fam.name}: unknown TYPE {fam.kind!r}")
            continue
        if fam.kind == "counter":
            for name, labels, value in fam.samples:
                if math.isnan(value) or value < 0:
                    problems.append(
                        f"{fam.name}: counter sample {labels} has "
                        f"non-monotonic value {value}"
                    )
        if fam.kind == "histogram":
            # Group sub-samples by the label set minus `le`.
            series: Dict[Tuple, Dict] = {}
            for name, labels, value in fam.samples:
                key = tuple(sorted(
                    (k, v) for k, v in labels.items() if k != "le"
                ))
                s = series.setdefault(
                    key, {"buckets": [], "sum": None, "count": None}
                )
                if name.endswith("_bucket"):
                    if "le" not in labels:
                        problems.append(
                            f"{fam.name}: _bucket sample missing le "
                            f"label: {labels}"
                        )
                        continue
                    s["buckets"].append(
                        (_parse_value(labels["le"]), value)
                    )
                elif name.endswith("_sum"):
                    s["sum"] = value
                elif name.endswith("_count"):
                    s["count"] = value
                else:
                    problems.append(
                        f"{fam.name}: unexpected histogram sample {name}"
                    )
            for key, s in series.items():
                buckets = sorted(s["buckets"], key=lambda bv: bv[0])
                if not buckets or not math.isinf(buckets[-1][0]):
                    problems.append(
                        f"{fam.name}{dict(key)}: histogram without an "
                        "le=\"+Inf\" bucket"
                    )
                    continue
                counts = [c for _, c in buckets]
                if any(b > a for a, b in zip(counts[1:], counts)):
                    problems.append(
                        f"{fam.name}{dict(key)}: bucket counts not "
                        f"monotonically non-decreasing: {counts}"
                    )
                if s["count"] is None or s["sum"] is None:
                    problems.append(
                        f"{fam.name}{dict(key)}: histogram missing "
                        "_sum/_count"
                    )
                elif counts[-1] != s["count"]:
                    problems.append(
                        f"{fam.name}{dict(key)}: le=\"+Inf\" bucket "
                        f"{counts[-1]} != _count {s['count']}"
                    )
    return problems


def merge_expositions(
    parts: Sequence[Tuple[Dict[str, str], str]],
) -> str:
    """Merge expositions into one, adding per-part labels — the fleet's
    aggregation: ``[({}, balancer_text), ({"replica": "0"}, r0_text),
    ...]``.  HELP/TYPE emit once per family (first declaration wins —
    replicas run the same code, so declarations agree); every sample of
    a part gets that part's extra labels.  A part that fails to parse is
    SKIPPED with a log line: one replica's garbage must not take down
    the whole fleet's scrape.
    """
    merged: Dict[str, Family] = {}
    for extra, text in parts:
        try:
            families = parse_exposition(text)
        except ValueError as e:
            log.warning("metrics merge: skipping unparsable part %s: %s",
                        extra, e)
            continue
        for name, fam in families.items():
            out = merged.get(name)
            if out is None:
                out = merged[name] = Family(name, fam.kind, fam.help)
            for sname, labels, value in fam.samples:
                labels = dict(labels)
                # Part labels go FIRST so a scrape reads replica="0"
                # up front; a sample's own label of the same name wins
                # (it is more specific).
                labels = {**extra, **labels}
                out.samples.append((sname, labels, value))
    lines: List[str] = []
    for fam in merged.values():
        if fam.help:
            lines.append(f"# HELP {fam.name} {_escape_help(fam.help)}")
        if fam.kind != "untyped":
            lines.append(f"# TYPE {fam.name} {fam.kind}")
        for sname, labels, value in fam.samples:
            lines.append(
                f"{sname}{_fmt_labels(labels)} {_fmt_value(value)}"
            )
    return "\n".join(lines) + ("\n" if lines else "")


# ------------------------------------------------------------- exporter

class _MetricsHandler(BaseHTTPRequestHandler):
    registry: MetricsRegistry = None  # type: ignore[assignment]

    def log_message(self, fmt, *args):
        log.debug("metrics http: " + fmt, *args)

    def do_GET(self):
        if self.path.split("?")[0] not in ("/metrics", "/"):
            body = json.dumps({"error": f"unknown path {self.path}"})
            self.send_response(404)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body.encode())
            return
        try:
            fn = getattr(self, "render_fn", None)
            body = (fn() if fn is not None else render(self.registry)).encode()
        except Exception as e:  # a scrape must answer, not die
            log.exception("metrics render failed")
            body = f"# render failed: {type(e).__name__}: {e}\n".encode()
        self.send_response(200)
        self.send_header("Content-Type", CONTENT_TYPE)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


_EXPORTER_LOCK = threading.Lock()
_EXPORTER: Optional[ThreadingHTTPServer] = None


def start_exporter(port: int, host: str = "127.0.0.1",
                   registry: Optional[MetricsRegistry] = None,
                   render_fn=None,
                   ) -> ThreadingHTTPServer:
    """Serve ``/metrics`` on a daemon thread (the training CLIs'
    ``--metrics_port``; 0 binds an ephemeral port — read it back from
    the return's ``server_address``).  Idempotent per process: a second
    call returns the running exporter (the two training entry points
    share one registry, so one scrape surface is correct).

    ``render_fn`` overrides the body production entirely — an
    aggregator (the sweep supervisor merging per-job expositions via
    :func:`merge_expositions`) serves something richer than one
    registry's render; exceptions still answer the scrape with a
    comment line rather than killing the connection."""
    global _EXPORTER
    with _EXPORTER_LOCK:
        if _EXPORTER is not None:
            return _EXPORTER
        handler = type("Handler", (_MetricsHandler,), {
            "registry": registry or get_registry(),
            "render_fn": staticmethod(render_fn) if render_fn else None,
        })
        server = ThreadingHTTPServer((host, int(port)), handler)
        server.daemon_threads = True
        thread = threading.Thread(
            target=server.serve_forever, name="dwt-metrics-exporter",
            daemon=True,
        )
        thread.start()
        _EXPORTER = server
        return server


def exporter_port() -> Optional[int]:
    """Bound port of the running exporter (None when not started)."""
    with _EXPORTER_LOCK:
        return (
            _EXPORTER.server_address[1] if _EXPORTER is not None else None
        )


def stop_exporter() -> None:
    """Shut the exporter down (tests; CLIs just exit the process)."""
    global _EXPORTER
    with _EXPORTER_LOCK:
        server, _EXPORTER = _EXPORTER, None
    if server is not None:
        server.shutdown()
        server.server_close()
