"""Functional compute ops (pure, jit-able, differentiable)."""

from dwt_tpu.ops.whitening import (  # noqa: F401
    WhiteningStats,
    init_whitening_stats,
    group_whiten,
    group_cov,
    whitening_matrix,
    apply_whitening,
)
from dwt_tpu.ops.pallas_whitening import (  # noqa: F401
    pallas_group_whiten,
)
from dwt_tpu.ops.batch_norm import (  # noqa: F401
    BatchNormStats,
    init_batch_norm_stats,
    batch_norm,
)
from dwt_tpu.ops.losses import (  # noqa: F401
    at_least_f32,
    entropy_loss,
    mec_loss,
    nll_loss,
    softmax_cross_entropy,
    accuracy,
)
