"""OfficeHome entrypoint — reference ``resnet50_dwt_mec_officehome.py:
495-600`` flag surface (plus dwt_tpu extensions)."""

from __future__ import annotations

import argparse

from dwt_tpu.config import OfficeHomeConfig
from dwt_tpu.utils import MetricLogger


def build_parser() -> argparse.ArgumentParser:
    d = OfficeHomeConfig()
    p = argparse.ArgumentParser(description="dwt_tpu DWT-MEC OfficeHome trainer")
    p.add_argument("--num_workers", type=int, default=d.num_workers,
                   help="item-loading worker threads (decode+augment)")
    p.add_argument("--data_stall_timeout", type=float,
                   default=d.data_stall_timeout,
                   help="data-pipeline head-of-window stall budget "
                        "(seconds): a worker silent past this is logged, "
                        "counted (dwt_data_stalls_total), and its item "
                        "speculatively re-submitted to a fresh worker — "
                        "dead/slow-worker recovery instead of a silent "
                        "stall.  0 disables detection")
    p.add_argument("--source_batch_size", type=int, default=d.source_batch_size)
    p.add_argument("--target_batch_size", type=int, default=d.target_batch_size,
                   help="accepted for parity; loaders use source_batch_size, "
                        "as in reference (:565)")
    p.add_argument("--test_batch_size", type=int, default=d.test_batch_size)
    p.add_argument("--s_dset_path", type=str, default=d.s_dset_path)
    p.add_argument("--t_dset_path", type=str, default=d.t_dset_path)
    p.add_argument("--resnet_path", type=str, default=d.resnet_path)
    p.add_argument("--img_resize", type=int, default=d.img_resize)
    p.add_argument("--img_crop_size", type=int, default=d.img_crop_size)
    p.add_argument("--num_iters", type=int, default=d.num_iters)
    p.add_argument("--check_acc_step", type=int, default=d.check_acc_step)
    p.add_argument("--lr_change_step", type=int, default=d.lr_change_step,
                   help="accepted for parity; milestone hardcoded at 6000, "
                        "as in reference (:398)")
    p.add_argument("--lr", type=float, default=d.lr)
    p.add_argument("--num_classes", type=int, default=d.num_classes)
    p.add_argument("--sgd_momentum", type=float, default=None,
                   help="reference default 0.5 is unused there; the actual "
                        "optimizer momentum is 0.9 (:590), which dwt_tpu uses "
                        "when the flag is not given (None sentinel, so an "
                        "explicit 0.5 is honored)")
    p.add_argument("--running_momentum", type=float, default=d.running_momentum)
    p.add_argument("--lambda_mec_loss", type=float, default=d.lambda_mec_loss)
    p.add_argument("--log_interval", type=int, default=d.log_interval)
    p.add_argument("--seed", type=int, default=d.seed)
    p.add_argument("--group_size", type=int, default=d.group_size)
    # dwt_tpu extensions
    p.add_argument("--arch", choices=["resnet50", "resnet101", "tiny"],
                   default=d.arch)
    p.add_argument("--backbone", type=str, default=d.backbone,
                   help="backbone-registry entry (wins over --arch): "
                        "resnet50|resnet101|resnet152|tiny|vit_dwt|"
                        "vit_tiny — resnet152/vit_dwt are the "
                        ">1-chip-HBM entries the fsdp preset targets "
                        "(dwt_tpu.nn.registry)")
    p.add_argument("--pad_classes_to", type=int, default=d.pad_classes_to,
                   help=">1: pad the classifier head's out dim up to a "
                        "multiple of this so an fsdp/model rules table "
                        "can shard the head when num_classes is "
                        "indivisible; padded logit columns are sliced "
                        "off inside the forward — counters stay exact")
    p.add_argument("--stat_collection_passes", type=int,
                   default=d.stat_collection_passes)
    p.add_argument("--synthetic", action="store_true")
    p.add_argument("--synthetic_size", type=int, default=d.synthetic_size)
    p.add_argument("--data_parallel", action="store_true")
    p.add_argument("--distributed", action="store_true",
                   help="multi-host bring-up: call jax.distributed.initialize(); "
                        "launch the same command on every host")
    p.add_argument("--pallas_whiten", action="store_true",
                   help="route whitening through the Pallas two-pass "
                        "kernels (single-chip; incompatible with "
                        "--data_parallel)")
    p.add_argument("--whitener",
                   choices=["cholesky", "newton_schulz", "swbn"],
                   default=d.whitener,
                   help="whitening numerics backend: cholesky (reference "
                        "unrolled factorization, default), newton_schulz "
                        "(fixed-K iteration of pure batched matmuls — "
                        "MXU-native, batches across sites), swbn (online "
                        "whitening-matrix tracking, no factorization — "
                        "eval runs off running estimates, so "
                        "--stat_collection_passes 0 collapses the eval "
                        "cadence from ~11 dataset passes to ~1)")
    p.add_argument("--apply_lowering",
                   choices=["auto", "grouped", "blockdiag"],
                   default=d.apply_lowering,
                   help="force the whitening-apply matmul lowering; auto "
                        "keeps the backend heuristic (CPU: blockdiag; "
                        "TPU: blockdiag up to the DWT_APPLY_CROSSOVER_C "
                        "channel crossover, default 128, then grouped)")
    p.add_argument("--dcn_slices", type=int, default=d.dcn_slices,
                   help=">1: 2-D (dcn, data) mesh — pod-level DP across "
                        "slices, per-slice reductions on ICI")
    p.add_argument("--mesh_shape", type=str, default=d.mesh_shape,
                   help="sharding-rules engine mesh as 'dcn,data,model' "
                        "sizes (e.g. 1,2,2); '4' and '2,4' shorthands "
                        "pad the missing axes to 1.  Unset keeps the "
                        "legacy single/--data_parallel decision")
    p.add_argument("--sharding_rules", type=str, default=d.sharding_rules,
                   help="rules table driving per-leaf placement: preset "
                        "'dp' (replicate all state — bitwise the legacy "
                        "paths), preset 'model' (out-channel model "
                        "sharding, whitening/BN stats pinned replicated), "
                        "preset 'fsdp' (shard ALL conv/dense kernels + "
                        "their Adam moments over the model axis — "
                        "per-host param+opt-state at ~1/model_axis; "
                        "stats stay replicated), or a path to a JSON "
                        "[[regex, spec], ...] file")
    p.add_argument("--steps_per_dispatch", type=int,
                   default=d.steps_per_dispatch,
                   help=">1: run k train steps per dispatch (lax.scan "
                        "over k stacked batches; chunks cut at eval/"
                        "checkpoint boundaries) — amortizes host "
                        "dispatch latency; same numerics")
    p.add_argument("--eval_steps_per_dispatch", type=int,
                   default=d.eval_steps_per_dispatch,
                   help="k eval/stat-collection batches per scanned "
                        "dispatch; eval counters stay device-resident "
                        "across the whole pass (O(1) host fetches) and "
                        "the 10-pass stat-collection protocol dispatches "
                        "at the same granularity")
    p.add_argument("--harvest_depth", type=int, default=d.harvest_depth,
                   help="async metric harvesting: depth of the bounded "
                        "ring deferring the train-record host fetch "
                        "(amortized 1/depth syncs per step; full drains "
                        "at eval/ckpt/preempt/rollback boundaries; "
                        "byte-identical records with original step "
                        "stamps; guard staleness <= depth).  0 = legacy "
                        "synchronous fetch")
    p.add_argument("--init_ckpt", type=str, default=None,
                   help="read-only Orbax init artifact (written by "
                        "dwt-convert); unlike --ckpt_dir it is never "
                        "written to, so repeated runs always start from "
                        "the converted weights")
    p.add_argument("--ckpt_dir", type=str, default=None)
    p.add_argument("--ckpt_every_iters", type=int, default=d.ckpt_every_iters)
    p.add_argument("--async_ckpt", action=argparse.BooleanOptionalAction,
                   default=d.async_ckpt,
                   help="background checkpoint pipeline: the loop only "
                        "snapshots + enqueues; digest/Orbax write/rename "
                        "run on a writer thread (--no-async_ckpt: every "
                        "save blocks the loop)")
    p.add_argument("--ckpt_format", choices=["full", "delta"],
                   default=d.ckpt_format,
                   help="checkpoint on-disk format: 'full' writes the "
                        "whole tree every save (byte-compatible default); "
                        "'delta' is the content-addressed incremental "
                        "store — only leaves whose digest moved are "
                        "written, the frozen-backbone fine-tune's save "
                        "bytes collapse to the churning head/stats")
    p.add_argument("--delta_max_chain", type=int, default=d.delta_max_chain,
                   help="delta-format chain cap: after this many chained "
                        "delta saves the next save is forced full, "
                        "bounding restore reads and torn-chain blast "
                        "radius")
    p.add_argument("--blob_store", type=str, default=d.blob_store,
                   help="delta-format blob store override: a SHARED "
                        "store path multiple runs (a sweep's pairs) save "
                        "into, deduping identical leaves (the frozen "
                        "backbone) across runs; sharing disables this "
                        "run's local blob GC — cross-run refcounted GC "
                        "is the sweep supervisor's (dwt-sweep).  Default: "
                        "<ckpt_dir>/blobs (private, locally GC'd)")
    p.add_argument("--anchor_every", type=int, default=d.anchor_every,
                   help=">0: every N iters also save an anchor checkpoint "
                        "under ckpt_dir/anchors, exempt from any pruning — "
                        "bounds rollback distance under repeated divergence")
    p.add_argument("--guard_policy",
                   choices=["none", "halt", "skip_step", "rollback"],
                   default=d.guard_policy,
                   help="divergence guard: on a non-finite loss/grad-norm, "
                        "halt, skip back to the last good in-memory state, "
                        "or roll back to the newest valid checkpoint with a "
                        "re-seeded data order")
    p.add_argument("--guard_interval", type=int, default=d.guard_interval,
                   help="steps between guard finite-checks (each check is "
                        "one host sync; NaN is absorbing, so detection is "
                        "at most interval-1 steps late)")
    p.add_argument("--guard_max_rollbacks", type=int,
                   default=d.guard_max_rollbacks,
                   help="rollback attempts before the guard halts the run")
    p.add_argument("--guard_lr_backoff", type=float, default=d.guard_lr_backoff,
                   help="in (0,1): first guard rung — revert to the last "
                        "good in-memory state and scale optimizer updates "
                        "by this factor (e.g. 0.5); recovers to 1.0 after "
                        "--guard_backoff_recovery clean checks, escalates "
                        "to --guard_policy if it strikes again while "
                        "backed off.  0 disables the rung")
    p.add_argument("--guard_backoff_recovery", type=int,
                   default=d.guard_backoff_recovery,
                   help="clean guard checks before a backed-off lr "
                        "recovers to 1.0 (re-arming the backoff rung)")
    p.add_argument("--watchdog_timeout", type=float, default=d.watchdog_timeout,
                   help=">0: hang watchdog — if no step boundary completes "
                        "for this many seconds, dump all-thread stacks "
                        "under ckpt_dir/watchdog/ and exit 113 so the "
                        "scheduler relaunches into resume; budget for the "
                        "first step's compile and boundary evals.  0 = off")
    p.add_argument("--watchdog_keep", type=int, default=d.watchdog_keep,
                   help="cap on retained watchdog stack dumps under "
                        "ckpt_dir/watchdog/ (oldest pruned first); a "
                        "relaunch loop must not fill the disk")
    p.add_argument("--preempt_notice_file", type=str,
                   default=d.preempt_notice_file,
                   help="preemption notice file: when this path comes "
                        "into existence (scheduler prolog/preStop hook), "
                        "every host takes a proactive save at the next "
                        "step boundary while training continues — the "
                        "later SIGTERM exits fast")
    p.add_argument("--preempt_notice_metadata",
                   action=argparse.BooleanOptionalAction,
                   default=d.preempt_notice_metadata,
                   help="poll the GCE instance/preempted metadata key "
                        "(~30 s advance warning on spot/preemptible VMs) "
                        "as a preemption notice source; URL overridable "
                        "via DWT_PREEMPT_METADATA_URL for tests")
    p.add_argument("--keep_ckpts", type=int, default=d.keep_ckpts,
                   help=">0: prune the main --ckpt_dir to the newest N "
                        "steps after each periodic/final save; anchors "
                        "(--anchor_every) and best_* artifacts live in "
                        "separate directories and are never pruned")
    p.add_argument("--obs_trace", type=str, default=d.obs_trace,
                   help="span tracing: write a Chrome trace-event JSON of "
                        "the run's per-phase spans (batch wait / step "
                        "dispatch / host fetch / consensus / checkpoint) "
                        "to this path — open in Perfetto or feed "
                        "tools/obs_report.py; DWT_OBS_TRACE env is the "
                        "flagless form.  Off by default; disabled spans "
                        "cost ~one global read")
    p.add_argument("--heartbeat_every", type=int, default=d.heartbeat_every,
                   help=">0: emit a heartbeat record (steps/s EWMA, host "
                        "RSS MB, device memory, async-ckpt in-flight "
                        "depth) every N steps — the cheap always-on "
                        "liveness signal when full tracing is off.  "
                        "0 disables")
    p.add_argument("--metrics_port", type=int, default=d.metrics_port,
                   help="live metrics plane: serve Prometheus text "
                        "exposition at /metrics on this port (daemon "
                        "thread; 0 = ephemeral, port logged as a "
                        "metrics_exporter record)")
    p.add_argument("--alert_rules", type=str, default=d.alert_rules,
                   help="SLO alert rules JSON evaluated each step "
                        "boundary against the live registry; fire/clear "
                        "transitions emit 'alert' JSONL records and the "
                        "dwt_alerts_firing gauge")
    p.add_argument("--bf16", action="store_true",
                   help="legacy alias for --compute_dtype bf16")
    p.add_argument("--compute_dtype", type=str, default=d.compute_dtype,
                   choices=("f32", "bf16"),
                   help="training compute dtype: params/optimizer state "
                        "stay f32; bf16 runs activations, backprop "
                        "traffic, and the whitening apply in bf16 (see "
                        "ops/whitening.py precision_policy).  f32 "
                        "(default) is bitwise the legacy path")
    p.add_argument("--remat", action="store_true",
                   help="rematerialize bottleneck blocks in backward "
                        "(less HBM, ~1/3 more FLOPs) for larger batches")
    p.add_argument("--metrics_jsonl", type=str, default=None)
    p.add_argument("--expect_accuracy", type=float, default=None,
                   help="repro assertion: exit nonzero unless final target "
                        "accuracy is within --tolerance of this (paper "
                        "Table-3 value, see baselines/)")
    p.add_argument("--tolerance", type=float, default=0.3,
                   help="±%% band for --expect_accuracy (BASELINE "
                        "north-star: 0.3)")
    p.add_argument("--debug_nans", action="store_true",
                   help="jax_debug_nans: fail fast at the op that produced a NaN "
                        "(the whitening Cholesky guard, SURVEY \u00a75)")
    return p


def config_from_args(args: argparse.Namespace) -> OfficeHomeConfig:
    fields = {f.name for f in OfficeHomeConfig.__dataclass_fields__.values()}
    kwargs = {k: v for k, v in vars(args).items() if k in fields}
    # The reference's *effective* SGD momentum is 0.9 regardless of the
    # (dead) --sgd_momentum flag; None (flag absent) maps to 0.9 so every
    # explicitly-passed value — including 0.5 — is honored.
    if kwargs.get("sgd_momentum") is None:
        kwargs["sgd_momentum"] = 0.9
    return OfficeHomeConfig(**kwargs)


def run_from_args(args: argparse.Namespace) -> float:
    """Shared entrypoint plumbing for the OfficeHome-recipe CLIs (this one
    and ``dwt_tpu.cli.visda``): debug toggles, logger lifecycle, dispatch,
    and the optional --expect_accuracy repro assertion."""
    if args.debug_nans:
        import jax

        jax.config.update("jax_debug_nans", True)
    from dwt_tpu.train.loop import run_officehome
    from dwt_tpu.utils import check_cli_accuracy

    logger = MetricLogger(jsonl_path=args.metrics_jsonl)
    try:
        acc = run_officehome(config_from_args(args), logger)
        if not check_cli_accuracy(
            acc, getattr(args, "expect_accuracy", None),
            getattr(args, "tolerance", 0.3), logger,
        ):
            raise SystemExit(1)
        return acc
    finally:
        logger.close()


def main(argv=None) -> float:
    return run_from_args(build_parser().parse_args(argv))


if __name__ == "__main__":
    main()
