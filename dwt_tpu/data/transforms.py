"""Image transforms: PIL for geometry, numpy/cv2 for the aug math.

The OfficeHome target-view augmentation stack replicated from the
reference (``resnet50_dwt_mec_officehome.py:481-492,535-543``): resize →
random crop → hflip → random affine perturbation → (near-no-op) gaussian
blur → normalize.  All callables are ``img -> img`` where ``img`` is a PIL
Image until ``ToArray`` and an HWC float32 numpy array after.
"""

from __future__ import annotations

import threading
from typing import Sequence

import numpy as np

try:
    import cv2

    _HAS_CV2 = True
except ImportError:  # pragma: no cover
    _HAS_CV2 = False


_ITEM_SEED = threading.local()


def set_item_seed(token) -> None:
    """Declare the (hashable, int-tuple) identity of the item being loaded
    on THIS thread; ``ThreadLocalRng`` derives its stream from it so an
    item's augmentations depend only on (rng seed, item token) — never on
    which worker thread loaded it.  ``batch_iterator`` sets this around
    every ``dataset[i]`` call; ``None`` clears it."""
    _ITEM_SEED.token = token


class ThreadLocalRng:
    """``np.random.Generator`` facade that is thread-safe AND item-deterministic.

    ``np.random.Generator`` is not thread-safe; when ``batch_iterator``
    runs ``dataset[i]`` on a worker pool, stochastic transforms sharing a
    single generator would race.  Worse, per-*thread* streams would make a
    fixed-seed run irreproducible (item→thread assignment is scheduler-
    dependent).  So: while an item is being loaded (``set_item_seed``
    active, which both loading paths of ``batch_iterator`` arrange), draws
    come from a generator seeded by ``(seed, *item_token)`` — identical
    whether the item loads sequentially, on any pool size, or on any
    thread.  Outside item context each thread falls back to its own
    spawned stream (valid draws, no races, no cross-run promise).
    """

    def __init__(self, seed: int = 0):
        self._entropy = int(seed)
        self._seq = np.random.SeedSequence(self._entropy)
        self._local = threading.local()
        self._lock = threading.Lock()

    def _gen(self) -> np.random.Generator:
        token = getattr(_ITEM_SEED, "token", None)
        if token is not None:
            if getattr(self._local, "token", None) != token:
                self._local.item_gen = np.random.default_rng(
                    np.random.SeedSequence((self._entropy,) + tuple(token))
                )
                self._local.token = token
            return self._local.item_gen
        gen = getattr(self._local, "gen", None)
        if gen is None:
            with self._lock:  # SeedSequence.spawn mutates internal state
                child = self._seq.spawn(1)[0]
            gen = np.random.default_rng(child)
            self._local.gen = gen
        return gen

    def integers(self, *args, **kwargs):
        return self._gen().integers(*args, **kwargs)

    def random(self, *args, **kwargs):
        return self._gen().random(*args, **kwargs)

    def normal(self, *args, **kwargs):
        return self._gen().normal(*args, **kwargs)

    def permutation(self, *args, **kwargs):
        return self._gen().permutation(*args, **kwargs)


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class Resize:
    """Resize to ``(size, size)`` (PIL bilinear), matching
    ``transforms.Resize((s, s))`` (``resnet50…py:528``)."""

    def __init__(self, size: int):
        self.size = size

    def __call__(self, img):
        from PIL import Image

        return img.resize((self.size, self.size), Image.BILINEAR)


class RandomCrop:
    def __init__(self, size: int, rng: np.random.Generator | None = None):
        self.size = size
        self.rng = rng or np.random.default_rng()

    def __call__(self, img):
        w, h = img.size
        if (w, h) == (self.size, self.size):
            return img
        left = int(self.rng.integers(0, w - self.size + 1))
        top = int(self.rng.integers(0, h - self.size + 1))
        return img.crop((left, top, left + self.size, top + self.size))


class RandomHorizontalFlip:
    def __init__(self, p: float = 0.5, rng: np.random.Generator | None = None):
        self.p = p
        self.rng = rng or np.random.default_rng()

    def __call__(self, img):
        from PIL import Image

        if self.rng.random() < self.p:
            return img.transpose(Image.FLIP_LEFT_RIGHT)
        return img


class ToArray:
    """PIL (or numpy) → HWC float32 in [0, 1] — torch ``ToTensor`` minus
    the NCHW permute (TPU wants channels-last)."""

    def __call__(self, img) -> np.ndarray:
        a = np.asarray(img, dtype=np.float32)
        if a.ndim == 2:
            a = a[:, :, None]
        if a.max() > 1.5:  # uint8-ranged input
            a = a / 255.0
        return a


class Normalize:
    def __init__(self, mean: Sequence[float], std: Sequence[float]):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)

    def __call__(self, a: np.ndarray) -> np.ndarray:
        return (a - self.mean) / self.std


def random_affine(
    a: np.ndarray, sigma: float = 0.1, rng: np.random.Generator | None = None
) -> np.ndarray:
    """The reference's ``_random_affine_augmentation`` on HWC arrays
    (``resnet50…py:481-487``): identity 2x3 matrix with N(0, sigma)
    perturbations, zero translation."""
    rng = rng or np.random.default_rng()
    m = np.float32(
        [
            [1 + rng.normal(0, sigma), rng.normal(0, sigma), 0],
            [rng.normal(0, sigma), 1 + rng.normal(0, sigma), 0],
        ]
    )
    h, w = a.shape[:2]
    if _HAS_CV2:
        out = cv2.warpAffine(a, m, (w, h))
        if out.ndim == 2:
            out = out[:, :, None]
        return out.astype(np.float32)
    # scipy fallback: affine_transform uses inverse coords, x/y swapped.
    from scipy import ndimage

    full = np.eye(3, dtype=np.float32)
    full[:2] = m[[1, 0]][:, [1, 0, 2]]  # swap x/y convention
    inv = np.linalg.inv(full)
    out = np.stack(
        [
            ndimage.affine_transform(
                a[..., c], inv[:2, :2], offset=inv[:2, 2], order=1
            )
            for c in range(a.shape[-1])
        ],
        axis=-1,
    )
    return out.astype(np.float32)


def gaussian_blur(a: np.ndarray, sigma: float = 0.1) -> np.ndarray:
    """The reference's ``_gaussian_blur`` (``resnet50…py:489-492``) —
    ``ksize = int(sigma + 0.5) * 8 + 1``, which is 1 at the default sigma,
    i.e. deliberately near-no-op; replicated, not 'fixed' (SURVEY §7
    quirks)."""
    ksize = int(sigma + 0.5) * 8 + 1
    if ksize <= 1:
        return a
    if _HAS_CV2:
        out = cv2.GaussianBlur(a, (ksize, ksize), sigma)
        if out.ndim == 2:
            out = out[:, :, None]
        return out.astype(np.float32)
    from scipy import ndimage

    out = np.stack(
        [ndimage.gaussian_filter(a[..., c], sigma) for c in range(a.shape[-1])],
        axis=-1,
    )
    return out.astype(np.float32)
