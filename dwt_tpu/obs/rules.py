"""Declarative SLO / alert rules over the live metrics registry.

A rules file is JSON — a list of rule objects (or ``{"rules": [...]}``)::

    [
      {"name": "steps_stalled", "metric": "dwt_train_steps_per_s",
       "op": "<", "threshold": 0.5, "for_s": 30, "severity": "critical"},
      {"name": "ckpt_failing",
       "metric": "dwt_ckpt_save_failures_total",
       "op": ">", "threshold": 0, "severity": "warning"},
      {"name": "serve_shedding",
       "metric": "dwt_serve_requests_total", "labels": {"status": "shed"},
       "op": ">", "threshold": 100, "for_s": 10}
    ]

Semantics (the classic alerting model, fake-clock testable):

* a rule's condition is ``value <op> threshold`` per matching series
  (``labels`` is a subset filter over the series' label set; each
  matching series is tracked independently);
* ``for_s`` is the hysteresis: the condition must hold CONTINUOUSLY for
  that long before the alert fires (a single bad sample does not page);
  once firing, the first healthy evaluation clears it;
* an absent metric makes the rule inert (the subsystem feeding it may
  not be active in this run) — absence is not an alert.

:class:`AlertEngine` samples the registry at step-boundary/heartbeat
cadence (throttled internally), returns fire/clear transitions for the
caller to emit as ``alert`` JSONL records on the existing metric
stream, and exports the firing set as the ``dwt_alerts_firing`` gauge —
so a scraper sees machine-evaluated SLO state next to the raw series.

The fleet's :class:`~dwt_tpu.fleet.canary.PostSwapMonitor` consumes the
same :class:`AlertRule` shape against its per-version access-window
stats (plain value dicts, not the registry) via :func:`rule_fires`;
there, ``baseline_factor`` may replace ``threshold`` — the effective
threshold becomes ``factor × the pre-swap baseline`` armed at swap time.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import operator
import time
from typing import Callable, Dict, List, Mapping, Optional, Sequence, Tuple

from dwt_tpu.obs.registry import MetricsRegistry, get_registry

log = logging.getLogger(__name__)

__all__ = [
    "AlertRule",
    "AlertEvent",
    "AlertEngine",
    "load_rules",
    "parse_rules",
    "rule_fires",
]

_OPS: Dict[str, Callable[[float, float], bool]] = {
    ">": operator.gt,
    ">=": operator.ge,
    "<": operator.lt,
    "<=": operator.le,
    "==": operator.eq,
    "!=": operator.ne,
}

_SEVERITIES = ("info", "warning", "critical")

_RULE_KEYS = {
    "name", "metric", "op", "threshold", "for_s", "severity", "labels",
    "baseline_factor",
}


@dataclasses.dataclass(frozen=True)
class AlertRule:
    """One declarative SLO condition (see module doc)."""

    name: str
    metric: str
    op: str
    threshold: Optional[float] = None
    for_s: float = 0.0
    severity: str = "warning"
    labels: Optional[Tuple[Tuple[str, str], ...]] = None
    # PostSwapMonitor only: threshold = baseline_factor x armed baseline.
    baseline_factor: Optional[float] = None

    def matches(self, series_labels: Mapping[str, str]) -> bool:
        if not self.labels:
            return True
        return all(
            series_labels.get(k) == v for k, v in self.labels
        )

    def condition(self, value: float,
                  threshold: Optional[float] = None) -> bool:
        t = self.threshold if threshold is None else threshold
        if t is None:
            return False
        return _OPS[self.op](float(value), float(t))

    def describe(self, value: float,
                 threshold: Optional[float] = None) -> str:
        t = self.threshold if threshold is None else threshold
        return f"{self.metric} {value:g} {self.op} {t:g}"


def parse_rules(spec) -> List[AlertRule]:
    """Validate a decoded rules document (strict: unknown keys, bad
    ops/severities, missing fields all raise — a typo'd rule silently
    never firing is the failure mode this engine exists to remove)."""
    if isinstance(spec, dict):
        if set(spec.keys()) != {"rules"}:
            raise ValueError(
                f"rules document must be a list or {{'rules': [...]}}; "
                f"got keys {sorted(spec.keys())}"
            )
        spec = spec["rules"]
    if not isinstance(spec, list):
        raise ValueError(f"rules document must be a list, got {type(spec)}")
    rules: List[AlertRule] = []
    seen = set()
    for i, r in enumerate(spec):
        if not isinstance(r, dict):
            raise ValueError(f"rule #{i} is not an object: {r!r}")
        unknown = set(r) - _RULE_KEYS
        if unknown:
            raise ValueError(f"rule #{i}: unknown keys {sorted(unknown)}")
        for key in ("name", "metric", "op"):
            if key not in r:
                raise ValueError(f"rule #{i}: missing required {key!r}")
        if r["op"] not in _OPS:
            raise ValueError(
                f"rule {r['name']!r}: unknown op {r['op']!r} "
                f"(valid: {sorted(_OPS)})"
            )
        severity = r.get("severity", "warning")
        if severity not in _SEVERITIES:
            raise ValueError(
                f"rule {r['name']!r}: unknown severity {severity!r} "
                f"(valid: {_SEVERITIES})"
            )
        has_thr = r.get("threshold") is not None
        has_factor = r.get("baseline_factor") is not None
        if has_thr == has_factor:
            raise ValueError(
                f"rule {r['name']!r}: exactly one of threshold / "
                "baseline_factor is required"
            )
        if r["name"] in seen:
            raise ValueError(f"duplicate rule name {r['name']!r}")
        seen.add(r["name"])
        labels = r.get("labels")
        if labels is not None:
            if not isinstance(labels, dict):
                raise ValueError(
                    f"rule {r['name']!r}: labels must be an object"
                )
            labels = tuple(sorted(
                (str(k), str(v)) for k, v in labels.items()
            ))
        rules.append(AlertRule(
            name=str(r["name"]),
            metric=str(r["metric"]),
            op=str(r["op"]),
            threshold=(
                float(r["threshold"]) if has_thr else None
            ),
            for_s=float(r.get("for_s", 0.0)),
            severity=severity,
            labels=labels,
            baseline_factor=(
                float(r["baseline_factor"]) if has_factor else None
            ),
        ))
    return rules


def load_rules(path: str) -> List[AlertRule]:
    with open(path) as f:
        try:
            spec = json.load(f)
        except json.JSONDecodeError as e:
            raise ValueError(f"{path}: not valid JSON: {e}") from None
    return parse_rules(spec)


def rule_fires(rule: AlertRule, values: Mapping[str, float],
               baselines: Optional[Mapping[str, float]] = None,
               ) -> Optional[str]:
    """Evaluate one rule against a plain values dict (the
    PostSwapMonitor path: per-version access-window stats).  Returns the
    firing description, or None (condition false / metric absent /
    baseline required but unknown).  No hysteresis here — the monitor's
    window size IS its hysteresis."""
    value = values.get(rule.metric)
    if value is None:
        return None
    threshold = rule.threshold
    if rule.baseline_factor is not None:
        base = (baselines or {}).get(rule.metric)
        if base is None:
            return None
        threshold = rule.baseline_factor * float(base)
        if rule.condition(value, threshold):
            return (
                f"{rule.metric} {float(value):g} {rule.op} "
                f"{rule.baseline_factor:g}x baseline {float(base):g}"
            )
        return None
    if rule.condition(value, threshold):
        return rule.describe(float(value))
    return None


@dataclasses.dataclass(frozen=True)
class AlertEvent:
    """One fire/clear transition (the ``alert`` JSONL record body)."""

    rule: str
    state: str                     # "firing" | "resolved"
    metric: str
    value: float
    threshold: float
    severity: str
    labels: Dict[str, str]
    pending_s: float               # how long the condition had held

    def record_fields(self) -> dict:
        out = {
            "alert": self.rule,
            "state": self.state,
            "metric": self.metric,
            "value": round(self.value, 6),
            "threshold": self.threshold,
            "severity": self.severity,
            "pending_s": round(self.pending_s, 3),
        }
        if self.labels:
            out["labels"] = dict(self.labels)
        return out


class _SeriesState:
    __slots__ = ("pending_since", "firing")

    def __init__(self):
        self.pending_since: Optional[float] = None
        self.firing = False


class AlertEngine:
    """Evaluate rules against a registry; track pending/firing state.

    ``evaluate()`` returns the TRANSITIONS since the last call (fire and
    clear events) — steady states emit nothing, so the metric stream
    carries alert edges, not spam.  ``maybe_evaluate()`` is the
    boundary-cadence form: throttled to ``min_interval_s`` so a
    steps_per_dispatch=1 hot loop pays one clock read per boundary.

    The firing set is exported as the ``dwt_alerts_firing`` gauge
    (labeled ``alertname``/``severity``), rebuilt each evaluation.
    """

    def __init__(self, rules: Sequence[AlertRule],
                 registry: Optional[MetricsRegistry] = None,
                 clock: Callable[[], float] = time.monotonic,
                 min_interval_s: float = 1.0):
        for r in rules:
            if r.baseline_factor is not None:
                raise ValueError(
                    f"rule {r.name!r}: baseline_factor rules are for the "
                    "fleet's post-swap monitor; registry rules need an "
                    "absolute threshold"
                )
        self.rules = list(rules)
        self.registry = registry or get_registry()
        self._clock = clock
        self.min_interval_s = float(min_interval_s)
        self._last_eval: Optional[float] = None
        self._states: Dict[Tuple[str, Tuple], _SeriesState] = {}
        self._warned_histogram: set = set()
        self._firing_gauge = self.registry.gauge(
            "dwt_alerts_firing",
            "alert rules currently firing (1 per alertname/severity)",
            labelnames=("alertname", "severity"),
        )

    def firing(self) -> List[str]:
        """Names of rules with at least one firing series."""
        out = []
        for (name, _key), st in self._states.items():
            if st.firing and name not in out:
                out.append(name)
        return out

    def maybe_evaluate(self) -> List[AlertEvent]:
        now = self._clock()
        if (self._last_eval is not None
                and now - self._last_eval < self.min_interval_s):
            return []
        return self.evaluate(now)

    def evaluate(self, now: Optional[float] = None) -> List[AlertEvent]:
        now = self._clock() if now is None else now
        self._last_eval = now
        events: List[AlertEvent] = []
        seen = set()
        for rule in self.rules:
            fam = self.registry.get(rule.metric)
            if (fam is not None and fam.kind == "histogram"
                    and rule.name not in self._warned_histogram):
                # A histogram's sampled "value" is its observation
                # COUNT, not a latency — a rule written against (say)
                # dwt_ckpt_stall_ms > 500 would fire after the 500th
                # save, not a 500 ms stall.  Warn once instead of
                # letting the misread fire (or never fire) silently.
                self._warned_histogram.add(rule.name)
                log.warning(
                    "alert rule %r: metric %r is a histogram; the rule "
                    "evaluates its observation COUNT, not observed "
                    "values — use a counter/gauge metric if you meant "
                    "a level threshold", rule.name, rule.metric,
                )
            for labels, value in self.registry.samples(rule.metric):
                if not rule.matches(labels):
                    continue
                key = (rule.name, tuple(sorted(labels.items())))
                seen.add(key)
                st = self._states.get(key)
                if st is None:
                    st = self._states[key] = _SeriesState()
                if rule.condition(value):
                    if st.pending_since is None:
                        st.pending_since = now
                    held = now - st.pending_since
                    if not st.firing and held >= rule.for_s:
                        st.firing = True
                        events.append(AlertEvent(
                            rule.name, "firing", rule.metric,
                            float(value), float(rule.threshold),
                            rule.severity, dict(labels), held,
                        ))
                else:
                    if st.firing:
                        events.append(AlertEvent(
                            rule.name, "resolved", rule.metric,
                            float(value), float(rule.threshold),
                            rule.severity, dict(labels),
                            now - (st.pending_since or now),
                        ))
                    st.firing = False
                    st.pending_since = None
        # A series that disappeared (family cleared) resolves silently:
        # drop its state so a re-appearing series starts clean.
        for key in list(self._states):
            if key not in seen:
                del self._states[key]
        # Export the firing set: clear + re-set is O(firing) and keeps
        # stale label combinations out of the scrape.
        severities = {r.name: r.severity for r in self.rules}
        self._firing_gauge.clear()
        for name in self.firing():
            self._firing_gauge.labels(
                alertname=name, severity=severities.get(name, "warning"),
            ).set(1)
        return events
