"""Image transforms: PIL for geometry, numpy/cv2 for the aug math.

The OfficeHome target-view augmentation stack replicated from the
reference (``resnet50_dwt_mec_officehome.py:481-492,535-543``): resize →
random crop → hflip → random affine perturbation → (near-no-op) gaussian
blur → normalize.  All callables are ``img -> img`` where ``img`` is a PIL
Image until ``ToArray`` and an HWC float32 numpy array after.
"""

from __future__ import annotations

from typing import Sequence

import numpy as np

try:
    import cv2

    _HAS_CV2 = True
except ImportError:  # pragma: no cover
    _HAS_CV2 = False


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class Resize:
    """Resize to ``(size, size)`` (PIL bilinear), matching
    ``transforms.Resize((s, s))`` (``resnet50…py:528``)."""

    def __init__(self, size: int):
        self.size = size

    def __call__(self, img):
        from PIL import Image

        return img.resize((self.size, self.size), Image.BILINEAR)


class RandomCrop:
    def __init__(self, size: int, rng: np.random.Generator | None = None):
        self.size = size
        self.rng = rng or np.random.default_rng()

    def __call__(self, img):
        w, h = img.size
        if (w, h) == (self.size, self.size):
            return img
        left = int(self.rng.integers(0, w - self.size + 1))
        top = int(self.rng.integers(0, h - self.size + 1))
        return img.crop((left, top, left + self.size, top + self.size))


class RandomHorizontalFlip:
    def __init__(self, p: float = 0.5, rng: np.random.Generator | None = None):
        self.p = p
        self.rng = rng or np.random.default_rng()

    def __call__(self, img):
        from PIL import Image

        if self.rng.random() < self.p:
            return img.transpose(Image.FLIP_LEFT_RIGHT)
        return img


class ToArray:
    """PIL (or numpy) → HWC float32 in [0, 1] — torch ``ToTensor`` minus
    the NCHW permute (TPU wants channels-last)."""

    def __call__(self, img) -> np.ndarray:
        a = np.asarray(img, dtype=np.float32)
        if a.ndim == 2:
            a = a[:, :, None]
        if a.max() > 1.5:  # uint8-ranged input
            a = a / 255.0
        return a


class Normalize:
    def __init__(self, mean: Sequence[float], std: Sequence[float]):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)

    def __call__(self, a: np.ndarray) -> np.ndarray:
        return (a - self.mean) / self.std


def random_affine(
    a: np.ndarray, sigma: float = 0.1, rng: np.random.Generator | None = None
) -> np.ndarray:
    """The reference's ``_random_affine_augmentation`` on HWC arrays
    (``resnet50…py:481-487``): identity 2x3 matrix with N(0, sigma)
    perturbations, zero translation."""
    rng = rng or np.random.default_rng()
    m = np.float32(
        [
            [1 + rng.normal(0, sigma), rng.normal(0, sigma), 0],
            [rng.normal(0, sigma), 1 + rng.normal(0, sigma), 0],
        ]
    )
    h, w = a.shape[:2]
    if _HAS_CV2:
        out = cv2.warpAffine(a, m, (w, h))
        if out.ndim == 2:
            out = out[:, :, None]
        return out.astype(np.float32)
    # scipy fallback: affine_transform uses inverse coords, x/y swapped.
    from scipy import ndimage

    full = np.eye(3, dtype=np.float32)
    full[:2] = m[[1, 0]][:, [1, 0, 2]]  # swap x/y convention
    inv = np.linalg.inv(full)
    out = np.stack(
        [
            ndimage.affine_transform(
                a[..., c], inv[:2, :2], offset=inv[:2, 2], order=1
            )
            for c in range(a.shape[-1])
        ],
        axis=-1,
    )
    return out.astype(np.float32)


def gaussian_blur(a: np.ndarray, sigma: float = 0.1) -> np.ndarray:
    """The reference's ``_gaussian_blur`` (``resnet50…py:489-492``) —
    ``ksize = int(sigma + 0.5) * 8 + 1``, which is 1 at the default sigma,
    i.e. deliberately near-no-op; replicated, not 'fixed' (SURVEY §7
    quirks)."""
    ksize = int(sigma + 0.5) * 8 + 1
    if ksize <= 1:
        return a
    if _HAS_CV2:
        out = cv2.GaussianBlur(a, (ksize, ksize), sigma)
        if out.ndim == 2:
            out = out[:, :, None]
        return out.astype(np.float32)
    from scipy import ndimage

    out = np.stack(
        [ndimage.gaussian_filter(a[..., c], sigma) for c in range(a.shape[-1])],
        axis=-1,
    )
    return out.astype(np.float32)
