#!/usr/bin/env python
"""Input-pipeline benchmark: decode throughput vs workers + sampler cost.

Two questions, one JSON record (last line on stdout, the repo's bench
contract — ``bench.py --phase data`` embeds this module, and
``tools/obs_diff.py`` extracts every ``data_*``/``sampler_*`` field so
``bench.py --compare`` gates input throughput like any other metric):

1. **images/s vs ``--workers``** — one full epoch of
   ``batch_iterator`` over a synthetic dataset whose per-item cost is a
   ``--decode_ms`` sleep (stands in for PIL/cv2 time, which releases
   the GIL exactly like the real decoders).  Sweeps the
   ordered-reassembly pool (``data/pipeline.OrderedWorkerPool``), so
   the numbers include its window/stall machinery, not an idealized
   pool.  ``data_w<N>_imgs_per_sec`` per arm; the headline metric
   ``data_pipeline_imgs_per_sec`` is the best arm.
2. **seekable-vs-materialized sampler overhead** — the per-epoch index
   cost of the Feistel ``SeekableSampler`` against
   ``np.random.permutation`` at ``--sampler_n`` items
   (``sampler_seekable_ms`` / ``sampler_materialized_ms`` /
   ``sampler_overhead_pct``), plus ``sampler_seek_ms``: mapping only
   the last batch of the epoch — the O(remaining) seek a mid-epoch
   resume actually pays, vs regenerating the whole order.

Usage::

    python tools/data_bench.py
    python tools/data_bench.py --items 4096 --decode_ms 0.5 --workers 0,2,4,8
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:  # runnable as a script from anywhere
    sys.path.insert(0, _REPO)


class _SyntheticDecode:
    """Dataset whose item cost is a deterministic sleep + tiny numpy
    work — the sleep releases the GIL like a real PIL/cv2 decode, so
    worker scaling here predicts real scaling."""

    def __init__(self, n: int, decode_ms: float):
        self.n = int(n)
        self.decode_s = float(decode_ms) / 1e3
        self._img = np.zeros((32, 32, 3), np.float32)

    def __len__(self) -> int:
        return self.n

    def __getitem__(self, i: int):
        if self.decode_s:
            time.sleep(self.decode_s)
        return self._img + np.float32(i), np.int64(i % 10)


def _epoch_imgs_per_sec(ds, batch: int, workers: int) -> float:
    from dwt_tpu.data import batch_iterator

    t0 = time.perf_counter()
    n = 0
    for b in batch_iterator(ds, batch, shuffle=True, seed=1, epoch=0,
                            num_workers=workers, substitute=True):
        n += len(b[1])
    return n / (time.perf_counter() - t0)


def _sampler_costs(n: int, batch: int) -> dict:
    from dwt_tpu.data import SeekableSampler

    t0 = time.perf_counter()
    s = SeekableSampler(n, seed=1, epoch=0)
    s.positions()
    seekable_ms = (time.perf_counter() - t0) * 1e3
    t0 = time.perf_counter()
    np.random.default_rng((1, 0)).permutation(n)
    materialized_ms = (time.perf_counter() - t0) * 1e3
    t0 = time.perf_counter()
    s.positions(n - batch)  # the mid-epoch seek: only the tail is mapped
    seek_ms = (time.perf_counter() - t0) * 1e3
    return {
        "sampler_seekable_ms": round(seekable_ms, 3),
        "sampler_materialized_ms": round(materialized_ms, 3),
        "sampler_overhead_pct": round(
            (seekable_ms - materialized_ms) / materialized_ms * 100.0, 1
        ) if materialized_ms else 0.0,
        "sampler_seek_ms": round(seek_ms, 3),
    }


def run(items: int = 2048, batch: int = 32, workers=(0, 2, 4),
        decode_ms: float = 0.3, sampler_n: int = 1_000_000) -> dict:
    """The full sweep as one bench-contract record."""
    ds = _SyntheticDecode(items, decode_ms)
    record = {
        "metric": "data_pipeline_imgs_per_sec",
        "unit": "imgs/sec",
        "vs_baseline": 1.0,
        "backend": "host",
        "items": int(items),
        "batch": int(batch),
        "decode_ms": float(decode_ms),
    }
    best = 0.0
    for w in workers:
        rate = _epoch_imgs_per_sec(ds, batch, int(w))
        record[f"data_w{int(w)}_imgs_per_sec"] = round(rate, 1)
        best = max(best, rate)
        print(f"data_bench: workers={w}: {rate:.1f} imgs/s",
              file=sys.stderr)
    record["value"] = round(best, 1)
    record.update(_sampler_costs(int(sampler_n), batch))
    record["sampler_n"] = int(sampler_n)
    return record


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="input-pipeline bench: imgs/s vs workers + "
                    "seekable-sampler overhead"
    )
    ap.add_argument("--items", type=int, default=2048)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--workers", default="0,2,4",
                    help="comma-separated worker counts to sweep")
    ap.add_argument("--decode_ms", type=float, default=0.3,
                    help="synthetic per-item decode cost (GIL-releasing)")
    ap.add_argument("--sampler_n", type=int, default=1_000_000,
                    help="domain size for the sampler-cost comparison")
    args = ap.parse_args(argv)
    workers = [int(w) for w in str(args.workers).split(",") if w != ""]
    record = run(args.items, args.batch, workers, args.decode_ms,
                 args.sampler_n)
    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    sys.exit(main())
