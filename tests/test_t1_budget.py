"""tools/t1_budget.py contract: the tier-1 budget gate must trip BEFORE
the suite hits its hard timeout, name the slowest tests, and treat a
summary-less log (a run that died mid-flight) as a failure."""

import json
import os
import sys

sys.path.insert(
    0, os.path.join(os.path.dirname(os.path.dirname(__file__)), "tools")
)

from t1_budget import main, parse_log  # noqa: E402

_LOG_OK = """\
........ [100%]
============================= slowest 25 durations =============================
101.50s call     tests/test_resilience.py::test_sigterm_saves_final
44.81s call     tests/test_chaos.py::test_chaos_smoke
0.30s setup    tests/test_nn.py::test_lenet
=========== 207 passed, 2 skipped in 600.00s (0:10:00) ===========
"""

# The tier-1 recipe runs ``pytest -q``: same summary, no ==== rails.
_LOG_OK_QUIET = """\
........ [100%]
============================= slowest 25 durations =============================
44.81s call     tests/test_chaos.py::test_chaos_smoke
231 passed, 2 skipped, 42 deselected in 684.83s (0:11:24)
"""

_LOG_OVER = _LOG_OK.replace("600.00s (0:10:00)", "800.25s (0:13:20)")


def test_parse_log_extracts_wall_and_durations():
    wall, durations = parse_log(_LOG_OK)
    assert wall == 600.0
    assert durations[0] == (101.5, "call", "tests/test_resilience.py::test_sigterm_saves_final")
    assert len(durations) == 3


def test_quiet_mode_summary_parses(tmp_path, capsys):
    log = tmp_path / "t1.log"
    log.write_text(_LOG_OK_QUIET)
    assert main(["--log", str(log)]) == 0
    record = json.loads(capsys.readouterr().out)
    assert record["wall_s"] == 684.83 and not record["over_threshold"]


def test_inside_budget_exits_zero(tmp_path, capsys):
    log = tmp_path / "t1.log"
    log.write_text(_LOG_OK)
    assert main(["--log", str(log), "--budget", "870", "--frac", "0.8"]) == 0
    record = json.loads(capsys.readouterr().out)
    assert record["wall_s"] == 600.0 and not record["over_threshold"]
    assert record["slowest"][0]["seconds"] == 101.5


def test_over_threshold_exits_nonzero(tmp_path, capsys):
    log = tmp_path / "t1.log"
    log.write_text(_LOG_OVER)
    assert main(["--log", str(log), "--budget", "870", "--frac", "0.8"]) == 3
    record = json.loads(capsys.readouterr().out)
    assert record["over_threshold"] and record["headroom_s"] < 0


def test_dead_run_without_summary_is_a_failure(tmp_path):
    log = tmp_path / "t1.log"
    log.write_text("collected 200 items\n....\nKilled\n")
    assert main(["--log", str(log)]) == 2


def test_missing_log_is_a_failure(tmp_path):
    assert main(["--log", str(tmp_path / "absent.log")]) == 2
