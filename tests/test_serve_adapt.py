"""Guarded online domain adaptation tests (ISSUE-18).

Tier-1 (fast): sanitization units, the drift metric, exact-moment
parity between ragged and padded dispatch (the batcher pad-and-mask
seam), the min-sample gate and momentum clamp under a fake clock, the
rollback → freeze → exponential re-arm ladder, the shifted-domain end
to end (an adapted generation passes the canary and measurably closes
the drift the frozen stats could not — cholesky AND swbn cache-refresh
paths), the canary refusing a degraded adapted candidate, the post-swap
rollback freezing the adapter, the ``--no-adapt``/default inertness
contract, and the composed poison+drift chaos run (sanitized out, zero
degraded swaps, healthy serving, intact access log).

Slow-marked (tools/t1_budget.py discipline): the dwt-serve subprocess
with live adaptation draining cleanly on SIGTERM.
"""

from __future__ import annotations

import io
import json
import os
import signal
import subprocess
import sys
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class _FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


@pytest.fixture(autouse=True)
def _disarm_faults():
    from dwt_tpu.resilience import inject

    yield
    inject.disarm()


@pytest.fixture(scope="module")
def adapt_setup():
    """One LeNet state + engine shared by the adapter tests (compiles
    are the cost; the engine's live state is restored after any test
    that swaps)."""
    import jax
    import jax.numpy as jnp
    import optax

    from dwt_tpu.nn import LeNetDWT
    from dwt_tpu.serve import ServeEngine
    from dwt_tpu.train import create_train_state

    model = LeNetDWT(group_size=4)
    rng = np.random.default_rng(0)
    sample = jnp.asarray(rng.normal(size=(2, 4, 28, 28, 1)), jnp.float32)
    state = create_train_state(
        model, jax.random.key(0), sample, optax.identity()
    )
    engine = ServeEngine(
        model, state.params, state.batch_stats, (28, 28, 1),
        buckets=(1, 4, 8), step=1, digest="seed",
    )
    return model, state, engine


@pytest.fixture()
def restored_engine(adapt_setup):
    """Hand out the shared engine and put its original generation back
    afterwards, whatever the test swapped in."""
    model, state, engine = adapt_setup
    original = engine.state
    yield engine
    engine.swap(original)


def _make_adapter(engine, *, canary=None, monitor=None, access_log=None,
                  clock=None, **kw):
    from dwt_tpu.fleet import DeployController
    from dwt_tpu.serve.adapt import DomainAdapter

    controller = DeployController(
        engine, access_log=access_log, canary=canary, monitor=monitor
    )
    kw.setdefault("adapt_every_s", 1.0)
    kw.setdefault("min_samples", 16)
    kw.setdefault("collect_batch", 8)
    adapter = DomainAdapter(
        engine, controller, access_log=access_log,
        clock=clock or time.monotonic, **kw,
    )
    return adapter, controller


# ----------------------------------------------------------- sanitization

def test_sanitize_rows_rejects_nonfinite_and_out_of_band():
    from dwt_tpu.serve.adapt import sanitize_rows

    x = np.ones((5, 2, 2), np.float32)
    x[1, 0, 0] = np.nan
    x[2, 1, 1] = np.inf
    x[3, 0, 1] = -np.inf
    x[4] = 2e3  # finite but out of band
    keep = sanitize_rows(x, max_abs=1e3)
    assert keep.tolist() == [True, False, False, False, False]
    # The band is inclusive, and an empty keep-set is representable.
    assert sanitize_rows(np.full((1, 4), 1e3, np.float32), 1e3).all()
    assert not sanitize_rows(np.full((2, 4), np.nan, np.float32), 1e3).any()


def test_stats_drift_zero_on_identity_and_scale_free():
    from dwt_tpu.serve.adapt import stats_drift

    live = {"a": np.ones((3, 3)), "b": np.full((2,), 2.0)}
    assert stats_drift(live, live) == 0.0
    moved = {"a": live["a"] * 1.5, "b": live["b"] * 1.5}
    d = stats_drift(live, moved)
    assert d == pytest.approx(0.5, rel=1e-6)
    # Scale-free: the same RELATIVE move measures the same on a model
    # 10x the size.
    big_live = {k: v * 10.0 for k, v in live.items()}
    big_moved = {k: v * 15.0 for k, v in live.items()}
    assert stats_drift(big_live, big_moved) == pytest.approx(d, rel=1e-6)


# --------------------------------------- padded-dispatch moment parity

def test_padded_rows_never_enter_moments_exact_parity(restored_engine):
    """Satellite contract: the window stats advanced from a PADDED
    dispatch (bucket tensor + real_n, the batcher's repeat-last-row
    convention) are bitwise the stats advanced from the ragged real
    rows.  Padding is plausible data — only the real_n slice may
    count."""
    import jax

    engine = restored_engine
    rng = np.random.default_rng(7)
    real = rng.normal(size=(6, 28, 28, 1)).astype(np.float32)
    padded = np.concatenate(
        [real, np.repeat(real[-1:], 2, axis=0)], axis=0
    )  # bucket 8, real_n 6 — the pad rows would pass sanitization

    a_pad, _ = _make_adapter(engine, collect_batch=6)
    a_rag, _ = _make_adapter(engine, collect_batch=6)
    a_pad.offer(padded, real_n=6)
    a_rag.offer(real, real_n=6)
    a_pad._absorb(a_pad._drain_queue())
    a_rag._absorb(a_rag._drain_queue())
    assert a_pad.window_samples == a_rag.window_samples == 6
    for lp, lr in zip(jax.tree.leaves(jax.device_get(a_pad._win_stats)),
                      jax.tree.leaves(jax.device_get(a_rag._win_stats))):
        np.testing.assert_array_equal(lp, lr)


def test_dispatcher_hook_feeds_real_rows_only(restored_engine):
    """ServeClient wiring: a ragged request dispatches as a padded
    bucket, and the attached adapter's queue receives exactly the real
    rows."""
    from dwt_tpu.serve import ServeClient

    engine = restored_engine
    client = ServeClient(engine, max_batch_delay_ms=1.0)
    adapter, _ = _make_adapter(engine)
    client.attach_adapter(adapter)
    try:
        x = np.random.default_rng(3).normal(
            size=(3, 28, 28, 1)
        ).astype(np.float32)  # pads to bucket 4
        client.infer(x)
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            with adapter._qlock:
                n = adapter._queue_samples
            if n >= 3:
                break
            time.sleep(0.01)
        batches = adapter._drain_queue()
        assert sum(b.shape[0] for b in batches) == 3
        np.testing.assert_array_equal(np.concatenate(batches, axis=0), x)
        # Detach restores the bitwise-inert dispatch loop.
        client.attach_adapter(None)
        assert client._dispatcher.batch_hook is None
    finally:
        client.close()


# --------------------------------------------------- gates and fold math

def test_min_sample_gate_keeps_thin_window(restored_engine):
    engine = restored_engine
    clock = _FakeClock()
    log_buf = io.StringIO()
    from dwt_tpu.serve import AccessLog

    alog = AccessLog(stream=log_buf)
    adapter, _ = _make_adapter(
        engine, access_log=alog, clock=clock,
        min_samples=16, collect_batch=8,
    )
    x = np.random.default_rng(1).normal(
        size=(8, 28, 28, 1)
    ).astype(np.float32)
    adapter.offer(x, real_n=8)
    clock.t += 2.0  # past cadence
    assert adapter.step() == "thin_window"
    # The thin window is KEPT (it keeps accumulating), nothing deployed,
    # and the drift gauge still updated (a quiet replica should alarm).
    assert adapter.window_samples == 8
    assert adapter.generation == 0
    assert adapter.last_drift is not None
    events = [json.loads(l) for l in log_buf.getvalue().splitlines()]
    assert [e["kind"] for e in events] == ["adapt_build"]
    assert events[0]["ok"] is False
    assert events[0]["reason"] == "thin_window"
    # More traffic crosses the gate on the next cadence.
    adapter.offer(x, real_n=8)
    clock.t += 2.0
    assert adapter.step() in ("swapped", "refused")


def test_momentum_clamp_bounds_the_fold(restored_engine):
    """momentum=0.9 with max_momentum=0.5 folds at exactly 0.5: the
    swapped generation's stats are live + 0.5*(window − live), leaf for
    leaf (same float64-then-cast arithmetic)."""
    import jax

    engine = restored_engine
    clock = _FakeClock()
    adapter, controller = _make_adapter(
        engine, clock=clock, min_samples=16, collect_batch=8,
        momentum=0.9, max_momentum=0.5,
    )
    assert adapter._effective_momentum() == 0.5
    rng = np.random.default_rng(2)
    x = (rng.normal(size=(16, 28, 28, 1)) * 1.7 + 0.9).astype(np.float32)
    adapter.offer(x, real_n=16)
    adapter._absorb(adapter._drain_queue())
    live_host = jax.device_get(engine.state.batch_stats)
    win_host = jax.device_get(adapter._win_stats)
    clock.t += 2.0
    assert adapter.step() == "swapped"
    expected = jax.tree.map(
        lambda a, b: (
            np.asarray(a) + 0.5 * (np.asarray(b, np.float64)
                                   - np.asarray(a))
        ).astype(np.asarray(a).dtype),
        live_host, win_host,
    )
    got = jax.device_get(engine.state.batch_stats)
    for e, g in zip(jax.tree.leaves(expected), jax.tree.leaves(got)):
        np.testing.assert_array_equal(e, g)
    assert adapter.generation == 1 and controller.swap_count == 1
    assert adapter.window_samples == 0  # the folded window is spent


def test_rollback_freeze_doubles_and_rearms():
    """The freeze ladder under a fake clock: base, 2x, 4x per
    consecutive rollback, capped at max doublings; a surviving adapted
    generation resets the counter; the window that built the bad
    generation is dropped."""

    class _StubEngine:
        pass

    class _StubController:
        def add_verdict_listener(self, fn):
            pass

    from dwt_tpu.serve.adapt import DomainAdapter
    from dwt_tpu.serve.engine import Version

    clock = _FakeClock()
    adapter = DomainAdapter.__new__(DomainAdapter)  # skip engine wiring
    # Only the guard state matters for this unit.
    adapter._clock = clock
    adapter.freeze_base_s = 10.0
    adapter.max_freeze_doublings = 2
    adapter.alert_engine = None
    adapter._frozen_until = 0.0
    adapter._freeze_reason = None
    adapter._consecutive_rollbacks = 0
    adapter._win_stats = object()
    adapter._win_samples = 5
    adapter._pending_rows = [np.zeros((1, 2))]

    class _Counter:
        def labels(self, **kw):
            return self

        def inc(self, *a):
            pass

    adapter._m_generations = _Counter()
    v = Version(1, "x")

    adapter._on_verdict("reload", v, "rollback: not ours")
    assert adapter.frozen_reason() is None  # checkpoint rollbacks ignored

    adapter._on_verdict("adapt", v, "rollback: p99")
    assert adapter._frozen_until == pytest.approx(10.0)
    assert "rollback backoff" in adapter.frozen_reason()
    assert adapter._win_stats is None and adapter._win_samples == 0
    assert adapter._pending_rows == []

    clock.t = 11.0
    assert adapter.frozen_reason() is None  # re-armed on its own
    adapter._on_verdict("adapt", v, "rollback: again")
    assert adapter._frozen_until == pytest.approx(11.0 + 20.0)
    clock.t = 40.0
    adapter._on_verdict("adapt", v, "rollback: again")
    assert adapter._frozen_until == pytest.approx(40.0 + 40.0)
    clock.t = 90.0
    adapter._on_verdict("adapt", v, "rollback: again")
    assert adapter._frozen_until == pytest.approx(90.0 + 40.0)  # capped

    adapter._on_verdict("adapt", v, "ok")
    assert adapter._consecutive_rollbacks == 0


def test_alert_firing_freezes_folding(restored_engine):
    engine = restored_engine
    clock = _FakeClock()

    class _StubAlerts:
        firing_now = ["serve_p99_slo"]

        def maybe_evaluate(self):
            pass

        def firing(self):
            return self.firing_now

    alerts = _StubAlerts()
    adapter, _ = _make_adapter(
        engine, clock=clock, min_samples=8, collect_batch=8,
        alert_engine=alerts,
    )
    x = np.random.default_rng(4).normal(
        size=(8, 28, 28, 1)
    ).astype(np.float32)
    adapter.offer(x, real_n=8)
    clock.t += 2.0
    assert adapter.step() is None  # frozen: fold never attempted
    assert "alert firing" in adapter.frozen_reason()
    assert adapter.fold_attempts == 0 and adapter.generation == 0
    # The alert clears; the next cadence folds.
    alerts.firing_now = []
    clock.t += 2.0
    assert adapter.step() in ("swapped", "refused")


# ------------------------------------------------- shifted-domain e2e

@pytest.mark.parametrize("whitener", ["cholesky", "swbn"])
def test_adapted_generation_beats_frozen_stats(whitener, tmp_path):
    """Acceptance: under a shifted input domain, one canary-accepted
    adapted generation measurably closes the gap the frozen stats
    cannot — the drift of the NEXT traffic window against the adapted
    stats is far below the drift against the frozen stats.  Covers both
    the factorizing (cholesky) and the tracked-matrix (swbn) whiten
    cache refresh paths, and the lifecycle events on the JSONL
    stream."""
    import jax
    import jax.numpy as jnp
    import optax

    from dwt_tpu.fleet import CanaryGate
    from dwt_tpu.nn import LeNetDWT
    from dwt_tpu.serve import AccessLog, ServeEngine
    from dwt_tpu.train import create_train_state

    # momentum=0.6 (weight of the NEW observation) lets a 4-batch window
    # track the traffic moments closely, so ONE fold shows up clearly in
    # the drift metric; the production default (0.1) converges the same
    # way, just over more cadences.
    model = LeNetDWT(group_size=4, whitener=whitener, momentum=0.6)
    rng = np.random.default_rng(0)
    sample = jnp.asarray(rng.normal(size=(2, 4, 28, 28, 1)), jnp.float32)
    state = create_train_state(
        model, jax.random.key(0), sample, optax.identity()
    )
    engine = ServeEngine(
        model, state.params, state.batch_stats, (28, 28, 1), buckets=(8,),
        step=1, digest="seed",
    )
    canary_x = rng.normal(size=(8, 28, 28, 1)).astype(np.float32)
    log_buf = io.StringIO()
    alog = AccessLog(stream=log_buf)
    clock = _FakeClock()
    from dwt_tpu.fleet import DeployController
    from dwt_tpu.serve.adapt import DomainAdapter

    controller = DeployController(
        engine, access_log=alog, canary=CanaryGate(engine, canary_x)
    )
    adapter = DomainAdapter(
        engine, controller, access_log=alog, adapt_every_s=1.0,
        min_samples=32, collect_batch=8, momentum=0.5, clock=clock,
    )

    def shifted(n, seed):
        r = np.random.default_rng(seed)
        return (r.normal(size=(n, 28, 28, 1)) * 1.6 + 0.8).astype(
            np.float32
        )

    v0 = engine.version.label
    cache0 = engine.state.cache
    adapter.offer(shifted(64, 1), real_n=64)
    clock.t += 2.0
    assert adapter.step() == "swapped"
    drift_frozen = adapter.last_drift  # traffic vs the FROZEN stats
    assert drift_frozen > 0
    assert adapter.generation == 1
    assert engine.version.label != v0
    # The whiten cache was refactorized for the adapted stats: same
    # structure, different leaves.
    changed = any(
        not np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree.leaves(jax.device_get(cache0)),
                        jax.tree.leaves(jax.device_get(engine.state.cache)))
    )
    assert changed
    # Serving the shifted domain on the adapted generation stays finite.
    assert np.isfinite(engine.infer(shifted(8, 9))).all()

    # The SAME traffic distribution measured against the adapted stats:
    # each fold closes the gap (deeper layers chase the earlier layers'
    # new whitening, so convergence takes a few cadences — the drift
    # must fall monotonically and substantially).
    drifts = [drift_frozen]
    for seed in (2, 3):
        adapter.offer(shifted(64, seed), real_n=64)
        clock.t += 2.0
        assert adapter.step() in ("swapped", "refused")
        drifts.append(adapter.last_drift)
    assert drifts[1] < drifts[0] and drifts[2] < drifts[1]
    drift_adapted = drifts[-1]
    assert drift_adapted < 0.7 * drift_frozen

    kinds = [json.loads(l)["kind"] for l in log_buf.getvalue().splitlines()]
    assert kinds[:3] == ["adapt_build", "adapt_canary", "adapt_swap"]
    swap_ev = [json.loads(l) for l in log_buf.getvalue().splitlines()
               if json.loads(l)["kind"] == "adapt_swap"][0]
    assert swap_ev["from_version"] == v0

    # /stats adaptation fields ride the client surface.
    from dwt_tpu.serve import ServeClient

    client = ServeClient(engine, max_batch_delay_ms=1.0, access_log=alog)
    client.attach_adapter(adapter)
    try:
        s = client.stats()["adaptation"]
        assert s["generation"] == adapter.generation
        assert s["frozen"] is False
        assert s["domain_shift"] == pytest.approx(drift_adapted, abs=1e-6)
    finally:
        client.close()


def test_canary_refuses_degraded_adapted_candidate(restored_engine):
    """A window that would wreck fixture accuracy never goes live: the
    gate's verdict is counted/logged as refused and the live generation
    does not move."""
    import jax

    from dwt_tpu.fleet import CanaryGate

    engine = restored_engine
    rng = np.random.default_rng(5)
    x = rng.normal(size=(8, 28, 28, 1)).astype(np.float32)
    y = np.argmax(engine.infer(x), axis=-1)  # live accuracy 100%
    clock = _FakeClock()
    adapter, controller = _make_adapter(
        engine, canary=CanaryGate(engine, x, y, max_regress_pp=5.0),
        clock=clock, min_samples=16, collect_batch=8, momentum=0.5,
        max_momentum=1.0,
    )
    v0 = engine.version.label
    # Degraded-but-finite window stats: every float moment leaf shoved
    # far off the data manifold (integer leaves — the BN sample count —
    # keep their dtype and value; the fold must preserve leaf dtypes for
    # the compiled executables to accept the candidate at all).
    live_host = jax.device_get(engine.state.batch_stats)
    degraded = jax.tree.map(
        lambda a: (
            (np.asarray(a) + 1e4).astype(np.asarray(a).dtype)
            if np.issubdtype(np.asarray(a).dtype, np.floating)
            else np.asarray(a)
        ),
        live_host,
    )
    adapter._win_stats = degraded
    adapter._win_samples = 64
    verdict = adapter.try_fold()
    assert verdict == "refused"
    assert engine.version.label == v0
    assert adapter.generation == 0 and controller.swap_count == 0
    # A refusal is not a rollback: nothing freezes, the next window may
    # try again immediately.
    assert adapter.frozen_reason() is None


def test_post_swap_rollback_freezes_then_rearms(restored_engine):
    """The full consequence path: an adapted generation swaps in, the
    post-swap monitor sees errors, the controller rolls back to the
    pre-adaptation state, the adapter freezes, and the freeze expires on
    its own."""
    from dwt_tpu.fleet import PostSwapMonitor
    from dwt_tpu.serve import AccessLog

    engine = restored_engine
    log_buf = io.StringIO()
    alog = AccessLog(stream=log_buf)
    clock = _FakeClock()
    monitor = PostSwapMonitor(
        alog, error_rate_threshold=0.2, min_requests=8,
        decide_after_s=1000.0, clock=clock,
    )
    adapter, controller = _make_adapter(
        engine, monitor=monitor, access_log=alog, clock=clock,
        min_samples=16, collect_batch=8, freeze_base_s=10.0,
    )
    v0 = engine.version.label
    x = np.random.default_rng(6).normal(
        size=(16, 28, 28, 1)
    ).astype(np.float32) * 1.5
    adapter.offer(x, real_n=16)
    clock.t += 2.0
    assert adapter.step() == "swapped"
    v1 = engine.version.label
    assert v1 != v0 and monitor.armed and monitor.armed_origin == "adapt"

    # The adapted generation serves nothing but errors.
    for _ in range(8):
        alog.record("error", 1, version=v1, error="boom")
    t_rollback = clock.t
    assert adapter.step() is None  # poll performed the rollback
    assert engine.version.label == v0
    assert controller.rollback_count == 1
    assert adapter._consecutive_rollbacks == 1
    reason = adapter.frozen_reason()
    assert reason is not None and "rollback backoff" in reason
    kinds = [json.loads(l)["kind"] for l in log_buf.getvalue().splitlines()
             if json.loads(l)["kind"] != "access"]
    assert "adapt_rollback" in kinds
    # Frozen: the next cadence does not fold even with a fat window.
    adapter.offer(x, real_n=16)
    clock.t += 2.0
    assert adapter.step() is None
    assert adapter.generation == 1  # unchanged
    # The freeze expires; adaptation re-arms by itself.
    clock.t = t_rollback + 11.0 + 2.0
    assert adapter.frozen_reason() is None


# --------------------------------------------------------- inertness

def test_no_adapt_default_is_inert(restored_engine):
    """The kill switch and the default: adapt_enabled is False for the
    stock parser, for --adapt_every 0, and for --no-adapt whatever the
    cadence says; an unattached client's dispatch loop carries no hook
    and /stats carries no adaptation block."""
    from dwt_tpu.serve import ServeClient
    from dwt_tpu.serve.server import adapt_enabled, build_parser

    p = build_parser()
    assert not adapt_enabled(p.parse_args([]))
    assert adapt_enabled(p.parse_args(["--adapt_every", "5"]))
    assert not adapt_enabled(
        p.parse_args(["--adapt_every", "5", "--no-adapt"])
    )
    assert not adapt_enabled(
        p.parse_args(["--adapt_every", "5", "--no_adapt"])
    )

    client = ServeClient(restored_engine, max_batch_delay_ms=1.0)
    try:
        assert client._dispatcher.batch_hook is None
        assert "adaptation" not in client.stats()
    finally:
        client.close()


# ------------------------------------------------------------- chaos

def test_chaos_poison_and_drift_composed(restored_engine):
    """One composed DWT_FAULT_PLAN drives drifted traffic with poisoned
    requests riding it through the real client + adapter: every
    poisoned row is sanitized out of the accumulator, no adapted
    generation is rolled back (zero degraded swaps), serving stays
    healthy, and the access log is intact JSONL."""
    from dwt_tpu.fleet import CanaryGate
    from dwt_tpu.resilience import inject
    from dwt_tpu.serve import AccessLog, ServeClient

    engine = restored_engine
    inject.arm(inject.FaultPlan.from_spec({
        "serve_poison_requests": [3, 6, 9, 12],
        "serve_drift_shift": {"at_request": 0, "offset": 0.7,
                              "scale": 1.4},
    }))
    log_buf = io.StringIO()
    alog = AccessLog(stream=log_buf)
    clock = _FakeClock()
    canary_x = np.random.default_rng(8).normal(
        size=(8, 28, 28, 1)
    ).astype(np.float32)
    adapter, controller = _make_adapter(
        engine, canary=CanaryGate(engine, canary_x), access_log=alog,
        clock=clock, min_samples=16, collect_batch=8,
    )
    client = ServeClient(engine, max_batch_delay_ms=1.0, access_log=alog)
    client.attach_adapter(adapter)
    try:
        base = np.random.default_rng(9).normal(
            size=(1, 28, 28, 1)
        ).astype(np.float32)
        served = 0
        for i in range(24):
            xi = inject.maybe_shift_request(i, base)
            xi = inject.maybe_poison_request(i, xi)
            out = client.infer(xi)
            assert out.shape[0] == 1
            served += 1
            if i % 8 == 7:  # fold mid-traffic, like the cadence thread
                clock.t += 2.0
                adapter.step()
        clock.t += 2.0
        adapter.step()
    finally:
        client.close()
    # Every poisoned request was served (a bad payload 500s itself at
    # worst — here it serves; it NEVER reaches the stats)...
    assert served == 24
    # ...and every poisoned row was dropped at the sanitizer.
    assert adapter.dropped_rows == 4
    # Zero degraded swaps: whatever adapted, nothing rolled back.
    assert controller.rollback_count == 0
    assert adapter._consecutive_rollbacks == 0
    # The drifted-but-clean traffic did adapt.
    assert adapter.fold_attempts >= 1
    # The access log is intact JSONL, access + adapt lifecycle only.
    kinds = set()
    for line in log_buf.getvalue().splitlines():
        kinds.add(json.loads(line)["kind"])
    assert "access" in kinds
    assert not any(k.endswith("rollback") for k in kinds)


@pytest.mark.slow
def test_sigterm_drain_with_live_adaptation(tmp_path):
    """dwt-serve with --adapt_every under traffic: serve_ready reports
    the adapter, /stats grows the adaptation block, and SIGTERM drains
    to exit 0 with an intact access log — the adapter thread never
    wedges the drain."""
    access = str(tmp_path / "access.jsonl")
    proc = subprocess.Popen(
        [sys.executable, "-m", "dwt_tpu.serve.server",
         "--init_random", "--model", "lenet", "--buckets", "1,4",
         "--max_batch_delay_ms", "2", "--port", "0",
         "--access_log", access,
         "--adapt_every", "0.3", "--adapt_min_samples", "4",
         "--adapt_batch", "4"],
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    try:
        ready = json.loads(proc.stdout.readline())
        assert ready["kind"] == "serve_ready"
        assert ready["adapt"] is True
        port = ready["port"]
        rng = np.random.default_rng(0)

        import urllib.request

        def _post(x):
            body = json.dumps({"inputs": np.asarray(x).tolist()}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/infer", data=body, method="POST"
            )
            with urllib.request.urlopen(req, timeout=30.0) as resp:
                return resp.status, json.loads(resp.read())

        x = rng.normal(size=(4, 28, 28, 1)).astype(np.float32)
        for _ in range(8):
            status, payload = _post(x)
            assert status == 200 and len(payload["logits"]) == 4
        time.sleep(0.7)  # at least one adaptation cadence under traffic
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/stats", timeout=30.0
        ) as resp:
            stats = json.loads(resp.read())
        assert "adaptation" in stats
        assert stats["adaptation"]["generation"] >= 0

        proc.send_signal(signal.SIGTERM)
        rc = proc.wait(timeout=120)
        assert rc == 0, proc.stderr.read()[-2000:]
        out = proc.stdout.read()
        summary = json.loads(out.strip().splitlines()[-1])
        assert summary["kind"] == "serve_summary"
        for line in open(access).read().splitlines():
            json.loads(line)  # intact JSONL, no torn records
    finally:
        if proc.poll() is None:
            proc.kill()
