"""PyTorch ResNet50-DWT checkpoint → ``ResNetDWT`` variables.

Reproduces the reference's loading pipeline (``resnet50_dwt_mec_officehome
.py:365-378``) for the published ``model_best_gr_4.pth.tar``:

* the archive is ``{'state_dict': {...}}`` with ``module.``-prefixed keys
  (DataParallel artifact) — prefix stripped (``:370-373``);
* whitening sites use ``…bn{k}.wh.running_mean`` (``[1,C,1,1]``) /
  ``…bn{k}.wh.running_variance`` (``[G,g,g]``) with affines at
  ``…bn{k}.gamma/beta`` (``[C,1,1]``) — key scheme at ``:76-90``;
* BN sites use ``…bn{k}.running_mean/running_var`` with affines at
  ``…bn{k}.weight/bias`` (``:93-105``);
* downsample norms live at ``layer{L}.0.downsample_bn.*`` (``:181-213``)
  and the shortcut conv at ``layer{L}.0.downsample.0.weight`` (``:345``);
* ALL domain branches are seeded from the SAME checkpoint stats and
  diverge only through their EMAs (``:74-105``; SURVEY §7 quirks) — here:
  tiled along the leading domain axis;
* ``strict=False`` semantics (``:376``): checkpoint keys with no (or
  shape-incompatible) destination are skipped and reported; model leaves
  the checkpoint doesn't cover keep their fresh init (the reference
  kaiming-re-inits convs for exactly this case, ``:299-304``).

Layout transforms: conv ``OIHW → HWIO``; linear ``[out,in] → [in,out]``.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np


@dataclass
class ConversionReport:
    """What ``strict=False`` would have told you, made explicit."""

    loaded: List[str] = field(default_factory=list)
    skipped_unexpected: List[str] = field(default_factory=list)
    skipped_shape_mismatch: List[Tuple[str, tuple, tuple]] = field(
        default_factory=list
    )

    def summary(self) -> str:
        return (
            f"loaded={len(self.loaded)} "
            f"unexpected={len(self.skipped_unexpected)} "
            f"shape_mismatch={len(self.skipped_shape_mismatch)}"
        )


def load_pytorch_checkpoint(path: str) -> Dict[str, np.ndarray]:
    """Read a ``.pth(.tar)`` archive to numpy, stripping ``module.``."""
    import torch

    archive = torch.load(path, map_location="cpu", weights_only=False)
    state_dict = archive.get("state_dict", archive)
    out = {}
    for key, value in state_dict.items():
        if key.startswith("module."):
            key = key[len("module.") :]
        out[key] = np.asarray(value.detach().cpu().numpy())
    return out


# torch key (post module.-strip) → (collection, flax path, transform tag)
_CONV_RE = re.compile(r"^layer(\d+)\.(\d+)\.conv(\d)\.weight$")
_DOWNSAMPLE_CONV_RE = re.compile(r"^layer(\d+)\.(\d+)\.downsample\.0\.weight$")
_NORM_RE = re.compile(
    r"^(?:layer(\d+)\.(\d+)\.)?(bn\d|downsample_bn)\.(.+)$"
)

# norm-suffix → (collection, leaf path under the dn module, transform)
_NORM_LEAVES = {
    # whitening sites (stem + layer1)
    "wh.running_mean": ("batch_stats", ("whitening", "mean"), "squeeze_tile"),
    "wh.running_variance": ("batch_stats", ("whitening", "cov"), "tile"),
    "gamma": ("params", ("gamma",), "squeeze"),
    "beta": ("params", ("beta",), "squeeze"),
    # BN sites (layers 2-4)
    "running_mean": ("batch_stats", ("bn", "mean"), "tile"),
    "running_var": ("batch_stats", ("bn", "var"), "tile"),
    "weight": ("params", ("gamma",), "squeeze"),
    "bias": ("params", ("beta",), "squeeze"),
    "num_batches_tracked": ("batch_stats", ("bn", "count"), "tile"),
}


def _site_name(bn_name: str) -> str:
    """Reference norm-site name → dwt module name (``bn1``→``dn1``)."""
    if bn_name == "downsample_bn":
        return "downsample_dn"
    return "dn" + bn_name[len("bn") :]


def _resolve(key: str) -> Optional[Tuple[str, Tuple[str, ...], str]]:
    """Map one torch key to (collection, flax path, transform) or None."""
    if key == "conv1.weight":
        return ("params", ("conv1", "kernel"), "conv")
    m = _CONV_RE.match(key)
    if m:
        stage, block, k = m.groups()
        return (
            "params",
            (f"layer{stage}_{block}", f"conv{k}", "kernel"),
            "conv",
        )
    m = _DOWNSAMPLE_CONV_RE.match(key)
    if m:
        stage, block = m.groups()
        return (
            "params",
            (f"layer{stage}_{block}", "downsample_conv", "kernel"),
            "conv",
        )
    if key in ("fc_out.weight", "fc.weight"):
        return ("params", ("fc_out", "kernel"), "linear")
    if key in ("fc_out.bias", "fc.bias"):
        return ("params", ("fc_out", "bias"), "none")
    m = _NORM_RE.match(key)
    if m:
        stage, block, bn_name, leaf = m.groups()
        resolved = _NORM_LEAVES.get(leaf)
        if resolved is None:
            return None
        collection, leaf_path, transform = resolved
        site = _site_name(bn_name)
        if stage is None:
            path = (site,) + leaf_path  # stem: bn1.* → dn1
        else:
            path = (f"layer{stage}_{block}", site) + leaf_path
        return (collection, path, transform)
    return None


def _transform(value: np.ndarray, tag: str, num_domains: int) -> np.ndarray:
    if tag == "conv":  # OIHW → HWIO
        return np.transpose(value, (2, 3, 1, 0))
    if tag == "linear":  # [out, in] → [in, out]
        return np.transpose(value, (1, 0))
    if tag == "squeeze":  # [C,1,1] / [1,C] → [C]
        return value.reshape(-1)
    if tag == "squeeze_tile":  # [1,C,1,1] → [D, C]
        flat = value.reshape(-1)
        return np.broadcast_to(flat, (num_domains,) + flat.shape).copy()
    if tag == "tile":  # stat of any shape → [D, ...]
        return np.broadcast_to(value, (num_domains,) + value.shape).copy()
    return value


def _get(tree: Any, path: Tuple[str, ...]) -> Any:
    node = tree
    for part in path:
        if isinstance(node, dict):
            if part not in node:
                return None
            node = node[part]
        elif hasattr(node, "_fields"):  # NamedTuple stat containers
            if part not in node._fields:
                return None
            node = getattr(node, part)
        else:
            return None
    return node


def _set(tree: Any, path: Tuple[str, ...], value: Any) -> Any:
    """Functional set: returns a copy of ``tree`` with ``path`` replaced."""
    part, rest = path[0], path[1:]
    if isinstance(tree, dict):
        new = dict(tree)
        new[part] = value if not rest else _set(tree[part], rest, value)
        return new
    if hasattr(tree, "_fields"):
        child = getattr(tree, part)
        return tree._replace(
            **{part: value if not rest else _set(child, rest, value)}
        )
    raise TypeError(f"cannot descend into {type(tree)} at {part}")


def convert_resnet_state_dict(
    state_dict: Dict[str, np.ndarray],
    variables: Dict[str, Any],
    num_domains: int = 3,
) -> Tuple[Dict[str, Any], ConversionReport]:
    """Merge a torch DWT state_dict into freshly-initialized variables.

    ``variables`` is ``model.init(...)`` output for a ``ResNetDWT``; returns
    ``(new_variables, report)`` without mutating the input.
    """
    report = ConversionReport()
    new_vars = {k: v for k, v in variables.items()}

    for key, raw in state_dict.items():
        resolved = _resolve(key)
        if resolved is None:
            report.skipped_unexpected.append(key)
            continue
        collection, path, tag = resolved
        target = _get(new_vars.get(collection, {}), path)
        if target is None:
            report.skipped_unexpected.append(key)
            continue
        value = _transform(np.asarray(raw), tag, num_domains)
        if tuple(value.shape) != tuple(target.shape):
            report.skipped_shape_mismatch.append(
                (key, tuple(value.shape), tuple(target.shape))
            )
            continue
        value = jax.numpy.asarray(value, dtype=target.dtype)
        new_vars[collection] = _set(new_vars[collection], path, value)
        report.loaded.append(key)

    return new_vars, report
