"""Async metric harvesting (ISSUE-14): the train-record host fetch off
the hot path.

Acceptance pins, in the project's established sync-count discipline:

* a counting shim on the harvester's ONE blocking rendezvous
  (``AsyncMetricHarvester._wait``) proves per-step host syncs on the
  train hot path drop from 1 (depth 0 — legacy synchronous fetch) to
  amortized 1/depth at ``--harvest_depth 2``;
* the metric JSONL records are byte-identical (modulo wall-clock
  fields) between the two depths, with their ORIGINAL step stamps, and
  boundary drains lose/reorder nothing;
* the harvested divergence guard detects a NaN at step *s* within the
  ring depth and reverts to a strictly pre-NaN snapshot (bounded
  staleness), with stale pre-recovery flags generation-fenced;
* the finite-flag-augmented train step still lowers for TPU off-chip
  (``jax.export`` — the CI seam that caught the PR-4 Mosaic blocker).
"""

import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dwt_tpu.train import harvest
from dwt_tpu.train.harvest import AsyncMetricHarvester

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_WALL_FIELDS = ("elapsed_s", "eval_s", "eval_imgs_per_s", "seconds")


def _count_waits(monkeypatch):
    """Counting shim on the one blocking device→host rendezvous."""
    calls = []
    real = AsyncMetricHarvester._wait

    def counting(self, entries):
        calls.append(len(entries))
        return real(self, entries)

    monkeypatch.setattr(AsyncMetricHarvester, "_wait", counting)
    return calls


# ------------------------------------------------------------ ring policy


def test_ready_entries_drain_opportunistically_without_sync(monkeypatch):
    """Entries whose copies already landed emit at the next put with NO
    blocking rendezvous at all — the common steady state, where the
    device has caught up with a `depth`-old entry by the time the ring
    is consulted again."""
    calls = _count_waits(monkeypatch)
    monkeypatch.setattr(harvest._Entry, "ready", lambda self: True)
    emitted = []
    h = AsyncMetricHarvester(2)
    for s in range(1, 9):
        h.put(s, s, values={"v": jnp.asarray(float(s))},
              emit=lambda vals: emitted.append(float(vals["v"])))
    assert calls == []  # zero blocking syncs
    assert emitted == [float(s) for s in range(1, 9)]  # FIFO, complete
    assert h.pending == 0 and h.puts == 8 and h.emitted == 8


def test_ring_overflow_forces_one_rendezvous_per_depth(monkeypatch):
    """Worst case (device never catches up — ready() always False): the
    ring overflow drains the WHOLE ring in ONE blocking rendezvous, so
    the amortized sync count is bounded by 1/depth per entry, never 1."""
    calls = _count_waits(monkeypatch)
    monkeypatch.setattr(harvest._Entry, "ready", lambda self: False)
    emitted = []
    h = AsyncMetricHarvester(2)
    for s in range(1, 9):
        h.put(s, s, values={"v": jnp.asarray(float(s))},
              emit=lambda vals: emitted.append(float(vals["v"])))
    # Overflow at puts 3 and 6 (ring > depth) — one rendezvous for the
    # 3 pending entries each time; 2 entries still in flight at the end.
    assert calls == [3, 3]
    assert emitted == [float(s) for s in range(1, 7)]
    assert h.pending == 2
    h.drain()  # boundary drain flushes the tail
    assert calls == [3, 3, 2]
    assert emitted == [float(s) for s in range(1, 9)]  # FIFO, complete


def test_depth0_is_synchronous_per_put(monkeypatch):
    calls = _count_waits(monkeypatch)
    emitted = []
    h = AsyncMetricHarvester(0)
    for s in range(1, 5):
        h.put(s, s, values={"v": jnp.asarray(float(s))},
              emit=lambda vals: emitted.append(float(vals["v"])))
    assert calls == [1, 1, 1, 1]  # legacy: one sync per record
    assert emitted == [1.0, 2.0, 3.0, 4.0]
    assert not h.async_mode


def test_boundary_drain_flushes_partial_ring(monkeypatch):
    calls = _count_waits(monkeypatch)
    monkeypatch.setattr(harvest._Entry, "ready", lambda self: False)
    emitted = []
    h = AsyncMetricHarvester(4)
    for s in (1, 2, 3):  # under depth: nothing drained yet
        h.put(s, s, values={"v": jnp.asarray(float(s))},
              emit=lambda vals: emitted.append(float(vals["v"])))
    assert emitted == [] and h.pending == 3
    h.drain()  # the eval/ckpt/preempt/rollback boundary call
    assert emitted == [1.0, 2.0, 3.0]
    assert calls == [3] and h.pending == 0
    h.drain()  # idempotent on empty
    assert calls == [3]


def test_put_without_payload_is_free():
    """A step that logs nothing and feeds no guard books NO ring entry
    (and no copy): the non-cadence fast path."""
    h = AsyncMetricHarvester(2)
    h.put(1, 1)
    assert h.puts == 0 and h.pending == 0


def test_harvest_gauges_and_heartbeat_fields(tmp_path, monkeypatch):
    from dwt_tpu.obs.registry import get_registry
    from dwt_tpu.utils.metrics import HeartbeatEmitter, MetricLogger

    monkeypatch.setattr(harvest._Entry, "ready", lambda self: False)
    h = AsyncMetricHarvester(3)
    for s in range(1, 4):
        h.put(s, s, values={"v": jnp.asarray(1.0)}, emit=lambda vals: None)
    h.drain()
    reg = get_registry()
    assert reg.value("dwt_harvest_ring_depth") == 0  # just drained
    # Drained after the 3rd put: oldest entry (step 1) was 2 steps
    # stale relative to the newest dispatched step.
    assert reg.value("dwt_harvest_lag_steps") == 2
    jsonl = tmp_path / "hb.jsonl"
    logger = MetricLogger(jsonl_path=str(jsonl))
    hb = HeartbeatEmitter(logger, every=1)
    hb.step(1)
    hb.step(2)
    logger.close()
    recs = [json.loads(l) for l in jsonl.read_text().splitlines()]
    beats = [r for r in recs if r["kind"] == "heartbeat"]
    assert beats and beats[-1]["harvest_lag_steps"] == 2
    assert beats[-1]["harvest_ring_depth"] == 0


# ------------------------------------------- CLI-level parity + sync count

_BASE = [
    "--synthetic", "--synthetic_size", "32",
    "--source_batch_size", "8", "--target_batch_size", "8",
    "--test_batch_size", "16", "--group_size", "4",
    "--epochs", "2", "--log_interval", "1",
]


def _run_digits(tmp_path, name, *extra):
    from dwt_tpu.cli.usps_mnist import main

    jsonl = str(tmp_path / f"{name}.jsonl")
    acc = main([*_BASE, "--metrics_jsonl", jsonl, *extra])
    assert 0.0 <= acc <= 100.0
    return [json.loads(l) for l in open(jsonl).read().splitlines()]


def _strip_wall(recs):
    return [
        {k: v for k, v in r.items() if k not in _WALL_FIELDS} for r in recs
    ]


def test_records_byte_identical_and_syncs_amortized(tmp_path, monkeypatch):
    """THE acceptance pin: at --harvest_depth 2 the train hot path's
    host syncs drop from 1/step to 1/depth (counting shim on the one
    rendezvous), and the emitted JSONL records are byte-identical to
    the depth-0 synchronous path's — same kinds, same ORIGINAL step
    stamps, same values, same order — modulo wall-clock fields."""
    calls = _count_waits(monkeypatch)
    recs0 = _run_digits(tmp_path, "d0", "--harvest_depth", "0")
    d0_waits = len(calls)
    calls.clear()
    recs2 = _run_digits(tmp_path, "d2", "--harvest_depth", "2")
    d2_waits = len(calls)
    # 2 epochs x 4 steps, log_interval 1: depth 0 pays one rendezvous
    # per step — exactly 8.  Depth 2 is bounded by one full-ring
    # rendezvous per `depth` puts plus the per-epoch boundary drains
    # (<= 4 here); entries the device finished in time drain
    # opportunistically with no rendezvous at all, so the count can
    # only be lower.
    assert d0_waits == 8
    assert d2_waits <= 4, d2_waits
    assert _strip_wall(recs0) == _strip_wall(recs2)
    train0 = [r["step"] for r in recs0 if r["kind"] == "train"]
    assert train0 == list(range(1, 9))  # exact, ordered, nothing lost


def test_chunked_path_streams_through_ring(tmp_path, monkeypatch):
    calls = _count_waits(monkeypatch)
    recs0 = _run_digits(
        tmp_path, "c0", "--harvest_depth", "0", "--steps_per_dispatch", "2"
    )
    calls.clear()
    recs2 = _run_digits(
        tmp_path, "c2", "--harvest_depth", "2", "--steps_per_dispatch", "2"
    )
    # 4 chunk dispatches (2 epochs x 2 chunks): at most one rendezvous
    # per 2 chunk entries (fewer when copies land in time).
    assert len(calls) <= 2
    assert _strip_wall(recs0) == _strip_wall(recs2)
    assert [r["step"] for r in recs2 if r["kind"] == "train"] == list(
        range(1, 9)
    )


# ----------------------------------------------- guard: bounded staleness


def _guard_state(tag: float):
    import optax

    from dwt_tpu.train.optim import with_lr_backoff
    from dwt_tpu.train.state import TrainState

    tx = with_lr_backoff(optax.sgd(0.1))
    params = {"w": jnp.full((3,), tag)}
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats={},
        opt_state=tx.init(params),
    )


def _tag(state) -> float:
    return float(jax.tree.leaves(state.params)[0][0])


def test_guard_detects_within_depth_and_reverts_pre_nan():
    """Bounded staleness: a NaN flag for step s delivered depth entries
    late still reverts to a snapshot strictly OLDER than s — the
    snapshot refreshed inside the undrained window (potentially
    poisoned, NaN is absorbing) is discarded."""
    from dwt_tpu.resilience.guard import DivergenceGuard

    guard = DivergenceGuard("skip_step", interval=1)
    s0 = _guard_state(0.0)
    guard.prime(s0)
    guard.enable_harvest(2, 0)
    # Boundaries 1 and 2 pass with their flags current.
    for s in (1, 2):
        guard.observe_flags(s, s, np.asarray(True))
        out = guard.check_harvested(_guard_state(float(s)), 1, s)
        assert _tag(out) == float(s)
    # Step 3 goes NaN but its flag is still in flight: boundaries 3 and
    # 4 look clean and refresh snapshots from (poisoned) states.
    out = guard.check_harvested(_guard_state(3.0), 1, 3)
    out = guard.check_harvested(_guard_state(4.0), 1, 4)
    # The flag drains at the step-5 put: detection at boundary 5 =
    # s + 2 = within the ring depth.
    guard.observe_flags(3, 3, np.asarray(False))
    recovered = guard.check_harvested(_guard_state(5.0), 1, 5)
    # Reverted to the step-2 snapshot: the newest strictly pre-NaN one.
    assert _tag(recovered) == 2.0
    assert guard.recoveries == 1


def test_guard_chunked_flags_pick_first_bad_step(tmp_path):
    from dwt_tpu.resilience.guard import DivergenceGuard, RollbackRequest

    guard = DivergenceGuard("rollback", interval=1)
    s0 = _guard_state(0.0)
    guard.prime(s0)
    guard.enable_harvest(2, 0)
    guard.observe_flags(1, 4, np.asarray([True, True, False, False]))
    with pytest.raises(RollbackRequest) as ei:
        guard.check_harvested(_guard_state(4.0), 4, 4)
    assert ei.value.step == 3  # first non-finite inner step, not the hi


def test_halt_path_drains_pending_records(tmp_path):
    """A DivergenceError (halt policy) propagates out of the loop — the
    finally-drain must still flush the ring, so the post-mortem JSONL
    keeps the train records leading into the divergence."""
    from dwt_tpu.cli.usps_mnist import main
    from dwt_tpu.resilience import inject
    from dwt_tpu.resilience.guard import DivergenceError
    from dwt_tpu.resilience.inject import FaultPlan

    inject.arm(FaultPlan(nan_at_step=7))
    jsonl = str(tmp_path / "halt.jsonl")
    try:
        with pytest.raises(DivergenceError):
            main([*_BASE, "--metrics_jsonl", jsonl, "--harvest_depth", "3",
                  "--guard_policy", "halt", "--guard_interval", "1"])
    finally:
        inject.disarm()
    recs = [json.loads(l) for l in open(jsonl).read().splitlines()]
    train_steps = [r["step"] for r in recs if r["kind"] == "train"]
    # Every executed step's record survived the halt — including the
    # ones still in the ring when the guard raised.
    assert train_steps == list(range(1, max(train_steps) + 1))
    assert max(train_steps) >= 7
    assert any(r["kind"] == "divergence" for r in recs)


def test_generation_fence_makes_stale_flags_inert(monkeypatch):
    """After a recovery, flags still in flight belong to the poisoned
    trajectory: bump_generation keeps their RECORDS but must not re-trip
    the guard on the replayed segment."""
    from dwt_tpu.resilience.guard import DivergenceGuard

    monkeypatch.setattr(harvest._Entry, "ready", lambda self: False)
    guard = DivergenceGuard("skip_step", interval=1)
    emitted = []
    h = AsyncMetricHarvester(4, flag_observer=guard.observe_flags)
    guard.prime(_guard_state(0.0))
    guard.enable_harvest(4, 0)
    h.put(1, 1, values={"v": jnp.asarray(1.0)},
          flag=jnp.asarray(False),
          emit=lambda vals: emitted.append(float(vals["v"])))
    h.bump_generation()  # the boundary fenced a recovery
    h.drain()
    assert emitted == [1.0]  # record still narrates the step
    # But the stale verdict never reached the guard:
    out = guard.check_harvested(_guard_state(2.0), 1, 2)
    assert guard.recoveries == 0 and _tag(out) == 2.0


def test_late_draining_strike_during_backoff_still_escalates():
    """Ladder guarantee under harvested lag: a step that RAN while
    backed off must escalate when its bad flag drains, even if the
    scale already recovered in the meantime — otherwise a recurring
    divergence could loop backoff/recover forever and never reach the
    configured policy (the backoff-episode span check)."""
    from dwt_tpu.resilience.guard import DivergenceGuard

    guard = DivergenceGuard("skip_step", interval=1, lr_backoff=0.5,
                            backoff_recovery=1)
    guard.prime(_guard_state(0.0))
    guard.enable_harvest(2, 0)
    # Boundary 1: a drained bad flag engages rung 1.
    guard.observe_flags(1, 1, np.asarray(False))
    s = guard.check_harvested(_guard_state(1.0), 1, 1)
    assert guard.in_backoff and guard.backoffs == 1
    # Step 2 runs BACKED OFF and diverges, but its flag is still in
    # flight; boundary 2 looks clean and the scale recovers.
    s = guard.check_harvested(s, 1, 2)
    assert not guard.in_backoff
    # Step 2's bad flag drains at boundary 3: escalate to skip_step —
    # rung 1 must NOT re-engage for a strike inside the closed episode.
    guard.observe_flags(2, 2, np.asarray(False))
    guard.check_harvested(s, 1, 3)
    assert guard.backoffs == 1  # no second backoff
    assert guard.recoveries == 2  # the skip_step rung fired instead


def test_history_prunes_with_deterministic_floor():
    """The snapshot history stays near the legacy 2 copies when the
    harvester's pending floor advances — only the newest snapshot below
    the floor (the worst-case revert target) plus newer ones are kept,
    and a late bad flag still reverts strictly pre-NaN."""
    from dwt_tpu.resilience.guard import DivergenceGuard

    floor = {"v": None}
    guard = DivergenceGuard("skip_step", interval=1)
    guard.prime(_guard_state(0.0))
    guard.enable_harvest(4, 0, floor_fn=lambda: floor["v"])
    for s in range(1, 10):
        floor["v"] = s - 1 if s > 1 else None
        if s > 1:
            guard.observe_flags(s - 1, s - 1, np.asarray(True))
        guard.check_harvested(_guard_state(float(s)), 1, s)
    assert len(guard._snaps) <= 3  # not the depth+2 = 6 worst case
    guard.observe_flags(9, 9, np.asarray(False))
    out = guard.check_harvested(_guard_state(10.0), 1, 10)
    assert _tag(out) == 8.0  # newest strictly pre-NaN snapshot


def test_pending_floor_tracks_put_control_flow():
    h = AsyncMetricHarvester(2)
    assert h.pending_floor() is None
    for s in (1, 2, 3):
        h.put(s, s, values={"v": jnp.asarray(float(s))},
              emit=lambda vals: None)
    # Last depth=2 puts were steps 2 and 3: nothing older than step 2
    # can still be pending, whatever the local drain timing did.
    assert h.pending_floor() == 2


def test_reset_stamps_clears_floor_for_rollback_rewind():
    """A rollback restore rewinds step numbering; the handlers call
    reset_stamps after their full drain so a stale pre-rollback floor
    cannot make the guard prune the restore-point snapshot the replay
    may still need."""
    h = AsyncMetricHarvester(2)
    for s in (999, 1000):
        h.put(s, s, values={"v": jnp.asarray(float(s))},
              emit=lambda vals: None)
    assert h.pending_floor() == 999
    h.drain()
    h.reset_stamps()
    assert h.pending_floor() is None  # conservative: no pruning
    # Replayed (rewound) puts re-arm the floor in the new numbering.
    for s in (501, 502):
        h.put(s, s, values={"v": jnp.asarray(float(s))},
              emit=lambda vals: None)
    assert h.pending_floor() == 501


def test_mirror_recovery_aligns_with_firing_hosts_history():
    """Multi-host alignment under harvesting: the firing host discards
    every snapshot at/after the bad step; a mirror host (finite local
    flags) receives that bad step on the consensus vector's
    rollback_step slot and must discard the SAME snapshots — both hosts
    revert to the identical (replicated) state, plus the mirror drops
    its detection-boundary refresh the firing host never took."""
    from dwt_tpu.resilience.guard import DivergenceGuard

    def build():
        g = DivergenceGuard("skip_step", interval=1)
        g.prime(_guard_state(0.0))
        g.enable_harvest(2, 0)
        # Both hosts pushed snapshots at boundaries 1 and 2 in lockstep.
        for s in (1, 2):
            g.check_harvested(_guard_state(float(s)), 1, s)
        return g

    firing, mirror = build(), build()
    # NaN at step 2 on the firing host only (host-local fault); its flag
    # drains at boundary 3.  The mirror's check at 3 passes and pushes a
    # snapshot the firing host never takes.
    firing.observe_flags(2, 2, np.asarray(False))
    fired = firing.check_harvested(_guard_state(3.0), 1, 3)
    mirror.check_harvested(_guard_state(3.0), 1, 3)
    assert firing.last_bad_step == 2
    mirrored = mirror.mirror_recovery(
        _guard_state(3.0), 3, bad_step=firing.last_bad_step
    )
    # Both reverted to the step-1 snapshot — strictly pre-NaN, shared.
    assert _tag(fired) == _tag(mirrored) == 1.0


# ----------------------------------------------- off-chip TPU lowering pin


def _export_for_tpu(step, state, batch):
    try:
        from jax import export
    except ImportError as e:  # pragma: no cover - env-dependent
        pytest.skip(f"missing jax.export: {e}")
    exp = export.export(jax.jit(step), platforms=("tpu",))(
        jax.tree.map(
            lambda l: jax.ShapeDtypeStruct(jnp.shape(l),
                                           jnp.asarray(l).dtype),
            state,
        ),
        jax.tree.map(
            lambda l: jax.ShapeDtypeStruct(jnp.shape(l),
                                           jnp.asarray(l).dtype),
            batch,
        ),
    )
    return exp.mlir_module()


def test_finite_flag_digits_step_lowers_for_tpu_offchip():
    """ISSUE-14 satellite: the finite-flag-augmented digits train step
    (flagship 32+32 shapes) passes full TPU lowering off-chip — the same
    jax.export seam that caught the PR-4 Mosaic 2-D-dot blocker."""
    sys.path.insert(0, REPO)
    try:
        from bench import _build_lenet
    finally:
        sys.path.pop(0)
    from dwt_tpu.train import adam_l2, make_digits_train_step
    from dwt_tpu.nn import LeNetDWT

    _, state, b = _build_lenet(32)
    model = LeNetDWT(group_size=4)
    tx = adam_l2(1e-3, 5e-4)
    raw = make_digits_train_step(model, tx, 0.1)
    module = _export_for_tpu(raw, state, b)
    assert "is_finite" in module or "stablehlo" in module


@pytest.mark.slow  # resnet50@224 traces for minutes on CPU
def test_finite_flag_officehome_flagship_step_lowers_for_tpu_offchip():
    sys.path.insert(0, REPO)
    try:
        from bench import _build_resnet50
    finally:
        sys.path.pop(0)
    from dwt_tpu.train import make_officehome_train_step

    model, tx, state, b = _build_resnet50(18, 224, use_pallas=False)
    raw = make_officehome_train_step(model, tx, 0.1)
    module = _export_for_tpu(raw, state, b)
    assert "stablehlo" in module or "module" in module
