"""Hang watchdog: turn a wedged step into a diagnosed, relaunchable exit.

The one failure the rest of the resilience layer cannot see is the one
where nothing happens: a deadlocked collective (one host restarted, the
others blocked in an all-reduce), a wedged TPU runtime, or an I/O mount
that stops answering.  The process is alive, the scheduler is happy, and
the job burns its allocation making zero progress until a human notices.

:class:`HangWatchdog` is a daemon thread fed by step-boundary heartbeats
from the training loops.  When no heartbeat arrives for
``timeout_s`` seconds it (1) dumps ALL thread stacks to
``ckpt_dir/watchdog/stacks-<pid>-<ts>.txt`` — capped at the newest
``keep`` dumps (``--watchdog_keep``), so a relaunch loop (113 → resume →
hang again) cannot fill the disk — (``faulthandler`` — exactly the
evidence a post-mortem needs: *which* collective/syscall every thread is
blocked in), (2) writes one unbuffered line to stderr naming the dump,
and (3) hard-exits with :data:`WATCHDOG_EXIT_CODE` — distinct from both
a clean preemption exit (0) and an ordinary crash (1), so schedulers can
recognize "hang, relaunch me" and the relaunch lands in the existing
newest-valid-checkpoint resume path.

``os._exit`` (not ``sys.exit``) on purpose: the main thread is wedged,
so unwinding it is impossible — raising in a daemon thread would be
silently discarded, and any attempt to run atexit/finally handlers could
block on the very lock that hung the process.

Heartbeats are a single monotonic-clock store (no lock: CPython assigns
floats atomically, and the worst race costs one poll interval of
detection latency), so the hot path pays nothing measurable.  Timeouts
must budget for the slowest legitimate gap between heartbeats — the
first step's jit compile and any boundary eval — which is why the loops
also beat after evals/saves, and why the default is "off" (0) on CPU
test configs.
"""

from __future__ import annotations

import contextlib
import faulthandler
import os
import sys
import threading
import time
from typing import Callable, Optional

# "Hang detected" — distinct from 0 (clean preempt save) and 1 (error),
# outside the shell's 126/127/128+N conventions, documented in README's
# failure-semantics table.  Schedulers treat it as "relaunch to resume".
WATCHDOG_EXIT_CODE = 113


class HangWatchdog:
    """Context manager running the stall detector while a loop trains.

    ``timeout_s <= 0`` disables everything — ``heartbeat()`` stays a
    no-op-cheap call so the loops need no conditionals.  ``_exit`` is
    injectable for unit tests (the default really exits the process).
    """

    # Default stack-dump retention: a relaunch loop (exit 113 → scheduler
    # resume → hang again) writes one dump per attempt, forever — without
    # a cap it fills the checkpoint mount with the evidence of its own
    # failure.  The newest few dumps carry all the diagnostic value.
    DEFAULT_KEEP = 5

    def __init__(
        self,
        timeout_s: float,
        ckpt_dir: Optional[str] = None,
        logger=None,
        keep: int = DEFAULT_KEEP,
        _exit: Callable[[int], None] = os._exit,
    ):
        self.timeout_s = float(timeout_s or 0.0)
        self.enabled = self.timeout_s > 0
        self.keep = max(int(keep), 1)  # the dump being written always stays
        self._ckpt_dir = ckpt_dir
        self._logger = logger  # unused in the handler (see preemption.py);
        # kept for API symmetry with the other resilience context managers.
        self._exit = _exit
        self._beat = time.monotonic()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._suspended = 0
        self.fired = False  # observable by injected-_exit unit tests
        self.stacks_path: Optional[str] = None
        self.spans_path: Optional[str] = None  # flight-recorder dump

    # ------------------------------------------------------------------ API

    def heartbeat(self) -> None:
        """Step-boundary liveness signal (atomic store; safe anywhere)."""
        self._beat = time.monotonic()

    @contextlib.contextmanager
    def suspended(self):
        """Mask the watchdog across a legitimately-unbounded blocking
        section — a SYNCHRONOUS checkpoint save (multi-host downgrade or
        ``--no-async_ckpt``) can run longer than any sane step timeout,
        and killing it mid-write every attempt would livelock the run on
        the same save boundary forever.  The trade is explicit: a save
        hung on dead storage is not caught while masked (its bounded
        I/O retries are the defense there).  Exiting re-heartbeats, so
        the save's duration never counts against the next interval."""
        self._suspended += 1
        try:
            yield
        finally:
            # Heartbeat BEFORE unmasking: the reverse order leaves a
            # window where the poll thread sees _suspended == 0 with a
            # beat predating the whole masked section and fires on a
            # healthy process.
            self.heartbeat()
            self._suspended -= 1

    def __enter__(self) -> "HangWatchdog":
        if self.enabled:
            self._beat = time.monotonic()
            self._thread = threading.Thread(
                target=self._watch, name="dwt-hang-watchdog", daemon=True
            )
            self._thread.start()
        return self

    def __exit__(self, *exc) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None

    # ------------------------------------------------------------- internals

    def _watch(self) -> None:
        # Poll at a quarter of the timeout: detection latency stays under
        # 1.25x the configured timeout without a busy loop.
        poll = max(min(self.timeout_s / 4.0, 1.0), 0.05)
        while not self._stop.wait(poll):
            if self._suspended:
                continue  # inside a masked blocking section (sync save)
            stalled = time.monotonic() - self._beat
            if stalled > self.timeout_s:
                self._fire(stalled)
                return

    def _prune_dumps(self, d: str, keep: int) -> None:
        """Cap ``stacks-*.txt`` files to the newest ``keep`` (oldest
        mtime first out) — relaunch loops must not fill the disk with
        dumps.  The flight-recorder span dumps have the same retention,
        applied inside ``obs.flight_dump`` (every producer — watchdog
        and guard-event paths — goes through it)."""
        try:
            dumps = [
                os.path.join(d, name)
                for name in os.listdir(d)
                if name.startswith("stacks-") and name.endswith(".txt")
            ]
            dumps.sort(key=os.path.getmtime)
            for stale in dumps[: max(len(dumps) - keep, 0)]:
                os.unlink(stale)
        except OSError:
            pass  # retention is best-effort; never blocks the dump/exit

    def _dump_stacks(self, stalled: float) -> Optional[str]:
        if not self._ckpt_dir:
            return None
        try:
            d = os.path.join(self._ckpt_dir, "watchdog")
            os.makedirs(d, exist_ok=True)
            # pid+timestamp name: successive relaunches (fresh pids) AND a
            # recycled pid both get distinct files; retention prunes by
            # age, keeping room for this dump inside the cap.
            self._prune_dumps(d, self.keep - 1)
            path = os.path.join(d, f"stacks-{os.getpid()}-{int(time.time())}.txt")
            with open(path, "w") as f:
                f.write(
                    f"hang watchdog: pid={os.getpid()} "
                    f"stalled={stalled:.1f}s timeout={self.timeout_s:.1f}s "
                    f"exit_code={WATCHDOG_EXIT_CODE}\n"
                    "all-thread stacks at detection time:\n\n"
                )
                f.flush()
                faulthandler.dump_traceback(file=f, all_threads=True)
                f.flush()
                os.fsync(f.fileno())
            return path
        except OSError:
            return None  # a dead ckpt mount must not stop the exit

    def _flight_dump(self, stalled: float) -> Optional[str]:
        """Flight recorder: the stacks say where every thread IS; the
        last seconds of spans say what they had been DOING.  Dumped next
        to the stack file, same retention cap; never blocks the exit.

        The window reaches BACK PAST the stall: by the time the watchdog
        fires, the wedged threads have recorded nothing for ``stalled``
        seconds — a trailing window shorter than that would be empty by
        construction, missing exactly the activity that led into the
        hang."""
        if not self._ckpt_dir:
            return None
        try:
            from dwt_tpu.obs import FLIGHT_WINDOW_S, flight_dump

            d = os.path.join(self._ckpt_dir, "watchdog")
            return flight_dump(
                d, reason=f"watchdog_stall {stalled:.1f}s",
                last_s=stalled + FLIGHT_WINDOW_S,
                keep=self.keep,  # flight_dump prunes spans-*.json itself
            )
        except Exception:  # noqa: BLE001 — nothing may block the exit
            return None

    def _fire(self, stalled: float) -> None:
        self.fired = True
        self.stacks_path = self._dump_stacks(stalled)
        self.spans_path = self._flight_dump(stalled)
        try:
            # Unbuffered, signal-handler-grade write: the process state is
            # unknown (that is the premise), so no logging machinery here.
            os.write(
                2,
                (
                    f"[watchdog] no step-boundary heartbeat for "
                    f"{stalled:.1f}s (timeout {self.timeout_s:.1f}s); "
                    f"stacks: {self.stacks_path or 'unavailable'}; "
                    f"exiting {WATCHDOG_EXIT_CODE} for scheduler relaunch\n"
                ).encode(),
            )
        except OSError:
            pass
        if self.stacks_path is None:
            # No ckpt_dir: at least leave the stacks on stderr.
            try:
                faulthandler.dump_traceback(file=sys.stderr, all_threads=True)
            except Exception:  # noqa: BLE001 — nothing may block the exit
                pass
        self._exit(WATCHDOG_EXIT_CODE)
