"""Jitted train/eval step factories for the two reference experiments.

Each factory closes over a model and optimizer and returns a pure function
``(state, batch) -> (state, metrics)`` suitable for ``jax.jit`` (single
device) or ``shard_map`` over a mesh (``dwt_tpu.parallel``).  Passing
``axis_name`` makes the step all-reduce gradients and metrics across the
mapped axis; the model's norm sites must be built with the same
``axis_name`` so batch moments are pmean'd too (SURVEY §5 distributed note).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax import lax

from dwt_tpu.ops.losses import (
    at_least_f32,
    entropy_loss,
    mec_loss,
    nll_loss,
    softmax_cross_entropy,
)
from dwt_tpu.ops.whitening import AxisName
from dwt_tpu.train.optim import grads_in_param_dtype
from dwt_tpu.train.state import TrainState

Batch = Dict[str, jax.Array]
Metrics = Dict[str, jax.Array]


def _apply_grads(
    state: TrainState,
    tx: optax.GradientTransformation,
    grads: Any,
    batch_stats: Any,
) -> TrainState:
    # bf16 compute: any reduced-precision gradient leaf widens to the
    # param dtype (f32) HERE, before the optimizer's moment EMAs — see
    # optim.grads_in_param_dtype.  Identity under f32 compute.
    grads = grads_in_param_dtype(grads, state.params)
    updates, opt_state = tx.update(grads, state.opt_state, state.params)
    params = optax.apply_updates(state.params, updates)
    return state.replace(
        step=state.step + 1,
        params=params,
        batch_stats=batch_stats,
        opt_state=opt_state,
    )


def _pmean_if(tree: Any, axis_name: Optional[AxisName]) -> Any:
    if axis_name is None:
        return tree
    return lax.pmean(tree, axis_name)


def _mean_grads_if(grads: Any, axis_name: Optional[AxisName]) -> Any:
    """Turn per-replica gradients of a *local-mean* loss into the gradient
    of the global-mean loss.

    Under shard_map with varying-axis tracking (jax >= 0.9), differentiating
    wrt a REPLICATED param tree already inserts the cross-replica psum in
    the transpose (the cotangent of an unvarying input must be unvarying),
    so ``grads`` here is ``Σ_replicas ∂loss_r/∂θ`` — an explicit ``pmean``
    would be an identity on the already-reduced value and leave gradients
    at ``axis_size ×`` the global-batch gradient.  Dividing by the axis
    size yields exactly ``∂((1/R)Σ_r loss_r)/∂θ``, the single-device
    global-batch gradient (SURVEY §4.4 invariant) — verified to float
    tolerance by ``tests/test_parallel.py``.

    Older jax (the 0.4.x line, ``check_rep`` era — no varying-axis
    tracking) does NOT insert that transpose psum: ``grads`` arrive
    per-replica and need an explicit ``pmean`` — which also makes them
    statically-inferable replicated, satisfying ``check_rep`` for the
    replicated ``out_specs``.  ``lax.axis_size`` only exists in the new
    era, so its presence is the capability probe.  Both branches produce
    the identical global-mean gradient; the same parity tests verify
    whichever branch the installed jax takes.
    """
    if axis_name is None:
        return grads
    if hasattr(lax, "axis_size"):  # varying-axis-tracking era: see above
        size = lax.axis_size(axis_name)
        return jax.tree.map(lambda g: g / size, grads)
    return lax.pmean(grads, axis_name)


def make_digits_train_step(
    model,
    tx: optax.GradientTransformation,
    lambda_entropy: float = 0.1,
    axis_name: Optional[AxisName] = None,
) -> Callable[[TrainState, Batch], Tuple[TrainState, Metrics]]:
    """Digits (USPS↔MNIST) step: cls loss on source + λ·entropy on target.

    Reference loop body at ``usps_mnist.py:281-308``: concat halves, one
    forward, ``nll(log_softmax(src), y) + λ·H(tgt)``, Adam step.  Here the
    two domains arrive stacked (``[2, N, 28, 28, 1]``).
    """

    def train_step(state: TrainState, batch: Batch):
        x = jnp.stack([batch["source_x"], batch["target_x"]])

        def loss_fn(params):
            logits, updated = model.apply(
                {"params": params, "batch_stats": state.batch_stats},
                x,
                train=True,
                mutable=["batch_stats"],
            )
            cls = softmax_cross_entropy(logits[0], batch["source_y"])
            ent = lambda_entropy * entropy_loss(logits[1])
            return cls + ent, (updated["batch_stats"], cls, ent)

        (loss, (stats, cls, ent)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params)
        grads = _mean_grads_if(grads, axis_name)
        metrics = _pmean_if(
            {"loss": loss, "cls_loss": cls, "entropy_loss": ent}, axis_name
        )
        # Global grad norm rides along as a device scalar: the divergence
        # guard's finite-check input (and a free training-health metric) —
        # grads can go non-finite a step before the loss does.
        metrics["grad_norm"] = optax.global_norm(grads)
        metrics["finite"] = _finite_flag(metrics)
        return _apply_grads(state, tx, grads, stats), metrics

    return train_step


def _finite_flag(metrics: Metrics) -> jax.Array:
    """Device-side all-finite verdict over loss + grad norm — ONE bool
    scalar computed inside the compiled step, so the harvested guard
    (``--harvest_depth``, ISSUE-14) inspects a single host byte per step
    instead of forcing the whole metrics tree.  Computed after the
    cross-replica reductions, so it is replicated wherever the metrics
    are."""
    return jnp.isfinite(metrics["loss"]) & jnp.isfinite(
        metrics["grad_norm"]
    )


def make_officehome_train_step(
    model,
    tx: optax.GradientTransformation,
    lambda_mec: float = 0.1,
    axis_name: Optional[AxisName] = None,
) -> Callable[[TrainState, Batch], Tuple[TrainState, Metrics]]:
    """OfficeHome step: cls on source + λ·MEC between the two target views.

    Reference loop body at ``resnet50_dwt_mec_officehome.py:400-431``:
    concat thirds (source, target, augmented-target), one forward,
    ``nll + λ·MEC(tgt, tgt_aug)``, SGD step.  Domains arrive stacked
    (``[3, N, H, W, C]``).
    """

    def train_step(state: TrainState, batch: Batch):
        x = jnp.stack(
            [batch["source_x"], batch["target_x"], batch["target_aug_x"]]
        )

        def loss_fn(params):
            logits, updated = model.apply(
                {"params": params, "batch_stats": state.batch_stats},
                x,
                train=True,
                mutable=["batch_stats"],
            )
            cls = softmax_cross_entropy(logits[0], batch["source_y"])
            mec = lambda_mec * mec_loss(logits[1], logits[2])
            return cls + mec, (updated["batch_stats"], cls, mec)

        (loss, (stats, cls, mec)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params)
        grads = _mean_grads_if(grads, axis_name)
        metrics = _pmean_if(
            {"loss": loss, "cls_loss": cls, "mec_loss": mec}, axis_name
        )
        # See make_digits_train_step: the divergence guard's finite-check
        # input, computed on the already-reduced global gradients.
        metrics["grad_norm"] = optax.global_norm(grads)
        metrics["finite"] = _finite_flag(metrics)
        return _apply_grads(state, tx, grads, stats), metrics

    return train_step


def make_scanned_step(
    train_step: Callable[[TrainState, Batch], Tuple[TrainState, Metrics]],
    k: int,
) -> Callable[[TrainState, Batch], Tuple[TrainState, Metrics]]:
    """Run ``k`` train steps per dispatch via ``lax.scan``.

    The input batch pytree carries a leading axis of length ``k`` (k
    stacked batches); the scan threads the train state through all k
    steps inside ONE compiled program, so the host pays one dispatch —
    and, through the axon relay, one dispatch round-trip — per k steps
    instead of per step.  Metrics come back stacked ``[k, ...]`` so the
    caller can log every inner step exactly as if they were dispatched
    one by one (reference logging cadence, ``usps_mnist.py:305-308``).

    Numerics are the single-step path's: the body is the same
    ``train_step``; only the dispatch granularity changes.  Parity is
    pinned by ``tests/test_train.py::test_scanned_step_matches_sequential``.
    Caveat: bitwise identity with the per-dispatch path is NOT guaranteed
    — the scan body and the standalone step are different XLA programs
    and may fuse float reductions differently (ulp-level), which
    sign-normalizing optimizers (Adam's first steps) can amplify to
    lr-sized parameter differences.  This is recompile-level
    nondeterminism, the same class as changing XLA versions, not a
    semantic divergence; losses/gradients agree to float tolerance.
    """
    if k < 1:
        raise ValueError(f"steps_per_dispatch must be >= 1, got {k}")

    def scanned(state: TrainState, batches: Batch):
        def body(s, b):
            return train_step(s, b)

        return lax.scan(body, state, batches, length=k)

    return scanned


def stack_batches(batches):
    """Stack a list of batch pytrees along a new leading axis (host-side,
    numpy) for :func:`make_scanned_step`."""
    import numpy as np

    return jax.tree.map(lambda *xs: np.stack(xs), *batches)


def make_eval_step(
    model, axis_name: Optional[AxisName] = None
) -> Callable[[Any, Any, jax.Array, jax.Array], Metrics]:
    """Eval step accumulators matching the reference ``test()`` functions.

    Returns summed nll loss, correct-prediction count, and sample count per
    call (``usps_mnist.py:310-327``, ``resnet50…py:447-464`` accumulate sum
    loss / correct over the whole test set and normalize at the end); with
    ``axis_name`` the counters are psum'd across replicas.
    """

    def eval_step(params, batch_stats, x: jax.Array, y: jax.Array) -> Metrics:
        logits = model.apply(
            {"params": params, "batch_stats": batch_stats}, x, train=False
        )
        logp = jax.nn.log_softmax(at_least_f32(logits), axis=-1)
        loss_sum = nll_loss(logp, y, reduction="sum")
        correct = jnp.sum(
            (jnp.argmax(logits, axis=-1) == y).astype(jnp.int32)
        )
        count = jnp.asarray(y.shape[0], jnp.int32)
        out = {"loss_sum": loss_sum, "correct": correct, "count": count}
        if axis_name is not None:
            out = lax.psum(out, axis_name)
        return out

    return eval_step


def eval_counters() -> Metrics:
    """Zero-initialized device-resident eval accumulators.

    The reference ``test()`` functions accumulate sum-loss / correct /
    count over the whole test set (``usps_mnist.py:310-327``); the fast
    eval path keeps exactly those three scalars ON DEVICE across every
    batch and fetches them once at the end of the pass.
    """
    return {
        "loss_sum": jnp.zeros((), jnp.float32),
        "correct": jnp.zeros((), jnp.int32),
        "count": jnp.zeros((), jnp.int32),
    }


def eval_variables(params: Any, batch_stats: Any, cache: Any) -> Dict:
    """The eval-mode ``model.apply`` variables dict: params + frozen
    running stats + (when the model has whitening sites) the pass's
    precomputed ``whiten_cache`` collection.  Shared by the accumulating
    eval step and the serving engine's forward so both paths assemble
    IDENTICAL programs — the bitwise-parity contract between served
    logits and eval counters rests on this being one code path."""
    variables = {"params": params, "batch_stats": batch_stats}
    if cache:  # static: {} (no whitening sites) vs the cache tree
        variables = {**variables, **cache}
    return variables


def make_serve_forward(
    model,
) -> Callable[[Any, Any, Any, jax.Array], jax.Array]:
    """``(params, batch_stats, cache, x) -> logits`` — the deployment
    forward: target-branch eval mode, frozen running stats, whitening
    matrices read from the precomputed cache.  This is the exact forward
    the accumulating eval step reduces into counters; the serving engine
    AOT-compiles it per bucket shape (``dwt_tpu.serve.engine``)."""

    def forward(params, batch_stats, cache, x):
        return model.apply(eval_variables(params, batch_stats, cache), x,
                           train=False)

    return forward


def make_accum_eval_step(
    model, axis_name: Optional[AxisName] = None
) -> Callable[[Metrics, Any, Any, Any, Dict[str, jax.Array]], Metrics]:
    """Accumulating, scanned eval dispatch: ``(counters, params, stats,
    cache, chunk) -> counters``.

    ``cache`` is the pass's precomputed whitening-matrix collection
    (``ops.whitening.build_whiten_cache`` — ``{"whiten_cache": tree}``,
    or ``{}`` for models with no whitening sites): eval-mode norm sites
    read their frozen-stats factorization from it instead of re-running
    it per batch per site.

    ``chunk`` stacks k batches — ``{"x": [k, N, ...], "y": [k, N],
    "mask": [k, N] bool}`` — and the scan threads the counter carry
    through all k batches inside ONE compiled program, so a full eval
    pass costs ``ceil(B/k)`` dispatches and O(1) host fetches instead of
    one blocking ``float()`` per batch (the ``--eval_steps_per_dispatch``
    machinery; the train-path analogue is :func:`make_scanned_step`).

    ``mask`` marks real samples: the loader pads ragged final batches to
    a uniform shape (``batch_iterator(pad_and_mask=True)``) so every
    dispatch compiles once, and padded rows contribute nothing to any
    counter — counts stay exact.  With ``axis_name`` the chunk's counter
    deltas are ``psum``'d across replicas ONCE per dispatch (not per
    inner batch), which makes the same function the per-replica body for
    ``shard_map`` (``parallel.make_sharded_eval_step``).
    """

    def accum_eval(counters, params, batch_stats, cache, chunk):
        variables = eval_variables(params, batch_stats, cache)

        def body(c, b):
            logits = model.apply(
                variables,
                b["x"],
                train=False,
            )
            logp = jax.nn.log_softmax(at_least_f32(logits), axis=-1)
            per_sample = nll_loss(logp, b["y"], reduction="none")
            mask = b["mask"]
            hit = (jnp.argmax(logits, axis=-1) == b["y"]) & mask
            delta = {
                "loss_sum": jnp.sum(jnp.where(mask, per_sample, 0.0)),
                "correct": jnp.sum(hit.astype(jnp.int32)),
                "count": jnp.sum(mask.astype(jnp.int32)),
            }
            return jax.tree.map(jnp.add, c, delta), None

        zeros = jax.tree.map(jnp.zeros_like, counters)
        total, _ = lax.scan(body, zeros, chunk)
        if axis_name is not None:
            total = lax.psum(total, axis_name)
        return jax.tree.map(jnp.add, counters, total)

    return accum_eval


def make_scanned_collect(
    collect_fn: Callable[[TrainState, jax.Array], TrainState],
) -> Callable[[TrainState, jax.Array], TrainState]:
    """Scan a stat-collection step over ``xs [k, N, ...]`` — k collection
    batches per dispatch, state (the ``batch_stats`` EMA carry) resident
    on device across all of them.  Numerics are the per-batch path's:
    the body IS ``collect_fn``; only the dispatch granularity changes."""

    def scanned(state: TrainState, xs: jax.Array) -> TrainState:
        def body(s, x):
            return collect_fn(s, x), None

        state, _ = lax.scan(body, state, xs)
        return state

    return scanned


def make_stat_collection_step(
    model, num_domains: int
) -> Callable[[TrainState, jax.Array], TrainState]:
    """The post-training stat-collection pass (gradient-free train forward).

    Reference ``eval_pass_collect_stats`` (``resnet50…py:380-389``): after
    training, run 10 full passes over the target *test* set with the model
    in train mode under no_grad, feeding ``cat(data, data, data)`` — the
    same batch tiled into every domain slot — purely to advance the running
    stats toward the target distribution ("dont care about source statistics
    after its trained", ``:387``).  Only ``batch_stats`` changes.
    """

    def collect(state: TrainState, x: jax.Array) -> TrainState:
        tiled = jnp.broadcast_to(x[None], (num_domains,) + x.shape)
        _, updated = model.apply(
            {"params": state.params, "batch_stats": state.batch_stats},
            tiled,
            train=True,
            mutable=["batch_stats"],
        )
        return state.replace_stats(updated["batch_stats"])

    return collect
