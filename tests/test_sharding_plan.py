"""Sharding-rules engine tests (ISSUE-9): the ShardingPlan contracts.

Four contract groups, mirroring the plan's consumers:

* **rules matching** — ordered first-match-wins ``re.search`` over
  ``jax.tree_util.keystr`` paths, anchoring, scalar exemption, and the
  stat/opt-state path shapes (optimizer moments shard WITH their params;
  whitening/BN running stats pin replicated under the model preset);
* **fail-fast diagnostics** — a leaf matched by no rule raises listing
  the full keystr and the active table; duplicate and fully-shadowed
  rules warn with the winning pattern; specs that cannot apply (rank,
  divisibility, unknown axis) name leaf + rule + mesh at plan time;
* **bitwise dp** — the replica-mode plan step IS the historical
  ``make_sharded_train_step`` program (same wrapper, explicitly-passed
  all-``P()`` specs), asserted bit-for-bit; plan place→gather round-trips
  bitwise under the model preset;
* **restore-to-spec + format cross** — a checkpoint saved under the dp
  plan restores directly onto model shardings (sharding inspection: the
  leaves LAND sharded, no replicated intermediate) and vice versa, for
  BOTH on-disk formats (Orbax and host-shard).

The in-process gspmd smoke here is the tier-1 companion of the
slow-marked ``__graft_entry__`` dryrun matrix case (16-device subprocess,
``tests/test_graft_entry.py``).
"""

import functools
import json
import logging
import os
import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from dwt_tpu.nn import LeNetDWT
from dwt_tpu.parallel import (
    MODEL_AXIS,
    PRESETS,
    ShardingPlan,
    load_rules_file,
    make_mesh,
    make_plan_mesh,
    make_sharded_train_step,
    match_partition_rules,
    parse_mesh_shape,
    plan_from_flags,
    replicate_state,
    shard_batch,
)
from dwt_tpu.train import adam_l2, create_train_state, make_digits_train_step


def _batch(n=8, seed=0):
    rng = np.random.default_rng(seed)
    return {
        "source_x": jnp.asarray(rng.normal(size=(n, 28, 28, 1)), jnp.float32),
        "source_y": jnp.asarray(rng.integers(0, 10, size=(n,))),
        "target_x": jnp.asarray(
            rng.normal(loc=0.5, size=(n, 28, 28, 1)), jnp.float32
        ),
    }


@functools.lru_cache(maxsize=1)
def _lenet_state():
    """One shared (model, tx, state) init for the whole module — the
    LeNet init trace is the expensive part of every test here."""
    model = LeNetDWT(group_size=4)
    tx = adam_l2(1e-3, 5e-4)
    batch = _batch()
    sample = jnp.stack([batch["source_x"], batch["target_x"]])
    state = create_train_state(model, jax.random.key(0), sample, tx)
    return model, tx, state


# ------------------------------------------------------------ rule matching


def test_parse_mesh_shape_forms_and_errors():
    assert parse_mesh_shape("1,4,2") == (1, 4, 2)
    assert parse_mesh_shape("4") == (1, 4, 1)       # pure DP shorthand
    assert parse_mesh_shape("2,4") == (2, 4, 1)     # multi-slice DP
    with pytest.raises(ValueError, match="comma-separated ints"):
        parse_mesh_shape("2x4")
    with pytest.raises(ValueError, match="1-3 positive sizes"):
        parse_mesh_shape("1,2,3,4")
    with pytest.raises(ValueError, match="1-3 positive sizes"):
        parse_mesh_shape("0,4")


def test_match_rules_first_match_wins_ordered():
    tree = {"conv": {"kernel": np.zeros((3, 3, 4, 8))},
            "fc": {"kernel": np.zeros((16, 8))}}
    specs = match_partition_rules(
        [
            (r"\['conv'\]", P(None, None, None, "model")),
            (r"kernel", P("model", None)),   # fc wins here, conv must not
            (r".*", P()),
        ],
        tree,
    )
    assert specs["conv"]["kernel"] == P(None, None, None, "model")
    assert specs["fc"]["kernel"] == P("model", None)


def test_match_rules_anchoring_against_full_keystr():
    tree = {"a": {"b": np.zeros((4, 4))}, "b": np.zeros((4, 4))}
    # ^-anchored pattern matches only the top-level 'b' path.
    specs = match_partition_rules(
        [(r"^\['b'\]$", P("model", None)), (r".*", P())], tree
    )
    assert specs["b"] == P("model", None)
    assert specs["a"]["b"] == P()


def test_scalars_and_single_element_leaves_never_partitioned():
    tree = {"step": np.asarray(3), "one": np.zeros((1,)),
            "w": np.zeros((4, 4))}
    # The table never gets to claim the scalar/1-element leaves — even a
    # catch-all sharded rule leaves them P().
    specs = match_partition_rules([(r".*", P("model", None))], tree)
    assert specs["step"] == P() and specs["one"] == P()
    assert specs["w"] == P("model", None)


def test_model_preset_stat_and_opt_state_path_shapes():
    """The DWT contract on real TrainState paths: conv/fc kernels (and
    their optimizer-moment twins) model-shard, whitening/BN running
    stats and the fc5 head stay replicated."""
    _, _, state = _lenet_state()
    specs = match_partition_rules(PRESETS["model"], state)
    model_dim = P(None, None, None, MODEL_AXIS)
    assert specs.params["conv1"]["kernel"] == model_dim
    assert specs.params["fc3"]["kernel"] == P(None, MODEL_AXIS)
    assert specs.params["fc5"]["kernel"] == P()      # head: replicated
    # Optimizer moments shard WITH their params (rules match layer
    # names, not containers).
    mu = specs.opt_state[1].mu
    assert mu["conv1"]["kernel"] == model_dim
    assert mu["fc3"]["kernel"] == P(None, MODEL_AXIS)
    assert mu["fc5"]["kernel"] == P()
    # Whitening/BN running stats: REPLICATED — their cross-replica
    # moment averaging is the algorithm.
    stats = jax.tree.leaves(
        match_partition_rules(PRESETS["model"], state.batch_stats)
    )
    assert all(s == P() for s in stats)


def test_fsdp_preset_path_shapes_and_moment_twins():
    """The fsdp contract on real TrainState paths: EVERY conv/dense
    kernel — including the head — shards over the model axis, the Adam
    moments land on their params' specs by rule construction, and
    whitening/BN running stats stay replicated (their cross-replica
    averaging IS the paper's algorithm)."""
    _, _, state = _lenet_state()
    specs = match_partition_rules(PRESETS["fsdp"], state)
    conv_dim = P(None, None, None, MODEL_AXIS)
    fc_dim = P(None, MODEL_AXIS)
    assert specs.params["conv1"]["kernel"] == conv_dim
    assert specs.params["fc3"]["kernel"] == fc_dim
    # The head shards too — the defining delta vs the model preset.
    assert specs.params["fc5"]["kernel"] == fc_dim
    for moments in (specs.opt_state[1].mu, specs.opt_state[1].nu):
        assert moments["conv1"]["kernel"] == conv_dim
        assert moments["fc5"]["kernel"] == fc_dim
    assert all(
        s == P() for s in jax.tree.leaves(
            match_partition_rules(PRESETS["fsdp"], state.batch_stats)
        )
    )
    # The save-side gather gates (sync + async ckpt) key off this.
    plan = ShardingPlan.gspmd(
        make_plan_mesh((1, 4, 2)), PRESETS["fsdp"], name="fsdp"
    )
    assert plan.uses_state_sharding and plan.uses_model_axis


def test_moment_spec_skew_raises_naming_both_rules():
    """A table whose moment rule wins a different spec than the param's
    rule must raise at plan time naming BOTH rules — silent param/moment
    spec skew corrupts Adam updates."""
    _, _, state = _lenet_state()
    skewed = [
        (r"\.(mu|nu)\[", P()),                       # moments: replicated
        (r"conv\w*'\]\['kernel'\]", P(None, None, None, MODEL_AXIS)),
        (r"'\]\['kernel'\]", P(None, MODEL_AXIS)),   # params: sharded
        (r".*", P()),
    ]
    with pytest.raises(ValueError) as ei:
        match_partition_rules(skewed, state, what="skewed table")
    msg = str(ei.value)
    assert "moment" in msg and "skewed table" in msg
    assert "mu|nu" in msg                            # the moment's rule
    assert "kernel" in msg                           # ...and the param's


def test_indivisible_head_error_names_pad_flag():
    """A model-axis rule on an indivisible classifier head must point at
    the fix: the pad_classes_to flag, not just the arithmetic."""
    mesh = make_plan_mesh((1, 4, 2))
    plan = ShardingPlan.gspmd(
        mesh, [(r"fc_out", P(None, MODEL_AXIS)), (r".*", P())], name="t"
    )
    with pytest.raises(ValueError) as ei:
        plan.tree_specs({"fc_out": {"kernel": np.zeros((2048, 65))}})
    msg = str(ei.value)
    assert "does not divide 65" in msg
    assert "pad_classes_to" in msg and "--pad_classes_to 2" in msg


def test_no_match_raises_with_keystr_and_table():
    tree = {"params": {"conv9": {"kernel": np.zeros((3, 3, 4, 8))}}}
    with pytest.raises(ValueError) as ei:
        match_partition_rules(
            [(r"\['fc\d'\]", P()), (r"bias", P())], tree, what="params"
        )
    msg = str(ei.value)
    assert "['params']['conv9']['kernel']" in msg   # full keystr path
    # The active table is listed, rules indexed in order.
    assert "active table" in msg and "[0]" in msg and "fc" in msg


def test_shadowed_rule_warns_with_winning_pattern(caplog):
    tree = {"w": np.zeros((4, 4))}
    with caplog.at_level(logging.WARNING, logger="dwt_tpu.parallel.plan"):
        specs = match_partition_rules(
            [(r".*", P()), (r"\['w'\]", P("model", None))], tree
        )
    assert specs["w"] == P()                        # first match won
    assert any("fully shadowed" in r.message for r in caplog.records)
    assert any("'.*'" in r.getMessage() for r in caplog.records)


def test_duplicate_rule_warns(caplog):
    mesh = make_plan_mesh((1, 2, 1), jax.devices()[:2])
    with caplog.at_level(logging.WARNING, logger="dwt_tpu.parallel.plan"):
        ShardingPlan.gspmd(
            mesh, [(r".*", P()), (r".*", P(None, "model"))], name="dup"
        )
    assert any("duplicate sharding rule" in r.getMessage()
               for r in caplog.records)


def test_spec_validation_names_leaf_rule_and_mesh():
    mesh = make_plan_mesh((1, 2, 2), jax.devices()[:4])
    plan = ShardingPlan.gspmd(
        mesh, [(r"w", P(None, MODEL_AXIS)), (r".*", P())], name="t"
    )
    # Divisibility: 5 % 2 != 0 — named leaf, rule, axis, size.
    with pytest.raises(ValueError, match=r"does not divide 5"):
        plan.tree_specs({"w": np.zeros((4, 5))})
    # Rank: spec longer than the leaf's rank.
    with pytest.raises(ValueError, match=r"rank"):
        plan.tree_specs({"w": np.zeros((4,))})
    # Unknown axis name.
    bad = ShardingPlan.gspmd(
        mesh, [(r"w", P("nonexistent")), (r".*", P())], name="t2"
    )
    with pytest.raises(ValueError, match=r"mesh axes are"):
        bad.tree_specs({"w": np.zeros((4, 4))})


def test_load_rules_file_roundtrip_and_errors(tmp_path):
    path = tmp_path / "rules.json"
    path.write_text(json.dumps([
        [r"(\.|\[')(batch_stats|whiten_cache)", []],
        [r"conv\w*'\]\['kernel'\]", [None, None, None, "model"]],
        [r"fsdp", [["data", "model"]]],
        [r".*", []],
    ]))
    rules = load_rules_file(str(path))
    assert rules[1][1] == P(None, None, None, "model")
    assert rules[2][1] == P(("data", "model"))       # multi-axis dim
    assert rules[3][1] == P()
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps([["(unclosed", []]]))
    with pytest.raises(ValueError, match="bad regex"):
        load_rules_file(str(bad))
    bad.write_text(json.dumps([[".*", "model"]]))
    with pytest.raises(ValueError, match="spec must be"):
        load_rules_file(str(bad))


# --------------------------------------------------------- flag resolution


def test_plan_from_flags_legacy_decisions():
    # No sharding flags: single mode — today's unsharded path.
    plan = plan_from_flags()
    assert plan.mode == "single" and plan.mesh is None
    assert plan.data_size == 1 and plan.step_axis_name is None
    # --data_parallel: replica over the historical make_mesh.
    plan = plan_from_flags(data_parallel=True)
    assert plan.mode == "replica" and plan.name == "dp"
    assert plan.data_size == jax.device_count()
    # Historical error contracts survive the refactor.
    with pytest.raises(ValueError, match="dcn_slices"):
        plan_from_flags(dcn_slices=4)
    with pytest.raises(ValueError, match="divisible"):
        plan_from_flags(data_parallel=True, batch_size=3)
    with pytest.raises(ValueError, match="pallas_whiten"):
        plan_from_flags(data_parallel=True, pallas_whiten=True)


def test_plan_from_flags_rules_engine_decisions():
    plan = plan_from_flags(mesh_shape="1,4,2", sharding_rules="model")
    assert plan.mode == "gspmd" and plan.uses_model_axis
    assert plan.data_size == 4                       # model axis: no batch
    assert plan.step_axis_name is None               # axis-free model
    # dp rules + a model axis: wasted chips, refused.
    with pytest.raises(ValueError, match="model axis"):
        plan_from_flags(mesh_shape="1,2,2", sharding_rules="dp")
    # dp rules over an explicit mesh shape: the replica engine.
    plan = plan_from_flags(mesh_shape="2,4", sharding_rules="dp",
                           data_parallel=True)
    assert plan.mode == "replica"
    assert tuple(plan.mesh.devices.shape) == (2, 4)
    # Batch divisibility is checked against the plan's DATA shards.
    with pytest.raises(ValueError, match="divisible"):
        plan_from_flags(mesh_shape="1,4,2", sharding_rules="model",
                        batch_size=6)
    # A mesh larger than the device count fails loudly on BOTH engine
    # branches — the dp-preset path must not silently truncate.
    with pytest.raises(ValueError, match="devices"):
        plan_from_flags(mesh_shape="1,64", sharding_rules="dp")
    with pytest.raises(ValueError, match="devices"):
        plan_from_flags(mesh_shape="1,64,2", sharding_rules="model")
    # --data_parallel promises the bitwise shard_map program; a non-dp
    # rules table routes through gspmd — the conflict must raise, not
    # silently drop either promise.
    with pytest.raises(ValueError, match="data_parallel conflicts"):
        plan_from_flags(data_parallel=True, sharding_rules="model")


# ------------------------------------------------- bitwise dp + round trip


@pytest.mark.slow
def test_dp_preset_plan_step_bitwise_vs_legacy_wrapper():
    """The replica-mode plan step must be the SAME program as the
    historical make_sharded_train_step wrapper — bit-for-bit, not just
    close: the plan passes explicit all-P() state specs into the same
    shard_map.  Slow-marked (t1 budget): the dp-preset bitwise claim
    stays continuously pinned by the CLI digest A/Bs recorded in
    CHANGES.md and the replica-mode eval tests; this full two-program
    compile A/B runs in the slow tier."""
    model, tx, state = _lenet_state()
    mesh = make_mesh(jax.devices()[:8])
    model_dp = LeNetDWT(group_size=4, axis_name="data")
    raw = make_digits_train_step(model_dp, tx, 0.1, axis_name="data")
    batch = _batch()

    legacy = make_sharded_train_step(raw, mesh)
    s_legacy, m_legacy = legacy(
        replicate_state(state, mesh), shard_batch(batch, mesh)
    )

    plan = ShardingPlan.replica(mesh)
    assert plan.step_axis_name == "data"             # 1-D mesh: bare name
    plan_step = plan.make_train_step(raw)
    s_plan, m_plan = plan_step(
        replicate_state(state, mesh), plan.shard_batch(batch)
    )
    for a, b in zip(jax.tree.leaves(s_legacy), jax.tree.leaves(s_plan)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for k in m_legacy:
        np.testing.assert_array_equal(
            np.asarray(m_legacy[k]), np.asarray(m_plan[k])
        )


def test_gspmd_model_sharded_step_and_gather_roundtrip():
    """Tier-1 gspmd smoke (the in-process companion of the slow graft
    dryrun case): plan placement genuinely model-shards the kernels, one
    axis-free train step keeps them sharded, and place→gather
    round-trips bitwise."""
    model, tx, state = _lenet_state()
    plan = ShardingPlan.gspmd(
        make_plan_mesh((1, 4, 2)), PRESETS["model"], name="model"
    )
    placed = plan.place(state, "train state")
    kernel = placed.params["conv1"]["kernel"]
    assert MODEL_AXIS in str(kernel.sharding.spec)
    # 32 out-channels over a model axis of 2: each shard holds 16.
    assert kernel.addressable_shards[0].data.shape[-1] == 16

    raw = make_digits_train_step(model, tx, 0.1, axis_name=None)
    step = plan.make_train_step(raw)
    new_state, metrics = step(placed, plan.shard_batch(_batch()))
    assert np.isfinite(float(metrics["loss"]))
    assert int(new_state.step) == 1
    assert MODEL_AXIS in str(
        new_state.params["conv1"]["kernel"].sharding.spec
    )
    # Whitening stats stayed replicated through the sharded step.
    cov = new_state.batch_stats["dn1"]["whitening"].cov
    assert cov.sharding.spec == P()

    gathered = plan.gather(new_state)
    for g, s in zip(jax.tree.leaves(gathered), jax.tree.leaves(new_state)):
        assert getattr(g.sharding, "spec", P()) == P()
        np.testing.assert_array_equal(
            np.asarray(jax.device_get(g)), np.asarray(jax.device_get(s))
        )


# ------------------------------------- restore-to-spec + ckpt format cross


def _host_shard_save(ckpt_dir, step, state):
    from dwt_tpu.utils.checkpoint import (
        host_fetch,
        promote_host_shards,
        save_host_shard,
    )

    host = host_fetch(state)
    assert save_host_shard(ckpt_dir, step, host, process_index=0)
    return promote_host_shards(ckpt_dir, step, process_count=1)


@pytest.mark.parametrize(
    "fmt",
    [
        # Orbax save/restore machinery is the expensive half; the
        # host-shard param (pure numpy I/O) keeps the cross-plan +
        # restore-to-spec contract tier-1.  (t1 budget)
        pytest.param("orbax", marks=pytest.mark.slow),
        "host_shards",
    ],
)
@pytest.mark.parametrize("preset", ["model", "fsdp"])
def test_checkpoint_cross_plan_both_formats(tmp_path, fmt, preset):
    """Save under the dp plan, restore under the model-/fsdp-sharded
    plan (the leaves must LAND already-sharded — restore-to-spec, no
    replicated intermediate) and vice versa, for both on-disk formats.
    The fsdp rows extend the PR-9 cross matrix: the head and moments
    are sharded too, and the same gather-on-save path covers them."""
    from dwt_tpu.utils.checkpoint import restore_state, save_state

    _, _, state = _lenet_state()
    plan = ShardingPlan.gspmd(
        make_plan_mesh((1, 4, 2)), PRESETS[preset], name=preset
    )

    # dp save -> model-sharded restore.
    dp_dir = str(tmp_path / "dp")
    if fmt == "orbax":
        save_state(dp_dir, 3, state)
    else:
        _host_shard_save(dp_dir, 3, state)
    shardings = plan.restore_shardings(state)
    assert shardings is not None                     # gspmd: specs exist
    restored = restore_state(dp_dir, state, shardings=shardings)
    kernel = restored.params["conv1"]["kernel"]
    # Restore-to-spec proof: the restored leaf IS on its target sharding.
    assert kernel.sharding == shardings.params["conv1"]["kernel"]
    assert kernel.addressable_shards[0].data.shape[-1] == 16
    if preset == "fsdp":
        # fsdp's defining delta: head + Adam moments restore sharded.
        head = restored.params["fc5"]["kernel"]
        assert MODEL_AXIS in str(head.sharding.spec)
        mu = restored.opt_state[1].mu["conv1"]["kernel"]
        assert mu.sharding == shardings.opt_state[1].mu["conv1"]["kernel"]
        assert MODEL_AXIS in str(mu.sharding.spec)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(kernel)),
        np.asarray(state.params["conv1"]["kernel"]),
    )
    assert int(restored.step) == int(state.step)

    # model-sharded save (gathered on the way out) -> dp restore.
    sharded_state = plan.place(restored, "train state")
    md_dir = str(tmp_path / "model")
    gathered = plan.gather(sharded_state)
    if fmt == "orbax":
        save_state(md_dir, 3, gathered)
    else:
        _host_shard_save(md_dir, 3, gathered)
    # dp/single restore: no shardings — today's uncommitted-leaf path.
    back = restore_state(md_dir, state)
    for a, b in zip(jax.tree.leaves(back), jax.tree.leaves(state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_per_leaf_shard_and_gather_fns():
    """The SNIPPETS make_shard_and_gather_fns surface: per-leaf
    callables that place onto the leaf's rules sharding / return it
    replicated."""
    plan = ShardingPlan.gspmd(
        make_plan_mesh((1, 2, 2), jax.devices()[:4]), PRESETS["model"],
        name="model",
    )
    tree = {"conv1": {"kernel": jnp.ones((3, 3, 4, 8))},
            "conv1_bias": {"bias": jnp.ones((8,))}}
    sfns = plan.shard_fns(tree)
    placed = jax.tree.map(lambda f, l: f(l), sfns, tree)
    assert MODEL_AXIS in str(placed["conv1"]["kernel"].sharding.spec)
    assert placed["conv1_bias"]["bias"].sharding.spec == P()
    gfns = plan.gather_fns(placed)
    gathered = jax.tree.map(lambda f, l: f(l), gfns, placed)
    assert gathered["conv1"]["kernel"].sharding.spec == P()
    np.testing.assert_array_equal(
        np.asarray(gathered["conv1"]["kernel"]),
        np.asarray(tree["conv1"]["kernel"]),
    )


def test_place_is_noop_on_already_placed_leaves():
    """Leaves already on their target sharding (what restore-to-spec
    produces) pass through place() untouched — on multi-host the host
    round-trip they skip would RAISE on non-addressable leaves."""
    plan = ShardingPlan.gspmd(
        make_plan_mesh((1, 2, 2), jax.devices()[:4]), PRESETS["model"],
        name="model",
    )
    tree = {"conv1": {"kernel": jnp.ones((3, 3, 4, 8))}}
    placed = plan.place(tree)
    again = plan.place(placed)
    assert again["conv1"]["kernel"] is placed["conv1"]["kernel"]


def test_uses_state_sharding_covers_fsdp_style_tables():
    """The save-gather gate must trip on ANY sharded state axis, not
    just the model axis — an FSDP-style table sharding kernels over
    'data' leaves state non-process-replicated too."""
    mesh = make_plan_mesh((1, 4, 2))
    fsdp = ShardingPlan.gspmd(
        mesh, [(r"kernel", P(None, None, None, "data")), (r".*", P())],
        name="fsdp",
    )
    assert fsdp.uses_state_sharding and not fsdp.uses_model_axis
    model = ShardingPlan.gspmd(mesh, PRESETS["model"], name="model")
    assert model.uses_state_sharding and model.uses_model_axis
    dp_like = ShardingPlan.gspmd(mesh, PRESETS["dp"], name="dp-ish")
    assert not dp_like.uses_state_sharding
    assert not ShardingPlan.single().uses_state_sharding


def test_dcn_slices_mesh_shape_mismatch_raises_both_ways():
    """--dcn_slices N with a mesh dcn axis of 1 must raise too: silently
    flattening the requested multi-slice topology would push per-slice
    reductions onto the data-center network."""
    with pytest.raises(ValueError, match="dcn axis"):
        plan_from_flags(mesh_shape="1,4,2", sharding_rules="model",
                        dcn_slices=2)
    with pytest.raises(ValueError, match="dcn axis"):
        plan_from_flags(mesh_shape="4,2,1", sharding_rules="model",
                        dcn_slices=2)


def test_replica_and_single_plans_restore_without_shardings():
    """The non-gspmd paths keep the historical restore byte flow:
    restore_shardings is None, so leaves come back uncommitted (the
    multi-host DP resume contract)."""
    _, _, state = _lenet_state()
    assert ShardingPlan.single().restore_shardings(state) is None
    mesh = make_mesh(jax.devices()[:8])
    assert ShardingPlan.replica(mesh).restore_shardings(state) is None


# ------------------------------------------------- off-chip TPU lowering


def test_model_sharded_train_step_lowers_for_tpu_offchip():
    """ISSUE-9 satellite: one model-sharded train step must pass the full
    TPU lowering off-chip (jax.export) at a representative (1, 4, 2)
    mesh — the same guard the Pallas kernels carry, extended to the
    rules-engine path, so a Mosaic/SPMD blocker surfaces here and not on
    first chip time."""
    try:
        from jax import export
    except ImportError as e:  # pragma: no cover - env-dependent
        pytest.skip(f"missing jax.export: {e}")

    model, tx, state = _lenet_state()
    plan = ShardingPlan.gspmd(
        make_plan_mesh((1, 4, 2)), PRESETS["model"], name="model"
    )
    st_sh = plan.tree_shardings(state, "train state")
    raw = make_digits_train_step(model, tx, 0.1, axis_name=None)
    jitted = jax.jit(
        raw,
        in_shardings=(st_sh, plan.batch_sharding()),
        out_shardings=(st_sh, plan.replicated),
    )
    batch = _batch()
    exp = export.export(jitted, platforms=("tpu",))(
        jax.tree.map(
            lambda l, s: jax.ShapeDtypeStruct(
                jnp.shape(l), jnp.asarray(l).dtype, sharding=s
            ),
            state, st_sh,
        ),
        jax.tree.map(
            lambda l: jax.ShapeDtypeStruct(
                jnp.shape(l), jnp.asarray(l).dtype,
                sharding=plan.batch_sharding(),
            ),
            batch,
        ),
    )
    module = exp.mlir_module()
    assert "sharding" in module                       # SPMD annotations
    assert exp.nr_devices == 8


def test_fsdp_train_step_lowers_for_tpu_offchip():
    """ISSUE-19 satellite: the fsdp-preset train step (params + moments
    sharded over the model axis, stats replicated) must pass the full
    TPU lowering off-chip at the (1, 4, 2) mesh — the Mosaic 2-D-dot
    blocker class has bitten twice before."""
    try:
        from jax import export
    except ImportError as e:  # pragma: no cover - env-dependent
        pytest.skip(f"missing jax.export: {e}")

    model, tx, state = _lenet_state()
    plan = ShardingPlan.gspmd(
        make_plan_mesh((1, 4, 2)), PRESETS["fsdp"], name="fsdp"
    )
    st_sh = plan.tree_shardings(state, "train state")
    raw = make_digits_train_step(model, tx, 0.1, axis_name=None)
    jitted = jax.jit(
        raw,
        in_shardings=(st_sh, plan.batch_sharding()),
        out_shardings=(st_sh, plan.replicated),
    )
    batch = _batch()
    exp = export.export(jitted, platforms=("tpu",))(
        jax.tree.map(
            lambda l, s: jax.ShapeDtypeStruct(
                jnp.shape(l), jnp.asarray(l).dtype, sharding=s
            ),
            state, st_sh,
        ),
        jax.tree.map(
            lambda l: jax.ShapeDtypeStruct(
                jnp.shape(l), jnp.asarray(l).dtype,
                sharding=plan.batch_sharding(),
            ),
            batch,
        ),
    )
    module = exp.mlir_module()
    assert "sharding" in module
    assert exp.nr_devices == 8


def test_vit_fsdp_eval_forward_lowers_for_tpu_offchip():
    """ISSUE-19 satellite: the ViT-DWT eval forward under the fsdp
    preset (attention/MLP kernels + padded head on the model axis) must
    pass the full TPU lowering off-chip at the (1, 4, 2) mesh."""
    try:
        from jax import export
    except ImportError as e:  # pragma: no cover - env-dependent
        pytest.skip(f"missing jax.export: {e}")

    from dwt_tpu.nn import build_backbone

    model = build_backbone("vit_tiny", num_classes=65, pad_classes_to=2)
    sample = jnp.zeros((3, 2, 16, 16, 3), jnp.float32)
    variables = model.init(jax.random.key(0), sample, True)
    plan = ShardingPlan.gspmd(
        make_plan_mesh((1, 4, 2)), PRESETS["fsdp"], name="fsdp"
    )
    v_sh = plan.tree_shardings(variables, "vit variables")
    fwd = jax.jit(
        lambda v, x: model.apply(v, x, False),
        in_shardings=(v_sh, plan.batch_sharding()),
        out_shardings=plan.replicated,
    )
    exp = export.export(fwd, platforms=("tpu",))(
        jax.tree.map(
            lambda l, s: jax.ShapeDtypeStruct(
                jnp.shape(l), jnp.asarray(l).dtype, sharding=s
            ),
            variables, v_sh,
        ),
        jax.ShapeDtypeStruct(
            (8, 16, 16, 3), jnp.float32, sharding=plan.batch_sharding()
        ),
    )
    module = exp.mlir_module()
    assert "sharding" in module
    assert exp.nr_devices == 8
