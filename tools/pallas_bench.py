"""Whitening-op microbench: XLA `group_whiten` vs the Pallas kernels.

Times the training-mode op (moments + factorize + apply, fwd only and
fwd+bwd) at the flagship whitening-site shapes (PERF.md inventory) on the
default backend.  This is the measurement that finalizes the Pallas
go/no-go once the TPU is reachable; on CPU the Pallas path runs in
interpreter mode, so CPU numbers validate plumbing, not performance —
the JSON marks which.

Usage: PYTHONPATH=/root/repo:/root/.axon_site python tools/pallas_bench.py
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Flagship whitening sites (PERF.md): (rows = N*H*W at batch 54, channels).
SITES = {
    "stem": (54 * 112 * 112, 64),
    "layer1_bn3": (54 * 56 * 56, 256),
}


def _fetch(out):
    """Force execution with a host fetch — through the axon relay,
    block_until_ready resolves the local handle without waiting for
    remote execution (see bench.py:two_point_per_step).  The chip runs
    one stream, so fetching the LAST call's result waits for all queued
    calls."""
    import jax

    leaf = jax.tree_util.tree_leaves(out)[0]
    float(leaf.reshape(-1)[0])


def _time(fn, *args, steps=20):
    def run(n):
        t0 = time.perf_counter()
        for _ in range(n):
            out = fn(*args)
        _fetch(out)
        return time.perf_counter() - t0

    run(2)  # warmup
    # Two-point: cancels the fixed per-fetch relay round-trip.
    n1 = max(1, steps // 4)
    n2 = max(steps, n1 + 4)
    dt1, dt2 = run(n1), run(n2)
    per = (dt2 - dt1) / (n2 - n1)
    return per if per > 0 else dt2 / n2


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--rows_cap", type=int, default=None,
                    help="cap site rows (CPU plumbing runs)")
    ap.add_argument("--dtype", choices=["bf16", "f32"], default="bf16")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import numpy as np

    from dwt_tpu.ops import (
        group_whiten,
        init_whitening_stats,
        pallas_group_whiten,
    )

    backend = jax.default_backend()
    interpret = backend != "tpu"
    dtype = jnp.bfloat16 if args.dtype == "bf16" else jnp.float32

    for site, (rows, c) in SITES.items():
        if args.rows_cap:
            rows = min(rows, args.rows_cap)
        x = jnp.asarray(
            np.random.default_rng(0).normal(size=(rows, c)), dtype
        )
        stats = init_whitening_stats(c, 4)

        def xla_fwd(x):
            y, _ = group_whiten(x, stats, group_size=4, train=True)
            return y

        def pal_fwd(x):
            y, _ = pallas_group_whiten(
                x, stats, group_size=4, train=True, interpret=interpret
            )
            return y

        record = {
            "site": site,
            "rows": rows,
            "channels": c,
            "dtype": args.dtype,
            "backend": backend,
            "pallas_interpret_mode": interpret,
        }
        # Apply-lowering A/B (grouped tiny-K einsum vs block-diag matmul;
        # apply_whitening's "auto" picks blockdiag for C<=128) — isolates
        # the one sub-op with an MXU-shape choice.
        from dwt_tpu.ops.whitening import apply_whitening

        w_rand = jnp.asarray(
            np.random.default_rng(1).normal(size=(c // 4, 4, 4)),
            jnp.float32,
        )
        for lowering in ("grouped", "blockdiag"):
            fn = jax.jit(
                lambda x, lo=lowering: apply_whitening(
                    x, w_rand, compute_dtype=dtype, lowering=lo
                )
            )
            record[f"apply_{lowering}_ms"] = round(
                _time(fn, x, steps=args.steps) * 1e3, 3
            )

        record["xla_fwd_ms"] = round(
            _time(jax.jit(xla_fwd), x, steps=args.steps) * 1e3, 3
        )
        record["pallas_fwd_ms"] = round(
            _time(jax.jit(pal_fwd), x, steps=args.steps) * 1e3, 3
        )

        def xla_step(x):
            return jax.value_and_grad(lambda x: jnp.sum(xla_fwd(x) ** 2))(x)

        def pal_step(x):
            return jax.value_and_grad(lambda x: jnp.sum(pal_fwd(x) ** 2))(x)

        record["xla_fwdbwd_ms"] = round(
            _time(jax.jit(xla_step), x, steps=args.steps) * 1e3, 3
        )
        record["pallas_fwdbwd_ms"] = round(
            _time(jax.jit(pal_step), x, steps=args.steps) * 1e3, 3
        )
        record["fwd_speedup"] = round(
            record["xla_fwd_ms"] / max(record["pallas_fwd_ms"], 1e-9), 3
        )
        print(json.dumps(record), flush=True)


if __name__ == "__main__":
    main()
