"""Parity tests for the functional BN op against torch.nn.functional.batch_norm
(the reference delegates to it at ``utils/batch_norm.py:66-69``)."""

import numpy as np
import jax.numpy as jnp
import torch
import torch.nn.functional as F

from dwt_tpu.ops import BatchNormStats, batch_norm, init_batch_norm_stats


def run_torch(x_nchw, rm, rv, train, momentum=0.1, eps=1e-5):
    rm_t = torch.tensor(rm.copy())
    rv_t = torch.tensor(rv.copy())
    y = F.batch_norm(
        torch.tensor(x_nchw), rm_t, rv_t, weight=None, bias=None,
        training=train, momentum=momentum, eps=eps,
    )
    return y.numpy(), rm_t.numpy(), rv_t.numpy()


def to_nhwc(x_nchw):
    return np.transpose(x_nchw, (0, 2, 3, 1))


def from_nhwc(x_nhwc):
    return np.transpose(x_nhwc, (0, 3, 1, 2))


def test_train_matches_torch_2d():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(6, 5, 4, 4)).astype(np.float32) * 3 + 1
    rm = rng.normal(size=5).astype(np.float32)
    rv = rng.uniform(0.5, 2.0, size=5).astype(np.float32)
    ty, trm, trv = run_torch(x, rm, rv, train=True)
    stats = BatchNormStats(jnp.asarray(rm), jnp.asarray(rv), jnp.zeros((), jnp.int32))
    y, ns = batch_norm(jnp.asarray(to_nhwc(x)), stats, train=True)
    np.testing.assert_allclose(from_nhwc(np.asarray(y)), ty, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(ns.mean), trm, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(ns.var), trv, rtol=1e-4, atol=1e-5)


def test_eval_matches_torch():
    rng = np.random.default_rng(1)
    x = rng.normal(size=(3, 8, 2, 2)).astype(np.float32)
    rm = rng.normal(size=8).astype(np.float32)
    rv = rng.uniform(0.5, 2.0, size=8).astype(np.float32)
    ty, _, _ = run_torch(x, rm, rv, train=False)
    stats = BatchNormStats(jnp.asarray(rm), jnp.asarray(rv), jnp.zeros((), jnp.int32))
    y, ns = batch_norm(jnp.asarray(to_nhwc(x)), stats, train=False)
    np.testing.assert_allclose(from_nhwc(np.asarray(y)), ty, rtol=1e-4, atol=1e-5)
    np.testing.assert_array_equal(np.asarray(ns.mean), rm)


def test_1d_input_matches_torch():
    # LeNet FC sites use BatchNorm1d(affine=False) (usps_mnist.py:214-228)
    rng = np.random.default_rng(2)
    x = rng.normal(size=(16, 10)).astype(np.float32)
    rm = np.zeros(10, np.float32)
    rv = np.ones(10, np.float32)
    y_t = F.batch_norm(
        torch.tensor(x), torch.tensor(rm.copy()), torch.tensor(rv.copy()),
        training=True, momentum=0.1, eps=1e-5,
    ).numpy()
    stats = init_batch_norm_stats(10)
    y, _ = batch_norm(jnp.asarray(x), stats, train=True)
    np.testing.assert_allclose(np.asarray(y), y_t, rtol=1e-4, atol=1e-5)


def test_cumulative_mode():
    # momentum=None → factor 1/num_batches_tracked (batch_norm.py:61-64)
    rng = np.random.default_rng(3)
    stats = init_batch_norm_stats(4)
    xs = [rng.normal(size=(8, 4)).astype(np.float32) for _ in range(3)]
    for i, x in enumerate(xs):
        _, stats = batch_norm(jnp.asarray(x), stats, train=True, momentum=None)
        assert int(stats.count) == i + 1
    # after first batch factor=1 → running == batch stats exactly;
    # torch equivalent with momentum=None over same sequence:
    rm = torch.zeros(4)
    rv = torch.ones(4)
    bn = torch.nn.BatchNorm1d(4, momentum=None, affine=False)
    for x in xs:
        bn(torch.tensor(x))
    np.testing.assert_allclose(np.asarray(stats.mean), bn.running_mean.numpy(), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(stats.var), bn.running_var.numpy(), rtol=1e-4, atol=1e-5)


def test_bf16_activations_use_folded_halfwidth_path():
    rng = np.random.default_rng(5)
    x32 = rng.normal(size=(16, 6, 6, 8)).astype(np.float32)
    stats = init_batch_norm_stats(8)
    y32, s32 = batch_norm(jnp.asarray(x32), stats, train=True)
    y16, s16 = batch_norm(jnp.asarray(x32, jnp.bfloat16), stats, train=True)
    assert y16.dtype == jnp.bfloat16
    assert s16.mean.dtype == jnp.float32 and s16.var.dtype == jnp.float32
    # Folded bf16 path tracks the exact f32 path to bf16 resolution.
    np.testing.assert_allclose(
        np.asarray(y16, dtype=np.float32), np.asarray(y32), atol=0.05
    )
    np.testing.assert_allclose(
        np.asarray(s16.mean), np.asarray(s32.mean), rtol=1e-2, atol=1e-3
    )
