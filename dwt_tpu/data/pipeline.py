"""Checkpointable multi-stream data plane: per-stream cursors, exact
mid-epoch seek, and an ordered-reassembly worker pipeline.

The training loops consume several zipped streams (source, target, and
— for OfficeHome — the target-augmented view riding the same target
iterator).  Before this module, resume was epoch-granular: the loops
reconstructed ``start_epoch = step // steps_per_epoch`` and dropped the
within-epoch position, so a preempted or rolled-back run replayed or
skipped batches and the fixed-seed reproducibility promise broke across
every restart.  This module closes that gap:

* :class:`DataPlane` — ONE per-run authority over every stream's seed
  lineage and position.  Each stream's epoch order is a
  :class:`~dwt_tpu.data.sampler.SeekableSampler` permutation (a pure
  function of ``(seed + seed_bump, epoch)``), each stream's position a
  ``(epoch, batch_cursor)`` pair that advances in lockstep with the
  optimizer step, and :meth:`DataPlane.snapshot` is the explicit
  ``DataState`` that travels inside every checkpoint
  (``utils/checkpoint.py`` manifests, all three formats).  Resume and
  guard rollback call :meth:`load_snapshot`/:meth:`seek_step` and
  re-open all streams at the exact batch cursor — producing the
  bitwise-identical remaining batch-id sequence a never-killed run
  would have seen (the per-item seed tokens ``(seed, epoch, index)``
  already make transforms deterministic, so this closes the last
  nondeterminism).
* :class:`OrderedWorkerPool` — the decode/augment worker pool rebuilt
  as an ordered-reassembly pipeline: a bounded in-flight window keyed
  by global item position, head-of-window stall *detection* (a dead or
  wedged worker logs, bumps ``dwt_data_stalls_total``, and is
  speculatively re-submitted — ``dwt_data_worker_respawns_total`` —
  instead of silently wedging the epoch; an unrecoverable stall
  starves the step boundary and the hang watchdog's all-thread dump
  names the ``dwt-data`` worker it is stuck on), and live
  instrumentation: ``dwt_data_pipeline_depth`` / ``dwt_data_worker_busy``
  gauges and the ``dwt_data_decode_ms`` histogram, plus ``reassembly``
  spans beside the prefetch thread's existing ``batch_build`` ones so
  ``tools/obs_report.py`` attributes data-plane time.
* **batch-id trail** — ``DWT_DATA_TRAIL=<dir>`` appends one JSONL line
  per *produced* batch (``{role, epoch, cursor, ids}``) per stream; the
  chaos tests diff these trails against an uninterrupted golden run to
  prove the exact-resume contract from outside the process.

Multi-host: the per-process split stays ``batch_iterator``'s
``shard=(index, count)`` slice (derived from the run's ShardingPlan
process topology by the loops), and the plane preserves its two
collective invariants — epochs truncate to a multiple of
``count * batch_size`` so every process yields the SAME batch count,
and quarantined items are *substituted*, never dropped, so those counts
(and therefore stream positions as functions of the global step) stay
fixed for the life of the run.
"""

from __future__ import annotations

import collections
import json
import logging
import os
import queue
import threading
import time
from concurrent.futures import FIRST_COMPLETED, Future, wait
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterator, Optional, Tuple

log = logging.getLogger(__name__)

# DataState schema version: bump if the JSON shape or the sampler's
# position function (FEISTEL_ROUNDS, key derivation) ever changes — a
# mismatched version restores via the epoch-boundary fallback instead of
# silently seeking into a different permutation.
DATA_STATE_VERSION = 1

# Batch-id trail hook (chaos/e2e proof): a directory to append one JSONL
# line per produced batch per stream.  Off (None/empty) in production.
TRAIL_ENV = "DWT_DATA_TRAIL"

# Default head-of-window stall budget: generous enough for a cold NFS
# read, small enough that a genuinely dead worker is detected within one
# watchdog period at the default timeouts.
DEFAULT_STALL_TIMEOUT_S = 60.0


# ---------------------------------------------------------------- DataState


@dataclass
class StreamPos:
    """One stream's seed lineage + position (the per-stream DataState)."""

    seed: int            # base shuffle seed (rollback bump recorded apart)
    epoch_len: int       # batches per epoch, per process (FIXED — module doc)
    epoch: int = 0
    cursor: int = 0      # batches already consumed within ``epoch``
    quarantine_subs: int = 0  # quarantine substitutions since run start
    alias_of: Optional[str] = None  # e.g. target_aug rides target's iterator

    def advance(self, n: int) -> None:
        self.cursor += int(n)
        while self.epoch_len > 0 and self.cursor >= self.epoch_len:
            self.cursor -= self.epoch_len
            self.epoch += 1

    def seek_step(self, consumed: int) -> None:
        """Position after ``consumed`` total batches from (0, 0) — exact
        because epoch lengths are fixed (substitution semantics)."""
        consumed = max(0, int(consumed))
        if self.epoch_len > 0:
            self.epoch, self.cursor = divmod(consumed, self.epoch_len)
        else:
            self.epoch, self.cursor = 0, 0


class DataPlane:
    """Per-run stream-state authority (module doc).

    ``register`` each stream role once, ``advance`` at every step
    boundary (all streams consume one batch per optimizer step — the
    zipped iteration both CLIs run), ``snapshot`` at every checkpoint,
    and ``load_snapshot``/``seek_step`` before re-opening streams on
    resume or rollback.  Iterators come from :meth:`epoch_iterator`
    (epoch-scoped; digits) or :meth:`stream` (infinite with epoch
    rollover; officehome) and always start at the plane's current
    position for their role.
    """

    def __init__(self, *, shard: Optional[Tuple[int, int]] = None,
                 num_workers: int = 0,
                 stall_timeout: float = DEFAULT_STALL_TIMEOUT_S,
                 quarantine_registry=None, seed_bump: int = 0):
        self.streams: Dict[str, StreamPos] = {}
        self.shard = shard
        self.num_workers = int(num_workers)
        self.stall_timeout = float(stall_timeout)
        self.quarantine_registry = quarantine_registry
        self.seed_bump = int(seed_bump)
        self._trail_dir = os.environ.get(TRAIL_ENV) or None

    # -------------------------------------------------------- registration

    def register(self, role: str, seed: int, epoch_len: int,
                 alias_of: Optional[str] = None) -> None:
        """Declare one stream.  ``alias_of`` records a derived view (the
        OfficeHome target-augmented stream) that consumes the SAME
        iterator as its parent: it appears in the DataState (its seek
        semantics are the parent's) but opens no iterator of its own."""
        self.streams[role] = StreamPos(
            seed=int(seed), epoch_len=int(epoch_len), alias_of=alias_of
        )

    # ------------------------------------------------------------ position

    def advance(self, n: int = 1) -> None:
        for pos in self.streams.values():
            pos.advance(n)

    def seek_step(self, consumed: int) -> None:
        for pos in self.streams.values():
            pos.seek_step(consumed)

    def seek_epoch(self, epoch: int) -> None:
        """Epoch-boundary position (cursor 0) — the legacy-resume
        fallback for checkpoints without a usable data_state."""
        for pos in self.streams.values():
            pos.epoch = max(0, int(epoch))
            pos.cursor = 0

    def note_substitution(self, role: str) -> None:
        pos = self.streams.get(role)
        if pos is not None:
            pos.quarantine_subs += 1
            if pos.alias_of is None:
                for other in self.streams.values():
                    if other.alias_of == role:
                        other.quarantine_subs += 1

    # ------------------------------------------------------------ snapshot

    def snapshot(self) -> dict:
        """The JSON-ready DataState written into checkpoint manifests."""
        return {
            "version": DATA_STATE_VERSION,
            "seed_bump": int(self.seed_bump),
            "streams": {
                role: {
                    "seed": pos.seed,
                    "epoch_len": pos.epoch_len,
                    "epoch": pos.epoch,
                    "cursor": pos.cursor,
                    "quarantine_subs": pos.quarantine_subs,
                    **({"alias_of": pos.alias_of} if pos.alias_of else {}),
                }
                for role, pos in self.streams.items()
            },
        }

    def load_snapshot(self, state: Optional[dict]) -> bool:
        """Adopt a checkpoint's DataState; False when it cannot be used
        (absent, wrong version, mismatched streams/epoch lengths) — the
        caller then takes the logged epoch-boundary fallback.

        An ``epoch_len`` mismatch means the dataset (or batch/shard
        geometry) changed since the save: the recorded cursor indexes a
        different permutation, so seeking with it would *silently* train
        a wrong-but-plausible order — exactly what this refuses.
        """
        if not isinstance(state, dict):
            return False
        if state.get("version") != DATA_STATE_VERSION:
            log.warning(
                "checkpoint data_state version %r != %d; falling back to "
                "epoch-boundary resume", state.get("version"),
                DATA_STATE_VERSION,
            )
            return False
        streams = state.get("streams")
        if not isinstance(streams, dict) or set(streams) != set(self.streams):
            log.warning(
                "checkpoint data_state streams %s do not match this run's "
                "%s; falling back to epoch-boundary resume",
                sorted(streams or ()), sorted(self.streams),
            )
            return False
        for role, rec in streams.items():
            pos = self.streams[role]
            if int(rec.get("epoch_len", -1)) != pos.epoch_len:
                log.warning(
                    "checkpoint data_state %s epoch_len %s != this run's %d "
                    "(dataset/batch/shard geometry changed); falling back "
                    "to epoch-boundary resume", role, rec.get("epoch_len"),
                    pos.epoch_len,
                )
                return False
            if int(rec.get("seed", pos.seed)) != pos.seed:
                # Same hazard as a geometry change: the recorded cursor
                # indexes a permutation keyed by a DIFFERENT seed, so
                # seeking with it would silently skip/repeat items while
                # claiming an exact resume.
                log.warning(
                    "checkpoint data_state %s seed %s != this run's %d "
                    "(--seed changed since the save); falling back to "
                    "epoch-boundary resume", role, rec.get("seed"),
                    pos.seed,
                )
                return False
        for role, rec in streams.items():
            pos = self.streams[role]
            pos.epoch = int(rec.get("epoch", 0))
            pos.cursor = int(rec.get("cursor", 0))
            pos.quarantine_subs = int(rec.get("quarantine_subs", 0))
            pos.advance(0)  # normalize a cursor saved exactly at epoch end
        self.seed_bump = int(state.get("seed_bump", 0))
        return True

    # ----------------------------------------------------------- iterators

    def _effective_seed(self, role: str) -> int:
        return self.streams[role].seed + self.seed_bump

    def _trail_writer(self, role: str, epoch: int, start: int):
        """Per-iterator batch-id trail hook (None when disabled)."""
        if not self._trail_dir:
            return None
        os.makedirs(self._trail_dir, exist_ok=True)
        path = os.path.join(self._trail_dir, f"{role}.jsonl")
        cursor = [int(start)]

        def on_batch_ids(ids) -> None:
            with open(path, "a") as f:
                f.write(json.dumps({
                    "role": role, "epoch": int(epoch),
                    "cursor": cursor[0], "ids": [int(i) for i in ids],
                }) + "\n")
            cursor[0] += 1

        return on_batch_ids

    def epoch_iterator(self, dataset, role: str, batch_size: int, *,
                       epoch: Optional[int] = None,
                       start_batch: Optional[int] = None) -> Iterator:
        """One epoch's batches for ``role``, starting at the plane's
        current cursor (or an explicit ``epoch``/``start_batch``)."""
        from dwt_tpu.data.loader import batch_iterator

        pos = self.streams[role]
        epoch = pos.epoch if epoch is None else int(epoch)
        start = pos.cursor if start_batch is None else int(start_batch)
        return batch_iterator(
            dataset, batch_size, shuffle=True,
            seed=self._effective_seed(role), epoch=epoch,
            shard=self.shard, num_workers=self.num_workers,
            quarantine_registry=self.quarantine_registry,
            quarantine_key=role, start_batch=start, substitute=True,
            on_batch_ids=self._trail_writer(role, epoch, start),
            on_substitute=lambda: self.note_substitution(role),
            stall_timeout=self.stall_timeout,
        )

    def stream(self, dataset, role: str, batch_size: int) -> Iterator:
        """Infinite stream for ``role``: epoch rollover with the epoch
        counter advancing forever, the first epoch opened at the plane's
        current ``(epoch, cursor)`` — the exact-resume twin of
        ``loader.infinite``."""
        pos = self.streams[role]

        def gen():
            epoch, start = pos.epoch, pos.cursor
            while True:
                yielded = False
                for item in self.epoch_iterator(
                    dataset, role, batch_size, epoch=epoch, start_batch=start
                ):
                    yielded = True
                    yield item
                if not yielded and start == 0:
                    raise RuntimeError(
                        f"stream {role!r}: epoch {epoch} yielded nothing"
                    )
                epoch += 1
                start = 0

        return gen()


# ------------------------------------------------- ordered worker pipeline


_metrics_lock = threading.Lock()
_metrics = None


def _pool_metrics():
    """Lazy singleton of the pool's live-registry instruments."""
    global _metrics
    if _metrics is None:
        with _metrics_lock:
            if _metrics is None:
                from dwt_tpu.obs.registry import get_registry

                reg = get_registry()
                _metrics = (
                    reg.gauge(
                        "dwt_data_pipeline_depth",
                        "in-flight items in the ordered-reassembly window",
                    ),
                    reg.gauge(
                        "dwt_data_worker_busy",
                        "data worker threads currently decoding",
                    ),
                    reg.histogram(
                        "dwt_data_decode_ms",
                        "per-item decode+augment wall time (worker thread)",
                    ),
                    reg.counter(
                        "dwt_data_stalls_total",
                        "head-of-window stall detections (dead/slow worker)",
                    ),
                    reg.counter(
                        "dwt_data_worker_respawns_total",
                        "speculative re-submissions after a stalled item",
                    ),
                )
    return _metrics


class _SharedLevel:
    """Process-wide level behind a gauge.  The busy/depth gauges are
    process-global but several pools run concurrently (both train loops
    zip a source and a target stream, each with its own pool): per-pool
    ``set()`` would be last-writer-wins, under-reporting to whichever
    pool wrote last.  Contributions aggregate here instead."""

    def __init__(self):
        self._lock = threading.Lock()
        self._total = 0

    def add(self, delta: int, gauge) -> int:
        with self._lock:
            self._total += int(delta)
            gauge.set(self._total)
            return self._total


_BUSY_LEVEL = _SharedLevel()
_DEPTH_LEVEL = _SharedLevel()


class OrderedWorkerPool:
    """Order-preserving decode pool with a bounded window and stall
    detection (module doc).

    :meth:`imap` maps ``fn`` over ``items`` on ``num_workers`` threads,
    yielding results in submission order.  The in-flight window is
    bounded (memory stays proportional to the pool), and the wait on the
    head-of-window item is *watched*: past ``stall_timeout`` seconds the
    item is logged, counted, and speculatively re-submitted to a fresh
    worker (first completion wins — item loads are deterministic under
    their seed tokens, so either result is bitwise the same).  A worker
    that died mid-item therefore costs one timeout, not the epoch.
    """

    def __init__(self, num_workers: int,
                 stall_timeout: float = DEFAULT_STALL_TIMEOUT_S,
                 name: str = "dwt-data"):
        self.num_workers = max(1, int(num_workers))
        self.stall_timeout = float(stall_timeout)
        self.name = name
        self._busy = 0
        self._busy_lock = threading.Lock()

    def _wrap(self, fn: Callable, arg) -> Any:
        _, busy_g, decode_h, _, _ = _pool_metrics()
        with self._busy_lock:
            self._busy += 1  # per-pool count (the stall log message)
        _BUSY_LEVEL.add(1, busy_g)
        t0 = time.perf_counter()
        try:
            return fn(arg)
        finally:
            decode_h.observe((time.perf_counter() - t0) * 1e3)
            with self._busy_lock:
                self._busy -= 1
            _BUSY_LEVEL.add(-1, busy_g)

    def _run_future(self, fn: Callable, arg, fut: Future) -> None:
        if not fut.set_running_or_notify_cancel():
            return
        try:
            fut.set_result(self._wrap(fn, arg))
        except BaseException as e:
            fut.set_exception(e)

    def _respawn(self, fn: Callable, arg) -> Future:
        """Run one stalled item on a dedicated FRESH daemon thread —
        guaranteed to make progress even when every pool worker is
        wedged (the dead-worker recovery path)."""
        fut: Future = Future()
        threading.Thread(
            target=self._run_future, args=(fn, arg, fut),
            name=f"{self.name}-respawn", daemon=True,
        ).start()
        return fut

    @staticmethod
    def _pick_done(done) -> Any:
        """First COMPLETION wins: when the wedged original and its
        respawn land in the same wake, prefer an attempt that produced a
        result — re-raising the loser's exception while a bitwise-good
        result sits beside it would turn a recovered stall into a dead
        epoch.  All-failed raises the first exception as before."""
        ok = [f for f in done if f.exception() is None]
        return (ok[0] if ok else next(iter(done))).result()

    def _await_head(self, fn, arg, futures, spawn_worker) -> Any:
        """Wait for the head-of-window item; detect + recover stalls.

        ``futures`` is the set of attempts for THIS item (grows by one
        per respawn).  A stall recovers along TWO axes: the item itself
        is re-submitted to a dedicated fresh thread, and
        ``spawn_worker`` adds a replacement POOL worker draining the
        shared queue — the wedged worker's lost capacity is restored, so
        a dead worker costs one timeout, not one timeout per remaining
        item.  Only one respawn per item: an item that stalls its
        replacement too is genuinely wedged, and from there the periodic
        warnings plus the starved step boundary (no heartbeat → hang
        watchdog, whose all-thread dump shows the stuck ``dwt-data``
        worker) are the surfacing.  The ``reassembly`` span covers the
        post-detection wait itself, so a trace attributes the stall time
        to the data plane instead of the unattributed residual.
        """
        from dwt_tpu import obs

        _, _, _, stall_c, respawn_c = _pool_metrics()
        done, _ = wait(futures, timeout=self.stall_timeout,
                       return_when=FIRST_COMPLETED)
        if done:  # fast path: no stall, no span
            return self._pick_done(done)
        waited = self.stall_timeout
        respawned = False
        with obs.span("reassembly", "data", stalled_item=str(arg)):
            while True:
                stall_c.inc()
                log.warning(
                    "data pipeline stalled %.1fs waiting for item %r "
                    "(dead or wedged %s worker; %d busy)",
                    waited, arg, self.name, self._busy,
                )
                if not respawned:
                    futures = set(futures)
                    futures.add(self._respawn(fn, arg))
                    # Restore the (presumed-wedged) worker's capacity —
                    # capped: a cold-storage epoch of merely-SLOW items
                    # trips detection per item, and uncapped replacements
                    # would grow the pool without bound for the rest of
                    # the epoch.  Past the cap the one-shot respawn above
                    # still guarantees per-item progress.
                    spawn_worker(cap=3 * self.num_workers)
                    respawn_c.inc()
                    respawned = True
                done, _ = wait(futures, timeout=self.stall_timeout,
                               return_when=FIRST_COMPLETED)
                if done:
                    return self._pick_done(done)
                waited += self.stall_timeout

    def imap(self, fn: Callable, items) -> Iterator:
        """Ordered map of ``fn`` over ``items`` on the worker pool.

        The pool is built of DAEMON threads (a hand-rolled queue, not
        ``ThreadPoolExecutor``): a genuinely dead worker — the very
        fault this pipeline detects — must not block interpreter exit
        through concurrent.futures' atexit join.  Orderly teardown still
        happens (``stop`` drains the live workers within one poll tick);
        only a wedged thread is abandoned, exactly like an abandoned
        prefetch producer.
        """
        depth_g = _pool_metrics()[0]
        window = max(2 * self.num_workers, 8)
        it = iter(items)
        tasks: "queue.SimpleQueue" = queue.SimpleQueue()
        stop = threading.Event()
        spawned = [0]

        def worker():
            # Shutdown is the polled ``stop`` flag alone (no queue
            # sentinel): a wedged worker can't be told anything anyway,
            # and live ones exit within one poll tick.
            while not stop.is_set():
                try:
                    task = tasks.get(timeout=0.2)
                except queue.Empty:
                    continue
                self._run_future(fn, task[0], task[1])

        def spawn_worker(cap: Optional[int] = None):
            k = spawned[0]
            if cap is not None and k >= cap:
                return
            spawned[0] += 1
            threading.Thread(
                target=worker, name=f"{self.name}-{k}", daemon=True
            ).start()

        for _ in range(self.num_workers):
            spawn_worker()

        def submit(arg) -> Future:
            fut: Future = Future()
            tasks.put((arg, fut))
            return fut

        watched = self.stall_timeout > 0
        depth_contrib = 0  # this pool's share of the global depth gauge
        try:
            pending: "collections.deque" = collections.deque()
            for arg in it:
                pending.append((arg, submit(arg)))
                if len(pending) >= window:
                    break
            while pending:
                arg, fut = pending.popleft()
                _DEPTH_LEVEL.add(len(pending) - depth_contrib, depth_g)
                depth_contrib = len(pending)
                if watched:
                    item = self._await_head(fn, arg, {fut}, spawn_worker)
                else:
                    item = fut.result()
                for arg2 in it:  # top the window back up
                    pending.append((arg2, submit(arg2)))
                    break
                yield item
        finally:
            stop.set()
            _DEPTH_LEVEL.add(-depth_contrib, depth_g)
