"""Checkpoint watcher: candidate-version events off the ranked walk.

The training loop's save path already guarantees that a checkpoint
directory is either finalized-and-valid or invisible (``.tmp-*`` writes
+ atomic rename + manifest/size validation — ``utils.checkpoint``).  The
watcher therefore needs no coordination with the writer at all: polling
:func:`~dwt_tpu.utils.checkpoint.ranked_checkpoints` sees exactly the
finalized steps, in all three on-disk formats, with unpromoted
host-shard/delta steps and torn Orbax writes excluded by construction —
a ``cas_delta`` step (ISSUE-13) is a candidate only once its whole
parent chain and every referenced blob validate, so the fleet can never
deploy a delta the restore walk would refuse.  A candidate event is
"the newest valid step changed": step + manifest params digest (the
delta manifests record the same whole-params digest), which together
are the version identity the whole fleet speaks — the dedup key is
unchanged, and a delta save whose digest moved IS a new candidate
(:class:`~dwt_tpu.serve.engine.Version`).
"""

from __future__ import annotations

import logging
import os
import threading
from dataclasses import dataclass
from typing import Callable, Optional

from dwt_tpu.utils.checkpoint import MANIFEST, _read_manifest, ranked_checkpoints

log = logging.getLogger(__name__)


@dataclass(frozen=True)
class Candidate:
    """One finalized checkpoint proposed for deployment."""

    step: int
    digest: Optional[str]  # manifest params_digest (None: legacy artifact)
    path: str              # the step directory (restore_tree input)
    source: str            # "checkpoint" | "anchor"

    @property
    def key(self):
        """Version identity: a re-saved step with different params is a
        DIFFERENT candidate (the digest moves), a re-poll of the same
        artifact is not."""
        return (self.step, self.digest)


def newest_candidate(ckpt_dir: str) -> Optional[Candidate]:
    """The newest valid checkpoint under ``ckpt_dir`` (main + anchors,
    both formats) as a :class:`Candidate`, or None.  One validity walk —
    the same ranking every restore path uses, so the fleet can never
    deploy a step that resume would refuse."""
    for step, _, source, d in ranked_checkpoints(ckpt_dir):
        path = os.path.join(
            os.path.abspath(os.path.expanduser(d)), str(step)
        )
        manifest = _read_manifest(path)
        if manifest is None and os.path.exists(
                os.path.join(path, MANIFEST)):
            # Manifest present but unreadable: ranked_checkpoints would
            # not have listed it; defensive skip for the race where it
            # was torn between the walk and this read.
            continue
        digest = (manifest or {}).get("params_digest")
        return Candidate(step=int(step), digest=digest, path=path,
                         source=source)
    return None


class CheckpointWatcher:
    """Daemon polling ``ckpt_dir`` and emitting candidate events.

    Two forms share one core:

    * ``poll_once()`` — pure pull: the newest candidate if its version
      identity differs from the last one returned (the reloader's loop
      calls this; trivially unit-testable, no thread, no sleeps);
    * ``start(callback)`` / ``stop()`` — the daemon form: a thread polls
      every ``poll_s`` and invokes ``callback(candidate)`` on change.

    The watcher dedups on ``(step, digest)``, so a torn poll can never
    emit the same artifact twice, while a same-step re-save (digest
    moved) IS a new candidate.
    """

    def __init__(self, ckpt_dir: str, poll_s: float = 2.0):
        self.ckpt_dir = ckpt_dir
        self.poll_s = float(poll_s)
        self._last_key = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def prime(self, candidate: Optional[Candidate]) -> None:
        """Mark ``candidate`` as already deployed so the first poll does
        not re-emit the version the server just loaded at startup."""
        self._last_key = candidate.key if candidate else None

    def poll_once(self) -> Optional[Candidate]:
        try:
            cand = newest_candidate(self.ckpt_dir)
        except OSError as e:  # transient fs hiccup: poll again later
            log.warning("checkpoint watch poll failed: %s", e)
            return None
        if cand is None or cand.key == self._last_key:
            return None
        self._last_key = cand.key
        return cand

    # ------------------------------------------------------------ daemon

    def start(self, callback: Callable[[Candidate], None]) -> None:
        if self._thread is not None:
            raise RuntimeError("watcher already started")

        def _run():
            while not self._stop.wait(self.poll_s):
                cand = self.poll_once()
                if cand is not None:
                    try:
                        callback(cand)
                    except Exception:
                        log.exception(
                            "checkpoint watcher callback failed for "
                            "step %s", cand.step,
                        )

        self._thread = threading.Thread(
            target=_run, name="dwt-ckpt-watcher", daemon=True
        )
        self._thread.start()

    def stop(self, timeout: float = 10.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
