"""Per-request serving metrics: JSONL access records + latency summary.

Every served (or shed) request produces ONE access record — the serving
twin of the training loops' metric stream.  Records are machine-parseable
JSON lines so the same tooling that reads training JSONL reads access
logs, and the aggregate view (p50/p95/p99 latency, imgs/s, shed rate)
is computed with the shared nearest-rank percentile helper in
``dwt_tpu.utils.metrics`` — one percentile definition across training,
eval, consensus, and serving reports.

Access-record schema (all times milliseconds)::

    {"kind": "access", "status": "ok" | "shed" | "error",
     "bucket": 8,          # compiled bucket the batch dispatched into
     "batch_n": 8,         # padded batch size (== bucket)
     "real_n": 5,          # un-padded samples in the batch
     "n": 1,               # samples in THIS request
     "queue_ms": 1.9,      # enqueue -> dispatch (admission + coalescing)
     "device_ms": 3.1,     # H2D-staged dispatch -> logits fetched
     "e2e_ms": 5.4,        # enqueue -> response ready
     "retry_after_ms": 50} # shed responses only

``queue_ms``/``device_ms`` are batch-level quantities stamped onto every
request that rode the batch; ``e2e_ms`` is per-request.
"""

from __future__ import annotations

import collections
import json
import logging
import threading
import time
from typing import IO, Optional

from dwt_tpu.utils.metrics import percentile_summary

log = logging.getLogger(__name__)

# Aggregation window: enough for a long sustained-load run's tail to be
# measured honestly without unbounded memory on a server that stays up
# for days.
_WINDOW = 100_000


class AccessLog:
    """Thread-safe access-record sink: optional JSONL file + aggregates.

    The dispatcher and front-end threads both write here; a lock (not a
    queue) suffices because records are tiny and the file write is the
    only I/O.  ``jsonl_path=None`` keeps aggregation only (the in-process
    client and the bench use the aggregates; the CLI server also writes
    the file).
    """

    def __init__(self, jsonl_path: Optional[str] = None,
                 stream: Optional[IO] = None):
        self._lock = threading.Lock()
        self._file = open(jsonl_path, "a") if jsonl_path else None
        self._stream = stream
        self._t0 = time.perf_counter()
        self.served_requests = 0
        self.served_imgs = 0
        self.shed_requests = 0
        self.error_requests = 0
        self._e2e_ms = collections.deque(maxlen=_WINDOW)
        self._queue_ms = collections.deque(maxlen=_WINDOW)
        self._device_ms = collections.deque(maxlen=_WINDOW)
        self._write_failed = False  # warn once, not per record

    def record(self, status: str, n: int, **fields) -> None:
        rec = {"kind": "access", "status": status, "n": int(n), **{
            k: (round(float(v), 3) if isinstance(v, float) else v)
            for k, v in fields.items()
        }}
        with self._lock:
            if status == "ok":
                self.served_requests += 1
                self.served_imgs += int(n)
                if "e2e_ms" in fields:
                    self._e2e_ms.append(float(fields["e2e_ms"]))
                if "queue_ms" in fields:
                    self._queue_ms.append(float(fields["queue_ms"]))
                if "device_ms" in fields:
                    self._device_ms.append(float(fields["device_ms"]))
            elif status == "shed":
                self.shed_requests += 1
            else:
                self.error_requests += 1
            # Logging is availability-decoupled: record() runs on the
            # dispatcher thread, and a full disk must degrade to lost
            # access records — not to a dead dispatcher that sheds all
            # traffic while inference itself is healthy.
            line = json.dumps(rec) + "\n"
            for sink in (self._file, self._stream):
                if sink is not None:
                    try:
                        sink.write(line)
                    except (OSError, ValueError) as e:
                        if not self._write_failed:
                            self._write_failed = True
                            log.warning(
                                "access-log write failed (%s); further "
                                "records may be lost", e,
                            )

    def summary(self) -> dict:
        """Aggregate view over the run (latencies over the bounded
        window): the /stats response body and the drain-time footer."""
        # Snapshot under the lock, sort/aggregate OUTSIDE it: summary()
        # is a /stats poll, and the dispatcher's record() must not queue
        # behind O(window log window) percentile math on the hot path.
        with self._lock:
            seconds = time.perf_counter() - self._t0
            out = {
                "kind": "serve_summary",
                "served_requests": self.served_requests,
                "served_imgs": self.served_imgs,
                "shed_requests": self.shed_requests,
                "error_requests": self.error_requests,
                "seconds": round(seconds, 3),
                "imgs_per_s": round(
                    self.served_imgs / max(seconds, 1e-9), 1
                ),
            }
            windows = [
                ("e2e_ms", list(self._e2e_ms)),
                ("queue_ms", list(self._queue_ms)),
                ("device_ms", list(self._device_ms)),
            ]
        for name, window in windows:
            out.update(percentile_summary(
                window, (50.0, 95.0, 99.0), prefix=f"{name}_p"
            ))
        return out

    def windows(self) -> dict:
        """Consistent snapshot of the latency windows plus the lifetime
        served-request count.  The serve bench takes one snapshot before
        and one after each offered-load run and keeps the last
        ``served_after - served_before`` samples of each window — correct
        even after the bounded deques wrap (an index diff would not be),
        so every sweep point reports only its OWN requests' tail."""
        with self._lock:
            return {
                "served_requests": self.served_requests,
                "e2e_ms": list(self._e2e_ms),
                "queue_ms": list(self._queue_ms),
                "device_ms": list(self._device_ms),
            }

    def flush(self) -> None:
        with self._lock:
            if self._file is not None:
                try:
                    self._file.flush()
                except OSError as e:
                    log.warning("access-log flush failed: %s", e)

    def close(self) -> None:
        with self._lock:
            if self._file is not None:
                try:
                    self._file.close()
                except OSError as e:
                    log.warning("access-log close failed: %s", e)
                self._file = None
