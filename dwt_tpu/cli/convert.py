"""Standalone PyTorch→Orbax checkpoint converter.

A reference user arrives with ``model_best_gr_4.pth.tar``
(``/root/reference/README.md:11``); the training CLIs convert it inline at
every start (``--resnet_path``).  This CLI converts ONCE into an Orbax
step-0 artifact that ``--ckpt_dir`` then resumes from directly — the
recommended flow for repeated runs and for hosts without torch installed
(conversion is the only torch dependency in the framework).

Usage::

    dwt-convert --torch_ckpt .../model_best_gr_4.pth.tar \
        --out_dir /ckpts/resnet50_dwt_init [--arch resnet50] \
        [--num_classes 65] [--group_size 4]
"""

from __future__ import annotations

import argparse


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        description="Convert a reference PyTorch DWT checkpoint to an "
        "Orbax training-state artifact"
    )
    p.add_argument("--torch_ckpt", required=True,
                   help="path to model_best_gr_*.pth.tar")
    p.add_argument("--out_dir", required=True,
                   help="Orbax checkpoint dir (written at step 0; pass the "
                        "same path as --ckpt_dir to the training CLI)")
    p.add_argument("--arch", choices=["resnet50", "resnet101"],
                   default="resnet50")
    p.add_argument("--num_classes", type=int, default=65)
    p.add_argument("--group_size", type=int, default=4)
    return p


def convert(args) -> str:
    import jax
    import jax.numpy as jnp

    from dwt_tpu.config import OfficeHomeConfig
    from dwt_tpu.convert import (
        convert_resnet_state_dict,
        load_pytorch_checkpoint,
    )
    from dwt_tpu.nn import ResNetDWT
    from dwt_tpu.train import create_train_state
    from dwt_tpu.train.optim import officehome_tx
    from dwt_tpu.utils import save_state

    # The training loops hardcode the reference's 3 streams (source,
    # target, augmented target); any other value would write an artifact
    # no training CLI can restore.
    num_domains = 3
    model = getattr(ResNetDWT, args.arch)(
        num_classes=args.num_classes,
        group_size=args.group_size,
        num_domains=num_domains,
    )
    # officehome_tx: the SAME optimizer constructor the training loop uses,
    # so the opt-state pytree structure matches the loop's restore template
    # (scheduled lrs carry ScaleByScheduleState; constants would not).
    # Small spatial init: conv/norm/fc param shapes are resolution-free
    # (global average pool), and the init trace is ~10x cheaper than 224².
    sample = jnp.zeros((num_domains, 2, 64, 64, 3), jnp.float32)
    state = create_train_state(
        model, jax.random.key(0), sample, officehome_tx(OfficeHomeConfig())
    )

    sd = load_pytorch_checkpoint(args.torch_ckpt)
    variables = {"params": state.params, "batch_stats": state.batch_stats}
    variables, report = convert_resnet_state_dict(
        sd, variables, num_domains=num_domains
    )
    print(report.summary())
    state = state.replace(
        params=variables["params"], batch_stats=variables["batch_stats"]
    )
    path = save_state(args.out_dir, 0, state)
    print(f"wrote {path}")
    return path


def main(argv=None) -> int:
    convert(build_parser().parse_args(argv))
    return 0  # console-script wrapper calls sys.exit(main())


if __name__ == "__main__":
    main()
