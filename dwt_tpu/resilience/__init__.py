"""dwt_tpu.resilience — keep long preemptible runs alive and honest.

Production TPU training dies more ways than the reference code ever had
to survive: the scheduler preempts a VM (SIGTERM, short grace window —
possibly on ONE host of a pod), the numerics diverge (a Cholesky NaN
poisons every later step), I/O fails half-way (torn checkpoints,
undecodable dataset items), and sometimes nothing happens at all (a
deadlocked collective burning allocation silently).  This package
provides the corresponding defenses, plus deterministic fault injection
(:mod:`~dwt_tpu.resilience.inject`) so every recovery path is provable
in CI on CPU:

* :class:`PreemptionHandler` — flag-only signal handler polled at step
  boundaries; final checkpoint + clean exit 0 on SIGTERM/SIGINT.
* :class:`Coordinator` — multi-host consensus: host-local stop/diverged
  flags are allgathered at every step boundary, so any-host SIGTERM or
  divergence becomes an all-host save/skip/rollback decision instead of
  a hung collective.  Single-process runs short-circuit at zero cost.
* :class:`DivergenceGuard` — amortized jitted finite-checks with an
  escalation ladder: optional ``lr_backoff`` rung (gentle replay via an
  injectable optimizer scale), then ``skip_step`` / ``rollback`` /
  ``halt``.
* :class:`HangWatchdog` — heartbeat-fed stall detector; dumps all-thread
  stacks under ``ckpt_dir/watchdog/`` and exits
  :data:`WATCHDOG_EXIT_CODE` so schedulers relaunch into the resume path.
* :class:`AsyncCheckpointer` — single-in-flight background checkpoint
  pipeline (snapshot → digest → write off the hot path; rendezvous via
  ``flush()`` at preemption/final/rollback/best-record points).
  :class:`MultiHostAsyncCheckpointer` is its collective-free multi-host
  form: host-side snapshot on the main thread, pure-I/O per-process
  shard writes, and process-0 promotion driven by save-done bits on the
  consensus vector.
* :class:`NoticeWatcher` — scheduler preemption-notice polling (GCE
  metadata / notice file); any-host notice → all-host proactive save at
  the next boundary, so the later SIGTERM exits fast.
* atomic validated checkpoints live in :mod:`dwt_tpu.utils.checkpoint`
  (write-to-tmp + rename, per-step manifest, newest-valid fallback);
  retry/quarantine item loading lives in :mod:`dwt_tpu.data.loader`.
"""

from dwt_tpu.resilience import inject
from dwt_tpu.resilience.async_ckpt import (
    AsyncCheckpointer,
    DeltaAsyncCheckpointer,
    MultiHostAsyncCheckpointer,
    MultiHostDeltaAsyncCheckpointer,
    snapshot_state,
)
from dwt_tpu.resilience.coord import Coordinator, Decision
from dwt_tpu.resilience.notice import NoticeWatcher
from dwt_tpu.resilience.guard import (
    POLICIES,
    DivergenceError,
    DivergenceGuard,
    RollbackRequest,
)
from dwt_tpu.resilience.preemption import PreemptionHandler
from dwt_tpu.resilience.watchdog import WATCHDOG_EXIT_CODE, HangWatchdog

__all__ = [
    "AsyncCheckpointer",
    "DeltaAsyncCheckpointer",
    "MultiHostAsyncCheckpointer",
    "MultiHostDeltaAsyncCheckpointer",
    "NoticeWatcher",
    "snapshot_state",
    "Coordinator",
    "Decision",
    "DivergenceError",
    "DivergenceGuard",
    "HangWatchdog",
    "POLICIES",
    "PreemptionHandler",
    "RollbackRequest",
    "WATCHDOG_EXIT_CODE",
    "inject",
]
