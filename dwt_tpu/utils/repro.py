"""Accuracy-reproduction verdicts (BASELINE.json north star: ±0.3%).

The reference validated correctness by the accuracy its ``test()`` printed
(``usps_mnist.py:310-327``, ``resnet50…py:447-464``); this module turns
that into an assertable contract: CLIs take ``--expect_accuracy``/
``--tolerance`` and exit nonzero when the trained model misses the target,
and the sweep compares a whole expectation table (paper Table 3).

Expected values must come from the paper PDF (see ``baselines/``) — they
are intentionally shipped as ``null`` templates, not hardcoded from
memory (SURVEY §6).
"""

from __future__ import annotations

import json
from typing import Dict, Optional


def accuracy_verdict(
    actual: float, expected: float, tolerance: float
) -> dict:
    """One repro check: |actual − expected| ≤ tolerance."""
    delta = actual - expected
    return {
        "expected": float(expected),
        "actual": float(actual),
        "delta": round(float(delta), 4),
        "tolerance": float(tolerance),
        "ok": abs(delta) <= tolerance,
    }


def check_cli_accuracy(
    accuracy: float,
    expect_accuracy: Optional[float],
    tolerance: float,
    logger=None,
) -> bool:
    """CLI plumbing: no-op (True) when no expectation was given; otherwise
    log/print the verdict and return whether it passed."""
    if expect_accuracy is None:
        return True
    verdict = accuracy_verdict(accuracy, expect_accuracy, tolerance)
    if logger is not None:
        logger.log("accuracy_check", 0, **verdict)
    else:  # pragma: no cover - all CLIs pass a logger
        print(f"[accuracy_check] {verdict}")
    return verdict["ok"]


def load_expect_table(path: str) -> Dict[str, Optional[float]]:
    """Load a ``{"Source->Target": acc_or_null}`` expectation table.

    ``null`` entries are allowed (template not yet filled from the paper
    PDF) and are skipped by :func:`sweep_verdicts`.
    """
    with open(path) as f:
        table = json.load(f)
    if not isinstance(table, dict):
        raise ValueError(f"{path}: expectation table must be a JSON object")
    out: Dict[str, Optional[float]] = {}
    for key, value in table.items():
        if key.startswith("_"):  # comment/metadata keys
            continue
        if value is not None and (
            isinstance(value, bool) or not isinstance(value, (int, float))
        ):
            raise ValueError(
                f"{path}: {key!r} must be a number or null, got {value!r}"
            )
        out[key] = None if value is None else float(value)
    return out


def sweep_verdicts(
    results: Dict[str, float],
    expected: Dict[str, Optional[float]],
    tolerance: float,
) -> dict:
    """Verdict table for a sweep: per-pair checks plus the mean.

    Pairs with a ``null`` expectation (or absent from ``expected``) are
    reported as ``skipped``.  Non-null expectations that match NO result
    (typo'd key, subset sweep) are listed under ``unmatched`` and force
    ``all_ok`` to False — a silently dropped expectation must never read
    as "Table 3 reproduced".
    """
    pairs = {}
    checked_ok = []
    for pair, acc in results.items():
        exp = expected.get(pair)
        if exp is None:
            pairs[pair] = {"actual": float(acc), "skipped": True}
            continue
        verdict = accuracy_verdict(acc, exp, tolerance)
        pairs[pair] = verdict
        checked_ok.append(verdict["ok"])
    unmatched = sorted(
        k for k, v in expected.items() if v is not None and k not in results
    )
    mean_actual = sum(results.values()) / max(len(results), 1)
    mean_expected_vals = [v for v in expected.values() if v is not None]
    all_ok = all(checked_ok) if checked_ok else None
    if unmatched:
        all_ok = False
    summary = {
        "pairs": pairs,
        "checked": len(checked_ok),
        "skipped": len(results) - len(checked_ok),
        "unmatched": unmatched,
        "all_ok": all_ok,
        "mean_actual": round(mean_actual, 4),
    }
    if mean_expected_vals and len(mean_expected_vals) == len(expected):
        summary["mean_expected"] = round(
            sum(mean_expected_vals) / len(mean_expected_vals), 4
        )
    return summary
