"""OfficeHome full 12-pair sweep — BASELINE.json configs[3] (paper Table 3).

The reference has no sweep driver (each of the 12 source→target pairs is a
separate ``resnet50_dwt_mec_officehome.py`` invocation); this CLI runs all
ordered domain pairs with the same recipe and reports the per-pair target
top-1 plus the Table-3-style mean.

Usage::

    python -m dwt_tpu.cli.officehome_sweep \
        --dataset_root .../OfficeHomeDataset_10072016 \
        --resnet_path .../model_best_gr_4.pth.tar \
        --results_json sweep.json

Any OfficeHome flag applies to every pair (``--num_iters``, ``--remat``,
``--data_parallel``, ...).  ``--synthetic`` sweeps generated data — a
no-dataset smoke of the whole matrix.
"""

from __future__ import annotations

import argparse
import itertools
import json
import os

from dwt_tpu.cli import officehome as _oh


def build_parser() -> argparse.ArgumentParser:
    p = _oh.build_parser()
    p.description = "dwt_tpu DWT-MEC OfficeHome 12-pair sweep"
    p.add_argument("--dataset_root", type=str, default=None,
                   help="OfficeHomeDataset root containing the domain dirs")
    p.add_argument("--domains", type=str,
                   default="Art,Clipart,Product,RealWorld",
                   help="comma-separated domain dir names")
    p.add_argument("--pairs", type=str, default=None,
                   help='subset like "Art:Clipart,Product:Art" '
                        "(default: all ordered pairs)")
    p.add_argument("--results_json", type=str, default=None)
    p.add_argument("--expect_table", type=str, default=None,
                   help='JSON {"Source->Target": acc_or_null} of paper '
                        "Table-3 targets (see baselines/); after the sweep "
                        "a per-pair ±tolerance verdict table is produced "
                        "and the exit code reflects it")
    return p


def _pairs(args):
    domains = [d.strip() for d in args.domains.split(",") if d.strip()]
    if args.pairs:
        pairs = []
        for item in args.pairs.split(","):
            item = item.strip()
            if not item:
                continue
            if ":" not in item:
                raise SystemExit(
                    f'--pairs entries must be "Source:Target"; got {item!r}'
                )
            s, t = item.split(":", 1)
            pairs.append((s.strip(), t.strip()))
        return pairs
    return [(s, t) for s, t in itertools.permutations(domains, 2)]


def main(argv=None) -> float:
    args = build_parser().parse_args(argv)
    if not args.synthetic and not args.dataset_root:
        raise SystemExit("--dataset_root is required unless --synthetic")

    if getattr(args, "expect_accuracy", None) is not None:
        # One value cannot assert 12 different pairs; refusing beats
        # silently dropping the user's assertion.
        raise SystemExit(
            "--expect_accuracy is a single-run flag; the sweep takes "
            "per-pair targets via --expect_table (see baselines/)"
        )
    expected = None
    if args.expect_table:
        from dwt_tpu.utils import load_expect_table

        expected = load_expect_table(args.expect_table)

    pairs = _pairs(args)
    if expected is not None:
        # Fail fast on typo'd table keys before hours of training: every
        # non-null expectation must correspond to a planned pair.
        planned = {f"{s}->{t}" for s, t in pairs}
        unknown = sorted(
            k for k, v in expected.items()
            if v is not None and k not in planned
        )
        if unknown:
            raise SystemExit(
                f"--expect_table entries match no planned pair: {unknown} "
                f"(planned: {sorted(planned)})"
            )
    if len(set(pairs)) != len(pairs):
        raise SystemExit(f"--pairs contains duplicates: {pairs}")
    if args.dataset_root:
        # Fail fast on typo'd domain names before any pair trains.
        missing = [
            d for pair in pairs for d in pair
            if not os.path.isdir(os.path.join(args.dataset_root, d))
        ]
        if missing:
            raise SystemExit(
                f"domain dirs not found under {args.dataset_root}: "
                f"{sorted(set(missing))}"
            )

    results = {}
    base_ckpt = args.ckpt_dir
    base_jsonl = args.metrics_jsonl

    def _payload(**extra):
        return {
            "pairs": results,
            "mean": sum(results.values()) / max(len(results), 1),
            "completed": len(results),
            "total": len(pairs),
            **extra,
        }

    def _write_results(**extra):
        # Atomic AND durable (tmp + fsync + rename): the sweep
        # supervisor treats this file as the pair's completion record —
        # an un-fsynced rename surviving a host crash as a zero-byte
        # file would erase a finished pair's result.
        tmp = args.results_json + ".tmp"
        with open(tmp, "w") as f:
            json.dump(_payload(**extra), f, indent=2)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, args.results_json)

    for source, target in pairs:
        tag = f"{source}2{target}"
        if args.dataset_root:
            args.s_dset_path = os.path.join(args.dataset_root, source)
            args.t_dset_path = os.path.join(args.dataset_root, target)
        if base_ckpt:
            args.ckpt_dir = os.path.join(base_ckpt, tag)
        if base_jsonl:
            # One metrics file per pair — records from different pairs are
            # otherwise indistinguishable (step counters restart per pair).
            root, ext = os.path.splitext(base_jsonl)
            args.metrics_jsonl = f"{root}.{tag}{ext or '.jsonl'}"
        acc = _oh.run_from_args(args)
        results[f"{source}->{target}"] = acc
        print(f"[sweep] {source}->{target}: {acc:.2f}")
        if args.results_json:
            # Written atomically after EVERY pair so a crash at any point
            # keeps all completed results.
            _write_results()

    mean = sum(results.values()) / max(len(results), 1)
    print(f"[sweep] mean over {len(results)} pairs: {mean:.2f}")

    if expected is not None:
        from dwt_tpu.utils import sweep_verdicts

        summary = sweep_verdicts(results, expected, args.tolerance)
        for pair, v in summary["pairs"].items():
            if v.get("skipped"):
                print(f"[verdict] {pair}: actual={v['actual']:.2f} "
                      "(no expectation — fill baselines/ from the paper)")
            else:
                status = "OK" if v["ok"] else "FAIL"
                print(f"[verdict] {pair}: actual={v['actual']:.2f} "
                      f"expected={v['expected']:.2f} Δ={v['delta']:+.2f} "
                      f"(±{v['tolerance']}) {status}")
        print(f"[verdict] checked={summary['checked']} "
              f"skipped={summary['skipped']} all_ok={summary['all_ok']}")
        if args.results_json:
            _write_results(verdicts=summary)
        if summary["all_ok"] is False:
            raise SystemExit(1)
    return mean


if __name__ == "__main__":
    main()
