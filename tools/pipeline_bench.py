"""Host input-pipeline microbench: decode+augment imgs/s, no model.

Measures what the host can feed the chip: synthetic JPEGs are written once
to a temp dir, then the OfficeHome dual-view pipeline (resize 256 → crop
224 → hflip → affine → blur → normalize, ``resnet50…py:527-543``) is timed
through ``batch_iterator`` at several worker counts.  Compare against the
device roofline in PERF.md (2–3.5k imgs/s/chip for ResNet50-DWT): the
pipeline must meet or beat the device rate or training is host-bound —
the reason ``num_workers`` is a real worker pool, not just queue depth.

Prints one JSON line per worker count:
``{"workers": N, "imgs_per_sec": X, "dual_view": true, ...}``
"""

import argparse
import json
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from dwt_tpu.data import (
    Compose,
    ImageFolderDataset,
    RandomCrop,
    RandomHorizontalFlip,
    Resize,
    ThreadLocalRng,
    batch_iterator,
)

MEAN = [0.485, 0.456, 0.406]
STD = [0.229, 0.224, 0.225]


def write_synthetic_jpegs(root: str, n: int, size: int, classes: int = 2):
    from PIL import Image

    rng = np.random.default_rng(0)
    for i in range(n):
        d = os.path.join(root, f"class_{i % classes}")
        os.makedirs(d, exist_ok=True)
        arr = rng.integers(0, 256, size=(size, size, 3), dtype=np.uint8)
        Image.fromarray(arr).save(
            os.path.join(d, f"img_{i:05d}.jpg"), quality=88
        )


def build_dataset(root: str, resize: int, crop: int, seed: int = 0):
    # Mirrors dwt_tpu.train.loop._officehome_datasets — fused native
    # (C++) pixel tails when available, numpy/cv2 fallback otherwise.
    # A/B the two with DWT_DISABLE_NATIVE=1.
    from dwt_tpu.data import FusedAffineBlurNormalize, FusedToArrayNormalize

    rng = ThreadLocalRng(seed)
    base_tf = Compose(
        [Resize(resize), RandomCrop(crop, rng=rng),
         FusedToArrayNormalize(MEAN, STD)]
    )
    aug_tf = Compose(
        [Resize(resize), RandomCrop(crop, rng=rng),
         RandomHorizontalFlip(rng=rng),
         FusedAffineBlurNormalize(MEAN, STD, rng=rng)]
    )
    return ImageFolderDataset(root, transform=base_tf, transform_aug=aug_tf)


def run(dataset, batch: int, workers: int, min_seconds: float) -> dict:
    # Warm one batch (imports, PIL caches), then time whole epochs until
    # the clock budget is spent.
    next(iter(batch_iterator(dataset, batch, shuffle=False,
                             num_workers=workers)))
    images = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < min_seconds:
        for b in batch_iterator(dataset, batch, shuffle=True, seed=1,
                                epoch=images, num_workers=workers):
            images += b[0].shape[0]
    dt = time.perf_counter() - t0
    return {
        "workers": workers,
        "imgs_per_sec": round(images / dt, 1),
        "dual_view": True,
        "batch": batch,
        "seconds": round(dt, 2),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--images", type=int, default=256,
                    help="synthetic JPEG count")
    ap.add_argument("--jpeg_size", type=int, default=300)
    ap.add_argument("--resize", type=int, default=256)
    ap.add_argument("--crop", type=int, default=224)
    ap.add_argument("--batch", type=int, default=18)
    ap.add_argument("--seconds", type=float, default=5.0,
                    help="min timing window per worker count")
    ap.add_argument("--workers", type=int, nargs="*",
                    default=[1, 2, 4, 8, 16])
    args = ap.parse_args()

    with tempfile.TemporaryDirectory(prefix="dwt_pipe_bench_") as root:
        write_synthetic_jpegs(root, args.images, args.jpeg_size)
        ds = build_dataset(root, args.resize, args.crop)
        for w in args.workers:
            print(json.dumps(run(ds, args.batch, w, args.seconds)),
                  flush=True)


if __name__ == "__main__":
    main()
