"""Pallas TPU kernels for the grouped whitening op (SURVEY §2.2 new-table).

The fused grouped-whitening op mandated "where XLA fusion is insufficient"
(math spec: reference ``utils/whitening.py:37-61``).  PERF.md's cost
analysis found the chain is 1.4% of step FLOPs but touches the largest
activations in the net; the win a hand-fused kernel can offer is HBM
traffic, not compute.  This module implements that design so the go/no-go
can be decided by *measurement* the moment the chip is reachable:

* **Pass 1 — moments** (`_moments_call`): ONE read of ``x`` accumulates
  both the channel sums and the per-group second-moment matrices in VMEM
  f32 accumulators (``cov = E[xxᵀ] − m mᵀ`` instead of the two-pass
  center-then-cov, which would read ``x`` twice — the rewrite XLA will not
  do on its own).
* **Factorization** stays in plain JAX: ``[G, g, g]`` Cholesky + triangular
  solve is microscopic (g=4) and XLA handles it fine.
* **Pass 2 — apply** (`_apply_call`): one read of ``x``, one write of
  ``y = L⁻¹(x − m)`` with the matmul in the activation dtype (bf16 nets ride
  the bf16 MXU path, f32 accumulation).

Total HBM traffic: 2 reads + 1 write of ``x`` vs the XLA path's 3 reads +
1 write (mean pass, cov pass over centered data, apply pass).

Gradients: ``pallas_group_whiten`` is differentiable w.r.t. ``x`` via a
``custom_vjp`` whose backward *recomputes* the pure-JAX forward
(``dwt_tpu.ops.whitening``) and uses its VJP — exact same cotangents as the
XLA path (pinned by tests), at remat-style extra backward FLOPs.  The
hand-derived backward that reuses ``L⁻¹`` (PERF.md sketch) is only worth
building if the measured trace says the chain matters.

Kernels run compiled on TPU and in interpreter mode elsewhere (tests).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from dwt_tpu.ops.whitening import (
    WhiteningStats,
    _resolve_groups,
    _shrink,
    get_whitener,
)

try:  # pallas is TPU-oriented; import lazily-tolerant for exotic builds
    from jax.experimental import pallas as pl

    HAS_PALLAS = True
except ImportError:  # pragma: no cover
    HAS_PALLAS = False

# Rows per grid step. 512 keeps the f32 tile under ~0.5 MB at C=256.
_TILE_M = 512


def _auto_interpret() -> bool:
    return jax.default_backend() != "tpu"


# ------------------------------------------------------------- pass 1


def _moments_kernel(x_ref, s1_ref, s2_ref, *, total_rows, tile_m):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        s1_ref[:] = jnp.zeros_like(s1_ref)
        s2_ref[:] = jnp.zeros_like(s2_ref)

    x = x_ref[:].astype(jnp.float32)
    # Mask rows past the ragged end (the out-of-range tail of the last
    # block reads padding, which must not pollute the sums).
    rows = lax.broadcasted_iota(jnp.int32, (tile_m, 1), 0) + i * tile_m
    x = jnp.where(rows < total_rows, x, 0.0)
    s1_ref[:] += jnp.sum(x, axis=0, keepdims=True)
    # Full xᵀx [C, C] as ONE 2-D dot; _moments_call extracts the per-group
    # diagonal blocks outside the kernel.  Mosaic (this jax line) lowers
    # only 2-D dots — the per-group batched einsum ([G, g, g] directly)
    # is a 3-D dot_general and fails TPU lowering (pinned off-chip by
    # tests/test_pallas_whitening.py::test_kernels_lower_for_tpu_offchip).
    # The off-block products are wasted MXU FLOPs (C/g per useful one),
    # but the op is HBM-bound (PERF.md: 1.4% of step FLOPs) and the full
    # dot keeps the MXU on its native path; the whitened sites' widest C
    # is 256, so the VMEM accumulator stays ≤ 256 KB f32.  HIGHEST
    # precision as in the XLA op's group_cov: statistics feeding a
    # Cholesky must not ride the TPU's default bf16 multiply passes —
    # doubly so here, where E[xxᵀ]−mmᵀ cancels leading bits.
    s2_ref[:] += lax.dot_general(
        x,
        x,
        dimension_numbers=(((0,), (0,)), ((), ())),
        precision=lax.Precision.HIGHEST,
        preferred_element_type=jnp.float32,
    )


def _moments_call(
    x2d: jax.Array, num_groups: int, group_size: int, interpret: bool
) -> Tuple[jax.Array, jax.Array]:
    """(mean [C], biased cov [G, g, g]) in ONE pass over ``x2d [M, C]``."""
    m_rows, c = x2d.shape
    tile_m = min(_TILE_M, max(8, m_rows))
    grid = (pl.cdiv(m_rows, tile_m),)
    kernel = functools.partial(
        _moments_kernel, total_rows=m_rows, tile_m=tile_m
    )
    s1, s2 = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[pl.BlockSpec((tile_m, c), lambda i: (i, 0))],
        out_specs=(
            pl.BlockSpec((1, c), lambda i: (0, 0)),
            pl.BlockSpec((c, c), lambda i: (0, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((1, c), jnp.float32),
            jax.ShapeDtypeStruct((c, c), jnp.float32),
        ),
        interpret=interpret,
    )(x2d)
    mean = s1[0] / m_rows
    # Group-diagonal blocks of the full second-moment matrix — the same
    # sums the per-group einsum produced, reduced by the same f32 dot.
    gi = jnp.arange(num_groups)
    e_xx = (
        s2.reshape(num_groups, group_size, num_groups, group_size)[gi, :, gi, :]
        / m_rows
    )
    mg = mean.reshape(num_groups, group_size)
    cov = e_xx - jnp.einsum("gc,gd->gcd", mg, mg)
    return mean, cov


# ------------------------------------------------------------- pass 2


def _apply_kernel(x_ref, m_ref, w_ref, o_ref, *, compute_dtype):
    x = x_ref[:]
    xn = (x.astype(jnp.float32) - m_ref[:]).astype(compute_dtype)
    # y[m, d] = Σ_c W_bd[d, c] · xn[m, c] with W_bd the block-diagonal
    # whitening matrix: the grouped 1x1 conv (reference whitening.py:55)
    # as ONE 2-D matmul — Mosaic-lowerable (see _moments_kernel) and on
    # the MXU's native path; zeros off the blocks are wasted FLOPs the
    # HBM-bound op never notices.  Matmul in the activation dtype (bf16
    # MXU path), f32 accumulation.
    y = lax.dot_general(
        xn,
        w_ref[:].astype(compute_dtype),
        dimension_numbers=(((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    o_ref[:] = y.astype(o_ref.dtype)


def _apply_call(
    x2d: jax.Array,
    mean: jax.Array,
    w: jax.Array,
    interpret: bool,
) -> jax.Array:
    """``(x − m) @ W_bdᵀ`` with ``w [G, g, g]`` expanded block-diagonal;
    matmul in the activation dtype."""
    from jax.scipy.linalg import block_diag

    m_rows, c = x2d.shape
    tile_m = min(_TILE_M, max(8, m_rows))
    grid = (pl.cdiv(m_rows, tile_m),)
    kernel = functools.partial(_apply_kernel, compute_dtype=x2d.dtype)
    w_bd = block_diag(*w)  # [C, C]; block g at rows/cols g·gs
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((tile_m, c), lambda i: (i, 0)),
            pl.BlockSpec((1, c), lambda i: (0, 0)),
            pl.BlockSpec((c, c), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((tile_m, c), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((m_rows, c), x2d.dtype),
        interpret=interpret,
    )(x2d, mean.reshape(1, c).astype(jnp.float32), w_bd)


# ------------------------------------------------- differentiable train path


def _pure_train_y(x2d, group_size, eps, whitener):
    """XLA-op forward (y only) used for the recompute VJP.

    Delegates to ``group_whiten`` itself (train-mode y is independent of
    the incoming stats) so the backward can never drift from the XLA
    path's numerics."""
    from dwt_tpu.ops.whitening import group_whiten

    c = x2d.shape[-1]
    y, _ = group_whiten(
        x2d,
        whitener.init_stats(c, group_size),
        group_size=group_size,
        train=True,
        eps=eps,
        whitener=whitener,
    )
    return y


# The whitener rides the nondiff slots as the resolved INSTANCE (hashable
# by identity; registry names resolve to singletons) so a configured
# backend — e.g. NewtonSchulzWhitener(num_iters=2) — uses the same
# numerics in the train factorization, the recompute VJP, and eval.
@functools.partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3, 4))
def _train_whiten(x2d, group_size, eps, interpret, whitener):
    num_groups, g = _resolve_groups(x2d.shape[-1], group_size)
    mean, cov = _moments_call(x2d, num_groups, g, interpret)
    # Factorization stays plain JAX (see module docstring) — which is the
    # pluggable seam: any factorizing backend slots in here, Mosaic never
    # sees it (lowering pinned off-chip by tests/test_pallas_whitening.py).
    w = whitener.matrix_from_cov(_shrink(cov, eps))
    y = _apply_call(x2d, mean, w, interpret)
    return y, mean, cov


def _train_whiten_fwd(x2d, group_size, eps, interpret, whitener):
    out = _train_whiten(x2d, group_size, eps, interpret, whitener)
    return out, (x2d,)


def _train_whiten_bwd(group_size, eps, interpret, whitener, res, cots):
    (x2d,) = res
    gy, _, _ = cots  # mean/cov cotangents are zero (EMA is stop-gradient)
    _, vjp = jax.vjp(
        lambda x: _pure_train_y(x, group_size, eps, whitener), x2d
    )
    (dx,) = vjp(gy.astype(x2d.dtype))
    return (dx,)


_train_whiten.defvjp(_train_whiten_fwd, _train_whiten_bwd)


# ------------------------------------------------------------- public op


def pallas_group_whiten(
    x: jax.Array,
    stats: WhiteningStats,
    *,
    group_size: int,
    train: bool,
    momentum: float = 0.1,
    eps: float = 1e-3,
    interpret: Optional[bool] = None,
    whitener="cholesky",  # registry name or a Whitener instance
) -> Tuple[jax.Array, WhiteningStats]:
    """Drop-in for :func:`dwt_tpu.ops.whitening.group_whiten` (single-chip).

    Same semantics and state convention; no ``axis_name`` — under data
    parallelism the moment pmean couples replicas, so sharded models keep
    the XLA op (whose moments pmean inside shard_map).  ``interpret``
    defaults to auto: compiled on TPU, interpreter elsewhere (tests).
    ``whitener`` selects the factorization backend (factorizing backends
    only — swbn's online matrix update has no Pallas seam).
    """
    if not HAS_PALLAS:  # pragma: no cover
        raise RuntimeError("pallas unavailable in this jax build")
    wh = get_whitener(whitener)
    if wh.matrix_from_cov is None:
        raise ValueError(
            f"pallas_group_whiten supports factorizing whiteners only, "
            f"not {wh.name!r}"
        )
    interpret = _auto_interpret() if interpret is None else interpret
    num_features = x.shape[-1]
    num_groups, g = _resolve_groups(num_features, group_size)
    x2d = x.reshape(-1, num_features)

    if train:
        y2, mean, cov = _train_whiten(x2d, g, eps, interpret, wh)
        return (
            y2.reshape(x.shape),
            wh.update_stats(stats, mean, cov, momentum, None),
        )

    w = wh.eval_matrix(stats, eps, jnp.float32)
    y2 = _apply_call(x2d, stats.mean, w, interpret)
    return y2.reshape(x.shape), stats
