"""dwt_tpu.fleet — continuous deployment for the serving path (ISSUE-11).

Closes the train → serve loop: the training loop keeps writing
checkpoints; every serving replica watches the same ``ckpt_dir``
(:mod:`~dwt_tpu.fleet.watcher` — the resilience layer's own
newest-valid ranked walk, so unpromoted/torn steps are invisible by
construction), gates each candidate through a fixture eval
(:mod:`~dwt_tpu.fleet.canary`), hot-swaps it into the live engine as
one atomic pointer flip between dispatches
(:mod:`~dwt_tpu.fleet.reload` + ``ServeEngine.swap`` — in-flight
buckets finish on the old version, no mixed-version batch ever), and
auto-rolls back to the last-good version when the post-swap access-log
windows regress.  :mod:`~dwt_tpu.fleet.balancer` (``dwt-fleet``) fronts
N replica subprocesses with a least-outstanding-requests load balancer:
per-replica health off ``/healthz``, 503/connect-error ejection with
re-admission, SIGTERM → drain every replica → exit 0.
:mod:`~dwt_tpu.fleet.autoscale` closes the capacity loop: an
SLO-driven control loop scales the replica count between
``--min_replicas``/``--max_replicas`` off the fleet's own aggregated
signals, and the router weights picks by measured per-replica drain
rate so heterogeneous fleets take proportional traffic.

:class:`~dwt_tpu.fleet.autoscale.Autoscaler` is exported lazily (see
``__getattr__``): importing it pulls in the balancer's serve-server
dependency chain, which the lighter fleet consumers (watcher/canary
users) should not pay for.
"""

from dwt_tpu.fleet.canary import CanaryGate, CanaryVerdict, PostSwapMonitor
from dwt_tpu.fleet.reload import DeployController, HotReloader
from dwt_tpu.fleet.watcher import Candidate, CheckpointWatcher

__all__ = [
    "Candidate",
    "CheckpointWatcher",
    "CanaryGate",
    "CanaryVerdict",
    "PostSwapMonitor",
    "DeployController",
    "HotReloader",
    "Autoscaler",
    "ScaleDecision",
]


def __getattr__(name):
    if name in ("Autoscaler", "ScaleDecision"):
        from dwt_tpu.fleet import autoscale

        return getattr(autoscale, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
