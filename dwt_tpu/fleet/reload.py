"""Hot reload: watch → restore → canary → atomic swap → monitor → rollback.

The :class:`HotReloader` owns the whole continuous-deployment lifecycle
for ONE serving process.  Everything expensive — the loose checkpoint
read, the structural graft onto the model template, the whiten-cache
factorization, the device placement through the sharding plan — runs on
the reloader's own thread while the dispatcher keeps serving the live
generation (the double buffer); only the final pointer flip
(``ServeEngine.swap``) touches the serving path, and that flip is a
single reference assignment between dispatches.

Failure containment mirrors the training guard ladder:

* a candidate that fails to RESTORE (torn bytes, digest mismatch —
  ``restore_tree`` re-verifies the manifest digest) or to BUILD
  (structure/shape mismatch at ``adapt_tree``) is refused and
  remembered, so the watcher re-seeing the same artifact does not retry
  it forever;
* a candidate the :class:`~dwt_tpu.fleet.canary.CanaryGate` refuses
  (non-finite / regressed fixture eval) likewise never goes live;
* a candidate that goes live but regresses the post-swap access-log
  windows (:class:`~dwt_tpu.fleet.canary.PostSwapMonitor`) is rolled
  back to the last-good state — kept device-resident since the swap —
  and blacklisted.

Every transition writes a JSONL event (``reload``/``canary``/``swap``/
``rollback``) through the access log, version-labelled, so one file
tells the deployment story next to the requests it affected.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from dwt_tpu import obs
from dwt_tpu.fleet.canary import CanaryGate, PostSwapMonitor
from dwt_tpu.fleet.watcher import Candidate, CheckpointWatcher, newest_candidate
from dwt_tpu.serve.engine import EngineState, ServeEngine, Version
from dwt_tpu.utils.checkpoint import restore_tree

log = logging.getLogger(__name__)


class HotReloader:
    """One serving process's continuous-deployment loop.

    ``step()`` is the single-iteration core (poll → maybe deploy → maybe
    roll back) — unit-testable with no thread; ``start()``/``stop()``
    wrap it in a daemon.  ``reload_newest(force=True)`` is the bench's
    direct lever: swap the newest checkpoint in NOW (even if it is the
    version already live — a same-checkpoint swap is the numeric no-op
    the parity tests pin).
    """

    def __init__(
        self,
        engine: ServeEngine,
        ckpt_dir: str,
        *,
        access_log=None,
        poll_s: float = 2.0,
        canary: Optional[CanaryGate] = None,
        monitor: Optional[PostSwapMonitor] = None,
    ):
        self.engine = engine
        self.ckpt_dir = ckpt_dir
        self.access_log = access_log
        self.canary = canary
        self.monitor = monitor
        self.watcher = CheckpointWatcher(ckpt_dir, poll_s)
        # The version the server booted with must not redeploy on the
        # first poll: prime the watcher with it when it IS the newest.
        boot = newest_candidate(ckpt_dir)
        if boot is not None and self._is_live(boot):
            self.watcher.prime(boot)
        self.rejected: dict = {}     # version key -> refusal reason
        self.last_good: Optional[EngineState] = None
        self._last_good_label: Optional[str] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.swap_count = 0
        self.rollback_count = 0

    def _is_live(self, cand: Candidate) -> bool:
        """Is this candidate the generation already serving?  Digest
        first — it is the content identity and identical whether it came
        from the manifest or was recomputed over the restored params;
        the step number alone can differ between a checkpoint's
        directory name and the train state it holds (legacy manifests
        without a digest fall back to the step)."""
        live = self.engine.version
        if cand.digest is not None and live.digest is not None:
            return cand.digest == live.digest
        return cand.step == live.step

    # ------------------------------------------------------------- events

    def _event(self, kind: str, **fields) -> None:
        if self.access_log is not None:
            self.access_log.event(kind, **fields)

    def _reject(self, cand_key, label: str, reason: str) -> None:
        self.rejected[cand_key] = reason
        log.warning("fleet: candidate %s refused: %s", label, reason)
        self._event("canary", version=label, ok=False, reason=reason)

    # ------------------------------------------------------------ deploy

    def _build_candidate(self, cand: Candidate) -> EngineState:
        with obs.span("reload_restore", "fleet", step=cand.step):
            tree = restore_tree(cand.path)  # digest re-verified here
        return self.engine.build_state_from_tree(
            tree,
            version=Version(cand.step, cand.digest),
            what=f"candidate step {cand.step}",
        )

    def deploy(self, cand: Candidate, *, skip_canary: bool = False) -> bool:
        """Restore → build → canary → swap one candidate.  Returns True
        when the candidate went live."""
        label = Version(cand.step, cand.digest).label
        self._event("reload", version=label, step=cand.step,
                    source=cand.source)
        try:
            state = self._build_candidate(cand)
        except Exception as e:
            self._reject(cand.key, label,
                         f"restore/build failed: {type(e).__name__}: {e}")
            return False
        label = state.version.label  # digest may have been computed late
        if self.canary is not None and not skip_canary:
            # Measure the live baseline BEFORE the swap moves it.
            verdict = self.canary.check(state)
            self._event("canary", version=label, ok=verdict.ok,
                        reason=verdict.reason, **verdict.metrics)
            if not verdict.ok:
                self._reject(cand.key, label, verdict.reason)
                return False
        old_label = self.engine.version.label
        baseline_p99 = None
        if self.access_log is not None:
            baseline_p99 = self.access_log.version_stats(old_label).get(
                "e2e_ms_p99"
            )
        with obs.span("swap", "fleet", version=label):
            prev = self.engine.swap(state)
        self.swap_count += 1
        self.last_good = prev
        self._last_good_label = old_label
        self._event("swap", version=label, from_version=old_label,
                    step=cand.step)
        if self.monitor is not None:
            self.monitor.arm(label, baseline_p99)
        return True

    def rollback(self, reason: str) -> bool:
        """Swap the last-good state back in and blacklist the regressed
        version.  Returns False when there is nothing to roll back to
        (first deploy of a fresh server — keep serving, keep alarming)."""
        bad = self.engine.version
        if self.last_good is None:
            log.error(
                "fleet: %s but no last-good state to roll back to "
                "(version %s stays live)", reason, bad.label,
            )
            self._event("rollback", version=bad.label, ok=False,
                        reason=reason)
            return False
        with obs.span("swap", "fleet", version=self.last_good.version.label,
                      rollback=1):
            self.engine.swap(self.last_good)
        self.rollback_count += 1
        self.rejected[(bad.step, bad.digest)] = reason
        self._event("rollback", version=bad.label,
                    to_version=self.last_good.version.label,
                    reason=reason)
        log.warning(
            "fleet: rolled back %s -> %s (%s)",
            bad.label, self.last_good.version.label, reason,
        )
        # The rolled-back-to state is live again; nothing newer is good.
        self.last_good = None
        if self.monitor is not None:
            self.monitor.disarm()
        return True

    def reload_newest(self, *, force: bool = False,
                      skip_canary: bool = False) -> bool:
        """Deploy the newest valid checkpoint directly (bench/ops lever).
        ``force`` redeploys even the live version (a same-checkpoint
        swap: numerically a no-op, operationally the swap-cost probe)."""
        cand = newest_candidate(self.ckpt_dir)
        if cand is None:
            return False
        if not force and self._is_live(cand):
            return False
        return self.deploy(cand, skip_canary=skip_canary)

    # -------------------------------------------------------------- loop

    def step(self) -> None:
        """One reloader iteration: act on a monitor verdict, then on a
        new candidate.  Rollback first — deploying on top of a regressed
        version would destroy the evidence."""
        if self.monitor is not None and self.monitor.armed:
            verdict = self.monitor.verdict()
            if verdict is None:
                return  # undecided: hold new deploys until the window fills
            if verdict.startswith("rollback"):
                self.rollback(verdict)
                return
            self.monitor.disarm()  # "ok": the new version is the bar now
        cand = self.watcher.poll_once()
        if cand is None:
            return
        if cand.key in self.rejected:
            log.info(
                "fleet: skipping already-refused candidate step %s (%s)",
                cand.step, self.rejected[cand.key],
            )
            return
        self.deploy(cand)

    def start(self) -> None:
        if self._thread is not None:
            raise RuntimeError("reloader already started")

        def _run():
            while not self._stop.wait(self.watcher.poll_s):
                try:
                    self.step()
                except Exception:
                    log.exception("fleet: reloader step failed")

        self._thread = threading.Thread(
            target=_run, name="dwt-fleet-reload", daemon=True
        )
        self._thread.start()

    def stop(self, timeout: float = 30.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        self.watcher.stop()
