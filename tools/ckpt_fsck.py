#!/usr/bin/env python
"""Offline checkpoint-tree auditor (the ROADMAP's ``ckpt_fsck``).

Read-only walk over a ``--ckpt_dir`` tree — main steps, ``anchors/``,
``best_*/`` — reporting, per candidate:

* validity (``checkpoint_invalid_reason`` — the SAME authority the
  ranked restore walk uses, so "fsck says torn" == "resume will skip");
* on-disk format, and for ``cas_delta`` manifests the resolved chain
  depth (manifests a restore must read) and chain base;
* whether a ``data_state`` (exact mid-epoch resume cursor) is recorded;

plus store-level accounting for the content-addressed blob store:

* **missing** blobs — referenced by some manifest but absent/truncated
  (each shows up as an invalid candidate too);
* **orphaned** blobs — referenced by NO manifest (a crashed stage, a
  GC that hasn't run): their total bytes are the tree's reclaimable
  space (``gc_blobs`` would sweep them once aged);
* in-flight ``.tmp-*`` stages (informational — invisible to restore).

**Multi-run mode** (``--store``): audit a SHARED blob store against N
run trees at once — the sweep layout, where every pair's checkpoints
refcount into one CAS store and no single run dir can account for it.
References are unioned across all runs (exactly the view
``gc_blobs(..., manifest_roots=...)`` sweeps against), so "orphaned"
means referenced by NO run — per-run accounting would misreport a
sibling's blobs as garbage.  The report carries a per-run section
(candidates, torn count, referenced blobs) plus the store totals.

Exit codes: 0 = every kept/anchor/best candidate is restorable;
1 = at least one candidate is torn (its reason printed); 2 = unusable
input (no such directory).  ``--json`` emits one machine-readable
record instead of the table.

Usage::

    python tools/ckpt_fsck.py /path/to/ckpt_dir
    python tools/ckpt_fsck.py /path/to/ckpt_dir --json
    python tools/ckpt_fsck.py --store /sweep/blobs /sweep/*/ckpt/*
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO not in sys.path:  # runnable as a script from anywhere
    sys.path.insert(0, _REPO)

from dwt_tpu.ckpt.store import (  # noqa: E402
    BLOBS_DIR,
    _blob_path,
    resolve_leaves,
)
from dwt_tpu.utils.checkpoint import (  # noqa: E402
    ANCHOR_SUBDIR,
    MANIFEST,
    _TMP_PREFIX,
    _read_manifest,
    checkpoint_invalid_reason,
)


def _candidate_dirs(root: str):
    """``(label, step_dir)`` for every step candidate under the tree:
    main-dir digits, anchors/, and best_*/ one level down."""
    for name in sorted(os.listdir(root)):
        path = os.path.join(root, name)
        if name.isdigit() and os.path.isdir(path):
            yield "main", path
        elif name == ANCHOR_SUBDIR and os.path.isdir(path):
            for sub in sorted(os.listdir(path)):
                if sub.isdigit():
                    yield "anchor", os.path.join(path, sub)
        elif name.startswith("best") and os.path.isdir(path):
            for sub in sorted(os.listdir(path)):
                if sub.isdigit():
                    yield name, os.path.join(path, sub)


def _chain_info(step_dir: str, manifest: dict):
    """(depth, base_step, resolved) for a cas candidate — ONE chain
    resolution shared with the caller's blob accounting; (None, None,
    None) if broken (the validity column already carries the reason)."""
    try:
        resolved = resolve_leaves(step_dir, manifest)
    except ValueError:
        return None, None, None
    base = _read_manifest(resolved.chain_dirs[-1]) or {}
    return len(resolved.chain_dirs) - 1, base.get("step"), resolved


def _walk_tree(root: str, referenced: dict):
    """Audit one checkpoint tree: returns ``(candidates, tmp_stages)``
    and accumulates blob references into ``referenced``
    (digest -> (nbytes, store_root)) — shared by the single-dir audit
    and the multi-run union."""
    candidates = []
    for label, step_dir in _candidate_dirs(root):
        reason = checkpoint_invalid_reason(step_dir)
        manifest = _read_manifest(step_dir) or {}
        fmt = manifest.get("format", "orbax" if manifest else "legacy")
        rec = {
            "kind": label,
            "step": int(os.path.basename(step_dir)),
            "path": os.path.relpath(step_dir, root),
            "format": fmt,
            "valid": reason is None,
            "reason": reason,
            "data_state": manifest.get("data_state") is not None,
        }
        if fmt == "cas_delta":
            depth, base, resolved = _chain_info(step_dir, manifest)
            rec["chain_depth"] = depth
            rec["chain_base_step"] = base
            if resolved is not None:
                for entry, store in resolved.entries.values():
                    referenced.setdefault(
                        entry["digest"],
                        (int(entry["nbytes"]), store),
                    )
        candidates.append(rec)
    # In-flight stages also pin blobs (a staged-but-unpromoted save's
    # fresh blobs are NOT orphans — gc_blobs counts them too).
    tmp_stages = []
    for name in sorted(os.listdir(root)) if os.path.isdir(root) else []:
        if not name.startswith(_TMP_PREFIX):
            continue
        tmp_stages.append(name)
        manifest = _read_manifest(os.path.join(root, name))
        if manifest and manifest.get("format") == "cas_delta":
            try:
                resolved = resolve_leaves(os.path.join(root, name), manifest)
                for entry, store in resolved.entries.values():
                    referenced.setdefault(
                        entry["digest"], (int(entry["nbytes"]), store)
                    )
            except ValueError:
                pass
    return candidates, tmp_stages


def _scan_store(store: str) -> dict:
    """digest -> size for every blob physically in the store."""
    on_disk = {}
    if os.path.isdir(store):
        for shard in os.listdir(store):
            sdir = os.path.join(store, shard)
            if not os.path.isdir(sdir):
                continue
            for name in os.listdir(sdir):
                if name.endswith(".bin"):
                    try:
                        on_disk[name[:-4]] = os.path.getsize(
                            os.path.join(sdir, name)
                        )
                    except OSError:
                        continue
    return on_disk


def _store_accounting(store: str, referenced: dict) -> dict:
    """missing/orphaned/reclaimable totals for ``store`` against the
    given reference union.  References into OTHER stores are excluded —
    a tree whose manifests point at a different store (mixed layouts)
    must not spray phantom 'missing' blobs here."""
    store = os.path.abspath(store)
    on_disk = _scan_store(store)

    def _absent_or_truncated(digest, nbytes, st):
        try:
            return os.path.getsize(_blob_path(st, digest)) != int(nbytes)
        except OSError:
            return True

    here = {
        d: (nbytes, st) for d, (nbytes, st) in referenced.items()
        if os.path.abspath(st) == store
    }
    missing = sorted(
        d for d, (nbytes, st) in here.items()
        if _absent_or_truncated(d, nbytes, st)
    )
    orphaned = sorted(set(on_disk) - set(referenced))
    return {
        "blobs_on_disk": len(on_disk),
        "store_bytes": int(sum(on_disk.values())),
        "blobs_referenced": len(here),
        "blobs_missing": len(missing),
        "missing_digests": missing[:16],
        "blobs_orphaned": len(orphaned),
        "reclaimable_bytes": int(sum(on_disk[d] for d in orphaned)),
    }


def audit(ckpt_dir: str) -> dict:
    """The full single-tree read-only audit record (see module doc)."""
    root = os.path.abspath(os.path.expanduser(ckpt_dir))
    referenced = {}  # digest -> (nbytes, store_root)
    candidates, tmp_stages = _walk_tree(root, referenced)
    report = {
        "kind": "ckpt_fsck",
        "ckpt_dir": root,
        "candidates": candidates,
        "valid_candidates": sum(1 for c in candidates if c["valid"]),
        "torn_candidates": sum(1 for c in candidates if not c["valid"]),
        "tmp_stages": tmp_stages,
    }
    report.update(_store_accounting(os.path.join(root, BLOBS_DIR),
                                    referenced))
    return report


def audit_store(store: str, run_dirs: list) -> dict:
    """Multi-run audit: one shared store, N checkpoint trees (module
    doc).  The reference union across ALL trees is what decides
    orphaned/reclaimable — the same view cross-run GC uses."""
    store = os.path.abspath(os.path.expanduser(store))
    referenced = {}
    runs = []
    for run_dir in run_dirs:
        root = os.path.abspath(os.path.expanduser(run_dir))
        before = len(referenced)
        candidates, tmp_stages = _walk_tree(root, referenced)
        runs.append({
            "ckpt_dir": root,
            "candidates": candidates,
            "valid_candidates": sum(1 for c in candidates if c["valid"]),
            "torn_candidates": sum(
                1 for c in candidates if not c["valid"]
            ),
            "tmp_stages": tmp_stages,
            # Blobs THIS run introduced to the union — with heavy
            # cross-run dedup (frozen backbones) later runs add few.
            "new_blobs_referenced": len(referenced) - before,
        })
    report = {
        "kind": "ckpt_fsck_store",
        "store": store,
        "runs": runs,
        "valid_candidates": sum(r["valid_candidates"] for r in runs),
        "torn_candidates": sum(r["torn_candidates"] for r in runs),
    }
    report.update(_store_accounting(store, referenced))
    return report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="read-only checkpoint-tree auditor (exit 1 on any "
                    "torn kept/anchor/best candidate)"
    )
    ap.add_argument("ckpt_dir", nargs="+",
                    help="checkpoint tree(s) to audit (several only "
                         "with --store)")
    ap.add_argument("--store", type=str, default=None,
                    help="shared blob store: audit it against the UNION "
                         "of references across every ckpt_dir (the "
                         "sweep layout)")
    ap.add_argument("--json", action="store_true",
                    help="one machine-readable JSON record instead of "
                         "the table")
    args = ap.parse_args(argv)
    if len(args.ckpt_dir) > 1 and not args.store:
        print("ckpt_fsck: multiple ckpt_dirs require --store (whose "
              "store would the union audit?)", file=sys.stderr)
        return 2
    for d in args.ckpt_dir:
        if not os.path.isdir(d):
            print(f"ckpt_fsck: {d}: not a directory", file=sys.stderr)
            return 2
    if args.store and not os.path.isdir(args.store):
        print(f"ckpt_fsck: --store {args.store}: not a directory",
              file=sys.stderr)
        return 2

    def _print_candidates(candidates, tmp_stages, indent="  "):
        for c in candidates:
            chain = (
                f" chain_depth={c['chain_depth']}"
                f" base={c['chain_base_step']}"
                if c.get("chain_depth") is not None else ""
            )
            status = "ok" if c["valid"] else f"TORN ({c['reason']})"
            ds = "+data_state" if c["data_state"] else "-data_state"
            print(f"{indent}[{c['kind']:>7}] step {c['step']:>8} "
                  f"{c['format']:<12} {ds}{chain}  {status}")
        if tmp_stages:
            print(f"{indent}in-flight stages: {', '.join(tmp_stages)}")

    def _print_store_line(report, indent="  "):
        print(
            f"{indent}blobs: {report['blobs_on_disk']} on disk "
            f"({report['store_bytes']} bytes), "
            f"{report['blobs_referenced']} referenced, "
            f"{report['blobs_missing']} missing, "
            f"{report['blobs_orphaned']} orphaned "
            f"({report['reclaimable_bytes']} reclaimable bytes)"
        )

    if args.store:
        report = audit_store(args.store, args.ckpt_dir)
        if args.json:
            print(json.dumps(report))
        else:
            print(f"ckpt_fsck: shared store {report['store']} against "
                  f"{len(report['runs'])} run(s)")
            for r in report["runs"]:
                print(f"  run {r['ckpt_dir']}: "
                      f"{r['valid_candidates']} valid, "
                      f"{r['torn_candidates']} torn, "
                      f"+{r['new_blobs_referenced']} new blob ref(s)")
                _print_candidates(r["candidates"], r["tmp_stages"],
                                  indent="    ")
            _print_store_line(report)
            verdict = (
                "clean" if report["torn_candidates"] == 0
                else f"{report['torn_candidates']} torn candidate(s)"
            )
            print(f"  verdict: {verdict}")
        return 0 if report["torn_candidates"] == 0 else 1

    report = audit(args.ckpt_dir[0])
    if args.json:
        print(json.dumps(report))
    else:
        print(f"ckpt_fsck: {report['ckpt_dir']}")
        _print_candidates(report["candidates"], report["tmp_stages"])
        _print_store_line(report)
        verdict = (
            "clean" if report["torn_candidates"] == 0
            else f"{report['torn_candidates']} torn candidate(s)"
        )
        print(f"  verdict: {verdict}")
    return 0 if report["torn_candidates"] == 0 else 1


if __name__ == "__main__":
    sys.exit(main())
