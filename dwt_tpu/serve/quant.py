"""Int8 post-training weight quantization — a serving DEPLOYMENT FORMAT.

The checkpoint on disk never changes: blobs stay f32 (delta/CAS chains
keep deduping across precision configs, and a quantized artifact can
always be re-derived).  Quantization happens at :meth:`ServeEngine.build_state`
time, off the dispatcher thread, producing:

* an int8 params tree (same structure, float leaves -> int8), and
* a dequant SCALE tree (same structure, one f32 per-tensor scale per
  leaf) carried on :class:`~dwt_tpu.serve.engine.EngineState` —

so the compiled bucket forward dequantizes ``q * scale`` on device (XLA
fuses the cast into the first consumer matmul) and a hot swap can never
pair new int8 weights with old scales: they travel in ONE EngineState.

Symmetric per-tensor quantization: ``scale = max|w| / 127``,
``q = round(w / scale)``.  Good enough for weight-only int8 on the
paper's nets (the accuracy check is NOT this module's job — every
quantized candidate goes through the fleet's :class:`CanaryGate`
fixture-accuracy gate before taking traffic, and ``PostSwapMonitor``
rolls back the ones that regress live).  Integer/bool leaves pass
through untouched with scale 1.

The scale tree is structure-complete (every leaf has one) so it jits as
a plain pytree argument; non-quantized leaves are recognized at trace
time by dtype, not by a sentinel value.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp

INT8_MAX = 127.0


def _quantize_leaf(leaf):
    if not jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating):
        return leaf, jnp.ones((), jnp.float32)
    w = jnp.asarray(leaf, jnp.float32)
    amax = jnp.max(jnp.abs(w))
    # All-zero leaf: scale 1 keeps the dequant exact (q is all zeros).
    scale = jnp.where(amax > 0, amax / INT8_MAX, 1.0)
    q = jnp.clip(jnp.round(w / scale), -INT8_MAX, INT8_MAX)
    return q.astype(jnp.int8), scale.astype(jnp.float32)


def quantize_int8(params: Any) -> Tuple[Any, Any]:
    """``params -> (int8 tree, f32 per-tensor scale tree)``.

    Pure function of the f32 weights — safe to run off the dispatcher
    thread (build_state's contract); jitted by the caller if wanted.
    """
    leaves, treedef = jax.tree.flatten(params)
    qs, scales = zip(*(_quantize_leaf(l) for l in leaves)) if leaves else ((), ())
    return jax.tree.unflatten(treedef, qs), jax.tree.unflatten(treedef, scales)


def dequantize_int8(qparams: Any, scales: Any, dtype=jnp.float32) -> Any:
    """``q * scale`` leaf-wise back to ``dtype`` (int8 leaves only —
    pass-through leaves come back as-is).  Runs INSIDE the compiled
    serve forward, so the dequant is device-side and fuses."""
    return jax.tree.map(
        lambda q, s: (q.astype(dtype) * s.astype(dtype))
        if q.dtype == jnp.int8 else q,
        qparams, scales,
    )
