"""Golden tests for the grouped whitening op (SURVEY §4.1-4.2).

The numpy "reference implementation" below encodes the math of
``/root/reference/utils/whitening.py:37-61`` from its formulas (mean →
center → per-group biased covariance → shrinkage → Cholesky → inverse →
grouped apply → EMA with momentum on the NEW value).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.experimental import enable_x64

from dwt_tpu.ops import (
    WhiteningStats,
    group_whiten,
    init_whitening_stats,
)

EPS = 1e-3


def ref_whiten_nhwc(x, running_mean, running_cov, group_size, train,
                    momentum=0.1, eps=EPS):
    """Numpy reference: channels-last grouped Cholesky whitening."""
    n, h, w, c = x.shape
    g = min(c, group_size)
    ng = c // g
    if train:
        m = x.reshape(-1, c).mean(0)
    else:
        m = running_mean
    xn = x - m
    t = xn.reshape(-1, ng, g)  # [M, G, g]
    cov = np.einsum("mgc,mgd->gcd", t, t) / t.shape[0]
    if train:
        use_cov = (1 - eps) * cov + eps * np.eye(g)
    else:
        use_cov = (1 - eps) * running_cov + eps * np.eye(g)
    li = np.linalg.inv(np.linalg.cholesky(use_cov))  # [G, g, g]
    y = np.einsum("mgc,gdc->mgd", t, li).reshape(x.shape)
    if train:
        new_mean = momentum * x.reshape(-1, c).mean(0) + (1 - momentum) * running_mean
        new_cov = momentum * cov + (1 - momentum) * running_cov
        return y, new_mean, new_cov
    return y, running_mean, running_cov


def make_input(shape=(4, 5, 5, 8), seed=0):
    rng = np.random.default_rng(seed)
    return rng.normal(size=shape).astype(np.float32) * 2.0 + 0.5


def test_train_output_matches_reference_math():
    x = make_input()
    stats = init_whitening_stats(8, 4)
    y, new_stats = group_whiten(
        x, stats, group_size=4, train=True
    )
    ref_y, ref_mean, ref_cov = ref_whiten_nhwc(
        x, np.zeros(8), np.ones((2, 4, 4)), 4, train=True
    )
    np.testing.assert_allclose(np.asarray(y), ref_y, rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(new_stats.mean), ref_mean, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(new_stats.cov), ref_cov, rtol=1e-4, atol=1e-5)


def test_output_has_identity_group_covariance():
    x = make_input((16, 7, 7, 16), seed=3)
    stats = init_whitening_stats(16, 4)
    y, _ = group_whiten(x, stats, group_size=4, train=True)
    y = np.asarray(y, dtype=np.float64)
    m = y.reshape(-1, 16).mean(0)
    t = (y - m).reshape(-1, 4, 4)
    cov = np.einsum("mgc,mgd->gcd", t, t) / t.shape[0]
    for gi in range(4):
        np.testing.assert_allclose(cov[gi], np.eye(4), atol=5e-3)


def test_eval_uses_running_stats_with_reshrinkage():
    x = make_input(seed=5)
    rng = np.random.default_rng(7)
    a = rng.normal(size=(2, 4, 4))
    run_cov = (a @ a.transpose(0, 2, 1) + 3 * np.eye(4)).astype(np.float32)
    run_mean = rng.normal(size=8).astype(np.float32)
    stats = WhiteningStats(mean=jnp.asarray(run_mean), cov=jnp.asarray(run_cov))
    y, out_stats = group_whiten(x, stats, group_size=4, train=False)
    ref_y, _, _ = ref_whiten_nhwc(x, run_mean, run_cov, 4, train=False)
    np.testing.assert_allclose(np.asarray(y), ref_y, rtol=1e-4, atol=1e-4)
    # eval must not touch the stats
    np.testing.assert_array_equal(np.asarray(out_stats.mean), run_mean)
    np.testing.assert_array_equal(np.asarray(out_stats.cov), run_cov)


def test_ema_accumulates_unshrunk_cov_with_momentum_on_new():
    x = make_input(seed=11)
    run_mean = np.full(8, 0.25, np.float32)
    run_cov = np.tile(np.eye(4, dtype=np.float32) * 2, (2, 1, 1))
    stats = WhiteningStats(mean=jnp.asarray(run_mean), cov=jnp.asarray(run_cov))
    mom = 0.3
    _, new_stats = group_whiten(x, stats, group_size=4, train=True, momentum=mom)
    batch_mean = x.reshape(-1, 8).mean(0)
    xn = x - batch_mean
    t = xn.reshape(-1, 2, 4)
    batch_cov = np.einsum("mgc,mgd->gcd", t, t) / t.shape[0]  # UNSHRUNK
    np.testing.assert_allclose(
        np.asarray(new_stats.mean), mom * batch_mean + (1 - mom) * run_mean,
        rtol=1e-5, atol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(new_stats.cov), mom * batch_cov + (1 - mom) * run_cov,
        rtol=1e-4, atol=1e-5,
    )


def test_gradients_flow_and_match_finite_differences():
    x64 = make_input((2, 3, 3, 4), seed=13).astype(np.float64)

    with enable_x64():
        stats = WhiteningStats(
            mean=jnp.zeros(4, jnp.float64),
            cov=jnp.ones((1, 4, 4), jnp.float64),
        )

        def f(x):
            y, _ = group_whiten(x, stats, group_size=4, train=True)
            return jnp.sum(jnp.sin(y))

        g = jax.grad(f)(jnp.asarray(x64))
        fd = np.zeros_like(x64)
        h = 1e-6
        base = float(f(jnp.asarray(x64)))
        flat = x64.reshape(-1)
        for i in range(0, flat.size, 7):  # sample of coordinates
            pert = flat.copy()
            pert[i] += h
            fd.reshape(-1)[i] = (float(f(jnp.asarray(pert.reshape(x64.shape)))) - base) / h
        idx = np.arange(0, flat.size, 7)
        np.testing.assert_allclose(
            np.asarray(g).reshape(-1)[idx], fd.reshape(-1)[idx],
            rtol=1e-3, atol=1e-4,
        )


def test_group_size_clamped_to_num_features():
    # reference: group_size = min(num_features, group_size) (whitening.py:14)
    x = make_input((4, 3, 3, 8), seed=17)
    stats = init_whitening_stats(8, 32)
    assert stats.cov.shape == (1, 8, 8)
    y, _ = group_whiten(x, stats, group_size=32, train=True)
    assert y.shape == x.shape


def test_indivisible_group_size_raises():
    with pytest.raises(ValueError):
        init_whitening_stats(6, 4)


def test_bf16_activations_use_f32_stats():
    x = make_input((8, 5, 5, 8), seed=19)
    stats = init_whitening_stats(8, 4)
    y16, s16 = group_whiten(
        jnp.asarray(x, jnp.bfloat16), stats, group_size=4, train=True
    )
    assert y16.dtype == jnp.bfloat16
    assert s16.mean.dtype == jnp.float32
    assert s16.cov.dtype == jnp.float32
    y32, _ = group_whiten(jnp.asarray(x), stats, group_size=4, train=True)
    np.testing.assert_allclose(
        np.asarray(y16, np.float32), np.asarray(y32), atol=0.15
    )


def test_jit_and_grad_compile():
    x = make_input()
    stats = init_whitening_stats(8, 4)

    @jax.jit
    def step(x, stats):
        def loss(x):
            y, ns = group_whiten(x, stats, group_size=4, train=True)
            return jnp.mean(y**2), ns

        (l, ns), g = jax.value_and_grad(loss, has_aux=True)(x)
        return l, ns, g

    l, ns, g = step(jnp.asarray(x), stats)
    assert np.isfinite(float(l))
    assert np.all(np.isfinite(np.asarray(g)))


# ------------------------------------------------- degenerate inputs
# SURVEY §5 NaN/PSD guard: the eps shrinkage (whitening.py:48 in the
# reference) must keep the Cholesky factorization finite — in outputs AND
# gradients — on inputs that make the raw covariance singular.


def _grad_norm(x, stats, **kw):
    def loss(x):
        y, _ = group_whiten(x, stats, train=True, **kw)
        return jnp.sum(y**2)

    return jax.grad(loss)(x)


def test_constant_input_stays_finite():
    # Zero variance in every channel: raw cov is all-zeros; shrinkage makes
    # it eps*I (PD), so outputs are exactly 0 and grads finite.
    stats = init_whitening_stats(8, 4)
    x = jnp.full((4, 5, 5, 8), 3.7, jnp.float32)
    y, new_stats = group_whiten(x, stats, group_size=4, train=True)
    # Rounding in the mean (~1e-7) is amplified by the ~1/sqrt(eps) (~32x)
    # whitening matrix of the eps*I covariance — near-zero, not exactly 0.
    np.testing.assert_allclose(np.asarray(y), 0.0, atol=1e-3)
    assert np.all(np.isfinite(np.asarray(new_stats.cov)))
    g = _grad_norm(x, stats, group_size=4)
    assert np.all(np.isfinite(np.asarray(g)))


def test_zero_variance_channel_inside_group():
    # One dead channel inside a group: raw cov is rank-deficient (PSD, not
    # PD); shrinkage restores PD.
    stats = init_whitening_stats(8, 4)
    x = np.asarray(make_input(), np.float32)
    x[..., 2] = -1.25  # constant channel 2 (group 0)
    x = jnp.asarray(x)
    y, _ = group_whiten(x, stats, group_size=4, train=True)
    assert np.all(np.isfinite(np.asarray(y)))
    g = _grad_norm(x, stats, group_size=4)
    assert np.all(np.isfinite(np.asarray(g)))


def test_duplicated_channels_rank_deficient_group():
    # Perfectly correlated channels: another PSD-but-singular covariance.
    stats = init_whitening_stats(8, 4)
    x = np.asarray(make_input(), np.float32)
    x[..., 1] = x[..., 0]
    x[..., 3] = 2.0 * x[..., 0]
    y, _ = group_whiten(jnp.asarray(x), stats, group_size=4, train=True)
    assert np.all(np.isfinite(np.asarray(y)))
    g = _grad_norm(jnp.asarray(x), stats, group_size=4)
    assert np.all(np.isfinite(np.asarray(g)))


def test_eval_on_fresh_all_ones_cov_stats():
    # Fresh stats carry the reference's torch.ones([G,g,g]) covariance init
    # (whitening.py:24): rank-1 PSD; eval-time shrinkage makes it PD. The
    # smallest shrunk eigenvalue is ~eps so outputs are amplified by up to
    # ~1/sqrt(eps) — large but finite is the reference-parity expectation.
    stats = init_whitening_stats(8, 4)
    x = jnp.asarray(make_input())
    y, out_stats = group_whiten(x, stats, group_size=4, train=False)
    assert np.all(np.isfinite(np.asarray(y)))
    assert out_stats is stats  # eval never mutates state
    assert float(jnp.max(jnp.abs(y))) < 10.0 / np.sqrt(EPS)


def test_bf16_degenerate_input_finite():
    # bf16 activations with a constant channel: stats are f32, outputs bf16.
    stats = init_whitening_stats(8, 4)
    x = np.asarray(make_input(), np.float32)
    x[..., 5] = 0.0
    xb = jnp.asarray(x, jnp.bfloat16)
    y, new_stats = group_whiten(xb, stats, group_size=4, train=True)
    assert y.dtype == jnp.bfloat16
    assert new_stats.cov.dtype == jnp.float32
    assert np.all(np.isfinite(np.asarray(y, dtype=np.float32)))
    g = _grad_norm(xb, stats, group_size=4)
    assert np.all(np.isfinite(np.asarray(g, dtype=np.float32)))


class TestUnrolledFactorization:
    """The statically-unrolled small-g Cholesky + triangular inverse must
    be numerically interchangeable with the LAPACK-style lowering it
    replaces (whitening_matrix picks the unrolled path for g <= 8)."""

    def _spd(self, g, batch=7, seed=0):
        rng = np.random.default_rng(seed)
        a = rng.normal(size=(batch, g, g))
        return jnp.asarray(a @ np.swapaxes(a, -1, -2) + g * np.eye(g))

    @pytest.mark.parametrize("g", [1, 2, 4, 8])
    def test_matches_lapack_path(self, g):
        from dwt_tpu.ops.whitening import (
            _cholesky_unrolled,
            _tri_inverse_unrolled,
        )
        from jax.scipy.linalg import solve_triangular

        cov = self._spd(g)
        chol_ref = jnp.linalg.cholesky(cov)
        np.testing.assert_allclose(
            _cholesky_unrolled(cov), chol_ref, rtol=1e-5, atol=1e-6
        )
        eye = jnp.broadcast_to(jnp.eye(g), cov.shape)
        inv_ref = solve_triangular(chol_ref, eye, lower=True)
        np.testing.assert_allclose(
            _tri_inverse_unrolled(chol_ref), inv_ref, rtol=1e-5, atol=1e-6
        )

    def test_gradients_match_lapack_path(self):
        from dwt_tpu.ops.whitening import (
            _cholesky_unrolled,
            _tri_inverse_unrolled,
        )
        from jax.scipy.linalg import solve_triangular

        cov = self._spd(4, batch=3, seed=1)

        def via_unrolled(c):
            return jnp.sum(_tri_inverse_unrolled(_cholesky_unrolled(c)) ** 2)

        def via_lapack(c):
            chol = jnp.linalg.cholesky(c)
            eye = jnp.broadcast_to(jnp.eye(4), c.shape)
            return jnp.sum(solve_triangular(chol, eye, lower=True) ** 2)

        g_u = jax.grad(via_unrolled)(cov)
        g_l = jax.grad(via_lapack)(cov)
        # The two paths use different (equally valid) cotangent
        # conventions for the symmetric input: the unrolled factorization
        # only reads the lower triangle, LAPACK's VJP symmetrizes.  For
        # any upstream producer of a symmetric cov (ours: T T^T / m, whose
        # pullback is (G + G^T) T / m) only G + G^T matters — compare that.
        sym = lambda g: g + jnp.swapaxes(g, -1, -2)
        np.testing.assert_allclose(sym(g_u), sym(g_l), rtol=1e-4, atol=1e-6)

    def test_whitening_matrix_still_whitens(self):
        # End-to-end: identity output covariance through the public op
        # (the unrolled path is now the default for g=4).
        from dwt_tpu.ops import group_whiten, init_whitening_stats

        rng = np.random.default_rng(2)
        x = jnp.asarray(rng.normal(size=(512, 8)) @ rng.normal(size=(8, 8)))
        y, _ = group_whiten(
            x, init_whitening_stats(8, 4), group_size=4, train=True
        )
        yc = np.asarray(y) - np.asarray(y).mean(0)
        cov = yc.T @ yc / yc.shape[0]
        for gi in range(2):
            blk = cov[4 * gi : 4 * gi + 4, 4 * gi : 4 * gi + 4]
            np.testing.assert_allclose(blk, np.eye(4), atol=5e-3)


class TestApplyLowering:
    """grouped vs block-diagonal apply lowerings are interchangeable
    (auto picks blockdiag for C<=128 — MXU tile efficiency; see
    apply_whitening)."""

    @pytest.mark.parametrize("C,g", [(8, 4), (64, 4), (256, 4)])
    def test_lowerings_match(self, C, g):
        from dwt_tpu.ops.whitening import apply_whitening

        rng = np.random.default_rng(0)
        xn = jnp.asarray(rng.normal(size=(97, C)), jnp.float32)
        G = C // g
        w = jnp.asarray(rng.normal(size=(G, g, g)), jnp.float32)
        y_g = apply_whitening(xn, w, lowering="grouped")
        y_b = apply_whitening(xn, w, lowering="blockdiag")
        np.testing.assert_allclose(y_g, y_b, rtol=1e-6, atol=1e-6)

    def test_lowerings_match_bf16(self):
        from dwt_tpu.ops.whitening import apply_whitening

        rng = np.random.default_rng(1)
        xn = jnp.asarray(rng.normal(size=(64, 16)), jnp.bfloat16)
        w = jnp.asarray(rng.normal(size=(4, 4, 4)), jnp.float32)
        y_g = apply_whitening(xn, w, compute_dtype=jnp.bfloat16,
                              lowering="grouped")
        y_b = apply_whitening(xn, w, compute_dtype=jnp.bfloat16,
                              lowering="blockdiag")
        np.testing.assert_allclose(
            np.asarray(y_g, np.float32), np.asarray(y_b, np.float32),
            rtol=2e-2, atol=2e-2,
        )
