"""Mesh construction and multi-host initialization."""

from __future__ import annotations

import os
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

# Canonical name of the data-parallel mesh axis; the same string must be the
# ``axis_name`` the model's norm sites pmean over.
DATA_AXIS = "data"
# Leading axis of the 2-D multi-slice mesh: crosses slice boundaries (DCN).
DCN_AXIS = "dcn"


def make_mesh(
    devices: Optional[Sequence[jax.Device]] = None,
    axis_name: str = DATA_AXIS,
    dcn_slices: Optional[int] = None,
) -> Mesh:
    """Data-parallel mesh over the given (default: all) devices.

    1-D by default: on a pod slice, ``jax.devices()`` is already ordered so
    that neighboring indices are ICI neighbors — a 1-D mesh keeps the
    gradient/moment all-reduces on ICI.

    ``dcn_slices=S`` (multi-slice / pod-level DP, BASELINE configs[4])
    builds the 2-D ``(DCN_AXIS, axis_name)`` mesh instead: devices reshape
    slice-major to ``[S, n_per_slice]`` (``jax.devices()`` orders devices
    by slice on multislice deployments), so collectives over ``axis_name``
    stay WITHIN a slice on ICI and only the ``S``-way reduction over
    ``DCN_AXIS`` crosses the data-center network.  XLA lowers a
    two-axis ``pmean``/``psum`` to the matching hierarchical reduction.
    """
    devices = list(devices if devices is not None else jax.devices())
    if dcn_slices and dcn_slices > 1:
        n = len(devices)
        if n % dcn_slices:
            raise ValueError(
                f"{n} devices cannot split into {dcn_slices} equal slices"
            )
        grid = np.asarray(devices).reshape(dcn_slices, n // dcn_slices)
        return Mesh(grid, (DCN_AXIS, axis_name))
    return Mesh(np.asarray(devices), (axis_name,))


def initialize_distributed(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Multi-host bring-up: ``jax.distributed.initialize`` wrapper.

    On Cloud TPU pods the arguments are auto-detected from the environment;
    explicit values support bare-metal/DCN setups.  Safe to call once per
    process before any device access.  (Reference has no analogue — it is
    single-process; SURVEY §5 distributed-backend note.)
    """
    _maybe_enable_cpu_collectives()
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    jax.distributed.initialize(**kwargs)


def _maybe_enable_cpu_collectives() -> None:
    """Multi-process runs on the CPU backend (CI, the 2-process consensus
    tests, laptop bring-up) need a cross-process collectives backend: the
    default CPU client refuses multiprocess computations outright on the
    jax 0.4.x line.  Select gloo when (a) the chosen platform is CPU,
    (b) the installed jax still exposes the knob (newer releases default
    it), and (c) the user hasn't chosen an implementation themselves.
    TPU/GPU backends bring their own collectives and are untouched.
    """
    platforms = (
        getattr(jax.config, "jax_platforms", None)
        or os.environ.get("JAX_PLATFORMS")
        or ""
    )
    if platforms.split(",")[0].strip() != "cpu":
        return
    values = getattr(jax.config, "values", {})
    if "jax_cpu_collectives_implementation" not in values:
        return  # newer jax: CPU collectives are built in / default gloo
    current = values["jax_cpu_collectives_implementation"]
    if current and current != "none":
        return  # explicit user choice wins ('none' is the unset default)
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
