"""dwt_tpu.serve — inference serving for the deployment forward (ISSUE-7).

The paper's deployment artifact — target-branch eval forward with frozen
running stats and test-time domain whitening — served as a
request/response engine: AOT-compiled fixed-bucket forwards
(:mod:`~dwt_tpu.serve.engine`), deadline micro-batching with bounded
queues and load shedding (:mod:`~dwt_tpu.serve.batcher`), in-process and
HTTP front ends with graceful SIGTERM drain
(:mod:`~dwt_tpu.serve.server`), and per-request JSONL access metrics
(:mod:`~dwt_tpu.serve.metrics`).  ``tools/serve_bench.py`` drives it
open-loop (Poisson arrivals) for latency-vs-offered-load curves.
"""

from dwt_tpu.serve.adapt import DomainAdapter
from dwt_tpu.serve.batcher import (
    DEFAULT_BUCKETS,
    Future,
    MicroBatcher,
    PlannedBatch,
    ShedError,
    bucket_for,
    plan_dispatch,
)
from dwt_tpu.serve.engine import EngineState, ServeEngine, Version
from dwt_tpu.serve.metrics import AccessLog
from dwt_tpu.serve.server import HttpServeClient, ServeClient

__all__ = [
    "DomainAdapter",
    "DEFAULT_BUCKETS",
    "Future",
    "MicroBatcher",
    "PlannedBatch",
    "ShedError",
    "bucket_for",
    "plan_dispatch",
    "EngineState",
    "ServeEngine",
    "Version",
    "AccessLog",
    "HttpServeClient",
    "ServeClient",
]
