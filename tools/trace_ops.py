"""Attribute step time per fused XLA op from a ``jax.profiler`` trace.

Reads the ``*.xplane.pb`` under a trace directory (written by
``tools/profile_step.py --trace DIR``) and prints a JSON report with one
entry PER PLANE LINE (lines overlap — e.g. "XLA Modules" spans the ops in
"XLA Ops" — so they are never summed together): per-line total, an
HLO-category rollup, and the top-N ops by summed duration.  This is the
measurement SURVEY §7 step 1 asks for before hand-writing Pallas kernels
("measure first") — it answers *where* the flagship step's time goes,
without TensorBoard.

On a TPU trace, the line to read is "XLA Ops" on the ``/device:TPU:0``
plane.  Parsing uses the XPlane protobuf bundled with the baked-in
tensorflow; no network, no UI.

Usage: python tools/trace_ops.py /tmp/dwt_trace [--top 40] [--line "XLA Ops"]
"""

import argparse
import glob
import json
import os
from collections import defaultdict


def load_xspaces(trace_dir):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = sorted(
        glob.glob(
            os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True
        )
    )
    if not paths:
        raise SystemExit(f"no *.xplane.pb under {trace_dir}")
    spaces = []
    for p in paths:
        xs = xplane_pb2.XSpace()
        with open(p, "rb") as f:
            xs.ParseFromString(f.read())
        spaces.append((p, xs))
    return spaces


def pick_planes(xspace):
    """Accelerator planes (``/device:`` minus host-CPU) when present,
    otherwise every plane (CPU-only runs)."""
    dev = [
        p
        for p in xspace.planes
        if p.name.startswith("/device:") and "CPU" not in p.name
    ]
    return dev or list(xspace.planes)


def _stat_str(st, stat_meta):
    """A stat's string value, resolving ref_value safely (None if absent)."""
    if st.str_value:
        return st.str_value
    if st.ref_value:
        sm = stat_meta.get(st.ref_value)
        return sm.name if sm is not None else None
    return None


def _category(ev, md, stat_meta):
    for holder in (ev, md):
        if holder is None:
            continue
        for st in holder.stats:
            sm = stat_meta.get(st.metadata_id)
            if sm is not None and sm.name == "hlo_category":
                val = _stat_str(st, stat_meta)
                if val:
                    return val
    return "uncategorized"


def aggregate_line(plane, line):
    """Sum event durations per metadata name within ONE line."""
    meta = plane.event_metadata
    stat_meta = plane.stat_metadata
    per_op = defaultdict(int)
    op_category = {}
    for ev in line.events:
        md = meta.get(ev.metadata_id)
        name = md.name if md is not None else f"id{ev.metadata_id}"
        per_op[name] += ev.duration_ps
        if name not in op_category:
            op_category[name] = _category(ev, md, stat_meta)
    per_category = defaultdict(int)
    for name, ps in per_op.items():
        per_category[op_category[name]] += ps
    return per_op, per_category, op_category


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("trace_dir")
    ap.add_argument("--top", type=int, default=40)
    ap.add_argument(
        "--line",
        default=None,
        help="only lines whose name contains this (e.g. 'XLA Ops')",
    )
    ap.add_argument(
        "--list-lines", action="store_true", help="just list plane/line names"
    )
    args = ap.parse_args()

    spaces = load_xspaces(args.trace_dir)
    report = {"trace_dir": args.trace_dir, "lines": []}
    for path, xs in spaces:
        for plane in pick_planes(xs):
            if args.list_lines:
                print(
                    json.dumps(
                        {
                            "file": os.path.basename(path),
                            "plane": plane.name,
                            "lines": [
                                {"name": ln.name, "events": len(ln.events)}
                                for ln in plane.lines
                            ],
                        }
                    )
                )
                continue
            for line in plane.lines:
                if (
                    args.line
                    and args.line.lower() not in line.name.lower()
                ):
                    continue
                per_op, per_cat, op_cat = aggregate_line(plane, line)
                total_ps = sum(per_op.values())
                if not total_ps:
                    continue
                top = sorted(per_op.items(), key=lambda kv: -kv[1])[
                    : args.top
                ]
                report["lines"].append(
                    {
                        "file": os.path.basename(path),
                        "plane": plane.name,
                        "line": line.name,
                        "total_ms": round(total_ps / 1e9, 3),
                        "categories_ms": {
                            k: round(v / 1e9, 3)
                            for k, v in sorted(
                                per_cat.items(), key=lambda kv: -kv[1]
                            )
                        },
                        "top_ops": [
                            {
                                "name": n,
                                "ms": round(ps / 1e9, 3),
                                "pct": round(100 * ps / total_ps, 2),
                                "category": op_cat[n],
                            }
                            for n, ps in top
                        ],
                    }
                )
    if not args.list_lines:
        print(json.dumps(report, indent=1))


if __name__ == "__main__":
    main()
