"""Live metrics plane (ISSUE-12): registry, exposition, rules, obs_diff.

Coverage map (the ISSUE's test checklist):

* registry concurrency (exact counts under threaded increments) and
  histogram bucket math (inclusive upper bounds, cumulative render);
* /metrics exposition golden — exact rendered text, format-validated —
  plus validator rejections of malformed text;
* fleet aggregation with one ejected replica (in-process balancer over
  fake replica HTTP servers);
* alert rule fire/clear hysteresis with a fake clock; strict rules-file
  parsing; PostSwapMonitor's rule-driven trips (defaults pinned by
  tests/test_fleet.py, custom rules here);
* obs_diff regression / ok / missing-metric verdicts and exit codes,
  identical-run self-diff passing;
* the training CLI's --metrics_port endpoint serving valid exposition
  DURING a run, with --alert_rules firing onto the JSONL stream;
* AccessLog lost-record accounting; heartbeat device-memory fields.

The dwt-serve / dwt-fleet endpoint acceptance (curl /metrics on a live
replica and the aggregating front end) rides one subprocess test.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from dwt_tpu.obs import prom, rules
from dwt_tpu.obs.registry import MetricsRegistry, get_registry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------- registry


def test_counter_concurrency_exact():
    reg = MetricsRegistry()
    c = reg.counter("t_total", "t", labelnames=("who",))
    child = c.labels(who="a")
    n_threads, per = 8, 5000

    def worker():
        for _ in range(per):
            child.inc()
            c.labels(who="b").inc(2)

    threads = [threading.Thread(target=worker) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert reg.value("t_total", {"who": "a"}) == n_threads * per
    assert reg.value("t_total", {"who": "b"}) == 2 * n_threads * per


def test_histogram_bucket_math():
    reg = MetricsRegistry()
    h = reg.histogram("lat_ms", "l", buckets=(1.0, 5.0, 10.0))
    # Upper bounds are INCLUSIVE (the Prometheus le contract).
    for v in (0.5, 1.0, 1.5, 5.0, 7.0, 10.0, 11.0, 1000.0):
        h.observe(v)
    bounds, counts, total, count = h._one().snapshot()
    assert bounds == (1.0, 5.0, 10.0)
    assert counts == [2, 2, 2, 2]  # per-bucket (non-cumulative) + +Inf
    assert count == 8 and total == pytest.approx(1036.0)
    text = prom.render(reg)
    assert 'lat_ms_bucket{le="1"} 2' in text
    assert 'lat_ms_bucket{le="5"} 4' in text       # cumulative
    assert 'lat_ms_bucket{le="10"} 6' in text
    assert 'lat_ms_bucket{le="+Inf"} 8' in text
    assert "lat_ms_count 8" in text
    assert prom.validate_exposition(text) == []


def test_registry_reregister_is_idempotent_but_typed():
    reg = MetricsRegistry()
    a = reg.counter("x_total", "x")
    assert reg.counter("x_total") is a
    with pytest.raises(ValueError):
        reg.gauge("x_total")
    with pytest.raises(ValueError):
        reg.counter("x_total", labelnames=("k",))
    with pytest.raises(ValueError):
        reg.counter("1bad")
    with pytest.raises(ValueError):
        reg.histogram("h", buckets=(5.0, 1.0))  # not ascending
    c = reg.counter("y_total", labelnames=("k",))
    with pytest.raises(ValueError):
        c.labels(wrong="v")
    with pytest.raises(ValueError):
        c.inc()  # labeled family needs .labels(...)
    with pytest.raises(ValueError):
        c.labels(k="v").inc(-1)  # counters only go up


def test_gauge_callback_sampled_at_collect():
    reg = MetricsRegistry()
    g = reg.gauge("depth", "queue depth")
    state = {"v": 3}
    g.set_function(lambda: state["v"])
    assert reg.value("depth") == 3
    state["v"] = 7
    assert "depth 7" in prom.render(reg)


# ----------------------------------------------------------- exposition


def test_exposition_golden():
    reg = MetricsRegistry()
    c = reg.counter("dwt_req_total", "requests", labelnames=("status",))
    c.labels(status="ok").inc(3)
    c.labels(status='we"ird\\').inc()
    g = reg.gauge("dwt_up", "is up")
    g.set(1)
    text = prom.render(reg)
    assert text == (
        "# HELP dwt_req_total requests\n"
        "# TYPE dwt_req_total counter\n"
        'dwt_req_total{status="ok"} 3\n'
        'dwt_req_total{status="we\\"ird\\\\"} 1\n'
        "# HELP dwt_up is up\n"
        "# TYPE dwt_up gauge\n"
        "dwt_up 1\n"
    )
    assert prom.validate_exposition(text) == []
    # Round-trip: escaped label values parse back to the original.
    fams = prom.parse_exposition(text)
    labels = [lab for _, lab, _ in fams["dwt_req_total"].samples]
    assert {"status": 'we"ird\\'} in labels


def test_label_escape_round_trip_backslash_sequences():
    # 'ckpt\next' (literal backslash + n): chained str.replace decoding
    # would eat the doubled backslash's second half plus the n — the
    # one-pass decoder must round-trip it through render -> parse, the
    # exact path the fleet's /metrics aggregation re-renders.
    tricky = ['ckpt\\next', 'a\\"b', "nl\nend", "\\\\", 'tail\\']
    reg = MetricsRegistry()
    g = reg.gauge("g", "g", labelnames=("v",))
    for v in tricky:
        g.labels(v=v).set(1)
    fams = prom.parse_exposition(prom.render(reg))
    got = [lab["v"] for _, lab, _ in fams["g"].samples]
    assert got == tricky
    merged = prom.merge_expositions([({"replica": "0"}, prom.render(reg))])
    fams2 = prom.parse_exposition(merged)
    assert [lab["v"] for _, lab, _ in fams2["g"].samples] == tricky


def test_validator_rejects_malformed():
    assert prom.validate_exposition("this is } not a sample\n")
    # Cumulative bucket counts that DECREASE.
    bad_hist = (
        "# TYPE h histogram\n"
        'h_bucket{le="1"} 5\n'
        'h_bucket{le="2"} 3\n'
        'h_bucket{le="+Inf"} 3\n'
        "h_sum 1\nh_count 3\n"
    )
    assert any("monotonically" in p
               for p in prom.validate_exposition(bad_hist))
    # +Inf bucket disagreeing with _count.
    bad_count = (
        "# TYPE h histogram\n"
        'h_bucket{le="+Inf"} 3\n'
        "h_sum 1\nh_count 4\n"
    )
    assert any("_count" in p for p in prom.validate_exposition(bad_count))
    # Histogram without +Inf.
    no_inf = (
        "# TYPE h histogram\n"
        'h_bucket{le="1"} 3\n'
        "h_sum 1\nh_count 3\n"
    )
    assert any("+Inf" in p for p in prom.validate_exposition(no_inf))
    assert any("unknown TYPE" in p for p in prom.validate_exposition(
        "# TYPE x flurble\nx 1\n"
    ))


def test_merge_expositions_adds_part_labels():
    reg = MetricsRegistry()
    reg.counter("served_total", "served").inc(5)
    text = prom.render(reg)
    merged = prom.merge_expositions([
        ({}, "# TYPE healthy gauge\nhealthy 2\n"),
        ({"replica": "0"}, text),
        ({"replica": "1"}, text),
        ({"replica": "2"}, "garbage {{{ not exposition\n"),  # skipped
    ])
    assert prom.validate_exposition(merged) == []
    assert 'served_total{replica="0"} 5' in merged
    assert 'served_total{replica="1"} 5' in merged
    assert "healthy 2" in merged
    assert merged.count("# TYPE served_total counter") == 1


# ---------------------------------------------------------------- rules


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_alert_fire_clear_hysteresis_fake_clock():
    reg = MetricsRegistry()
    g = reg.gauge("dwt_train_steps_per_s", "rate")
    g.set(10.0)
    clock = _Clock()
    engine = rules.AlertEngine(
        rules.parse_rules([{
            "name": "stalled", "metric": "dwt_train_steps_per_s",
            "op": "<", "threshold": 1.0, "for_s": 10.0,
            "severity": "critical",
        }]),
        registry=reg, clock=clock, min_interval_s=0.0,
    )
    assert engine.evaluate() == []          # healthy
    g.set(0.2)
    assert engine.evaluate() == []          # condition true, pending
    clock.t = 5.0
    assert engine.evaluate() == []          # still inside for_s
    g.set(5.0)
    assert engine.evaluate() == []          # recovered before firing
    g.set(0.2)
    clock.t = 20.0
    assert engine.evaluate() == []          # pending restarts at 20
    clock.t = 29.9
    assert engine.evaluate() == []
    clock.t = 30.0
    events = engine.evaluate()
    assert [(e.rule, e.state) for e in events] == [("stalled", "firing")]
    assert events[0].severity == "critical"
    assert engine.firing() == ["stalled"]
    # The firing set is exported as a gauge on the same registry.
    assert reg.value("dwt_alerts_firing", {
        "alertname": "stalled", "severity": "critical",
    }) == 1
    clock.t = 31.0
    assert engine.evaluate() == []          # steady firing: no re-emit
    g.set(50.0)
    events = engine.evaluate()
    assert [(e.rule, e.state) for e in events] == [
        ("stalled", "resolved")
    ]
    assert engine.firing() == []
    assert reg.value("dwt_alerts_firing", {
        "alertname": "stalled", "severity": "critical",
    }) is None


def test_alert_engine_throttles_and_filters_labels():
    reg = MetricsRegistry()
    c = reg.counter("req_total", "r", labelnames=("status",))
    c.labels(status="ok").inc(100)
    c.labels(status="shed").inc(5)
    clock = _Clock()
    engine = rules.AlertEngine(
        rules.parse_rules([{
            "name": "shedding", "metric": "req_total",
            "labels": {"status": "shed"}, "op": ">", "threshold": 1,
        }]),
        registry=reg, clock=clock, min_interval_s=10.0,
    )
    events = engine.maybe_evaluate()
    assert [(e.rule, e.labels) for e in events] == [
        ("shedding", {"status": "shed"})
    ]
    clock.t = 5.0
    assert engine.maybe_evaluate() == []    # throttled
    clock.t = 15.0
    assert engine.maybe_evaluate() == []    # steady state, no re-emit


def test_rules_parsing_is_strict(tmp_path):
    ok = [{"name": "a", "metric": "m", "op": ">", "threshold": 1}]
    assert len(rules.parse_rules(ok)) == 1
    assert len(rules.parse_rules({"rules": ok})) == 1
    with pytest.raises(ValueError):
        rules.parse_rules([{"name": "a", "metric": "m", "op": "~",
                            "threshold": 1}])
    with pytest.raises(ValueError):
        rules.parse_rules([{"name": "a", "metric": "m", "op": ">",
                            "threshold": 1, "typo_key": 2}])
    with pytest.raises(ValueError):  # threshold XOR baseline_factor
        rules.parse_rules([{"name": "a", "metric": "m", "op": ">"}])
    with pytest.raises(ValueError):
        rules.parse_rules([
            {"name": "a", "metric": "m", "op": ">", "threshold": 1},
            {"name": "a", "metric": "m", "op": "<", "threshold": 2},
        ])
    with pytest.raises(ValueError):
        rules.parse_rules([{"name": "a", "metric": "m", "op": ">",
                            "threshold": 1, "severity": "mild"}])
    p = tmp_path / "rules.json"
    p.write_text("not json")
    with pytest.raises(ValueError):
        rules.load_rules(str(p))
    # baseline_factor rules are monitor-only: the registry engine
    # refuses them at construction, not silently at runtime.
    with pytest.raises(ValueError):
        rules.AlertEngine(rules.parse_rules([{
            "name": "a", "metric": "m", "op": ">", "baseline_factor": 2,
        }]), registry=MetricsRegistry())


def test_post_swap_monitor_custom_rules():
    from dwt_tpu.fleet import PostSwapMonitor
    from dwt_tpu.serve import AccessLog

    alog = AccessLog()
    clock = _Clock()
    custom = rules.parse_rules([
        # Trip on MEDIAN latency against the armed p99 baseline: not a
        # built-in condition — only reachable through the rules surface.
        {"name": "p50_blown", "metric": "e2e_ms_p50", "op": ">",
         "threshold": 20.0, "severity": "critical"},
    ])
    mon = PostSwapMonitor(
        alog, min_requests=10, decide_after_s=30.0, clock=clock,
        rules=custom,
    )
    mon.arm("v2", baseline_p99=10.0)
    for _ in range(10):
        alog.record("ok", 1, version="v2", e2e_ms=25.0)
    v = mon.verdict()
    # The built-in p99 rule was REPLACED: only the custom rule trips.
    assert v == "rollback: e2e_ms_p50 25 > 20"
    # baseline_factor resolution path.
    mon2 = PostSwapMonitor(
        alog, min_requests=10, decide_after_s=30.0, clock=clock,
        rules=rules.parse_rules([
            {"name": "p99_vs_base", "metric": "e2e_ms_p99", "op": ">",
             "baseline_factor": 2.0},
        ]),
    )
    mon2.arm("v2", baseline_p99=10.0)
    v2 = mon2.verdict()
    assert v2 is not None and "2x baseline 10" in v2
    # baseline_factor on a metric with no armed baseline would be a
    # silently-inert gate: refused at construction.
    with pytest.raises(ValueError):
        PostSwapMonitor(alog, rules=rules.parse_rules([
            {"name": "bad", "metric": "error_rate", "op": ">",
             "baseline_factor": 3.0},
        ]))


# ------------------------------------------------------------- obs_diff

sys.path.insert(0, os.path.join(REPO, "tools"))
import obs_diff  # noqa: E402


def _bench_record(value=100.0, step_ms=10.0, metric="m_imgs_per_sec"):
    return {"metric": metric, "value": value, "unit": "imgs/sec",
            "step_time_ms": step_ms}


def _write(tmp_path, name, *records):
    p = tmp_path / name
    p.write_text("\n".join(json.dumps(r) for r in records))
    return str(p)


def test_obs_diff_self_diff_passes(tmp_path):
    base = _write(tmp_path, "a.json", _bench_record())
    assert obs_diff.main([base, base]) == 0


def test_obs_diff_regression_exit_code(tmp_path, capsys):
    base = _write(tmp_path, "a.json", _bench_record(value=100.0))
    cur = _write(tmp_path, "b.json",
                 _bench_record(value=80.0))  # -20% throughput
    assert obs_diff.main([base, cur, "--tolerance", "5"]) == 3
    out = capsys.readouterr().out
    assert "REGRESSED" in out and "| m_imgs_per_sec |" in out
    # Wider tolerance absorbs it.
    assert obs_diff.main([base, cur, "--tolerance", "25"]) == 0
    # Per-metric override beats the default.
    assert obs_diff.main([
        base, cur, "--tolerance", "25", "--tol", "m_imgs_per_sec=5",
    ]) == 3
    # Lower-better direction: step_time_ms INCREASING is the regression.
    cur2 = _write(tmp_path, "c.json",
                  _bench_record(value=100.0, step_ms=20.0))
    assert obs_diff.main([base, cur2]) == 3


def test_obs_diff_missing_metric_exit_code(tmp_path):
    base = _write(tmp_path, "a.json", _bench_record())
    cur = _write(tmp_path, "b.json",
                 _bench_record(metric="other_imgs_per_sec"))
    assert obs_diff.main([base, cur]) == 4
    assert obs_diff.main([base, cur, "--missing", "ignore"]) == 0
    bad = tmp_path / "bad.json"
    bad.write_text("not json at all")
    assert obs_diff.main([str(bad), cur]) == 2


def test_obs_diff_direction_override_and_unknown(tmp_path, capsys):
    base = _write(tmp_path, "a.json",
                  {"metric": "mystery_quantity", "value": 100.0})
    cur = _write(tmp_path, "b.json",
                 {"metric": "mystery_quantity", "value": 10.0})
    # Unknown direction: informational only, never gates.
    assert obs_diff.main([base, cur]) == 0
    assert "n/a" in capsys.readouterr().out
    assert obs_diff.main([
        base, cur, "--direction", "mystery_quantity=up",
    ]) == 3


def test_obs_diff_serve_bench_and_report_formats(tmp_path):
    sb = {"kind": "serve_bench", "offered_imgs_per_s": 400.0,
          "achieved_imgs_per_s": 395.0, "e2e_ms_p99": 80.0,
          "shed_rate": 0.0}
    report = {"kind": "obs_report", "processes": {"0": {"train": {
        "wall_s": 10.0, "n_steps": 100,
        "phases": {"step_dispatch": {"self_s": 4.0, "count": 100,
                                     "total_s": 4.0}},
        "unattributed_s": 0.5,
    }}}}
    base = _write(tmp_path, "a.jsonl", sb, report)
    m = obs_diff.load_metrics(base)
    assert m["serve@400.achieved_imgs_per_s"] == 395.0
    assert m["serve@400.e2e_ms_p99"] == 80.0
    assert m["p0.train_ms_per_step"] == pytest.approx(100.0)
    assert m["p0.step_dispatch_ms_per_step"] == pytest.approx(40.0)
    # Regressed p99 in an otherwise identical run.
    sb_bad = dict(sb, e2e_ms_p99=200.0)
    cur = _write(tmp_path, "b.jsonl", sb_bad, report)
    assert obs_diff.main([base, cur]) == 3
    # Round-driver wrapper ({"parsed": {...}}) unwraps.
    wrapped = _write(tmp_path, "c.json",
                     {"n": 5, "rc": 0, "parsed": _bench_record()})
    assert "m_imgs_per_sec" in obs_diff.load_metrics(wrapped)


# ------------------------------------------- satellites: serve-side obs


def test_access_log_lost_records_counted():
    from dwt_tpu.serve import AccessLog

    class _FullDisk:
        def write(self, s):
            raise OSError("No space left on device")

    before = get_registry().value("dwt_serve_lost_log_records_total") or 0
    alog = AccessLog(stream=_FullDisk())
    for _ in range(5):
        alog.record("ok", 1, e2e_ms=1.0)
    alog.event("swap", version="x")
    s = alog.summary()
    assert s["lost_log_records"] == 6
    after = get_registry().value("dwt_serve_lost_log_records_total")
    assert after - before == 6


def test_heartbeat_device_memory_fields(monkeypatch):
    import io

    from dwt_tpu.utils import metrics as um

    monkeypatch.setattr(
        um, "device_memory_stats",
        lambda: {"bytes_in_use": 1234, "peak_bytes_in_use": 5678},
    )
    stream = io.StringIO()
    logger = um.MetricLogger(stream=stream)
    hb = um.HeartbeatEmitter(logger, every=1)
    hb.step(0)
    hb.step(1)
    line = [ln for ln in stream.getvalue().splitlines()
            if ln.startswith("[heartbeat]")][-1]
    assert "device_bytes_in_use=1234" in line
    assert "device_peak_bytes_in_use=5678" in line
    assert get_registry().value(
        "dwt_device_memory_bytes", {"stat": "bytes_in_use"}
    ) == 1234


def test_device_memory_stats_never_raises():
    from dwt_tpu.utils.metrics import device_memory_stats

    out = device_memory_stats()  # CPU backend: None or a plain dict
    assert out is None or all(
        isinstance(v, int) for v in out.values()
    )


# -------------------------------------- fleet aggregation (in-process)


class _FakeReplicaHandler(BaseHTTPRequestHandler):
    metrics_text = ""

    def log_message(self, fmt, *args):
        pass

    def do_GET(self):
        if self.path == "/metrics":
            body = self.metrics_text.encode()
            ctype = prom.CONTENT_TYPE
        else:
            body = json.dumps({"ok": True}).encode()
            ctype = "application/json"
        self.send_response(200)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)


def _fake_replica_server(text):
    handler = type("H", (_FakeReplicaHandler,), {"metrics_text": text})
    srv = ThreadingHTTPServer(("127.0.0.1", 0), handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    return srv


def test_fleet_metrics_aggregation_with_ejected_replica():
    from dwt_tpu.fleet.balancer import Replica, ReplicaSet, make_handler

    r0_srv = _fake_replica_server(
        "# TYPE dwt_serve_imgs_total counter\ndwt_serve_imgs_total 11\n"
    )
    r1_srv = _fake_replica_server(
        "# TYPE dwt_serve_imgs_total counter\ndwt_serve_imgs_total 99\n"
    )
    try:
        r0 = Replica(0, "127.0.0.1", r0_srv.server_address[1])
        r1 = Replica(1, "127.0.0.1", r1_srv.server_address[1])
        rset = ReplicaSet([r0, r1])
        rset.eject(r1, "test: down")  # ejected replica contributes nothing
        # An (unstarted) autoscaler over the same rset: its gauges and
        # event counter must ride the aggregated exposition.  Force one
        # blocked decision so the labeled counter has a series.
        from dwt_tpu.fleet.autoscale import Autoscaler

        clock = _Clock()
        scaler = Autoscaler(
            rset, lambda rid: None, min_replicas=1, max_replicas=2,
            pressure_for_s=0.0, clock=clock,
        )
        r0.outstanding = 50  # pressure at max -> blocked:at_max
        d = scaler.tick()
        r0.outstanding = 0
        assert (d.action, d.reason) == ("blocked", "at_max")
        draining = threading.Event()
        front = ThreadingHTTPServer(
            ("127.0.0.1", 0), make_handler(rset, draining,
                                           autoscaler=scaler)
        )
        threading.Thread(target=front.serve_forever, daemon=True).start()
        try:
            port = front.server_address[1]
            text = urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=10
            ).read().decode()
        finally:
            front.shutdown()
            front.server_close()
    finally:
        for srv in (r0_srv, r1_srv):
            srv.shutdown()
            srv.server_close()
    assert prom.validate_exposition(text) == []
    # Healthy replica's series is passed through replica-labeled; the
    # ejected one is absent; the balancer's own series say why.
    assert 'dwt_serve_imgs_total{replica="0"} 11' in text
    assert 'replica="1"' not in text
    assert "dwt_fleet_healthy_replicas 1" in text
    assert 'dwt_fleet_ejections_total{rid="1"} ' in text
    # Autoscaler series ride the same exposition: the target gauge and
    # the labeled lifecycle-event counter (one blocked:at_max tick).
    assert "dwt_fleet_target_replicas 2" in text
    # (presence, not count: the counter is process-global and other
    # autoscaler tests in the same session feed the same series)
    assert ('dwt_fleet_scale_events_total{direction="blocked",'
            'reason="at_max"}' in text)
    assert "dwt_fleet_load_per_replica" in text


def test_respawner_backoff_fake_clock():
    from dwt_tpu.fleet.balancer import Replica, Respawner

    clock = _Clock()
    spawns = []

    class _Spawned:
        def __init__(self):
            self.proc = None
            self.port = 4242 + len(spawns)

    def spawn_fn(rid, argv, host):
        spawns.append(rid)
        if len(spawns) == 1:
            raise RuntimeError("spawn failed on arrival")
        return _Spawned()

    r = Replica(0, "127.0.0.1", 1000)
    resp = Respawner([], max_respawns=3, backoff_s=2.0,
                     spawn_fn=spawn_fn, clock=clock, background=False)
    # Attempt 1 at t=0 fails; next attempt due at 0 + 2*2^0 = 2 s.
    assert resp.maybe_respawn(r) is False
    assert spawns == [0]
    clock.t = 1.0
    assert resp.maybe_respawn(r) is False   # backoff holds
    assert spawns == [0]
    clock.t = 2.0
    assert resp.maybe_respawn(r) is True    # attempt 2 succeeds
    assert r.port == 4244 and r.respawns == 1
    # Attempt 3 due at 2 + 2*2^1 = 6 s.
    clock.t = 5.0
    assert resp.maybe_respawn(r) is False
    clock.t = 6.0
    assert resp.maybe_respawn(r) is True
    # Budget (3) exhausted: no further attempts, no further spawns.
    clock.t = 1000.0
    assert resp.maybe_respawn(r) is False
    assert len(spawns) == 3


# ------------------------------------ acceptance: CLIs' live endpoints


def test_training_cli_metrics_endpoint_and_alerts(tmp_path):
    """curl /metrics on a TRAINING CLI mid-run returns valid Prometheus
    exposition carrying the train-loop series, and --alert_rules fires
    onto the JSONL metric stream."""
    from dwt_tpu.cli.usps_mnist import main as digits_main

    rules_path = tmp_path / "rules.json"
    rules_path.write_text(json.dumps([{
        "name": "train_started", "metric": "dwt_train_steps_total",
        "op": ">", "threshold": 0, "severity": "info",
    }]))
    jsonl = tmp_path / "run.jsonl"
    result = []
    t = threading.Thread(target=lambda: result.append(digits_main([
        "--synthetic", "--synthetic_size", "32",
        "--source_batch_size", "8", "--target_batch_size", "8",
        "--test_batch_size", "16", "--group_size", "4",
        "--epochs", "2", "--log_interval", "2", "--heartbeat_every", "2",
        "--metrics_port", "0",
        "--alert_rules", str(rules_path),
        "--metrics_jsonl", str(jsonl),
    ])))
    t.start()
    try:
        deadline = time.monotonic() + 120
        text = ""
        while time.monotonic() < deadline:
            port = prom.exporter_port()
            if port is not None:
                try:
                    text = urllib.request.urlopen(
                        f"http://127.0.0.1:{port}/metrics", timeout=5
                    ).read().decode()
                except OSError:
                    text = ""
                # The steps family exists (at 0) before training starts;
                # the loss gauge only appears at the first logged step —
                # wait for BOTH so the scrape is a mid-run one.
                if ("dwt_train_steps_total" in text
                        and "dwt_train_loss" in text):
                    break
            time.sleep(0.05)
    finally:
        t.join(timeout=300)
    assert not t.is_alive() and result, "training run did not finish"
    assert "dwt_train_steps_total" in text, text[:2000]
    assert prom.validate_exposition(text) == []
    # The whole train-side surface made it into one scrape.
    for family in ("dwt_train_loss", "dwt_train_steps_per_s",
                   "dwt_host_rss_mb"):
        assert family in text, f"missing {family}"
    # The always-true rule fired exactly once onto the metric stream.
    recs = [json.loads(ln) for ln in jsonl.read_text().splitlines()]
    alerts = [r for r in recs if r["kind"] == "alert"]
    assert [a["state"] for a in alerts] == ["firing"]
    assert alerts[0]["alert"] == "train_started"
    assert any(r["kind"] == "metrics_exporter" for r in recs)
    # Scraped mid-run while steps were advancing: the gauge surface is
    # the run's own numbers, not zeros.
    fams = prom.parse_exposition(text)
    steps = fams["dwt_train_steps_total"].samples[0][2]
    assert steps > 0


def test_serve_and_fleet_metrics_endpoints():
    """Acceptance: curl /metrics on a live dwt-serve replica AND on the
    dwt-fleet front end; both return valid exposition, the fleet's is
    replica-labeled."""
    proc = subprocess.Popen(
        [sys.executable, "-m", "dwt_tpu.fleet.balancer",
         "--replicas", "1", "--port", "0",
         "--health_interval_s", "0.3", "--",
         "--init_random", "--model", "lenet", "--buckets", "1,4",
         "--max_batch_delay_ms", "2"],
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True,
    )
    try:
        ready = json.loads(proc.stdout.readline())
        assert ready["kind"] == "fleet_ready"
        front_port = ready["port"]
        replica_port = ready["replicas"][0]["port"]
        body = json.dumps(
            {"inputs": np.zeros((1, 28, 28, 1)).tolist()}
        ).encode()
        req = urllib.request.Request(
            f"http://127.0.0.1:{front_port}/infer", data=body,
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=60) as resp:
            assert resp.status == 200

        with urllib.request.urlopen(
            f"http://127.0.0.1:{replica_port}/metrics", timeout=10
        ) as resp:
            assert resp.headers["Content-Type"] == prom.CONTENT_TYPE
            replica_text = resp.read().decode()
        assert prom.validate_exposition(replica_text) == []
        assert "dwt_serve_requests_total" in replica_text
        assert 'dwt_serve_version{version=' in replica_text

        fleet_text = urllib.request.urlopen(
            f"http://127.0.0.1:{front_port}/metrics", timeout=10
        ).read().decode()
        assert prom.validate_exposition(fleet_text) == []
        assert 'replica="0"' in fleet_text
        assert "dwt_fleet_healthy_replicas 1" in fleet_text
        assert "dwt_fleet_proxied_total" in fleet_text

        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=120) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
