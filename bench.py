"""Canonical perf driver: jitted DWT train-step throughput on one chip.

Prints ONE JSON line:
``{"metric": ..., "value": N, "unit": "imgs/sec", "vs_baseline": N, ...}``
including an analytic MFU estimate (XLA cost-analysis FLOPs when available,
closed-form fallback otherwise, divided by the chip's peak bf16 FLOP/s).

Flagship benchmark (default): ResNet50-DWT OfficeHome train step at the
reference recipe — 18 images per domain stream (54-image concatenated
forward, ``resnet50_dwt_mec_officehome.py:500-502``), 224x224 crops,
group_size=4, bf16 compute with f32 whitening/BN statistics.
``--model lenet`` measures the digits step (32+32, ``usps_mnist.py:333-336``).

The reference publishes no throughput numbers (BASELINE.md) — the baseline
is established de novo; ``vs_baseline`` normalizes by the first recorded TPU
number below.

Robustness: the environment reaches the single TPU chip through an
experimental relay whose backend init can fail (Unavailable) or hang
outright when the chip claim is wedged.  Backend init is therefore probed in
a *subprocess* with a timeout, retried once, and on failure the benchmark
re-execs itself on CPU in a clean environment (relay vars stripped) so the
driver always records a parsable measurement with an honest ``backend``
field and a diagnostic.
"""

import argparse
import json
import os
import subprocess
import sys
import time

# First real-TPU measurement anchors vs_baseline; None -> vs_baseline=1.0.
# The anchor is ONLY comparable to runs of the same metric (flagship
# resnet50 at 224px) — other model/resolution records report vs_baseline=1 —
# AND of the same timing method: a scan-amortized step time divided into a
# per-call anchor would report a phantom speedup, so when the run's timing
# mode differs from BASELINE_TIMING the ratio uses the run's matching
# per-call number instead.
# Anchor: round-4 first honest TPU v5e number (2026-07-29), 94.8 ms/step,
# MFU 0.070, fetch-synchronized per-call two-point timing.
BASELINE_IMGS_PER_SEC = 569.64
BASELINE_METRIC = "resnet50_dwt_train_imgs_per_sec"
BASELINE_TIMING = "two_point"

_RELAY_VAR = "PALLAS_AXON_POOL_IPS"
# Backend init + one tiny compile (first compile 20-40s); overridable so a
# wedged-relay environment fails fast when the operator knows it's down.
# Worst-case budget (probe-first flow, since the TCP port check is only
# advisory): hung probe (150 s) + BENCH_RELAY_WAIT_S TCP poll (120 s) +
# hung re-probe (150 s) + CPU-fallback resnet50@96px child (~45 s compile
# + ~6.5 s/step x 5 steps, ~100 s total) ≈ 520 s — fits a 10-minute
# driver timeout only via the defaults below, so size them together.
_PROBE_TIMEOUT_S = int(os.environ.get("BENCH_PROBE_TIMEOUT_S", "150"))

# Peak dense bf16 FLOP/s per chip by device-kind substring (public specs).
_PEAK_FLOPS = [
    ("v5 lite", 197e12),  # v5e
    ("v5e", 197e12),
    ("v5p", 459e12),
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]


def _peak_flops(device_kind: str):
    kind = device_kind.lower()
    for sub, peak in _PEAK_FLOPS:
        if sub in kind:
            return peak
    return None


# Analytic fallback FLOPs per image for one *training* step (fwd + bwd ~= 3x
# fwd): ResNet50 fwd at 224x224 is ~4.1e9 MAC-derived FLOPs (8.2e9 FLOPs
# counting mul+add); LeNet-DWT fwd is ~6.6e6 FLOPs.  Used only when XLA
# cost analysis is unavailable.
_ANALYTIC_TRAIN_FLOPS_PER_IMG = {
    "resnet50": 3 * 8.2e9,
    "lenet": 3 * 1.3e7,
}


def _build_lenet(batch: int, dtype=None):
    """Model/state/batch + jitted train step for the digits benchmarks
    (shared with the --harvest_depth record-path sweep and the
    --compute_dtype precision sweep; ``dtype`` defaults to the reference
    recipe's f32)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dwt_tpu.nn import LeNetDWT
    from dwt_tpu.train import adam_l2, create_train_state, make_digits_train_step

    dtype = jnp.float32 if dtype is None else dtype
    rng = np.random.default_rng(0)
    b = {
        "source_x": jnp.asarray(
            rng.normal(size=(batch, 28, 28, 1)), dtype
        ),
        "source_y": jnp.asarray(rng.integers(0, 10, size=(batch,))),
        "target_x": jnp.asarray(
            rng.normal(size=(batch, 28, 28, 1)), dtype
        ),
    }
    model = LeNetDWT(group_size=4, dtype=dtype)
    tx = adam_l2(1e-3, 5e-4)
    state = create_train_state(
        model, jax.random.key(0), jnp.stack([b["source_x"], b["target_x"]]), tx
    )
    step = jax.jit(make_digits_train_step(model, tx, 0.1), donate_argnums=0)
    return step, state, b


def _bench_lenet(steps: int, batch: int):
    step, state, b = _build_lenet(batch)
    return _time_steps(step, state, b, steps, imgs_per_step=2 * batch)


def _bench_lenet_eval(steps: int, batch: int):
    """Inference throughput of the digits eval path — the reference
    ``test()`` loop (``usps_mnist.py:310-327``): target-branch-only
    forward with running stats.  Satellite of ISSUE-7: the digits forward
    is a serving workload too, so ``--phase eval`` must cover it."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from dwt_tpu.nn import LeNetDWT
    from dwt_tpu.train import create_train_state, make_eval_step

    rng = np.random.default_rng(0)
    b = {
        "target_x": jnp.asarray(
            rng.normal(size=(batch, 28, 28, 1)), jnp.float32
        ),
        "source_y": jnp.asarray(rng.integers(0, 10, size=(batch,))),
    }
    model = LeNetDWT(group_size=4)
    sample = jnp.stack([b["target_x"], b["target_x"]])
    state = create_train_state(
        model, jax.random.key(0), sample, optax.identity()
    )
    estep = make_eval_step(model)

    def step(s, batch_):
        m = estep(s.params, s.batch_stats, batch_["target_x"],
                  batch_["source_y"])
        return s, {"loss": m["loss_sum"]}

    return _time_steps(jax.jit(step), state, b, steps, imgs_per_step=batch)


def _build_resnet50(batch: int, image: int, use_pallas: bool, tx=None,
                    dtype=None):
    """Model/state/batch for the flagship benchmarks.  ``tx`` defaults to
    the reference SGD recipe; the eval bench passes ``optax.identity()``
    so no momentum buffers (a full extra param copy in HBM) are
    allocated for an inference measurement.  ``dtype`` defaults to the
    reference recipe's bf16 compute — the --compute_dtype sweep passes
    f32 explicitly to price the bf16 arm against it (the default build
    IS already the bf16 arm)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dwt_tpu.nn import ResNetDWT
    from dwt_tpu.train import create_train_state, sgd_two_group

    dtype = jnp.bfloat16 if dtype is None else dtype
    rng = np.random.default_rng(0)
    b = {
        "source_x": jnp.asarray(
            rng.normal(size=(batch, image, image, 3)), dtype
        ),
        "source_y": jnp.asarray(rng.integers(0, 65, size=(batch,))),
        "target_x": jnp.asarray(
            rng.normal(size=(batch, image, image, 3)), dtype
        ),
        "target_aug_x": jnp.asarray(
            rng.normal(size=(batch, image, image, 3)), dtype
        ),
    }
    model = ResNetDWT.resnet50(
        num_classes=65, group_size=4, dtype=dtype,
        use_pallas=use_pallas,
    )
    if tx is None:
        tx = sgd_two_group(1e-2, 1e-3)
    sample = jnp.stack([b["source_x"], b["target_x"], b["target_aug_x"]])
    state = create_train_state(model, jax.random.key(0), sample, tx)
    return model, tx, state, b


def _build_resnet50_step(batch: int, image: int = 224,
                         use_pallas: bool = False, dtype=None):
    """Flagship jitted train step + state/batch — ONE construction site
    shared by the main bench and the --harvest_depth/--compute_dtype
    sweeps so they can never measure divergent step recipes."""
    import jax

    from dwt_tpu.train import make_officehome_train_step

    model, tx, state, b = _build_resnet50(batch, image, use_pallas,
                                          dtype=dtype)
    step = jax.jit(
        make_officehome_train_step(model, tx, 0.1), donate_argnums=0
    )
    return step, state, b


def _bench_resnet50(steps: int, batch: int, image: int = 224,
                    use_pallas: bool = False):
    step, state, b = _build_resnet50_step(batch, image, use_pallas)
    return _time_steps(step, state, b, steps, imgs_per_step=3 * batch)


def _bench_resnet50_eval(steps: int, batch: int, image: int = 224):
    """Inference throughput of the eval path — the reference ``test()``
    loop (``resnet50_dwt_mec_officehome.py:447-464``): target-branch-only
    forward with running stats, batched argmax/nll counters."""
    import jax
    import optax

    from dwt_tpu.train import make_eval_step

    model, _, state, b = _build_resnet50(
        batch, image, use_pallas=False, tx=optax.identity()
    )
    estep = make_eval_step(model)

    # Shim to the (state, batch) -> (state, {"loss": ...}) shape the
    # shared timing helpers expect; params/stats ride inside `state`.
    def step(s, batch_):
        m = estep(s.params, s.batch_stats, batch_["target_x"],
                  batch_["source_y"])
        return s, {"loss": m["loss_sum"]}

    return _time_steps(
        jax.jit(step), state, b, steps, imgs_per_step=batch
    )


def _compile_with_flops(step, state, batch):
    """AOT-compile the step once; return (callable, flops or None).

    Reusing the compiled executable for timing avoids paying the (20-40s+)
    XLA compile twice; cost analysis comes from the same artifact.
    """
    try:
        compiled = step.lower(state, batch).compile()
    except Exception as e:  # relay/remote-compile may not support AOT
        print(f"bench: AOT compile unavailable ({e!r})", file=sys.stderr)
        return step, None
    flops = None
    try:
        analysis = compiled.cost_analysis()
        if isinstance(analysis, (list, tuple)):
            analysis = analysis[0]
        f = float(analysis.get("flops", 0.0))
        flops = f if f > 0 else None
    except Exception as e:
        print(f"bench: cost_analysis unavailable ({e!r})", file=sys.stderr)
    return compiled, flops


def enable_compile_cache():
    """Persistent XLA compilation cache (best-effort): the flagship step
    costs minutes to compile through the remote-compile relay, so mid-round
    runs warm the cache for the round-end driver bench.  Harmless no-op
    where unsupported."""
    try:
        import jax

        jax.config.update(
            "jax_compilation_cache_dir", "/tmp/jax_compile_cache"
        )
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 5)
    except Exception as e:
        print(f"bench: compile cache unavailable ({e!r})", file=sys.stderr)


def two_point_per_step(step, state, batch, steps, warmup=3):
    """Fetch-synchronized two-point per-step timing.

    Synchronizes by FETCHING a scalar, not ``block_until_ready``: through
    the axon relay ``block_until_ready`` resolves the local handle without
    waiting for remote execution (measured: a chained-matmul loop
    "finished" at 300x the chip's peak FLOP/s).  A host fetch of the loss
    forces the whole donated-state chain to execute everywhere.  The
    two-point form ``per_step = (t(n2) - t(n1)) / (n2 - n1)`` cancels the
    fixed per-fetch relay round-trip (~60-70 ms measured) that would
    otherwise dominate short runs.  Shared by bench.py and
    tools/profile_step.py so the two tools report comparable numbers.

    Returns ``(per_step_seconds, state, last_loss, degraded)`` —
    ``degraded`` is True when the two-point difference was non-positive
    (timing jitter on very fast steps) and the returned value is the
    single-run average, which re-includes the fetch round-trip.
    """

    def run(n, state):
        t0 = time.perf_counter()
        for _ in range(n):
            state, m = step(state, batch)
        loss = float(m["loss"])
        return time.perf_counter() - t0, state, loss

    # Warmup steady-state steps (compile already done when AOT worked).
    _, state, _ = run(warmup, state)
    n1 = max(1, steps // 4)
    n2 = max(steps, n1 + 4)
    dt1, state, _ = run(n1, state)
    dt2, state, loss = run(n2, state)
    per_step = (dt2 - dt1) / (n2 - n1)
    degraded = per_step <= 0
    if degraded:
        # Timing noise on very fast steps: fall back to the single-run
        # average, which RE-INCLUDES the fetch round-trip — callers must
        # surface ``degraded`` so the record is not read as a clean
        # two-point measurement.
        per_step = dt2 / n2
        print(
            "bench: two-point timing degenerate (dt2<=dt1); reporting "
            "single-run average INCLUDING the fetch round-trip",
            file=sys.stderr,
        )
    return per_step, state, loss, degraded


def scan_steps_fn(step_fn, k: int):
    """Wrap a train step in a ``lax.scan`` of ``k`` device steps per
    dispatch.  Through the axon relay every dispatch costs a host round
    trip that two-point timing cannot cancel (it cancels the *fetch*, not
    the per-call dispatch); k steps per call amortize it k-fold, so the
    marginal time/k converges to the chip's true step time — the number a
    non-relay deployment would see.  ``step_fn`` must be the raw (un-AOT)
    step; the scan body is compiled once inside the outer jit."""
    import jax
    from jax import lax

    def run_k(state, batch):
        def body(s, _):
            s, m = step_fn(s, batch)
            return s, m["loss"]

        state, losses = lax.scan(body, state, None, length=k)
        return state, {"loss": losses[-1]}

    return jax.jit(run_k, donate_argnums=0)


_SCAN_K = int(os.environ.get("BENCH_SCAN_K", "8"))


def scan_two_point(raw_step, state, batch, steps, k):
    """Two-point timing of ``k`` scanned device steps per dispatch.

    Shared by bench.py and tools/profile_step.py (same call-count
    calibration, same per-step division) so the two tools' scan numbers
    stay comparable.  Returns ``(per_step, state, loss, degraded)``.
    """
    run_k = scan_steps_fn(raw_step, k)
    calls = max(3, steps // k + 2)
    per_call, state, loss, degraded = two_point_per_step(
        run_k, state, batch, calls
    )
    return per_call / k, state, loss, degraded


def harvest_record_bench(step, state, batch, steps, depth, warmup=3):
    """Per-step wall of the RECORD path: dispatch + per-step metric
    handling through ``train/harvest.py``'s ring at ``depth`` (0 = the
    legacy synchronous ``float()``), log cadence 1 so EVERY step emits a
    record into a host-side sink.

    This is the A/B behind PERF.md "Hot-path harvest": the step benches
    above deliberately fetch once per timed run, so the per-step fetch
    tax the training loops actually pay (79.6% of loop wall in the PR-8
    attribution) is invisible to them by design.  Two-point timing like
    :func:`two_point_per_step`; every run ends with a full drain so the
    deferred fetch work is always inside the timed region.  Shared with
    ``tools/profile_step.py`` so the two tools' sweeps stay comparable.
    """
    from dwt_tpu.train.harvest import AsyncMetricHarvester

    sink = []

    def emit(vals):
        sink.append(float(vals["loss"]))

    def run(n, state):
        h = AsyncMetricHarvester(depth)
        t0 = time.perf_counter()
        for i in range(n):
            state, m = step(state, batch)
            h.put(i + 1, i + 1, values={"loss": m["loss"]}, emit=emit)
        h.drain()
        return time.perf_counter() - t0, state

    _, state = run(warmup, state)
    n1 = max(1, steps // 4)
    n2 = max(steps, n1 + 4)
    dt1, state = run(n1, state)
    dt2, state = run(n2, state)
    per_step = (dt2 - dt1) / (n2 - n1)
    degraded = per_step <= 0
    if degraded:
        # Timing jitter on very fast steps: the single-run average
        # RE-INCLUDES the fixed round-trips two-point timing cancels —
        # surfaced to the caller (like two_point_per_step) so a gated
        # record never silently mixes methodologies across runs.
        per_step = dt2 / n2
        print(
            f"bench: harvest depth={depth} two-point degenerate "
            "(dt2<=dt1); reporting single-run average",
            file=sys.stderr,
        )
    assert sink and all(s == s for s in sink), "non-finite loss in bench"
    return per_step, state, degraded


def _harvest_sweep(args, record):
    """The ``--harvest_depth`` sweep arm: record-path ms/step per listed
    ring depth, stamped into the bench record so ``--compare`` (through
    tools/obs_diff.py) gates the trajectory instead of eyeballing it."""
    depths = []
    for tok in str(args.harvest_depth).split(","):
        tok = tok.strip()
        if tok:
            depths.append(int(tok))
    if not depths:
        return
    if args.model == "lenet":
        step, state, b = _build_lenet(args.batch or 32)
    else:
        step, state, b = _build_resnet50_step(
            args.batch or 18, args.image, use_pallas=args.pallas
        )
    step, _ = _compile_with_flops(step, state, b)
    times = {}
    any_degraded = False
    for d in depths:
        per_step, state, degraded = harvest_record_bench(
            step, state, b, args.steps, d
        )
        times[d] = per_step
        record[f"harvest_d{d}_ms_per_step"] = round(per_step * 1e3, 3)
        if degraded:
            # Bool fields are ignored by obs_diff's numeric extraction,
            # so the marker rides the record without becoming a gated
            # metric itself.
            record[f"harvest_d{d}_degraded"] = True
            any_degraded = True
    deepest = max(times)
    if (
        0 in times and deepest > 0 and times[deepest] > 0
        and not any_degraded  # a mixed-methodology ratio gates nothing
    ):
        record["harvest_record_speedup"] = round(
            times[0] / times[deepest], 3
        )


def _compute_dtype_sweep(args, record):
    """The ``--compute_dtype`` sweep arm: train-step ms/step per listed
    compute dtype (f32, bf16), stamped into the bench record so
    ``--compare`` (through tools/obs_diff.py) gates the bf16 frontier
    instead of eyeballing it.

    Each arm REBUILDS the model at that dtype — the flagship default
    build is already bf16 (the reference recipe), so an honest f32-vs-bf16
    price needs both variants constructed explicitly from the same
    construction site (:func:`_build_resnet50_step` / :func:`_build_lenet`)
    rather than reusing the headline measurement for either arm.
    Params and optimizer state stay f32 in BOTH arms (flax param_dtype);
    only activations/gradients/whitening traffic change dtype — the same
    contract the training CLIs' --compute_dtype flag enforces.
    """
    import jax.numpy as jnp

    names = []
    for tok in str(args.compute_dtype).split(","):
        tok = tok.strip()
        if not tok:
            continue
        if tok not in ("f32", "bf16"):
            raise SystemExit(
                f"bench: unknown --compute_dtype arm {tok!r} "
                "(expected f32 and/or bf16)"
            )
        names.append(tok)
    if not names:
        return
    times = {}
    any_degraded = False
    for name in names:
        dt = jnp.bfloat16 if name == "bf16" else jnp.float32
        if args.model == "lenet":
            step, state, b = _build_lenet(args.batch or 32, dtype=dt)
        else:
            step, state, b = _build_resnet50_step(
                args.batch or 18, args.image, use_pallas=args.pallas,
                dtype=dt,
            )
        step, _ = _compile_with_flops(step, state, b)
        per_step, state, _, degraded = two_point_per_step(
            step, state, b, args.steps
        )
        times[name] = per_step
        record[f"compute_{name}_ms_per_step"] = round(per_step * 1e3, 3)
        if degraded:
            # Bool marker rides the record without becoming a gated
            # metric (obs_diff extracts numerics only).
            record[f"compute_{name}_degraded"] = True
            any_degraded = True
    if (
        "f32" in times and "bf16" in times and times["bf16"] > 0
        and not any_degraded  # a mixed-methodology ratio gates nothing
    ):
        record["bf16_step_speedup"] = round(
            times["f32"] / times["bf16"], 3
        )


def timing_label(scan_k: int, degraded: bool) -> str:
    """Three-way timing label, shared by bench.py and profile_step.py so
    identically-labeled numbers are measured identically."""
    if scan_k and not degraded:
        return f"scan_k{scan_k}_two_point"
    return "single_run_with_rtt" if degraded else "two_point"


def _time_steps(step, state, batch, steps, imgs_per_step):
    import jax
    import numpy as np

    raw_step = step
    step, flops_per_step = _compile_with_flops(step, state, batch)
    per_step, state, loss, degraded = two_point_per_step(
        step, state, batch, steps
    )
    # Device-truth timing: k steps per dispatch via lax.scan.  Skipped on
    # CPU, where dispatch is already free and the scanned program would
    # only pay a second full compile; elsewhere, falls back to the
    # per-call number if the scanned variant fails or runs slower.
    info = {"step_time_ms_percall": round(per_step * 1e3, 3)}
    if degraded:
        # Per-call number is a single-run average that re-includes the
        # fetch RTT — flagged so readers (and the vs_baseline methodology
        # correction) don't mistake it for a clean two-point measurement,
        # and never booked against the scan number as "dispatch overhead".
        info["percall_degraded"] = True
    if _SCAN_K > 0 and jax.default_backend() != "cpu":
        try:
            scan_per_step, state, loss, sdeg = scan_two_point(
                raw_step, state, batch, steps, _SCAN_K
            )
            if not sdeg and 0 < scan_per_step < per_step:
                info["timing_mode"] = timing_label(_SCAN_K, False)
                if not degraded:
                    info["dispatch_overhead_ms_per_step"] = round(
                        (per_step - scan_per_step) * 1e3, 3
                    )
                per_step, degraded = scan_per_step, False
        except Exception as e:
            print(f"bench: scan timing unavailable ({e!r})", file=sys.stderr)
    assert np.isfinite(loss), "non-finite loss in bench"
    return imgs_per_step / per_step, per_step, flops_per_step, degraded, info


def _relay_endpoints():
    """(host, probe_ports) from the first ``PALLAS_AXON_POOL_IPS`` entry,
    or None when no relay is configured.  The entry may carry an explicit
    ':port'; bare IPv6 addresses contain many colons — only a single-colon
    entry (or bracketed [v6]:port) is treated as host:port."""
    entry = (os.environ.get(_RELAY_VAR) or "").split(",")[0].strip()
    if not entry:
        return None
    host, probe_ports = entry, (8082, 8083)
    if entry.startswith("["):
        bracket, _, port_s = entry.partition("]")
        host = bracket[1:]
        port_s = port_s.lstrip(":")
        if port_s.isdigit():
            probe_ports = (int(port_s),)
    elif entry.count(":") == 1:
        maybe_host, _, port_s = entry.partition(":")
        if port_s.isdigit():
            host, probe_ports = maybe_host, (int(port_s),)
    return host, probe_ports


def _relay_open_ports():
    """TCP-probe the relay's gRPC ports (cheap, 2 s); None = no relay var."""
    import socket

    endpoints = _relay_endpoints()
    if endpoints is None:
        return None
    host, probe_ports = endpoints
    open_ports = []
    for port in probe_ports:
        try:
            with socket.create_connection((host, port), timeout=2):
                open_ports.append(port)
        except OSError:
            pass
    return open_ports


def _relay_diagnosis(mode: str = "hung") -> str:
    """Distinguish 'tunnel down' from 'claim wedged': the axon client dials
    the relay host named by ``PALLAS_AXON_POOL_IPS`` on :8082/:8083; if
    neither accepts a TCP connection, the gRPC client retries a refused
    connection forever and no amount of waiting helps.  ``mode`` names the
    observed failure ("hung" timeout vs "errored" nonzero exit) so the
    recorded note matches what happened."""
    endpoints = _relay_endpoints()
    if endpoints is None:
        return f"backend init {mode}; no TPU relay configured ({_RELAY_VAR} unset)"
    host, probe_ports = endpoints
    open_ports = _relay_open_ports()
    if not open_ports:
        ports = "/".join(str(p) for p in probe_ports)
        return (
            f"relay {host} ports {ports} refused — TPU tunnel likely "
            "down (advisory: the relay transport may not use these ports)"
        )
    return (
        f"relay {host} port(s) {open_ports} open but init {mode} — "
        "claim wedged?"
    )


def _wait_for_relay(max_wait_s: int):
    """Poll the relay ports with cheap TCP checks (not 150-s jax probes)
    for up to ``max_wait_s``.  Returns ``(ok, diagnosis)``: ok the moment
    a port accepts (or when no relay is configured, in which case jax
    decides the backend); on timeout, ``diagnosis`` describes the LAST
    observed port state (no re-probe — a port opening later would make a
    fresh probe contradict the recorded failure)."""
    deadline = time.monotonic() + max_wait_s
    first = True
    while True:
        open_ports = _relay_open_ports()
        if open_ports is None or open_ports:
            return True, None
        if first:
            print(
                f"bench: relay ports closed; polling TCP up to "
                f"{max_wait_s}s before falling back...",
                file=sys.stderr,
            )
            first = False
        # +10 for the upcoming sleep so the poll never overshoots its
        # budget by a whole cycle.
        if time.monotonic() + 10 >= deadline:
            host, probe_ports = _relay_endpoints()
            ports = "/".join(str(p) for p in probe_ports)
            return False, (
                f"relay {host} ports {ports} stayed closed for the full "
                "poll window"
            )
        time.sleep(10)


def _probe_backend():
    """Initialize the default backend in a subprocess with a timeout.

    Returns None on success (`jax.devices()` + one tiny computation
    complete); otherwise the observed failure mode, "hung" (timeout) or
    "errored" (nonzero exit).
    """
    code = (
        "import jax, jax.numpy as jnp; "
        "print(jax.default_backend()); "
        "print(float(jnp.ones((8, 8)).sum()))"
    )
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            timeout=_PROBE_TIMEOUT_S,
            capture_output=True,
            text=True,
        )
    except subprocess.TimeoutExpired:
        print(
            f"bench: backend probe hung >{_PROBE_TIMEOUT_S}s "
            f"({_relay_diagnosis('hung')})",
            file=sys.stderr,
        )
        return "hung"
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()[-3:]
        print(
            "bench: backend probe failed rc=%d: %s"
            % (proc.returncode, " | ".join(tail)),
            file=sys.stderr,
        )
        return "errored"
    return None


def _reexec_cpu_fallback(args, diagnosis: str) -> int:
    """Re-exec this script on CPU in a clean env; returns the child's rc."""
    env = {k: v for k, v in os.environ.items() if k != _RELAY_VAR}
    env["JAX_PLATFORMS"] = "cpu"
    if args.model == "lenet":
        # Honor an explicit lenet request (seconds on CPU).
        model_args = ["--model", "lenet"]
        if args.phase != "train":
            model_args += ["--phase", args.phase]
        steps = min(args.steps, 10)
    else:
        # The flagship model still gets timed, not a lenet stand-in:
        # reduced resolution and batch keep the full ResNet50-DWT step at
        # ~6.5 s on one CPU core (~45 s compile; ~100 s child total).
        model_args = ["--model", "resnet50", "--image", "96", "--batch", "4"]
        if args.pallas:  # keep the requested A/B variant in the fallback
            model_args.append("--pallas")
        if args.phase != "train":
            model_args += ["--phase", args.phase]
        steps = min(args.steps, 5)
    if getattr(args, "obs_trace", None):
        model_args += ["--obs_trace", args.obs_trace]
    if getattr(args, "harvest_depth", None):
        # The sweep arm rides the fallback too (the record path is a
        # host-side mechanism — its A/B is meaningful on any backend).
        model_args += ["--harvest_depth", args.harvest_depth]
    if getattr(args, "compute_dtype", None):
        # The precision sweep rides the fallback too: CPU bf16 is
        # emulated (the speedup will read ~1x or worse), but the record
        # keeps its fields so a --compare against a TPU baseline reports
        # an honest verdict instead of MISSING-by-accident.
        model_args += ["--compute_dtype", args.compute_dtype]
    if getattr(args, "compare", None):
        # The gate rides the fallback too: a CPU rerun still compares
        # against the baseline (like-for-like metric names make a TPU
        # baseline vs CPU fallback report MISSING, which is the honest
        # verdict).
        model_args += [
            "--compare", args.compare,
            "--compare_tolerance", str(args.compare_tolerance),
        ]
    cmd = [
        sys.executable,
        os.path.abspath(__file__),
        *model_args,
        "--steps",
        str(steps),
        "--no-probe",
        "--fallback-note",
        f"{diagnosis}; clean-env cpu rerun",
    ]
    return subprocess.call(cmd, env=env, cwd=os.path.dirname(os.path.abspath(__file__)))


def _maybe_compare(args, record) -> None:
    """Route the printed record through the shared cross-run gate
    (tools/obs_diff.py) when ``--compare`` names a baseline.  The record
    line always prints FIRST and the gate's table goes to stderr —
    stdout keeps the last-JSON-line-is-the-record contract — so the
    measurement is never lost to a gate verdict.  Exits nonzero on
    regression (3) / missing metric (4) / unusable baseline (2)."""
    if not args.compare:
        return
    sys.path.insert(
        0,
        os.path.join(os.path.dirname(os.path.abspath(__file__)), "tools"),
    )
    import obs_diff

    try:
        rc = obs_diff.gate(
            args.compare, record,
            default_tolerance_pct=args.compare_tolerance,
            out=sys.stderr,
        )
    except (OSError, ValueError) as e:
        # A typo'd/unreadable baseline must not turn a finished
        # multi-minute measurement into a traceback: diagnose and exit
        # with obs_diff's unusable-input code.
        print(f"bench: --compare failed: {e}", file=sys.stderr)
        sys.exit(2)
    if rc != 0:
        sys.exit(rc)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--model", choices=["lenet", "resnet50"], default="resnet50"
    )
    ap.add_argument("--steps", type=int, default=30)
    ap.add_argument(
        "--batch",
        type=int,
        default=None,
        help="per-domain-stream batch (default: reference recipe: "
        "18 for resnet50, 32 for lenet)",
    )
    ap.add_argument(
        "--image",
        type=int,
        default=224,
        help="resnet50 input resolution (the CPU fallback uses 96)",
    )
    ap.add_argument(
        "--pallas",
        action="store_true",
        help="resnet50 with the Pallas whitening kernels — run both ways "
        "on TPU to decide PERF.md's go/no-go at full-step level",
    )
    ap.add_argument(
        "--phase",
        choices=["train", "eval", "data"],
        default="train",
        help="train = fwd+bwd+update (the flagship metric); eval = the "
        "inference test() path (target branch, running stats); data = "
        "the input pipeline (tools/data_bench.py: imgs/s vs workers + "
        "seekable-sampler overhead — host-only, no device probe)",
    )
    ap.add_argument(
        "--harvest_depth",
        default=None,
        metavar="D0,D1,...",
        help="sweep arm (ISSUE-14): also time the RECORD path — "
        "dispatch + per-step metric handling through the "
        "train/harvest.py ring — at each listed depth (e.g. '0,2' for "
        "the sync-vs-async A/B).  Adds harvest_d<N>_ms_per_step fields "
        "(plus harvest_record_speedup when 0 and a deeper arm are both "
        "listed) to the record; --compare gates them like any metric",
    )
    ap.add_argument(
        "--compute_dtype",
        default=None,
        metavar="DT0,DT1,...",
        help="precision sweep arm: also time the train step rebuilt at "
        "each listed compute dtype ('f32,bf16' for the reduced-precision "
        "A/B).  Adds compute_<dt>_ms_per_step fields (plus "
        "bf16_step_speedup when both arms are listed) to the record; "
        "--compare gates them like any metric.  Params/optimizer state "
        "stay f32 in every arm — this prices exactly what the training "
        "CLIs' --compute_dtype flag changes",
    )
    ap.add_argument(
        "--no-probe",
        action="store_true",
        help="skip the subprocess backend probe (fallback path)",
    )
    ap.add_argument(
        "--obs_trace",
        default=None,
        help="span tracing: write a Chrome trace-event JSON of the bench "
        "run's spans (H2D staging, dispatch waits) to this path for "
        "tools/obs_report.py; DWT_OBS_TRACE env is the flagless form",
    )
    ap.add_argument(
        "--compare",
        default=None,
        metavar="BASELINE_JSON",
        help="regression gate: after measuring, diff this run's record "
        "against a stored baseline (e.g. BENCH_r05.json) through "
        "tools/obs_diff.py — prints the delta table and exits nonzero "
        "on regression (3) or a missing baseline metric (4)",
    )
    ap.add_argument(
        "--compare_tolerance",
        type=float,
        default=5.0,
        help="tolerance band in percent for --compare (default 5)",
    )
    ap.add_argument("--fallback-note", default=None, help=argparse.SUPPRESS)
    args = ap.parse_args()
    if args.pallas and args.model != "resnet50":
        ap.error("--pallas only applies to --model resnet50")
    if args.pallas and args.phase != "train":
        ap.error("--pallas is a training-path A/B; use --phase train")
    if args.harvest_depth and args.phase != "train":
        ap.error("--harvest_depth sweeps the TRAIN record path; "
                 "use --phase train")
    if args.compute_dtype and args.phase != "train":
        ap.error("--compute_dtype sweeps the TRAIN step; "
                 "use --phase train")

    if args.phase == "data":
        # Host-only arm: the input pipeline never touches the device, so
        # no backend probe — this arm keeps measuring when the chip
        # relay is down, and it rides the CPU-fallback re-exec verbatim.
        sys.path.insert(
            0,
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "tools"),
        )
        import data_bench

        record = data_bench.run(items=max(512, 64 * args.steps))
        if args.fallback_note:
            record["fallback"] = args.fallback_note
        print(json.dumps(record))
        _maybe_compare(args, record)
        return

    if not args.no_probe:
        # The subprocess jax probe is AUTHORITATIVE; the TCP port poll is
        # only advisory.  The relay's transport changed once already
        # (8082/8083 stopped listening while the backend kept working),
        # so closed probe ports must never skip the real probe — they
        # only inform how long to wait before giving up after a probe
        # failure.
        failure = _probe_backend()
        if failure is not None:
            # Probe failed: if the advisory ports are closed the tunnel
            # is plausibly down — poll cheaply (up to BENCH_RELAY_WAIT_S,
            # default 2 min; see the worst-case budget at
            # _PROBE_TIMEOUT_S) in case it comes back, then re-probe once
            # either way.
            relay_ok, poll_diagnosis = _wait_for_relay(
                int(os.environ.get("BENCH_RELAY_WAIT_S", "120"))
            )
            print("bench: retrying backend probe once...", file=sys.stderr)
            failure = _probe_backend()
            if failure is not None:
                diagnosis = _relay_diagnosis(failure)
                if not relay_ok and poll_diagnosis:
                    diagnosis += f"; tcp poll: {poll_diagnosis}"
                sys.exit(
                    _reexec_cpu_fallback(
                        args,
                        f"tpu backend init failed twice ({diagnosis})",
                    )
                )

    enable_compile_cache()
    import jax

    from dwt_tpu import obs

    obs.maybe_enable(args.obs_trace)
    if args.model == "lenet":
        batch = args.batch or 32
        if args.phase == "eval":
            imgs_per_sec, step_time, flops, degraded, tinfo = (
                _bench_lenet_eval(args.steps, batch)
            )
        else:
            imgs_per_sec, step_time, flops, degraded, tinfo = _bench_lenet(
                args.steps, batch
            )
        metric = f"lenet_dwt_{args.phase}_imgs_per_sec"
    else:
        batch = args.batch or 18
        if args.phase == "eval":
            (imgs_per_sec, step_time, flops, degraded, tinfo) = (
                _bench_resnet50_eval(args.steps, batch, args.image)
            )
        else:
            (imgs_per_sec, step_time, flops, degraded, tinfo) = (
                _bench_resnet50(
                    args.steps, batch, args.image, use_pallas=args.pallas
                )
            )
        px = "" if args.image == 224 else f"{args.image}px_"
        metric = f"resnet50_dwt_{px}{args.phase}_imgs_per_sec"
        if args.pallas:
            metric += "_pallas"

    flops_source = "xla_cost_analysis"
    if flops is None:
        flops_source = "analytic_estimate"
        n_imgs = (2 if args.model == "lenet" else 3) * batch
        per_img = _ANALYTIC_TRAIN_FLOPS_PER_IMG[args.model]
        if args.phase == "eval":
            n_imgs = batch
            per_img /= 3  # fwd only (train ~= 3x fwd)
        if args.model == "resnet50" and args.image != 224:
            per_img *= (args.image / 224) ** 2  # conv FLOPs scale with area
        flops = per_img * n_imgs

    device_kind = jax.devices()[0].device_kind
    peak = _peak_flops(device_kind)
    mfu = None
    if peak is not None and flops:
        mfu = flops / step_time / peak

    timing_label = tinfo.get(
        "timing_mode", "single_run_with_rtt" if degraded else "two_point"
    )
    # Only normalize runs comparable to the anchored workload — the
    # flagship 224px metric and its --pallas A/B twin (same model, same
    # shapes, different whitening lowering: the one ratio PERF.md's
    # go/no-go needs).  A 96px CPU fallback divided by a 224px TPU anchor
    # would be a meaningless ratio.  Methodology guard: when this run's
    # timing mode differs from the anchor's (BASELINE_TIMING), the ratio
    # uses the run's per-call number — a scan-amortized step time divided
    # into a per-call anchor would book the dispatch overhead as speedup.
    anchored = metric in (BASELINE_METRIC, BASELINE_METRIC + "_pallas")
    vs_value = imgs_per_sec
    if timing_label != BASELINE_TIMING and "step_time_ms_percall" in tinfo:
        vs_value = (
            imgs_per_sec * step_time / (tinfo["step_time_ms_percall"] / 1e3)
        )
    vs = (
        vs_value / BASELINE_IMGS_PER_SEC
        if BASELINE_IMGS_PER_SEC is not None and anchored
        else 1.0
    )
    record = {
        "metric": metric,
        "value": round(imgs_per_sec, 2),
        "unit": "imgs/sec",
        "vs_baseline": round(vs, 4),
        # The anchor travels with the record so rounds stay comparable
        # without reading source (null when this record's metric is not
        # anchored — a 96px/lenet value vs the 224px anchor would be a
        # meaningless ratio).
        "baseline_imgs_per_sec": (
            BASELINE_IMGS_PER_SEC if anchored else None
        ),
        "baseline_timing": BASELINE_TIMING if anchored else None,
        "step_time_ms": round(step_time * 1e3, 3),
        "mfu": None if mfu is None else round(mfu, 4),
        "flops_per_step": flops,
        "flops_source": flops_source,
        "backend": jax.default_backend(),
        "device_kind": device_kind,
        # scan_kN_two_point = N device steps per dispatch (amortizes the
        # relay dispatch round-trip: the chip-truth number);
        # two_point = fetch-synchronized per-call timing;
        # single_run_with_rtt = degenerate fallback that re-includes the
        # fetch round-trip (fast steps + timing jitter).
        "timing": timing_label,
    }
    for k in (
        "step_time_ms_percall",
        "percall_degraded",
        "dispatch_overhead_ms_per_step",
    ):
        if k in tinfo:
            record[k] = tinfo[k]
    if args.model == "resnet50":
        record["image_size"] = args.image
    if args.fallback_note:
        record["fallback"] = args.fallback_note
    if args.harvest_depth:
        _harvest_sweep(args, record)
    if args.compute_dtype:
        _compute_dtype_sweep(args, record)
    obs.export()  # no-op unless --obs_trace/DWT_OBS_TRACE
    print(json.dumps(record))
    _maybe_compare(args, record)


if __name__ == "__main__":
    main()
