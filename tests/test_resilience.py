"""End-to-end recovery proofs for dwt_tpu.resilience (CPU, synthetic data).

Every failure mode the resilience layer defends against is injected
deterministically (dwt_tpu/resilience/inject.py) and the recovery path is
driven to completion:

* kill-mid-save -> resume picks the newest *valid* checkpoint;
* truncated / digest-corrupt checkpoint -> newest-valid fallback;
* NaN at step k -> the configured guard policy fires (halt raises,
  skip_step continues from the in-memory snapshot, rollback restores the
  newest valid checkpoint and trains to completion);
* corrupt dataset item -> quarantined, epoch completes;
* SIGTERM mid-training -> final checkpoint + exit code 0, on both the
  per-step and steps_per_dispatch paths (subprocess tests).

All tests are tier-1-safe: JAX_PLATFORMS=cpu (conftest), synthetic data,
tiny models, no sleeps beyond subprocess polling.
"""

import json
import os
import signal
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dwt_tpu.nn import LeNetDWT
from dwt_tpu.resilience import (
    AsyncCheckpointer,
    DivergenceError,
    DivergenceGuard,
    PreemptionHandler,
    inject,
)
from dwt_tpu.resilience.inject import FaultPlan, FlakyDataset, SimulatedCrash
from dwt_tpu.train import adam_l2, create_train_state
from dwt_tpu.utils.checkpoint import (
    MANIFEST,
    is_valid_checkpoint,
    latest_step,
    params_digest,
    restore_state,
    save_state,
    valid_steps,
)


@pytest.fixture(autouse=True)
def _disarm_faults():
    """No injected fault may leak between tests (plans are process-global)."""
    yield
    inject.disarm()


# Built once per process: eager flax init costs seconds on CPU and the
# ~20 call sites in this file treat the state as immutable (JAX arrays
# are never mutated in place; .replace builds fresh pytrees), so sharing
# the base keeps the tier-1 wall clock inside its budget.
_TINY_BASE = None


def _tiny_state(step=0, scale=1.0):
    global _TINY_BASE
    if _TINY_BASE is None:
        model = LeNetDWT(group_size=4)
        tx = adam_l2(1e-3)
        sample = jnp.zeros((2, 4, 28, 28, 1), jnp.float32)
        _TINY_BASE = create_train_state(model, jax.random.key(0), sample, tx)
    state = _TINY_BASE
    if scale != 1.0:
        state = state.replace(
            params=jax.tree.map(lambda x: x * scale, state.params)
        )
    return state.replace(step=state.step + step)


# ------------------------------------------------- checkpoint validation


def test_kill_mid_save_resumes_newest_valid(tmp_path):
    """Acceptance (a): a crash between the checkpoint write and the atomic
    finalize rename must leave the previous checkpoint authoritative."""
    ck = str(tmp_path / "ck")
    good = _tiny_state(step=1)
    save_state(ck, 1, good)

    inject.arm(FaultPlan(crash_in_save=True))
    with pytest.raises(SimulatedCrash):
        save_state(ck, 2, _tiny_state(step=2, scale=2.0))

    # The torn save left no finalized "2": step 1 is still the newest
    # valid checkpoint and restores bit-exact.
    assert latest_step(ck) == 1
    restored = restore_state(ck, good)
    assert int(restored.step) == 1
    for a, b in zip(jax.tree.leaves(good), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # The next successful save finalizes AND sweeps the stale tmp dir.
    inject.disarm()
    save_state(ck, 2, _tiny_state(step=2))
    assert latest_step(ck) == 2
    assert not [d for d in os.listdir(ck) if d.startswith(".tmp-")]


def test_truncated_checkpoint_falls_back(tmp_path):
    """A checkpoint whose bytes on disk no longer match its manifest is
    invalid; latest_step/restore_state fall back to the older step."""
    ck = str(tmp_path / "ck")
    s1 = _tiny_state(step=1)
    save_state(ck, 1, s1)
    save_state(ck, 2, _tiny_state(step=2, scale=2.0))
    assert valid_steps(ck) == [1, 2]

    # Truncate the largest non-manifest file of step 2 (a dead filesystem
    # flushing a prefix of the array bytes).
    step2 = os.path.join(ck, "2")
    files = [
        os.path.join(sub, n)
        for sub, _, names in os.walk(step2)
        for n in names
        if n != MANIFEST
    ]
    victim = max(files, key=os.path.getsize)
    with open(victim, "r+b") as f:
        f.truncate(max(0, os.path.getsize(victim) // 2))

    assert not is_valid_checkpoint(step2)
    assert latest_step(ck) == 1
    restored = restore_state(ck, s1)
    assert int(restored.step) == 1
    # An explicitly requested truncated step must refuse, not guess.
    with pytest.raises(FileNotFoundError, match="truncated"):
        restore_state(ck, s1, step=2)


def test_digest_mismatch_falls_back(tmp_path):
    """Sizes intact but content wrong (bit corruption): the post-restore
    digest check rejects the checkpoint and fallback still works."""
    ck = str(tmp_path / "ck")
    s1 = _tiny_state(step=1)
    save_state(ck, 1, s1)
    save_state(ck, 2, _tiny_state(step=2))

    manifest_path = os.path.join(ck, "2", MANIFEST)
    manifest = json.load(open(manifest_path))
    size = os.path.getsize(manifest_path)
    manifest["params_digest"] = "0" * len(manifest["params_digest"])
    raw = json.dumps(manifest, indent=1)
    with open(manifest_path, "w") as f:
        f.write(raw.ljust(size))  # keep the recorded size valid

    assert is_valid_checkpoint(os.path.join(ck, "2"))  # sizes check out...
    restored = restore_state(ck, s1)  # ...but restore rejects the digest
    assert int(restored.step) == 1


def test_nonfinite_state_is_never_checkpointed(tmp_path):
    """A NaN-poisoned state must not become the newest 'valid' checkpoint:
    the digest proves integrity, not health, so rollback/resume would
    faithfully restore the poison.  save_state gates on finiteness."""
    ck = str(tmp_path / "ck")
    good = _tiny_state(step=1)
    save_state(ck, 1, good)
    bad = good.replace(
        step=good.step + 1,
        params=jax.tree.map(lambda x: x * jnp.nan, good.params),
    )
    assert save_state(ck, 2, bad) is None
    assert latest_step(ck) == 1  # the poisoned save left no artifact
    restored = restore_state(ck, good)
    assert int(restored.step) == 1


def test_params_digest_is_content_sensitive():
    s = _tiny_state()
    assert params_digest(s.params) == params_digest(s.params)
    bumped = jax.tree.map(lambda x: x + 1, s.params)
    assert params_digest(s.params) != params_digest(bumped)


# --------------------------------------------- async checkpoint pipeline


def test_async_save_is_byte_compatible_with_sync(tmp_path):
    """The writer thread runs save_state wholesale, so the on-disk format
    (manifest digest, file set) is identical to a synchronous save and the
    unmodified restore path accepts the async-written artifact."""
    state = _tiny_state(step=3)
    save_state(str(tmp_path / "sync"), 3, state)
    acp = AsyncCheckpointer()
    acp.save(str(tmp_path / "async"), 3, state)
    assert acp.flush() is not None

    m_sync = json.load(open(tmp_path / "sync" / "3" / MANIFEST))
    m_async = json.load(open(tmp_path / "async" / "3" / MANIFEST))
    # Same param bytes digested (Orbax's OCDBT data-file NAMES are
    # content-addressed per save, so the file lists aren't comparable).
    assert m_sync["params_digest"] == m_async["params_digest"]
    assert m_sync["step"] == m_async["step"]
    restored = restore_state(str(tmp_path / "async"), state)
    assert int(restored.step) == 3
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_async_kill_mid_save_resumes_newest_valid(tmp_path):
    """A crash inside the background writer must surface on flush and
    leave the previous checkpoint authoritative — same guarantee as the
    synchronous kill-mid-save case, shifted to the rendezvous point."""
    ck = str(tmp_path / "ck")
    good = _tiny_state(step=1)
    save_state(ck, 1, good)

    inject.arm(FaultPlan(crash_in_save=True))
    acp = AsyncCheckpointer()
    acp.save(ck, 2, _tiny_state(step=2, scale=2.0))
    with pytest.raises(SimulatedCrash):
        acp.flush()

    # The torn async save left no finalized "2": resume sees step 1.
    assert latest_step(ck) == 1
    restored = restore_state(ck, good)
    assert int(restored.step) == 1

    # The error was one-shot; the pipeline keeps working afterwards.
    inject.disarm()
    acp.save(ck, 2, _tiny_state(step=2))
    assert acp.flush() is not None
    assert latest_step(ck) == 2


def test_async_writer_error_surfaces_on_next_enqueue(tmp_path):
    """Without an intervening flush, a writer failure is raised by the
    NEXT save call (before the new save is enqueued) — never swallowed."""
    ck = str(tmp_path / "ck")
    inject.arm(FaultPlan(crash_in_save=True))
    acp = AsyncCheckpointer()
    acp.save(ck, 1, _tiny_state(step=1))
    with pytest.raises(SimulatedCrash):
        acp.save(ck, 2, _tiny_state(step=2))
    assert acp.in_flight is None  # the failed enqueue started nothing
    acp.save(ck, 2, _tiny_state(step=2))  # error was consumed; pipeline ok
    acp.flush()
    assert latest_step(ck) == 2


def test_async_close_without_raise_clears_error_keeps_pipeline_usable(tmp_path):
    """The rollback rendezvous joins the writer WITHOUT re-raising: a
    stale failed periodic save (already logged) must not abort the
    recovery path, and the pipeline must keep working afterwards."""
    ck = str(tmp_path / "ck")
    inject.arm(FaultPlan(crash_in_save=True))
    acp = AsyncCheckpointer()
    acp.save(ck, 1, _tiny_state(step=1))
    acp.close(raise_errors=False)  # no raise despite the writer failure
    acp.save(ck, 2, _tiny_state(step=2))
    assert acp.flush() is not None
    assert latest_step(ck) == 2


def test_async_flush_joins_in_flight_save(tmp_path, monkeypatch):
    """flush() — the rollback/preempt/best rendezvous — must join the
    writer before returning: a stalled in-flight save becomes durably
    visible to the subsequent restore walk, not raced."""
    import threading

    import dwt_tpu.utils.checkpoint as ckpt_mod

    started, release = threading.Event(), threading.Event()
    real_save = ckpt_mod.save_state

    def slow_save(*a, **kw):
        started.set()
        assert release.wait(30)
        return real_save(*a, **kw)

    monkeypatch.setattr(ckpt_mod, "save_state", slow_save)
    ck = str(tmp_path / "ck")
    acp = AsyncCheckpointer()
    acp.save(ck, 1, _tiny_state(step=1))
    assert started.wait(30)
    assert latest_step(ck) is None  # in flight: nothing finalized yet
    threading.Timer(0.05, release.set).start()
    acp.flush()  # blocks on the writer; returns only once finalized
    assert latest_step(ck) == 1
    restored = restore_state(ck, _tiny_state(step=1))
    assert int(restored.step) == 1


def test_async_second_save_applies_backpressure(tmp_path, monkeypatch):
    """A save arriving while one is in flight joins it (single in-flight),
    so saves finalize in order and the queue never grows unboundedly."""
    import threading

    import dwt_tpu.utils.checkpoint as ckpt_mod

    started, release = threading.Event(), threading.Event()
    real_save = ckpt_mod.save_state

    def slow_save(*a, **kw):
        started.set()
        assert release.wait(30)
        return real_save(*a, **kw)

    monkeypatch.setattr(ckpt_mod, "save_state", slow_save)
    ck = str(tmp_path / "ck")
    acp = AsyncCheckpointer()
    acp.save(ck, 1, _tiny_state(step=1))
    assert started.wait(30)
    threading.Timer(0.05, release.set).start()
    acp.save(ck, 2, _tiny_state(step=2))  # must join save 1 first
    assert 1 in valid_steps(ck)  # save 1 was finalized before 2 enqueued
    acp.flush()
    assert valid_steps(ck) == [1, 2]


# ------------------------------------------- multi-host host-shard format


def _corrupt_shard(ck, step, proc=0):
    """Truncate a shard's manifest so its recorded sizes no longer hold."""
    from dwt_tpu.utils.checkpoint import SHARD_MANIFEST, _mh_tmp_dir

    shard = os.path.join(_mh_tmp_dir(ck, step), f"shard_{proc}")
    blob = os.path.join(shard, "leaves.bin")
    with open(blob, "r+b") as f:
        f.truncate(os.path.getsize(blob) // 2)
    return shard


def test_host_shard_save_promote_restore_byte_compatible(tmp_path):
    """The collective-free host-shard format restores the exact same
    values as the synchronous Orbax path — byte-compatible state, with
    the manifest/validity/fallback contracts intact."""
    from dwt_tpu.utils.checkpoint import (
        host_fetch,
        promote_host_shards,
        save_host_shard,
    )

    state = _tiny_state(step=3)
    save_state(str(tmp_path / "sync"), 3, state)

    ck = str(tmp_path / "sh")
    host = host_fetch(state)
    assert save_host_shard(ck, 3, host, process_index=0)
    # Unpromoted: invisible to every validity/ranking walk.
    assert valid_steps(ck) == [] and latest_step(ck) is None
    path = promote_host_shards(ck, 3, process_count=1)
    assert valid_steps(ck) == [3] and is_valid_checkpoint(path)
    manifest = json.load(open(os.path.join(path, MANIFEST)))
    assert manifest["format"] == "host_shards"

    r_sync = restore_state(str(tmp_path / "sync"), state)
    r_shard = restore_state(ck, state)
    assert int(r_shard.step) == 3
    for a, b in zip(jax.tree.leaves(r_sync), jax.tree.leaves(r_shard)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_host_shard_promotion_refuses_torn_shard(tmp_path):
    """A host dying mid-shard-write leaves a torn shard; promotion must
    refuse (previous step stays authoritative) and restore must fall
    back past the unpromoted tmp dir."""
    from dwt_tpu.utils.checkpoint import (
        host_fetch,
        promote_host_shards,
        save_host_shard,
    )

    ck = str(tmp_path / "ck")
    good = _tiny_state(step=1)
    save_state(ck, 1, good)

    host = host_fetch(_tiny_state(step=2, scale=2.0))
    save_host_shard(ck, 2, host, process_index=0)
    save_host_shard(ck, 2, host, process_index=1)
    _corrupt_shard(ck, 2, proc=1)
    with pytest.raises(OSError, match="missing or torn"):
        promote_host_shards(ck, 2, process_count=2)
    # Nothing finalized: the previous step is still the resume source.
    assert latest_step(ck) == 1
    assert int(restore_state(ck, good).step) == 1


def test_host_shard_duplicate_promotion_is_idempotent(tmp_path):
    """A same-step save can be enqueued twice (a notice-driven proactive
    save coinciding with the cadence save), queueing two promotions: the
    second must succeed idempotently — NOT raise 'missing or torn' after
    the first consumed the tmp dir (that error would abort a healthy run
    at the next rendezvous)."""
    from dwt_tpu.resilience import MultiHostAsyncCheckpointer

    state = _tiny_state(step=5)
    ck = str(tmp_path / "ck")
    acp = MultiHostAsyncCheckpointer()
    acp.save(ck, 5, state)
    acp.flush()
    acp.save(ck, 5, state)  # duplicate save of the same step + dir
    acp.flush()
    acp.promote_up_to(acp.done_seq)  # both pending entries are due
    assert valid_steps(ck) == [5]
    acp.flush()  # no queued promotion error may surface
    assert int(restore_state(ck, state).step) == 5


def test_host_shard_missing_shard_refuses_promotion(tmp_path):
    """Promotion with fewer shards than processes (a writer that never
    ran) must refuse — the consensus said done, so this is a real fault."""
    from dwt_tpu.utils.checkpoint import (
        host_fetch,
        promote_host_shards,
        save_host_shard,
    )

    ck = str(tmp_path / "ck")
    save_host_shard(ck, 2, host_fetch(_tiny_state(step=2)), process_index=0)
    with pytest.raises(OSError, match="shard_1"):
        promote_host_shards(ck, 2, process_count=2)


def test_host_shard_digest_corruption_falls_back(tmp_path):
    """A promoted shard checkpoint whose recorded digest no longer
    matches the bytes must fail restore and fall back to an older valid
    step — the same defense the Orbax path has."""
    from dwt_tpu.utils.checkpoint import (
        SHARD_MANIFEST,
        host_fetch,
        promote_host_shards,
        save_host_shard,
    )

    ck = str(tmp_path / "ck")
    good = _tiny_state(step=1)
    save_state(ck, 1, good)
    save_host_shard(ck, 2, host_fetch(_tiny_state(step=2, scale=2.0)), 0)
    promote_host_shards(ck, 2, process_count=1)

    # Same-size digest corruption: still LISTS as valid, fails restore.
    mpath = os.path.join(ck, "2", "shard_0", SHARD_MANIFEST)
    manifest = json.load(open(mpath))
    size = os.path.getsize(mpath)
    manifest["params_digest"] = "0" * len(manifest["params_digest"])
    with open(mpath, "w") as f:
        f.write(json.dumps(manifest, indent=1).ljust(size))
    assert latest_step(ck) == 2  # size-valid…
    restored = restore_state(ck, good)  # …but restore walks past it
    assert int(restored.step) == 1


def test_host_shard_refuses_nonfinite_params(tmp_path):
    """The finite gate runs host-side on the writer thread: a NaN state
    writes NO shard (same contract as save_state returning None)."""
    from dwt_tpu.utils.checkpoint import host_fetch, save_host_shard

    state = _tiny_state(step=2)
    state = state.replace(
        params=jax.tree.map(lambda x: x * jnp.nan, state.params)
    )
    ck = str(tmp_path / "ck")
    assert not save_host_shard(ck, 2, host_fetch(state), 0)
    assert not os.path.exists(os.path.join(ck, ".tmp-mh-2", "shard_0"))


def test_multihost_async_ckpt_end_to_end_single_process(tmp_path):
    """MultiHostAsyncCheckpointer driven exactly like the loops drive it
    (save → boundary promote at the agreed done step → flush), forced on
    one process: done bits advance only after ALL targets' shards are
    durable, promotion finalizes, and the restored state matches."""
    from dwt_tpu.resilience import MultiHostAsyncCheckpointer

    state = _tiny_state(step=5)
    ck = str(tmp_path / "ck")
    acp = MultiHostAsyncCheckpointer()
    assert acp.done_seq == -1
    acp.save(ck, 5, state)
    acp.flush()
    assert acp.done_seq == 1  # save #1 fully written
    assert valid_steps(ck) == []  # written, not yet promoted
    acp.promote_up_to(acp.done_seq)
    assert valid_steps(ck) == [5]
    restored = restore_state(ck, state)
    assert int(restored.step) == 5
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # save_multi: one snapshot, two targets (periodic + anchor), one
    # done-seq advance covering BOTH.
    anchors = str(tmp_path / "ck" / "anchors")
    acp.save_multi([(ck, {}), (anchors, {})], 7, _tiny_state(step=7))
    acp.flush()
    assert acp.done_seq == 2
    acp.promote_up_to(2)
    assert valid_steps(ck) == [5, 7] and valid_steps(anchors) == [7]


def test_collectives_refused_on_writer_thread():
    """The always-on shim: any collective call site reached from a
    checkpoint writer thread must raise, not deadlock a pod later."""
    import threading

    from dwt_tpu.resilience.coord import Coordinator, assert_not_writer_thread

    # Direct: a writer-named thread is refused, the main thread passes.
    assert_not_writer_thread("test")  # main thread: fine
    errors = []

    def run():
        try:
            Coordinator(enabled=True).decide(stop=True)
        except RuntimeError as e:
            errors.append(str(e))

    t = threading.Thread(target=run, name="dwt-ckpt-writer-3")
    t.start()
    t.join()
    assert errors and "pure I/O" in errors[0]

    # save_state's multi-host path is guarded too (single-host writers
    # legitimately run save_state, so the guard gates on process count —
    # assert the call site exists rather than spinning up a pod).
    import inspect

    from dwt_tpu.utils import checkpoint as ckpt_mod

    assert "assert_not_writer_thread" in inspect.getsource(ckpt_mod.save_state)


# ---------------------------------------------------- preemption notice


def test_notice_watcher_file_source(tmp_path):
    """The generic scheduler integration: the notice file coming into
    existence latches ``noticed``."""
    from dwt_tpu.resilience import NoticeWatcher

    path = str(tmp_path / "preempt-notice")
    with NoticeWatcher(file_path=path, poll_s=0.1) as nw:
        assert nw.enabled and not nw.noticed
        time.sleep(0.25)
        assert not nw.noticed  # no false positives while absent
        open(path, "w").close()
        deadline = time.time() + 5.0
        while not nw.noticed and time.time() < deadline:
            time.sleep(0.05)
        assert nw.noticed


def test_notice_watcher_metadata_stub():
    """The GCE path against a local metadata stub: 'TRUE' latches the
    notice; anything else does not."""
    import http.server
    import threading

    from dwt_tpu.resilience import NoticeWatcher

    body = {"value": b"FALSE"}

    class H(http.server.BaseHTTPRequestHandler):
        def do_GET(self):
            # GCE semantics: the header must be present.
            assert self.headers.get("Metadata-Flavor") == "Google"
            self.send_response(200)
            self.end_headers()
            self.wfile.write(body["value"])

        def log_message(self, *a):
            pass

    srv = http.server.HTTPServer(("127.0.0.1", 0), H)
    thread = threading.Thread(target=srv.serve_forever, daemon=True)
    thread.start()
    url = f"http://127.0.0.1:{srv.server_port}/preempted"
    try:
        with NoticeWatcher(metadata=True, metadata_url=url, poll_s=0.1) as nw:
            time.sleep(0.3)
            assert not nw.noticed  # FALSE: not preempted yet
            body["value"] = b"TRUE"
            deadline = time.time() + 5.0
            while not nw.noticed and time.time() < deadline:
                time.sleep(0.05)
            assert nw.noticed
    finally:
        srv.shutdown()
        thread.join(timeout=5)


def test_notice_injected_flag_and_disarm():
    """notice_at_step latches the module flag an inert watcher still
    reads; inject.disarm() clears it (test hygiene)."""
    from dwt_tpu.resilience import NoticeWatcher, inject as inj
    from dwt_tpu.resilience.inject import FaultPlan

    nw = NoticeWatcher()  # no sources: inert, no thread
    assert not nw.enabled and not nw.noticed
    inj.arm(FaultPlan(notice_at_step=3))
    inj.at_step(2)
    assert not nw.noticed
    inj.at_step(3)
    assert nw.noticed
    inj.disarm()
    assert not nw.noticed


def test_boundary_notice_triggers_one_proactive_save():
    """The step boundary fires on_notice exactly once (the notice stays
    latched, the save must not repeat), records notice_step, and skips
    the save when stopping anyway."""
    from dwt_tpu.resilience import HangWatchdog, NoticeWatcher, PreemptionHandler
    from dwt_tpu.resilience.coord import Coordinator
    from dwt_tpu.resilience.inject import FaultPlan
    from dwt_tpu.train.loop import _StepBoundary

    calls = []
    boundary = _StepBoundary(
        guard=None,
        preempt=PreemptionHandler(),  # not entered: should_stop False
        coord=Coordinator(enabled=False),
        watchdog=HangWatchdog(0.0),
        notice_watcher=NoticeWatcher(),
    )
    boundary.on_notice = lambda st: calls.append(int(st)) or 42
    state = 11  # boundary treats state opaquely with guard=None
    state, stop = boundary(state, {}, 1, 1)
    assert not calls and boundary.notice_step is None
    inject.arm(FaultPlan(notice_at_step=2))
    state, stop = boundary(state, {}, 1, 2)
    assert calls == [11] and boundary.notice_step == 42 and not stop
    state, stop = boundary(state, {}, 1, 3)
    assert calls == [11]  # latched notice does not re-save


# ----------------------------------------------------- divergence guard


def _digits_argv(tmp_path, **over):
    base = {
        "synthetic_size": 32,
        "source_batch_size": 8,
        "target_batch_size": 8,
        "test_batch_size": 16,
        "group_size": 4,
        "epochs": 2,
        "log_interval": 1,
        "metrics_jsonl": str(tmp_path / "metrics.jsonl"),
    }
    base.update(over)
    argv = ["--synthetic"]
    for k, v in base.items():
        argv += [f"--{k}", str(v)]
    return argv


def _records(tmp_path):
    path = tmp_path / "metrics.jsonl"
    return [json.loads(l) for l in path.read_text().splitlines()]


def test_guard_halt_raises_on_injected_nan(tmp_path):
    from dwt_tpu.cli.usps_mnist import main

    inject.arm(FaultPlan(nan_at_step=3))
    with pytest.raises(DivergenceError, match="non-finite"):
        main(_digits_argv(tmp_path, guard_policy="halt", guard_interval=1))
    kinds = [r["kind"] for r in _records(tmp_path)]
    assert "divergence" in kinds


def test_guard_skip_step_recovers_and_completes(tmp_path):
    from dwt_tpu.cli.usps_mnist import main

    inject.arm(FaultPlan(nan_at_step=3))
    acc = main(
        _digits_argv(tmp_path, guard_policy="skip_step", guard_interval=1)
    )
    assert 0.0 <= acc <= 100.0
    kinds = [r["kind"] for r in _records(tmp_path)]
    assert "skip_step" in kinds
    # Training ran past the divergence to the final-epoch eval.
    tests = [r for r in _records(tmp_path) if r["kind"] == "test"]
    assert tests and tests[-1]["epoch"] == 1
    assert np.isfinite(tests[-1]["loss"])


def test_guard_rollback_restores_checkpoint_and_completes(tmp_path):
    """Acceptance (b): NaN at a step past the first epoch checkpoint; the
    rollback policy restores that checkpoint, re-seeds the data order, and
    the run still trains to completion with finite metrics."""
    from dwt_tpu.cli.usps_mnist import main

    ck = str(tmp_path / "ck")
    inject.arm(FaultPlan(nan_at_step=6))  # epoch 1 (steps/epoch = 4)
    acc = main(
        _digits_argv(
            tmp_path,
            epochs=3,
            guard_policy="rollback",
            guard_interval=1,
            ckpt_dir=ck,
            ckpt_every_epochs=1,
            anchor_every=1,
        )
    )
    assert 0.0 <= acc <= 100.0
    recs = _records(tmp_path)
    rollbacks = [r for r in recs if r["kind"] == "rollback"]
    assert len(rollbacks) == 1
    # Rolled back TO the epoch-1 checkpoint (step 4) FROM the poisoned step.
    assert rollbacks[0]["step"] == 4
    assert rollbacks[0]["from_step"] == 6
    assert rollbacks[0]["source"] == "checkpoint"
    tests = [r for r in recs if r["kind"] == "test"]
    assert tests[-1]["epoch"] == 2 and np.isfinite(tests[-1]["loss"])
    assert latest_step(ck) == 3 * 4
    # --anchor_every=1 also saved per-epoch anchors under ckpt_dir/anchors
    # (never pruned; the epoch replayed after the rollback re-saves its
    # anchor idempotently).
    from dwt_tpu.train.loop import _anchor_dir

    assert valid_steps(_anchor_dir(ck)) == [4, 8, 12]


@pytest.mark.slow  # ~23 s — the per-step rollback sibling above stays
# tier-1; this chunked variant re-proves the same restore walk through
# the scanned-dispatch boundary (chunk-boundary guard checks are also
# covered by the chaos matrix).
def test_guard_rollback_chunked_path(tmp_path):
    """The steps_per_dispatch path only regains host control at chunk
    boundaries; a mid-chunk NaN must still be caught and rolled back."""
    from dwt_tpu.cli.usps_mnist import main

    ck = str(tmp_path / "ck")
    inject.arm(FaultPlan(nan_at_step=6))
    acc = main(
        _digits_argv(
            tmp_path,
            epochs=3,
            steps_per_dispatch=2,
            guard_policy="rollback",
            guard_interval=1,
            ckpt_dir=ck,
            ckpt_every_epochs=1,
        )
    )
    assert 0.0 <= acc <= 100.0
    recs = _records(tmp_path)
    assert [r["kind"] for r in recs].count("rollback") == 1
    tests = [r for r in recs if r["kind"] == "test"]
    assert tests[-1]["epoch"] == 2 and np.isfinite(tests[-1]["loss"])


def test_guard_rollback_without_checkpoint_uses_memory_snapshot(tmp_path):
    from dwt_tpu.cli.usps_mnist import main

    inject.arm(FaultPlan(nan_at_step=3))
    acc = main(
        _digits_argv(tmp_path, guard_policy="rollback", guard_interval=1)
    )
    assert 0.0 <= acc <= 100.0
    rollbacks = [r for r in _records(tmp_path) if r["kind"] == "rollback"]
    assert rollbacks and rollbacks[0]["source"] == "memory"


@pytest.mark.slow
def test_guard_ladder_transient_nan_backs_off_and_recovers(tmp_path):
    """Acceptance (ladder, transient): a one-off NaN engages the lr_backoff
    rung — revert to the in-memory good state, scale updates down — and
    after the configured clean checks the scale recovers.  NO rollback is
    spent, NO checkpoint restore happens.

    Slow-marked for the tier-1 budget (PR 6): the ladder's rungs and
    escalation order stay tier-1-pinned by
    test_guard_ladder_persistent_nan_escalates_in_order.

    The same run also proves --keep_ckpts pruning (one CLI run serves
    both assertions — the tier-1 budget is full): the main dir keeps only
    the newest N periodic steps while anchors are never pruned."""
    from dwt_tpu.cli.usps_mnist import main
    from dwt_tpu.train.loop import _anchor_dir

    ck = str(tmp_path / "ck")
    inject.arm(FaultPlan(nan_at_step=3))
    # harvest_depth=0 pins the legacy synchronous guard check: the
    # exact checkpoint-step arithmetic below depends on WHICH boundary
    # detects the NaN, and under harvesting that is timing-dependent
    # within the (bounded) ring staleness.  The harvested ladder is
    # covered by tests/test_chaos.py::
    # test_chaos_nan_with_harvest_depth_detects_within_depth and the
    # staleness units in tests/test_harvest.py.
    acc = main(
        _digits_argv(
            tmp_path,
            epochs=3,
            harvest_depth=0,
            guard_policy="rollback",
            guard_interval=1,
            guard_lr_backoff=0.5,
            guard_backoff_recovery=2,
            ckpt_dir=ck,
            ckpt_every_epochs=1,
            anchor_every=1,
            keep_ckpts=2,
        )
    )
    assert 0.0 <= acc <= 100.0
    recs = _records(tmp_path)
    kinds = [r["kind"] for r in recs]
    assert "lr_backoff" in kinds and "lr_recover" in kinds
    assert "rollback" not in kinds  # the mild rung absorbed the spike
    backoff = next(r for r in recs if r["kind"] == "lr_backoff")
    recover = next(r for r in recs if r["kind"] == "lr_recover")
    assert backoff["scale"] == 0.5 and recover["scale"] == 1.0
    tests = [r for r in recs if r["kind"] == "test"]
    assert tests[-1]["epoch"] == 2 and np.isfinite(tests[-1]["loss"])
    # keep_ckpts: the in-memory revert at step 3 shifts epoch boundaries
    # back one step (state.step regresses by 1, gstep does not), so the
    # three periodic saves land at 3, 7, 11 — pruned to the newest 2;
    # per-epoch anchors keep all three.
    assert valid_steps(ck) == [7, 11]
    assert valid_steps(_anchor_dir(ck)) == [3, 7, 11]


def test_guard_ladder_persistent_nan_escalates_in_order(tmp_path):
    """Acceptance (ladder, persistent): a NaN burst walks the full ladder —
    lr_backoff first, then (striking again while backed off) rollback,
    then (rollback budget spent) halt — in that order."""
    from dwt_tpu.cli.usps_mnist import main

    ck = str(tmp_path / "ck")
    # Steps 6,9,12 poisoned: 6 engages the backoff rung, 9 strikes while
    # backed off (escalate: rollback to the newest checkpoint), 12
    # strikes with the rollback budget of 1 spent (halt).  Recovery is
    # set far out so the scale cannot recover between strikes and blur
    # the ladder order.  The strikes sit exactly 3 apart because of two
    # bounds the depth-2 harvest ring imposes on a loaded box:
    #  - flags for ADJACENT steps can land in one ready-prefix drain,
    #    and the guard issues ONE verdict per drain batch (the batch
    #    minimum — the revert it runs cures the whole window), so
    #    strikes 1 apart can collapse into a single rung;
    #  - a strike at step k is guaranteed its verdict by boundary k+2
    #    (dispatching step k+2 overflows the ring and drains
    #    everything), so a strike at k+3 is always dispatched AFTER the
    #    previous verdict landed — it can neither co-drain with it nor
    #    be consumed pre-verdict and fenced inert by the recovery's
    #    generation bump (a strike the replay can never reach proves
    #    nothing).
    # epochs=4 leaves boundaries after step 12 for the final strike's
    # flag to drain and fire the halt.
    inject.arm(FaultPlan(nan_at_step=[6, 9, 12]))
    with pytest.raises(DivergenceError, match="rollbacks already spent"):
        main(
            _digits_argv(
                tmp_path,
                epochs=4,
                guard_policy="rollback",
                guard_interval=1,
                guard_lr_backoff=0.5,
                guard_backoff_recovery=100,
                guard_max_rollbacks=1,
                ckpt_dir=ck,
                ckpt_every_epochs=1,
            )
        )
    kinds = [r["kind"] for r in _records(tmp_path)]
    assert "lr_backoff" in kinds and "rollback" in kinds
    assert kinds.index("lr_backoff") < kinds.index("rollback")
    assert "lr_recover" not in kinds


def _ladder_state():
    """Minimal REAL TrainState with a backoff-wrapped tx (cheap: no model
    init) for direct guard-ladder unit tests."""
    import optax

    from dwt_tpu.train.optim import with_lr_backoff
    from dwt_tpu.train.state import TrainState

    tx = with_lr_backoff(optax.sgd(0.1))
    params = {"w": jnp.ones(3)}
    return TrainState(
        step=jnp.zeros((), jnp.int32),
        params=params,
        batch_stats={},
        opt_state=tx.init(params),
    )


def test_guard_skip_escalation_keeps_backed_off_scale():
    """Regression: a skip_step escalation WHILE backed off must return a
    state still carrying the reduced scale — the good snapshot predates
    the backoff engagement, so handing it back verbatim would replay at
    exactly the lr that just diverged (and desync the guard's host
    mirror from the device scale)."""
    from dwt_tpu.train.optim import get_backoff_scale

    guard = DivergenceGuard(
        "skip_step", interval=1, lr_backoff=0.5, backoff_recovery=100
    )
    state = _ladder_state()
    guard.prime(state)
    bad = {"loss": jnp.asarray(float("nan"))}
    s1 = guard.step(state, bad, 1, 1)  # rung 1: backoff
    assert get_backoff_scale(s1.opt_state) == 0.5 and guard.in_backoff
    s2 = guard.step(s1, bad, 1, 2)  # escalation: skip while backed off
    assert get_backoff_scale(s2.opt_state) == 0.5  # scale survives
    assert guard.recoveries == 2


def test_guard_mirror_recovery_takes_same_rung():
    """The consensus mirror path: a host whose metrics looked finite must
    take the SAME in-memory rung the remote host reported — first the
    backoff engagement, then (still backed off) the skip escalation."""
    from dwt_tpu.train.optim import get_backoff_scale

    guard = DivergenceGuard(
        "skip_step", interval=1, lr_backoff=0.5, backoff_recovery=100
    )
    state = _ladder_state()
    guard.prime(state)
    s1 = guard.mirror_recovery(state, 3)
    assert get_backoff_scale(s1.opt_state) == 0.5 and guard.in_backoff
    s2 = guard.mirror_recovery(s1, 4)
    assert get_backoff_scale(s2.opt_state) == 0.5
    assert guard.recoveries == 2


def test_guard_mirror_reverts_to_pre_refresh_snapshot():
    """Regression: a host whose check PASSED at this boundary refreshed
    its good snapshot to the CURRENT state; mirroring a remote divergence
    must revert to the snapshot both hosts hold (the previous passing
    check), not the just-refreshed one — else the finite host 'reverts'
    to where it already is and the replicas fork."""
    guard = DivergenceGuard("skip_step", interval=1)
    state_a = _ladder_state()
    state_b = state_a.replace(
        params=jax.tree.map(lambda x: x * 2.0, state_a.params)
    )
    guard.prime(state_a)
    ok = {"loss": jnp.ones(())}
    out = guard.step(state_b, ok, 1, 1)  # passing check refreshes to B
    assert float(jax.tree.leaves(out.params)[0][0]) == 2.0
    mirrored = guard.mirror_recovery(out, 1)
    # Reverted to A — the snapshot the remote (failed-check) host used.
    assert float(jax.tree.leaves(mirrored.params)[0][0]) == 1.0


def test_consensus_event_codes_escalate_by_max():
    """Flag-vector combination: the max event code across hosts governs
    (halt > rollback > recovered > none) — exercised through the forced
    1-process allgather path."""
    from dwt_tpu.resilience.coord import (
        EVENT_HALT,
        EVENT_NONE,
        EVENT_RECOVERED,
        EVENT_ROLLBACK,
        Coordinator,
    )

    coord = Coordinator(enabled=True)
    d = coord.decide()
    assert d.event == EVENT_NONE and not d.diverged and not d.stop
    d = coord.decide(event=EVENT_RECOVERED)
    assert d.event == EVENT_RECOVERED and d.diverged
    d = coord.decide(stop=True, event=EVENT_ROLLBACK, rollback_step=9)
    assert d.stop and d.event == EVENT_ROLLBACK and d.rollback_step == 9
    assert EVENT_HALT > EVENT_ROLLBACK > EVENT_RECOVERED > EVENT_NONE
    assert coord.agree_step(5) == 5


def test_guard_backoff_without_policy_is_rejected():
    """--guard_lr_backoff with no active guard would be a silent no-op —
    the loop must refuse loudly instead (direct _make_guard call: the
    full CLI path would spend seconds on model init before the check)."""
    from dwt_tpu.config import DigitsConfig
    from dwt_tpu.train.loop import _make_guard

    with pytest.raises(ValueError, match="guard_lr_backoff"):
        _make_guard(DigitsConfig(guard_lr_backoff=0.5), None)


def test_guard_rejects_bad_backoff_factor():
    with pytest.raises(ValueError, match="lr_backoff"):
        DivergenceGuard("halt", interval=1, lr_backoff=1.5)


def test_guard_gives_up_after_max_rollbacks():
    guard = DivergenceGuard("rollback", interval=1, max_rollbacks=0)
    guard.prime({"w": jnp.ones(2)})
    bad = {"loss": jnp.asarray(float("nan"))}
    with pytest.raises(DivergenceError, match="rollbacks already spent"):
        guard.step({"w": jnp.ones(2)}, bad, 1, 1)


def test_guard_rejects_bad_policy():
    with pytest.raises(ValueError, match="guard policy"):
        DivergenceGuard("none", interval=1)


# ------------------------------------------------- data retry/quarantine


class _Tiny:
    def __len__(self):
        return 16

    def __getitem__(self, i):
        return np.float32(i), i


def test_transient_item_failure_is_retried():
    from dwt_tpu.data.loader import batch_iterator

    ds = FlakyDataset(_Tiny(), fail={5: 1})  # item 5 fails once, then loads
    got = list(batch_iterator(ds, 4, shuffle=False))
    xs = np.concatenate([x for x, _ in got])
    np.testing.assert_array_equal(xs, np.arange(16, dtype=np.float32))


def test_corrupt_item_quarantined_epoch_completes():
    """Acceptance (c): a corrupt item is logged and skipped; every other
    item still arrives and the epoch finishes (boundaries shift by one)."""
    from dwt_tpu.data.loader import batch_iterator

    ds = FlakyDataset(_Tiny(), corrupt=(5,))
    got = list(
        batch_iterator(ds, 4, shuffle=False, drop_last=False, num_workers=2)
    )
    xs = np.concatenate([x for x, _ in got])
    np.testing.assert_array_equal(
        xs, np.asarray([i for i in range(16) if i != 5], np.float32)
    )


def test_quarantined_item_sharded_substitutes_to_keep_batch_count():
    """Under shard=(index, count) a dropped item would desync the
    per-process batch counts the sharding invariant protects (a ragged
    tail deadlocks the collective); the bad item is replaced by a
    duplicate of the nearest good item instead."""
    from dwt_tpu.data.loader import batch_iterator

    # Shard 0 of 2 sees even items 0,2,...,14; corrupt one of them.
    ds = FlakyDataset(_Tiny(), corrupt=(4,))
    got = list(
        batch_iterator(ds, 4, shuffle=False, drop_last=True, shard=(0, 2))
    )
    assert len(got) == 2 and all(x.shape[0] == 4 for x, _ in got)
    xs = np.concatenate([x for x, _ in got])
    # Item 4's slot was filled by its predecessor, item 2.
    np.testing.assert_array_equal(
        xs, np.asarray([0, 2, 2, 6, 8, 10, 12, 14], np.float32)
    )

    # Corrupt FIRST item: the deficit is repaid by the first good item.
    ds = FlakyDataset(_Tiny(), corrupt=(0,))
    got = list(
        batch_iterator(ds, 4, shuffle=False, drop_last=True, shard=(0, 2))
    )
    assert len(got) == 2 and all(x.shape[0] == 4 for x, _ in got)
    assert float(got[0][0][0]) == 2.0  # duplicate of item 2 fills slot 0


def test_quarantine_false_restores_fail_fast():
    from dwt_tpu.data.loader import batch_iterator

    ds = FlakyDataset(_Tiny(), corrupt=(1,))
    with pytest.raises(OSError, match="corrupt"):
        list(batch_iterator(ds, 4, shuffle=False, quarantine=False))


class _CountingDataset:
    """Records which indices were actually accessed (FlakyDataset only
    counts successful reads; corrupt items raise before counting)."""

    def __init__(self, base):
        self.base = base
        self.accessed = set()

    def __len__(self):
        return len(self.base)

    def __getitem__(self, i):
        self.accessed.add(int(i))
        return self.base[int(i)]


def test_quarantine_persists_and_skips_on_resume(tmp_path):
    """A quarantined item id is written under ckpt_dir; a resumed run
    (fresh registry instance) skips it without a single access attempt —
    no retry ladder re-paid every epoch for a known-corrupt file."""
    from dwt_tpu.data.loader import QuarantineRegistry, batch_iterator

    reg = QuarantineRegistry.for_ckpt_dir(str(tmp_path / "ck"))
    ds = FlakyDataset(_Tiny(), corrupt=(5,))
    got = list(
        batch_iterator(ds, 4, shuffle=False, drop_last=False,
                       quarantine_registry=reg, quarantine_key="source")
    )
    xs = np.concatenate([x for x, _ in got])
    np.testing.assert_array_equal(
        xs, np.asarray([i for i in range(16) if i != 5], np.float32)
    )
    assert 5 in reg.known("source")
    assert os.path.exists(reg.path)

    # "Resume": a fresh registry reloads the persisted ids.
    reg2 = QuarantineRegistry.for_ckpt_dir(str(tmp_path / "ck"))
    assert 5 in reg2.known("source")
    assert reg2.known("target") == frozenset()  # index spaces are separate
    counting = _CountingDataset(_Tiny())
    got = list(
        batch_iterator(counting, 4, shuffle=False, drop_last=False,
                       quarantine_registry=reg2, quarantine_key="source")
    )
    xs = np.concatenate([x for x, _ in got])
    np.testing.assert_array_equal(
        xs, np.asarray([i for i in range(16) if i != 5], np.float32)
    )
    assert 5 not in counting.accessed


def test_quarantine_false_overrides_registry_skip(tmp_path):
    """Fail-fast callers must get the loud exception even for items the
    registry already condemned — the known-bad short-circuit is part of
    quarantine semantics, not a silent global skip list."""
    from dwt_tpu.data.loader import QuarantineRegistry, batch_iterator

    reg = QuarantineRegistry.for_ckpt_dir(str(tmp_path))
    reg.add("source", 5)
    ds = FlakyDataset(_Tiny(), corrupt=(5,))
    with pytest.raises(OSError, match="corrupt"):
        list(batch_iterator(ds, 4, shuffle=False, quarantine=False,
                            quarantine_registry=reg, quarantine_key="source"))


@pytest.mark.parametrize(
    "payload",
    [
        "{not json",                       # invalid JSON
        '{"source": [1, 7',                # truncated mid-write
        '[1, 2, 3]',                       # valid JSON, wrong shape (list)
        '"quarantine"',                    # valid JSON, wrong shape (str)
        '{"source": 3}',                   # values not iterable
        '{"source": ["a", "b"]}',          # ids not ints
    ],
    ids=["garbage", "truncated", "list", "string", "scalar-ids", "str-ids"],
)
def test_quarantine_registry_survives_corrupt_file(tmp_path, payload):
    """Fail-soft: a torn, garbage, or wrong-shaped registry file must not
    kill a resume at startup — warn and start from an empty registry (the
    worst cost is re-quarantining items as they fail again), and the
    registry must keep persisting afterwards."""
    from dwt_tpu.data.loader import QuarantineRegistry

    path = tmp_path / "ck" / QuarantineRegistry.FILENAME
    path.parent.mkdir(parents=True)
    path.write_text(payload)
    reg = QuarantineRegistry(str(path))
    assert reg.known("source") == frozenset()
    reg.add("source", 3)
    assert QuarantineRegistry(str(path)).known("source") == frozenset({3})


def test_quarantine_registry_partial_merge_keeps_good_entries(tmp_path):
    """A registry with one malformed entry keeps the entries that parse:
    fail-soft must not throw away good ids with the bad."""
    from dwt_tpu.data.loader import QuarantineRegistry

    path = tmp_path / "ck" / QuarantineRegistry.FILENAME
    path.parent.mkdir(parents=True)
    path.write_text('{"source": [1, 5], "target": "oops"}')
    reg = QuarantineRegistry(str(path))
    assert reg.known("source") == frozenset({1, 5})
    assert reg.known("target") == frozenset()


# ---------------------------------------------------- anchor checkpoints


def test_rollback_falls_back_to_anchor_checkpoint(tmp_path):
    """When every checkpoint in the main dir is gone (pruned/torn), the
    rollback restore falls back to ckpt_dir/anchors — the anchor cadence
    bounds the rollback distance."""
    from dwt_tpu.config import DigitsConfig
    from dwt_tpu.train.loop import _anchor_dir, _rollback_state

    ck = str(tmp_path / "ck")
    anchor_state = _tiny_state(step=4)
    save_state(_anchor_dir(ck), 4, anchor_state)
    assert latest_step(ck) is None  # main dir empty: only the anchor exists

    records = []

    class _Rec:
        def log(self, kind, step, **kw):
            records.append((kind, step, kw))

    guard = DivergenceGuard("rollback", interval=1)
    restored, src = _rollback_state(
        DigitsConfig(ckpt_dir=ck), _Rec(), guard, anchor_state, 9
    )
    assert int(restored.step) == 4
    assert src == "anchor"  # the loops re-seek the data plane from it
    kind, step, kw = records[-1]
    assert kind == "rollback" and step == 4 and kw["source"] == "anchor"


def test_rollback_prefers_newer_anchor_over_older_main_step(tmp_path):
    """Candidates are ranked by STEP across both dirs: a size-valid but
    digest-corrupt newest main checkpoint must fall back to a newer valid
    ANCHOR, not to an arbitrarily old main-dir step — the rollback
    distance stays bounded by the anchor cadence."""
    from dwt_tpu.config import DigitsConfig
    from dwt_tpu.train.loop import _anchor_dir, _rollback_state

    ck = str(tmp_path / "ck")
    save_state(ck, 2, _tiny_state(step=2))
    save_state(ck, 20, _tiny_state(step=20))
    save_state(_anchor_dir(ck), 6, _tiny_state(step=6))
    # Corrupt step 20's recorded digest, keeping the manifest size valid:
    # it still LISTS as the newest valid step but fails restore.
    manifest_path = os.path.join(ck, "20", MANIFEST)
    manifest = json.load(open(manifest_path))
    size = os.path.getsize(manifest_path)
    manifest["params_digest"] = "0" * len(manifest["params_digest"])
    with open(manifest_path, "w") as f:
        f.write(json.dumps(manifest, indent=1).ljust(size))

    records = []

    class _Rec:
        def log(self, kind, step, **kw):
            records.append((kind, step, kw))

    guard = DivergenceGuard("rollback", interval=1)
    restored, src = _rollback_state(
        DigitsConfig(ckpt_dir=ck), _Rec(), guard, _tiny_state(), 25
    )
    assert int(restored.step) == 6  # anchor 6, not main-dir step 2
    assert src == "anchor"
    assert records[-1][2]["source"] == "anchor"


def test_checkpoint_io_retry_backoff():
    from dwt_tpu.utils.checkpoint import _with_retries

    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise OSError("transient")
        return "ok"

    assert _with_retries(flaky, "t", retries=3, backoff_s=0.0) == "ok"
    assert len(calls) == 3
    with pytest.raises(OSError):
        _with_retries(lambda: (_ for _ in ()).throw(OSError("x")), "t",
                      retries=2, backoff_s=0.0)


# ----------------------------------------------------------- preemption


def test_watchdog_suspended_masks_blocking_section(tmp_path):
    """A synchronous checkpoint save may legitimately outlast the
    timeout; inside ``suspended()`` the watchdog must not fire, and the
    section's duration must not count against the next interval."""
    from dwt_tpu.resilience import HangWatchdog

    calls = []
    wd = HangWatchdog(0.2, ckpt_dir=str(tmp_path), _exit=calls.append)
    with wd:
        with wd.suspended():
            time.sleep(0.6)  # 3x the timeout: would fire if unmasked
        assert not wd.fired
        time.sleep(0.1)  # exit re-heartbeat: interval not yet exceeded
        assert not wd.fired
    assert calls == []


def test_watchdog_dump_retention_caps_files(tmp_path):
    """--watchdog_keep: firing with a directory full of earlier dumps
    (the relaunch-loop scenario: 113 → resume → hang again, forever)
    prunes the oldest so the cap holds — disks must not fill with the
    evidence of a repeating hang."""
    from dwt_tpu.resilience import HangWatchdog

    wd_dir = tmp_path / "watchdog"
    wd_dir.mkdir()
    for i in range(6):
        p = wd_dir / f"stacks-{1000 + i}-{i}.txt"
        p.write_text(f"old dump {i}")
        os.utime(p, (i + 1, i + 1))  # strictly increasing mtimes

    calls = []
    wd = HangWatchdog(5.0, ckpt_dir=str(tmp_path), keep=3, _exit=calls.append)
    wd._fire(99.0)  # the detection path, with the exit injected away
    assert calls == [113]
    dumps = sorted(os.listdir(wd_dir))
    assert len(dumps) == 3, dumps
    # The newest dump is the one just written (this pid), and the
    # survivors are the newest of the old ones.
    assert any(f"stacks-{os.getpid()}-" in d for d in dumps)
    assert "stacks-1005-5.txt" in dumps and "stacks-1000-0.txt" not in dumps


def test_preemption_handler_flag_and_restore():
    before = signal.getsignal(signal.SIGTERM)
    with PreemptionHandler() as p:
        assert not p.should_stop
        os.kill(os.getpid(), signal.SIGTERM)
        # Signal delivery is synchronous for a self-kill on the main thread.
        assert p.should_stop
        assert p.signum == signal.SIGTERM
    assert signal.getsignal(signal.SIGTERM) is before


def _spawn_digits(tmp_path, extra=()):
    ck = str(tmp_path / "ck")
    jsonl = str(tmp_path / "m.jsonl")
    argv = [
        sys.executable, "-m", "dwt_tpu.cli.usps_mnist",
        "--synthetic", "--synthetic_size", "32",
        "--source_batch_size", "8", "--target_batch_size", "8",
        "--test_batch_size", "16", "--group_size", "4",
        "--epochs", "500", "--log_interval", "1",
        "--ckpt_dir", ck, "--ckpt_every_epochs", "1000",
        "--metrics_jsonl", jsonl, *extra,
    ]
    # conftest already pinned JAX_PLATFORMS=cpu and stripped the relay var
    # from os.environ, so the child inherits a CPU-only config.
    proc = subprocess.Popen(
        argv, cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
    )
    return proc, ck, jsonl


def _wait_for_train_record(proc, jsonl, timeout=180.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if proc.poll() is not None:
            raise AssertionError(
                "trainer exited before SIGTERM: "
                + proc.stderr.read().decode(errors="replace")[-2000:]
            )
        if os.path.exists(jsonl):
            for line in open(jsonl).read().splitlines():
                if '"train"' in line:
                    return
        time.sleep(0.1)
    proc.kill()
    raise AssertionError("no train record within timeout")


def _assert_graceful_exit(proc, ck, jsonl):
    try:
        rc = proc.wait(timeout=180)
    except subprocess.TimeoutExpired:
        proc.kill()
        raise AssertionError("trainer did not exit after SIGTERM")
    stderr = proc.stderr.read().decode(errors="replace")
    assert rc == 0, f"exit code {rc}; stderr tail: {stderr[-2000:]}"
    # A final checkpoint was saved even though ckpt_every_epochs never hit.
    assert latest_step(ck) is not None
    kinds = [json.loads(l)["kind"] for l in open(jsonl).read().splitlines()]
    assert "preempt" in kinds


@pytest.mark.parametrize(
    "dispatch",
    [
        # Both variants cost a full trainer subprocess (~39 s each) and
        # ride the slow tier since PR 6: the SIGTERM→save→exit-0 contract
        # stays tier-1-proven by the composed chaos smoke
        # (test_chaos.py::test_chaos_smoke_composed_faults_exit0_resumable,
        # which adds notice + io_error on top of the SIGTERM).
        pytest.param("1", marks=pytest.mark.slow),
        pytest.param("4", marks=pytest.mark.slow),
    ],
)
def test_sigterm_saves_final_checkpoint_and_exits_zero(tmp_path, dispatch):
    """Acceptance (d): SIGTERM mid-training -> final checkpoint, a preempt
    record, exit 0 — on the per-step AND steps_per_dispatch paths.  With
    --async_ckpt on by default this is the SIGTERM→enqueue→flush→exit-0
    proof: the preempt path flushes the writer before returning, so the
    final checkpoint is durable despite the asynchronous save."""
    proc, ck, jsonl = _spawn_digits(
        tmp_path, extra=("--steps_per_dispatch", dispatch)
    )
    try:
        _wait_for_train_record(proc, jsonl)
        proc.send_signal(signal.SIGTERM)
        _assert_graceful_exit(proc, ck, jsonl)
    finally:
        if proc.poll() is None:
            proc.kill()


@pytest.mark.slow
def test_sigterm_sync_ckpt_path_still_graceful(tmp_path):
    """--no-async_ckpt keeps the PR-1 synchronous save path working: the
    same SIGTERM → final checkpoint → exit 0 contract (slow-marked: the
    fast tier already proves both dispatch paths with async on)."""
    proc, ck, jsonl = _spawn_digits(tmp_path, extra=("--no-async_ckpt",))
    try:
        _wait_for_train_record(proc, jsonl)
        proc.send_signal(signal.SIGTERM)
        _assert_graceful_exit(proc, ck, jsonl)
    finally:
        if proc.poll() is None:
            proc.kill()


def test_nan_injection_via_env_plan(tmp_path):
    """The DWT_FAULT_PLAN env var arms subprocess runs (used to prove the
    guard in a separately-spawned trainer); in-process, FaultPlan.from_env
    must parse it identically."""
    os.environ[inject.ENV_VAR] = json.dumps(
        {"nan_at_step": 7, "crash_in_save": True}
    )
    try:
        plan = FaultPlan.from_env()
        assert plan.nan_at_step == 7 and plan.crash_in_save is True
    finally:
        del os.environ[inject.ENV_VAR]
