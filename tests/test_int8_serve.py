"""Int8 deployment format + reduced-precision serve edge (tier-1).

The int8 path is a SERVING format, not a training one: checkpoints on
disk stay f32 (asserted here against the CAS manifest's per-leaf
dtypes), quantization happens at ``build_state`` time, and every
quantized candidate answers to the same ``CanaryGate`` fixture-accuracy
gate as any other deploy.  Covered:

* quantize/dequantize round-trip bounds and non-float passthrough;
* the int8 engine's state carries int8 float-leaves + an f32 scale tree
  and serves within the argmax band of the f32 engine;
* the canary ACCEPTS an honest quantized candidate and REFUSES a
  scale-corrupted one (per-leaf corruption — a uniform rescale of every
  scale is largely absorbed by the normalization layers and must not be
  what the test leans on);
* delta/CAS checkpoint restore into a bf16-cache engine round-trips:
  blobs f32 on disk, cast at placement, served logits finite and
  argmax-consistent.

Engine compiles are the cost; one module fixture with a single small
bucket keeps this inside the tier-1 budget.
"""

from __future__ import annotations

import json
import os

import numpy as np
import pytest


@pytest.fixture(scope="module")
def int8_setup():
    import jax
    import jax.numpy as jnp
    import optax

    from dwt_tpu.nn import LeNetDWT
    from dwt_tpu.serve import ServeEngine
    from dwt_tpu.train import create_train_state

    model = LeNetDWT(group_size=4)
    rng = np.random.default_rng(0)
    sample = jnp.asarray(rng.normal(size=(2, 4, 28, 28, 1)), jnp.float32)
    state = create_train_state(
        model, jax.random.key(0), sample, optax.identity()
    )
    f32 = ServeEngine(
        model, state.params, state.batch_stats, (28, 28, 1), buckets=(8,)
    )
    int8 = ServeEngine(
        model, state.params, state.batch_stats, (28, 28, 1), buckets=(8,),
        quantize=True,
    )
    fixture_x = np.random.default_rng(1).normal(
        size=(8, 28, 28, 1)
    ).astype(np.float32)
    return model, state, f32, int8, fixture_x


# ------------------------------------------------------------ quant units


def test_quantize_roundtrip_bounds():
    import jax.numpy as jnp

    from dwt_tpu.serve.quant import dequantize_int8, quantize_int8

    rng = np.random.default_rng(2)
    params = {
        "w": jnp.asarray(rng.normal(size=(16, 8)), jnp.float32),
        "zeros": jnp.zeros((4,), jnp.float32),
        "step": jnp.asarray(7, jnp.int32),  # non-float passthrough
    }
    q, scales = quantize_int8(params)
    assert q["w"].dtype == jnp.int8
    assert q["zeros"].dtype == jnp.int8
    assert q["step"].dtype == jnp.int32  # untouched
    assert scales["w"].dtype == jnp.float32
    assert float(scales["zeros"]) == 1.0  # zero-leaf guard
    deq = dequantize_int8(q, scales)
    # Per-tensor symmetric: |err| <= scale/2 everywhere.
    err = np.abs(np.asarray(deq["w"]) - np.asarray(params["w"]))
    assert float(err.max()) <= float(scales["w"]) / 2 + 1e-7
    np.testing.assert_array_equal(np.asarray(deq["zeros"]), 0.0)
    assert int(deq["step"]) == 7
    # Structure-complete scale tree: same treedef as params.
    import jax

    assert (jax.tree.structure(scales) == jax.tree.structure(params))


# --------------------------------------------------------- engine + state


def test_int8_engine_state_dtypes(int8_setup):
    import jax
    import jax.numpy as jnp

    _, _, _, int8, _ = int8_setup
    st = int8.state
    assert st.scales is not None
    float_leaves = [
        l for l in jax.tree.leaves(st.params)
        if jnp.issubdtype(l.dtype, jnp.integer)
    ]
    assert float_leaves, "no quantized leaves in int8 engine state"
    for leaf in jax.tree.leaves(st.params):
        assert leaf.dtype == jnp.int8, leaf.dtype
    for s in jax.tree.leaves(st.scales):
        assert s.dtype == jnp.float32


def test_int8_served_within_argmax_band(int8_setup):
    """Weight-only int8 on the fixture: finite logits, argmax agreement
    with the f32 engine within the configured band.  Logit CLOSENESS is
    deliberately not asserted — per-tensor dequant shifts logits by
    O(scale) while predictions stay put."""
    _, _, f32, int8, fixture_x = int8_setup
    ref = f32.infer(fixture_x, bucket=8)
    got = int8.infer(fixture_x, bucket=8)
    assert np.isfinite(got).all()
    agree = float(
        (np.argmax(ref, -1) == np.argmax(got, -1)).mean()
    )
    assert agree >= 0.75, f"int8 argmax agreement {agree}"


# ----------------------------------------------------------- canary gate


def test_canary_accepts_honest_quantized_candidate(int8_setup):
    from dwt_tpu.fleet.canary import CanaryGate

    _, _, f32, int8, fixture_x = int8_setup
    labels = np.argmax(f32.infer(fixture_x, bucket=8), -1)
    gate = CanaryGate(int8, fixture_x, labels, max_regress_pp=26.0)
    verdict = gate.check(int8.state)
    assert verdict.ok, verdict.reason


def test_canary_refuses_scale_corrupted_candidate(int8_setup):
    """A quantized candidate whose scale tree is corrupted PER LEAF
    (each leaf rescaled by a different factor, signs flipped) collapses
    fixture accuracy and must be refused before taking traffic.

    A uniform corruption (every scale x57) is NOT used on purpose: the
    whitening/BN layers renormalize activations per layer, so a uniform
    per-layer weight rescale largely survives argmax — the gate would
    pass and the test would prove nothing."""
    import jax
    import jax.numpy as jnp

    from dwt_tpu.fleet.canary import CanaryGate

    _, _, f32, int8, fixture_x = int8_setup
    labels = np.argmax(f32.infer(fixture_x, bucket=8), -1)
    gate = CanaryGate(int8, fixture_x, labels, max_regress_pp=5.0)
    assert gate.check(int8.state).ok  # baseline: honest state passes

    st = int8.state
    leaves, treedef = jax.tree.flatten(st.scales)
    crng = np.random.default_rng(3)
    bad = jax.tree.unflatten(
        treedef,
        [l * jnp.asarray(float(crng.uniform(-40.0, 40.0)), jnp.float32)
         for l in leaves],
    )
    verdict = gate.check(st._replace(scales=bad))
    assert not verdict.ok
    assert "accuracy" in verdict.reason or "finite" in verdict.reason
    # Refusal means the live state never changed: serving still healthy.
    assert np.isfinite(int8.infer(fixture_x, bucket=8)).all()


# ------------------------------------- delta/CAS restore into bf16 engine


def test_cas_restore_into_bf16_engine_roundtrip(tmp_path, int8_setup):
    """f32 delta/CAS checkpoint -> bf16-cache engine: the cast happens
    at placement (engine build), never at save — asserted against the
    manifest's per-leaf dtypes — and the restored engine's served
    argmax matches the source f32 engine's."""
    import jax
    import jax.numpy as jnp

    from dwt_tpu.ckpt.store import save_delta
    from dwt_tpu.serve import ServeEngine
    from dwt_tpu.utils.checkpoint import host_fetch

    model, state, f32, _, fixture_x = int8_setup
    ck = str(tmp_path / "ck")
    path = save_delta(ck, 11, host_fetch(state))
    assert path is not None

    # On-disk blobs are f32: the manifest records every leaf's dtype.
    with open(os.path.join(path, "manifest.json")) as f:
        manifest = json.load(f)
    float_entries = [
        e for e in manifest["leaves"] if "float" in e["dtype"]
    ]
    assert float_entries
    for e in float_entries:
        assert e["dtype"] == "float32", (e["path"], e["dtype"])

    restored = ServeEngine.from_checkpoint(
        ck, model, (28, 28, 1), buckets=(8,), cache_dtype=jnp.bfloat16,
    )
    cache_leaves = jax.tree.leaves(restored.state.cache)
    assert cache_leaves
    for leaf in cache_leaves:
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            assert leaf.dtype == jnp.bfloat16, leaf.dtype
    # Params were NOT down-cast — placement preserves the f32 blobs.
    for leaf in jax.tree.leaves(restored.state.params):
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            assert leaf.dtype == jnp.float32
    got = restored.infer(fixture_x, bucket=8)
    assert np.isfinite(got).all()
    ref = f32.infer(fixture_x, bucket=8)
    agree = float((np.argmax(ref, -1) == np.argmax(got, -1)).mean())
    assert agree >= 0.75, f"bf16-cache argmax agreement {agree}"


def test_cas_restore_quantized_engine(tmp_path, int8_setup):
    """The full deployment stack composes: f32 CAS checkpoint restored
    into an int8-weight engine — quantization is derived at build time,
    the artifact on disk never changes."""
    import jax
    import jax.numpy as jnp

    from dwt_tpu.ckpt.store import save_delta
    from dwt_tpu.serve import ServeEngine
    from dwt_tpu.utils.checkpoint import host_fetch

    model, state, f32, _, fixture_x = int8_setup
    ck = str(tmp_path / "ck")
    assert save_delta(ck, 3, host_fetch(state)) is not None
    restored = ServeEngine.from_checkpoint(
        ck, model, (28, 28, 1), buckets=(8,), quantize=True,
    )
    assert restored.state.scales is not None
    for leaf in jax.tree.leaves(restored.state.params):
        assert leaf.dtype == jnp.int8
    got = restored.infer(fixture_x, bucket=8)
    ref = f32.infer(fixture_x, bucket=8)
    agree = float((np.argmax(ref, -1) == np.argmax(got, -1)).mean())
    assert agree >= 0.75, f"restored-int8 argmax agreement {agree}"
