"""Training-layer tests: schedules, optimizers, digits end-to-end slice.

The overfit test is SURVEY §4.3's designated CPU-runnable integration slice:
a LeNet-DWT must drive its loss down on a synthetic digit batch, the eval
path must run off the trained running stats, and the state must thread
through ``lax.scan``.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from dwt_tpu.nn import LeNetDWT
from dwt_tpu.train import (
    adam_l2,
    create_train_state,
    make_digits_train_step,
    make_eval_step,
    make_scanned_step,
    make_stat_collection_step,
    multistep_schedule,
    sgd_two_group,
    stack_batches,
)


def _synthetic_digits(n=8, seed=0):
    """Tiny linearly-separable 'digit' batch: class k lights up row k."""
    rng = np.random.default_rng(seed)
    y = np.arange(n) % 4
    x = rng.normal(scale=0.1, size=(n, 28, 28, 1)).astype(np.float32)
    for i, k in enumerate(y):
        x[i, 3 * k : 3 * k + 3, :, 0] += 2.0
    return jnp.asarray(x), jnp.asarray(y)


@pytest.fixture(scope="module")
def digits_setup():
    model = LeNetDWT(group_size=4)
    sx, sy = _synthetic_digits(8, seed=0)
    tx_img, _ = _synthetic_digits(8, seed=1)
    batch = {"source_x": sx, "source_y": sy, "target_x": tx_img}
    tx = adam_l2(1e-3, weight_decay=5e-4)
    state = create_train_state(
        model, jax.random.key(0), jnp.stack([sx, tx_img]), tx
    )
    step = jax.jit(make_digits_train_step(model, tx, lambda_entropy=0.1))
    return model, tx, state, step, batch


def test_multistep_schedule_matches_torch_prestep_sequence():
    # torch MultiStepLR([50, 80], gamma=0.1) with scheduler.step() BEFORE
    # each epoch: decay lands on epochs 49 and 79 (0-indexed).
    sched = multistep_schedule(1e-3, [50, 80], 0.1, pre_step=True)
    lrs = [float(sched(e)) for e in range(100)]
    assert lrs[48] == pytest.approx(1e-3)
    assert lrs[49] == pytest.approx(1e-4)
    assert lrs[78] == pytest.approx(1e-4)
    assert lrs[79] == pytest.approx(1e-5, rel=1e-5)


def test_sgd_two_group_routes_lrs_by_head_key():
    params = {
        "fc_out": {"kernel": jnp.ones((3, 3))},
        "conv1": {"kernel": jnp.ones((3, 3))},
    }
    tx = sgd_two_group(head_lr=1.0, backbone_lr=0.1, momentum=0.0,
                       weight_decay=0.0)
    opt_state = tx.init(params)
    grads = jax.tree.map(jnp.ones_like, params)
    updates, _ = tx.update(grads, opt_state, params)
    np.testing.assert_allclose(np.asarray(updates["fc_out"]["kernel"]), -1.0)
    np.testing.assert_allclose(
        np.asarray(updates["conv1"]["kernel"]), -0.1, rtol=1e-6
    )


def test_digits_overfit_and_eval(digits_setup):
    model, _, state, step, batch = digits_setup
    _, first = step(state, batch)
    for _ in range(150):
        state, metrics = step(state, batch)
    # Trajectory (seeded): cls 2.87 -> ~0.67 by step 150 — comfortably
    # under 0.3x while leaving margin for platform-dependent drift.
    assert float(metrics["cls_loss"]) < 0.3 * float(first["cls_loss"])
    assert np.isfinite(float(metrics["loss"]))

    # Eval path: target-branch routing off the trained running stats.
    eval_step = jax.jit(make_eval_step(model))
    out = eval_step(
        state.params, state.batch_stats, batch["source_x"], batch["source_y"]
    )
    assert int(out["count"]) == 8
    assert np.isfinite(float(out["loss_sum"]))


def test_train_step_threads_through_scan(digits_setup):
    model, _, state, _, batch = digits_setup
    tx = adam_l2(1e-3)
    step = make_digits_train_step(model, tx, lambda_entropy=0.1)

    def body(carry, _):
        new_state, metrics = step(carry, batch)
        return new_state, metrics["loss"]

    final, losses = jax.jit(
        lambda s: jax.lax.scan(body, s, None, length=5)
    )(state)
    assert int(final.step) == int(state.step) + 5
    assert losses.shape == (5,)
    assert np.all(np.isfinite(np.asarray(losses)))
    # Stats must actually advance inside the scan.
    assert not np.allclose(
        np.asarray(jax.tree.leaves(final.batch_stats)[0]),
        np.asarray(jax.tree.leaves(state.batch_stats)[0]),
    )


def test_scanned_step_matches_sequential(digits_setup):
    """k steps per dispatch (make_scanned_step) must reproduce k
    dispatched steps: same params, same stats, same per-step metrics —
    only the dispatch granularity may differ (steps_per_dispatch
    contract, dwt_tpu/train/steps.py)."""
    model, _, _, _, _ = digits_setup
    # SGD, not Adam: Adam's first-step update is lr*sign(grad), so an
    # ulp-level gradient difference between two differently-fused XLA
    # programs (scan body vs standalone jit) flips near-zero grad signs
    # into 2*lr param differences — noise amplification, not semantics.
    # Under SGD the same ulp noise stays ulp-sized and the comparison is
    # meaningful.  (Loss/metric parity below is exact either way.)
    tx = optax.sgd(1e-2)
    state = create_train_state(
        model,
        jax.random.key(0),
        jnp.stack(
            [jnp.zeros((8, 28, 28, 1)), jnp.zeros((8, 28, 28, 1))]
        ),
        tx,
    )
    step = jax.jit(make_digits_train_step(model, tx, lambda_entropy=0.1))

    host_batches = []
    for s in range(3):
        sx, sy = _synthetic_digits(8, seed=10 + s)
        txi, _ = _synthetic_digits(8, seed=20 + s)
        host_batches.append(
            {
                "source_x": np.asarray(sx),
                "source_y": np.asarray(sy),
                "target_x": np.asarray(txi),
            }
        )

    seq_state = state
    seq_metrics = []
    for b in host_batches:
        seq_state, m = step(seq_state, b)
        seq_metrics.append(m)

    scanned = jax.jit(make_scanned_step(step, 3))
    scan_state, ms = scanned(state, stack_batches(host_batches))

    assert int(scan_state.step) == int(seq_state.step)
    for a, b in zip(
        jax.tree.leaves(scan_state.params), jax.tree.leaves(seq_state.params)
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-7
        )
    for a, b in zip(
        jax.tree.leaves(scan_state.batch_stats),
        jax.tree.leaves(seq_state.batch_stats),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-6, atol=1e-6
        )
    for j, m in enumerate(seq_metrics):
        for key in m:
            np.testing.assert_allclose(
                np.asarray(ms[key][j]), np.asarray(m[key]),
                rtol=1e-5, atol=1e-6,
            )


@pytest.mark.slow
def test_steps_per_dispatch_end_of_run_accuracy_band(tmp_path):
    """k=1 and k=4 dispatch must agree not only per-step (the parity test
    above) but at the END of a full run: same data order, same cadences,
    so the final target accuracies may differ only by the float noise of
    two differently-fused XLA programs.  Guards against a chunking bug
    that is per-step-invisible but compounds (e.g. a dropped boundary
    action or a stats carry skew).  Slow-marked: two full in-process
    runs; the fast tier keeps the per-step parity test above."""
    from dwt_tpu.cli.usps_mnist import main

    def run(k):
        return main([
            "--synthetic", "--synthetic_size", "64",
            "--source_batch_size", "8", "--target_batch_size", "8",
            "--test_batch_size", "32", "--group_size", "4",
            "--epochs", "2", "--log_interval", "100",
            "--steps_per_dispatch", str(k),
        ])

    acc1, acc4 = run(1), run(4)
    assert 0.0 <= acc1 <= 100.0 and 0.0 <= acc4 <= 100.0
    # Deterministic on CPU; measured |acc1 - acc4| = 0 on this config.
    # The band allows a few test-set items (32 samples -> 3.125 %/item)
    # to flip under platform-dependent fusion noise.
    assert abs(acc1 - acc4) <= 10.0, (acc1, acc4)


def test_scanned_step_rejects_bad_k(digits_setup):
    model, _, _, step, _ = digits_setup
    with pytest.raises(ValueError):
        make_scanned_step(step, 0)


def test_stat_collection_updates_only_stats(digits_setup):
    model, _, state, step, batch = digits_setup
    state, _ = step(state, batch)
    collect = jax.jit(make_stat_collection_step(model, num_domains=2))
    out = collect(state, batch["target_x"])
    # Params identical, stats changed.
    for a, b in zip(jax.tree.leaves(state.params), jax.tree.leaves(out.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    changed = [
        not np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(
            jax.tree.leaves(state.batch_stats), jax.tree.leaves(out.batch_stats)
        )
    ]
    assert any(changed)
