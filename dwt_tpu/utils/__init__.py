"""dwt_tpu.utils — metrics logging, checkpoints, repro verdicts."""

from dwt_tpu.utils.metrics import (
    HeartbeatEmitter,
    MetricLogger,
    host_rss_mb,
    percentile,
    percentile_summary,
)
from dwt_tpu.utils.checkpoint import (
    anchor_dir,
    checkpoint_invalid_reason,
    is_valid_checkpoint,
    latest_step,
    load_data_state,
    ranked_checkpoints,
    restore_newest,
    restore_state,
    restore_tree,
    save_state,
    valid_steps,
)
from dwt_tpu.utils.repro import (
    accuracy_verdict,
    check_cli_accuracy,
    load_expect_table,
    sweep_verdicts,
)

__all__ = [
    "HeartbeatEmitter",
    "MetricLogger",
    "host_rss_mb",
    "percentile",
    "percentile_summary",
    "anchor_dir",
    "checkpoint_invalid_reason",
    "is_valid_checkpoint",
    "latest_step",
    "load_data_state",
    "ranked_checkpoints",
    "restore_newest",
    "restore_state",
    "restore_tree",
    "save_state",
    "valid_steps",
    "accuracy_verdict",
    "check_cli_accuracy",
    "load_expect_table",
    "sweep_verdicts",
]
