"""Domain-adaptation losses (closed forms from SURVEY §2.2 rows 3-4).

All losses compute in at least float32: lower-precision logits (bf16) are
promoted to f32; f64 passes through untruncated (used by the f64 lockstep
trajectory-parity tests).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def at_least_f32(x: jax.Array) -> jax.Array:
    """Promote sub-f32 inputs (bf16/f16) to f32; f64 passes through.

    The shared promotion policy for every loss/metric reduction: bf16
    activations must not accumulate in half precision, and f64 (the
    lockstep trajectory-parity tests) must not be silently truncated.
    """
    return x.astype(jnp.promote_types(x.dtype, jnp.float32))


def entropy_loss(logits: jax.Array) -> jax.Array:
    """Mean Shannon entropy of softmax predictions.

    ``-mean_n sum_k p_nk log p_nk`` — the target-entropy-minimization term of
    the digits experiment (reference ``usps_mnist.py:183-194``).
    """
    logits = at_least_f32(logits)
    logp = jax.nn.log_softmax(logits, axis=-1)
    p = jnp.exp(logp)
    return -jnp.mean(jnp.sum(p * logp, axis=-1))


def mec_loss(logits_a: jax.Array, logits_b: jax.Array) -> jax.Array:
    """Min-Entropy Consensus loss between two views of the target batch.

    Per sample: ``min_k 0.5 * (-log p_a(k) - log p_b(k))``, then batch mean
    (reference ``utils/consensus_loss.py:11-24``).
    """
    la = jax.nn.log_softmax(at_least_f32(logits_a), axis=-1)
    lb = jax.nn.log_softmax(at_least_f32(logits_b), axis=-1)
    per_class = 0.5 * (-la - lb)  # [N, K]
    return jnp.mean(jnp.min(per_class, axis=-1))


def nll_loss(
    log_probs: jax.Array, labels: jax.Array, reduction: str = "mean"
) -> jax.Array:
    """Negative log likelihood of integer ``labels`` under ``log_probs``."""
    picked = jnp.take_along_axis(
        at_least_f32(log_probs), labels[:, None], axis=-1
    )[:, 0]
    if reduction == "mean":
        return -jnp.mean(picked)
    if reduction == "sum":
        return -jnp.sum(picked)
    if reduction == "none":
        return -picked
    raise ValueError(f"unknown reduction {reduction!r}")


def softmax_cross_entropy(
    logits: jax.Array, labels: jax.Array, reduction: str = "mean"
) -> jax.Array:
    """``nll(log_softmax(logits), labels)`` — the reference's cls loss
    (``usps_mnist.py:298``, ``resnet50_dwt_mec_officehome.py:425``)."""
    return nll_loss(
        jax.nn.log_softmax(at_least_f32(logits), axis=-1),
        labels,
        reduction,
    )


def accuracy(logits: jax.Array, labels: jax.Array) -> jax.Array:
    """Fraction of argmax predictions equal to ``labels`` (float32)."""
    pred = jnp.argmax(logits, axis=-1)
    return jnp.mean((pred == labels).astype(jnp.float32))
