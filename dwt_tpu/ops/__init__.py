"""Functional compute ops (pure, jit-able, differentiable)."""

from dwt_tpu.ops.whitening import (  # noqa: F401
    WHITENER_NAMES,
    SWBNStats,
    Whitener,
    WhiteningStats,
    apply_whitening,
    build_whiten_cache,
    get_whitener,
    group_cov,
    group_whiten,
    init_whitening_stats,
    newton_schulz_inverse_sqrt,
    whitening_matrix,
)
from dwt_tpu.ops.pallas_whitening import (  # noqa: F401
    pallas_group_whiten,
)
from dwt_tpu.ops.batch_norm import (  # noqa: F401
    BatchNormStats,
    init_batch_norm_stats,
    batch_norm,
)
from dwt_tpu.ops.losses import (  # noqa: F401
    at_least_f32,
    entropy_loss,
    mec_loss,
    nll_loss,
    softmax_cross_entropy,
    accuracy,
)
