"""Device-resident, mesh-sharded eval & stat-collection pipeline.

The reference protocols make the eval phase expensive by construction:
OfficeHome re-estimates whitening/BN statistics with a 10-pass sweep over
the target test set before every final test (``resnet50…py:380-389``), so
eval-phase cost is ~11 full dataset passes per cadence.  Until ISSUE-4
that phase ran as the repo's ONLY un-optimized device loop: unsharded
(every process redundantly forwarding full batches on one device) with a
blocking ``float()`` host sync per batch — while the train path already
had sharding, scan-amortized dispatch, and prefetch.

:class:`EvalPipeline` gives eval and stat-collection the same levers:

* **mesh sharding** (``--data_parallel``): each device forwards ``1/N``
  of every eval/stat batch via ``parallel.make_sharded_eval_step`` /
  ``make_sharded_collect_step`` — counter deltas ``psum``'d, norm-site
  moments ``pmean``'d, composed with the per-process multi-host split
  exactly like the train step;
* **device-resident accumulation**: the three eval counters live on
  device across the whole pass and the host fetches them ONCE
  (``steps.eval_counters`` / ``make_accum_eval_step``), so a full
  :meth:`evaluate` performs O(1) host fetches instead of one blocking
  sync per batch;
* **scanned dispatch** (``--eval_steps_per_dispatch k``): k batches per
  compiled dispatch via ``lax.scan``, amortizing the per-dispatch host
  round-trip k-fold (the eval twin of ``--steps_per_dispatch``);
* **prefetch**: both phases stage batches through
  ``prefetch_to_device`` with the training loops' staging depth;
* **once-per-pass factorization**: eval-mode whitening matrices are
  precomputed from the frozen running stats with every site's groups
  stacked into one batched call (``ops.whitening.build_whiten_cache``)
  and threaded to the norm sites — instead of every batch re-running
  Cholesky+inverse at every site.

Parity contract (pinned by ``tests/test_evalpipe.py``): sharded and
unsharded evals produce IDENTICAL correct/count counters (masked padding
keeps ragged tails exact), and sharded stat collection reproduces the
unsharded stats trajectory to the same float-reassociation tolerance
``tests/test_parallel.py`` holds the train step to.  Stat-collection
batches are never padded — padding would perturb the batch moments the
protocol exists to estimate — so a ragged final batch runs through the
axis-free tail step, bitwise-identically to the unsharded path.
"""

from __future__ import annotations

import logging
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from dwt_tpu import obs
from dwt_tpu.data.loader import (
    QUARANTINED,
    _load_item,
    batch_iterator,
    prefetch_to_device,
)
from dwt_tpu.ops.whitening import build_whiten_cache, get_whitener
from dwt_tpu.train.steps import (
    eval_counters,
    make_accum_eval_step,
    make_scanned_collect,
    make_stat_collection_step,
)
from dwt_tpu.utils.metrics import percentile_summary

log = logging.getLogger(__name__)


def _fetch(tree):
    """The ONE device→host rendezvous of an eval pass.

    Every host materialization in this module funnels through here so a
    counting shim (tests, ``tools/eval_bench.py``) can assert the O(1)
    host-fetch contract by monkeypatching a single seam.
    """
    return jax.device_get(tree)


def make_whiten_cache_fn(
    whitener: str = "cholesky",
    whiten_eps: float = 1e-3,
    eval_domain: int = 1,
):
    """Jitted once-per-pass whitening-matrix precompute:
    ``batch_stats -> {"whiten_cache": tree}`` (or ``{}``) with every
    site's groups stacked into one batched factorization.  Shared by
    :class:`EvalPipeline` and the serving engine (``dwt_tpu.serve``), so
    both eval and deployment forwards read matrices produced by the SAME
    compiled program from the same frozen stats."""
    _whitener = get_whitener(whitener)
    return jax.jit(
        lambda bs: build_whiten_cache(
            bs, _whitener, eps=whiten_eps, eval_domain=eval_domain
        )
    )


def _chunk_groups(batches, k: int):
    """Group consecutive batches into lists of ≤ k with UNIFORM leading
    length, cutting early when the batch size changes (the un-padded
    stat-collection stream ends with a ragged tail that must become its
    own dispatch — stacking it with full batches cannot compile)."""
    buf = []
    for b in batches:
        if buf and (
            len(buf) == k or b[0].shape[0] != buf[0][0].shape[0]
        ):
            yield buf
            buf = []
        buf.append(b)
    if buf:
        yield buf


def stack_eval_chunk(group):
    """``[(x, y, mask), ...] -> {"x": [k, N, ...], "y": [k, N],
    "mask": [k, N]}`` — the accumulating eval step's input layout."""
    xs, ys, ms = zip(*group)
    return {
        "x": np.stack([np.asarray(x, np.float32) for x in xs]),
        "y": np.stack([np.asarray(y) for y in ys]),
        "mask": np.stack([np.asarray(m, bool) for m in ms]),
    }


class EvalPipeline:
    """One per training run: compiled eval/stat dispatches + placement.

    ``build_model(axis_name=...)`` is the loops' model factory;
    ``mesh=None`` is the single-device pipeline (still scanned, device-
    resident, prefetched), a mesh turns on sharding.  ``num_domains``
    enables :meth:`collect_stats` (the OfficeHome protocol); digits runs
    leave it None.
    """

    def __init__(
        self,
        build_model,
        test_batch_size: int,
        *,
        plan=None,
        mesh=None,
        num_domains: Optional[int] = None,
        eval_k: int = 1,
        num_workers: int = 0,
        prefetch_size: int = 2,
        whitener: str = "cholesky",
        whiten_eps: float = 1e-3,
        eval_domain: int = 1,
    ):
        # ``plan`` is the run's ShardingPlan (ISSUE-9: one sharding
        # authority); ``mesh=`` is the pre-plan surface, mapped onto the
        # equivalent replica-mode dp plan.
        if plan is None:
            from dwt_tpu.parallel import ShardingPlan

            plan = ShardingPlan.from_mesh(mesh)
        self.test_batch_size = int(test_batch_size)
        self.eval_k = max(1, int(eval_k))
        self.num_workers = num_workers
        self.prefetch_size = prefetch_size
        self._plan = plan
        self._mesh = plan.mesh
        self._procs = jax.process_count()
        self.last_host_fetches = 0  # evidence stream for the bench/tests
        self._warned_unsharded_collect = False
        # Once-per-PASS whitening-matrix precompute (all sites' groups
        # stacked into one batched factorization): eval-mode forwards run
        # off frozen running stats, so re-factorizing at every site for
        # every batch — what the in-model path does — is pure waste.
        self._cache_fn = make_whiten_cache_fn(
            whitener, whiten_eps, eval_domain
        )

        model_free = build_model(axis_name=None)  # axis-free twin
        if plan.mode != "single":
            devices = plan.mesh.size
            if devices % self._procs != 0:
                raise ValueError(
                    f"mesh of {devices} devices cannot split over "
                    f"{self._procs} processes"
                )
            # Eval-mode forwards are per-sample (running stats, no batch
            # moments), so the global eval batch may be rounded UP to the
            # batch-shard count — masked padding keeps the counters exact
            # and the reference accuracies unchanged.  (The model axis
            # never shards the batch: data_size, not mesh.size.)
            self._eval_bs = (
                -(-self.test_batch_size // plan.data_size) * plan.data_size
            )
            self._replicated = plan.replicated
            self._transfer = lambda c: plan.shard_batch(c, chunked=True)
            # Replica mode: counter psum rides the mesh axes (the model
            # stays axis-free — no train-mode moments on the eval path).
            # GSPMD mode: axis-free everything — counters are global
            # values by jit semantics, the plan pins them replicated.
            self._eval_fn = plan.make_eval_step(
                make_accum_eval_step(
                    model_free, axis_name=plan.eval_axis_name
                )
            )
            if num_domains is not None:
                # Collect IS a train-mode forward.  Replica mode needs
                # the mesh-axis model so norm sites pmean their moments
                # into global-batch statistics (1-D meshes use the bare
                # axis name, matching the train path's convention);
                # GSPMD computes global moments from the axis-free model.
                model_collect = (
                    build_model(axis_name=plan.step_axis_name)
                    if plan.mode == "replica" else model_free
                )
                self._collect_sharded = plan.make_collect_step(
                    make_scanned_collect(
                        make_stat_collection_step(
                            model_collect, num_domains
                        )
                    )
                )
        else:
            self._eval_bs = self.test_batch_size
            self._replicated = None
            self._transfer = jax.device_put
            self._eval_fn = jax.jit(make_accum_eval_step(model_free))
        if num_domains is not None:
            self._collect_scanned = jax.jit(
                make_scanned_collect(
                    make_stat_collection_step(model_free, num_domains)
                )
            )
            # Axis-free tail step: the ragged final stat batch runs
            # unsharded (replicated under a mesh) — bitwise the unsharded
            # path's update, and identical on every process.
            self._collect_tail = jax.jit(
                make_stat_collection_step(model_free, num_domains)
            )

    # ------------------------------------------------------------- eval

    def _shard(self) -> Optional[tuple]:
        """Per-process slice spec: multi-host runs split every batch (DP)
        or the test set (legacy single-device path) across processes."""
        if self._procs > 1:
            return (jax.process_index(), self._procs)
        return None

    def _place(self, tree):
        """Replicate host values over the mesh (or default device) —
        the plan's own replicate path (one implementation repo-wide)."""
        return self._plan.place_replicated(tree)

    def evaluate(self, state, dataset) -> dict:
        """Accumulate eval counters over ``dataset``; one host fetch.

        Returns the reference ``test()`` quantities (loss, accuracy %,
        count) plus the phase's wall time and throughput — the metrics
        stream's evidence that the pipelined path holds.
        """
        t0 = time.perf_counter()
        self.last_host_fetches = 0  # counted below, not asserted by fiat
        local_bs = self._eval_bs // (self._procs if self._mesh is not None
                                     else 1)
        stream = batch_iterator(
            dataset,
            local_bs,
            shuffle=False,
            drop_last=False,
            shard=self._shard(),
            num_workers=self.num_workers,
            pad_and_mask=True,
        )
        counters = self._place(eval_counters())
        # The pass's whitening matrices, factorized ONCE from the frozen
        # running stats (site-stacked) and replicated like the stats.
        # The span measures the build's dispatch+placement enqueue (the
        # tracer never syncs); its device cost lands in the first
        # eval_dispatch that consumes it.
        with obs.span("whiten_cache_build", "eval"):
            cache = self._place(self._cache_fn(state.batch_stats))
        batches = prefetch_to_device(
            (stack_eval_chunk(g) for g in _chunk_groups(stream, self.eval_k)),
            size=self.prefetch_size,
            transfer=self._transfer,
        )
        dispatch_intervals = []  # host-side gap between chunk dispatches
        first = True
        try:
            t_prev = time.perf_counter()
            for chunk in obs.traced_iter(batches, "eval_batch_wait", "eval"):
                with obs.span("eval_dispatch", "eval"):
                    counters = self._eval_fn(
                        counters, state.params, state.batch_stats, cache,
                        chunk,
                    )
                t_now = time.perf_counter()
                if first:
                    # The first dispatch of a run pays the jit
                    # trace+compile (seconds); booking it as an interval
                    # would make dispatch_ms_p99 a false stall alarm.
                    first = False
                else:
                    dispatch_intervals.append(t_now - t_prev)
                t_prev = t_now
        finally:
            batches.close()
        with obs.span("eval_host_fetch", "eval"):
            vals = _fetch(counters)  # the pass's ONE device→host sync
        self.last_host_fetches += 1
        loss_sum = float(vals["loss_sum"])
        correct = int(vals["correct"])
        count = int(vals["count"])
        if self._mesh is None and self._procs > 1:
            # Legacy multi-host split without a mesh: each process
            # evaluated a disjoint subset; sum the counters (still O(1)
            # host work — one tiny collective per PASS, not per batch).
            from jax.experimental import multihost_utils

            sums = multihost_utils.process_allgather(
                np.asarray([loss_sum, float(correct), float(count)])
            ).sum(axis=0)
            self.last_host_fetches += 1  # the gather is a 2nd rendezvous
            loss_sum, correct, count = (
                float(sums[0]), int(sums[1]), int(sums[2])
            )
        seconds = time.perf_counter() - t0
        return {
            "loss": loss_sum / max(count, 1),
            "accuracy": 100.0 * correct / max(count, 1),
            "count": count,
            "eval_s": round(seconds, 3),
            "eval_imgs_per_s": round(count / max(seconds, 1e-9), 1),
            # Host-side interval between consecutive chunk dispatches
            # (staging wait + dispatch, NOT device latency — dispatch is
            # async): a fat p99 here means the prefetch pipeline stalled.
            # Shared percentile definition with the serving access log
            # and consensus records (utils.metrics).
            **percentile_summary(
                [v * 1e3 for v in dispatch_intervals], (50.0, 99.0),
                prefix="dispatch_ms_p",
            ),
        }

    # -------------------------------------------------- stat collection

    def _load_tail(self, dataset, start: int, stop: int, seed, epoch):
        """The final ragged stat batch, loaded IN FULL by every process
        (it is < one global batch) with the same per-item seed tokens the
        sharded stream uses — augmentation streams stay identical to the
        unsharded path's."""
        items = []
        for i in range(start, stop):
            item = _load_item(dataset, i, (seed, epoch, int(i)))
            if item is not QUARANTINED:
                items.append(item)
        if not items:
            return None
        return np.stack([np.asarray(it[0], np.float32) for it in items])

    def collect_stats(self, state, dataset, *, seed: int = 0, epoch: int = 0):
        """One full stat-collection pass (reference
        ``eval_pass_collect_stats``): gradient-free train-mode forwards
        advancing only ``batch_stats``, scanned k-per-dispatch, sharded
        over the mesh when the reference batch size splits evenly across
        it.  On a healthy data path the batch composition is EXACTLY the
        unsharded reference's (no padding, ragged tail unsharded), so
        the collected statistics match to reassociation tolerance.

        Caveat: a QUARANTINED item perturbs that parity — the loader's
        sharded stream substitutes a duplicate into the batch (and the
        single-process drop shifts later batch boundaries), so the
        affected batches' moments differ slightly from the
        drop-one-item unsharded oracle.  Collection batches carry no
        mask by design (a mask cannot be threaded through the models'
        norm-site moments), and stats are EMA-smoothed over
        ``stat_collection_passes × B`` batches, so a rare bad item moves
        the estimate negligibly — but bit-parity claims only hold with
        zero quarantines.
        """
        if not hasattr(self, "_collect_scanned"):
            raise RuntimeError(
                "EvalPipeline was built without num_domains; stat "
                "collection is an OfficeHome-recipe phase"
            )
        bs = self.test_batch_size
        n = len(dataset)
        sharded = (
            self._mesh is not None
            and bs % self._plan.data_size == 0
            and n >= bs
        )
        if self._mesh is not None and not sharded and n >= bs:
            if not self._warned_unsharded_collect:
                self._warned_unsharded_collect = True
                log.warning(
                    "stat collection runs unsharded: --test_batch_size "
                    "%d does not split over the plan's %d batch shards "
                    "(padding would perturb the collected moments); eval "
                    "itself stays sharded",
                    bs, self._plan.data_size,
                )
        if sharded:
            usable = n - n % bs
            local_bs = bs // self._procs
            stream = batch_iterator(
                dataset, local_bs, shuffle=False, drop_last=True,
                seed=seed, epoch=epoch, shard=self._shard(),
                num_workers=self.num_workers,
            )
            chunks = (
                np.stack([np.asarray(b[0], np.float32) for b in g])
                for g in _chunk_groups(stream, self.eval_k)
            )
            batches = prefetch_to_device(
                chunks, size=self.prefetch_size, transfer=self._transfer
            )
            try:
                for xs in obs.traced_iter(
                    batches, "collect_batch_wait", "eval"
                ):
                    with obs.span("collect_dispatch", "eval"):
                        state = self._collect_sharded(state, xs)
            finally:
                batches.close()
            if usable < n:
                tail = self._load_tail(dataset, usable, n, seed, epoch)
                if tail is not None:
                    with obs.span("collect_dispatch", "eval"):
                        state = self._collect_tail(state, self._place(tail))
                    # The tail step is a plain jit: under a gspmd plan
                    # its output may carry GSPMD-propagated shardings
                    # instead of the plan's pinned ones — re-pin, or the
                    # next explicitly-sharded dispatch raises on the
                    # mismatch.  No-op everywhere else.
                    state = self._plan.place(state, "train state")
            return state
        # Unsharded (or tiny-dataset) pipeline: still scanned, prefetched,
        # device-resident; the ragged tail cuts into its own dispatch.
        stream = batch_iterator(
            dataset, bs, shuffle=False, drop_last=False,
            seed=seed, epoch=epoch, num_workers=self.num_workers,
        )
        chunks = (
            np.stack([np.asarray(b[0], np.float32) for b in g])
            for g in _chunk_groups(stream, self.eval_k)
        )
        batches = prefetch_to_device(
            chunks, size=self.prefetch_size, transfer=self._place,
        )
        try:
            for xs in obs.traced_iter(batches, "collect_batch_wait", "eval"):
                with obs.span("collect_dispatch", "eval"):
                    state = self._collect_scanned(state, xs)
        finally:
            batches.close()
        # The unsharded fallback is a plain jit — re-pin the plan's
        # shardings before the next explicitly-sharded dispatch (see the
        # tail path above).  No-op except under a gspmd plan.
        return self._plan.place(state, "train state")
