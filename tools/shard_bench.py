"""Sharding-plan microbench: restore-to-spec vs replicate-then-reshard,
and the per-step cost of rules-driven specs vs the historical wrappers.

Two questions, answered with numbers (PERF.md "Sharding plan"):

1. **Restore placement** — the rules engine's restore-to-spec places
   every checkpoint leaf DIRECTLY onto its target sharding
   (``restore_state(..., shardings=plan.tree_shardings(t))``, via
   ``make_array_from_callback``), where the naive path restores
   replicated and then reshards (``restore_state(...)`` +
   ``plan.place(...)``).  The naive path's transient peak holds BOTH
   copies live — the replicated tree and the resharded one — which is
   exactly the HBM spike that blocks restoring a backbone larger than
   one chip.  Each arm runs in its OWN subprocess so ``ru_maxrss`` is a
   clean per-arm high-water mark; device-buffer bytes are computed from
   the live arrays' addressable shards at the steady state and at the
   naive arm's double-allocation point.

2. **Step dispatch** — the dp-preset replica plan must cost the same
   per step as the historical ``make_sharded_train_step`` wrapper (it
   is the SAME shard_map program with explicit all-``P()`` specs); the
   rules engine adds one table match at trace time, nothing per step.
   Timed as median per-step wall over ``--steps`` post-warmup steps,
   legacy wrapper vs plan, on the same mesh.

Run on CPU fake devices (the dryrun meshes)::

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/shard_bench.py

Prints one JSON record; ``--arm`` is the internal per-subprocess entry.
"""

import argparse
import json
import os
import resource
import statistics
import subprocess
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _build(model_name: str):
    import jax
    import jax.numpy as jnp

    from dwt_tpu.nn import LeNetDWT, ResNetDWT
    from dwt_tpu.train import adam_l2, create_train_state

    tx = adam_l2(1e-3)
    if model_name == "lenet":
        model = LeNetDWT(group_size=4)
        sample = jnp.zeros((2, 8, 28, 28, 1), jnp.float32)
    else:
        model = ResNetDWT.resnet50(group_size=4, num_classes=65)
        sample = jnp.zeros((3, 2, 64, 64, 3), jnp.float32)
    state = create_train_state(model, jax.random.key(0), sample, tx)
    return model, tx, state


def _plan(n_devices: int):
    from dwt_tpu.parallel import PRESETS, ShardingPlan, make_plan_mesh

    shape = (1, n_devices // 2, 2)
    return ShardingPlan.gspmd(
        make_plan_mesh(shape), PRESETS["model"], name="model"
    ), shape


def _device_bytes(tree):
    import jax

    total = 0
    for leaf in jax.tree.leaves(tree):
        if hasattr(leaf, "addressable_shards"):
            total += sum(s.data.nbytes for s in leaf.addressable_shards)
        else:
            total += getattr(leaf, "nbytes", 0)
    return int(total)


def _run_arm(arm: str, model_name: str, ckpt_dir: str) -> None:
    """Subprocess entry: one restore arm, clean ru_maxrss."""
    import jax

    from dwt_tpu.utils.checkpoint import restore_state, save_state

    model, tx, state = _build(model_name)
    plan, _ = _plan(jax.device_count())
    if not os.listdir(ckpt_dir):
        save_state(ckpt_dir, 1, state)

    t0 = time.perf_counter()
    if arm == "restore_to_spec":
        restored = restore_state(
            ckpt_dir, state, shardings=plan.restore_shardings(state)
        )
        jax.block_until_ready(restored)
        wall_s = time.perf_counter() - t0
        steady = _device_bytes(restored)
        peak_bytes = steady
    else:  # replicate_reshard
        replicated = restore_state(ckpt_dir, state)
        replicated = jax.device_put(replicated, plan.replicated)
        jax.block_until_ready(replicated)
        resharded = plan.place(replicated, "train state")
        jax.block_until_ready(resharded)
        wall_s = time.perf_counter() - t0
        # Double-allocation point: both trees are live RIGHT NOW.
        peak_bytes = _device_bytes(replicated) + _device_bytes(resharded)
        steady = _device_bytes(resharded)
        del replicated
    print(json.dumps({
        "arm": arm,
        "wall_s": round(wall_s, 4),
        "steady_device_mb": round(steady / 2**20, 2),
        "peak_device_mb": round(peak_bytes / 2**20, 2),
        "ru_maxrss_mb": round(
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024, 1
        ),
    }))


def _analytic_device_bytes(tree, specs, mesh) -> int:
    """Per-device bytes for ``tree`` placed to ``specs`` on ``mesh``:
    each leaf contributes its bytes divided by the product of the mesh
    axes its spec shards over (replicated leaves contribute fully)."""
    import jax
    import numpy as np
    from jax.sharding import PartitionSpec as P

    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    total = 0.0
    leaves = jax.tree.leaves(tree)
    # isinstance, NOT hasattr(.index): optax states are NamedTuples,
    # which also have .index and would be swallowed whole.
    spec_leaves = jax.tree.leaves(specs, is_leaf=lambda s: isinstance(s, P))
    assert len(leaves) == len(spec_leaves)
    for leaf, spec in zip(leaves, spec_leaves):
        denom = int(np.prod([
            sizes[a] for part in spec if part is not None
            for a in ((part,) if isinstance(part, str) else part)
        ] or [1]))
        total += leaf.size * leaf.dtype.itemsize / denom
    return int(total)


def _bench_fsdp(model_name: str, steps: int) -> dict:
    """The fsdp-preset arm: per-device param+opt-state bytes under
    dp/model/fsdp (analytic over the real param tree via eval_shape —
    resnet152 replicated x8 would not fit a CI host), a materialized
    lenet cross-check of the analytic formula, and the fsdp-vs-dp
    per-step A/B at equal data parallelism (the dp arm runs the SAME
    axis-free program on a (1,4,1) prefix mesh; fsdp adds only the
    model axis)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dwt_tpu.nn import LeNetDWT, build_backbone
    from dwt_tpu.parallel import PRESETS, ShardingPlan, make_plan_mesh
    from dwt_tpu.parallel.plan import match_partition_rules
    from dwt_tpu.train import adam_l2, create_train_state, make_digits_train_step

    shape = (1, jax.device_count() // 2, 2)
    mesh = make_plan_mesh(shape)

    # --- per-device state bytes over the REAL backbone param tree ---
    # pad_classes_to=2 is the designed fsdp path for the 65-class head
    # (the preset refuses an indivisible head, naming this flag).
    tx = adam_l2(1e-3)
    model = build_backbone(
        model_name, group_size=4, num_classes=65, pad_classes_to=2
    )
    sample = jax.ShapeDtypeStruct((3, 2, 64, 64, 3), jnp.float32)
    state_shapes = jax.eval_shape(
        lambda s: create_train_state(model, jax.random.key(0), s, tx), sample
    )
    param_opt = (state_shapes.params, state_shapes.opt_state)
    per_device = {}
    for preset in ("dp", "model", "fsdp"):
        specs = match_partition_rules(
            PRESETS[preset], state_shapes, mesh=mesh,
            what=f"{model_name} {preset}",
        )
        per_device[f"{preset}_param_opt_bytes"] = _analytic_device_bytes(
            param_opt, (specs.params, specs.opt_state), mesh
        )
        if preset == "fsdp":
            per_device["fsdp_stats_bytes"] = _analytic_device_bytes(
                state_shapes.batch_stats, specs.batch_stats, mesh
            )
    per_device["fsdp_bytes_reduction_x"] = round(
        per_device["dp_param_opt_bytes"]
        / max(per_device["fsdp_param_opt_bytes"], 1), 3
    )

    # --- materialized cross-check: the analytic formula must agree with
    # real addressable-shard bytes on a model small enough to place ---
    lenet, ltx, lstate = _build("lenet")
    fsdp_plan = ShardingPlan.gspmd(mesh, PRESETS["fsdp"], name="fsdp")
    placed = fsdp_plan.place(lstate, "train state")
    dev0 = mesh.devices.flat[0]
    measured = 0
    for leaf in jax.tree.leaves((placed.params, placed.opt_state)):
        for s in leaf.addressable_shards:
            if s.device == dev0:
                measured += s.data.nbytes
    lspecs = match_partition_rules(
        PRESETS["fsdp"], lstate, mesh=mesh, what="lenet fsdp"
    )
    analytic = _analytic_device_bytes(
        (lstate.params, lstate.opt_state),
        (lspecs.params, lspecs.opt_state), mesh,
    )
    check_ok = abs(measured - analytic) <= 0.01 * analytic

    # --- per-step A/B: the deployment question — the SAME devices and
    # global batch, configured as pure DP ((1,n,1), the dp preset's own
    # best layout) vs fsdp ((1,n/2,2)).  Anything else double-counts:
    # dp ON a model-axis mesh computes every sample once per model
    # replica, and a smaller dp mesh changes the simulation cost ---
    rng = np.random.default_rng(0)
    nb = shape[1] * 2
    batch = {
        "source_x": jnp.asarray(rng.normal(size=(nb, 28, 28, 1)), jnp.float32),
        "source_y": jnp.asarray(rng.integers(0, 10, size=(nb,))),
        "target_x": jnp.asarray(rng.normal(size=(nb, 28, 28, 1)), jnp.float32),
    }
    raw = make_digits_train_step(lenet, ltx, 0.1, axis_name=None)
    dp_plan = ShardingPlan.gspmd(
        make_plan_mesh((1, jax.device_count(), 1)), PRESETS["dp"], name="dp"
    )
    dp_ms = _median_step_ms(
        dp_plan.make_train_step(raw),
        dp_plan.place(lstate, "train state"),
        dp_plan.shard_batch(batch), steps,
    )
    fsdp_ms = _median_step_ms(
        fsdp_plan.make_train_step(raw), placed,
        fsdp_plan.shard_batch(batch), steps,
    )

    return {
        "kind": "shard_bench",
        "preset": "fsdp",
        "model": model_name,
        "mesh_shape": list(shape),
        "per_device": per_device,
        "analytic_check_ok": bool(check_ok),
        "step_ab": {
            "devices": jax.device_count(),
            "steps": steps,
            "dp_step_ms": round(dp_ms, 2),
            "fsdp_step_ms": round(fsdp_ms, 2),
            "fsdp_step_overhead_x": round(fsdp_ms / dp_ms, 3),
        },
    }


def _median_step_ms(step, state, batch, steps: int) -> float:
    import jax

    new_state, _ = step(state, batch)          # compile + first dispatch
    jax.block_until_ready(new_state)
    times = []
    for _ in range(steps):
        t0 = time.perf_counter()
        new_state, metrics = step(new_state, batch)
        jax.block_until_ready((new_state, metrics))
        times.append((time.perf_counter() - t0) * 1e3)
    return statistics.median(times)


def _bench_steps(model_name: str, steps: int) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dwt_tpu.nn import LeNetDWT
    from dwt_tpu.parallel import (
        ShardingPlan,
        make_mesh,
        make_sharded_train_step,
        replicate_state,
        shard_batch,
    )
    from dwt_tpu.train import make_digits_train_step

    assert model_name == "lenet", "step A/B runs the digits step (lenet)"
    model, tx, state = _build(model_name)
    n = jax.device_count()
    rng = np.random.default_rng(0)
    batch = {
        "source_x": jnp.asarray(rng.normal(size=(n, 28, 28, 1)), jnp.float32),
        "source_y": jnp.asarray(rng.integers(0, 10, size=(n,))),
        "target_x": jnp.asarray(rng.normal(size=(n, 28, 28, 1)), jnp.float32),
    }
    mesh = make_mesh()
    axis = "data" if len(mesh.axis_names) == 1 else tuple(mesh.axis_names)
    model_dp = LeNetDWT(group_size=4, axis_name=axis)
    raw = make_digits_train_step(model_dp, tx, 0.1, axis_name=axis)

    legacy = make_sharded_train_step(raw, mesh)
    legacy_ms = _median_step_ms(
        legacy, replicate_state(state, mesh), shard_batch(batch, mesh), steps
    )

    plan = ShardingPlan.replica(mesh)
    plan_step = plan.make_train_step(raw)
    plan_ms = _median_step_ms(
        plan_step, replicate_state(state, mesh), plan.shard_batch(batch),
        steps,
    )
    return {
        "devices": n,
        "steps": steps,
        "legacy_dp_step_ms": round(legacy_ms, 2),
        "plan_dp_step_ms": round(plan_ms, 2),
        "overhead_x": round(plan_ms / legacy_ms, 3),
    }


def main(argv=None):
    p = argparse.ArgumentParser(
        description="sharding-plan restore + step-overhead microbench"
    )
    p.add_argument("--model", choices=["lenet", "resnet50"], default="lenet")
    p.add_argument("--steps", type=int, default=30,
                   help="timed steps for the per-step A/B")
    p.add_argument("--preset", choices=["fsdp"], default=None,
                   help="fsdp: per-device param+opt-state bytes under "
                        "dp/model/fsdp over the real backbone tree "
                        "(default resnet152) + fsdp-vs-dp step A/B")
    p.add_argument("--backbone", default="resnet152",
                   help="registry entry for the --preset fsdp byte "
                        "accounting (dwt_tpu.nn.registry)")
    p.add_argument("--arm", default=None,
                   help="(internal) subprocess restore arm")
    p.add_argument("--ckpt_dir", default=None,
                   help="(internal) shared checkpoint dir for the arms")
    args = p.parse_args(argv)

    if args.arm:
        _run_arm(args.arm, args.model, args.ckpt_dir)
        return 0

    # Force the CPU dryrun mesh in THIS process too (jax is only
    # imported inside the bench fns, so this is early enough) — the
    # parent runs the step A/B itself.
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    if "xla_force_host_platform_device_count" not in os.environ.get(
        "XLA_FLAGS", ""
    ):
        os.environ["XLA_FLAGS"] = (
            os.environ.get("XLA_FLAGS", "")
            + " --xla_force_host_platform_device_count=8"
        ).strip()
    env = dict(os.environ)

    if args.preset == "fsdp":
        print(json.dumps(_bench_fsdp(args.backbone, args.steps)))
        return 0

    record = {"model": args.model, "restore": {}}
    with tempfile.TemporaryDirectory() as td:
        # Seed the checkpoint once (restore_to_spec arm runs first and
        # writes it; the dir is shared so both arms read the same bytes).
        for arm in ("restore_to_spec", "replicate_reshard"):
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__),
                 "--arm", arm, "--model", args.model, "--ckpt_dir", td],
                env=env, capture_output=True, text=True, timeout=1200,
            )
            if proc.returncode != 0:
                print(proc.stderr[-2000:], file=sys.stderr)
                return 1
            line = [l for l in proc.stdout.splitlines() if l.startswith("{")]
            record["restore"][arm] = json.loads(line[-1])
    r2s = record["restore"]["restore_to_spec"]
    naive = record["restore"]["replicate_reshard"]
    record["restore"]["peak_device_mb_saved"] = round(
        naive["peak_device_mb"] - r2s["peak_device_mb"], 2
    )
    record["restore"]["wall_speedup_x"] = round(
        naive["wall_s"] / max(r2s["wall_s"], 1e-9), 2
    )

    if args.model == "lenet":
        record["step_ab"] = _bench_steps(args.model, args.steps)
    print(json.dumps(record))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
