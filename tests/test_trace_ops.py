"""tools/trace_ops.py aggregation on a hand-built XSpace proto.

Pins the properties the TPU go/no-go read depends on: per-line totals are
never summed across overlapping lines, durations aggregate per op name,
hlo_category resolves through stat refs without crashing on dangling
refs, and host-CPU planes stay out of device reports.
"""

import json
import os
import subprocess
import sys

import pytest

tf_pb = pytest.importorskip(
    "tensorflow.tsl.profiler.protobuf.xplane_pb2",
    reason="tensorflow (xplane proto) not installed",
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _build_space():
    xs = tf_pb.XSpace()
    dev = xs.planes.add()
    dev.name = "/device:TPU:0"
    # op metadata
    dev.event_metadata[1].name = "fusion.1"
    dev.event_metadata[2].name = "convolution.2"
    dev.event_metadata[3].name = "whole-module"
    dev.stat_metadata[10].name = "hlo_category"
    dev.stat_metadata[11].name = "convolution"  # ref target for category
    # category stat on metadata: fusion.1 via str_value, conv.2 via ref
    st = dev.event_metadata[1].stats.add()
    st.metadata_id = 10
    st.str_value = "fusion"
    st2 = dev.event_metadata[2].stats.add()
    st2.metadata_id = 10
    st2.ref_value = 11
    # dangling ref: must not crash, falls back to uncategorized
    st3 = dev.event_metadata[3].stats.add()
    st3.metadata_id = 10
    st3.ref_value = 99  # no such stat_metadata entry

    ops_line = dev.lines.add()
    ops_line.name = "XLA Ops"
    for md_id, dur in ((1, 7_000_000), (2, 3_000_000), (1, 5_000_000)):
        ev = ops_line.events.add()
        ev.metadata_id = md_id
        ev.duration_ps = dur
    mod_line = dev.lines.add()
    mod_line.name = "XLA Modules"
    ev = mod_line.events.add()
    ev.metadata_id = 3
    ev.duration_ps = 15_000_000

    host = xs.planes.add()
    host.name = "/host:CPU"
    hl = host.lines.add()
    hl.name = "python"
    hev = hl.events.add()
    hev.metadata_id = 1
    hev.duration_ps = 999_000_000
    return xs


def test_aggregate_per_line_no_cross_line_double_count(tmp_path):
    xs = _build_space()
    run_dir = tmp_path / "plugins" / "profile" / "run1"
    run_dir.mkdir(parents=True)
    (run_dir / "vm.xplane.pb").write_bytes(xs.SerializeToString())

    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_ops.py"),
         str(tmp_path)],
        capture_output=True, text=True, timeout=180,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    report = json.loads(proc.stdout)
    by_line = {l["line"]: l for l in report["lines"]}
    # Host plane excluded when a device plane exists.
    assert set(by_line) == {"XLA Ops", "XLA Modules"}
    ops = by_line["XLA Ops"]
    # 7+5 ps aggregated for fusion.1; 3 for convolution.2 — and the module
    # line's 15 never leaks into the ops line's total.
    assert ops["total_ms"] == pytest.approx(0.015)
    top = {o["name"]: o for o in ops["top_ops"]}
    assert top["fusion.1"]["ms"] == pytest.approx(0.012)
    assert top["fusion.1"]["category"] == "fusion"
    assert top["convolution.2"]["category"] == "convolution"
    assert by_line["XLA Modules"]["top_ops"][0]["category"] == "uncategorized"


def test_line_filter(tmp_path):
    xs = _build_space()
    run_dir = tmp_path / "plugins" / "profile" / "run1"
    run_dir.mkdir(parents=True)
    (run_dir / "vm.xplane.pb").write_bytes(xs.SerializeToString())
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_ops.py"),
         str(tmp_path), "--line", "xla ops"],
        capture_output=True, text=True, timeout=180,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    report = json.loads(proc.stdout)
    assert [l["line"] for l in report["lines"]] == ["XLA Ops"]
