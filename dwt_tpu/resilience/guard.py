"""Divergence guard: amortized finite-checks with an escalation ladder.

The DWT forward path runs a Cholesky factorization per whitening site per
step; ill-conditioned batch covariances can (rarely) produce a NaN/Inf
that silently poisons every later step — on a preemptible multi-day run
the job keeps burning TPU hours training garbage.  Guarding every step
with a host-side ``isfinite`` would serialize the async dispatch queue,
so the guard checks every ``interval`` steps: it keeps device references
to the latest loss/grad-norm metrics (free — no sync) and only fetches a
single jitted boolean verdict at check boundaries.  NaN is absorbing
(poisoned params keep producing NaN losses), so an amortized check still
catches any divergence, at most ``interval - 1`` steps late.

Recovery is a LADDER, mildest rung first:

* ``lr_backoff`` (optional first rung, ``lr_backoff`` in (0, 1)) —
  revert to the in-memory snapshot from the last passing check AND scale
  the optimizer's updates by the factor (via the injectable
  :func:`~dwt_tpu.train.optim.scale_by_backoff` state — no recompile, no
  disk I/O).  A *transient* spike thus costs at most ``interval`` steps
  replayed gently; after ``backoff_recovery`` consecutive clean checks
  the scale recovers to 1.0 and the rung re-arms.  A divergence striking
  *while backed off* is persistent — escalate to the configured policy.
* ``skip_step`` — revert to the in-memory snapshot and continue with
  fresh batches (no disk I/O).
* ``rollback`` — raise :class:`RollbackRequest`; the training loop
  restores the newest *valid* on-disk checkpoint and re-seeds its data
  streams so the replayed segment draws a different batch order.
* ``halt`` — raise :class:`DivergenceError`; the scheduler/operator sees
  a failed job instead of a silently-ruined one.  ``rollback`` escalates
  here after ``max_rollbacks`` attempts.
"""

from __future__ import annotations

from typing import Any, Optional

POLICIES = ("none", "halt", "skip_step", "rollback")


class DivergenceError(RuntimeError):
    """Non-finite loss/grad detected and the policy says stop."""


class RollbackRequest(Exception):
    """Control-flow signal: restore the last valid checkpoint and retry.

    Raised by :class:`DivergenceGuard`, caught by the training loops'
    rollback wrapper — never escapes a loop.
    """

    def __init__(self, step: int, reason: str):
        super().__init__(reason)
        self.step = step
        self.reason = reason


def _snapshot(state: Any) -> Any:
    """Device-side deep copy of the train state.

    A plain reference is NOT enough: the ``steps_per_dispatch`` paths
    donate the input state's buffers to the compiled step, so a kept
    reference would be invalidated by the very next dispatch.  Fresh
    buffers survive donation.  Delegates to the async checkpointer's
    jitted whole-tree copy: this runs on the hot path every passing
    guard check, where the eager per-leaf form stalls tens of ms against
    a deep dispatch queue (measured in async_ckpt.py).
    """
    from dwt_tpu.resilience.async_ckpt import snapshot_state

    return snapshot_state(state)


class DivergenceGuard:
    def __init__(
        self,
        policy: str,
        interval: int,
        logger=None,
        max_rollbacks: int = 3,
        lr_backoff: float = 0.0,
        backoff_recovery: int = 3,
    ):
        if policy not in POLICIES or policy == "none":
            raise ValueError(
                f"guard policy must be one of {POLICIES[1:]}; got {policy!r}"
            )
        if lr_backoff and not (0.0 < lr_backoff < 1.0):
            raise ValueError(
                "guard lr_backoff must be a scale factor in (0, 1) "
                f"(0 disables the rung); got {lr_backoff!r}"
            )
        self.policy = policy
        self.interval = max(1, int(interval))
        self.max_rollbacks = max_rollbacks
        self.rollbacks = 0
        self.lr_backoff = float(lr_backoff or 0.0)
        self.backoff_recovery = max(1, int(backoff_recovery))
        self.backoffs = 0  # lifetime count of rung-1 engagements
        # Count of IN-MEMORY recoveries (lr_backoff + skip_step): these
        # rungs return a state instead of raising, so the step-boundary
        # consensus reads this counter to learn that a recovery fired
        # and broadcast it to the other hosts.
        self.recoveries = 0
        self._scale = 1.0  # current backoff scale (host mirror)
        self._clean_checks = 0  # passing checks since the scale dropped
        self._logger = logger
        self._since_check = 0
        self._good: Optional[Any] = None
        # Snapshot from the passing check BEFORE the latest one: a host
        # mirroring a remote divergence at this boundary must revert to
        # the state the remote host reverted to — and the remote host
        # never refreshed its snapshot at this boundary (its check
        # failed), while this host's passing check just did.
        self._prev_good: Optional[Any] = None
        self._verdict_fn = None

    # ------------------------------------------------------------- internals

    @property
    def _keeps_good(self) -> bool:
        # The backoff rung reverts to the in-memory snapshot too (NaN is
        # absorbing: reducing lr without discarding poisoned params would
        # train NaN at a smaller step size), so it needs one even under
        # the halt policy.
        return self.policy in ("skip_step", "rollback") or self.lr_backoff > 0

    def _finite(self, metrics) -> bool:
        """One host sync: jitted all-finite verdict over loss + grad norm.

        Accepts scalar metrics (per-step path) or ``[k]``-stacked metrics
        (chunked path) — ``all`` reduces either.
        """
        import jax
        import jax.numpy as jnp

        if self._verdict_fn is None:
            self._verdict_fn = jax.jit(
                lambda loss, gn: jnp.all(jnp.isfinite(loss))
                & jnp.all(jnp.isfinite(gn))
            )
        loss = metrics["loss"]
        gn = metrics.get("grad_norm", loss)
        return bool(self._verdict_fn(loss, gn))

    def _log(self, kind: str, step: int, **values) -> None:
        if self._logger is not None:
            self._logger.log(kind, step, sync=True, **values)

    def _set_scale(self, state: Any, scale: float) -> Any:
        from dwt_tpu.train.optim import set_backoff_scale

        self._scale = float(scale)
        return state.replace(
            opt_state=set_backoff_scale(state.opt_state, scale)
        )

    # ------------------------------------------------------------------ API

    def prime(self, state: Any) -> None:
        """Record the initial known-good state (pre-training or post-resume),
        so a divergence before the first passing check is still recoverable."""
        if self.lr_backoff > 0:
            from dwt_tpu.train.optim import has_backoff

            if not has_backoff(state.opt_state):
                raise ValueError(
                    "guard lr_backoff needs an optimizer wrapped with "
                    "dwt_tpu.train.optim.with_lr_backoff (no "
                    "BackoffScaleState in the opt state)"
                )
        if self._keeps_good:
            self._good = _snapshot(state)
            self._prev_good = self._good

    @property
    def good_state(self) -> Optional[Any]:
        """A fresh copy of the last known-good state (donation-safe)."""
        if self._good is None:
            return None
        return _snapshot(self._good)

    @property
    def in_backoff(self) -> bool:
        return self._scale != 1.0

    def reapply_backoff(self, state: Any) -> Any:
        """Re-impose the current backoff scale on a state restored from
        disk (whose saved scale predates the backoff): the segment
        replayed after a rollback escalation trains gently too."""
        if not self.in_backoff:
            return state
        self._clean_checks = 0
        return self._set_scale(state, self._scale)

    def step(self, state: Any, metrics: Any, n_steps: int, step_no: int) -> Any:
        """Account ``n_steps`` finished steps whose latest metrics are
        ``metrics``; run the amortized check when due.  Returns the state
        to continue from (replaced under ``lr_backoff``/``skip_step``
        recovery).

        ``metrics`` may hold device arrays — they are only fetched at
        check boundaries, so the async dispatch pipeline stays full
        between checks.
        """
        self._since_check += n_steps
        if self._since_check < self.interval:
            return state
        self._since_check = 0
        if self._finite(metrics):
            if self.in_backoff:
                self._clean_checks += 1
                if self._clean_checks >= self.backoff_recovery:
                    state = self._set_scale(state, 1.0)
                    self._log("lr_recover", step_no, scale=1.0,
                              clean_checks=self._clean_checks)
            if self._keeps_good:
                self._prev_good = self._good
                self._good = _snapshot(state)
            return state
        return self._diverged(state, step_no)

    def mirror_recovery(self, state: Any, step_no: int) -> Any:
        """Perform the divergence rung WITHOUT a local verdict: the
        step-boundary consensus reported another host's guard fired while
        this host's metrics looked finite (a host-local fault preceding
        the collective).  Hosts run the same guard config in step lock,
        so the local ladder takes the same rung the remote one did —
        keeping the replicated state identical across processes.  May
        raise exactly like a local detection (escalation is global too).

        This host's check PASSED at this boundary, refreshing ``_good``
        to the current state — a snapshot the remote (failed-check) host
        never took.  Reverting must target the snapshot BOTH hosts hold,
        the one from the previous passing check, so the refresh is
        rolled back first.
        """
        if self._prev_good is not None:
            self._good = self._prev_good
        return self._diverged(state, step_no)

    def _diverged(self, state: Any, step_no: int) -> Any:
        self._log(
            "divergence", step_no, policy=self.policy, scale=self._scale
        )
        if self.lr_backoff and not self.in_backoff and self._good is not None:
            # Rung 1: revert to the last good state, train gently.  Only
            # when not ALREADY backed off — a strike at reduced lr is
            # persistent and falls through to the configured policy.
            self.backoffs += 1
            self.recoveries += 1
            self._clean_checks = 0
            recovered = self._set_scale(self.good_state, self.lr_backoff)
            self._log("lr_backoff", step_no, scale=self.lr_backoff,
                      backoffs=self.backoffs)
            return recovered
        if self.policy == "skip_step" and self._good is not None:
            self._log("skip_step", step_no)
            self.recoveries += 1
            self._clean_checks = 0  # a backed-off skip re-earns recovery
            if self.in_backoff:
                # The snapshot predates the backoff engagement (no passing
                # check since), so its opt state still carries scale 1.0 —
                # re-impose the rung or the "gentle" replay would run at
                # exactly the lr that just diverged (and the host mirror
                # would desync from the device scale).
                return self._set_scale(self.good_state, self._scale)
            return self.good_state
        if self.policy == "rollback":
            if self.rollbacks >= self.max_rollbacks:
                raise DivergenceError(
                    f"non-finite loss/grad at step {step_no}; "
                    f"{self.rollbacks} rollbacks already spent — halting"
                )
            self.rollbacks += 1
            raise RollbackRequest(
                step_no, f"non-finite loss/grad at step {step_no}"
            )
        raise DivergenceError(
            f"non-finite loss/grad at step {step_no} (policy={self.policy})"
        )
