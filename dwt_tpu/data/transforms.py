"""Image transforms: PIL for geometry, numpy/cv2 for the aug math.

The OfficeHome target-view augmentation stack replicated from the
reference (``resnet50_dwt_mec_officehome.py:481-492,535-543``): resize →
random crop → hflip → random affine perturbation → (near-no-op) gaussian
blur → normalize.  All callables are ``img -> img`` where ``img`` is a PIL
Image until ``ToArray`` and an HWC float32 numpy array after.
"""

from __future__ import annotations

import threading
from typing import Sequence

import numpy as np

try:
    import cv2

    _HAS_CV2 = True
except ImportError:  # pragma: no cover
    _HAS_CV2 = False


_ITEM_SEED = threading.local()


def set_item_seed(token) -> None:
    """Declare the (hashable, int-tuple) identity of the item being loaded
    on THIS thread; ``ThreadLocalRng`` derives its stream from it so an
    item's augmentations depend only on (rng seed, item token) — never on
    which worker thread loaded it.  ``batch_iterator`` sets this around
    every ``dataset[i]`` call; ``None`` clears it."""
    _ITEM_SEED.token = token


class ThreadLocalRng:
    """``np.random.Generator`` facade that is thread-safe AND item-deterministic.

    ``np.random.Generator`` is not thread-safe; when ``batch_iterator``
    runs ``dataset[i]`` on a worker pool, stochastic transforms sharing a
    single generator would race.  Worse, per-*thread* streams would make a
    fixed-seed run irreproducible (item→thread assignment is scheduler-
    dependent).  So: while an item is being loaded (``set_item_seed``
    active, which both loading paths of ``batch_iterator`` arrange), draws
    come from a generator seeded by ``(seed, *item_token)`` — identical
    whether the item loads sequentially, on any pool size, or on any
    thread.  Outside item context each thread falls back to its own
    spawned stream (valid draws, no races, no cross-run promise).
    """

    def __init__(self, seed: int = 0):
        self._entropy = int(seed)
        self._seq = np.random.SeedSequence(self._entropy)
        self._local = threading.local()
        self._lock = threading.Lock()

    def _gen(self) -> np.random.Generator:
        token = getattr(_ITEM_SEED, "token", None)
        if token is not None:
            if getattr(self._local, "token", None) != token:
                self._local.item_gen = np.random.default_rng(
                    np.random.SeedSequence((self._entropy,) + tuple(token))
                )
                self._local.token = token
            return self._local.item_gen
        gen = getattr(self._local, "gen", None)
        if gen is None:
            with self._lock:  # SeedSequence.spawn mutates internal state
                child = self._seq.spawn(1)[0]
            gen = np.random.default_rng(child)
            self._local.gen = gen
        return gen

    def integers(self, *args, **kwargs):
        return self._gen().integers(*args, **kwargs)

    def random(self, *args, **kwargs):
        return self._gen().random(*args, **kwargs)

    def normal(self, *args, **kwargs):
        return self._gen().normal(*args, **kwargs)

    def permutation(self, *args, **kwargs):
        return self._gen().permutation(*args, **kwargs)


class Compose:
    def __init__(self, transforms: Sequence):
        self.transforms = list(transforms)

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class Resize:
    """Resize to ``(size, size)`` (PIL bilinear), matching
    ``transforms.Resize((s, s))`` (``resnet50…py:528``)."""

    def __init__(self, size: int):
        self.size = size

    def __call__(self, img):
        from PIL import Image

        return img.resize((self.size, self.size), Image.BILINEAR)


class RandomCrop:
    def __init__(self, size: int, rng: np.random.Generator | None = None):
        self.size = size
        self.rng = rng or np.random.default_rng()

    def __call__(self, img):
        w, h = img.size
        if (w, h) == (self.size, self.size):
            return img
        left = int(self.rng.integers(0, w - self.size + 1))
        top = int(self.rng.integers(0, h - self.size + 1))
        return img.crop((left, top, left + self.size, top + self.size))


class RandomHorizontalFlip:
    def __init__(self, p: float = 0.5, rng: np.random.Generator | None = None):
        self.p = p
        self.rng = rng or np.random.default_rng()

    def __call__(self, img):
        from PIL import Image

        if self.rng.random() < self.p:
            return img.transpose(Image.FLIP_LEFT_RIGHT)
        return img


class ToArray:
    """PIL (or numpy) → HWC float32 in [0, 1] — torch ``ToTensor`` minus
    the NCHW permute (TPU wants channels-last).

    Integer-dtype input always divides by 255 (torch ``ToTensor``
    semantics — value-sniffing would misread an all-dark uint8 crop as
    already-normalized, diverging from the native fused path on exactly
    those images); float input divides only when it looks 255-ranged.
    """

    def __call__(self, img) -> np.ndarray:
        raw = np.asarray(img)
        a = raw.astype(np.float32)
        if a.ndim == 2:
            a = a[:, :, None]
        if raw.dtype.kind in "ui":
            a = a / 255.0
        elif a.max() > 1.5:  # 255-ranged float input
            a = a / 255.0
        return a


class Normalize:
    def __init__(self, mean: Sequence[float], std: Sequence[float]):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)

    def __call__(self, a: np.ndarray) -> np.ndarray:
        return (a - self.mean) / self.std


def draw_affine_matrix(
    rng: np.random.Generator, sigma: float = 0.1
) -> np.ndarray:
    """The reference's random 2x3 matrix (``resnet50…py:482-485``):
    identity with N(0, sigma) perturbations, zero translation.  Split out
    so the native fused path and the cv2/scipy path consume the SAME rng
    draws in the same order (stream compatibility between the two)."""
    return np.float32(
        [
            [1 + rng.normal(0, sigma), rng.normal(0, sigma), 0],
            [rng.normal(0, sigma), 1 + rng.normal(0, sigma), 0],
        ]
    )


def random_affine(
    a: np.ndarray, sigma: float = 0.1, rng: np.random.Generator | None = None
) -> np.ndarray:
    """The reference's ``_random_affine_augmentation`` on HWC arrays
    (``resnet50…py:481-487``)."""
    rng = rng or np.random.default_rng()
    return warp_affine(a, draw_affine_matrix(rng, sigma))


def warp_affine(a: np.ndarray, m: np.ndarray) -> np.ndarray:
    """``cv2.warpAffine(a, m, (w, h))`` default semantics (bilinear,
    zero border, ``m`` inverted internally), with a scipy fallback."""
    h, w = a.shape[:2]
    if _HAS_CV2:
        out = cv2.warpAffine(a, m, (w, h))
        if out.ndim == 2:
            out = out[:, :, None]
        return out.astype(np.float32)
    # scipy fallback: affine_transform uses inverse coords, x/y swapped.
    from scipy import ndimage

    full = np.eye(3, dtype=np.float32)
    full[:2] = m[[1, 0]][:, [1, 0, 2]]  # swap x/y convention
    inv = np.linalg.inv(full)
    out = np.stack(
        [
            ndimage.affine_transform(
                a[..., c], inv[:2, :2], offset=inv[:2, 2], order=1
            )
            for c in range(a.shape[-1])
        ],
        axis=-1,
    )
    return out.astype(np.float32)


class FusedToArrayNormalize:
    """``ToArray() → Normalize(mean, std)`` in one native pass over the
    uint8 pixels (``dwt_tpu.native.normalize_from_u8``), falling back to
    the two-step numpy path when the native library is unavailable or the
    input isn't plain uint8 HWC.  Bit-compatible up to float32 rounding:
    both compute ``(v/255 - mean)/std``."""

    def __init__(self, mean: Sequence[float], std: Sequence[float]):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self._fallback = Compose([ToArray(), Normalize(mean, std)])

    def __call__(self, img) -> np.ndarray:
        from dwt_tpu import native

        a = np.asarray(img)
        if (
            native.available()
            and a.dtype == np.uint8
            and a.ndim == 3
            and a.shape[-1] <= 16
        ):
            return native.normalize_from_u8(a, self.mean, self.std)
        # Feed the already-converted array, not the PIL image — ToArray
        # accepts numpy, and re-converting would copy the buffer twice.
        return self._fallback(a)


class FusedAffineBlurNormalize:
    """The aug-view tail ``ToArray → random_affine → gaussian_blur →
    Normalize`` as one native pass (``warp_affine_normalize_from_u8``).

    Draws the affine matrix with :func:`draw_affine_matrix` — the same
    rng calls in the same order as :func:`random_affine` — so the fused
    and fallback paths consume identical random streams.  The fusion is
    only taken when the blur is its reference-default no-op
    (``ksize = int(sigma+0.5)*8+1 <= 1``, ``resnet50…py:489-492``);
    otherwise the unfused chain runs.
    """

    def __init__(
        self,
        mean: Sequence[float],
        std: Sequence[float],
        affine_sigma: float = 0.1,
        blur_sigma: float = 0.1,
        rng=None,
    ):
        self.mean = np.asarray(mean, np.float32)
        self.std = np.asarray(std, np.float32)
        self.affine_sigma = affine_sigma
        self.blur_sigma = blur_sigma
        self.rng = rng or np.random.default_rng()
        self.normalize = Normalize(mean, std)
        self.to_array = ToArray()

    def __call__(self, img) -> np.ndarray:
        from dwt_tpu import native

        a = np.asarray(img)
        m = draw_affine_matrix(self.rng, self.affine_sigma)
        blur_is_noop = int(self.blur_sigma + 0.5) * 8 + 1 <= 1
        if (
            blur_is_noop
            and native.available()
            and a.dtype == np.uint8
            and a.ndim == 3
            and a.shape[-1] <= 16
        ):
            return native.warp_affine_normalize_from_u8(
                a, m, self.mean, self.std
            )
        x = warp_affine(self.to_array(a), m)
        return self.normalize(gaussian_blur(x, self.blur_sigma))


def gaussian_blur(a: np.ndarray, sigma: float = 0.1) -> np.ndarray:
    """The reference's ``_gaussian_blur`` (``resnet50…py:489-492``) —
    ``ksize = int(sigma + 0.5) * 8 + 1``, which is 1 at the default sigma,
    i.e. deliberately near-no-op; replicated, not 'fixed' (SURVEY §7
    quirks)."""
    ksize = int(sigma + 0.5) * 8 + 1
    if ksize <= 1:
        return a
    if _HAS_CV2:
        out = cv2.GaussianBlur(a, (ksize, ksize), sigma)
        if out.ndim == 2:
            out = out[:, :, None]
        return out.astype(np.float32)
    from scipy import ndimage

    out = np.stack(
        [ndimage.gaussian_filter(a[..., c], sigma) for c in range(a.shape[-1])],
        axis=-1,
    )
    return out.astype(np.float32)
