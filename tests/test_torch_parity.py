"""Module-level parity: LeNetDWT vs a torch twin of the reference model.

The strongest accuracy-parity evidence obtainable without datasets: a
minimal torch reimplementation of the reference LeNet's behavior
(``usps_mnist.py:196-278`` — dual whitening/BN branches with a shared
affine, halves split in train, target-branch routing in eval), weight-tied
to ``LeNetDWT``, must produce the same train- and eval-mode outputs and the
same running-stat updates to float tolerance.

The torch twin is built here from the SURVEY formulas (NCHW, grouped
Cholesky whitening via ``bmm``/``cholesky``/``inverse``/grouped conv2d),
not imported from the reference.
"""

import numpy as np
import pytest

torch = pytest.importorskip("torch")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.experimental import enable_x64  # noqa: E402

from dwt_tpu.nn import LeNetDWT  # noqa: E402

import torch.nn as nn  # noqa: E402
import torch.nn.functional as F  # noqa: E402


class _TorchWhiten(nn.Module):
    """Grouped Cholesky whitening, NCHW (reference ``whitening.py:37-61``)."""

    def __init__(self, c, group_size, momentum=0.1, eps=1e-3):
        super().__init__()
        g = min(c, group_size)
        self.ng, self.g, self.eps, self.momentum = c // g, g, eps, momentum
        self.register_buffer("running_mean", torch.zeros(1, c, 1, 1))
        self.register_buffer("running_cov", torch.ones(self.ng, g, g))

    def forward(self, x):
        n, c, h, w = x.shape
        if self.training:
            m = x.mean(dim=(0, 2, 3)).view(1, c, 1, 1)
        else:
            m = self.running_mean
        xn = x - m
        t = xn.permute(1, 0, 2, 3).reshape(self.ng, self.g, -1)
        eye = torch.eye(self.g)
        if self.training:
            cov = torch.bmm(t, t.transpose(1, 2)) / t.shape[-1]
            shrunk = (1 - self.eps) * cov + self.eps * eye
        else:
            shrunk = (1 - self.eps) * self.running_cov + self.eps * eye
        inv = torch.inverse(torch.linalg.cholesky(shrunk))
        weight = inv.reshape(c, self.g, 1, 1)
        y = F.conv2d(xn, weight, groups=self.ng)
        if self.training:
            with torch.no_grad():
                self.running_mean.mul_(1 - self.momentum).add_(
                    self.momentum * m
                )
                self.running_cov.mul_(1 - self.momentum).add_(
                    self.momentum * cov
                )
        return y


class _TorchLeNetDWT(nn.Module):
    """Behavioral twin of the reference LeNet (dual-branch, shared affine)."""

    def __init__(self, group_size=4):
        super().__init__()
        self.conv1 = nn.Conv2d(1, 32, 5, padding=2)
        self.w1 = nn.ModuleList([_TorchWhiten(32, group_size) for _ in range(2)])
        self.g1 = nn.Parameter(torch.ones(1, 32, 1, 1))
        self.b1 = nn.Parameter(torch.zeros(1, 32, 1, 1))
        self.conv2 = nn.Conv2d(32, 48, 5, padding=2)
        self.w2 = nn.ModuleList([_TorchWhiten(48, group_size) for _ in range(2)])
        self.g2 = nn.Parameter(torch.ones(1, 48, 1, 1))
        self.b2 = nn.Parameter(torch.zeros(1, 48, 1, 1))
        self.fc3 = nn.Linear(2352, 100)
        self.n3 = nn.ModuleList(
            [nn.BatchNorm1d(100, affine=False) for _ in range(2)]
        )
        self.g3 = nn.Parameter(torch.ones(1, 100))
        self.b3 = nn.Parameter(torch.zeros(1, 100))
        self.fc4 = nn.Linear(100, 100)
        self.n4 = nn.ModuleList(
            [nn.BatchNorm1d(100, affine=False) for _ in range(2)]
        )
        self.g4 = nn.Parameter(torch.ones(1, 100))
        self.b4 = nn.Parameter(torch.zeros(1, 100))
        self.fc5 = nn.Linear(100, 10)
        self.n5 = nn.ModuleList(
            [nn.BatchNorm1d(10, affine=False) for _ in range(2)]
        )
        self.g5 = nn.Parameter(torch.ones(1, 10))
        self.b5 = nn.Parameter(torch.zeros(1, 10))

    def _branch(self, mods, x):
        if self.training:
            halves = torch.split(x, x.shape[0] // 2, dim=0)
            return torch.cat([mods[d](h) for d, h in enumerate(halves)], dim=0)
        return mods[1](x)  # eval: target branch only

    def forward(self, x):
        x = self.conv1(x)
        x = F.max_pool2d(F.relu(self._branch(self.w1, x) * self.g1 + self.b1), 2, 2)
        x = self.conv2(x)
        x = F.max_pool2d(F.relu(self._branch(self.w2, x) * self.g2 + self.b2), 2, 2)
        x = x.reshape(x.shape[0], -1)
        x = F.relu(self._branch(self.n3, self.fc3(x)) * self.g3 + self.b3)
        x = F.relu(self._branch(self.n4, self.fc4(x)) * self.g4 + self.b4)
        return self._branch(self.n5, self.fc5(x)) * self.g5 + self.b5


def _t2n(t):
    # Preserves the twin's dtype: f32 for the forward/grad parity tests,
    # f64 for the lockstep trajectory tests (under jax x64).  The copy is
    # load-bearing: ``.numpy()`` returns a VIEW of the torch tensor, and
    # ``jnp.asarray`` of a CPU numpy array can be zero-copy — without the
    # copy, in-place torch optimizer updates would silently mutate the
    # "tied" jax params after the fact.
    return t.detach().numpy().copy()


def _lenet_tree_from_torch(tm, get):
    """Map LeNet twin tensors (weights via ``get=lambda p: p`` or grads via
    ``get=lambda p: p.grad``) into the flax param-tree layout — the same
    transposes apply to both, since a gradient has its parameter's layout."""

    def conv_kernel(w):  # OIHW -> HWIO
        return jnp.asarray(_t2n(get(w)).transpose(2, 3, 1, 0))

    params = {}
    params["conv1"] = {
        "kernel": conv_kernel(tm.conv1.weight),
        "bias": jnp.asarray(_t2n(get(tm.conv1.bias))),
    }
    params["conv2"] = {
        "kernel": conv_kernel(tm.conv2.weight),
        "bias": jnp.asarray(_t2n(get(tm.conv2.bias))),
    }
    # fc3 consumes the flatten of [7,7,48] (NHWC) in flax but [48,7,7]
    # (NCHW) in torch — permute the input-dim blocks accordingly.
    w3 = _t2n(get(tm.fc3.weight)).reshape(100, 48, 7, 7).transpose(0, 2, 3, 1)
    params["fc3"] = {
        "kernel": jnp.asarray(w3.reshape(100, 2352).T),
        "bias": jnp.asarray(_t2n(get(tm.fc3.bias))),
    }
    for name, lin in (("fc4", tm.fc4), ("fc5", tm.fc5)):
        params[name] = {
            "kernel": jnp.asarray(_t2n(get(lin.weight)).T),
            "bias": jnp.asarray(_t2n(get(lin.bias))),
        }
    for i, (g, b) in enumerate(
        [(tm.g1, tm.b1), (tm.g2, tm.b2), (tm.g3, tm.b3), (tm.g4, tm.b4), (tm.g5, tm.b5)],
        start=1,
    ):
        params[f"dn{i}"] = {
            "gamma": jnp.asarray(_t2n(get(g)).reshape(-1)),
            "beta": jnp.asarray(_t2n(get(b)).reshape(-1)),
        }
    return params


def _flax_variables_from_torch(tm, variables):
    """Tie the flax model to the torch twin's weights (layouts converted)."""
    params = _lenet_tree_from_torch(tm, lambda p: p)
    return {"params": params, "batch_stats": variables["batch_stats"]}


# Function-scoped on purpose: a train-mode torch forward mutates running
# buffers even under no_grad, so sharing one twin across tests would
# desynchronize the stat comparison.
@pytest.fixture()
def tied_models():
    torch.manual_seed(0)
    tm = _TorchLeNetDWT(group_size=4).eval()
    # Perturb affines so the shared gamma/beta path is actually exercised.
    with torch.no_grad():
        for g, b in [(tm.g1, tm.b1), (tm.g2, tm.b2), (tm.g3, tm.b3),
                     (tm.g4, tm.b4), (tm.g5, tm.b5)]:
            g.add_(0.1 * torch.randn_like(g))
            b.add_(0.1 * torch.randn_like(b))
    fm = LeNetDWT(group_size=4)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(2, 6, 28, 28, 1)).astype(np.float32)
    variables = fm.init(jax.random.key(0), jnp.asarray(x), train=True)
    variables = _flax_variables_from_torch(tm, variables)
    return tm, fm, variables, x


def _torch_input(x):
    # [2, N, 28, 28, 1] NHWC domains -> concat halves NCHW.
    flat = x.reshape(-1, 28, 28, 1).transpose(0, 3, 1, 2)
    return torch.from_numpy(np.ascontiguousarray(flat))


def test_train_forward_matches_torch(tied_models):
    tm, fm, variables, x = tied_models
    tm.train()
    with torch.no_grad():
        out_t = tm(_torch_input(x))
    out_f, _ = fm.apply(
        variables, jnp.asarray(x), train=True, mutable=["batch_stats"]
    )
    np.testing.assert_allclose(
        np.asarray(out_f).reshape(-1, 10), _t2n(out_t), rtol=1e-3, atol=2e-4
    )


def test_stat_updates_and_eval_match_torch(tied_models):
    tm, fm, variables, x = tied_models
    # Two train passes advance every branch's EMA on both sides...
    tm.train()
    with torch.no_grad():
        tm(_torch_input(x))
        tm(_torch_input(x))
    vars_now = variables
    for _ in range(2):
        _, upd = fm.apply(
            vars_now, jnp.asarray(x), train=True, mutable=["batch_stats"]
        )
        vars_now = {"params": vars_now["params"], **upd}

    stats = vars_now["batch_stats"]
    for i, wmod in ((1, tm.w1), (2, tm.w2)):
        for d in range(2):
            np.testing.assert_allclose(
                np.asarray(stats[f"dn{i}"]["whitening"].mean[d]),
                _t2n(wmod[d].running_mean).reshape(-1),
                rtol=1e-4, atol=1e-5,
            )
            np.testing.assert_allclose(
                np.asarray(stats[f"dn{i}"]["whitening"].cov[d]),
                _t2n(wmod[d].running_cov),
                rtol=1e-4, atol=1e-5,
            )
    for i, nmod in ((3, tm.n3), (4, tm.n4), (5, tm.n5)):
        for d in range(2):
            np.testing.assert_allclose(
                np.asarray(stats[f"dn{i}"]["bn"].mean[d]),
                _t2n(nmod[d].running_mean),
                rtol=1e-4, atol=1e-5,
            )
            np.testing.assert_allclose(
                np.asarray(stats[f"dn{i}"]["bn"].var[d]),
                _t2n(nmod[d].running_var),
                rtol=1e-4, atol=1e-5,
            )

    # ...then eval (target-branch routing + running stats) must agree too.
    tm.eval()
    xe = x[1]  # a target-domain batch, [N, 28, 28, 1]
    with torch.no_grad():
        out_t = tm(torch.from_numpy(
            np.ascontiguousarray(xe.transpose(0, 3, 1, 2))
        ))
    out_f = fm.apply(vars_now, jnp.asarray(xe), train=False)
    np.testing.assert_allclose(
        np.asarray(out_f), _t2n(out_t), rtol=1e-3, atol=2e-4
    )


# ------------------------------------------------ ResNet Bottleneck parity
# Torch twin of the reference's triple-branch Bottleneck
# (resnet50_dwt_mec_officehome.py:66-262): thirds split at every norm site,
# shared affine after the branch concat, whitening branches for layer-1
# style blocks, BN branches otherwise, downsample norm site on block 0.


def _thirds_branch(module, mods, x):
    """Reference thirds routing: per-domain branch in train, target branch
    (index 1) in eval (``resnet50…py:220,241``)."""
    if module.training:
        thirds = torch.split(x, x.shape[0] // 3, dim=0)
        return torch.cat([mods[d](t) for d, t in enumerate(thirds)], dim=0)
    return mods[1](x)


class _TorchBottleneck(nn.Module):
    def __init__(self, cin, planes, stride=1, whiten=True, downsample=False,
                 group_size=4):
        super().__init__()
        out_ch = planes * 4

        def norms(c):
            if whiten:
                return nn.ModuleList(
                    [_TorchWhiten(c, group_size) for _ in range(3)]
                )
            return nn.ModuleList(
                [nn.BatchNorm2d(c, affine=False) for _ in range(3)]
            )

        self.conv1 = nn.Conv2d(cin, planes, 1, bias=False)
        self.n1, self.g1 = norms(planes), nn.Parameter(torch.randn(1, planes, 1, 1) * 0.1 + 1)
        self.b1 = nn.Parameter(torch.randn(1, planes, 1, 1) * 0.1)
        self.conv2 = nn.Conv2d(planes, planes, 3, stride=stride, padding=1,
                               bias=False)
        self.n2, self.g2 = norms(planes), nn.Parameter(torch.randn(1, planes, 1, 1) * 0.1 + 1)
        self.b2 = nn.Parameter(torch.randn(1, planes, 1, 1) * 0.1)
        self.conv3 = nn.Conv2d(planes, out_ch, 1, bias=False)
        self.n3, self.g3 = norms(out_ch), nn.Parameter(torch.randn(1, out_ch, 1, 1) * 0.1 + 1)
        self.b3 = nn.Parameter(torch.randn(1, out_ch, 1, 1) * 0.1)
        self.has_ds = downsample
        if downsample:
            self.ds_conv = nn.Conv2d(cin, out_ch, 1, stride=stride, bias=False)
            self.nd = norms(out_ch)
            self.gd = nn.Parameter(torch.randn(1, out_ch, 1, 1) * 0.1 + 1)
            self.bd = nn.Parameter(torch.randn(1, out_ch, 1, 1) * 0.1)

    def _branch(self, mods, x):
        return _thirds_branch(self, mods, x)

    def forward(self, x):
        identity = x
        out = F.relu(self._branch(self.n1, self.conv1(x)) * self.g1 + self.b1)
        out = F.relu(self._branch(self.n2, self.conv2(out)) * self.g2 + self.b2)
        out = self._branch(self.n3, self.conv3(out)) * self.g3 + self.b3
        if self.has_ds:
            identity = (
                self._branch(self.nd, self.ds_conv(x)) * self.gd + self.bd
            )
        return F.relu(out + identity)


def _tie_bottleneck(tm, variables):
    params = dict(variables["params"])

    def conv(w):
        return jnp.asarray(_t2n(w).transpose(2, 3, 1, 0))

    params["conv1"] = {"kernel": conv(tm.conv1.weight)}
    params["conv2"] = {"kernel": conv(tm.conv2.weight)}
    params["conv3"] = {"kernel": conv(tm.conv3.weight)}
    sites = [("dn1", tm.g1, tm.b1), ("dn2", tm.g2, tm.b2), ("dn3", tm.g3, tm.b3)]
    if tm.has_ds:
        params["downsample_conv"] = {"kernel": conv(tm.ds_conv.weight)}
        sites.append(("downsample_dn", tm.gd, tm.bd))
    for name, g, b in sites:
        params[name] = {
            "gamma": jnp.asarray(_t2n(g).reshape(-1)),
            "beta": jnp.asarray(_t2n(b).reshape(-1)),
        }
    return {"params": params, "batch_stats": variables["batch_stats"]}


@pytest.mark.parametrize("whiten,stride", [(True, 2), (False, 1)])
def test_bottleneck_matches_torch(whiten, stride):
    from dwt_tpu.nn.resnet import BottleneckDWT

    torch.manual_seed(1)
    cin, planes, n, hw = 16, 8, 3, 8
    tm = _TorchBottleneck(cin, planes, stride=stride, whiten=whiten,
                          downsample=True, group_size=4)
    fm = BottleneckDWT(planes=planes, stride=stride, use_whitening=whiten,
                       has_downsample=True, group_size=4)

    rng = np.random.default_rng(3)
    x = rng.normal(size=(3, n, hw, hw, cin)).astype(np.float32)
    variables = fm.init(jax.random.key(0), jnp.asarray(x), train=True)
    variables = _tie_bottleneck(tm, variables)

    def torch_in(a):
        flat = a.reshape(-1, hw, hw, cin).transpose(0, 3, 1, 2)
        return torch.from_numpy(np.ascontiguousarray(flat))

    # Train forward parity + stat advance.
    tm.train()
    with torch.no_grad():
        out_t = tm(torch_in(x))
    out_f, upd = fm.apply(
        variables, jnp.asarray(x), train=True, mutable=["batch_stats"]
    )
    got = np.asarray(out_f)          # [3, n, h', w', C]
    want = _t2n(out_t)               # [3n, C, h', w']
    want = want.reshape(3, n, *want.shape[1:]).transpose(0, 1, 3, 4, 2)
    np.testing.assert_allclose(got, want, rtol=1e-3, atol=2e-4)

    # Eval forward parity on the advanced running stats (target branch).
    tm.eval()
    vars_now = {"params": variables["params"], **upd}
    xe = x[1]
    with torch.no_grad():
        out_t = tm(torch.from_numpy(
            np.ascontiguousarray(xe.transpose(0, 3, 1, 2))
        ))
    out_f = fm.apply(vars_now, jnp.asarray(xe), train=False)
    want = _t2n(out_t).transpose(0, 2, 3, 1)
    np.testing.assert_allclose(np.asarray(out_f), want, rtol=1e-3, atol=2e-4)


# ----------------------------------------------------------- loss parity
# Torch twins of the reference losses, from their formulas:
# MEC (consensus_loss.py:11-24): per-sample min_k 1/2(-log p_x(k) - log
# p_y(k)), batch-meaned; Entropy (usps_mnist.py:188-194): mean Shannon
# entropy of the softmax.


def test_mec_loss_matches_torch():
    from dwt_tpu.ops import mec_loss

    rng = np.random.default_rng(7)
    a = rng.normal(size=(9, 13)).astype(np.float32)
    b = rng.normal(size=(9, 13)).astype(np.float32)

    ta, tb = torch.from_numpy(a), torch.from_numpy(b)
    la, lb = F.log_softmax(ta, dim=1), F.log_softmax(tb, dim=1)
    want = torch.mean(torch.min(-0.5 * (la + lb), dim=1).values).item()

    got = float(mec_loss(jnp.asarray(a), jnp.asarray(b)))
    np.testing.assert_allclose(got, want, rtol=1e-6)


def test_entropy_loss_matches_torch():
    from dwt_tpu.ops import entropy_loss

    rng = np.random.default_rng(8)
    a = rng.normal(size=(11, 10)).astype(np.float32)
    ta = torch.from_numpy(a)
    p = F.softmax(ta, dim=1)
    want = torch.mean(torch.sum(-p * torch.log(p), dim=1)).item()
    got = float(entropy_loss(jnp.asarray(a)))
    np.testing.assert_allclose(got, want, rtol=1e-5)


def test_cls_loss_matches_torch_nll_log_softmax():
    # The reference's classification loss composite (usps_mnist.py:298,
    # resnet50…py:425): F.nll_loss(F.log_softmax(logits), y), mean-reduced.
    from dwt_tpu.ops import softmax_cross_entropy

    rng = np.random.default_rng(9)
    logits = rng.normal(size=(14, 65)).astype(np.float32)
    y = rng.integers(0, 65, size=(14,))
    want = F.nll_loss(
        F.log_softmax(torch.from_numpy(logits), dim=1),
        torch.from_numpy(y),
    ).item()
    got = float(softmax_cross_entropy(jnp.asarray(logits), jnp.asarray(y)))
    np.testing.assert_allclose(got, want, rtol=1e-6)


# ------------------------------------------- full tiny-ResNet-DWT parity
# Stem (7x7/2 conv + triple whitening + 3x3/2 maxpool), one bottleneck per
# stage (stage 1 whitening, stages 2-4 BN, downsample at each stage head),
# global average pool, fc — the complete ResNetDWT composition against a
# torch twin (reference structure: resnet50…py:264-362).


class _TorchResNetDWT(nn.Module):
    def __init__(self, num_classes=7, group_size=4):
        super().__init__()
        self.conv1 = nn.Conv2d(3, 64, 7, stride=2, padding=3, bias=False)
        self.w1 = nn.ModuleList([_TorchWhiten(64, group_size) for _ in range(3)])
        self.g1 = nn.Parameter(torch.randn(1, 64, 1, 1) * 0.1 + 1)
        self.b1 = nn.Parameter(torch.randn(1, 64, 1, 1) * 0.1)
        specs = [  # (cin, planes, stride, whiten)
            (64, 64, 1, True),
            (256, 128, 2, False),
            (512, 256, 2, False),
            (1024, 512, 2, False),
        ]
        self.blocks = nn.ModuleList(
            [
                _TorchBottleneck(cin, planes, stride=stride, whiten=wh,
                                 downsample=True, group_size=group_size)
                for cin, planes, stride, wh in specs
            ]
        )
        self.fc = nn.Linear(2048, num_classes)

    def _branch(self, mods, x):
        return _thirds_branch(self, mods, x)

    def forward(self, x):
        x = self.conv1(x)
        x = F.relu(self._branch(self.w1, x) * self.g1 + self.b1)
        x = F.max_pool2d(x, 3, stride=2, padding=1)
        for block in self.blocks:
            x = block(x)
        x = x.mean(dim=(2, 3))
        return self.fc(x)


def test_full_tiny_resnet_matches_torch():
    tm, fm, variables = _tied_tiny_resnet()
    n, hw = 2, 32
    rng = np.random.default_rng(4)
    x = rng.normal(size=(3, n, hw, hw, 3)).astype(np.float32)

    tm.train()
    with torch.no_grad():
        out_t = tm(torch.from_numpy(np.ascontiguousarray(
            x.reshape(-1, hw, hw, 3).transpose(0, 3, 1, 2)
        )))
    out_f, upd = fm.apply(
        variables, jnp.asarray(x), train=True, mutable=["batch_stats"]
    )
    np.testing.assert_allclose(
        np.asarray(out_f).reshape(-1, 7), _t2n(out_t), rtol=1e-3, atol=5e-4
    )

    # Eval on the advanced stats through the target branches.
    tm.eval()
    vars_now = {"params": variables["params"], **upd}
    xe = x[1]
    with torch.no_grad():
        out_t = tm(torch.from_numpy(
            np.ascontiguousarray(xe.transpose(0, 3, 1, 2))
        ))
    out_f = fm.apply(vars_now, jnp.asarray(xe), train=False)
    np.testing.assert_allclose(
        np.asarray(out_f), _t2n(out_t), rtol=1e-3, atol=5e-4
    )


# ---------------------------------------- k-step trajectory parity
# The strongest paper-parity evidence obtainable with zero datasets: run
# the ACTUAL training recipes (optimizer included) in lockstep against the
# torch twin for several steps and require the per-step losses, the final
# parameters, and the final running stats to agree.  Per-op parity can't
# pin optimizer semantics (bias correction, L2-before-moments ordering,
# momentum init, pre-step MultiStepLR) — this does.
#
# Both sides run in FLOAT64: in f32 the trajectories are chaotic — ulp-level
# gradient differences through the Cholesky chain get amplified by Adam's
# sign normalization into lr-sized parameter moves within a handful of steps
# (the same mechanism documented at ``train/steps.py:168-174``), so a tight
# f32 lockstep comparison is impossible *in principle*.  In f64 the fp noise
# sits ~9 orders below the updates and any observable divergence is a real
# semantic mismatch (wrong decay ordering, missing bias correction, wrong lr
# routing, EMA convention drift).


def test_kstep_digits_trajectory_matches_torch_adam():
    """k lockstep Adam steps of the digits recipe (``usps_mnist.py:281-308``,
    Adam(lr=1e-3, weight_decay=5e-4) at ``:389``): per-step losses, final
    params, and final whitening running stats must track the torch twin to
    f64 tolerance."""
    from dwt_tpu.train import adam_l2, make_digits_train_step
    from dwt_tpu.train.state import TrainState

    k, n, lr, wd = 6, 6, 1e-3, 5e-4

    torch.manual_seed(0)
    tm = _TorchLeNetDWT(group_size=4).double()
    with torch.no_grad():
        for g, b in [(tm.g1, tm.b1), (tm.g2, tm.b2), (tm.g3, tm.b3),
                     (tm.g4, tm.b4), (tm.g5, tm.b5)]:
            g.add_(0.1 * torch.randn_like(g))
            b.add_(0.1 * torch.randn_like(b))
    fm = LeNetDWT(group_size=4, dtype=jnp.float64)

    rng = np.random.default_rng(21)
    batches = []
    for _ in range(k):
        x = rng.normal(size=(2, n, 28, 28, 1))  # float64
        y = rng.integers(0, 10, size=(n,))
        batches.append((x, y))

    with enable_x64():
        # Tie the flax model to the twin's PRE-training weights (f64 under
        # x64), then let both sides free-run.
        variables = fm.init(
            jax.random.key(0), jnp.asarray(batches[0][0]), train=True
        )
        variables = _flax_variables_from_torch(tm, variables)

        # torch side: the reference loop body verbatim, in double.
        tm.train()
        opt = torch.optim.Adam(tm.parameters(), lr=lr, weight_decay=wd)
        want_losses = []
        for x, y in batches:
            opt.zero_grad()
            out = tm(_torch_input(x))
            src, tgt = out[:n], out[n:]
            cls = F.nll_loss(F.log_softmax(src, dim=1), torch.from_numpy(y))
            p = F.softmax(tgt, dim=1)
            ent = torch.mean(torch.sum(-p * torch.log(p), dim=1))
            loss = cls + 0.1 * ent
            loss.backward()
            opt.step()
            want_losses.append(loss.item())

        # jax side: the actual step factory + optimizer constructor.
        tx = adam_l2(lr, weight_decay=wd)
        state = TrainState(
            step=jnp.zeros((), jnp.int32),
            params=variables["params"],
            batch_stats=variables["batch_stats"],
            opt_state=tx.init(variables["params"]),
        )
        step = jax.jit(make_digits_train_step(fm, tx, lambda_entropy=0.1))
        got_losses = []
        for x, y in batches:
            batch = {
                "source_x": jnp.asarray(x[0]),
                "target_x": jnp.asarray(x[1]),
                "source_y": jnp.asarray(y),
            }
            state, metrics = step(state, batch)
            got_losses.append(float(metrics["loss"]))

        np.testing.assert_allclose(
            got_losses, want_losses, rtol=1e-8, atol=1e-10
        )

        # Final parameters: k optimizer updates deep, both frameworks must
        # land on the same weights (pins bias correction + L2 ordering).
        # Tolerance is looser than the per-step losses: f64 gradient noise
        # through the Cholesky chain accumulates across k free-running
        # Adam updates (measured ~2e-8 abs / 6e-6 rel at k=6) — a real
        # semantic mismatch (wrong decay ordering, missing bias
        # correction) moves params by O(lr)=1e-3, five orders above this
        # band, and the per-step loss check at rtol=1e-8 above already
        # pins the update sequence.
        want_params = _lenet_tree_from_torch(tm, lambda p: p)

        def compare(path, w, g):
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), rtol=1e-4, atol=1e-7,
                err_msg=jax.tree_util.keystr(path),
            )

        jax.tree_util.tree_map_with_path(compare, want_params, state.params)

        # Final running stats: k EMA advances driven by the evolving params
        # (same free-running accumulation band as the params above).
        stats = state.batch_stats
        for i, wmod in ((1, tm.w1), (2, tm.w2)):
            for d in range(2):
                np.testing.assert_allclose(
                    np.asarray(stats[f"dn{i}"]["whitening"].mean[d]),
                    _t2n(wmod[d].running_mean).reshape(-1),
                    rtol=1e-5, atol=1e-8,
                )
                np.testing.assert_allclose(
                    np.asarray(stats[f"dn{i}"]["whitening"].cov[d]),
                    _t2n(wmod[d].running_cov),
                    rtol=1e-5, atol=1e-8,
                )


def _tied_tiny_resnet(seed=2, double=False):
    """Weight-tied (torch twin, flax model, variables) triple.  With
    ``double=True`` the twin is f64 and the caller must be inside
    ``jax.experimental.enable_x64()`` so the tied arrays stay f64."""
    from dwt_tpu.nn import ResNetDWT

    torch.manual_seed(seed)
    tm = _TorchResNetDWT(num_classes=7, group_size=4)
    if double:
        tm = tm.double()
    fm = ResNetDWT(
        stage_sizes=(1, 1, 1, 1), num_classes=7, group_size=4,
        dtype=jnp.float64 if double else jnp.float32,
    )

    n, hw = 2, 32
    rng = np.random.default_rng(4)
    x = rng.normal(size=(3, n, hw, hw, 3)).astype(np.float32)
    variables = fm.init(jax.random.key(0), jnp.asarray(x), train=True)

    params = dict(variables["params"])
    params["conv1"] = {
        "kernel": jnp.asarray(_t2n(tm.conv1.weight).transpose(2, 3, 1, 0))
    }
    params["dn1"] = {
        "gamma": jnp.asarray(_t2n(tm.g1).reshape(-1)),
        "beta": jnp.asarray(_t2n(tm.b1).reshape(-1)),
    }
    for stage, tblock in enumerate(tm.blocks, start=1):
        name = f"layer{stage}_0"
        sub = _tie_bottleneck(
            tblock, {"params": params[name], "batch_stats": {}}
        )
        params[name] = sub["params"]
    params["fc_out"] = {
        "kernel": jnp.asarray(_t2n(tm.fc.weight).T),
        "bias": jnp.asarray(_t2n(tm.fc.bias)),
    }
    return tm, fm, {"params": params, "batch_stats": variables["batch_stats"]}


@pytest.mark.slow  # ~85 s — the digits k-step trajectory (fast set)
# pins the same re-tied per-step parity machinery; tier-1 budget
# (tools/t1_budget.py) forced the heavier OfficeHome twin out.
def test_kstep_officehome_trajectory_matches_torch_sgd():
    """k re-tied single steps of the OfficeHome recipe on the tied tiny
    ResNet: two-group SGD (head lr, backbone lr×0.1, momentum 0.9, L2 5e-4
    — ``resnet50_dwt_mec_officehome.py:578-590``) under a pre-step
    MultiStepLR decay that FIRES mid-trajectory, loss = cls + 0.1·MEC
    (``:425``).  Pins momentum-buffer init, two-group routing, and the
    scheduler's effective lr sequence step by step.

    A free-running lockstep comparison is impossible even in f64: ulp-level
    gradient differences through the per-site Cholesky chain compound
    geometrically through momentum (measured ~8% loss drift by step 4), so
    before each step the flax params are RE-TIED to the torch twin's
    current weights and exactly one optimizer step runs on both sides.
    The jax momentum buffers and schedule counter still free-run across
    all k steps inside the optax state — they stay ulp-close because every
    gradient is evaluated at identical weights — so each step's post-update
    params comparison still exercises the k-deep optimizer trajectory
    (buffer accumulation, the step-3 decay) without chaotic divergence."""
    import warnings

    from dwt_tpu.train import (
        make_officehome_train_step,
        multistep_schedule,
        sgd_two_group,
    )
    from dwt_tpu.train.state import TrainState

    k, n, hw, lr, wd, mom = 5, 2, 32, 1e-2, 5e-4, 0.9

    rng = np.random.default_rng(31)
    batches = []
    for _ in range(k):
        x = rng.normal(size=(3, n, hw, hw, 3))  # float64
        y = rng.integers(0, 7, size=(n,))
        batches.append((x, y))

    with enable_x64():
        tm, fm, variables = _tied_tiny_resnet(double=True)

        def resnet_tree_from_torch():
            """The twin's CURRENT weights in the flax param-tree layout
            (same transposes as the init-time tie — a re-tie or an
            expected-value snapshot are the same mapping)."""
            p = {}
            p["conv1"] = {
                "kernel": jnp.asarray(
                    _t2n(tm.conv1.weight).transpose(2, 3, 1, 0)
                )
            }
            p["dn1"] = {
                "gamma": jnp.asarray(_t2n(tm.g1).reshape(-1)),
                "beta": jnp.asarray(_t2n(tm.b1).reshape(-1)),
            }
            for stage, tblock in enumerate(tm.blocks, start=1):
                sub = _tie_bottleneck(
                    tblock, {"params": {}, "batch_stats": {}}
                )
                p[f"layer{stage}_0"] = sub["params"]
            p["fc_out"] = {
                "kernel": jnp.asarray(_t2n(tm.fc.weight).T),
                "bias": jnp.asarray(_t2n(tm.fc.bias)),
            }
            return p

        # torch side: two param groups, pre-step scheduler (the reference's
        # PyTorch-1.0 ordering — scheduler.step() before each iteration).
        tm.train()
        head = list(tm.fc.parameters())
        head_ids = {id(p) for p in head}
        backbone = [p for p in tm.parameters() if id(p) not in head_ids]
        opt = torch.optim.SGD(
            [{"params": head, "lr": lr},
             {"params": backbone, "lr": lr * 0.1}],
            momentum=mom, weight_decay=wd,
        )
        sched = torch.optim.lr_scheduler.MultiStepLR(
            opt, milestones=[3], gamma=0.1
        )

        # jax side: the loop's own schedule + optimizer constructors.
        head_sched = multistep_schedule(lr, [3], 0.1, pre_step=True)
        backbone_sched = multistep_schedule(lr * 0.1, [3], 0.1, pre_step=True)
        tx = sgd_two_group(head_sched, backbone_sched, momentum=mom,
                           weight_decay=wd)
        state = TrainState(
            step=jnp.zeros((), jnp.int32),
            params=variables["params"],
            batch_stats=variables["batch_stats"],
            opt_state=tx.init(variables["params"]),
        )
        step = jax.jit(make_officehome_train_step(fm, tx, lambda_mec=0.1))

        def compare(path, w, g):
            # Tolerance sized to single-gradient f64 noise through the
            # whitening/Cholesky backward: even at identical weights the
            # two frameworks' conv1 gradients differ by ~2e-5 (measured
            # post-update diff ~5e-8 at lr 1e-3, shrinking 10x with the
            # step-3 decay and NOT compounding across steps — noise, not
            # drift).  A semantic miss (wrong group lr, decay not firing)
            # moves params by the full update, ~1e-5, 50x above this band.
            np.testing.assert_allclose(
                np.asarray(g), np.asarray(w), rtol=1e-4, atol=2e-7,
                err_msg=jax.tree_util.keystr(path),
            )

        for i, (x, y) in enumerate(batches):
            # Re-tie: step i starts from the twin's exact current weights.
            state = state.replace(params=resnet_tree_from_torch())

            with warnings.catch_warnings():
                warnings.simplefilter("ignore")  # pre-step order deliberate
                sched.step()
            opt.zero_grad()
            out = tm(torch.from_numpy(np.ascontiguousarray(
                x.reshape(-1, hw, hw, 3).transpose(0, 3, 1, 2)
            )))
            src, tgt, tga = out[:n], out[n:2 * n], out[2 * n:]
            cls = F.nll_loss(F.log_softmax(src, dim=1), torch.from_numpy(y))
            la = F.log_softmax(tgt, dim=1)
            lb = F.log_softmax(tga, dim=1)
            mec = torch.mean(torch.min(-0.5 * (la + lb), dim=1).values)
            loss = cls + 0.1 * mec
            loss.backward()
            opt.step()

            batch = {
                "source_x": jnp.asarray(x[0]),
                "target_x": jnp.asarray(x[1]),
                "target_aug_x": jnp.asarray(x[2]),
                "source_y": jnp.asarray(y),
            }
            state, metrics = step(state, batch)

            # Loss at the tied pre-step weights: pure forward parity.
            np.testing.assert_allclose(
                float(metrics["loss"]), loss.item(), rtol=1e-8, atol=1e-10,
                err_msg=f"step {i} loss",
            )
            # Post-step params: one update from identical weights — pins
            # this step's effective lr (the pre-step decay fires at i=2,
            # when the scheduler counter reaches milestone 3), group
            # routing, L2 placement, and the i-deep momentum buffers.
            jax.tree_util.tree_map_with_path(
                compare, resnet_tree_from_torch(), state.params
            )


def test_gradients_match_torch(tied_models):
    """Backward parity through the whole model: the digits training loss
    (cls + 0.1*entropy, ``usps_mnist.py:298-299``) must produce the same
    parameter gradients in both frameworks — including through the
    whitening Cholesky/inverse (their VJPs differ in implementation but
    must agree in value)."""
    from dwt_tpu.ops import entropy_loss, softmax_cross_entropy

    tm, fm, variables, x = tied_models
    n = x.shape[1]
    y = np.random.default_rng(11).integers(0, 10, size=(n,))

    # torch side: train-mode forward, composite loss, backward.
    tm.train()
    out = tm(_torch_input(x))
    src, tgt = out[:n], out[n:]
    cls = F.nll_loss(F.log_softmax(src, dim=1), torch.from_numpy(y))
    p = F.softmax(tgt, dim=1)
    ent = torch.mean(torch.sum(-p * torch.log(p), dim=1))
    (cls + 0.1 * ent).backward()
    want = _lenet_tree_from_torch(tm, lambda t: t.grad)

    # flax side: identical loss on the tied params.
    def loss_fn(params):
        logits, _ = fm.apply(
            {"params": params, "batch_stats": variables["batch_stats"]},
            jnp.asarray(x),
            train=True,
            mutable=["batch_stats"],
        )
        return softmax_cross_entropy(
            logits[0], jnp.asarray(y)
        ) + 0.1 * entropy_loss(logits[1])

    got = jax.grad(loss_fn)(variables["params"])

    # Structure-aware comparison: tree_map_with_path asserts identical key
    # structure up front, so a renamed key fails loudly instead of
    # mispairing leaves.
    def compare(path, w, g):
        np.testing.assert_allclose(
            np.asarray(g),
            np.asarray(w),
            rtol=2e-3,
            atol=1e-5,
            err_msg=jax.tree_util.keystr(path),
        )

    jax.tree_util.tree_map_with_path(compare, want, got)
