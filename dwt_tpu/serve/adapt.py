"""Guarded online domain adaptation at the serve edge (ISSUE-18).

The paper's whole mechanism is domain-specific whitening statistics, and
its post-training protocol (``EvalPipeline.collect_stats`` — train-mode
forwards over the *target* set purely to advance the running stats)
needs no gradients at all.  That makes adaptation a pure serving
operation: harvest target-domain moments from live traffic, fold them
into the frozen stats, refactorize the whiten cache, and you have a new
deployment generation — *a new target domain with zero training runs*.

Live traffic is untrusted, so every step of that loop is guarded:

* **sanitization** — rows with non-finite values or out-of-band
  magnitudes (``max_abs``) never enter the accumulator; a poisoned
  payload can 500 its own request but cannot poison the stats;
* **padded rows never count** — the accumulator consumes only the
  ``real_n`` real rows of each dispatched bucket (the batcher's
  pad-and-mask convention): repeated-last-row padding would bias the
  moments toward whatever request happened to land last in a bucket;
* **min-sample gate + clamped momentum** — a thin window folds nothing,
  and the EMA momentum is clamped (``max_momentum``) so even a skewed
  window cannot move the stats far in one generation;
* **the same deploy pipeline as a checkpoint** — every adapted
  generation is an immutable :class:`~dwt_tpu.serve.engine.EngineState`
  built through the engine's stats-only rebuild and submitted to the
  shared :class:`~dwt_tpu.fleet.reload.DeployController`: canary
  fixture eval → atomic swap → post-swap monitor → rollback;
* **rollback ⇒ freeze with exponential re-arm** — a rolled-back adapted
  generation freezes adaptation for ``freeze_base_s × 2^(k-1)`` (the
  blacklist analogue for generations that have no artifact to
  blacklist); the counter resets once an adapted generation survives
  its post-swap watch;
* **freeze-on-firing-alert + kill switch** — with ``--alert_rules``
  armed, any firing alert pauses folding (adapt into a healthy serving
  plane only); ``--no-adapt`` disables the subsystem entirely, and the
  default (``--adapt_every 0``) builds none of this — the serving path
  stays bitwise-identical to a non-adaptive server.

Observability: the ``dwt_serve_domain_shift`` gauge (relative distance
between the live stats and the traffic window — a drift alarm feed for
``--alert_rules``), the ``dwt_serve_adapt_generations_total{verdict}``
counter, ``adapt_build``/``adapt_canary``/``adapt_swap``/
``adapt_rollback`` JSONL lifecycle events on the access-log stream, and
adaptation fields on ``/stats``.
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Callable, Optional

import numpy as np

from dwt_tpu import obs
from dwt_tpu.serve.engine import EngineState, ServeEngine, Version
from dwt_tpu.utils.checkpoint import params_digest

log = logging.getLogger(__name__)


def make_collect_fn(engine: ServeEngine):
    """The compiled moment-collection forward for one serving engine:
    ``(params_arg, batch_stats, x) -> advanced batch_stats``.

    This is the evalpipe's stat-collection plumbing
    (``train.steps.make_stat_collection_step`` — the reference's
    post-training protocol: train-mode forward, gradient-free, the batch
    tiled into every domain slot so only ``batch_stats`` advances)
    rebound to the ENGINE's calling convention: ``params_arg`` is
    exactly what the bucket executables take (the raw tree, or the int8
    ``{"q", "scale"}`` bundle, which is dequantized in-graph the same
    way the serving forward does it).

    Output stat leaves are cast back to the input tree's dtypes: the
    folded tree must graft bitwise-compatibly onto the live state
    whatever the model's compute dtype (bf16 serving) did to the
    intermediate moments.
    """
    import jax
    import jax.numpy as jnp

    model = engine.model
    num_domains = getattr(model, "num_domains", 2)
    quantized = engine.quantize

    def collect(params_arg, batch_stats, x):
        params = params_arg
        if quantized:
            from dwt_tpu.serve.quant import dequantize_int8

            params = dequantize_int8(
                params_arg["q"], params_arg["scale"], dtype=jnp.float32
            )
        tiled = jnp.broadcast_to(x[None], (num_domains,) + x.shape)
        _, updated = model.apply(
            {"params": params, "batch_stats": batch_stats},
            tiled, train=True, mutable=["batch_stats"],
        )
        return jax.tree.map(
            lambda n, o: n.astype(o.dtype),
            updated["batch_stats"], batch_stats,
        )

    return jax.jit(collect)


def stats_drift(live, window) -> float:
    """Relative distance between two stats trees: ``‖w − l‖ / ‖l‖``
    (Frobenius over every leaf).  Scale-free — a gauge value an operator
    can write one alert threshold against regardless of model size —
    and zero exactly when the traffic window agrees with the frozen
    stats."""
    import jax

    num = 0.0
    den = 0.0
    for l, w in zip(jax.tree.leaves(live), jax.tree.leaves(window)):
        l = np.asarray(l, np.float64)
        w = np.asarray(w, np.float64)
        num += float(np.sum((w - l) ** 2))
        den += float(np.sum(l ** 2))
    return float(np.sqrt(num) / (np.sqrt(den) + 1e-12))


def sanitize_rows(x: np.ndarray, max_abs: float) -> np.ndarray:
    """Boolean keep-mask over rows: finite everywhere and within the
    amplitude band.  A poisoned request row (NaN/Inf payload, or a
    magnitude no real sample reaches) must never advance the stats."""
    flat = np.asarray(x).reshape(x.shape[0], -1)
    finite = np.isfinite(flat).all(axis=1)
    # Non-finite rows would make the band check itself warn; evaluate it
    # only where finite.
    in_band = np.zeros_like(finite)
    if finite.any():
        in_band[finite] = (
            np.abs(flat[finite]).max(axis=1) <= float(max_abs)
        )
    return finite & in_band


class DomainAdapter:
    """Serve-side target-domain stat accumulator behind the deploy gate.

    **Harvest** (dispatcher side, O(slice+append)): the dispatcher calls
    :meth:`offer` once per dispatched bucket with the batch tensor and
    its real-row count; only the real rows enter the bounded sample
    queue.  Nothing else runs on the serving hot path.

    **Accumulate** (adapter thread): :meth:`step` drains the queue,
    sanitizes rows, and advances a *window* stats tree — seeded from the
    live generation's stats — through the compiled collect forward, one
    fixed-size batch at a time (AOT-friendly: one shape, compiled once).

    **Fold + deploy** (adapter thread, on the ``adapt_every_s``
    cadence): with enough samples and nothing frozen, the window folds
    into the live stats under the clamped momentum, builds a candidate
    generation through ``ServeEngine.build_state_from_stats`` (same
    params/scales, new stats + refactorized cache), and submits it to
    the shared :class:`~dwt_tpu.fleet.reload.DeployController` — the
    exact path a hot-reloaded checkpoint takes.

    ``step()`` is the unit-testable single iteration (no thread);
    ``start()``/``stop()`` wrap it in a daemon, like ``HotReloader``.
    ``clock`` is injectable (fake-clock tests, the repo convention).
    """

    def __init__(
        self,
        engine: ServeEngine,
        controller,
        *,
        access_log=None,
        adapt_every_s: float = 30.0,
        min_samples: int = 64,
        momentum: float = 0.25,
        max_momentum: float = 0.5,
        collect_batch: int = 32,
        max_abs: float = 1e3,
        freeze_base_s: float = 30.0,
        max_freeze_doublings: int = 6,
        max_window_samples: int = 8192,
        alert_engine=None,
        poll_s: float = 0.25,
        clock: Callable[[], float] = time.monotonic,
    ):
        if adapt_every_s <= 0:
            raise ValueError("adapt_every_s must be > 0 (0 disables "
                             "adaptation at the flag layer, not here)")
        self.engine = engine
        self.controller = controller
        self.access_log = access_log
        self.adapt_every_s = float(adapt_every_s)
        self.min_samples = int(min_samples)
        self.momentum = float(momentum)
        self.max_momentum = float(max_momentum)
        self.collect_batch = int(collect_batch)
        self.max_abs = float(max_abs)
        self.freeze_base_s = float(freeze_base_s)
        self.max_freeze_doublings = int(max_freeze_doublings)
        self.max_window_samples = int(max_window_samples)
        self.alert_engine = alert_engine
        self.poll_s = float(poll_s)
        self._clock = clock
        self._collect = make_collect_fn(engine)

        # Dispatcher → adapter handoff: a bounded deque of real-row
        # arrays.  Oldest batches drop first — the window should track
        # RECENT traffic, and a stalled adapter must not grow host
        # memory without bound.
        self._queue: "collections.deque" = collections.deque()
        self._queue_samples = 0
        self._qlock = threading.Lock()

        # Window accumulator state (adapter thread only).
        self._win_stats = None          # device tree or None (empty window)
        self._win_samples = 0
        self._pending_rows: list = []   # sanitized rows awaiting a full batch
        self._last_fold = self._clock()

        # Guard state.
        self._frozen_until = 0.0
        self._freeze_reason: Optional[str] = None
        self._consecutive_rollbacks = 0

        # Lifetime counters (all host-side ints; read by /stats).
        self.generation = 0             # canary-accepted adapted swaps
        self.fold_attempts = 0
        self.dropped_rows = 0           # sanitization rejects
        self.dropped_backlog = 0        # queue overflow drops
        self.last_drift: Optional[float] = None

        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

        from dwt_tpu.obs.registry import get_registry

        reg = get_registry()
        self._m_drift = reg.gauge(
            "dwt_serve_domain_shift",
            "relative distance between live whitening/BN stats and the "
            "accumulated traffic window (0 = no drift)",
        )
        self._m_generations = reg.counter(
            "dwt_serve_adapt_generations_total",
            "adapted candidate generations by outcome",
            labelnames=("verdict",),
        )
        reg.gauge(
            "dwt_serve_adapt_window_samples",
            "sanitized samples accumulated toward the next fold",
        ).set_function(lambda: self.window_samples)
        reg.gauge(
            "dwt_serve_adapt_frozen",
            "1 while adaptation is frozen (rollback backoff, firing "
            "alert), else 0",
        ).set_function(lambda: 1 if self.frozen_reason() else 0)

        controller.add_verdict_listener(self._on_verdict)

    # ----------------------------------------------------------- harvest

    def offer(self, x: np.ndarray, real_n: int) -> None:
        """Dispatcher hook: enqueue the REAL rows of one dispatched
        bucket.  Padded tail rows (repeat-last-row, ``batcher.py``) are
        excluded here, at the source — the moment-parity contract the
        accumulator owes the batcher's pad-and-mask convention.  Cheap
        and non-blocking; never raises into the dispatcher."""
        try:
            rows = np.asarray(x)[: int(real_n)]
            if rows.shape[0] == 0:
                return
            with self._qlock:
                self._queue.append(rows)
                self._queue_samples += rows.shape[0]
                while (self._queue_samples > self.max_window_samples
                       and len(self._queue) > 1):
                    old = self._queue.popleft()
                    self._queue_samples -= old.shape[0]
                    self.dropped_backlog += old.shape[0]
        except Exception:  # the serving path must never pay for a bug here
            log.exception("adapt: offer failed; batch skipped")

    def _drain_queue(self) -> list:
        with self._qlock:
            batches = list(self._queue)
            self._queue.clear()
            self._queue_samples = 0
        return batches

    # ------------------------------------------------------------ window

    @property
    def window_samples(self) -> int:
        """Sanitized samples in the current window (collected or
        awaiting a full collect batch)."""
        return self._win_samples + sum(
            r.shape[0] for r in self._pending_rows
        )

    def _reset_window(self) -> None:
        self._win_stats = None
        self._win_samples = 0
        self._pending_rows = []

    def _absorb(self, batches: list) -> None:
        """Sanitize drained rows and advance the window stats through
        the compiled collect forward, one fixed-size batch at a time."""
        for rows in batches:
            keep = sanitize_rows(rows, self.max_abs)
            dropped = int(rows.shape[0] - int(keep.sum()))
            if dropped:
                self.dropped_rows += dropped
            if keep.any():
                self._pending_rows.append(
                    np.ascontiguousarray(
                        rows[keep], self.engine.input_dtype
                    )
                )
        if not self._pending_rows:
            return
        pool = (
            np.concatenate(self._pending_rows, axis=0)
            if len(self._pending_rows) > 1 else self._pending_rows[0]
        )
        n_full = pool.shape[0] // self.collect_batch
        if n_full == 0:
            self._pending_rows = [pool]
            return
        live = self.engine.state
        if self._win_stats is None:
            # The window EMA starts AT the live stats and advances
            # toward the traffic — the evalpipe collect protocol, per
            # window.
            self._win_stats = live.batch_stats
        stats = self._win_stats
        with obs.span("adapt_collect", "serve",
                      batches=n_full, n=n_full * self.collect_batch):
            for i in range(n_full):
                xb = pool[
                    i * self.collect_batch: (i + 1) * self.collect_batch
                ]
                stats = self._collect(
                    self.engine._forward_params(live), stats, xb
                )
        self._win_stats = stats
        self._win_samples += n_full * self.collect_batch
        rest = pool[n_full * self.collect_batch:]
        self._pending_rows = [rest] if rest.shape[0] else []

    # ------------------------------------------------------------ guards

    def frozen_reason(self) -> Optional[str]:
        """Why folding is currently paused, or None.  Rollback backoff
        re-arms on its own once the (exponential) window passes; a
        firing alert freezes for exactly as long as it fires."""
        if self._clock() < self._frozen_until:
            return self._freeze_reason or "rollback backoff"
        if self.alert_engine is not None:
            self.alert_engine.maybe_evaluate()
            firing = self.alert_engine.firing()
            if firing:
                return f"alert firing: {','.join(firing)}"
        return None

    def _on_verdict(self, origin: str, version: Version,
                    verdict: str) -> None:
        if origin != "adapt":
            return
        if verdict == "ok":
            # An adapted generation survived its post-swap watch: the
            # freeze ladder resets.
            self._consecutive_rollbacks = 0
            return
        # Rolled back.  No artifact to blacklist (the generation was
        # built from traffic, not a file) — the consequence is time:
        # freeze folding, doubling per consecutive regression, and drop
        # the window that built the bad generation.
        self._consecutive_rollbacks += 1
        doublings = min(
            self._consecutive_rollbacks - 1, self.max_freeze_doublings
        )
        freeze_s = self.freeze_base_s * (2 ** doublings)
        self._frozen_until = self._clock() + freeze_s
        self._freeze_reason = (
            f"rollback backoff {freeze_s:.0f}s "
            f"(#{self._consecutive_rollbacks}: {verdict})"
        )
        self._reset_window()
        self._m_generations.labels(verdict="rolled_back").inc()
        log.warning("adapt: %s", self._freeze_reason)

    # -------------------------------------------------------------- fold

    def _effective_momentum(self) -> float:
        return max(0.0, min(self.momentum, self.max_momentum))

    def try_fold(self) -> Optional[str]:
        """One fold attempt: gate → fold → build → submit.  Returns the
        verdict string (also counted on the generations metric), or None
        when there was nothing to attempt (empty window)."""
        import jax

        self._last_fold = self._clock()
        if self._win_samples == 0:
            return None
        self.fold_attempts += 1
        live = self.engine.state
        live_host = jax.device_get(live.batch_stats)
        win_host = jax.device_get(self._win_stats)
        drift = stats_drift(live_host, win_host)
        self.last_drift = drift
        self._m_drift.set(drift)
        if self._win_samples < self.min_samples:
            # Thin window: keep accumulating, fold next cadence.  The
            # drift gauge still updates — a drifting-but-quiet replica
            # should alarm even while the gate holds.
            self._event("adapt_build", ok=False, reason="thin_window",
                        samples=self._win_samples, drift=drift)
            self._m_generations.labels(verdict="thin_window").inc()
            return "thin_window"
        m = self._effective_momentum()
        folded = jax.tree.map(
            lambda a, b: (
                np.asarray(a)
                + m * (np.asarray(b, np.float64) - np.asarray(a))
            ).astype(np.asarray(a).dtype),
            live_host, win_host,
        )
        finite = all(
            np.isfinite(leaf).all() for leaf in jax.tree.leaves(folded)
        )
        if not finite:
            # Should be unreachable past sanitization — but a candidate
            # with non-finite stats must never even reach the canary.
            self._event("adapt_build", ok=False, reason="nonfinite",
                        samples=self._win_samples, drift=drift)
            self._m_generations.labels(verdict="nonfinite").inc()
            self._reset_window()
            return "nonfinite"
        # Version identity: the params are unchanged, so the digest must
        # come from what DID change — the folded stats tree.  Distinct
        # per generation, stable across replicas seeing the same
        # traffic.
        version = Version(live.version.step, params_digest(folded))
        self._event("adapt_build", ok=True, version=version.label,
                    samples=self._win_samples, drift=drift,
                    momentum=m)
        candidate = self.engine.build_state_from_stats(
            live, folded, version=version
        )
        went_live, reason = self.controller.submit(
            candidate, origin="adapt"
        )
        self._reset_window()
        if went_live:
            self.generation += 1
            self._m_generations.labels(verdict="swapped").inc()
            return "swapped"
        self._m_generations.labels(verdict="refused").inc()
        log.warning("adapt: candidate %s refused: %s",
                    version.label, reason)
        return "refused"

    def _event(self, kind: str, **fields) -> None:
        if self.access_log is not None:
            self.access_log.event(kind, **fields)

    # -------------------------------------------------------------- loop

    def step(self) -> Optional[str]:
        """One adapter iteration: act on any post-swap verdict, absorb
        queued traffic, and fold on cadence.  Returns the fold verdict
        when one was attempted."""
        status = self.controller.poll()
        self._absorb(self._drain_queue())
        if status == "hold":
            # A generation (ours or a checkpoint's) is under post-swap
            # watch: keep accumulating, do not deploy on top of it.
            return None
        if self._clock() - self._last_fold < self.adapt_every_s:
            return None
        reason = self.frozen_reason()
        if reason is not None:
            # Push the cadence out rather than busy-retrying the gate.
            self._last_fold = self._clock()
            return None
        return self.try_fold()

    def stats(self) -> dict:
        """Adaptation fields for ``/stats``."""
        reason = self.frozen_reason()
        return {
            "generation": self.generation,
            "frozen": reason is not None,
            **({"frozen_reason": reason} if reason else {}),
            "window_samples": self.window_samples,
            "fold_attempts": self.fold_attempts,
            "dropped_rows": self.dropped_rows,
            "consecutive_rollbacks": self._consecutive_rollbacks,
            **({"domain_shift": round(self.last_drift, 6)}
               if self.last_drift is not None else {}),
        }

    def start(self) -> None:
        if self._thread is not None:
            raise RuntimeError("adapter already started")

        def _run():
            while not self._stop.wait(self.poll_s):
                try:
                    self.step()
                except Exception:
                    log.exception("adapt: step failed")

        self._thread = threading.Thread(
            target=_run, name="dwt-serve-adapt", daemon=True
        )
        self._thread.start()

    def stop(self, timeout: float = 30.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
