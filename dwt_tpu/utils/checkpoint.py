"""Atomic, validated Orbax checkpointing for ``TrainState`` (SURVEY §5).

The reference never saves anything (checkpoint/resume is read-only there,
``resnet50…py:367``); preemption resilience on TPU requires periodic saves
— and saves that a preemption can land *inside*.  Three defenses:

* **atomic finalize** — Orbax writes into a ``.tmp-…`` sibling; only after
  the manifest is written is the directory renamed to ``<step>``.  A kill
  at any point leaves either the previous checkpoints untouched plus a
  recognizable tmp dir (swept by the next save), never a half-written
  ``<step>`` that a resume would trust.
* **per-step manifest** — ``manifest.json`` inside each checkpoint records
  the step, a SHA-256 digest of the param tree, a wall-clock timestamp,
  and every file's size.  ``latest_step``/``restore_state`` treat a
  checkpoint as valid only if the manifest and all recorded sizes check
  out (detects truncation without reading array bytes), and the digest is
  re-verified after restore (detects bit corruption).
* **newest-valid fallback** — restore walks candidates newest → oldest and
  returns the first that validates AND restores, instead of crashing the
  resumed job on the artifact the crash itself tore.

Checkpoint I/O additionally retries transient ``OSError`` with bounded
exponential backoff (flaky NFS/GCS fuse mounts).  Directories without a
manifest are accepted as legacy artifacts (pre-manifest converter output)
— finalized-by-rename still guarantees they are complete.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import shutil
import time
from typing import Any, Callable, List, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from dwt_tpu.resilience import inject

log = logging.getLogger(__name__)

MANIFEST = "manifest.json"
_TMP_PREFIX = ".tmp-"

# Transient-I/O retry policy (checkpoint save/restore only; item-level
# data retries live in dwt_tpu.data.loader).
IO_RETRIES = 3
IO_BACKOFF_S = 0.05


def _root(ckpt_dir: str) -> str:
    return os.path.abspath(os.path.expanduser(ckpt_dir))


def _with_retries(fn: Callable[[], Any], what: str,
                  retries: int = IO_RETRIES,
                  backoff_s: float = IO_BACKOFF_S) -> Any:
    """Run ``fn`` retrying transient ``OSError`` with bounded backoff."""
    for attempt in range(retries):
        try:
            return fn()
        except OSError as e:
            if attempt == retries - 1:
                raise
            delay = backoff_s * (2 ** attempt)
            log.warning(
                "%s failed (%s); retry %d/%d in %.2fs",
                what, e, attempt + 1, retries - 1, delay,
            )
            time.sleep(delay)


def params_digest(params: Any) -> str:
    """SHA-256 over the param tree's leaves (values, shapes, dtypes, and
    tree paths), host-side.  Order-stable: ``jax.tree`` flattening order."""
    h = hashlib.sha256()
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        arr = np.asarray(jax.device_get(leaf))
        h.update(jax.tree_util.keystr(path).encode())
        h.update(str(arr.dtype).encode())
        h.update(str(arr.shape).encode())
        h.update(arr.tobytes())
    return h.hexdigest()


def _write_manifest(path: str, step: int, digest: str) -> None:
    files = {}
    for sub, _, names in os.walk(path):
        for name in names:
            full = os.path.join(sub, name)
            files[os.path.relpath(full, path)] = os.path.getsize(full)
    manifest = {
        "step": int(step),
        "params_digest": digest,
        "timestamp": time.time(),
        "files": files,
    }
    with open(os.path.join(path, MANIFEST), "w") as f:
        json.dump(manifest, f, indent=1)


def _read_manifest(path: str) -> Optional[dict]:
    try:
        with open(os.path.join(path, MANIFEST)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def is_valid_checkpoint(path: str) -> bool:
    """A finalized checkpoint whose manifest (if any) checks out.

    Unfinalized tmp dirs are never valid; manifest-less finalized dirs are
    legacy artifacts and accepted as-is.
    """
    if not os.path.isdir(path) or os.path.basename(path).startswith(_TMP_PREFIX):
        return False
    if not os.path.exists(os.path.join(path, MANIFEST)):
        return True  # legacy (pre-manifest) checkpoint
    manifest = _read_manifest(path)
    if manifest is None:
        return False
    for rel, size in manifest.get("files", {}).items():
        full = os.path.join(path, rel)
        if not os.path.exists(full) or os.path.getsize(full) != size:
            return False
    return True


def valid_steps(ckpt_dir: str) -> List[int]:
    """Ascending step numbers of the valid checkpoints under ``ckpt_dir``."""
    root = _root(ckpt_dir)
    if not os.path.isdir(root):
        return []
    return sorted(
        int(d)
        for d in os.listdir(root)
        if d.isdigit() and is_valid_checkpoint(os.path.join(root, d))
    )


def latest_step(ckpt_dir: str) -> Optional[int]:
    steps = valid_steps(ckpt_dir)
    return steps[-1] if steps else None


# A .tmp- dir older than this is presumed abandoned (its writer dead) and
# swept; a younger one may be a live save (multi-host Orbax writes, or a
# concurrent job sharing the ckpt_dir) and is left alone — a live Orbax
# save is seconds to minutes.
STALE_TMP_AGE_S = 3600.0


def _sweep_stale_tmp(root: str, keep_name: Optional[str] = None) -> None:
    """Remove leftover ``.tmp-`` dirs old enough that their writer is
    certainly dead.  ``keep_name`` protects the current save's own tmp."""
    now = time.time()
    for d in os.listdir(root):
        if not d.startswith(_TMP_PREFIX) or d == keep_name:
            continue
        full = os.path.join(root, d)
        try:
            if now - os.path.getmtime(full) <= STALE_TMP_AGE_S:
                continue
        except OSError:
            continue
        shutil.rmtree(full, ignore_errors=True)


def tree_all_finite(tree: Any) -> bool:
    """One fused device verdict: every floating/complex leaf is finite."""
    import jax.numpy as jnp

    leaves = [
        leaf
        for leaf in jax.tree_util.tree_leaves(tree)
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.inexact)
    ]
    if not leaves:
        return True
    verdict = jax.jit(
        lambda ls: jnp.all(jnp.stack([jnp.all(jnp.isfinite(x)) for x in ls]))
    )(leaves)
    return bool(verdict)


def save_state(
    ckpt_dir: str, step: int, state: Any, keep: Optional[int] = None,
    require_finite: bool = True,
) -> Optional[str]:
    """Atomically write ``state`` under ``ckpt_dir/<step>``; returns the path.

    Overwrites an existing same-step checkpoint so crash-resume re-saves
    are idempotent.  ``keep=N`` prunes to the newest ``N`` steps after
    saving (``keep=1`` is the reference's single-artifact "model_best"
    convention).  A crash anywhere before the final rename leaves the
    previous checkpoints untouched.

    ``require_finite`` (default) refuses to save non-finite params —
    logged and skipped, returning ``None``: a NaN-poisoned checkpoint
    would validate (the digest proves integrity, not health) and become
    the "newest valid" step that both plain resume and the divergence
    guard's rollback would then faithfully restore.  The divergence can
    strike between guard checks, so the save path must gate too.

    Multi-host: every process calls this (Orbax coordinates the array
    writes into the SHARED tmp dir); only process 0 touches the
    filesystem around it (manifest, finalize rename, sweep, prune), and
    all processes sync before returning so none races ahead to read
    ``latest_step`` before the rename.
    """
    if require_finite and not tree_all_finite(getattr(state, "params", state)):
        log.warning(
            "skipping checkpoint save @%d: non-finite params (a NaN "
            "checkpoint would poison newest-valid resume)", step,
        )
        return None
    root = _root(ckpt_dir)
    os.makedirs(root, exist_ok=True)
    final = os.path.join(root, str(int(step)))
    # Shared (not per-process) tmp name: on multi-host runs every process
    # must hand Orbax the SAME path for its coordinated multi-process save.
    tmp_name = f"{_TMP_PREFIX}{int(step)}"
    tmp = os.path.join(root, tmp_name)
    primary = jax.process_index() == 0
    if primary and os.path.exists(tmp):
        shutil.rmtree(tmp)

    def _write():
        # Fault hook: one injected OSError per write ATTEMPT — inside the
        # retry wrapper, so a transient count is absorbed by the backoff
        # and a persistent one surfaces like a dead filesystem would.
        inject.maybe_io_error(f"save @{step}")
        with ocp.StandardCheckpointer() as ckptr:
            ckptr.save(tmp, state, force=True)

    try:
        _with_retries(_write, f"checkpoint save @{step}")
        if primary:
            _write_manifest(
                tmp, step, params_digest(getattr(state, "params", state))
            )
            # Fault hook: a preemption/SIGKILL landing here leaves only the
            # unfinalized tmp dir — exactly what restore must survive.
            inject.maybe_crash_mid_save(step)
            if os.path.exists(final):
                # Same-step re-save: never open a window with the old
                # artifact deleted and the new one not yet in place (a
                # crash there would eat the newest — possibly only —
                # checkpoint).  Move the old step aside into the tmp
                # namespace (atomic rename), finalize, then drop the aside.
                aside = os.path.join(
                    root, f"{_TMP_PREFIX}replaced-{int(step)}"
                )
                if os.path.exists(aside):
                    shutil.rmtree(aside)
                os.replace(final, aside)
                os.replace(tmp, final)
                shutil.rmtree(aside, ignore_errors=True)
            else:
                os.replace(tmp, final)
    except OSError:
        if primary:
            shutil.rmtree(tmp, ignore_errors=True)
        raise
    if primary:
        _sweep_stale_tmp(root)
        if keep is not None:
            for old in valid_steps(root)[:-keep]:
                shutil.rmtree(os.path.join(root, str(old)), ignore_errors=True)
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"dwt_ckpt_save_{int(step)}")
    return final


def _restore_one(path: str, template: Any) -> Any:
    abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct, template)

    def _read():
        with ocp.StandardCheckpointer() as ckptr:
            return ckptr.restore(path, abstract)

    restored = _with_retries(_read, f"checkpoint restore {path}")
    manifest = _read_manifest(path)
    if manifest is not None and "params_digest" in manifest:
        got = params_digest(getattr(restored, "params", restored))
        if got != manifest["params_digest"]:
            raise ValueError(
                f"checkpoint {path} failed digest validation "
                f"({got[:12]}… != manifest {manifest['params_digest'][:12]}…)"
            )
    return restored


def restore_state(ckpt_dir: str, template: Any, step: Optional[int] = None) -> Any:
    """Restore the checkpoint at ``step`` shaped like ``template``.

    ``step=None`` restores the newest checkpoint that both validates and
    restores, walking older candidates on failure (a torn or corrupted
    newest checkpoint falls back instead of killing the resumed job).  An
    explicit ``step`` must be valid and restore cleanly, or this raises.
    """
    root = _root(ckpt_dir)
    if step is not None:
        path = os.path.join(root, str(int(step)))
        if not is_valid_checkpoint(path):
            raise FileNotFoundError(
                f"checkpoint step {step} under {ckpt_dir} is missing, "
                "unfinalized, or truncated"
            )
        return _restore_one(path, template)

    candidates = valid_steps(root)
    errors: List[str] = []
    for s in reversed(candidates):
        path = os.path.join(root, str(s))
        try:
            restored = _restore_one(path, template)
            if errors:
                log.warning(
                    "restored step %d after skipping invalid newer "
                    "checkpoints: %s", s, "; ".join(errors),
                )
            return restored
        except (OSError, ValueError) as e:
            errors.append(f"step {s}: {e}")
    raise FileNotFoundError(
        f"no restorable checkpoints under {ckpt_dir}"
        + (f" (tried: {'; '.join(errors)})" if errors else "")
    )
