"""AOT-bucketed inference engine: the deployment forward, compiled once.

The paper's deployment artifact is the target-branch eval forward —
frozen running stats, domain-specific whitening at test time, no
augmentation (``dwt_tpu.train.steps.make_serve_forward``).  The engine
makes that forward servable:

* **load once**: params + ``batch_stats`` restore from a training
  checkpoint through the SAME newest-valid ranked walk training resume
  uses (``utils.checkpoint.restore_newest`` — main dir + anchors, both
  the Orbax and host-shard on-disk formats, digest-verified), with NO
  optimizer reconstruction (template-free ``restore_tree``);
* **whiten once**: every site's eval whitening matrix precomputes from
  the frozen stats in one batched factorization
  (``evalpipe.make_whiten_cache_fn`` — the eval pipeline's own cache
  builder), then lives on device for the server's lifetime;
* **compile once per bucket**: ``jax.jit(fwd).lower(...).compile()``
  ahead of time for each fixed bucket shape, so the FIRST request of any
  size pays milliseconds, not an XLA compile;
* **device-resident**: params/stats/cache are placed on device at load
  through the run's :class:`~dwt_tpu.parallel.ShardingPlan` — replicated
  replica fan-out under the dp preset, rules-driven model sharding under
  a gspmd plan (whitening stats and the cache stay replicated per the
  preset's contract); per-request traffic is just the bucket batch H2D
  and the logits D2H.  The host-array loose restore plus plan placement
  is serve's restore-to-spec: each leaf lands directly on its target
  sharding, no replicated device intermediate.

**Hot swap (the continuous-deployment fleet, ``dwt_tpu.fleet``).**  The
weights the compiled bucket executables close over are NOT baked into
the executables — params/stats/cache are arguments — so a new
checkpoint's trees, built into a fresh :class:`EngineState` off the
dispatcher thread (:meth:`ServeEngine.build_state`: same adapt → cache
factorization → plan placement path as load), swap in as one atomic
pointer flip (:meth:`ServeEngine.swap`).  The dispatcher snapshots the
state ONCE per batch, so an in-flight bucket finishes on the version it
started with and no batch ever mixes versions; the old state is
returned to the caller (the fleet's rollback buffer).
"""

from __future__ import annotations

import logging
import time
from dataclasses import dataclass
from typing import Any, Dict, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dwt_tpu import obs
from dwt_tpu.serve.batcher import DEFAULT_BUCKETS, bucket_for, pad_to_bucket
from dwt_tpu.serve.quant import dequantize_int8, quantize_int8
from dwt_tpu.train.evalpipe import make_whiten_cache_fn
from dwt_tpu.train.steps import make_serve_forward
from dwt_tpu.utils import restore_newest
from dwt_tpu.utils.checkpoint import adapt_tree, params_digest

log = logging.getLogger(__name__)


@dataclass(frozen=True)
class Version:
    """Identity of the weights a response was computed with: checkpoint
    step + short params digest.  Stamped into every access record and
    ``/stats`` so post-swap latency/error windows are attributable to
    the version that served them — the signal the canary rollback reads.
    A fresh-init engine has no checkpoint identity (``label`` =
    ``"fresh"``)."""

    step: Optional[int] = None
    digest: Optional[str] = None

    @property
    def label(self) -> str:
        if self.step is None and self.digest is None:
            return "fresh"
        d = (self.digest or "nodigest")[:8]
        return f"{self.step}-{d}"


class EngineState(NamedTuple):
    """One immutable generation of device-resident serving weights.

    The whole deployment artifact — params, frozen whitening/BN running
    stats, and the whiten cache precomputed from them — travels as ONE
    value, so a swap can never pair new params with an old cache (a torn
    mixed-generation forward would break the bitwise eval contract).

    ``scales`` is the int8 deployment format's dequant scale tree (one
    f32 per-tensor scale per param leaf, ``serve.quant``): None on
    unquantized engines, and ALWAYS travelling with the int8 params it
    dequantizes — a swap can no more tear weights from their scales than
    params from their cache."""

    params: Any
    batch_stats: Any
    cache: Any
    version: Version
    scales: Any = None


class ServeEngine:
    """Compiled bucket forwards over device-resident weights.

    ``input_shape`` is the per-sample shape (e.g. ``(28, 28, 1)`` for
    digits, ``(224, 224, 3)`` for OfficeHome); ``plan`` (the run's
    :class:`~dwt_tpu.parallel.ShardingPlan`) shards every bucket batch's
    sample axis over the plan's data axes — replica fan-out, with bucket
    sizes rounded UP to data-shard multiples so the shards stay equal
    (pad-and-mask keeps the returned logits exact; the model axis never
    shards the batch).  ``mesh=`` is the pre-plan surface, mapped onto
    the equivalent replica-mode dp plan.
    """

    def __init__(
        self,
        model,
        params,
        batch_stats,
        input_shape: Tuple[int, ...],
        *,
        buckets: Sequence[int] = DEFAULT_BUCKETS,
        whitener: Optional[str] = None,
        whiten_eps: Optional[float] = None,
        eval_domain: Optional[int] = None,
        plan=None,
        mesh=None,
        input_dtype=np.float32,
        step: Optional[int] = None,
        source: Optional[str] = None,
        digest: Optional[str] = None,
        quantize: bool = False,
        cache_dtype=None,
    ):
        if plan is None:
            from dwt_tpu.parallel import ShardingPlan

            plan = ShardingPlan.from_mesh(mesh)
        self.model = model
        self.input_shape = tuple(input_shape)
        self.input_dtype = np.dtype(input_dtype)
        self.source = source      # "checkpoint" | "anchor" | None
        self._plan = plan
        self._mesh = plan.mesh
        if plan.data_size > 1:
            buckets = sorted({
                -(-int(b) // plan.data_size) * plan.data_size
                for b in buckets
            })
        self.buckets = tuple(sorted(set(int(b) for b in buckets)))

        if whitener is None:
            # The cache must be factorized by the SAME backend the model
            # was built with (swbn caches the tracked matrix itself, the
            # factorizing backends differ in ulps) — read it off the
            # model rather than trusting a separately-passed flag.
            whitener = getattr(model, "whitener", "cholesky")
        if eval_domain is None:
            # The cache's stat branch must be the branch the model's norm
            # sites serve from — read it off the model, don't guess.
            eval_domain = getattr(model, "eval_domain", 1)
        if whiten_eps is None:
            # Same reasoning for the shrinkage eps: a cache factorized
            # with a different eps than the model's in-site path would
            # break the bitwise contract with the uncached eval forward.
            whiten_eps = getattr(model, "whiten_eps", 1e-3)
        # Kept so hot-swapped candidates factorize their cache with the
        # SAME compiled builder + numerics the initial load used.
        self._cache_fn = make_whiten_cache_fn(
            whitener, whiten_eps, eval_domain
        )
        # int8 deployment format (serve.quant): params quantize per
        # generation in build_state (off the dispatcher thread), the
        # compiled forward dequantizes on device.  The cache_dtype cast
        # (bf16 serving) happens AFTER the f32 factorization — the cache
        # is frozen per generation, so the precision is a one-time cost.
        self.quantize = bool(quantize)
        self._cache_dtype = (
            None if cache_dtype is None else jnp.dtype(cache_dtype)
        )
        self.swap_count = 0
        self._state = self.build_state(
            params, batch_stats, version=Version(step, digest)
        )
        forward = make_serve_forward(model)
        if self.quantize:
            base_forward = forward

            def forward(params, batch_stats, cache, x):
                # params arrives as the {"q", "scale"} bundle (see
                # _forward_params); dequant runs inside the compiled
                # program so XLA fuses it into the first consumers and
                # only int8 weights live in the executable's inputs.
                deq = dequantize_int8(
                    params["q"], params["scale"], dtype=jnp.float32
                )
                return base_forward(deq, batch_stats, cache, x)
        self._x_sharding = plan.batch_sharding()
        fwd = plan.make_serve_forward(forward)
        self._compiled: Dict[int, object] = {}
        self.compile_s: Dict[int, float] = {}
        jitted = jax.jit(fwd)
        st = self._state
        for b in self.buckets:
            spec = jax.ShapeDtypeStruct(
                (b,) + self.input_shape, self.input_dtype,
                sharding=self._x_sharding,
            )
            t0 = time.perf_counter()
            self._compiled[b] = jitted.lower(
                self._forward_params(st), st.batch_stats, st.cache, spec
            ).compile()
            self.compile_s[b] = round(time.perf_counter() - t0, 3)
        log.info(
            "serve engine ready: buckets %s compiled in %s s (version=%s)",
            self.buckets, self.compile_s, st.version.label,
        )

    # ------------------------------------------------------ state / versions

    @property
    def state(self) -> EngineState:
        """The live generation — snapshot this ONCE per batch; everything
        computed from one snapshot is single-version by construction."""
        return self._state

    @property
    def version(self) -> Version:
        return self._state.version

    @property
    def params(self):
        return self._state.params

    @property
    def batch_stats(self):
        return self._state.batch_stats

    @property
    def cache(self):
        return self._state.cache

    @property
    def step(self) -> Optional[int]:
        return self._state.version.step

    def _forward_params(self, st: EngineState):
        """The params argument the compiled bucket forwards take: the
        raw tree, or (int8 format) the weights+scales bundle — bundled
        per call from ONE EngineState snapshot, so the pair is always
        same-generation."""
        if self.quantize:
            return {"q": st.params, "scale": st.scales}
        return st.params

    def _factorize_cache(self, batch_stats):
        """Whiten cache from frozen stats: the shared compiled builder,
        plus the one-time bf16 cast (serve_dtype) — factorization itself
        is ALWAYS f32 (shared numerics with eval)."""
        cache = self._cache_fn(batch_stats)
        if cache and self._cache_dtype is not None:
            cache = jax.tree.map(
                lambda a: a.astype(self._cache_dtype)
                if jnp.issubdtype(a.dtype, jnp.floating) else a,
                cache,
            )
        return cache

    def build_state(
        self, params, batch_stats, *, version: Optional[Version] = None
    ) -> EngineState:
        """Build one swappable generation: factorize the whiten cache
        from the frozen stats and place everything per the plan — the
        restore-to-spec placement path (host leaves land directly on
        their target shardings).  Safe to run OFF the dispatcher thread:
        nothing here touches the live ``_state``, so serving continues
        on the old generation while the new one builds (the double
        buffer)."""
        with obs.span("build_state", "fleet",
                      version=version.label if version else "fresh"):
            cache = self._factorize_cache(batch_stats)
            scales = None
            if self.quantize:
                # Off-dispatcher by the same contract as the cache
                # factorization: nothing below touches the live _state.
                params, scales = quantize_int8(params)
            plan = self._plan
            if plan.mode == "gspmd":
                placed = plan.place(
                    {"params": params, "batch_stats": batch_stats,
                     "whiten_cache": cache},
                    "serve state",
                )
                params = placed["params"]
                batch_stats = placed["batch_stats"]
                cache = placed["whiten_cache"] if cache else cache
                if scales is not None:
                    scales = plan.place_replicated(scales)
            else:
                params = plan.place_replicated(params)
                batch_stats = plan.place_replicated(batch_stats)
                cache = plan.place_replicated(cache) if cache else cache
                if scales is not None:
                    scales = plan.place_replicated(scales)
        return EngineState(params, batch_stats, cache,
                           version or Version(), scales)

    def build_state_from_stats(
        self, base: EngineState, batch_stats, *, version: Version
    ) -> EngineState:
        """Adapted generation: ``base``'s params (and int8 scales)
        UNCHANGED, a mutated ``batch_stats`` tree, and the whiten cache
        refactorized from it — the serve-side online-adaptation build
        path (``dwt_tpu.serve.adapt``).

        Reusing ``base.params`` verbatim matters twice over: the params
        are already device-placed (no re-upload per adapted generation),
        and on a quantized engine they are already int8 — pushing them
        back through :meth:`build_state` would re-quantize quantized
        weights.  Off-dispatcher safe by the same contract as
        :meth:`build_state`."""
        with obs.span("build_state", "fleet", version=version.label,
                      adapt=1):
            cache = self._factorize_cache(batch_stats)
            plan = self._plan
            if plan.mode == "gspmd":
                placed = plan.place(
                    {"batch_stats": batch_stats, "whiten_cache": cache},
                    "serve state",
                )
                batch_stats = placed["batch_stats"]
                cache = placed["whiten_cache"] if cache else cache
            else:
                batch_stats = plan.place_replicated(batch_stats)
                cache = plan.place_replicated(cache) if cache else cache
        return EngineState(base.params, batch_stats, cache, version,
                           base.scales)

    def build_state_from_tree(
        self, tree: dict, *, version: Optional[Version] = None,
        what: str = "candidate",
    ) -> EngineState:
        """Loose checkpoint tree (``restore_tree`` output) → swappable
        generation: graft params/stats onto the model's typed template
        (structural validation — a candidate from a different
        architecture fails HERE, not at forward time), then
        :meth:`build_state`."""
        if not isinstance(tree, dict) or "params" not in tree \
                or "batch_stats" not in tree:
            raise ValueError(
                f"{what}: restored tree has no params/batch_stats — "
                "not a TrainState artifact"
            )
        template = abstract_variables(self.model, self.input_shape)
        params = adapt_tree(
            tree["params"], template["params"], f"{what} params"
        )
        batch_stats = adapt_tree(
            tree["batch_stats"], template["batch_stats"],
            f"{what} batch_stats",
        )
        if version is None:
            step = tree.get("step")
            version = Version(
                None if step is None else int(np.asarray(step)),
                params_digest(params),
            )
        return self.build_state(params, batch_stats, version=version)

    def swap(self, state: EngineState) -> EngineState:
        """Atomic generation flip; returns the PREVIOUS state (the
        fleet keeps it as the rollback buffer).  The single reference
        assignment is the whole cutover: batches whose snapshot predates
        it finish on the old generation, the next snapshot serves the
        new one — no lock, no pause, no torn mixed-version batch."""
        prev = self._state
        self._state = state
        self.swap_count += 1
        log.info(
            "serve engine swapped: %s -> %s (swap #%d)",
            prev.version.label, state.version.label, self.swap_count,
        )
        return prev

    # -------------------------------------------------------------- loading

    @classmethod
    def from_checkpoint(
        cls,
        ckpt_dir: str,
        model,
        input_shape: Tuple[int, ...],
        **kwargs,
    ) -> "ServeEngine":
        """Restore the newest valid checkpoint (main dir + anchors, either
        on-disk format) and build the engine from its params/stats.

        The restore is template-free (no optimizer reconstruction), so
        the stat structs come back as plain dicts; a one-time
        ``model.init`` provides the typed structure to graft them onto —
        which doubles as structural validation that the checkpoint
        matches the model the server was asked to build."""
        out = restore_newest(ckpt_dir)  # template-free loose restore
        if out is None:
            raise FileNotFoundError(
                f"no restorable checkpoints under {ckpt_dir} (main or "
                "anchors) — nothing to serve"
            )
        tree, source = out
        if not isinstance(tree, dict) or "params" not in tree \
                or "batch_stats" not in tree:
            raise ValueError(
                f"checkpoint under {ckpt_dir} restored without params/"
                "batch_stats — not a TrainState artifact"
            )
        variables = abstract_variables(model, input_shape)
        params = adapt_tree(
            tree["params"], variables["params"], f"{ckpt_dir} params"
        )
        batch_stats = adapt_tree(
            tree["batch_stats"], variables["batch_stats"],
            f"{ckpt_dir} batch_stats",
        )
        step = tree.get("step")
        return cls(
            model, params, batch_stats, input_shape,
            step=None if step is None else int(np.asarray(step)),
            source=source,
            # The version digest is the restore-verified params digest,
            # recomputed host-side (also covers manifest-less legacy
            # artifacts, which record none).
            digest=params_digest(params),
            **kwargs,
        )

    # ------------------------------------------------------------ inference

    def stage(self, x: np.ndarray):
        """H2D placement of one bucket batch — the ``transfer`` hook for
        ``prefetch_to_device`` double-buffered staging (server dispatch
        thread overlaps the next batch's H2D with this one's compute)."""
        x = np.ascontiguousarray(x, self.input_dtype)
        if self._x_sharding is None:
            return jax.device_put(x)
        return jax.device_put(x, self._x_sharding)

    def forward(self, x_staged, bucket: int,
                state: Optional[EngineState] = None):
        """Compiled forward of one staged bucket batch -> device logits.

        ``state`` pins the generation (the dispatcher passes its
        per-batch snapshot; the canary passes a candidate under test);
        default is the live state."""
        fn = self._compiled.get(int(bucket))
        if fn is None:
            raise ValueError(
                f"no compiled forward for bucket {bucket} "
                f"(compiled: {self.buckets})"
            )
        st = state if state is not None else self._state
        return fn(self._forward_params(st), st.batch_stats, st.cache,
                  x_staged)

    def infer(self, x: np.ndarray, bucket: Optional[int] = None,
              state: Optional[EngineState] = None) -> np.ndarray:
        """Convenience synchronous path: pad → stage → forward → fetch.

        ``x`` is ``[n, ...sample]`` with ``n`` ≤ the largest bucket;
        returns the ``[n, classes]`` logits for the REAL rows only.  The
        server's batched path does these stages on separate threads; this
        single-call form serves tests, the in-process client's unbatched
        mode, and the canary gate's fixture eval (which passes a
        CANDIDATE ``state`` without swapping it live).
        """
        x = np.asarray(x, self.input_dtype)
        n = x.shape[0]
        if bucket is None:
            bucket = bucket_for(n, self.buckets)
        elif n < 1 or n > bucket:
            raise ValueError(f"got {n} samples for bucket {bucket}")
        logits = jax.device_get(
            self.forward(self.stage(pad_to_bucket(x, bucket)), bucket,
                         state=state)
        )
        return np.asarray(logits)[:n]


def abstract_variables(model, input_shape: Tuple[int, ...]) -> Any:
    """Shape-only ``model.init`` template (``jax.eval_shape`` — no FLOPs,
    no device memory): the typed structure loose checkpoint dicts graft
    onto, shared by the initial load and every hot-reload candidate."""
    import jax.numpy as jnp

    num_domains = getattr(model, "num_domains", 2)
    sample = jnp.zeros(
        (num_domains, 1) + tuple(input_shape), jnp.float32
    )
    return jax.eval_shape(
        lambda: model.init(jax.random.key(0), sample, train=True)
    )
