"""The driver contract for bench.py: ONE parsable JSON line, always.

The driver runs ``python bench.py`` at round end and records the parsed
line; a null/parse-failure means the round has no perf signal at all, so
the resilience chain (probe → retry → clean-env CPU fallback with an
honest diagnosis) is contract, not convenience.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

REQUIRED_KEYS = {"metric", "value", "unit", "vs_baseline"}


def _last_json_line(stdout: str) -> dict:
    lines = [l for l in stdout.strip().splitlines() if l.startswith("{")]
    assert lines, f"no JSON line in output: {stdout!r}"
    return json.loads(lines[-1])


@pytest.mark.slow  # 46s full resnet50@96px subprocess bench
# (t1_budget headroom, PR-17 slow-mark round); the record contract
# stays tier-1-covered by the lenet eval/data phase tests below
def test_bench_no_probe_emits_contract_json():
    env = {k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "bench.py", "--model", "lenet", "--steps", "3",
         "--no-probe"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=420,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    record = _last_json_line(proc.stdout)
    assert REQUIRED_KEYS <= set(record)
    assert record["value"] > 0 and record["unit"] == "imgs/sec"
    assert record["flops_source"] in ("xla_cost_analysis", "analytic_estimate")


def test_bench_lenet_eval_phase_supported():
    """ISSUE-7 satellite: ``--phase eval`` must cover the digits forward
    too (it used to hard-error for --model lenet), so the serving
    workload's single-chip floor is measurable for both models."""
    env = {k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"}
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.run(
        [sys.executable, "bench.py", "--model", "lenet", "--phase", "eval",
         "--steps", "3", "--no-probe"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=420,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    record = _last_json_line(proc.stdout)
    assert REQUIRED_KEYS <= set(record)
    assert record["metric"] == "lenet_dwt_eval_imgs_per_sec"
    assert record["value"] > 0
    # Eval is not the anchored flagship metric: no baseline ratio games.
    assert record["vs_baseline"] == 1.0
    assert record["baseline_imgs_per_sec"] is None


@pytest.mark.slow
@pytest.mark.skipif(
    __import__("importlib.util", fromlist=["util"]).find_spec("axon") is None,
    reason="relay startup hook (axon sitecustomize) not installed — arming "
    "PALLAS_AXON_POOL_IPS would be a no-op and the probe would succeed",
)
def test_bench_fallback_chain_emits_contract_json():
    # Arm the relay var with an unroutable address and shrink the probe
    # timeout: both probes must fail, and the clean-env CPU fallback must
    # still emit the JSON line with the relay diagnosis embedded.
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = "203.0.113.1"
    env["BENCH_PROBE_TIMEOUT_S"] = "5"
    env["BENCH_RELAY_WAIT_S"] = "5"  # cheap TCP poll, shortened for CI
    env.pop("JAX_PLATFORMS", None)
    proc = subprocess.run(
        [sys.executable, "bench.py", "--steps", "3"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=420,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    record = _last_json_line(proc.stdout)
    assert REQUIRED_KEYS <= set(record)
    assert record["backend"] == "cpu"
    assert "fallback" in record and "203.0.113.1" in record["fallback"]
    # The fallback times the FLAGSHIP model (reduced 96px), not a stand-in.
    assert "resnet50" in record["metric"]
    assert record["image_size"] == 96
    assert "baseline_imgs_per_sec" in record


class _FakeClock:
    """Deterministic stand-in for time.perf_counter: the contract tests
    model step cost and fetch round-trip as exact clock advances instead
    of real sleeps — wall-clock scheduling jitter made this test flaky
    under CI load (red at seed)."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_two_point_per_step_cancels_fixed_overhead(monkeypatch):
    """The shared timing helper must return the marginal per-step cost,
    not (steps + fetch round-trip)/steps — the property that makes relay
    numbers honest (bench.py:two_point_per_step).  Mocked monotonic
    clock: the cancellation is arithmetic, so the check can be exact."""
    import bench

    clock = _FakeClock()
    monkeypatch.setattr(bench.time, "perf_counter", clock)
    per_step_true, fetch_overhead = 0.003, 0.070  # ~the measured relay RTT

    class _Loss:
        # float(m["loss"]) is the synchronizing fetch: charge the fixed
        # round-trip exactly once per run() call, like the relay does.
        def __float__(self):
            clock.t += fetch_overhead
            return 0.5

    def step(state, batch):
        clock.t += per_step_true
        return state + 1, {"loss": _Loss()}

    per_step, state, loss, degraded = bench.two_point_per_step(
        step, 0, None, steps=8
    )
    assert not degraded
    assert loss == 0.5
    assert state == 3 + 2 + 8  # warmup + n1 + n2 all thread the state
    # (n2*c + rtt) - (n1*c + rtt) over n2-n1 cancels rtt exactly.
    assert per_step == pytest.approx(per_step_true, abs=1e-12)


def test_two_point_per_step_degraded_fallback(monkeypatch):
    """A non-positive two-point difference must fall back to the
    single-run average and SAY SO (the 'timing' field's contract).
    Zero-cost steps + a fixed fetch make the difference exactly zero."""
    import bench

    clock = _FakeClock()
    monkeypatch.setattr(bench.time, "perf_counter", clock)

    class _Loss:
        def __float__(self):
            clock.t += 0.070
            return 1.0

    def step(state, batch):
        return state, {"loss": _Loss()}

    per_step, _, _, degraded = bench.two_point_per_step(step, 0, None, steps=8)
    assert degraded is True
    assert per_step == pytest.approx(0.070 / 8)  # single-run avg, rtt included
