"""Data-parallel wrapping of train steps via shard_map.

Design: the train step is written once (``dwt_tpu.train.steps``) as a pure
per-replica function with an optional ``axis_name``; this module places it
on a mesh.  The batch's per-domain sample axis shards across ``DATA_AXIS``
(every replica sees an equal slice of every domain), the train state is
replicated, and three in-step collectives make per-replica execution exactly
reproduce the reference's single-device global-batch numerics:

* ``pmean`` of norm-site batch moments (inside the ops),
* gradient averaging (inside the step): under varying-axis tracking the
  backward pass auto-psums cotangents of the replicated params, so the step
  divides by the axis size rather than calling ``pmean`` — see
  ``dwt_tpu.train.steps._mean_grads_if``,
* ``psum`` of eval counters (inside the eval step).

Everything rides XLA collectives over ICI — there is no host-side
communication code to maintain, which IS the TPU-native distributed backend
(SURVEY §5).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

try:  # jax >= 0.5 exposes shard_map at top level
    from jax import shard_map as _shard_map
except ImportError:  # pragma: no cover - older jax
    from jax.experimental.shard_map import shard_map as _shard_map

def _batch_spec(mesh: Mesh) -> P:
    """Leading batch axis sharded over EVERY mesh axis — 1-D ``("data",)``
    and 2-D ``("dcn", "data")`` meshes both flatten onto the sample dim."""
    return P(tuple(mesh.axis_names))


def make_sharded_train_step(
    step_fn: Callable,
    mesh: Mesh,
    jit: bool = True,
    state_specs=None,
) -> Callable:
    """shard_map a ``(state, batch) -> (state, metrics)`` step over ``mesh``.

    ``step_fn`` must already carry the mesh's axis name(s) internally (grad
    averaging, op moment pmean) — build it with ``axis_name =
    tuple(mesh.axis_names)`` (a bare string for the 1-D mesh).  Every batch
    leaf is sharded along its leading axis over all mesh axes.

    ``state_specs`` is the plan's per-leaf spec pytree for the state
    (ISSUE-9: the plan — not this wrapper — owns placement); the default
    ``P()`` prefix replicates every leaf, which under the dp preset is the
    identical partitioning (and program) either way.
    """
    mapped = _shard_map(
        step_fn,
        mesh=mesh,
        in_specs=(state_specs if state_specs is not None else P(),
                  _batch_spec(mesh)),
        out_specs=(state_specs if state_specs is not None else P(), P()),
    )
    return jax.jit(mapped) if jit else mapped


def make_sharded_scanned_step(
    step_fn: Callable,
    mesh: Mesh,
    k: int,
    jit: bool = True,
    state_specs=None,
) -> Callable:
    """``make_sharded_train_step`` for a k-steps-per-dispatch chunk.

    The chunk pytree carries ``[k, batch, ...]`` leaves: axis 0 is the
    scan (time) axis — replicated — and axis 1 is the sample axis,
    sharded exactly as the single-step path shards axis 0.  Inside the
    shard_map the scan body is the same per-replica ``step_fn``, so all
    three cross-replica collectives (moment pmean, grad averaging, metric
    pmean) run per inner step, and numerics match k dispatched steps.
    ``state_specs``: see :func:`make_sharded_train_step`.
    """
    from dwt_tpu.train.steps import make_scanned_step

    mapped = _shard_map(
        make_scanned_step(step_fn, k),
        mesh=mesh,
        in_specs=(state_specs if state_specs is not None else P(),
                  _chunk_spec(mesh)),
        out_specs=(state_specs if state_specs is not None else P(), P()),
    )
    return jax.jit(mapped) if jit else mapped


def _chunk_spec(mesh: Mesh) -> P:
    """Chunk leaves are ``[k, batch, ...]``: scan axis replicated, sample
    axis sharded over every mesh axis."""
    return P(None, tuple(mesh.axis_names))


def make_sharded_eval_step(
    accum_eval: Callable,
    mesh: Mesh,
    jit: bool = True,
) -> Callable:
    """shard_map an accumulating eval dispatch over ``mesh``.

    ``accum_eval`` is ``steps.make_accum_eval_step(model, axis_name=
    tuple(mesh.axis_names))``: counters/params/stats — and the pass's
    precomputed whitening-matrix cache (replicated like the stats it was
    factorized from) — replicated, the ``{"x", "y", "mask"}`` chunk
    sharded on its sample axis (axis 1 — chunk layout ``[k, batch,
    ...]``), and the chunk's counter deltas ``psum``'d across the mesh
    inside the step, so the returned counters are the GLOBAL
    accumulators on every replica — the eval-path twin of
    :func:`make_sharded_train_step`'s counter psum.
    """
    mapped = _shard_map(
        accum_eval,
        mesh=mesh,
        in_specs=(P(), P(), P(), P(), _chunk_spec(mesh)),
        out_specs=P(),
    )
    return jax.jit(mapped) if jit else mapped


def make_sharded_collect_step(
    scanned_collect: Callable,
    mesh: Mesh,
    jit: bool = True,
) -> Callable:
    """shard_map a scanned stat-collection dispatch over ``mesh``.

    ``scanned_collect`` is ``steps.make_scanned_collect(collect_fn)``
    where ``collect_fn``'s model carries the mesh axis name(s): each
    replica forwards its slice of every collection batch and the norm
    sites ``pmean`` their moments across the mesh, so the EMA update
    every replica applies is computed from the GLOBAL batch moments —
    the stats trajectory of the unsharded reference path, to float
    reassociation tolerance (``tests/test_evalpipe.py``).  State is
    replicated; ``xs`` is ``[k, batch, ...]`` with the sample axis
    sharded.
    """
    mapped = _shard_map(
        scanned_collect,
        mesh=mesh,
        in_specs=(P(), _chunk_spec(mesh)),
        out_specs=P(),
    )
    return jax.jit(mapped) if jit else mapped


def make_sharded_serve_forward(
    forward: Callable,
    mesh: Mesh,
    jit: bool = True,
) -> Callable:
    """shard_map the serving forward over ``mesh`` (ISSUE-7 replica
    fan-out): ``forward`` is ``steps.make_serve_forward(model)`` —
    ``(params, batch_stats, cache, x) -> logits`` — with params, frozen
    stats, and the whitening cache replicated and the bucket batch's
    sample axis sharded over every mesh axis.  Eval-mode forwards are
    per-sample (running stats, no batch moments), so the per-replica body
    needs NO collectives: logits come back sharded on the same sample
    axis and the host's single ``device_get`` gathers them.  Bucket sizes
    must divide the mesh (``serve.engine`` rounds them up)."""
    mapped = _shard_map(
        forward,
        mesh=mesh,
        in_specs=(P(), P(), P(), _batch_spec(mesh)),
        out_specs=_batch_spec(mesh),
    )
    return jax.jit(mapped) if jit else mapped


def shard_batch(batch: Any, mesh: Mesh, chunked: bool = False) -> Any:
    """Place every batch leaf with its leading axis sharded over the mesh
    (``chunked=True``: the SECOND axis — leaf layout ``[k, batch, ...]``).

    Single-process: a plain sharded ``device_put``.  Multi-host (the mesh
    spans devices of several processes): every process passes its LOCAL
    shard — the slice its ``batch_iterator(shard=(process_index,
    process_count))`` produced — and the leaves are assembled into global
    arrays whose sharded axis is the concatenation over processes.
    """
    spec = _chunk_spec(mesh) if chunked else _batch_spec(mesh)
    sharding = NamedSharding(mesh, spec)
    if jax.process_count() == 1:
        return jax.device_put(batch, sharding)
    import numpy as np

    return jax.tree.map(
        lambda a: jax.make_array_from_process_local_data(
            sharding, np.asarray(a)
        ),
        batch,
    )


def replicate_state(state: Any, mesh: Mesh) -> Any:
    """Replicate a train state (or any pytree) across the mesh."""
    sharding = NamedSharding(mesh, P())
    return jax.device_put(state, sharding)
