"""Multi-branch domain normalization modules (whitening / batch norm).

Generalizes the reference's per-domain norm-branch pattern: the 2-branch
``ws*/wt*`` + shared ``gamma*/beta*`` sites of LeNet (``usps_mnist.py:200-228``)
and the 3-branch ``bns*/bnt*/bnt*_aug`` sites of the ResNet Bottleneck
(``resnet50_dwt_mec_officehome.py:73-213``) are both ``num_domains`` instances
of one stat collection with a single shared affine.

Stats live in the Flax ``batch_stats`` collection, stacked along a leading
domain axis, so the whole model state is one pytree that jits/shards/scans
cleanly.  Training applies branch ``d`` to domain slice ``d`` via ``vmap``
over the stacked stats; eval applies the ``eval_domain`` branch to the whole
(domain-axis-free) batch.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as fnn

from dwt_tpu.ops.batch_norm import BatchNormStats, batch_norm, init_batch_norm_stats
from dwt_tpu.ops.whitening import (
    WHITEN_CACHE_COL,
    AxisName,
    WhiteningStats,
    get_whitener,
    group_whiten,
)


def merge_domains(x: jax.Array) -> jax.Array:
    """``[D, N, ...] -> [D*N, ...]`` for the dense/conv compute path."""
    return x.reshape((x.shape[0] * x.shape[1],) + x.shape[2:])


def split_domains(x: jax.Array, num_domains: int) -> jax.Array:
    """``[D*N, ...] -> [D, N, ...]`` for the norm sites."""
    return x.reshape((num_domains, x.shape[0] // num_domains) + x.shape[1:])


def apply_domain_norm(x: jax.Array, norm, train: bool, num_domains: int):
    """Apply a domain norm to a merged ``[D*N, ...]`` training batch (or a
    plain eval batch): split to the domain layout, normalize, re-merge."""
    if train:
        return merge_domains(norm(split_domains(x, num_domains), train))
    return norm(x, train)


def _check_train_input(x: jax.Array, num_domains: int, name: str) -> None:
    if x.shape[0] != num_domains:
        raise ValueError(
            f"{name}: training input must carry a leading domain axis of "
            f"size num_domains={num_domains}; got shape {x.shape}"
        )


class DomainWhiten(fnn.Module):
    """``num_domains`` grouped-whitening branches sharing one affine.

    Train input ``[D, N, ..., C]`` → branch ``d`` whitens slice ``d`` with its
    own running stats (all EMAs advance).  Eval input ``[N, ..., C]`` →
    ``eval_domain``'s running stats whiten everything, no state change —
    the reference's target-branch eval routing (``usps_mnist.py:258-277``).

    ``use_affine=True`` matches the models' shared ``gamma/beta`` applied
    after the branch concat (``usps_mnist.py:202-203``,
    ``resnet50_dwt_mec_officehome.py:55-57``) — affine after concat and
    affine per branch are the same computation.
    """

    features: int
    group_size: int
    num_domains: int = 2
    eval_domain: int = 1
    momentum: float = 0.1
    eps: float = 1e-3
    use_affine: bool = True
    axis_name: Optional[AxisName] = None
    # Route through the Pallas two-pass kernels (ops/pallas_whitening.py).
    # Single-chip only: the kernel has no cross-replica moment pmean, so it
    # cannot be combined with ``axis_name`` (data parallelism).
    use_pallas: bool = False
    # Numerics backend (--whitener): cholesky | newton_schulz | swbn.
    # Stats structure follows the backend (swbn adds the tracked matrix),
    # so checkpoints are per-backend artifacts.
    whitener: str = "cholesky"

    @fnn.compact
    def __call__(self, x: jax.Array, train: bool) -> jax.Array:
        whitener = get_whitener(self.whitener)
        if self.use_pallas and self.axis_name is not None:
            raise ValueError(
                "DomainWhiten(use_pallas=True) is single-chip: the Pallas "
                "kernel computes local moments only and cannot reproduce "
                "the cross-replica pmean that axis_name requires"
            )
        if self.use_pallas and whitener.matrix_from_cov is None:
            raise ValueError(
                "DomainWhiten(use_pallas=True) supports factorizing "
                "whiteners only: the Pallas seam has no online "
                f"whitening-matrix state update ({self.whitener!r})"
            )
        proto = whitener.init_stats(self.features, self.group_size)
        stats_var = self.variable(
            "batch_stats",
            "whitening",
            lambda: jax.tree.map(
                lambda a: jnp.tile(a, (self.num_domains,) + (1,) * a.ndim), proto
            ),
        )
        stats: WhiteningStats = stats_var.value

        if train:
            _check_train_input(x, self.num_domains, self.name or "DomainWhiten")
            if self.use_pallas:
                from dwt_tpu.ops.pallas_whitening import pallas_group_whiten

                # Static unrolled domain loop (D is 2-3): pallas_call +
                # custom_vjp compose more robustly unrolled than vmapped.
                outs = [
                    pallas_group_whiten(
                        x[d],
                        jax.tree.map(lambda a, d=d: a[d], stats),
                        group_size=self.group_size,
                        train=True,
                        momentum=self.momentum,
                        eps=self.eps,
                        whitener=self.whitener,
                    )
                    for d in range(self.num_domains)
                ]
                y = jnp.stack([o[0] for o in outs])
                new_stats = jax.tree.map(
                    lambda *leaves: jnp.stack(leaves), *[o[1] for o in outs]
                )
            else:
                whiten = partial(
                    group_whiten,
                    group_size=self.group_size,
                    train=True,
                    momentum=self.momentum,
                    eps=self.eps,
                    axis_name=self.axis_name,
                    whitener=whitener,
                )
                y, new_stats = jax.vmap(whiten)(x, stats)
            if not self.is_initializing():
                stats_var.value = new_stats
        else:
            branch = jax.tree.map(lambda a: a[self.eval_domain], stats)
            if self.use_pallas:
                from dwt_tpu.ops.pallas_whitening import pallas_group_whiten

                y, _ = pallas_group_whiten(
                    x,
                    branch,
                    group_size=self.group_size,
                    train=False,
                    eps=self.eps,
                    whitener=self.whitener,
                )
            else:
                # Once-per-pass precomputed eval matrix (ops.whitening.
                # build_whiten_cache, threaded by EvalPipeline); absent →
                # factorize from the running stats as before.
                cached = (
                    self.get_variable(WHITEN_CACHE_COL, "w")
                    if self.has_variable(WHITEN_CACHE_COL, "w")
                    else None
                )
                y, _ = group_whiten(
                    x,
                    branch,
                    group_size=self.group_size,
                    train=False,
                    eps=self.eps,
                    whitener=whitener,
                    eval_matrix=cached,
                )

        if self.use_affine:
            gamma = self.param(
                "gamma", fnn.initializers.ones, (self.features,), jnp.float32
            )
            beta = self.param(
                "beta", fnn.initializers.zeros, (self.features,), jnp.float32
            )
            y = y * gamma.astype(y.dtype) + beta.astype(y.dtype)
        return y


class DomainBatchNorm(fnn.Module):
    """``num_domains`` stat-injectable BN branches sharing one affine.

    The functional analogue of the reference's paired ``bns*/bnt*``
    ``BatchNorm1d(affine=False)`` sites with shared ``gamma/beta``
    (``usps_mnist.py:214-228``) and the ResNet BN triples
    (``resnet50_dwt_mec_officehome.py:91-105``).  "Stat injection" (the whole
    reason the reference vendors BN) is just overwriting the ``batch_stats``
    collection — see ``dwt_tpu.convert``.
    """

    features: int
    num_domains: int = 2
    eval_domain: int = 1
    momentum: Optional[float] = 0.1
    eps: float = 1e-5
    use_affine: bool = True
    axis_name: Optional[AxisName] = None

    @fnn.compact
    def __call__(self, x: jax.Array, train: bool) -> jax.Array:
        proto = init_batch_norm_stats(self.features)
        stats_var = self.variable(
            "batch_stats",
            "bn",
            lambda: jax.tree.map(
                lambda a: jnp.tile(a, (self.num_domains,) + (1,) * a.ndim), proto
            ),
        )
        stats: BatchNormStats = stats_var.value

        if train:
            _check_train_input(x, self.num_domains, self.name or "DomainBatchNorm")
            bn = partial(
                batch_norm,
                train=True,
                momentum=self.momentum,
                eps=self.eps,
                axis_name=self.axis_name,
            )
            y, new_stats = jax.vmap(bn)(x, stats)
            if not self.is_initializing():
                stats_var.value = new_stats
        else:
            branch = jax.tree.map(lambda a: a[self.eval_domain], stats)
            y, _ = batch_norm(x, branch, train=False, eps=self.eps)

        if self.use_affine:
            gamma = self.param(
                "gamma", fnn.initializers.ones, (self.features,), jnp.float32
            )
            beta = self.param(
                "beta", fnn.initializers.zeros, (self.features,), jnp.float32
            )
            y = y * gamma.astype(y.dtype) + beta.astype(y.dtype)
        return y
