"""``dwt-sweep`` — the preemptible multi-run sweep entry point.

One invocation drives the whole OfficeHome pair matrix as supervised
training subprocesses over bounded job slots::

    dwt-sweep --sweep_root /runs/officehome --slots 4 \\
        --pairs Art:Clipart,Art:Product,... \\
        -- --synthetic --arch tiny --num_iters 100 ...

Everything after ``--`` is passed verbatim to each training job (the
fleet CLI's idiom); the supervisor owns the per-pair plumbing flags
(``--ckpt_dir``, ``--metrics_jsonl``, ``--results_json``,
``--preempt_notice_file``, ``--blob_store``, ``--metrics_port``), so
passing those after ``--`` is an error.

Relaunch is the same command line: the journal at
``<sweep_root>/sweep.json`` tells the new supervisor which pairs are
done, which jobs still run (adopted), and which to reschedule.  Exit
code: 0 when every pair completed (and the verdict table, if given,
passed); 1 when any pair was quarantined or a verdict failed.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import sys
from typing import List, Optional, Sequence

from dwt_tpu.sweep.supervisor import JobSpec, SweepSupervisor

log = logging.getLogger(__name__)

# Plumbing the supervisor owns; a user value would be silently
# overridden per pair, so reject it loudly instead.
_RESERVED_JOB_FLAGS = (
    "--ckpt_dir", "--metrics_jsonl", "--results_json",
    "--preempt_notice_file", "--blob_store", "--metrics_port",
    "--pairs", "--expect_table", "--expect_accuracy",
)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="dwt-sweep",
        description="preemptible multi-run sweep supervisor "
                    "(job args after --)",
    )
    p.add_argument("--sweep_root", type=str, required=True,
                   help="root dir: journal, per-pair run dirs, shared "
                        "blob store")
    p.add_argument("--domains", type=str,
                   default="Art,Clipart,Product,RealWorld",
                   help="comma-separated domain names")
    p.add_argument("--pairs", type=str, default=None,
                   help='subset like "Art:Clipart,Product:Art" '
                        "(default: all ordered pairs)")
    p.add_argument("--slots", type=int, default=2,
                   help="concurrent training jobs")
    p.add_argument("--job_max_respawns", type=int, default=2,
                   help="crashes per pair before quarantine "
                        "(preemption resumes are never charged)")
    p.add_argument("--job_backoff_s", type=float, default=2.0,
                   help="base crash-respawn backoff; attempt k waits "
                        "backoff * 2^(k-1)")
    p.add_argument("--poll_interval_s", type=float, default=1.0)
    p.add_argument("--job_stall_timeout_s", type=float, default=0.0,
                   help="SIGKILL a job silent (no metrics JSONL "
                        "activity) this long; 0 disables")
    p.add_argument("--blob_store", type=str, default=None,
                   help="shared CAS blob store for every run "
                        "(default <sweep_root>/blobs); 'none' gives "
                        "each run a private store")
    p.add_argument("--gc_every_polls", type=int, default=120,
                   help="cross-run shared-store GC cadence in poll "
                        "ticks; 0 = only once at sweep end")
    p.add_argument("--gc_min_age_s", type=float, default=None,
                   help="override the store's GC age guard (tests)")
    p.add_argument("--results_json", type=str, default=None,
                   help="aggregate per-pair accuracies here "
                        "(default <sweep_root>/results.json)")
    p.add_argument("--expect_table", type=str, default=None,
                   help="JSON of per-pair accuracy targets; verdicts "
                        "are evaluated over COMPLETED pairs after the "
                        "sweep")
    p.add_argument("--tolerance", type=float, default=1.0,
                   help="verdict tolerance in accuracy points")
    p.add_argument("--metrics_port", type=int, default=None,
                   help="serve the aggregated /metrics (supervisor + "
                        "every job under a pair label); 0 = ephemeral")
    p.add_argument("--alert_rules", type=str, default=None,
                   help="alert rules JSON evaluated against the "
                        "supervisor registry each poll")
    return p


def parse_pairs(domains: str, pairs: Optional[str]) -> List[tuple]:
    """The sweep CLI's own pair parsing — same grammar as
    ``officehome_sweep --pairs`` but independent of that parser (the
    supervisor must not construct a training argparser just to learn
    its matrix)."""
    names = [d.strip() for d in domains.split(",") if d.strip()]
    if pairs:
        out = []
        for item in pairs.split(","):
            item = item.strip()
            if not item:
                continue
            if ":" not in item:
                raise SystemExit(
                    f'--pairs entries must be "Source:Target"; got {item!r}'
                )
            s, t = item.split(":", 1)
            out.append((s.strip(), t.strip()))
    else:
        import itertools

        out = [(s, t) for s, t in itertools.permutations(names, 2)]
    if len(set(out)) != len(out):
        raise SystemExit(f"--pairs contains duplicates: {out}")
    if not out:
        raise SystemExit("empty pair matrix")
    return out


def make_argv_fn(job_argv: Sequence[str], blob_store: Optional[str],
                 python: str = sys.executable):
    """Build each pair's training command line: the single-pair
    ``officehome_sweep`` invocation with the supervisor-owned plumbing
    flags pointed into the pair's run dir."""

    def argv_fn(spec: JobSpec) -> List[str]:
        argv = [
            python, "-m", "dwt_tpu.cli.officehome_sweep",
            "--pairs", f"{spec.source}:{spec.target}",
            "--results_json", spec.result_json,
            "--ckpt_dir", spec.ckpt_base,
            "--metrics_jsonl", spec.metrics_base,
            "--preempt_notice_file", spec.notice_file,
            "--metrics_port", "0",
        ]
        if blob_store:
            argv += ["--ckpt_format", "delta", "--blob_store", blob_store]
        return argv + list(job_argv)

    return argv_fn


def _write_aggregate(path: str, payload: dict) -> None:
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=2)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def main(argv: Optional[Sequence[str]] = None) -> int:
    logging.basicConfig(
        level=logging.INFO,
        format="%(asctime)s %(levelname)s %(name)s: %(message)s",
    )
    argv = list(sys.argv[1:] if argv is None else argv)
    if "--" in argv:
        split = argv.index("--")
        own, job_argv = argv[:split], argv[split + 1:]
    else:
        own, job_argv = argv, []
    args = build_parser().parse_args(own)

    clash = sorted(set(_RESERVED_JOB_FLAGS) & set(job_argv))
    if clash:
        raise SystemExit(
            f"dwt-sweep owns {clash} (set per pair); configure the sweep "
            "with its own flags before the --"
        )

    pairs = parse_pairs(args.domains, args.pairs)
    sweep_root = os.path.abspath(args.sweep_root)
    if args.blob_store and args.blob_store.lower() == "none":
        blob_store = None
    else:
        blob_store = os.path.abspath(
            args.blob_store or os.path.join(sweep_root, "blobs")
        )

    expected = None
    if args.expect_table:
        from dwt_tpu.utils import load_expect_table

        expected = load_expect_table(args.expect_table)
        planned = {f"{s}->{t}" for s, t in pairs}
        unknown = sorted(
            k for k, v in expected.items()
            if v is not None and k not in planned
        )
        if unknown:
            raise SystemExit(
                f"--expect_table entries match no planned pair: {unknown} "
                f"(planned: {sorted(planned)})"
            )

    sup = SweepSupervisor(
        pairs, sweep_root, make_argv_fn(job_argv, blob_store),
        slots=args.slots,
        job_max_respawns=args.job_max_respawns,
        backoff_s=args.job_backoff_s,
        poll_interval_s=args.poll_interval_s,
        stall_timeout_s=args.job_stall_timeout_s,
        blob_store=blob_store,
        gc_every_polls=args.gc_every_polls,
        gc_min_age_s=args.gc_min_age_s,
        alert_rules=args.alert_rules,
        metrics_port=args.metrics_port,
    )
    summary = sup.run()

    for pair, acc in sorted(summary["pairs"].items()):
        print(f"[sweep] {pair}: {acc:.2f}")
    for tag, reason in sorted(summary["quarantined"].items()):
        print(f"[sweep] QUARANTINED {tag}: {reason}")
    print(f"[sweep] completed {summary['completed']}/{summary['total']} "
          f"mean={summary['mean']:.2f}")

    failed = bool(summary["quarantined"])
    if expected is not None and summary["pairs"]:
        from dwt_tpu.utils import sweep_verdicts

        verdicts = sweep_verdicts(summary["pairs"], expected,
                                  args.tolerance)
        summary["verdicts"] = verdicts
        for pair, v in verdicts["pairs"].items():
            if v.get("skipped"):
                print(f"[verdict] {pair}: actual={v['actual']:.2f} "
                      "(no expectation)")
            else:
                status = "OK" if v["ok"] else "FAIL"
                print(f"[verdict] {pair}: actual={v['actual']:.2f} "
                      f"expected={v['expected']:.2f} Δ={v['delta']:+.2f} "
                      f"(±{v['tolerance']}) {status}")
        if verdicts["all_ok"] is False:
            failed = True

    results_json = args.results_json or os.path.join(
        sweep_root, "results.json"
    )
    _write_aggregate(results_json, summary)

    if summary["drained"]:
        # A drained supervisor exits 0 like a preempted job: parked in
        # good order, relaunch to continue.
        print("[sweep] drained (supervisor preempted); relaunch the same "
              "command to continue")
        return 0
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
