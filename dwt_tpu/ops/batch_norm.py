"""Stat-injectable batch normalization as a pure functional op.

Re-provides the behavior of the reference's vendored BN
(``utils/batch_norm.py:14-88``) — whose one reason to exist is that running
buffers can be *injected* (seeded from a checkpoint) — in functional form:
stats are explicit inputs/outputs, so "injection" is just passing a different
``BatchNormStats`` value. Semantics matched:

* normalization uses the biased batch variance in training and the running
  variance in eval (``batch_norm.py:66-69`` → ``F.batch_norm`` semantics);
* the running-variance EMA accumulates the UNBIASED batch variance
  (torch ``F.batch_norm`` internal update convention);
* EMA convention ``running <- momentum*new + (1-momentum)*running`` with
  momentum weighting the new value (``batch_norm.py:114-120`` docstring);
* ``momentum=None`` selects the cumulative-average mode driven by
  ``num_batches_tracked`` (``batch_norm.py:61-64``);
* affine γ/β are NOT part of this op — the models share one γ/β across
  domain branches (e.g. ``usps_mnist.py:214-215`` pairs with shared
  ``gamma3/beta3``), so the affine lives in the module layer.

Works on any channels-last input (``[N, C]`` or ``[N, H, W, C]``): moments
reduce over all leading axes. ``axis_name`` gives cross-replica pmean moments
for data parallelism (SURVEY §5 distributed backend note).
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from dwt_tpu.ops.whitening import AxisName


class BatchNormStats(NamedTuple):
    mean: jax.Array  # [C] float32
    var: jax.Array  # [C] float32
    count: jax.Array  # [] int32 — num_batches_tracked (cumulative mode)


def init_batch_norm_stats(num_features: int, dtype=jnp.float32) -> BatchNormStats:
    return BatchNormStats(
        mean=jnp.zeros((num_features,), dtype),
        var=jnp.ones((num_features,), dtype),
        count=jnp.zeros((), jnp.int32),
    )


def _normalize(x, xf, m, var, eps):
    """``(x - m) * rsqrt(var + eps)`` with f32 statistics.

    f32 activations use the exact centered form.  Lower-precision
    activations (bf16) get the scale/bias folding ``x*s + (-m*s)`` applied
    in the activation dtype — per-channel f32 scalars, bf16 elementwise, the
    same recipe Flax's own BatchNorm uses — so the elementwise chain stays
    half-width instead of materializing an f32 copy of the activation.
    """
    scale = lax.rsqrt(var + eps)
    if x.dtype == xf.dtype:
        return (xf - m) * scale
    return x * scale.astype(x.dtype) + (-(m * scale)).astype(x.dtype)


def batch_norm(
    x: jax.Array,
    stats: BatchNormStats,
    *,
    train: bool,
    momentum: Optional[float] = 0.1,
    eps: float = 1e-5,
    axis_name: Optional[AxisName] = None,
) -> Tuple[jax.Array, BatchNormStats]:
    """Normalize channels-last ``x``; returns ``(y, new_stats)``.

    ``momentum=None`` → cumulative average factor ``1/count`` like the
    reference's ``batch_norm.py:61-64``.
    """
    xf = x.astype(jnp.promote_types(x.dtype, jnp.float32))
    if train:
        reduce_axes = tuple(range(x.ndim - 1))
        n = 1
        for a in reduce_axes:
            n *= x.shape[a]
        m = jnp.mean(xf, axis=reduce_axes)
        msq = jnp.mean(jnp.square(xf), axis=reduce_axes)
        if axis_name is not None:
            m = lax.pmean(m, axis_name)
            msq = lax.pmean(msq, axis_name)
            n = n * lax.psum(1, axis_name)
        var = msq - jnp.square(m)  # biased — used for normalization
        y = _normalize(x, xf, m, var, eps)

        count = stats.count + 1
        if momentum is None:
            factor = 1.0 / count.astype(xf.dtype)
        else:
            factor = jnp.asarray(momentum, xf.dtype)
        # Unbiased variance feeds the EMA (torch F.batch_norm convention).
        # The EMA computes in xf's (promoted) precision but is cast back to
        # the stored stats dtype: f64 activations must not flip the stats
        # pytree to f64 mid-training — a dtype change recompiles jit and
        # breaks the lax.scan carry of make_scanned_step under x64.
        unbiased = var * (n / max(n - 1, 1))
        new_stats = BatchNormStats(
            mean=(
                factor * lax.stop_gradient(m) + (1.0 - factor) * stats.mean
            ).astype(stats.mean.dtype),
            var=(
                factor * lax.stop_gradient(unbiased)
                + (1.0 - factor) * stats.var
            ).astype(stats.var.dtype),
            count=count,
        )
        return y.astype(x.dtype), new_stats
    else:
        y = _normalize(x, xf, stats.mean, stats.var, eps)
        return y.astype(x.dtype), stats
