"""Sweep control plane: journal atomicity, relaunch adoption policy,
crash quarantine, preemption-as-free-reschedule, and the chaos
acceptance for the whole supervised matrix.

Tier-1 splits two ways:

* **journal + supervisor units** — in-process, with fake jobs
  (``python -c`` scripts that crash, park, or write a result) standing
  in for training: scheduling policy is independent of what the job
  computes, so these run in seconds;
* **one real-CLI chaos smoke** — a 2-pair synthetic OfficeHome sweep
  through ``dwt-sweep`` with a preemption injected into one pair:
  notice → SIGTERM → save-and-exit-0 → free reschedule → both pairs
  complete.

The composed-fault acceptance (job SIGKILL mid-save + preemption +
supervisor SIGKILL mid-schedule + concurrent cross-run GC, accuracies
equal an undisturbed sweep's, ``ckpt_fsck --store`` zero missing) and
the drain/relaunch case are slow-marked — they run several real
training subprocesses end to end.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from dwt_tpu.resilience import inject
from dwt_tpu.sweep import journal as jnl
from dwt_tpu.sweep.cli import make_argv_fn, parse_pairs
from dwt_tpu.sweep.journal import (
    SweepJournal,
    decide_adoption,
    job_process_alive,
)
from dwt_tpu.sweep.supervisor import JobSpec, SweepSupervisor

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# The ~45s synthetic OfficeHome pair config every real-CLI sweep case
# trains per pair: tiny arch, 40 iters (~20s compile + ~20s of real
# stepping — wide enough that an injected mid-train preemption reliably
# lands between the first flushed train record and the finish line).
_TINY_JOB = (
    "--synthetic", "--synthetic_size", "12", "--arch", "tiny",
    "--img_crop_size", "32", "--num_classes", "5",
    "--source_batch_size", "6", "--test_batch_size", "6",
    "--num_iters", "40", "--check_acc_step", "20",
    "--stat_collection_passes", "1", "--log_interval", "1",
    "--group_size", "4", "--ckpt_every_iters", "10",
)


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    inject.disarm()


# ------------------------------------------------------------- journal


def test_journal_update_is_atomic_and_durable(tmp_path):
    path = str(tmp_path / "sweep.json")
    j = SweepJournal(path)
    j.ensure_pairs([("A", "B"), ("B", "A")],
                   lambda tag: str(tmp_path / tag))
    j.update("A2B", status=jnl.RUNNING, pid=123, attempts=1)
    # No tmp residue after the atomic replace...
    assert [n for n in os.listdir(tmp_path) if ".tmp-" in n] == []
    # ...and a fresh load (the relaunch) reads exactly the last update.
    j2 = SweepJournal.load(path)
    assert j2.pairs["A2B"]["status"] == jnl.RUNNING
    assert j2.pairs["A2B"]["pid"] == 123
    assert j2.pairs["B2A"]["status"] == jnl.PENDING


def test_journal_refuses_unreadable_and_stale_matrix(tmp_path):
    path = str(tmp_path / "sweep.json")
    with open(path, "w") as f:
        f.write("{not json")
    with pytest.raises(RuntimeError, match="refusing to overwrite"):
        SweepJournal.load(path)
    os.remove(path)
    j = SweepJournal(path)
    j.ensure_pairs([("A", "B")], lambda tag: str(tmp_path / tag))
    j2 = SweepJournal.load(path)
    with pytest.raises(RuntimeError, match="different --pairs"):
        j2.ensure_pairs([("X", "Y")], lambda tag: str(tmp_path / tag))


def test_adoption_policy_adopt_vs_reschedule(tmp_path):
    run_dir = str(tmp_path / "A2B")
    entry = {"status": jnl.RUNNING, "pid": 4242, "run_dir": run_dir}

    def alive_with_token(pid, token):
        return pid == 4242 and token == run_dir

    assert decide_adoption(entry, alive=alive_with_token) == "adopt"
    # Dead (or recycled) pid → reschedule.
    assert decide_adoption(entry, alive=lambda p, t: False) == "reschedule"
    # Journal-before-spawn death: running with no pid recorded.
    assert decide_adoption(
        {"status": jnl.RUNNING, "pid": None, "run_dir": run_dir}
    ) == "reschedule"
    # Settled entries are not the relaunch's business.
    for status in (jnl.PENDING, jnl.DONE, jnl.QUARANTINED):
        assert decide_adoption({"status": status, "pid": 4242,
                                "run_dir": run_dir}) == "keep"


def test_job_process_alive_checks_cmdline_token(tmp_path):
    token = str(tmp_path / "some_run_dir")
    proc = subprocess.Popen(
        [sys.executable, "-c", "import time; time.sleep(60)", token]
    )
    try:
        # Brief fork→exec race: /proc/<pid>/cmdline shows the child's
        # argv only once exec lands.  Irrelevant to the real adoption
        # path (a relaunch inspects jobs spawned long before), so the
        # test just waits it out.
        deadline = time.monotonic() + 5
        while (not job_process_alive(proc.pid, token)
               and time.monotonic() < deadline):
            time.sleep(0.05)
        assert job_process_alive(proc.pid, token)
        # A live pid whose cmdline does NOT carry the run dir is pid
        # reuse, not our job.
        assert not job_process_alive(proc.pid, "/definitely/not/there")
    finally:
        proc.kill()
        proc.wait()
    assert not job_process_alive(proc.pid, token)


# ------------------------------------------- supervisor with fake jobs

_FAST = dict(poll_interval_s=0.02, backoff_s=0.01)


def _ok_job(spec: JobSpec):
    """Fake training: immediately writes the pair's result."""
    code = (
        "import json, sys\n"
        "json.dump({'pairs': {sys.argv[2]: 1.0}}, open(sys.argv[1], 'w'))\n"
    )
    return [sys.executable, "-c", code,
            spec.result_json, spec.pair_key, spec.run_dir]


def _crash_job(spec: JobSpec):
    return [sys.executable, "-c", "import sys; sys.exit(3)", spec.run_dir]


def _preempt_once_job(spec: JobSpec):
    """First spawn: logs a ``preempt`` record and exits 0 (the loops'
    save-and-exit contract).  Second spawn: finishes."""
    code = (
        "import json, os, sys\n"
        "run, res, key, metrics = sys.argv[1:5]\n"
        "marker = os.path.join(run, 'preempted_once')\n"
        "if not os.path.exists(marker):\n"
        "    open(marker, 'w').close()\n"
        "    with open(metrics, 'a') as f:\n"
        "        f.write(json.dumps({'kind': 'preempt', 'step': 1}) + '\\n')\n"
        "    sys.exit(0)\n"
        "json.dump({'pairs': {key: 0.5}}, open(res, 'w'))\n"
    )
    return [sys.executable, "-c", code, spec.run_dir, spec.result_json,
            spec.pair_key, spec.metrics_jsonl]


def test_supervisor_runs_matrix_over_bounded_slots(tmp_path):
    sup = SweepSupervisor(
        [("A", "B"), ("B", "A"), ("A", "C")], str(tmp_path), _ok_job,
        slots=2, **_FAST,
    )
    summary = sup.run()
    assert summary["completed"] == 3 and not summary["quarantined"]
    assert summary["pairs"] == {"A->B": 1.0, "B->A": 1.0, "A->C": 1.0}
    # The journal on disk agrees — it IS the result of record.
    j = SweepJournal.load(str(tmp_path / jnl.JOURNAL_NAME))
    assert j.all_settled()


def test_supervisor_quarantines_repeated_crasher_matrix_completes(tmp_path):
    def argv_fn(spec):
        return _crash_job(spec) if spec.tag == "A2B" else _ok_job(spec)

    sup = SweepSupervisor(
        [("A", "B"), ("B", "A")], str(tmp_path), argv_fn,
        slots=2, job_max_respawns=2, **_FAST,
    )
    summary = sup.run()
    # The crasher burned its budget (2 crashes) and was quarantined; the
    # healthy pair still completed — one bad pair must not sink the sweep.
    assert list(summary["quarantined"]) == ["A2B"]
    assert "crash" in summary["quarantined"]["A2B"]
    assert summary["pairs"] == {"B->A": 1.0}
    assert summary["respawns"] == {"A2B": 2}
    entry = sup.journal.pairs["A2B"]
    assert entry["status"] == jnl.QUARANTINED and entry["crashes"] == 2


def test_supervisor_preemption_is_free_reschedule(tmp_path):
    sup = SweepSupervisor(
        [("A", "B")], str(tmp_path), _preempt_once_job, slots=1,
        job_max_respawns=1, **_FAST,
    )
    summary = sup.run()
    # exit 0 + preempt record = free: no crash charged, so even a budget
    # of 1 survives the reschedule, and the pair completes.
    assert summary["pairs"] == {"A->B": 0.5}
    assert summary["preempt_resumes"] == {"A2B": 1}
    assert summary["respawns"] == {} and not summary["quarantined"]


def test_relaunch_adopts_live_job_and_reschedules_dead_one(tmp_path):
    pairs = [("A", "B"), ("B", "A")]
    specs = {
        f"{s}2{t}": JobSpec(s, t, str(tmp_path / f"{s}2{t}"))
        for s, t in pairs
    }
    # Simulate the predecessor supervisor's wake: A2B's job is STILL
    # RUNNING (a real process, run-dir token on its cmdline, writes its
    # result then exits); B2A's job died with the predecessor.
    adopt_spec = specs["A2B"]
    os.makedirs(adopt_spec.run_dir)
    code = (
        "import json, sys, time\n"
        "json.dump({'pairs': {sys.argv[2]: 0.9}}, open(sys.argv[1], 'w'))\n"
        "time.sleep(0.4)\n"
    )
    orphan = subprocess.Popen(
        [sys.executable, "-c", code, adopt_spec.result_json,
         adopt_spec.pair_key, adopt_spec.run_dir]
    )
    try:
        j = SweepJournal(str(tmp_path / jnl.JOURNAL_NAME))
        j.ensure_pairs(pairs, lambda tag: specs[tag].run_dir)
        j.update("A2B", status=jnl.RUNNING, pid=orphan.pid, attempts=1)
        j.update("B2A", status=jnl.RUNNING, pid=None, attempts=1)

        sup = SweepSupervisor(pairs, str(tmp_path), _ok_job, slots=2,
                              **_FAST)
        summary = sup.run()
    finally:
        if orphan.poll() is None:
            orphan.kill()
        orphan.wait()
    # Adopted job's own result (0.9) survived — it was NOT respawned
    # (a respawn would have run _ok_job and overwritten with 1.0);
    # the pid-less entry was rescheduled and completed normally.
    assert summary["pairs"] == {"A->B": 0.9, "B->A": 1.0}
    assert not summary["quarantined"]


def test_supervisor_stall_detection_kills_wedged_job(tmp_path):
    def wedged(spec):
        # Never writes metrics, never exits: the hung-compile shape.
        return [sys.executable, "-c", "import time; time.sleep(600)",
                spec.run_dir]

    sup = SweepSupervisor(
        [("A", "B")], str(tmp_path), wedged, slots=1,
        job_max_respawns=1, stall_timeout_s=0.3, **_FAST,
    )
    t0 = time.monotonic()
    summary = sup.run()
    # SIGKILLed for silence, charged as a crash, quarantined on budget
    # exhaustion — and nowhere near the job's own 600s.
    assert time.monotonic() - t0 < 60
    assert list(summary["quarantined"]) == ["A2B"]
    assert "stalled" in summary["quarantined"]["A2B"]


# ------------------------------------------------------------ cli bits


def test_parse_pairs_grammar():
    assert parse_pairs("A,B,C", None) == [
        ("A", "B"), ("A", "C"), ("B", "A"), ("B", "C"),
        ("C", "A"), ("C", "B"),
    ]
    assert parse_pairs("A,B", "A:B, B:A") == [("A", "B"), ("B", "A")]
    with pytest.raises(SystemExit):
        parse_pairs("A,B", "A-B")
    with pytest.raises(SystemExit):
        parse_pairs("A,B", "A:B,A:B")


def test_argv_fn_owns_plumbing_flags(tmp_path):
    spec = JobSpec("Art", "Clipart", str(tmp_path / "Art2Clipart"))
    argv = make_argv_fn(["--synthetic"], str(tmp_path / "blobs"))(spec)
    assert argv.count("--ckpt_dir") == 1
    assert spec.result_json in argv and spec.notice_file in argv
    assert "--blob_store" in argv and "--synthetic" in argv


# ----------------------------------------------------- real-CLI chaos


def _run_sweep(root, pairs, plan=None, timeout=420, extra=()):
    """One dwt-sweep subprocess over the tiny synthetic config."""
    argv = [
        sys.executable, "-m", "dwt_tpu.sweep.cli",
        "--sweep_root", str(root), "--pairs", pairs, "--slots", "2",
        "--poll_interval_s", "0.2", "--job_backoff_s", "0.5",
        *extra, "--", *_TINY_JOB,
    ]
    env = dict(os.environ)
    env.pop(inject.ENV_VAR, None)
    if plan is not None:
        env[inject.ENV_VAR] = json.dumps(plan)
    proc = subprocess.Popen(argv, cwd=REPO, env=env,
                            stdout=subprocess.PIPE, stderr=subprocess.STDOUT)
    try:
        out, _ = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.communicate()
        pytest.fail("sweep supervisor hung — the outcome the control "
                    "plane exists to prevent")
    return proc.returncode, out.decode(errors="replace")


def _sweep_results(root):
    with open(os.path.join(str(root), "results.json")) as f:
        return json.load(f)


@pytest.mark.slow  # 187s: heaviest tier-1 test (t1_budget headroom,
# PR-17 slow-mark round) — the preemption path keeps subprocess
# coverage via the faster supervisor unit tests above
def test_sweep_smoke_with_injected_preemption(tmp_path):
    """Tier-1 acceptance smoke: a 2-pair synthetic sweep with one pair
    preempted mid-run (notice → SIGTERM → save-and-exit-0) completes
    every pair, records the preemption as a FREE reschedule, and exits
    0."""
    rc, out = _run_sweep(
        tmp_path / "sweep", "Art:Clipart,Clipart:Art",
        plan={"sweep_preempt_pairs": ["Art2Clipart"]},
    )
    assert rc == 0, out
    res = _sweep_results(tmp_path / "sweep")
    assert res["completed"] == 2 and not res["quarantined"], out
    assert set(res["pairs"]) == {"Art->Clipart", "Clipart->Art"}
    # The preempted pair resumed for free: no crash respawn was charged.
    assert res["preempt_resumes"] == {"Art2Clipart": 1}, out
    assert res["respawns"] == {}, out
    # The preempted job parked through the save-and-exit contract: its
    # metrics JSONL carries the fsync'd preempt record.
    m = os.path.join(str(tmp_path / "sweep"), "Art2Clipart",
                     "metrics.Art2Clipart.jsonl")
    kinds = [json.loads(l).get("kind") for l in open(m)]
    assert "preempt" in kinds


@pytest.mark.slow
def test_sweep_composed_chaos_matches_undisturbed_accuracies(tmp_path):
    """THE acceptance case: one pair's job SIGKILLed mid-save, the other
    preempted, the supervisor itself SIGKILLed mid-schedule (journal
    written, spawn not yet issued), cross-run GC sweeping the shared
    store throughout — the relaunched supervisor adopts/reschedules per
    journal, every pair completes with accuracies IDENTICAL to an
    undisturbed sweep, and ``ckpt_fsck --store`` finds zero missing
    blobs (GC never ate a referenced one)."""
    gc_args = ("--gc_every_polls", "5", "--gc_min_age_s", "2")

    rc, out = _run_sweep(tmp_path / "calm", "Art:Clipart,Clipart:Art",
                         extra=gc_args)
    assert rc == 0, out
    calm = _sweep_results(tmp_path / "calm")
    assert calm["completed"] == 2, out

    # Disturbed pass 1: faults armed.  Schedule events 1 and 2 are the
    # initial spawns; event 3 is the first fault-driven reschedule — the
    # supervisor dies there with the journal claiming a spawn that never
    # happened.
    chaos_root = tmp_path / "chaos"
    plan = {
        "sweep_job_kill_mid_save": ["Art2Clipart"],
        "sweep_preempt_pairs": ["Clipart2Art"],
        "kill_supervisor_at_schedule": 3,
    }
    rc, out1 = _run_sweep(chaos_root, "Art:Clipart,Clipart:Art",
                          plan=plan, extra=gc_args)
    assert rc == -signal.SIGKILL, out1
    journal = SweepJournal.load(
        os.path.join(str(chaos_root), jnl.JOURNAL_NAME)
    )
    assert not journal.all_settled()

    # Relaunch: same command, no faults.  Adopts whatever survived the
    # dead supervisor, reschedules the rest, finishes the matrix.
    rc, out2 = _run_sweep(chaos_root, "Art:Clipart,Clipart:Art",
                          extra=gc_args)
    assert rc == 0, out1 + out2
    chaos = _sweep_results(chaos_root)
    assert chaos["completed"] == 2 and not chaos["quarantined"], out2

    # Exact resume exactness, end to end: the battered sweep's
    # accuracies equal the calm sweep's, pair for pair.
    assert chaos["pairs"] == calm["pairs"], (out1, out2)

    # Store audit: every blob any run's manifests reference is present
    # and whole — concurrent GC swept only garbage.
    run_trees = [
        os.path.join(str(chaos_root), tag, "ckpt", tag)
        for tag in ("Art2Clipart", "Clipart2Art")
    ]
    fsck = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "ckpt_fsck.py"),
         "--store", os.path.join(str(chaos_root), "blobs"),
         *run_trees, "--json"],
        cwd=REPO, capture_output=True, text=True,
    )
    report = json.loads(fsck.stdout)
    assert report["blobs_missing"] == 0, fsck.stdout
    assert report["blobs_on_disk"] > 0


@pytest.mark.slow
def test_sweep_supervisor_drain_and_relaunch(tmp_path):
    """Supervisor SIGTERM mid-sweep: it warns every job (notice file),
    waits out their save-and-exit-0, journals the matrix parked, and
    exits 0; the relaunch completes everything."""
    root = tmp_path / "sweep"
    argv = [
        sys.executable, "-m", "dwt_tpu.sweep.cli",
        "--sweep_root", str(root), "--pairs", "Art:Clipart,Clipart:Art",
        "--slots", "2", "--poll_interval_s", "0.2", "--", *_TINY_JOB,
    ]
    env = dict(os.environ)
    env.pop(inject.ENV_VAR, None)
    proc = subprocess.Popen(argv, cwd=REPO, env=env,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT)
    # Wait for both jobs to actually train (metrics files appear), then
    # preempt the SUPERVISOR.
    deadline = time.monotonic() + 240
    metrics = [
        os.path.join(str(root), tag, f"metrics.{tag}.jsonl")
        for tag in ("Art2Clipart", "Clipart2Art")
    ]
    while time.monotonic() < deadline:
        if all(os.path.exists(m) for m in metrics):
            break
        if proc.poll() is not None:
            break
        time.sleep(0.2)
    assert proc.poll() is None, proc.communicate()[0].decode()
    proc.send_signal(signal.SIGTERM)
    out, _ = proc.communicate(timeout=240)
    assert proc.returncode == 0, out.decode(errors="replace")

    rc, out2 = _run_sweep(root, "Art:Clipart,Clipart:Art")
    assert rc == 0, out2
    res = _sweep_results(root)
    assert res["completed"] == 2 and not res["quarantined"], out2
