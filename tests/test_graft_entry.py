"""The driver-entrypoint contracts: hijack-proof dryrun, lazy imports.

The TPU-relay startup hook (armed by ``PALLAS_AXON_POOL_IPS``) pins jax's
platform selection at the config level and hangs backend init when the
chip claim is wedged; ``__graft_entry__.dryrun_multichip`` must therefore
(a) never touch jax at import time, and (b) re-exec the mesh dryrun in a
subprocess with the var stripped and CPU forced.
"""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_graft_entry_import_is_jax_free():
    # Importing the module must not pull in jax — with the hook armed and a
    # wedged claim, any backend init in the parent would hang the driver.
    code = (
        "import sys; import __graft_entry__; "
        "assert 'jax' not in sys.modules, 'module import must stay jax-free'"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], cwd=REPO, capture_output=True,
        text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stderr


def test_dryrun_reexecs_clean_when_hijack_armed():
    # Arm the hook with an unroutable pool IP: the dryrun must still
    # complete by re-execing itself in a cleaned environment.
    env = dict(os.environ, PALLAS_AXON_POOL_IPS="203.0.113.1")
    proc = subprocess.run(
        [
            sys.executable,
            "-c",
            "import __graft_entry__ as g; g.dryrun_multichip(2)",
        ],
        cwd=REPO,
        env=env,
        capture_output=True,
        text=True,
        # Above dryrun_multichip's internal 900s re-exec timeout: on a
        # grandchild hang, subprocess.run's kill only reaps the direct
        # child and then blocks on the inherited pipes until the inner
        # timeout fires — a smaller value here would be ineffective anyway.
        timeout=1000,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "dryrun_multichip OK" in proc.stdout


@pytest.mark.slow
def test_dryrun_16_devices_covers_4_slices_and_consensus():
    """The widened dryrun: a 16-fake-device mesh must exercise BOTH the
    2x8 and 4x4 (dcn, data) layouts, the full (dcn, data, model) gspmd
    mesh with a forced gather and a restore-to-spec round trip (the
    sharding-rules engine end to end, ISSUE-9), plus the forced
    consensus allgather (the flag-vector collective the loops issue at
    step boundaries) — coverage beyond the 8-dev/2-slice corner.  Direct
    --dryrun subprocess (own XLA device count), no relay re-exec
    involved.  The cheap in-process gspmd smoke stays tier-1 in
    tests/test_sharding_plan.py."""
    env = {k: v for k, v in os.environ.items() if k != "PALLAS_AXON_POOL_IPS"}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=16"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "__graft_entry__.py"),
         "--dryrun", "16"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=900,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "dryrun_multichip OK: 16-device mesh" in proc.stdout
    assert "2x8 (dcn, data) mesh" in proc.stdout
    assert "4x4 (dcn, data) mesh" in proc.stdout
    assert "2x4x2 (dcn, data, model) gspmd mesh" in proc.stdout
    assert "gather + restore-to-spec verified" in proc.stdout
    assert "dryrun consensus OK" in proc.stdout
