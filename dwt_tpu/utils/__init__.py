"""dwt_tpu.utils — metrics logging, checkpoints, repro verdicts."""

from dwt_tpu.utils.metrics import MetricLogger
from dwt_tpu.utils.checkpoint import (
    is_valid_checkpoint,
    latest_step,
    restore_state,
    save_state,
    valid_steps,
)
from dwt_tpu.utils.repro import (
    accuracy_verdict,
    check_cli_accuracy,
    load_expect_table,
    sweep_verdicts,
)

__all__ = [
    "MetricLogger",
    "is_valid_checkpoint",
    "latest_step",
    "restore_state",
    "save_state",
    "valid_steps",
    "accuracy_verdict",
    "check_cli_accuracy",
    "load_expect_table",
    "sweep_verdicts",
]
