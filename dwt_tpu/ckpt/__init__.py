"""Content-addressed incremental checkpoint store (ISSUE-13).

``store.py`` holds the whole subsystem: the shared blob store (leaf
bytes keyed by digest), delta manifests chaining to a parent full save,
chain validation, refcounted blob GC, and the streaming restore that
reads each leaf straight from its blob onto its target sharding.
"""

from dwt_tpu.ckpt.store import (
    BLOBS_DIR,
    GC_MIN_AGE_S,
    blob_store_root,
    cas_invalid_reason,
    gc_blobs,
    promote_delta,
    resolve_leaves,
    restore_cas_state,
    restore_cas_tree,
    save_delta,
    stage_delta,
    tree_bytes,
)

__all__ = [
    "BLOBS_DIR",
    "GC_MIN_AGE_S",
    "blob_store_root",
    "cas_invalid_reason",
    "gc_blobs",
    "promote_delta",
    "resolve_leaves",
    "restore_cas_state",
    "restore_cas_tree",
    "save_delta",
    "stage_delta",
    "tree_bytes",
]
