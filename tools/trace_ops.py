"""Attribute step time per fused XLA op from a ``jax.profiler`` trace.

Reads the ``*.xplane.pb`` under a trace directory (written by
``tools/profile_step.py --trace DIR``) and prints a JSON report: total
device time, per-HLO-category rollup, and the top-N fused ops by summed
duration.  This is the measurement SURVEY §7 step 1 asks for before
hand-writing Pallas kernels ("measure first") — it answers *where* the
94.8 ms flagship step goes, without TensorBoard.

Parsing uses the XPlane protobuf bundled with the baked-in tensorflow
(``tensorflow.core.profiler.protobuf.xplane_pb2``); no network, no UI.

Usage: python tools/trace_ops.py /tmp/dwt_trace [--top 40] [--line "XLA Ops"]
"""

import argparse
import glob
import json
import os
from collections import defaultdict


def load_xspaces(trace_dir):
    from tensorflow.tsl.profiler.protobuf import xplane_pb2

    paths = sorted(
        glob.glob(
            os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True
        )
    )
    if not paths:
        raise SystemExit(f"no *.xplane.pb under {trace_dir}")
    spaces = []
    for p in paths:
        xs = xplane_pb2.XSpace()
        with open(p, "rb") as f:
            xs.ParseFromString(f.read())
        spaces.append((p, xs))
    return spaces


def device_planes(xspace):
    """TPU/accelerator planes if present, else the host plane (CPU runs)."""
    dev = [
        p
        for p in xspace.planes
        if p.name.startswith("/device:")
        and "CPU" not in p.name
        or "TPU" in p.name
    ]
    return dev or list(xspace.planes)


def aggregate(plane, line_filter=None):
    """Sum event durations per metadata name within matching lines."""
    meta = plane.event_metadata
    stat_meta = plane.stat_metadata
    per_op = defaultdict(int)
    per_category = defaultdict(int)
    op_category = {}
    for line in plane.lines:
        if line_filter and line_filter.lower() not in line.name.lower():
            continue
        for ev in line.events:
            md = meta.get(ev.metadata_id)
            name = md.name if md else f"id{ev.metadata_id}"
            per_op[name] += ev.duration_ps
            cat = None
            for st in ev.stats:
                sm = stat_meta.get(st.metadata_id)
                if sm and sm.name == "hlo_category":
                    cat = (
                        st.str_value
                        or stat_meta.get(st.ref_value).name
                        if st.ref_value
                        else st.str_value
                    )
            if cat is None and md is not None:
                for st in md.stats:
                    sm = stat_meta.get(st.metadata_id)
                    if sm and sm.name == "hlo_category":
                        cat = st.str_value or (
                            stat_meta.get(st.ref_value).name
                            if st.ref_value
                            else None
                        )
            op_category[name] = cat or "uncategorized"
    for name, ps in per_op.items():
        per_category[op_category[name]] += ps
    return per_op, per_category, op_category


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("trace_dir")
    ap.add_argument("--top", type=int, default=40)
    ap.add_argument(
        "--line",
        default=None,
        help="only lines whose name contains this (e.g. 'XLA Ops')",
    )
    ap.add_argument(
        "--list-lines", action="store_true", help="just list plane/line names"
    )
    args = ap.parse_args()

    spaces = load_xspaces(args.trace_dir)
    report = {"trace_dir": args.trace_dir, "planes": []}
    for path, xs in spaces:
        for plane in device_planes(xs):
            if args.list_lines:
                print(
                    json.dumps(
                        {
                            "file": os.path.basename(path),
                            "plane": plane.name,
                            "lines": [
                                {"name": ln.name, "events": len(ln.events)}
                                for ln in plane.lines
                            ],
                        }
                    )
                )
                continue
            per_op, per_cat, op_cat = aggregate(plane, args.line)
            total_ps = sum(per_op.values())
            if not total_ps:
                continue
            top = sorted(per_op.items(), key=lambda kv: -kv[1])[: args.top]
            report["planes"].append(
                {
                    "file": os.path.basename(path),
                    "plane": plane.name,
                    "total_ms": round(total_ps / 1e9, 3),
                    "categories_ms": {
                        k: round(v / 1e9, 3)
                        for k, v in sorted(
                            per_cat.items(), key=lambda kv: -kv[1]
                        )
                    },
                    "top_ops": [
                        {
                            "name": n,
                            "ms": round(ps / 1e9, 3),
                            "pct": round(100 * ps / total_ps, 2),
                            "category": op_cat[n],
                        }
                        for n, ps in top
                    ],
                }
            )
    if not args.list_lines:
        print(json.dumps(report, indent=1))


if __name__ == "__main__":
    main()
