"""Chaos matrix: every injected fault ends the run in one of exactly two
states — exit 0 with a resumable newest-valid checkpoint, or a clean
diagnosed halt (nonzero exit + written evidence) — NEVER a hang and never
a torn state a resume would trust.

Each case spawns the digits trainer as a real subprocess with a
``DWT_FAULT_PLAN`` armed in its environment (dwt_tpu/resilience/inject.py)
and asserts the contract from outside, the way a scheduler would see it.
The matrix (all single-process cases plus the 2-process consensus cases)
is slow-marked; one composed-fault smoke stays in tier-1.

Also here: the strict ``FaultPlan`` spec parsing tests — a fault plan
that silently injects nothing proves nothing, so bad/duplicate/
overlapping specs must raise, not no-op.
"""

import json
import os
import subprocess
import sys

import pytest
from test_distributed import _free_port

from dwt_tpu.resilience import WATCHDOG_EXIT_CODE, inject
from dwt_tpu.resilience.inject import FaultPlan
from dwt_tpu.utils.checkpoint import is_valid_checkpoint, latest_step, valid_steps

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Digits synthetic config every chaos case runs: 4 steps/epoch, periodic
# save every epoch.  Runs that must end by fault use epochs=500 (they
# never finish naturally inside the subprocess timeout — ending any other
# way than the expected one fails the case); runs that must COMPLETE
# override epochs.
_BASE_ARGS = (
    "--synthetic", "--synthetic_size", "32",
    "--source_batch_size", "8", "--target_batch_size", "8",
    "--test_batch_size", "16", "--group_size", "4",
    "--log_interval", "1", "--ckpt_every_epochs", "1",
)


@pytest.fixture(autouse=True)
def _disarm_faults():
    yield
    inject.disarm()


def _run_digits(tmp_path, plan, extra=(), timeout=300, env_extra=None,
                ck=None, jsonl=None):
    """Spawn the digits CLI with ``plan`` armed; kill-on-timeout enforces
    the matrix's no-hang guarantee from outside the process."""
    ck = ck or str(tmp_path / "ck")
    jsonl = jsonl or str(tmp_path / "m.jsonl")
    argv = [
        sys.executable, "-m", "dwt_tpu.cli.usps_mnist",
        *_BASE_ARGS, "--ckpt_dir", ck, "--metrics_jsonl", jsonl, *extra,
    ]
    env = dict(os.environ)
    env.update(env_extra or {})
    env[inject.ENV_VAR] = json.dumps(plan)
    proc = subprocess.Popen(
        argv, cwd=REPO, env=env,
        stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
    )
    try:
        _, stderr = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.communicate()
        pytest.fail(
            f"chaos run hung (plan={plan}) — the one outcome the matrix "
            "forbids"
        )
    return proc.returncode, ck, jsonl, stderr.decode(errors="replace")


def _kinds(jsonl):
    if not os.path.exists(jsonl):
        return []
    return [json.loads(l)["kind"] for l in open(jsonl).read().splitlines()]


def _assert_resumable(ck):
    """'Resumable' as resume sees it: a newest step that VALIDATES
    (manifest + recorded sizes) — not merely a directory that exists."""
    step = latest_step(ck)
    assert step is not None, f"no valid checkpoint under {ck}"
    assert is_valid_checkpoint(os.path.join(ck, str(step)))
    return step


# ----------------------------------------------------- tier-1 chaos smoke


def test_chaos_kill_mid_delta_promote_falls_back(tmp_path):
    """ISSUE-13 fault kind: SIGKILL inside the delta store's promote —
    after the blobs and staged manifest are durable, before the finalize
    rename.  The staged step must stay invisible (an incomplete chain is
    never a restore candidate), and the relaunch resumes from the
    previous finalized step — never a torn or mixed-generation restore.
    Also the delta format's CLI E2E: both runs save through the async
    delta writer."""
    delta_args = ("--ckpt_format", "delta", "--epochs", "3")
    # Saves land at steps 4, 8, 12; the kill hits the SECOND save (a
    # delta chained on the step-4 full) mid-promote.
    rc, ck, jsonl, _ = _run_digits(
        tmp_path, {"kill_mid_delta_promote": 8}, extra=delta_args,
    )
    assert rc == -9  # SIGKILLed from inside the writer's promote
    assert valid_steps(ck) == [4]
    # The stage survived as an invisible .tmp-cas dir, blobs durable.
    assert any(d.startswith(".tmp-cas-8") for d in os.listdir(ck))

    rc, ck, jsonl, stderr = _run_digits(tmp_path, {}, extra=delta_args)
    assert rc == 0, stderr[-2000:]
    kinds = _kinds(jsonl)
    assert "resume" in kinds
    resume = [
        json.loads(l) for l in open(jsonl).read().splitlines()
        if json.loads(l)["kind"] == "resume"
    ][0]
    assert resume["step"] == 4  # the previous finalized step, not the torn 8
    assert _assert_resumable(ck) == 12  # completed: 3 epochs * 4 steps
    with open(os.path.join(ck, "12", "manifest.json")) as f:
        assert json.load(f)["format"] == "cas_delta"


def test_chaos_smoke_composed_faults_exit0_resumable(tmp_path):
    """Fast tier-1 case, four fault kinds composed in ONE plan: a slow
    step (the watchdog must tolerate a transient stall), one flaky save
    write (the retry ladder must absorb it), a preemption NOTICE (the
    scheduler's advance warning → proactive save while training
    continues), then SIGTERM at a later boundary (the preemption path
    must exit 0 FAST — the proactively saved checkpoint is the resume
    source and no second checkpoint is written).  Ends with a validated,
    genuinely restorable checkpoint."""
    rc, ck, jsonl, stderr = _run_digits(
        tmp_path,
        plan={
            "slow_step_at": 2, "slow_step_s": 0.3,
            "io_error_saves": 1,
            "notice_at_step": 4,
            "sigterm_at_step": 6,
        },
        extra=("--epochs", "500", "--watchdog_timeout", "120"),
    )
    assert rc == 0, f"stderr tail: {stderr[-2000:]}"
    assert "notice_save" in _kinds(jsonl)
    assert "preempt" in _kinds(jsonl)
    step = _assert_resumable(ck)
    assert step == 4  # the proactive notice save, NOT the SIGTERM boundary
    # Fast exit: the SIGTERM path wrote no second checkpoint, and the
    # preempt record names the proactive save as the resume source.
    assert not os.path.isdir(os.path.join(ck, "6"))
    recs = [json.loads(l) for l in open(jsonl).read().splitlines()]
    pre = [r for r in recs if r["kind"] == "preempt"][-1]
    assert pre["step"] == 6 and pre["resume_step"] == 4

    # Prove "resumable" end-to-end: an in-process relaunch restores the
    # artifact (epochs == already-trained epochs -> restore + eval only).
    from dwt_tpu.cli.usps_mnist import main

    jsonl2 = str(tmp_path / "resume.jsonl")
    acc = main([
        *_BASE_ARGS, "--ckpt_dir", ck, "--metrics_jsonl", jsonl2,
        "--epochs", "1",
    ])
    assert 0.0 <= acc <= 100.0
    assert "resume" in _kinds(jsonl2)


def test_chaos_nan_with_harvest_depth_detects_within_depth(tmp_path):
    """ISSUE-14: nan_at_step composed with --harvest_depth 2.  The
    harvested guard's verdict is a flag delivered at most ring-depth
    dispatches late: the NaN at step 6 must be detected within 2 steps
    (the divergence record stamps both the bad step and the boundary
    that acted on it), the rollback must land a strictly pre-NaN
    checkpoint, and the run must still complete."""
    rc, ck, jsonl, stderr = _run_digits(
        tmp_path,
        plan={"nan_at_step": 6},
        extra=(
            "--epochs", "3", "--harvest_depth", "2",
            "--guard_policy", "rollback", "--guard_interval", "1",
        ),
    )
    assert rc == 0, f"stderr tail: {stderr[-2000:]}"
    recs = [json.loads(l) for l in open(jsonl).read().splitlines()]
    div = [r for r in recs if r["kind"] == "divergence"]
    assert div, "no divergence record"
    assert div[0]["step"] == 6  # the verdict names the BAD step...
    assert div[0]["detected_at"] - div[0]["step"] <= 2  # ...within depth
    rb = [r for r in recs if r["kind"] == "rollback"]
    assert rb and rb[0]["from_step"] == 6
    assert rb[0]["step"] < 6  # pre-NaN restore target (epoch-1 ckpt)
    assert _assert_resumable(ck) == 12  # trained to completion


def test_chaos_sigterm_drain_loses_no_records(tmp_path):
    """ISSUE-14: the preempt path drains the harvest ring inside the
    grace window — the metric stream shows EVERY executed step exactly
    once, in order, with its original stamp (nothing lost to in-flight
    entries, nothing duplicated by the drain), alongside the exit-0
    save-and-resume contract."""
    rc, ck, jsonl, stderr = _run_digits(
        tmp_path,
        plan={"sigterm_at_step": 6},
        extra=("--epochs", "500", "--harvest_depth", "2"),
    )
    assert rc == 0, f"stderr tail: {stderr[-2000:]}"
    recs = [json.loads(l) for l in open(jsonl).read().splitlines()]
    train_steps = [r["step"] for r in recs if r["kind"] == "train"]
    # log_interval 1: steps 1..6 ran before the boundary stop — each
    # logged exactly once, in order, despite 2 being in flight when the
    # SIGTERM's stop decision landed.
    assert train_steps == [1, 2, 3, 4, 5, 6]
    # The drain precedes the preempt narration on the stream.
    kinds = [r["kind"] for r in recs]
    assert kinds.index("preempt") > max(
        i for i, k in enumerate(kinds) if k == "train"
    )
    assert _assert_resumable(ck) == 6


# ----------------------------------------- exact mid-epoch resume (ISSUE-15)

# The digits chaos geometry: synthetic_size 32 / global batch 8 -> 4
# batches per epoch per stream, and the zipped loop consumes one batch
# per stream per step, so global batch index = epoch * 4 + cursor.
_STEPS_PER_EPOCH = 4


def _read_trail(trail_dir, role):
    """[(epoch, cursor, ids), ...] in production order (may contain
    positions produced-ahead by the prefetch thread but never trained)."""
    path = os.path.join(trail_dir, f"{role}.jsonl")
    if not os.path.exists(path):
        return []
    return [
        (r["epoch"], r["cursor"], r["ids"])
        for r in map(json.loads, open(path).read().splitlines())
    ]


@pytest.mark.slow  # 61s (t1_budget headroom, PR-17 slow-mark round);
# the drain/resume contract stays tier-1-covered by the sigterm-drain
# and kill-mid-promote chaos tests
def test_chaos_sigterm_mid_epoch_exact_resume(tmp_path):
    """Tentpole acceptance (ISSUE-15): a SIGTERM mid-epoch, then a
    relaunch, replays exactly the remaining batch-id sequence — no
    duplicate, no loss — byte-identical to an uninterrupted golden run,
    for every stream.  Proven from outside via the DWT_DATA_TRAIL
    batch-id trail: the resumed run's first produced batch is exactly
    the checkpoint's recorded cursor, and every resumed position's ids
    equal the golden run's."""
    gold_dir = str(tmp_path / "trail_gold")
    rc, _, _, stderr = _run_digits(
        tmp_path, {}, extra=("--epochs", "3"),
        env_extra={"DWT_DATA_TRAIL": gold_dir},
        ck=str(tmp_path / "gold_ck"), jsonl=str(tmp_path / "gold.jsonl"),
    )
    assert rc == 0, stderr[-2000:]

    kill_dir = str(tmp_path / "trail_kill")
    ck = str(tmp_path / "ck")
    rc, _, _, stderr = _run_digits(
        tmp_path, {"sigterm_at_step": 6}, extra=("--epochs", "500"),
        env_extra={"DWT_DATA_TRAIL": kill_dir}, ck=ck,
        jsonl=str(tmp_path / "kill.jsonl"),
    )
    assert rc == 0, stderr[-2000:]
    assert _assert_resumable(ck) == 6  # mid-epoch: epoch 1, cursor 2

    resume_dir = str(tmp_path / "trail_resume")
    rc, _, jsonl, stderr = _run_digits(
        tmp_path, {}, extra=("--epochs", "3"),
        env_extra={"DWT_DATA_TRAIL": resume_dir}, ck=ck,
        jsonl=str(tmp_path / "resume.jsonl"),
    )
    assert rc == 0, stderr[-2000:]
    recs = [json.loads(l) for l in open(jsonl).read().splitlines()]
    res = [r for r in recs if r["kind"] == "resume"][-1]
    assert res["step"] == 6 and res["data"] == "exact" and res["cursor"] == 2

    for role in ("source", "target"):
        golden = {(e, c): ids for e, c, ids in _read_trail(gold_dir, role)}
        resumed = _read_trail(resume_dir, role)
        assert resumed, f"no resumed trail for {role}"
        # The resume opens EXACTLY at the recorded cursor — the very
        # first produced batch is global index 6 = (epoch 1, cursor 2):
        # nothing before it is replayed (no duplicate)...
        assert (resumed[0][0], resumed[0][1]) == (1, 2), role
        # ...and the remaining sequence is complete and contiguous (no
        # loss), byte-identical to the golden run's ids at every
        # position.
        want = [(1, 2), (1, 3), (2, 0), (2, 1), (2, 2), (2, 3)]
        assert [(e, c) for e, c, _ in resumed] == want, role
        for e, c, ids in resumed:
            assert ids == golden[(e, c)], (role, e, c)


@pytest.mark.slow
def test_chaos_rollback_reseeks_mid_epoch_cursor(tmp_path):
    """Sibling acceptance: a guard rollback to a MID-epoch checkpoint
    (the notice-driven step-6 save) re-opens every stream at the exact
    recorded cursor — not the epoch boundary — with the rollback's
    re-seeded shuffle order.  The post-rollback ids are verified against
    the seekable sampler directly (the order is a pure function of
    (seed + bump, epoch), so the expectation needs no golden run)."""
    from dwt_tpu.data import SeekableSampler
    from dwt_tpu.train.loop import _ROLLBACK_SEED_STRIDE

    trail = str(tmp_path / "trail")
    rc, ck, jsonl, stderr = _run_digits(
        tmp_path,
        {"notice_at_step": 6, "nan_at_step": 7},
        extra=("--epochs", "3", "--guard_policy", "rollback",
               "--guard_interval", "1", "--harvest_depth", "0"),
        env_extra={"DWT_DATA_TRAIL": trail},
    )
    assert rc == 0, stderr[-2000:]
    recs = [json.loads(l) for l in open(jsonl).read().splitlines()]
    rb = [r for r in recs if r["kind"] == "rollback"]
    assert rb and rb[0]["step"] == 6  # restored the mid-epoch notice save
    assert _assert_resumable(ck) == 12  # trained to completion

    for role, seed in (("source", 1), ("target", 2)):
        entries = _read_trail(trail, role)
        # The re-seek: position (1, 2) is produced TWICE — once in the
        # first attempt (pre-divergence order), once after the rollback
        # (re-seeded order) — and the second time its ids come from the
        # BUMPED seed lineage at the same cursor.
        hits = [i for i, (e, c, _) in enumerate(entries) if (e, c) == (1, 2)]
        assert len(hits) == 2, (role, hits)
        replay = entries[hits[1]:]
        assert [(e, c) for e, c, _ in replay] == [
            (1, 2), (1, 3), (2, 0), (2, 1), (2, 2), (2, 3)
        ], role
        bump = _ROLLBACK_SEED_STRIDE
        for e, c, ids in replay:
            sampler = SeekableSampler(32, seed=seed + bump, epoch=e)
            want = sampler.positions(c * 8, (c + 1) * 8).tolist()
            assert ids == want, (role, e, c)


@pytest.mark.slow
def test_chaos_dead_worker_detected_and_survived(tmp_path):
    """ISSUE-15 satellite: a pool worker dying mid-epoch (dead_worker_at)
    is detected at --data_stall_timeout, logged, respawned, and the run
    completes with the batch order intact (the golden-free invariant:
    the trail equals the no-fault sampler order — a substitution never
    happened, the item itself was fine)."""
    trail = str(tmp_path / "trail")
    rc, ck, jsonl, stderr = _run_digits(
        tmp_path,
        {"dead_worker_at": {"source": [3]}},
        extra=("--epochs", "2", "--data_stall_timeout", "2"),
        env_extra={"DWT_DATA_TRAIL": trail},
    )
    assert rc == 0, stderr[-2000:]
    assert "stalled" in stderr
    assert _assert_resumable(ck) == 8
    from dwt_tpu.data import SeekableSampler

    for e, c, ids in _read_trail(trail, "source"):
        want = SeekableSampler(32, seed=1, epoch=e).positions(
            c * 8, (c + 1) * 8
        ).tolist()
        assert ids == want, (e, c)


@pytest.mark.slow
def test_chaos_two_process_sharded_exact_resume(tmp_path):
    """Acceptance: exact mid-epoch resume under the 2-process sharded
    split — each process's trail (its own shard slice) is byte-identical
    to its golden twin's remaining sequence after SIGTERM + relaunch,
    and the shared checkpoint carries ONE data_state both ranks agree
    on."""
    def spawn(rank_plans, extra, tag):
        return _spawn_two_process_digits(
            tmp_path, rank_plans,
            extra=(*extra, "--ckpt_every_epochs", "1000"),
            env_extra={
                r: {"DWT_DATA_TRAIL": str(tmp_path / f"trail_{tag}_{r}")}
                for r in range(2)
            },
            ck=str(tmp_path / f"ck_{tag}"),
        )

    results, _ = spawn({}, ("--epochs", "3"), "gold")
    for rank, (rc, out) in enumerate(results):
        assert rc == 0, f"gold rank {rank}:\n{out[-3000:]}"

    results, _ = spawn(
        {1: {"sigterm_at_step": 6}}, ("--epochs", "500"), "kill"
    )
    for rank, (rc, out) in enumerate(results):
        assert rc == 0, f"kill rank {rank}:\n{out[-3000:]}"
    ck = str(tmp_path / "ck_kill")
    assert latest_step(ck) == 6
    from dwt_tpu.utils.checkpoint import load_data_state

    ds = load_data_state(os.path.join(ck, "6"))
    # 64 items / global batch 8 -> 8 steps per epoch; step 6 = cursor 6.
    assert ds["streams"]["source"]["epoch"] == 0
    assert ds["streams"]["source"]["cursor"] == 6

    results, logs = spawn({}, ("--epochs", "3"), "kill")  # resume, same ck
    for rank, (rc, out) in enumerate(results):
        assert rc == 0, f"resume rank {rank}:\n{out[-3000:]}"
    for path in logs:
        recs = [json.loads(l) for l in open(path).read().splitlines()]
        res = [r for r in recs if r["kind"] == "resume"][-1]
        assert res["step"] == 6 and res["data"] == "exact"

    for rank in range(2):
        for role in ("source", "target"):
            gold = {
                (e, c): ids for e, c, ids in _read_trail(
                    str(tmp_path / f"trail_gold_{rank}"), role
                )
            }
            resumed = _read_trail(
                str(tmp_path / f"trail_kill_{rank}"), role
            )
            # The kill run's trail, then the resume run's appended to the
            # same per-rank file: the resumed portion starts at (0, 6).
            tail = resumed[
                max(i for i, (e, c, _) in enumerate(resumed)
                    if (e, c) == (0, 6)):
            ]
            want = [(0, 6), (0, 7)] + [
                (e, c) for e in range(1, 3) for c in range(8)
            ]
            assert [(e, c) for e, c, _ in tail] == want, (rank, role)
            for e, c, ids in tail:
                assert ids == gold[(e, c)], (rank, role, e, c)


# ------------------------------------------------------- full matrix (slow)


@pytest.mark.slow
@pytest.mark.parametrize(
    "name,plan,extra,expect",
    [
        # Preemption: SIGTERM at a boundary -> save-and-exit-0.
        (
            "sigterm",
            {"sigterm_at_step": 6},
            ("--epochs", "500"),
            {"rc": 0, "kinds": ["preempt"], "resumable_step": 6},
        ),
        # NaN burst + halt policy -> diagnosed halt (logged divergence).
        (
            "nan_burst_halt",
            {"nan_at_step": [5, 6, 7]},
            ("--epochs", "500", "--guard_policy", "halt",
             "--guard_interval", "1"),
            {"rc": "nonzero", "kinds": ["divergence"],
             "stderr": "non-finite"},
        ),
        # NaN + rollback policy -> restore the epoch checkpoint, finish.
        (
            "nan_rollback",
            {"nan_at_step": 6},
            ("--epochs", "3", "--guard_policy", "rollback",
             "--guard_interval", "1"),
            {"rc": 0, "kinds": ["rollback", "test"], "resumable": True},
        ),
        # Crash between checkpoint write and finalize rename: the error
        # surfaces (diagnosed), the PREVIOUS checkpoint stays authoritative.
        (
            "crash_mid_save",
            {"crash_in_save": 8},
            ("--epochs", "500"),
            {"rc": "nonzero", "stderr": "injected crash",
             "resumable_step": 4},
        ),
        # Corrupt dataset item -> quarantined, run completes, id persisted.
        (
            "flaky_item",
            {"corrupt_items": {"source": [3]}},
            ("--epochs", "2"),
            {"rc": 0, "resumable": True, "quarantine": True},
        ),
        # Transient save I/O (within the retry budget) -> absorbed.
        (
            "io_error_transient",
            {"io_error_saves": 2},
            ("--epochs", "2"),
            {"rc": 0, "resumable_step": 8},
        ),
        # Persistent save I/O -> the save fails after bounded retries and
        # the failure surfaces (diagnosed halt), no torn artifact.
        (
            "io_error_persistent",
            {"io_error_saves": 99},
            ("--epochs", "500"),
            {"rc": "nonzero", "stderr": "injected I/O error"},
        ),
    ],
)
def test_chaos_matrix(tmp_path, name, plan, extra, expect):
    rc, ck, jsonl, stderr = _run_digits(tmp_path, plan, extra)
    if expect["rc"] == "nonzero":
        assert rc not in (0, WATCHDOG_EXIT_CODE), (
            f"{name}: expected diagnosed halt, got rc={rc}; "
            f"stderr tail: {stderr[-2000:]}"
        )
    else:
        assert rc == expect["rc"], (
            f"{name}: rc={rc}; stderr tail: {stderr[-2000:]}"
        )
    for kind in expect.get("kinds", ()):
        assert kind in _kinds(jsonl), f"{name}: no {kind!r} record"
    if "stderr" in expect:
        assert expect["stderr"] in stderr, (
            f"{name}: diagnosis {expect['stderr']!r} missing from stderr "
            f"tail: {stderr[-2000:]}"
        )
    if expect.get("resumable"):
        _assert_resumable(ck)
    if "resumable_step" in expect:
        assert _assert_resumable(ck) == expect["resumable_step"], name
    if expect.get("quarantine"):
        qpath = os.path.join(ck, "quarantine.json")
        assert os.path.exists(qpath), f"{name}: quarantine not persisted"
        assert 3 in json.load(open(qpath))["source"]
    # No torn state in any outcome: every finalized step dir validates.
    for d in (os.listdir(ck) if os.path.isdir(ck) else []):
        if d.isdigit():
            assert is_valid_checkpoint(os.path.join(ck, d)), (
                f"{name}: torn finalized checkpoint {d}"
            )


@pytest.mark.slow
def test_chaos_hang_watchdog_diagnoses_and_exits_distinct(tmp_path):
    """A mid-training hang (wedged collective stand-in) must not outlive
    the watchdog: all-thread stacks land under ckpt_dir/watchdog/, the
    exit code is the distinct WATCHDOG_EXIT_CODE, and the checkpoint from
    the completed epoch remains valid for the relaunch."""
    rc, ck, jsonl, stderr = _run_digits(
        tmp_path,
        plan={"hang_at_step": 6},
        extra=("--epochs", "500", "--watchdog_timeout", "12"),
        timeout=240,
    )
    assert rc == WATCHDOG_EXIT_CODE, f"stderr tail: {stderr[-2000:]}"
    assert "[watchdog]" in stderr
    wd_dir = os.path.join(ck, "watchdog")
    stacks = [f for f in os.listdir(wd_dir) if f.startswith("stacks-")]
    assert stacks, "no stack dump written"
    dump = open(os.path.join(wd_dir, stacks[0])).read()
    assert "hang watchdog" in dump and "Thread" in dump
    # The epoch-1 periodic save (step 4) predates the hang: resumable.
    assert _assert_resumable(ck) == 4


@pytest.mark.slow
def test_chaos_two_process_consensus_sigterm_one_host(tmp_path):
    """Acceptance: only process 1 receives SIGTERM; the step-boundary
    consensus must turn it into an ALL-host save-and-exit-0 at the SAME
    step — not a hung collective on process 0."""
    port = _free_port()
    procs, logs = [], []
    for rank in range(2):
        env = {k: v for k, v in os.environ.items()
               if k not in ("PALLAS_AXON_POOL_IPS", inject.ENV_VAR)}
        env.update(
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            DWT_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            DWT_NUM_PROCESSES="2",
            DWT_PROCESS_ID=str(rank),
            PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
        )
        if rank == 1:  # ONLY host 1 is preempted
            env[inject.ENV_VAR] = json.dumps({"sigterm_at_step": 3})
        jsonl = str(tmp_path / f"metrics_{rank}.jsonl")
        logs.append(jsonl)
        procs.append(
            subprocess.Popen(
                [
                    sys.executable, "-m", "dwt_tpu.cli.usps_mnist",
                    "--synthetic", "--synthetic_size", "64",
                    "--distributed", "--data_parallel",
                    "--epochs", "500",  # only the consensus stop ends it
                    "--group_size", "4",
                    "--source_batch_size", "8",
                    "--target_batch_size", "8",
                    "--test_batch_size", "8",
                    "--num_workers", "0",
                    "--log_interval", "1",
                    "--metrics_jsonl", jsonl,
                    "--ckpt_dir", str(tmp_path / "shared_ck"),
                    "--ckpt_every_epochs", "1000",
                ],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
                cwd=REPO,
            )
        )
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=480)
            outs.append(out)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(
            "consensus processes timed out — the un-signaled host is "
            "likely hung in a collective (the exact failure consensus "
            "exists to prevent)"
        )
    for rank, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {rank} failed:\n{out[-3000:]}"

    # Both hosts logged the consensus stop at the SAME step...
    preempts = []
    for path in logs:
        recs = [json.loads(l) for l in open(path).read().splitlines()]
        pre = [r for r in recs if r["kind"] == "preempt"]
        assert pre, f"no preempt record in {path}"
        preempts.append(pre[-1]["step"])
    assert preempts[0] == preempts[1] == 3

    # ...and the coordinated checkpoint is ONE valid artifact at that
    # step, in the collective-free host-shard format (multi-host async
    # saves no longer downgrade to the Orbax barrier path — ISSUE-5).
    ck = tmp_path / "shared_ck"
    assert latest_step(str(ck)) == 3
    assert is_valid_checkpoint(str(ck / "3"))
    assert (ck / "3" / "shard_0").exists() and (ck / "3" / "shard_1").exists()
    assert json.load(open(ck / "3" / "manifest.json"))["format"] == "host_shards"


def _spawn_two_process_digits(tmp_path, rank_plans, extra=(), timeout=480,
                              env_extra=None, ck=None):
    """Launch the 2-process digits trainer (shared ckpt_dir, consensus
    path); ``rank_plans[r]`` arms a fault plan in rank r's env only,
    ``env_extra[r]`` adds env vars there (e.g. a per-rank trail dir).
    Returns ``[(returncode, output), ...]``; kill-on-timeout enforces the
    no-hang contract from outside."""
    port = _free_port()
    procs, logs = [], []
    for rank in range(2):
        env = {k: v for k, v in os.environ.items()
               if k not in ("PALLAS_AXON_POOL_IPS", inject.ENV_VAR,
                            "DWT_DATA_TRAIL")}
        env.update(
            JAX_PLATFORMS="cpu",
            XLA_FLAGS="--xla_force_host_platform_device_count=4",
            DWT_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            DWT_NUM_PROCESSES="2",
            DWT_PROCESS_ID=str(rank),
            PYTHONPATH=REPO + os.pathsep + env.get("PYTHONPATH", ""),
        )
        env.update((env_extra or {}).get(rank, {}))
        if rank_plans.get(rank):
            env[inject.ENV_VAR] = json.dumps(rank_plans[rank])
        jsonl = str(tmp_path / f"metrics_{rank}.jsonl")
        logs.append(jsonl)
        procs.append(
            subprocess.Popen(
                [
                    sys.executable, "-m", "dwt_tpu.cli.usps_mnist",
                    "--synthetic", "--synthetic_size", "64",
                    "--distributed", "--data_parallel",
                    "--group_size", "4",
                    "--source_batch_size", "8",
                    "--target_batch_size", "8",
                    "--test_batch_size", "8",
                    "--num_workers", "0",
                    "--log_interval", "1",
                    "--metrics_jsonl", jsonl,
                    "--ckpt_dir", ck or str(tmp_path / "shared_ck"),
                    *extra,
                ],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
                cwd=REPO,
            )
        )
    results = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            results.append((p.returncode, out))
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail(
            "2-process chaos run hung — the one outcome the matrix forbids"
        )
    return results, logs


@pytest.mark.slow
def test_chaos_two_process_kill_mid_shard_resumes_previous_step(tmp_path):
    """Acceptance: with multi-host async saves (shard format), SIGKILLing
    one host mid-shard-write must leave the PREVIOUS finalized step as
    the resume source — the torn shard's step never promotes, the
    surviving host exits by watchdog (not a hang), and a 2-process
    relaunch resumes both hosts from the finalized step and completes."""
    # Phase 1: save every epoch (8 steps — 64 items / global batch 8);
    # rank 1 dies inside its shard write of the step-16 save (epoch 2),
    # after the first save (step 8) finalized at an epoch-2 boundary.
    results, _ = _spawn_two_process_digits(
        tmp_path,
        {1: {"kill_writer_mid_shard": 16}},
        extra=("--epochs", "500", "--ckpt_every_epochs", "1",
               "--watchdog_timeout", "25"),
    )
    rcs = [rc for rc, _ in results]
    assert rcs[1] == -9, f"rank 1 should die by SIGKILL, got {rcs[1]}"
    # Rank 0 must NOT hang in the next allgather: the watchdog (or the
    # distributed runtime noticing the dead peer) gets it out nonzero.
    assert rcs[0] != 0, f"rank 0 exited 0 despite its dead peer"

    ck = str(tmp_path / "shared_ck")
    # Step 8 (epoch 1) was written by both hosts and promoted by the
    # consensus save-done bits; step 16's shard_1 is torn, so it must
    # never have finalized.
    assert latest_step(ck) == 8
    assert is_valid_checkpoint(os.path.join(ck, "8"))
    assert not os.path.isdir(os.path.join(ck, "16"))
    for d in os.listdir(ck):
        if d.isdigit():
            assert is_valid_checkpoint(os.path.join(ck, d)), (
                f"torn finalized checkpoint {d}"
            )

    # Phase 2: relaunch BOTH hosts; they resume from the finalized step 8
    # and complete 3 epochs (24 steps) cleanly.
    results, logs = _spawn_two_process_digits(
        tmp_path, {}, extra=("--epochs", "3", "--ckpt_every_epochs", "1"),
    )
    for rank, (rc, out) in enumerate(results):
        assert rc == 0, f"relaunch rank {rank} failed:\n{out[-3000:]}"
    for path in logs:
        recs = [json.loads(l) for l in open(path).read().splitlines()]
        res = [r for r in recs if r["kind"] == "resume"]
        assert res and res[-1]["step"] == 8, f"no step-8 resume in {path}"
    assert latest_step(ck) == 24


@pytest.mark.slow
def test_chaos_two_process_notice_one_host_saves_all(tmp_path):
    """Acceptance: a preemption notice visible on ONE host becomes an
    all-host proactive save at the SAME step (consensus notice bit) while
    training continues; the later SIGTERM (on the OTHER host) exits 0 on
    both without writing a second checkpoint — the proactive save is the
    resume source."""
    results, logs = _spawn_two_process_digits(
        tmp_path,
        {0: {"notice_at_step": 3}, 1: {"sigterm_at_step": 6}},
        extra=("--epochs", "500", "--ckpt_every_epochs", "1000"),
    )
    for rank, (rc, out) in enumerate(results):
        assert rc == 0, f"rank {rank} failed:\n{out[-3000:]}"
    saves, stops = [], []
    for path in logs:
        recs = [json.loads(l) for l in open(path).read().splitlines()]
        ns = [r for r in recs if r["kind"] == "notice_save"]
        assert ns, f"no notice_save record in {path}"
        saves.append(ns[-1]["step"])
        pre = [r for r in recs if r["kind"] == "preempt"]
        assert pre and pre[-1]["resume_step"] == 3
        stops.append(pre[-1]["step"])
    assert saves == [3, 3]  # both hosts saved the same step, together
    assert stops[0] == stops[1] == 6
    ck = str(tmp_path / "shared_ck")
    assert latest_step(ck) == 3  # the proactive save, promoted
    assert not os.path.isdir(os.path.join(ck, "6"))  # no second save
    assert json.load(
        open(os.path.join(ck, "3", "manifest.json"))
    )["format"] == "host_shards"


# ------------------------------------------------ FaultPlan spec parsing


def _env_plan(monkeypatch, spec_json: str):
    monkeypatch.setenv(inject.ENV_VAR, spec_json)


def test_fault_plan_parses_composed_kinds(monkeypatch):
    _env_plan(monkeypatch, json.dumps({
        "nan_at_step": [3, 4], "sigterm_at_step": 6,
        "slow_step_at": 2, "slow_step_s": 0.5,
        "io_error_saves": 2, "crash_in_save": True,
        "corrupt_items": {"source": [5], "target": [1, 2]},
        "notice_at_step": 5, "kill_writer_mid_shard": 8,
    }))
    plan = FaultPlan.from_env()
    assert plan.nan_at_step == [3, 4]
    assert plan.sigterm_at_step == 6
    assert plan.slow_step_at == 2 and plan.slow_step_s == 0.5
    assert plan.io_error_saves == 2 and plan.crash_in_save is True
    assert plan.corrupt_items == {"source": [5], "target": [1, 2]}
    assert plan.notice_at_step == 5
    assert plan.kill_writer_mid_shard == 8


def test_fault_plan_scalar_nan_stays_scalar(monkeypatch):
    _env_plan(monkeypatch, json.dumps({"nan_at_step": 7}))
    assert FaultPlan.from_env().nan_at_step == 7


@pytest.mark.parametrize(
    "spec,match",
    [
        ({"hang_at_stp": 3}, "unknown fault kind"),
        ({"nan_at_step": "three"}, "int step"),
        ({"nan_at_step": [3, 3]}, "duplicate steps"),
        ({"nan_at_step": True}, "int step"),
        ({"hang_at_step": 4, "sigterm_at_step": 4}, "pick one control fault"),
        # Even at DIFFERENT steps: chunked dispatch can land both on one
        # boundary, where the hang silently swallows the SIGTERM.
        ({"hang_at_step": 9, "sigterm_at_step": 5}, "cannot compose"),
        ({"slow_step_s": -1.0}, "non-negative"),
        ({"slow_step_s": 30}, "arms nothing"),
        ({"io_error_saves": -2}, "non-negative"),
        ({"crash_in_save": "yes"}, "true .* or an"),
        ({"corrupt_items": {"eval": [1]}}, "source"),
        ({"corrupt_items": [1, 2]}, "map a stream role"),
        # The notice is an ADVANCE warning: a plan where it cannot fire
        # before the SIGTERM proves nothing about the proactive save.
        ({"notice_at_step": 6, "sigterm_at_step": 6}, "must precede"),
        ({"notice_at_step": 9, "sigterm_at_step": 5}, "must precede"),
        ({"notice_at_step": 0}, "never fire"),
        ({"kill_writer_mid_shard": "yes"}, "true .* or an int"),
        ({"kill_writer_mid_shard": 0}, "true .* or an int"),
    ],
)
def test_fault_plan_rejects_bad_specs(monkeypatch, spec, match):
    _env_plan(monkeypatch, json.dumps(spec))
    with pytest.raises(ValueError, match=match):
        FaultPlan.from_env()


def test_fault_plan_rejects_duplicate_kinds(monkeypatch):
    # json.loads would silently keep the LAST value; the plan must refuse.
    _env_plan(monkeypatch, '{"nan_at_step": 1, "nan_at_step": 2}')
    with pytest.raises(ValueError, match="duplicate fault kind"):
        FaultPlan.from_env()


def test_fault_plan_rejects_non_object(monkeypatch):
    _env_plan(monkeypatch, "[1, 2]")
    with pytest.raises(ValueError, match="JSON object"):
        FaultPlan.from_env()


def test_fault_plan_rejects_invalid_json(monkeypatch):
    _env_plan(monkeypatch, "{not json")
    with pytest.raises(ValueError, match="not valid JSON"):
        FaultPlan.from_env()


def test_fault_plan_nan_burst_fires_each_step_once():
    """Burst semantics drive the escalation ladder: every listed step
    fires exactly once, so the poison re-strikes after each recovery."""
    import dataclasses

    import jax.numpy as jnp
    import numpy as np

    @dataclasses.dataclass
    class _State:
        params: dict

        def replace(self, params):
            return _State(params)

    def _nan(metrics):
        return bool(np.isnan(np.asarray(metrics["loss"])))

    fresh = lambda: {"loss": jnp.ones(())}
    inject.arm(FaultPlan(nan_at_step=[2, 4]))
    s = _State({"w": jnp.ones(2)})
    _, m1 = inject.maybe_nan(s, fresh(), 1)
    assert not _nan(m1)  # step 1: not armed
    s2, m2 = inject.maybe_nan(s, fresh(), 2)
    assert _nan(m2)  # step 2 fired
    assert np.isnan(np.asarray(s2.params["w"])).all()
    _, m3 = inject.maybe_nan(s, fresh(), 2)
    assert not _nan(m3)  # step 2 is spent
    _, m4 = inject.maybe_nan(s, fresh(), 3, 5)
    assert _nan(m4)  # step 4 fired inside the chunk range
    assert inject.current().nan_at_step is None  # burst exhausted
