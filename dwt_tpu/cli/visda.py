"""VisDA-2017 entrypoint — BASELINE.json configs[4] (ResNet101-DWT).

The reference repo has no VisDA script (its entrypoints are digits and
OfficeHome only — SURVEY §0 file inventory); BASELINE.json names the
VisDA-2017 synthetic→real config as a target of the TPU build.  This CLI is
the OfficeHome machinery (``resnet50_dwt_mec_officehome.py:495-600`` recipe:
triple-stream MEC training, 10-pass stat collection) re-parameterized with
the VisDA constants: 12 classes, ResNet101 backbone, train/validation
ImageFolder splits.  All OfficeHome flags remain available for overrides.
"""

from __future__ import annotations

from dwt_tpu.cli import officehome as _oh

_VISDA_DEFAULTS = {
    "arch": "resnet101",
    "num_classes": 12,
    "s_dset_path": "../data/visda-2017/train",
    "t_dset_path": "../data/visda-2017/validation",
    # No checkpoint by default: the OfficeHome default is a ResNet50
    # state_dict whose keys would silently partial-load into ResNet101
    # (strict=False semantics); pass an explicit ResNet101 checkpoint.
    "resnet_path": "",
}


def build_parser():
    p = _oh.build_parser()
    p.description = "dwt_tpu DWT-MEC VisDA-2017 trainer (ResNet101-DWT)"
    p.set_defaults(**_VISDA_DEFAULTS)
    return p


def main(argv=None) -> float:
    return _oh.run_from_args(build_parser().parse_args(argv))


if __name__ == "__main__":
    main()
