"""Deterministic fault injection for the resilience subsystem.

Production training must survive failure classes that are impossible to
reproduce on demand with real hardware: numeric divergence (a NaN loss at
some step — possibly a *burst* of consecutive NaN steps), a
preemption/crash landing *inside* a checkpoint save, a checkpoint
truncated by a dead filesystem, a corrupt/undecodable dataset item, a
preemption SIGTERM landing on one host of a multi-host run, a hung
collective/step, a transiently slow step, and flaky checkpoint I/O.  This
module provides deterministic stand-ins for each, consulted by the
production code at exactly the points the real fault would strike:

* ``maybe_nan(state, metrics, lo, hi)`` — called by the train loops after
  each dispatch; poisons params + metrics with NaN, when an armed step
  falls in ``[lo, hi]`` (the divergence-guard recovery paths).  A list of
  steps models a NaN *burst*: the poison re-strikes after each recovery,
  driving the guard's escalation ladder.
* ``maybe_crash_mid_save(step)`` — called by ``save_state`` after the
  checkpoint bytes are written but *before* the atomic finalize rename;
  raises :class:`SimulatedCrash`, leaving an unfinalized tmp directory
  behind exactly like a SIGKILL mid-save (the restore-fallback path).
* ``maybe_io_error(what)`` — called by ``save_state`` at the top of each
  write *attempt*; raises ``OSError`` for the first ``io_error_saves``
  attempts.  A count within the retry budget is absorbed by the bounded
  backoff; a larger one surfaces as a diagnosed save failure.
* ``at_step(lo, hi)`` — the step-boundary control faults, called by the
  loops once per step/chunk: ``slow_step`` (sleep once — a transient
  stall a sane watchdog timeout must tolerate), ``notice_at_step``
  (preemption NOTICE — the scheduler's advance warning becomes visible
  on this host; drives the all-host proactive-save consensus),
  ``sigterm_at_step`` (self-delivered SIGTERM — deterministic
  preemption, including one-host-of-many for the consensus tests), and
  ``hang`` (never return — a wedged collective; only the hang watchdog
  gets the process out).
* ``maybe_kill_writer_mid_shard(step)`` — called by ``save_host_shard``
  between the leaf bytes and the shard manifest; SIGKILLs the process,
  i.e. a host dying mid-shard-write (promotion must refuse the torn
  shard; the previous finalized step stays authoritative).
* ``maybe_kill_mid_delta_promote(step)`` — called by the delta store's
  ``promote_delta`` after the chain validates but before the finalize
  rename; SIGKILLs the process, i.e. dying mid-promote of a
  content-addressed save (blobs durable, manifest staged-but-invisible
  — relaunch must resume from the previous finalized step).
* ``maybe_missing_parent_blob(step, paths)`` — called after the delta
  save at ``step`` finalizes; deletes ONE blob a delta ancestor wrote
  (never one of the base full save's own), modeling an externally
  damaged store.  The newest-valid walk must skip the whole torn chain
  back to the last full save — never a mixed-generation restore.
* ``wrap_dataset(ds, role)`` — wraps a train dataset in
  :class:`FlakyDataset` when the plan condemns items for that role,
  driving the loader's retry/quarantine path from a subprocess.
* sweep-supervisor kinds (``dwt_tpu/sweep``):
  ``maybe_kill_supervisor_at_schedule(n)`` SIGKILLs the supervisor
  between its journal update and the job spawn (relaunch must
  reschedule the pid-less entry); ``take_sweep_preempt(tag)`` tells the
  supervisor to preempt that running job (notice file, then SIGTERM);
  ``take_sweep_job_fault(tag)`` yields a per-job ``DWT_FAULT_PLAN``
  (kill-mid-delta-promote) the supervisor injects into that pair's next
  spawn — a job dying inside a save, under the supervisor's watch.
* serving-traffic kinds (``dwt_tpu/serve``): ``maybe_shift_request(i, x)``
  applies ``serve_drift_shift`` — an affine input-distribution shift
  (``x*scale + offset``) from request index ``at_request`` onward.
  Deliberately NOT one-shot: a domain shift is a new steady state, not
  an event — the online adapter must see it on every request until it
  adapts.  ``maybe_poison_request(i, x)`` applies
  ``serve_poison_requests`` — at each armed request index (one-shot per
  index), the payload is replaced with garbage cycling NaN, Inf, and
  out-of-band magnitudes by index: the sanitization layer must keep all
  three out of the stat accumulator while serving stays healthy.  The
  kinds compose (drift first — the world moved — then poison rides the
  drifted stream).
* fleet-traffic kinds (``dwt_tpu/fleet``): ``traffic_spike`` multiplies
  serve_bench's offered Poisson rate by ``factor`` from request index
  ``at_request`` onward — a step change in demand, persistent like
  drift (a spike is the new steady state until the autoscaler absorbs
  it).  ``take_replica_slow(rid)`` yields a per-replica
  ``DWT_FAULT_PLAN`` the fleet injects into that replica's spawn env
  (the ``take_sweep_job_fault`` pattern); inside the replica,
  ``maybe_replica_slow()`` sleeps the dispatcher ``sleep_s`` per batch
  — a straggler, not a corpse: it answers health probes and serves,
  just slowly, so the weighted router (not the prober) must starve it.
* :class:`FlakyDataset` — the in-process form: chosen indices raise for
  the first N accesses (transient I/O) or always (corrupt item), hang
  forever on their first access (``dead_worker_at`` — the pool worker
  holding the item is lost, exactly like a thread wedged in a dead
  filesystem read; the pipeline's stall detection must respawn the
  item, not the epoch), or stall their first access for
  ``slow_item_s`` seconds (``slow_item_at`` — a per-item decode stall
  the ordered-reassembly window must absorb without reordering).

All hooks are no-ops (one ``is None`` check) unless a plan is armed, so
the production hot paths pay nothing.  Arm programmatically with
:func:`arm`, or via the ``DWT_FAULT_PLAN`` env var (JSON, read once at
first use) for subprocess tests; the kinds compose — one plan may slow a
step, fail a save twice, and then deliver SIGTERM.  Every fault fires at
most once per arm (each element of a burst list counts once): recovery
paths must not re-trip on the state they just repaired.
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import signal
import time
from typing import Any, Dict, List, Optional, Tuple

ENV_VAR = "DWT_FAULT_PLAN"


class SimulatedCrash(Exception):
    """Raised by an armed kill-mid-save hook (stands in for SIGKILL)."""


def _as_step_list(
    value: Any, field: str, minimum: int = 1
) -> Optional[List[int]]:
    """Normalize an int-or-list spec; reject bools/floats/duplicates and
    values below ``minimum`` (global steps are 1-based, item indices
    0-based — an out-of-range value can never fire, and a fault plan
    that injects nothing proves nothing)."""
    if value is None:
        return None
    items = value if isinstance(value, list) else [value]
    steps: List[int] = []
    for v in items:
        if isinstance(v, bool) or not isinstance(v, int):
            raise ValueError(
                f"{ENV_VAR}: {field} must be an int step or list of int "
                f"steps; got {v!r}"
            )
        if v < minimum:
            raise ValueError(
                f"{ENV_VAR}: {field} values must be >= {minimum} "
                f"(got {v}) — a value that can never fire is a silent "
                "no-op, not a fault"
            )
        steps.append(v)
    if len(set(steps)) != len(steps):
        raise ValueError(f"{ENV_VAR}: duplicate steps in {field}: {steps}")
    return sorted(steps)


@dataclasses.dataclass
class FaultPlan:
    """One-shot fault schedule.  Fields default to "never fire"."""

    # Poison params/metrics with NaN after the train step with this
    # (1-based) global step number completes.  A list is a burst: each
    # listed step fires once, so the poison re-strikes after recovery.
    nan_at_step: Any = None
    # Raise SimulatedCrash inside save_state after the bytes are written
    # but before the finalize rename.  True = next save; int = the save
    # at that step.
    crash_in_save: Any = None
    # Step-boundary control faults (see module docstring).
    hang_at_step: Optional[int] = None
    slow_step_at: Optional[int] = None
    slow_step_s: float = 1.0
    sigterm_at_step: Optional[int] = None
    # Number of save-write ATTEMPTS that raise OSError (each bounded-
    # backoff retry is one attempt, so 2 is absorbed, 99 is persistent).
    io_error_saves: int = 0
    # {"source": [idx, ...], "target": [...]} — items the loops' datasets
    # report as corrupt (the loader quarantines them).
    corrupt_items: Optional[Dict[str, List[int]]] = None
    # {"source": [idx, ...]} — the pool worker loading that item hangs
    # forever on its FIRST access (a dead/wedged worker mid-epoch); the
    # data pipeline's head-of-window stall detection must log, count,
    # and respawn the item on a fresh worker.  Subsequent accesses (the
    # respawned attempt) succeed.
    dead_worker_at: Optional[Dict[str, List[int]]] = None
    # {"source": [idx, ...]} — that item's FIRST decode stalls for
    # slow_item_s seconds, then succeeds (a transiently slow item the
    # ordered window must absorb in order).
    slow_item_at: Optional[Dict[str, List[int]]] = None
    slow_item_s: float = 1.0
    # Step boundary at which a preemption NOTICE becomes visible on this
    # host (stands in for the GCE metadata warning / a scheduler notice
    # file): the loops take an all-host proactive save and keep training.
    notice_at_step: Optional[int] = None
    # SIGKILL this process from inside the host-shard writer, after the
    # leaf bytes are written but before the shard manifest — a host dying
    # mid-shard-write.  True = next shard write; int = the save at that
    # step.  Promotion must refuse the torn shard and the previous
    # finalized step stays authoritative.
    kill_writer_mid_shard: Any = None
    # SIGKILL this process inside the delta store's promote, after the
    # staged chain validates but before the finalize rename.  True =
    # next promote; int = the save at that step.  The staged tmp dir
    # stays invisible to the walk; relaunch resumes the previous step.
    kill_mid_delta_promote: Any = None
    # After the delta save at this step finalizes, delete one blob its
    # chain inherits from a DELTA ancestor (the base full save's blobs
    # are never touched) — the newest-valid walk must fall back past the
    # torn chain to the last full save.
    missing_parent_blob: Optional[int] = None
    # --- sweep-supervisor faults (dwt_tpu/sweep) -----------------------
    # SIGKILL the sweep SUPERVISOR inside its Nth scheduling event
    # (1-based), after the journal records the pair as scheduled but
    # before the job subprocess spawns — the worst-ordered supervisor
    # death: a relaunch must treat the pid-less "running" entry as
    # reschedulable, adopt genuinely-running jobs, and finish the matrix.
    kill_supervisor_at_schedule: Optional[int] = None
    # Pair tags (e.g. "Art2Clipart") the supervisor preempts — notice
    # file first, SIGTERM on the next poll — the first time each is
    # observed running.  Models the scheduler reclaiming a subset of
    # slots: the job saves-and-exits-0 and its RESUME reschedules free
    # (no crash charge).  One-shot per tag.
    sweep_preempt_pairs: Optional[List[str]] = None
    # Pair tags whose FIRST spawn gets {"kill_mid_delta_promote": true}
    # injected into its own DWT_FAULT_PLAN env — the job SIGKILLs itself
    # mid-save; the supervisor must count the crash, respawn within the
    # budget, and the respawn resumes from the previous finalized step.
    sweep_job_kill_mid_save: Optional[List[str]] = None
    # --- serving-traffic faults (dwt_tpu/serve) ------------------------
    # 0-based request indices whose payload is replaced with garbage
    # (cycling NaN / Inf / out-of-band magnitude by index) before
    # submission.  One-shot per index.  The sanitization layer must keep
    # every poisoned row out of the online-adaptation accumulator.
    serve_poison_requests: Optional[List[int]] = None
    # {"at_request": N, "offset": f, "scale": f} — from request index N
    # onward, inputs become x*scale + offset: a synthetic target-domain
    # shift.  Persistent (NOT one-shot): a domain shift is a new steady
    # state the adapter must keep seeing until it adapts.
    serve_drift_shift: Optional[Dict[str, Any]] = None
    # --- fleet-traffic faults (dwt_tpu/fleet) --------------------------
    # {"at_request": N, "factor": f} — from request index N onward,
    # serve_bench's Poisson inter-arrival gaps divide by ``factor``: a
    # step change in offered rate.  Persistent like drift: a traffic
    # spike is the new steady state until capacity absorbs it.
    traffic_spike: Optional[Dict[str, Any]] = None
    # {"rid": R, "sleep_s": s} — replica R's dispatcher sleeps ``s``
    # seconds per batch: a straggler (answers probes, serves slowly),
    # not a corpse.  The fleet consumes this via take_replica_slow(rid)
    # at spawn time (one-shot per arm: a respawn of the straggler runs
    # clean); inside the replica the sleep itself is persistent.
    replica_slow_at: Optional[Dict[str, Any]] = None

    _FIELDS = (
        "nan_at_step", "crash_in_save", "hang_at_step", "slow_step_at",
        "slow_step_s", "sigterm_at_step", "io_error_saves", "corrupt_items",
        "notice_at_step", "kill_writer_mid_shard", "kill_mid_delta_promote",
        "missing_parent_blob", "dead_worker_at", "slow_item_at",
        "slow_item_s", "kill_supervisor_at_schedule", "sweep_preempt_pairs",
        "sweep_job_kill_mid_save", "serve_poison_requests",
        "serve_drift_shift", "traffic_spike", "replica_slow_at",
    )

    @classmethod
    def from_spec(cls, spec: Dict[str, Any]) -> "FaultPlan":
        """Build a validated plan from a parsed JSON object.

        Silent no-ops are the worst failure mode of a fault plan — a test
        that injects nothing proves nothing — so unknown kinds, bad
        types, duplicate steps, and overlapping control faults all raise
        instead of being dropped.
        """
        unknown = sorted(set(spec) - set(cls._FIELDS))
        if unknown:
            raise ValueError(
                f"{ENV_VAR}: unknown fault kind(s) {unknown}; "
                f"valid kinds: {list(cls._FIELDS)}"
            )
        nan = _as_step_list(spec.get("nan_at_step"), "nan_at_step")
        if nan is not None and not isinstance(spec["nan_at_step"], list):
            nan = nan[0]  # scalar in, scalar out (burst lists stay lists)

        def _opt_int(field):
            v = spec.get(field)
            if v is None:
                return None
            if isinstance(v, bool) or not isinstance(v, int):
                raise ValueError(
                    f"{ENV_VAR}: {field} must be an int step; got {v!r}"
                )
            if v < 1:
                raise ValueError(
                    f"{ENV_VAR}: {field} must be a 1-based step >= 1; "
                    f"got {v} (it would never fire)"
                )
            return v

        hang = _opt_int("hang_at_step")
        slow = _opt_int("slow_step_at")
        sigterm = _opt_int("sigterm_at_step")
        notice = _opt_int("notice_at_step")
        if notice is not None and sigterm is not None and notice >= sigterm:
            raise ValueError(
                f"{ENV_VAR}: notice_at_step ({notice}) must precede "
                f"sigterm_at_step ({sigterm}) — a notice is the scheduler's "
                "advance warning, and a plan where it cannot fire before "
                "the SIGTERM proves nothing about the proactive save"
            )
        if hang is not None and sigterm is not None:
            raise ValueError(
                f"{ENV_VAR}: hang_at_step and sigterm_at_step cannot "
                "compose in one plan — a hang ends the process's useful "
                "life, and with steps_per_dispatch > 1 both can land on "
                "the SAME chunk boundary where the hang silently "
                "swallows the SIGTERM; pick one control fault per plan"
            )
        slow_s = spec.get("slow_step_s", 1.0)
        if isinstance(slow_s, bool) or not isinstance(slow_s, (int, float)) \
                or slow_s < 0:
            raise ValueError(
                f"{ENV_VAR}: slow_step_s must be a non-negative number; "
                f"got {slow_s!r}"
            )
        if "slow_step_s" in spec and slow is None:
            raise ValueError(
                f"{ENV_VAR}: slow_step_s without slow_step_at arms "
                "nothing — name the step the stall should hit"
            )
        io_saves = spec.get("io_error_saves", 0)
        if isinstance(io_saves, bool) or not isinstance(io_saves, int) \
                or io_saves < 0:
            raise ValueError(
                f"{ENV_VAR}: io_error_saves must be a non-negative int; "
                f"got {io_saves!r}"
            )
        crash = spec.get("crash_in_save")
        if crash is not None and crash is not True and (
                isinstance(crash, bool) or not isinstance(crash, int)
                or crash < 1):
            raise ValueError(
                f"{ENV_VAR}: crash_in_save must be true (next save) or an "
                f"int step >= 1; got {crash!r}"
            )
        def _true_or_step(field):
            v = spec.get(field)
            if v is not None and v is not True and (
                    isinstance(v, bool) or not isinstance(v, int) or v < 1):
                raise ValueError(
                    f"{ENV_VAR}: {field} must be true (next occurrence) "
                    f"or an int step >= 1; got {v!r}"
                )
            return v

        kill_writer = _true_or_step("kill_writer_mid_shard")
        kill_promote = _true_or_step("kill_mid_delta_promote")
        missing_blob = _opt_int("missing_parent_blob")
        kill_supervisor = _opt_int("kill_supervisor_at_schedule")

        def _tag_list(field):
            """Validate a pair-tag list spec (scalar string allowed)."""
            value = spec.get(field)
            if value is None:
                return None
            items = value if isinstance(value, list) else [value]
            if not items:
                raise ValueError(
                    f"{ENV_VAR}: {field} must name at least one pair tag "
                    "— an empty list injects nothing"
                )
            tags = []
            for v in items:
                if not isinstance(v, str) or not v:
                    raise ValueError(
                        f"{ENV_VAR}: {field} entries must be non-empty "
                        f"pair tags like 'Art2Clipart'; got {v!r}"
                    )
                tags.append(v)
            if len(set(tags)) != len(tags):
                raise ValueError(
                    f"{ENV_VAR}: duplicate tags in {field}: {tags}"
                )
            return tags

        preempt_pairs = _tag_list("sweep_preempt_pairs")
        job_kill_mid_save = _tag_list("sweep_job_kill_mid_save")

        def _role_items(field):
            """Validate a role→item-index map (corrupt_items and the
            data-pipeline fault kinds share the shape and rules)."""
            value = spec.get(field)
            if value is None:
                return None
            if not isinstance(value, dict):
                raise ValueError(
                    f"{ENV_VAR}: {field} must map a stream role to a "
                    f"list of item indices; got {value!r}"
                )
            normalized = {}
            for role, ids in value.items():
                if role not in ("source", "target"):
                    raise ValueError(
                        f"{ENV_VAR}: {field} role must be 'source' or "
                        f"'target'; got {role!r}"
                    )
                # Keep the NORMALIZED list: a scalar spec must arm, not
                # crash (or silently no-op) at wrap_dataset.  Item
                # indices are 0-based (unlike steps).
                normalized[role] = _as_step_list(
                    ids, f"{field}[{role!r}]", minimum=0
                )
            return normalized

        corrupt = _role_items("corrupt_items")
        dead_worker = _role_items("dead_worker_at")
        slow_item = _role_items("slow_item_at")
        slow_item_s = spec.get("slow_item_s", 1.0)
        if isinstance(slow_item_s, bool) or not isinstance(
                slow_item_s, (int, float)) or slow_item_s < 0:
            raise ValueError(
                f"{ENV_VAR}: slow_item_s must be a non-negative number; "
                f"got {slow_item_s!r}"
            )
        if "slow_item_s" in spec and slow_item is None:
            raise ValueError(
                f"{ENV_VAR}: slow_item_s without slow_item_at arms "
                "nothing — name the item the stall should hit"
            )
        # Request indices are 0-based like item indices, not 1-based
        # like steps.  Keep the normalized list: a scalar spec must arm.
        poison = _as_step_list(
            spec.get("serve_poison_requests"), "serve_poison_requests",
            minimum=0,
        )
        drift = spec.get("serve_drift_shift")
        if drift is not None:
            if not isinstance(drift, dict):
                raise ValueError(
                    f"{ENV_VAR}: serve_drift_shift must be an object like "
                    '{"at_request": N, "offset": f, "scale": f}; '
                    f"got {drift!r}"
                )
            bad_keys = sorted(set(drift) - {"at_request", "offset", "scale"})
            if bad_keys:
                raise ValueError(
                    f"{ENV_VAR}: unknown serve_drift_shift key(s) "
                    f"{bad_keys}; valid: ['at_request', 'offset', 'scale']"
                )
            at = drift.get("at_request", 0)
            if isinstance(at, bool) or not isinstance(at, int) or at < 0:
                raise ValueError(
                    f"{ENV_VAR}: serve_drift_shift.at_request must be a "
                    f"0-based request index >= 0; got {at!r}"
                )
            offset = drift.get("offset", 0.0)
            scale = drift.get("scale", 1.0)
            for name, v in (("offset", offset), ("scale", scale)):
                if isinstance(v, bool) or not isinstance(v, (int, float)) \
                        or not math.isfinite(v):
                    raise ValueError(
                        f"{ENV_VAR}: serve_drift_shift.{name} must be a "
                        f"finite number; got {v!r} — non-finite inputs are "
                        "serve_poison_requests' job, not a domain shift"
                    )
            if float(scale) == 1.0 and float(offset) == 0.0:
                raise ValueError(
                    f"{ENV_VAR}: serve_drift_shift with scale=1 and "
                    "offset=0 is the identity — a shift that moves "
                    "nothing proves nothing"
                )
            drift = {
                "at_request": at,
                "offset": float(offset),
                "scale": float(scale),
            }
        spike = spec.get("traffic_spike")
        if spike is not None:
            if not isinstance(spike, dict):
                raise ValueError(
                    f"{ENV_VAR}: traffic_spike must be an object like "
                    '{"at_request": N, "factor": f}; '
                    f"got {spike!r}"
                )
            bad_keys = sorted(set(spike) - {"at_request", "factor"})
            if bad_keys:
                raise ValueError(
                    f"{ENV_VAR}: unknown traffic_spike key(s) {bad_keys}; "
                    "valid: ['at_request', 'factor']"
                )
            at = spike.get("at_request", 0)
            if isinstance(at, bool) or not isinstance(at, int) or at < 0:
                raise ValueError(
                    f"{ENV_VAR}: traffic_spike.at_request must be a "
                    f"0-based request index >= 0; got {at!r}"
                )
            factor = spike.get("factor")
            if isinstance(factor, bool) or not isinstance(
                    factor, (int, float)) or not math.isfinite(factor) \
                    or factor <= 0:
                raise ValueError(
                    f"{ENV_VAR}: traffic_spike.factor must be a finite "
                    f"number > 0; got {factor!r}"
                )
            if float(factor) == 1.0:
                raise ValueError(
                    f"{ENV_VAR}: traffic_spike with factor=1 is the "
                    "identity — a rate step that steps nowhere proves "
                    "nothing"
                )
            spike = {"at_request": at, "factor": float(factor)}
        slow_replica = spec.get("replica_slow_at")
        if slow_replica is not None:
            if not isinstance(slow_replica, dict):
                raise ValueError(
                    f"{ENV_VAR}: replica_slow_at must be an object like "
                    '{"rid": R, "sleep_s": s}; '
                    f"got {slow_replica!r}"
                )
            bad_keys = sorted(set(slow_replica) - {"rid", "sleep_s"})
            if bad_keys:
                raise ValueError(
                    f"{ENV_VAR}: unknown replica_slow_at key(s) "
                    f"{bad_keys}; valid: ['rid', 'sleep_s']"
                )
            rid = slow_replica.get("rid")
            if isinstance(rid, bool) or not isinstance(rid, int) or rid < 0:
                raise ValueError(
                    f"{ENV_VAR}: replica_slow_at.rid must be a replica "
                    f"id >= 0; got {rid!r}"
                )
            sleep_s = slow_replica.get("sleep_s")
            if isinstance(sleep_s, bool) or not isinstance(
                    sleep_s, (int, float)) or not math.isfinite(sleep_s) \
                    or sleep_s <= 0:
                raise ValueError(
                    f"{ENV_VAR}: replica_slow_at.sleep_s must be a finite "
                    f"number > 0 (a zero-second straggler is a silent "
                    f"no-op); got {sleep_s!r}"
                )
            slow_replica = {"rid": rid, "sleep_s": float(sleep_s)}
        return cls(
            nan_at_step=nan,
            crash_in_save=crash,
            hang_at_step=hang,
            slow_step_at=slow,
            slow_step_s=float(slow_s),
            sigterm_at_step=sigterm,
            io_error_saves=io_saves,
            corrupt_items=corrupt,
            notice_at_step=notice,
            kill_writer_mid_shard=kill_writer,
            kill_mid_delta_promote=kill_promote,
            missing_parent_blob=missing_blob,
            dead_worker_at=dead_worker,
            slow_item_at=slow_item,
            slow_item_s=float(slow_item_s),
            kill_supervisor_at_schedule=kill_supervisor,
            sweep_preempt_pairs=preempt_pairs,
            sweep_job_kill_mid_save=job_kill_mid_save,
            serve_poison_requests=poison,
            serve_drift_shift=drift,
            traffic_spike=spike,
            replica_slow_at=slow_replica,
        )

    @classmethod
    def from_env(cls) -> Optional["FaultPlan"]:
        raw = os.environ.get(ENV_VAR)
        if not raw:
            return None

        def _no_duplicates(pairs):
            keys = [k for k, _ in pairs]
            dupes = sorted({k for k in keys if keys.count(k) > 1})
            if dupes:
                raise ValueError(
                    f"{ENV_VAR}: duplicate fault kind(s) {dupes} — the "
                    "second spec would silently shadow the first"
                )
            return dict(pairs)

        try:
            spec = json.loads(raw, object_pairs_hook=_no_duplicates)
        except json.JSONDecodeError as e:
            raise ValueError(f"{ENV_VAR} is not valid JSON: {e}") from e
        if not isinstance(spec, dict):
            raise ValueError(
                f"{ENV_VAR} must be a JSON object of fault kinds; "
                f"got {type(spec).__name__}"
            )
        return cls.from_spec(spec)


_plan: Optional[FaultPlan] = None
_env_checked = False


def arm(plan: FaultPlan) -> None:
    global _plan, _env_checked
    _plan = plan
    _env_checked = True


def disarm() -> None:
    global _plan, _env_checked
    _plan = None
    # Re-reading the env on the next current() would re-arm a consumed
    # subprocess plan — mark it checked so disarm is final in-process.
    _env_checked = True
    # A fired notice_at_step latched the notice module's injected flag;
    # clear it so in-process tests cannot leak a notice into each other.
    from dwt_tpu.resilience import notice as _notice

    _notice.reset_injected()


def current() -> Optional[FaultPlan]:
    """The armed plan, lazily picking up ``DWT_FAULT_PLAN`` once."""
    global _plan, _env_checked
    if not _env_checked:
        _env_checked = True
        _plan = FaultPlan.from_env()
    return _plan


def _poison_tree(tree: Any) -> Any:
    import jax
    import jax.numpy as jnp

    def nan_like(x):
        if hasattr(x, "dtype") and jnp.issubdtype(x.dtype, jnp.floating):
            return x * jnp.asarray(jnp.nan, x.dtype)
        return x

    return jax.tree.map(nan_like, tree)


def maybe_nan(state, metrics, lo: int, hi: Optional[int] = None) -> Tuple[Any, Any]:
    """Poison ``(state.params, metrics)`` with NaN if an armed step is in
    ``[lo, hi]`` (both inclusive; ``hi`` defaults to ``lo``).  Each armed
    step fires once; a burst list re-strikes after every recovery.

    The chunked (``steps_per_dispatch``) path passes the whole dispatched
    step range, since the host only regains control at chunk boundaries —
    the same granularity at which a real mid-chunk NaN becomes observable.
    """
    plan = current()
    if plan is None or plan.nan_at_step is None:
        return state, metrics
    hi = lo if hi is None else hi
    steps = (plan.nan_at_step if isinstance(plan.nan_at_step, list)
             else [plan.nan_at_step])
    hit = [s for s in steps if lo <= s <= hi]
    if not hit:
        return state, metrics
    remaining = [s for s in steps if s not in hit]  # each element one-shot
    plan.nan_at_step = remaining or None
    state = state.replace(params=_poison_tree(state.params))
    metrics = _poison_tree(dict(metrics))
    if "finite" in metrics:
        # The step's device-side finite flag (train/steps.py) was
        # computed from the REAL metrics before this injection; a real
        # NaN would have flipped it, so the simulated one must too — or
        # the harvested guard (--harvest_depth) would never see the
        # poison it is being tested against.
        import jax.numpy as jnp

        metrics["finite"] = jnp.zeros_like(metrics["finite"])
    return state, metrics


def maybe_crash_mid_save(step: int) -> None:
    """Raise :class:`SimulatedCrash` if armed for this save.  Fires once."""
    plan = current()
    if plan is None or plan.crash_in_save is None:
        return
    if plan.crash_in_save is True or int(plan.crash_in_save) == int(step):
        plan.crash_in_save = None  # one-shot
        raise SimulatedCrash(f"injected crash during checkpoint save @{step}")


def maybe_io_error(what: str = "save") -> None:
    """Raise ``OSError`` for the first ``io_error_saves`` attempts.

    Called at the top of each checkpoint write attempt (inside the
    bounded-backoff retry wrapper), so a small count models a transient
    mount hiccup the retries absorb, and a large one a dead filesystem
    the caller must diagnose.
    """
    plan = current()
    if plan is None or not plan.io_error_saves:
        return
    plan.io_error_saves -= 1
    raise OSError(f"injected I/O error during checkpoint {what}")


def at_step(lo: int, hi: Optional[int] = None) -> None:
    """Step-boundary control faults: slow, then notice, then SIGTERM,
    then hang.

    Ordering matters for composed plans at one boundary: a slow step must
    finish (the watchdog tolerates it) before the terminal faults, and a
    preemption notice must become visible before the SIGTERM it warns of
    (``from_spec`` additionally requires the notice STEP to precede the
    SIGTERM step).  Hang and SIGTERM never share a plan (``from_spec``
    rejects the combination — chunked dispatch could land both on one
    boundary, where the hang would silently swallow the SIGTERM); the
    hang never returns — only the watchdog (or the scheduler's SIGKILL)
    ends the process, exactly like a wedged collective.
    """
    plan = current()
    if plan is None:
        return
    hi = lo if hi is None else hi
    if plan.slow_step_at is not None and lo <= plan.slow_step_at <= hi:
        plan.slow_step_at = None  # one-shot
        time.sleep(plan.slow_step_s)
    if plan.notice_at_step is not None and lo <= plan.notice_at_step <= hi:
        plan.notice_at_step = None  # one-shot
        from dwt_tpu.resilience import notice as _notice

        _notice.trigger_injected()
    if plan.sigterm_at_step is not None and lo <= plan.sigterm_at_step <= hi:
        plan.sigterm_at_step = None  # one-shot
        os.kill(os.getpid(), signal.SIGTERM)
    if plan.hang_at_step is not None and lo <= plan.hang_at_step <= hi:
        plan.hang_at_step = None
        while True:  # a wedged collective does not poll flags either
            time.sleep(60.0)


def maybe_kill_writer_mid_shard(step: int) -> None:
    """SIGKILL the process if armed for this shard write.  Called by
    ``save_host_shard`` after the leaf bytes are durably written but
    before the shard manifest — a real kill (not an exception the writer
    thread would catch): the whole point is the HOST dying mid-write,
    leaving a torn shard that promotion must refuse."""
    plan = current()
    if plan is None or plan.kill_writer_mid_shard is None:
        return
    if plan.kill_writer_mid_shard is True or (
        int(plan.kill_writer_mid_shard) == int(step)
    ):
        plan.kill_writer_mid_shard = None  # one-shot (if we survive…)
        os.kill(os.getpid(), signal.SIGKILL)


def maybe_kill_mid_delta_promote(step: int) -> None:
    """SIGKILL the process if armed for this delta promote.  Called by
    ``promote_delta`` after the staged chain validates but BEFORE the
    finalize rename — the blobs are durable, the manifest is still in
    the ``.tmp-cas-*`` stage, so the walk never sees the step and a
    relaunch resumes from the previous finalized checkpoint."""
    plan = current()
    if plan is None or plan.kill_mid_delta_promote is None:
        return
    if plan.kill_mid_delta_promote is True or (
        int(plan.kill_mid_delta_promote) == int(step)
    ):
        plan.kill_mid_delta_promote = None  # one-shot (if we survive…)
        os.kill(os.getpid(), signal.SIGKILL)


def maybe_missing_parent_blob(step: int, inherited_blobs: Any) -> None:
    """Delete one chain-inherited blob if armed for this save's step —
    an externally damaged store (buggy cleanup job, partial filesystem
    loss) striking a blob the newest delta depends on but did not write.
    ``inherited_blobs`` are paths written by DELTA ancestors only, so
    the base full save stays restorable and the walk's fallback target
    is well-defined.  Raises when the armed save has no such blobs
    (a plan that cannot tear a chain proves nothing)."""
    plan = current()
    if plan is None or plan.missing_parent_blob is None:
        return
    if int(plan.missing_parent_blob) != int(step):
        return
    plan.missing_parent_blob = None  # one-shot
    for path in inherited_blobs:
        if os.path.exists(path):
            os.remove(path)
            return
    raise ValueError(
        f"{ENV_VAR}: missing_parent_blob armed at step {step}, but that "
        "save inherits no delta-ancestor blobs (a full save or a "
        "chain-base save) — the fault would be a silent no-op"
    )


def maybe_kill_supervisor_at_schedule(event: int) -> None:
    """SIGKILL the sweep supervisor if armed for its ``event``-th
    scheduling event (1-based).  Called between the journal update that
    records the pair as scheduled and the job subprocess spawn — the
    ordering that leaves the journal claiming a job that never started:
    the relaunched supervisor must reschedule it, not wait on a ghost."""
    plan = current()
    if plan is None or plan.kill_supervisor_at_schedule is None:
        return
    if int(plan.kill_supervisor_at_schedule) == int(event):
        plan.kill_supervisor_at_schedule = None  # one-shot (if we survive…)
        os.kill(os.getpid(), signal.SIGKILL)


def maybe_shift_request(i: int, x: Any) -> Any:
    """Apply the armed ``serve_drift_shift`` to request ``i``'s payload.

    From ``at_request`` onward every input becomes ``x*scale + offset``
    — a synthetic target-domain shift.  Deliberately NOT one-shot: a
    domain shift is a new steady state, not an event, and the online
    adapter must keep seeing the shifted distribution until it adapts.
    Returns a shifted copy (never mutates the caller's array)."""
    plan = current()
    if plan is None or plan.serve_drift_shift is None:
        return x
    shift = plan.serve_drift_shift
    if int(i) < int(shift.get("at_request", 0)):
        return x
    import numpy as np

    x = np.asarray(x)
    return (x * float(shift.get("scale", 1.0))
            + float(shift.get("offset", 0.0))).astype(x.dtype)


def maybe_poison_request(i: int, x: Any) -> Any:
    """Replace request ``i``'s payload with garbage when armed.

    One-shot per armed index.  The poison cycles by index — ``i % 3``
    picks NaN, Inf, or an out-of-band magnitude (1e6) — so one composed
    plan exercises every branch of the serve-side sanitizer.  Values are
    written to a strided slice of a COPY: part of the row stays
    plausible, the way a half-corrupted payload looks in production.
    Compose with :func:`maybe_shift_request` drift-first (the world
    moved; the poison rides the drifted stream)."""
    plan = current()
    if plan is None or not plan.serve_poison_requests:
        return x
    if int(i) not in plan.serve_poison_requests:
        return x
    plan.serve_poison_requests = [
        r for r in plan.serve_poison_requests if r != int(i)
    ] or None
    import numpy as np

    x = np.array(x, copy=True)
    if not np.issubdtype(x.dtype, np.floating):
        x = x.astype(np.float32)
    val = (float("nan"), float("inf"), 1e6)[int(i) % 3]
    x.reshape(-1)[::3] = val
    return x


def traffic_spike() -> Optional[Dict[str, Any]]:
    """The armed ``traffic_spike`` spec, or None.  Consulted by
    serve_bench when it builds the open-loop arrival process: from
    request index ``at_request`` onward the Poisson inter-arrival gaps
    divide by ``factor``.  Persistent (NOT one-shot): a demand step is
    the new steady state — the autoscaler, not the load generator,
    decides when it stops hurting."""
    plan = current()
    if plan is None:
        return None
    return plan.traffic_spike


def take_replica_slow(rid: int) -> Optional[Dict[str, Any]]:
    """The per-replica fault plan (a ``DWT_FAULT_PLAN`` JSON object) the
    fleet injects into replica ``rid``'s spawn env, or None.  One-shot
    per arm — the ``take_sweep_job_fault`` pattern: if the straggler is
    later SIGKILLed, its respawn runs clean, so what the test proves is
    the router starving the straggler, not the prober reaping it."""
    plan = current()
    if plan is None or not plan.replica_slow_at:
        return None
    if int(plan.replica_slow_at["rid"]) != int(rid):
        return None
    spec = dict(plan.replica_slow_at)
    plan.replica_slow_at = None
    return {"replica_slow_at": spec}


def maybe_replica_slow() -> None:
    """Sleep the armed ``replica_slow_at.sleep_s`` once per call.
    Called by the serve dispatcher at the top of every batch; inside a
    replica the plan arrives via its own env (rid already matched by
    the fleet), so the sleep is unconditional while armed.  Persistent:
    a straggler is a steady state, not an event."""
    plan = current()
    if plan is None or not plan.replica_slow_at:
        return
    time.sleep(float(plan.replica_slow_at["sleep_s"]))


def take_sweep_preempt(tag: str) -> bool:
    """True (once per tag) when the supervisor should preempt the
    running job for ``tag``: deliver its notice file, then SIGTERM on
    the next poll — the scheduler-reclaims-a-slot fault."""
    plan = current()
    if plan is None or not plan.sweep_preempt_pairs:
        return False
    if tag not in plan.sweep_preempt_pairs:
        return False
    plan.sweep_preempt_pairs = [
        t for t in plan.sweep_preempt_pairs if t != tag
    ] or None
    return True


def take_sweep_job_fault(tag: str) -> Optional[Dict[str, Any]]:
    """The per-job fault plan (a ``DWT_FAULT_PLAN`` JSON object) the
    supervisor injects into ``tag``'s next spawn, or None.  One-shot per
    tag: the RESPAWN of a mid-save-killed job must run clean, or the
    quarantine budget — not the resume — is what the test exercises."""
    plan = current()
    if plan is None or not plan.sweep_job_kill_mid_save:
        return None
    if tag not in plan.sweep_job_kill_mid_save:
        return None
    plan.sweep_job_kill_mid_save = [
        t for t in plan.sweep_job_kill_mid_save if t != tag
    ] or None
    return {"kill_mid_delta_promote": True}


def wrap_dataset(dataset: Any, role: str) -> Any:
    """Wrap ``dataset`` in :class:`FlakyDataset` when the plan condemns
    items for ``role`` ('source'/'target') under ANY of the item-level
    kinds (corrupt, dead-worker hang, slow decode); pass-through
    otherwise.  The kinds compose on one wrapper — a plan may corrupt
    item 3 and hang the worker on item 7 of the same stream."""
    plan = current()
    if plan is None:
        return dataset

    def _ids(table):
        if not table:
            return ()
        ids = table.get(role)
        if isinstance(ids, int):  # programmatic arm() may pass a bare index
            ids = [ids]
        return tuple(int(i) for i in ids or ())

    corrupt = _ids(plan.corrupt_items)
    hang = _ids(plan.dead_worker_at)
    slow = _ids(plan.slow_item_at)
    if not (corrupt or hang or slow):
        return dataset
    return FlakyDataset(
        dataset, corrupt=corrupt, hang=hang, slow=slow,
        slow_s=plan.slow_item_s,
    )


class FlakyDataset:
    """Dataset wrapper whose chosen indices misbehave on access.

    ``fail={idx: n}`` — index ``idx`` raises :class:`OSError` for its
    first ``n`` accesses, then succeeds (transient I/O; exercises retry).
    ``corrupt=(idx, ...)`` — those indices always raise (undecodable item;
    exercises quarantine).
    ``hang=(idx, ...)`` — the FIRST access blocks forever (the worker
    thread is lost, a dead worker; exercises the pipeline's stall
    detection + respawn — the respawned second access succeeds).
    ``slow=(idx, ...)`` — the first access sleeps ``slow_s`` then
    succeeds (a per-item decode stall; exercises ordered reassembly).
    Deterministic: behavior depends only on the access count per index.
    Access counting is lock-guarded — these hooks fire on concurrent
    pool workers, and a double-counted first access would silently skip
    the armed fault.
    """

    def __init__(self, base, fail: Optional[Dict[int, int]] = None,
                 corrupt: Tuple[int, ...] = (), hang: Tuple[int, ...] = (),
                 slow: Tuple[int, ...] = (), slow_s: float = 1.0):
        import threading

        self.base = base
        self.fail = dict(fail or {})
        self.corrupt = frozenset(corrupt)
        self.hang = frozenset(hang)
        self.slow = frozenset(slow)
        self.slow_s = float(slow_s)
        self._counts: Dict[int, int] = {}
        self._lock = threading.Lock()

    def __len__(self) -> int:
        return len(self.base)

    def __getitem__(self, i: int):
        i = int(i)
        if i in self.corrupt:
            raise OSError(f"injected corrupt item {i}")
        with self._lock:
            seen = self._counts.get(i, 0)
            self._counts[i] = seen + 1
        if seen < self.fail.get(i, 0):
            raise OSError(f"injected transient failure {i} (attempt {seen + 1})")
        if seen == 0 and i in self.hang:
            import threading

            threading.Event().wait()  # a dead worker never comes back
        if seen == 0 and i in self.slow:
            time.sleep(self.slow_s)
        return self.base[i]
