"""Serving subsystem tests (ISSUE-7).

Tier-1 (fast): the pure batcher planner under a fake clock (the
``test_bench_contract`` ``_FakeClock`` pattern — no sleeps, no timing
flake), load-shed admission, bitwise served-logits parity vs the
eval-mode forward at every bucket size (padded tails included),
checkpoint-restore-into-server for BOTH on-disk formats, the shared
percentile helper, one batcher→engine→metrics smoke, and the SIGTERM
graceful-drain subprocess proof.

Slow-marked (tools/t1_budget.py discipline): sustained open-loop load
and the multi-device mesh fan-out subprocess matrix.
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# --------------------------------------------------------- percentile unit

def test_percentile_nearest_rank():
    from dwt_tpu.utils.metrics import percentile

    vals = list(range(1, 101))  # 1..100
    assert percentile(vals, 50) == 50
    assert percentile(vals, 95) == 95
    assert percentile(vals, 99) == 99
    assert percentile(vals, 100) == 100
    assert percentile(vals, 0) == 1
    # Nearest-rank returns an OBSERVED sample, order-independent.
    assert percentile([9.0, 1.0, 5.0], 50) == 5.0
    assert percentile([7.5], 99) == 7.5
    with pytest.raises(ValueError):
        percentile([], 50)
    with pytest.raises(ValueError):
        percentile([1.0], 123)


def test_percentile_summary_keys_and_empty():
    from dwt_tpu.utils.metrics import percentile_summary

    out = percentile_summary([3.0, 1.0, 2.0], (50.0, 99.0), prefix="e2e_ms_p")
    assert out == {"e2e_ms_p50": 2.0, "e2e_ms_p99": 3.0}
    # Empty input emits NO fields — absent percentiles must not read as 0.
    assert percentile_summary([], (50.0,)) == {}


# ---------------------------------------------------------- batcher planner

class _FakeClock:
    """Deterministic stand-in for time.monotonic (the deadline source)."""

    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


def test_plan_dispatch_fills_largest_bucket_immediately():
    from dwt_tpu.serve.batcher import plan_dispatch

    buckets = (1, 8, 32)
    # 32 queued samples fill the largest bucket: dispatch NOW, deadline
    # irrelevant.
    assert plan_dispatch([8, 8, 16], buckets, now=0.0, oldest_t=0.0,
                         max_delay_s=10.0) == 3
    # Order-preserving prefix: 8+8+20 > 32, so only the first two go even
    # though dropping the middle one would pack better.
    assert plan_dispatch([8, 8, 20], buckets, now=0.0, oldest_t=0.0,
                         max_delay_s=10.0) == 2


def test_plan_dispatch_waits_until_deadline():
    from dwt_tpu.serve.batcher import plan_dispatch

    buckets = (1, 8, 32)
    # Under-filled and under deadline: wait.
    assert plan_dispatch([3], buckets, now=0.004, oldest_t=0.0,
                         max_delay_s=0.005) == 0
    # Deadline reached: flush what's queued.
    assert plan_dispatch([3], buckets, now=0.005, oldest_t=0.0,
                         max_delay_s=0.005) == 1
    # Empty queue: nothing to do.
    assert plan_dispatch([], buckets, now=1.0, oldest_t=None,
                         max_delay_s=0.005) == 0


def test_plan_dispatch_rejects_unbucketable_head():
    from dwt_tpu.serve.batcher import plan_dispatch

    with pytest.raises(ValueError):
        plan_dispatch([64], (1, 8, 32), now=0.0, oldest_t=0.0,
                      max_delay_s=0.01)


def test_batcher_deadline_coalescing_fake_clock():
    from dwt_tpu.serve.batcher import MicroBatcher

    clock = _FakeClock()
    b = MicroBatcher(buckets=(1, 4, 8), max_batch_delay_ms=5.0,
                     max_queue_items=64, clock=clock)
    f1 = b.submit(np.ones((1, 2, 2, 1), np.float32))
    f2 = b.submit(np.full((2, 2, 2, 1), 2.0, np.float32))
    # Before the deadline nothing dispatches (3 < largest bucket 8).
    assert b.next_batch(timeout=0) is None
    clock.t = 0.0051  # oldest request's deadline passed
    pb = b.next_batch(timeout=0)
    assert pb is not None
    assert pb.bucket == 4 and pb.real_n == 3  # smallest bucket that fits
    # Pad-and-mask: the tail repeats the last REAL row, masked out.
    assert pb.mask.tolist() == [True, True, True, False]
    np.testing.assert_array_equal(pb.x[3], pb.x[2])
    assert pb.slices == [(0, 1), (1, 3)]
    assert not f1.done() and not f2.done()  # resolution is the dispatcher's
    b.close()
    assert b.next_batch(timeout=0) is None  # closed + drained


def test_batcher_full_bucket_dispatches_without_deadline():
    from dwt_tpu.serve.batcher import MicroBatcher

    clock = _FakeClock()
    b = MicroBatcher(buckets=(1, 4), max_batch_delay_ms=60_000.0,
                     max_queue_items=64, clock=clock)
    for _ in range(4):
        b.submit(np.ones((1, 2, 2, 1), np.float32))
    pb = b.next_batch(timeout=0)  # full largest bucket: no wait
    assert pb is not None and pb.bucket == 4 and pb.real_n == 4
    assert pb.mask.all()


def test_batcher_load_shedding_and_drain():
    from dwt_tpu.serve.batcher import MicroBatcher, ShedError

    clock = _FakeClock()
    b = MicroBatcher(buckets=(1, 4), max_batch_delay_ms=5.0,
                     max_queue_items=4, clock=clock)
    for _ in range(4):
        b.submit(np.ones((1, 2, 2, 1), np.float32))
    with pytest.raises(ShedError) as exc:
        b.submit(np.ones((1, 2, 2, 1), np.float32))
    assert exc.value.retry_after_ms >= 1
    assert exc.value.queued == 4
    # Drain: queued work still dispatches (no deadline games), new
    # arrivals shed with retry-after.
    b.drain()
    with pytest.raises(ShedError) as drain_exc:
        b.submit(np.ones((1, 2, 2, 1), np.float32))
    # Drain is permanent for this process: the retry-after must be a real
    # back-off, not the queue-depth estimate (1 ms once flushed).
    assert drain_exc.value.retry_after_ms >= 1000
    pb = b.next_batch(timeout=0)
    assert pb is not None and pb.real_n == 4
    assert b.next_batch(timeout=0) is None  # drained empty


def test_access_log_write_failure_does_not_raise():
    """A full disk degrades to lost access records — record() runs on the
    dispatcher thread and must never kill it over logging I/O."""
    from dwt_tpu.serve.metrics import AccessLog

    class _FullDisk:
        def write(self, s):
            raise OSError(28, "No space left on device")

    alog = AccessLog(stream=_FullDisk())
    alog.record("ok", 1, e2e_ms=1.0)  # must not raise
    alog.record("ok", 2, e2e_ms=2.0)
    s = alog.summary()
    assert s["served_requests"] == 2 and s["served_imgs"] == 3


def test_batcher_rejects_oversized_and_malformed():
    from dwt_tpu.serve.batcher import MicroBatcher

    b = MicroBatcher(buckets=(1, 4), max_batch_delay_ms=1.0)
    with pytest.raises(ValueError):
        b.submit(np.ones((5, 2, 2, 1), np.float32))  # > largest bucket
    with pytest.raises(ValueError):
        b.submit(np.ones((2, 2), np.float32)[0])  # not [n, ...sample]
    with pytest.raises(ValueError):
        MicroBatcher(buckets=(4, 1))  # not ascending


# ------------------------------------------------------------ shared state

@pytest.fixture(scope="module")
def tiny_serve_setup():
    """One LeNet state + engine for every engine-level test (compiles are
    the cost; sharing keeps this file inside the tier-1 budget)."""
    import jax
    import jax.numpy as jnp
    import optax

    from dwt_tpu.nn import LeNetDWT
    from dwt_tpu.serve import ServeEngine
    from dwt_tpu.train import create_train_state

    model = LeNetDWT(group_size=4)
    rng = np.random.default_rng(0)
    sample = jnp.asarray(rng.normal(size=(2, 4, 28, 28, 1)), jnp.float32)
    state = create_train_state(
        model, jax.random.key(0), sample, optax.identity()
    )
    engine = ServeEngine(
        model, state.params, state.batch_stats, (28, 28, 1),
        buckets=(1, 4, 8),
    )
    return model, state, engine


# ------------------------------------------------- served-logits parity

def test_served_logits_bitwise_parity_every_bucket(tiny_serve_setup):
    """Acceptance: served logits are BITWISE the eval-mode forward's for
    the same params/whiten_cache at every bucket size, including padded
    tails.  The oracle is an independently-jitted eval-mode
    ``model.apply`` (frozen running stats + the precomputed whiten
    cache) at the bucket shape."""
    import jax

    from dwt_tpu.train.evalpipe import make_whiten_cache_fn
    from dwt_tpu.train.steps import eval_variables

    model, state, engine = tiny_serve_setup
    cache = make_whiten_cache_fn("cholesky")(state.batch_stats)
    oracle = jax.jit(
        lambda p, s, c, x: model.apply(
            eval_variables(p, s, c), x, train=False
        )
    )
    rng = np.random.default_rng(7)
    for bucket in engine.buckets:
        for n in {1, bucket - 1, bucket} - {0}:
            x = rng.normal(size=(n, 28, 28, 1)).astype(np.float32)
            served = engine.infer(x, bucket=bucket)
            padded = x
            if n < bucket:
                padded = np.concatenate(
                    [x, np.repeat(x[-1:], bucket - n, axis=0)]
                )
            want = np.asarray(
                oracle(state.params, state.batch_stats, cache, padded)
            )[:n]
            np.testing.assert_array_equal(
                served, want,
                err_msg=f"bucket={bucket} n={n} served logits diverge "
                "from the eval-mode forward",
            )


def test_served_counters_match_evalpipe(tiny_serve_setup):
    """Served responses reduce to EXACTLY the eval pipeline's counters
    on the same dataset (count exact, accuracy identical — the masked
    padded tails contribute nothing on either path)."""
    from dwt_tpu.data import ArrayDataset
    from dwt_tpu.serve import ServeClient
    from dwt_tpu.train.evalpipe import EvalPipeline

    model, state, engine = tiny_serve_setup
    rng = np.random.default_rng(3)
    n = 27  # deliberately ragged vs every bucket and the eval batch
    xs = rng.normal(size=(n, 28, 28, 1)).astype(np.float32)
    ys = rng.integers(0, 10, size=(n,))
    dataset = ArrayDataset(xs, ys)

    evalp = EvalPipeline(
        lambda axis_name=None: model, test_batch_size=8, eval_k=2
    )
    ref = evalp.evaluate(state, dataset)
    assert ref["count"] == n
    # The test record now carries dispatch-interval percentiles from the
    # shared helper (uniform p50/p99 reporting satellite).
    assert "dispatch_ms_p50" in ref and "dispatch_ms_p99" in ref

    client = ServeClient(engine, max_batch_delay_ms=1.0)
    try:
        futures = [
            client.submit(xs[i:i + 5]) for i in range(0, n, 5)
        ]
        logits = np.concatenate([f.result(60.0) for f in futures])
    finally:
        client.close()
    assert logits.shape == (n, 10)
    correct = int((np.argmax(logits, axis=-1) == ys).sum())
    assert ref["accuracy"] == pytest.approx(100.0 * correct / n, abs=1e-9)


# ------------------------------------------- checkpoint-restore-into-server

def test_restore_into_server_orbax_format(tmp_path, tiny_serve_setup):
    from dwt_tpu.serve import ServeEngine
    from dwt_tpu.utils import save_state

    model, state, engine = tiny_serve_setup
    ck = str(tmp_path / "ck")
    save_state(ck, 5, state)
    restored = ServeEngine.from_checkpoint(ck, model, (28, 28, 1),
                                           buckets=(4,))
    assert restored.source == "checkpoint"
    x = np.random.default_rng(1).normal(size=(3, 28, 28, 1)).astype(
        np.float32
    )
    np.testing.assert_array_equal(restored.infer(x), engine.infer(x))


def test_restore_into_server_host_shard_format(tmp_path, tiny_serve_setup):
    from dwt_tpu.serve import ServeEngine
    from dwt_tpu.utils.checkpoint import (
        host_fetch,
        promote_host_shards,
        save_host_shard,
    )

    model, state, engine = tiny_serve_setup
    ck = str(tmp_path / "ck")
    assert save_host_shard(ck, 7, host_fetch(state), 0)
    promote_host_shards(ck, 7, 1)
    restored = ServeEngine.from_checkpoint(ck, model, (28, 28, 1),
                                           buckets=(4,))
    x = np.random.default_rng(2).normal(size=(4, 28, 28, 1)).astype(
        np.float32
    )
    np.testing.assert_array_equal(restored.infer(x), engine.infer(x))


def test_restore_into_server_wrong_model_fails_loudly(
    tmp_path, tiny_serve_setup
):
    """A checkpoint grafted onto a structurally different model must
    raise with the offending path named, not serve garbage."""
    from dwt_tpu.nn import LeNetDWT
    from dwt_tpu.serve import ServeEngine
    from dwt_tpu.utils import save_state

    model, state, _ = tiny_serve_setup
    ck = str(tmp_path / "ck")
    save_state(ck, 3, state)
    wrong = LeNetDWT(group_size=2)  # different whitening group structure
    with pytest.raises((ValueError, FileNotFoundError)):
        ServeEngine.from_checkpoint(ck, wrong, (28, 28, 1), buckets=(1,))


def test_keystr_to_path_roundtrip():
    import jax

    from dwt_tpu.utils.checkpoint import keystr_to_path

    tree = {"params": {"conv1": {"kernel": 1}}, "nested": [2, 3]}
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    paths = [keystr_to_path(jax.tree_util.keystr(p)) for p, _ in flat]
    assert ("params", "conv1", "kernel") in paths
    assert ("nested", "0") in paths
    with pytest.raises(ValueError):
        keystr_to_path("garbage!")


# ------------------------------------------------------------- fast smoke

def test_smoke_batcher_engine_metrics(tiny_serve_setup):
    """Tier-1 smoke: a few mixed-size requests through
    batcher → engine → metrics; access records carry the documented
    schema and the summary aggregates with the shared percentiles."""
    from dwt_tpu.serve import ServeClient
    from dwt_tpu.serve.metrics import AccessLog

    model, state, engine = tiny_serve_setup
    access = AccessLog()
    client = ServeClient(engine, max_batch_delay_ms=1.0, access_log=access)
    rng = np.random.default_rng(11)
    try:
        futs = [
            client.submit(rng.normal(size=(k, 28, 28, 1)).astype(np.float32))
            for k in (1, 2, 3, 1)
        ]
        for k, f in zip((1, 2, 3, 1), futs):
            assert f.result(60.0).shape == (k, 10)
    finally:
        client.close()
    summary = access.summary()
    assert summary["served_requests"] == 4
    assert summary["served_imgs"] == 7
    assert summary["shed_requests"] == 0
    for key in ("e2e_ms_p50", "e2e_ms_p95", "e2e_ms_p99",
                "queue_ms_p50", "device_ms_p50", "imgs_per_s"):
        assert key in summary, key


def test_mismatched_sample_shape_rejected_at_admission(tiny_serve_setup):
    """A request with the wrong sample dims must 400 at submit — not
    reach the dispatcher, where its np.concatenate failure would take
    down every other rider of the coalesced batch."""
    from dwt_tpu.serve import ServeClient

    model, state, engine = tiny_serve_setup
    client = ServeClient(engine, max_batch_delay_ms=50.0)
    try:
        ok = client.submit(np.zeros((1, 28, 28, 1), np.float32))
        with pytest.raises(ValueError, match="input shape"):
            client.submit(np.zeros((1, 28, 28, 3), np.float32))
        with pytest.raises(ValueError):
            client.submit(np.zeros((1, 14, 14), np.float32))
        # The well-formed request sharing the window still serves.
        assert ok.result(60.0).shape == (1, 10)
        assert client.dispatcher_alive
    finally:
        client.close()


def test_drain_race_timeout_none_does_not_strand_queued(tiny_serve_setup):
    """A poll-timeout None from next_batch racing drain() must not make
    the dispatcher exit with requests still queued — their futures would
    strand until the client timeout, violating the drain contract
    ("queued requests keep dispatching until empty").  The dispatcher
    may only exit on None once the batcher is stopping AND empty."""
    import threading

    from dwt_tpu.serve import ServeClient

    model, state, engine = tiny_serve_setup
    client = ServeClient(engine, max_batch_delay_ms=5000.0)
    try:
        b = client.batcher
        real = b.next_batch
        fired = threading.Event()

        def raced_next_batch(timeout=None):
            if not fired.is_set():
                if b.queued_items:
                    # The race: drain() lands inside a poll that then
                    # returns a timeout-None with the queue non-empty.
                    fired.set()
                    b.drain()
                    return None
                return real(timeout=0.05)
            return real(timeout=timeout)

        b.next_batch = raced_next_batch
        fut = client.submit(np.zeros((1, 28, 28, 1), np.float32))
        # Without the queue-empty exit condition the dispatcher returns
        # on the injected None and this times out.
        assert fut.result(30.0).shape == (1, 10)
        assert fired.is_set()
    finally:
        client.close(drain=False, timeout=10.0)


def test_heartbeat_age_tracks_oldest_inflight_batch(tiny_serve_setup):
    """A dispatcher wedged inside the device call must show a GROWING
    heartbeat age even though the batch-wait poll (which runs on the
    prefetch producer thread) keeps stamping the beat — the age follows
    the oldest unresolved in-flight batch, falling back to the poll beat
    only when nothing is in flight."""
    import time as _time

    from dwt_tpu.serve.server import _Dispatcher
    from dwt_tpu.serve.batcher import MicroBatcher
    from dwt_tpu.serve.metrics import AccessLog

    model, state, engine = tiny_serve_setup
    d = _Dispatcher(engine, MicroBatcher(buckets=engine.buckets),
                    AccessLog())  # not started: unit-test the property
    d._beat = _time.monotonic()
    assert d.heartbeat_age_s < 1.0
    # A batch pulled 5 s ago and never resolved dominates a fresh beat.
    d._inflight.append((object(), _time.monotonic() - 5.0))
    d._beat = _time.monotonic()
    assert d.heartbeat_age_s >= 5.0
    d._inflight.popleft()
    assert d.heartbeat_age_s < 1.0


def test_cancelled_future_does_not_kill_dispatcher(tiny_serve_setup):
    """fut.cancel() on a queued request must not blow up the dispatcher
    when it later resolves the batch (set_result on a cancelled Future
    raises InvalidStateError) — other riders and later requests still
    serve."""
    from dwt_tpu.serve import ServeClient

    model, state, engine = tiny_serve_setup
    client = ServeClient(engine, max_batch_delay_ms=300.0)
    try:
        one = np.zeros((1, 28, 28, 1), np.float32)
        f1 = client.submit(one)
        f2 = client.submit(one)
        f1.cancel()  # races the dispatch; either outcome must be survivable
        assert f2.result(60.0).shape == (1, 10)
        f3 = client.submit(one)
        assert f3.result(60.0).shape == (1, 10)
        assert client.dispatcher_alive
    finally:
        client.close()


def test_engine_infer_rejects_empty_and_oversize(tiny_serve_setup):
    """The engine's unbatched convenience path shares the batcher's
    admission contract: n=0 and n>bucket fail with a clear ValueError,
    not a low-level AOT shape mismatch."""
    model, state, engine = tiny_serve_setup
    empty = np.zeros((0, 28, 28, 1), np.float32)
    with pytest.raises(ValueError, match="at least one sample"):
        engine.infer(empty)
    with pytest.raises(ValueError, match="samples for bucket"):
        engine.infer(empty, bucket=engine.buckets[0])
    big = np.zeros((max(engine.buckets) + 1, 28, 28, 1), np.float32)
    with pytest.raises(ValueError, match="largest bucket"):
        engine.infer(big)
    with pytest.raises(ValueError, match="bucket"):
        engine.infer(big, bucket=engine.buckets[0])


def test_dispatcher_death_fails_fast_and_unhealthy():
    """A staging/placement failure must not strand waiters until their
    client timeout: the dispatcher fails every pending future promptly,
    closes admission, and reports unhealthy."""
    from dwt_tpu.serve import ServeClient

    class _BrokenEngine:
        buckets = (1, 4)
        input_shape = (28, 28, 1)
        step = None

        def stage(self, x):
            raise RuntimeError("device exploded")

        def forward(self, x, bucket):  # pragma: no cover - never reached
            raise AssertionError("forward after failed staging")

    client = ServeClient(_BrokenEngine(), max_batch_delay_ms=1.0)
    fut = client.submit(np.zeros((1, 28, 28, 1), np.float32))
    with pytest.raises(RuntimeError, match="device exploded"):
        fut.result(timeout=30.0)
    client._dispatcher.join(timeout=30.0)
    assert not client.dispatcher_alive
    assert isinstance(client.dispatcher_error, RuntimeError)
    with pytest.raises(RuntimeError):  # admission closed, not hanging
        client.submit(np.zeros((1, 28, 28, 1), np.float32))
    assert client.access_log.error_requests == 1


# ---------------------------------------------------- SIGTERM drain proof

def _post_infer(port: int, x, timeout=30.0):
    body = json.dumps({"inputs": np.asarray(x).tolist()}).encode()
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/infer", data=body, method="POST"
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read() or b"{}")


def test_sigterm_drains_cleanly_under_load(tmp_path):
    """Acceptance: SIGTERM during load → in-flight requests complete,
    the queue drains (or sheds with retry-after), exit 0, no torn
    responses — the serving mirror of the resilience SIGTERM tests."""
    access = str(tmp_path / "access.jsonl")
    proc = subprocess.Popen(
        [sys.executable, "-m", "dwt_tpu.serve.server",
         "--init_random", "--model", "lenet", "--buckets", "1,4",
         "--max_batch_delay_ms", "2", "--port", "0",
         "--access_log", access],
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    try:
        ready = json.loads(proc.stdout.readline())
        assert ready["kind"] == "serve_ready"
        port = ready["port"]
        rng = np.random.default_rng(0)
        x = rng.normal(size=(1, 28, 28, 1)).astype(np.float32)
        # Warm the path, then SIGTERM with requests in flight.
        status, payload = _post_infer(port, x)
        assert status == 200 and len(payload["logits"]) == 1

        import threading

        results = []

        def _load():
            for _ in range(40):
                try:
                    results.append(_post_infer(port, x, timeout=30.0))
                except (ConnectionError, OSError):
                    results.append(("conn", None))

        loader = threading.Thread(target=_load)
        loader.start()
        time.sleep(0.15)  # mid-load
        proc.send_signal(signal.SIGTERM)
        loader.join(timeout=120)
        rc = proc.wait(timeout=120)
        assert rc == 0, proc.stderr.read()[-2000:]
        # Every HTTP response was whole: 200 with logits, or an explicit
        # drain/shed answer carrying retry-after — never torn JSON.
        served = shed = 0
        for status, payload in results:
            if status == 200:
                assert payload and "logits" in payload
                served += 1
            elif status in (429, 503):
                assert "retry_after_ms" in payload
                shed += 1
            else:
                assert status == "conn"  # listener already down
        assert served >= 1
        out = proc.stdout.read()
        summary = json.loads(out.strip().splitlines()[-1])
        assert summary["kind"] == "serve_summary"
        assert summary["served_requests"] >= served
        # The access log is intact JSONL (no torn records).
        for line in open(access).read().splitlines():
            assert json.loads(line)["kind"] == "access"
    finally:
        if proc.poll() is None:
            proc.kill()


# -------------------------------------------------------------- slow tier

@pytest.mark.slow
def test_sustained_overload_sheds_not_queues(tiny_serve_setup):
    """Open-loop overload (tools/serve_bench.run_load): offered load far
    past CPU capacity must shed — bounded queue, nonzero shed rate, and
    the SERVED tail still bounded by queue_cap/throughput, instead of
    latencies growing with the offered load (the unbounded-queue death
    spiral the admission control exists to prevent)."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from serve_bench import run_load

    from dwt_tpu.serve import ServeClient

    model, state, engine = tiny_serve_setup
    client = ServeClient(
        engine, max_batch_delay_ms=2.0, max_queue_items=64
    )
    try:
        client.infer(np.zeros((1, 28, 28, 1), np.float32))  # warm
        record = run_load(
            client, (28, 28, 1), offered=20_000.0, seconds=1.5,
            request_n=1,
        )
    finally:
        client.close()
    assert record["shed"] > 0 and record["shed_rate"] > 0.2
    assert record["served"] > 0
    # Bounded tail: with a 64-sample queue cap the worst served request
    # waited roughly cap/throughput, not offered-load-many seconds.
    assert record["e2e_ms_p99"] < 10_000


@pytest.mark.slow
def test_mesh_replica_fanout_two_devices():
    """--data_parallel fan-out on a forced 2-device host: bucket sizes
    round up to mesh multiples and served logits match the unsharded
    engine to f32 reassociation tolerance (a different XLA program;
    bitwise is per-program)."""
    code = r"""
import numpy as np, jax, jax.numpy as jnp, optax, json
from dwt_tpu.nn import LeNetDWT
from dwt_tpu.train import create_train_state
from dwt_tpu.serve import ServeEngine
from dwt_tpu.parallel import make_mesh

assert jax.device_count() == 2
model = LeNetDWT(group_size=4)
rng = np.random.default_rng(0)
sample = jnp.asarray(rng.normal(size=(2, 4, 28, 28, 1)), jnp.float32)
state = create_train_state(model, jax.random.key(0), sample, optax.identity())
x = rng.normal(size=(5, 28, 28, 1)).astype(np.float32)
ref = ServeEngine(model, state.params, state.batch_stats, (28, 28, 1),
                  buckets=(8,)).infer(x)
eng = ServeEngine(model, state.params, state.batch_stats, (28, 28, 1),
                  buckets=(1, 8), mesh=make_mesh())
assert eng.buckets == (2, 8), eng.buckets  # 1 rounded up to the mesh
out = eng.infer(x)
np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-3)
print("OK")
"""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2"
    ).strip()
    proc = subprocess.run(
        [sys.executable, "-c", code], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "OK" in proc.stdout
