"""Chrome trace-event export + crash-surviving flight recorder.

Exports the tracer's span buffers as Chrome trace-event JSON — the
format Perfetto (https://ui.perfetto.dev), ``chrome://tracing``, and
TensorBoard's trace viewer all load.  One file per process; multi-host
runs stamp ``pid = jax.process_index()`` (when available) and the shared
``run_id`` into every file so they merge by concatenating
``traceEvents``.

The **flight recorder** answers the post-mortem question the watchdog's
stack dumps cannot: the stacks say where every thread *is*, the last-N
seconds of spans say what they had been *doing*.  ``flight_dump`` writes
that trailing window next to the ``stacks-*.txt`` evidence and is safe
to call from the watchdog thread while the main thread is wedged (pure
Python + file I/O, ring reads are lock-poll only).
"""

from __future__ import annotations

import json
import os
import time
from typing import List, Optional

from dwt_tpu.obs import spans as _spans

# Required per-event keys of a complete ("X") trace event — the contract
# tests/test_obs.py validates exported files against.
CHROME_EVENT_KEYS = ("name", "cat", "ph", "ts", "dur", "pid", "tid")


def _process_index() -> int:
    """jax.process_index() without forcing backend init on a process
    that never touched jax (the report tool, early failures)."""
    try:
        import jax

        return int(jax.process_index())
    except Exception:
        return 0


def to_chrome_trace(records: List[dict], tracer=None,
                    pid: Optional[int] = None) -> dict:
    """Span dicts (``Tracer.snapshot`` layout) -> Chrome trace JSON dict.

    Timestamps convert from the tracer's perf_counter epoch to unix
    microseconds via the tracer's one wall-clock anchor, so files from
    processes with different monotonic epochs line up when merged.
    """
    tracer = tracer or _spans.get_tracer()
    if pid is None:
        pid = _process_index()
    anchor = 0.0
    run_id = None
    if tracer is not None:
        anchor = tracer.t0_unix - tracer.t0_perf
        run_id = tracer.run_id
    events = []
    tids = {}
    for r in records:
        tids.setdefault(r["tid"], r.get("thread", str(r["tid"])))
        ev = {
            "name": r["name"],
            "cat": r["cat"] or "span",
            "ph": "X",
            "ts": (r["ts"] + anchor) * 1e6,  # microseconds
            "dur": r["dur"] * 1e6,
            "pid": int(pid),
            "tid": int(r["tid"]),
        }
        args = dict(r.get("attrs") or {})
        if run_id is not None:
            args.setdefault("run_id", run_id)
        if args:
            ev["args"] = args
        events.append(ev)
    # Metadata events name the process/threads in the viewer.
    meta = [{
        "name": "process_name", "ph": "M", "pid": int(pid), "tid": 0,
        "args": {"name": f"dwt run={run_id or '?'} proc={pid}"},
    }]
    for tid, tname in sorted(tids.items()):
        meta.append({
            "name": "thread_name", "ph": "M", "pid": int(pid),
            "tid": int(tid), "args": {"name": tname},
        })
    out = {
        "traceEvents": meta + events,
        "displayTimeUnit": "ms",
        "otherData": {
            "run_id": run_id,
            "process_index": int(pid),
            "producer": "dwt_tpu.obs",
        },
    }
    if tracer is not None:
        out["otherData"]["dropped_spans"] = tracer.dropped_spans()
    return out


def export(path: Optional[str] = None) -> Optional[str]:
    """Write the full span buffers as a Chrome trace file.

    ``path`` defaults to the configured ``--obs_trace`` target; returns
    the written path, or None when tracing is disabled or no path is
    known.  Multi-process runs suffix non-zero process indices so hosts
    sharing a filesystem don't clobber one file.
    """
    tracer = _spans.get_tracer()
    if tracer is None:
        return None
    path = path or _spans.export_path()
    if not path:
        return None
    pid = _process_index()
    if pid != 0:
        root, ext = os.path.splitext(path)
        path = f"{root}.proc{pid}{ext or '.json'}"
    trace = to_chrome_trace(tracer.snapshot(), tracer, pid=pid)
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    tmp = f"{path}.tmp-{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(trace, f)
    os.replace(tmp, path)
    return path


# Trailing window the flight recorder keeps: long enough to cover a few
# steps plus the stall that tripped the watchdog, short enough that the
# dump stays small and the signal is "what JUST happened".
FLIGHT_WINDOW_S = 5.0

# Default dump-retention cap when the caller has no --watchdog_keep to
# pass through (guard-event dumps on a loop run without a watchdog): a
# flapping guard over a long traced run must not fill the disk.
DEFAULT_FLIGHT_KEEP = 5


def _prune_span_dumps(directory: str, keep: int) -> None:
    """Cap ``spans-*.json`` files in ``directory`` to the newest ``keep``
    (oldest mtime first out).  Best-effort: retention must never block
    the dump it makes room for."""
    try:
        dumps = [
            os.path.join(directory, name)
            for name in os.listdir(directory)
            if name.startswith("spans-") and name.endswith(".json")
        ]
        dumps.sort(key=os.path.getmtime)
        for stale in dumps[: max(len(dumps) - keep, 0)]:
            os.unlink(stale)
    except OSError:
        pass


def flight_dump(directory: str, reason: str,
                last_s: float = FLIGHT_WINDOW_S,
                keep: Optional[int] = DEFAULT_FLIGHT_KEEP) -> Optional[str]:
    """Dump the last ``last_s`` seconds of spans to
    ``<directory>/spans-<pid>-<ts>[-<n>].json`` (Chrome trace format, so
    the same viewers open it); the ``-<n>`` suffix keeps same-second
    dumps distinct (a local plus a remote-mirrored guard event at one
    boundary).  ``keep`` caps the directory's span dumps (None skips
    pruning — the watchdog prunes with its own ``--watchdog_keep``).
    No-op (None) when tracing is disabled; never raises — this runs on
    the watchdog thread mid-stall and on guard event paths where a
    logging failure must not mask the real fault.
    """
    tracer = _spans.get_tracer()
    if tracer is None:
        return None
    try:
        records = tracer.snapshot(last_s=last_s)
        trace = to_chrome_trace(records, tracer)
        trace["otherData"]["flight_reason"] = reason
        trace["otherData"]["window_s"] = last_s
        os.makedirs(directory, exist_ok=True)
        if keep is not None:
            _prune_span_dumps(directory, max(keep - 1, 0))
        base = os.path.join(
            directory, f"spans-{os.getpid()}-{int(time.time())}"
        )
        path = base + ".json"
        seq = 0
        while os.path.exists(path):
            seq += 1
            path = f"{base}-{seq}.json"
        with open(path, "w") as f:
            json.dump(trace, f)
            f.flush()
            os.fsync(f.fileno())
        return path
    except Exception:  # noqa: BLE001 — diagnostics must never kill the run
        return None


def validate_chrome_trace(trace: dict) -> List[str]:
    """Structural validation of an exported trace (the test contract):
    returns a list of problems, empty = valid.  Checks the required keys,
    numeric non-negative ts/dur, int pid/tid, and known phase codes."""
    problems = []
    events = trace.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents missing or not a list"]
    for i, ev in enumerate(events):
        ph = ev.get("ph")
        if ph == "M":
            if "name" not in ev or "args" not in ev:
                problems.append(f"event {i}: metadata without name/args")
            continue
        if ph != "X":
            problems.append(f"event {i}: unexpected phase {ph!r}")
            continue
        for key in CHROME_EVENT_KEYS:
            if key not in ev:
                problems.append(f"event {i}: missing key {key!r}")
        ts, dur = ev.get("ts"), ev.get("dur")
        if not isinstance(ts, (int, float)) or ts < 0:
            problems.append(f"event {i}: bad ts {ts!r}")
        if not isinstance(dur, (int, float)) or dur < 0:
            problems.append(f"event {i}: bad dur {dur!r}")
        if not isinstance(ev.get("pid"), int):
            problems.append(f"event {i}: pid not int")
        if not isinstance(ev.get("tid"), int):
            problems.append(f"event {i}: tid not int")
    return problems
